"""BASELINE config #3: CIFAR-10 CNN under AEASGD (elastic averaging on ICI).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/cifar10_aeasgd.py --workers 8 --epochs 2
"""

import argparse

import distkeras_tpu as dk
from distkeras_tpu.datasets import cifar10
from distkeras_tpu.evaluators import AccuracyEvaluator
from distkeras_tpu.models.cnn import cifar10_cnn
from distkeras_tpu.predictors import ClassPredictor


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--window", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--rho", type=float, default=3.0)
    p.add_argument("--rows", type=int, default=8192)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--metrics", default=None, help="JSONL metrics path")
    args = p.parse_args()

    df = cifar10(n=args.rows, data_dir=args.data_dir)
    train_df, test_df = df.split(0.9, seed=1)

    trainer = dk.AEASGD(
        cifar10_cnn(), worker_optimizer="sgd",
        loss="sparse_categorical_crossentropy", batch_size=args.batch_size,
        num_epoch=args.epochs, num_workers=args.workers,
        communication_window=args.window, learning_rate=args.lr, rho=args.rho,
        compute_dtype="bfloat16", metrics_path=args.metrics,
    )
    trained = trainer.train(train_df, shuffle=True)
    h = trainer.get_history()
    print(f"AEASGD: loss {h[0]:.4f} -> {h[-1]:.4f} in {trainer.get_training_time():.1f}s")

    pred = ClassPredictor(trained, features_col="features",
                          output_col="prediction").predict(test_df)
    print("test accuracy:", AccuracyEvaluator(prediction_col="prediction",
                                              label_col="label").evaluate(pred))


if __name__ == "__main__":
    main()
