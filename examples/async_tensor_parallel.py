"""Async disciplines x tensor parallelism: every worker is a tp submesh.

The reference's async workers were single-GPU processes; here an AEASGD
"worker" can itself be a tensor-parallel transformer replica. This example
trains a small TransformerLM with elastic averaging over W workers, each
tp-sharded over 2 chips of a (data, model) mesh — the same
``trainer.train(dataframe)`` call as every other trainer.

    # CPU virtual mesh (4 workers x tp=2 on 8 virtual devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/async_tensor_parallel.py
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import TransformerLM

    tp = 2
    workers = max(1, jax.device_count() // tp)
    L, V = 32, 256
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, size=(workers * 512, L))
    df = dk.DataFrame({"features": toks.astype(np.int32),
                       "label": np.roll(toks, -1, 1).astype(np.int32)})

    model = Model.build(
        TransformerLM(vocab_size=V, num_layers=2, d_model=64, num_heads=4,
                      d_ff=128, max_seq_len=L),
        jnp.zeros((1, L), jnp.int32))

    trainer = dk.AEASGD(
        model, num_workers=workers, parallel={"model": tp},
        worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        batch_size=8, communication_window=4, num_epoch=2,
        learning_rate=1e-3, rho=5.0)
    print(f"AEASGD over {workers} workers, each a tp={tp} replica "
          f"({jax.device_count()} devices total) ...")
    trainer.train(df, shuffle=True)
    h = trainer.get_history()
    print(f"done: {len(h)} fold rounds, loss {h[0]:.4f} -> {h[-1]:.4f}")
    assert h[-1] < h[0]


if __name__ == "__main__":
    main()
