"""Streaming inference — the TPU-native port of the reference's Kafka example.

The reference ships a Kafka streaming-inference pipeline (SURVEY.md §2, examples
row): a producer pushes feature records onto a topic; a Spark consumer maps the
trained model over each microbatch and re-emits records with predictions. Here
the "topic" is a bounded queue fed by a producer thread and the consumer is
:class:`~distkeras_tpu.predictors.StreamingPredictor.predict_stream`, which
coalesces arbitrary microbatches into fixed-shape padded chunks so every forward
pass hits one compiled executable.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/streaming_inference.py
"""

import argparse
import queue
import threading
import time

import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.predictors import StreamingClassPredictor


def make_blobs(n, d=8, c=4, seed=0):
    # Class centers are fixed across seeds; only the sample draw varies, so a
    # model trained on seed 0 generalizes to the seed-1 stream.
    centers = np.random.default_rng(42).normal(scale=4.0, size=(c, d))
    rng = np.random.default_rng(seed)
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.6, size=(n, d))).astype(np.float32)
    return x, y.astype(np.int32)


def producer(q, x, y, microbatch, delay_s):
    """Simulates the Kafka producer: pushes (features, labels) microbatches."""
    for start in range(0, len(x), microbatch):
        q.put((x[start:start + microbatch], y[start:start + microbatch]))
        time.sleep(delay_s)
    q.put(None)  # end-of-stream marker


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=4096)
    ap.add_argument("--microbatch", type=int, default=37)  # ragged on purpose
    ap.add_argument("--chunk-size", type=int, default=512)
    ap.add_argument("--delay-ms", type=float, default=1.0)
    args = ap.parse_args()

    # 1. Train a small classifier (stand-in for the reference's saved model).
    x, y = make_blobs(args.records)
    df = dk.DataFrame({"features": x, "label": y})
    trainer = dk.SingleTrainer(
        dk.Model.build(MLP(hidden=(32,), num_outputs=4),
                       np.zeros((1, x.shape[1]), np.float32)),
        worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        batch_size=64, num_epoch=3, learning_rate=0.01,
    )
    model = trainer.train(df, shuffle=True)

    # 2. Producer thread feeds a bounded queue (the "topic").
    q: queue.Queue = queue.Queue(maxsize=8)
    sx, sy = make_blobs(args.records, seed=1)
    t = threading.Thread(target=producer,
                         args=(q, sx, sy, args.microbatch, args.delay_ms / 1e3),
                         daemon=True)
    t.start()

    labels = []

    def topic():
        while True:
            item = q.get()
            if item is None:
                return
            feats, labs = item
            labels.append(labs)
            yield feats

    # 3. Consumer: predictions stream out one array per microbatch, in order.
    predictor = StreamingClassPredictor(model, chunk_size=args.chunk_size)
    n_seen = n_correct = 0
    t0 = time.perf_counter()
    for i, pred in enumerate(predictor.predict_stream(topic())):
        n_seen += len(pred)
        n_correct += int((pred == labels[i]).sum())
        if (i + 1) % 20 == 0:
            dt = time.perf_counter() - t0
            print(f"microbatch {i + 1}: {n_seen} records, "
                  f"rolling accuracy {n_correct / n_seen:.3f}, "
                  f"{n_seen / dt:.0f} records/s")
    dt = time.perf_counter() - t0
    print(f"stream done: {n_seen} records in {dt:.2f}s "
          f"({n_seen / dt:.0f} records/s), accuracy {n_correct / n_seen:.3f}")
    assert n_seen == args.records


if __name__ == "__main__":
    main()
