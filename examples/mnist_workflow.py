"""The classic dist-keras MNIST workflow, ported from the reference's
``examples/workflow.ipynb``: preprocess -> distributed train -> predict -> evaluate.

Run on any jax backend; use the virtual mesh for a laptop dry run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mnist_workflow.py --trainer adag --workers 8
"""

import argparse

import jax.numpy as jnp
import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.data import MinMaxTransformer, OneHotTransformer, ReshapeTransformer
from distkeras_tpu.datasets import mnist
from distkeras_tpu.evaluators import AccuracyEvaluator, F1Evaluator
from distkeras_tpu.models.cnn import mnist_cnn
from distkeras_tpu.models.mlp import mnist_mlp
from distkeras_tpu.predictors import ClassPredictor

TRAINERS = {
    "single": lambda m, a: dk.SingleTrainer(
        m, worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        features_col="img", label_col="label", batch_size=a.batch_size,
        num_epoch=a.epochs, learning_rate=a.lr),
    "downpour": lambda m, a: dk.DOWNPOUR(
        m, worker_optimizer="sgd", loss="sparse_categorical_crossentropy",
        features_col="img", label_col="label", batch_size=a.batch_size,
        num_epoch=a.epochs, num_workers=a.workers,
        communication_window=a.window, learning_rate=a.lr),
    "adag": lambda m, a: dk.ADAG(
        m, worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        features_col="img", label_col="label", batch_size=a.batch_size,
        num_epoch=a.epochs, num_workers=a.workers,
        communication_window=a.window, learning_rate=a.lr),
    "dynsgd": lambda m, a: dk.DynSGD(
        m, worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        features_col="img", label_col="label", batch_size=a.batch_size,
        num_epoch=a.epochs, num_workers=a.workers,
        communication_window=a.window, learning_rate=a.lr),
    "aeasgd": lambda m, a: dk.AEASGD(
        m, worker_optimizer="sgd", loss="sparse_categorical_crossentropy",
        features_col="img", label_col="label", batch_size=a.batch_size,
        num_epoch=a.epochs, num_workers=a.workers,
        communication_window=a.window, learning_rate=a.lr, rho=3.0),
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--trainer", choices=sorted(TRAINERS), default="adag")
    p.add_argument("--model", choices=["mlp", "cnn"], default="cnn")
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--window", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.002)
    p.add_argument("--rows", type=int, default=16384)
    p.add_argument("--data-dir", default=None, help="dir with MNIST idx.gz files")
    args = p.parse_args()

    # 1. Load + preprocess (the reference's transformer pipeline, minus Spark).
    df = mnist(n=args.rows, data_dir=args.data_dir)
    df = MinMaxTransformer(0.0, 1.0, input_col="features",
                           output_col="features_norm").transform(df)
    df = ReshapeTransformer("features_norm", "img", (28, 28, 1)).transform(df)
    df = OneHotTransformer(10, input_col="label",
                           output_col="label_one_hot").transform(df)
    train_df, test_df = df.split(0.9, seed=1)
    print(f"dataset: {train_df.count()} train / {test_df.count()} test "
          f"(synthetic={getattr(df, 'synthetic', '?')})")

    # 2. Train.
    model = mnist_cnn() if args.model == "cnn" else mnist_mlp()
    trainer = TRAINERS[args.trainer](model, args)
    trained = trainer.train(train_df, shuffle=True)
    h = trainer.get_history()
    print(f"{args.trainer}: {len(h)} fold rounds, loss {h[0]:.4f} -> {h[-1]:.4f}, "
          f"{trainer.get_training_time():.1f}s")

    # 3. Predict + evaluate.
    pred_df = ClassPredictor(trained, features_col="img",
                             output_col="prediction").predict(test_df)
    acc = AccuracyEvaluator(prediction_col="prediction", label_col="label").evaluate(pred_df)
    f1 = F1Evaluator(prediction_col="prediction", label_col="label").evaluate(pred_df)
    print(f"test accuracy: {acc:.4f}  macro-F1: {f1:.4f}")


if __name__ == "__main__":
    main()
