"""Flagship multi-axis training: TransformerLM on a (data, seq, model) mesh.

dp x sp x tp in one jitted step — ring attention over ``seq``, gradient pmean
over ``data``, GSPMD tensor parallelism over ``model`` — through the same
one-class trainer UX as every reference algorithm: ``ParallelTrainer`` wires
the SPMD engine into the full run harness (checkpoint/resume, metrics JSONL,
``rounds_per_program``). Dry-run anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_spmd.py --steps 20
"""

import argparse

import jax
import jax.numpy as jnp

from distkeras_tpu import ParallelTrainer
from distkeras_tpu.datasets import synthetic_lm
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.transformer import TransformerLM
from distkeras_tpu.parallel.spmd import spmd_mesh_for
from distkeras_tpu.runtime.mesh import SEQ_AXIS


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--batch-per-dp", type=int, default=4)
    p.add_argument("--checkpoint-dir", default=None)
    args = p.parse_args()

    # Factor the chips into (data, seq, model) — the same split the engine
    # would get from spmd_mesh_for; expressed as the trainer's `parallel` map.
    shape = dict(spmd_mesh_for(jax.device_count()).shape)
    print("mesh:", shape)

    arch = dict(vocab_size=args.vocab, num_layers=args.layers,
                d_model=args.d_model, num_heads=4, d_ff=4 * args.d_model,
                max_seq_len=args.seq_len)
    model = Model.build(TransformerLM(**arch),
                        jnp.zeros((1, args.seq_len), jnp.int32))
    # Ring attention streams K/V blocks around the ICI ring over `seq`.
    model = model.with_module(
        TransformerLM(**arch, seq_axis=SEQ_AXIS, attn_impl="ring"))
    print(f"params: {model.num_params:,}")

    B = args.batch_per_dp * shape["data"]
    df = synthetic_lm(n=B * args.steps, vocab_size=args.vocab,
                      seq_len=args.seq_len + 1)

    trainer = ParallelTrainer(
        model, parallel=shape,
        worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        batch_size=B, learning_rate=3e-3, steps_per_program=4,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=4 if args.checkpoint_dir else 0,
        resume=bool(args.checkpoint_dir),
        on_round=lambda r, loss: print(f"round {r}: loss {float(loss):.4f}"),
    )
    trainer.train(df)
    h = trainer.get_history()
    if len(h):
        print(f"trained in {trainer.get_training_time():.1f}s; "
              f"loss {h[0]:.4f} -> {h[-1]:.4f}")
    else:  # resumed a checkpoint already past the final round
        print("checkpoint already covers every round; nothing to train")


if __name__ == "__main__":
    main()
