"""Flagship multi-axis training: TransformerLM on a (data, seq, model) mesh.

dp x sp x tp in one jitted step — ring attention over ``seq``, gradient pmean over
``data``, GSPMD tensor parallelism over ``model``. Dry-run anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_spmd.py --steps 20
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.datasets import synthetic_lm
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.transformer import TransformerLM
from distkeras_tpu.parallel.sharding import TRANSFORMER_TP_RULES
from distkeras_tpu.parallel.spmd import SPMDEngine, spmd_mesh_for
from distkeras_tpu.runtime.mesh import DATA_AXIS, SEQ_AXIS


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--batch-per-dp", type=int, default=4)
    args = p.parse_args()

    mesh = spmd_mesh_for(jax.device_count())
    print("mesh:", dict(mesh.shape))

    arch = dict(vocab_size=args.vocab, num_layers=args.layers, d_model=args.d_model,
                num_heads=4, d_ff=4 * args.d_model, max_seq_len=args.seq_len)
    model = Model.build(TransformerLM(**arch),
                        jnp.zeros((1, args.seq_len), jnp.int32))
    model = Model(module=TransformerLM(**arch, seq_axis=SEQ_AXIS, attn_impl="ring"),
                  params=model.params)
    print(f"params: {model.num_params:,}")

    engine = SPMDEngine(model, "adam", "sparse_categorical_crossentropy", mesh,
                        TRANSFORMER_TP_RULES, learning_rate=3e-3)
    state = engine.init_state()

    B = args.batch_per_dp * mesh.shape[DATA_AXIS]
    df = synthetic_lm(n=B * args.steps, vocab_size=args.vocab,
                      seq_len=args.seq_len + 1)
    sharding = engine.batch_sharding()
    for step in range(args.steps):
        rows = slice(step * B, (step + 1) * B)
        tokens = jax.device_put(jnp.asarray(df["features"][rows]), sharding)
        targets = jax.device_put(jnp.asarray(df["label"][rows]), sharding)
        state, loss = engine.step(state, tokens, targets)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
