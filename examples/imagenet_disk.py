"""ImageNet-shaped out-of-core training — the BASELINE #5 data story.

The reference's Spark DataFrame kept training data partitioned across
executors and spillable to disk; ~150 GB of ImageNet never had to fit in any
single host's RAM. This example exercises the TPU-side replacement at that
shape without shipping a dataset: a **virtual** (sparse-file) image store of
any logical size, laid out as memmapped ``.npy`` shard files, feeding ResNet
synchronous DP through the standard ``trainer.train(dataframe)`` call. Rows
are gathered from disk per fold round (only the touched pages ever
materialize); on a multi-host mesh each process stages only its own workers'
shards (``tests/test_multihost.py::test_two_process_disjoint_shards`` runs
exactly that).

    # quick smoke (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imagenet_disk.py

    # the full ImageNet-at-scale virtual shape (sparse file: allocates only
    # the pages training touches; one epoch streams the whole logical set):
    python examples/imagenet_disk.py --virtual-gb 150 --image-hw 224
"""

import argparse
import json
import os
import tempfile

if os.environ.get("JAX_PLATFORMS"):  # honor even under overriding site hooks
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np


def build_virtual_store(root: str, virtual_gb: float, image_hw: int,
                        classes: int) -> None:
    """A sharded store whose feature shards are SPARSE ``.npy`` files:
    logical size ``virtual_gb``, disk usage only what training touches.
    Real pipelines write dense shards with ``ShardWriter``; the manifest
    and reader are identical either way."""
    from distkeras_tpu.data.shards import _shard_file

    os.makedirs(root, exist_ok=True)
    row_bytes = image_hw * image_hw * 3 * 4
    n = max(512, int(virtual_gb * 1e9 // row_bytes))
    rows_per_shard = max(1, min(n // 8, 65536))
    shard_rows = []
    rng = np.random.default_rng(0)
    off = 0
    while off < n:
        rows = min(rows_per_shard, n - off)
        s = len(shard_rows)
        np.save(os.path.join(root, _shard_file(s, "label")),
                rng.integers(0, classes, size=rows).astype(np.int32))
        # open_memmap writes a valid .npy header then truncates to full
        # size — a sparse file until pages are actually written.
        mm = np.lib.format.open_memmap(
            os.path.join(root, _shard_file(s, "features")), mode="w+",
            dtype=np.float32, shape=(rows, image_hw, image_hw, 3))
        del mm
        shard_rows.append(rows)
        off += rows
    offsets = np.concatenate([[0], np.cumsum(shard_rows)]).tolist()
    with open(os.path.join(root, "manifest.json"), "w") as f:
        json.dump({
            "version": 1,
            "num_rows": int(offsets[-1]),
            "columns": {
                "features": {"dtype": "float32",
                             "shape": [image_hw, image_hw, 3]},
                "label": {"dtype": "int32", "shape": []},
            },
            "shard_rows": [int(r) for r in shard_rows],
            "shard_offsets": [int(o) for o in offsets[:-1]],
        }, f)


def augment(feats: np.ndarray, labels: np.ndarray, rng: np.random.Generator):
    """Standard ImageNet training augmentation as a training-time transform
    (``Trainer(transform=...)``): per-image random horizontal flip + random
    crop from 4-pixel-padded. Runs host-side during staging, deterministic in
    (seed, round, worker) — out-of-core stores get per-epoch randomized
    augmentation that ingest-time transforms cannot express."""
    n, h, w, _ = feats.shape
    out = np.where(
        (rng.random(n) < 0.5)[:, None, None, None], feats[:, :, ::-1], feats)
    pad = 4
    padded = np.pad(out, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    ys = rng.integers(0, 2 * pad + 1, size=n)
    xs = rng.integers(0, 2 * pad + 1, size=n)
    out = np.stack([padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
                    for i in range(n)])
    return out, labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--virtual-gb", type=float, default=0.05,
                   help="logical dataset size (sparse on disk); try 150")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--image-hw", type=int, default=64)
    p.add_argument("--store", default=None,
                   help="shard dir (default: a temp dir)")
    args = p.parse_args()

    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.resnet import ResNet

    root = args.store or tempfile.mkdtemp(prefix="imagenet_virtual_")
    print(f"building virtual {args.virtual_gb:g} GB store in {root} ...")
    build_virtual_store(root, args.virtual_gb, args.image_hw, classes=1000)
    du = sum(os.stat(os.path.join(root, f)).st_blocks * 512
             for f in os.listdir(root))
    sdf = dk.ShardedDataFrame(root)
    print(f"logical rows: {sdf.count():,} "
          f"({sdf.count() * args.image_hw**2 * 3 * 4 / 1e9:.1f} GB logical); "
          f"actual disk use: {du / 1e6:.1f} MB")

    model = Model.build(
        ResNet(stage_sizes=(1, 1, 1, 1), base_features=16, num_outputs=1000,
               groups=8),
        np.zeros((1, args.image_hw, args.image_hw, 3), np.float32), seed=0)
    workers = jax.device_count()
    trainer = dk.SynchronousDistributedTrainer(
        model, loss="sparse_categorical_crossentropy", num_workers=workers,
        batch_size=args.batch_size, num_epoch=1, learning_rate=0.01,
        steps_per_program=2, compute_dtype="bfloat16", transform=augment,
        on_round=lambda r, loss: print(f"round {r}: loss {float(loss):.4f}"))
    print(f"training ResNet sync-DP on {workers} worker(s) with random "
          "crop/flip augmentation; one epoch streams the full logical "
          "dataset from disk ...")
    trainer.train(sdf)
    h = trainer.get_history()
    print(f"done: {len(h)} rounds, loss {h[0]:.4f} -> {h[-1]:.4f}")


if __name__ == "__main__":
    main()
