"""ImageNet-shaped out-of-core training — the BASELINE #5 data story.

The reference's Spark DataFrame kept training data partitioned across
executors and spillable to disk; ~150 GB of ImageNet never had to fit in any
single host's RAM. This example exercises the TPU-side replacement at that
shape without shipping a dataset: a **virtual** (sparse-file) image store of
any logical size, laid out as memmapped ``.npy`` shard files, feeding ResNet
synchronous DP through the standard ``trainer.train(dataframe)`` call. Rows
are gathered from disk per fold round (only the touched pages ever
materialize); on a multi-host mesh each process stages only its own workers'
shards (``tests/test_multihost.py::test_two_process_disjoint_shards`` runs
exactly that).

    # quick smoke (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imagenet_disk.py

    # the full ImageNet-at-scale virtual shape (sparse file: allocates only
    # the pages training touches; one epoch streams the whole logical set):
    python examples/imagenet_disk.py --virtual-gb 150 --image-hw 224
"""

import argparse
import json
import os
import tempfile

if os.environ.get("JAX_PLATFORMS"):  # honor even under overriding site hooks
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np


def build_virtual_store(root: str, virtual_gb: float, image_hw: int,
                        classes: int, dtype: str = "float32") -> None:
    """A sharded store whose feature shards are SPARSE ``.npy`` files:
    logical size ``virtual_gb``, disk usage only what training touches.
    Real pipelines write dense shards with ``ShardWriter``; the manifest
    and reader are identical either way. ``dtype='uint8'`` is the realistic
    ImageNet layout (raw bytes on disk, float conversion in the train-time
    transform — 4x less disk/gather traffic than float32 shards)."""
    from distkeras_tpu.data.shards import _shard_file

    os.makedirs(root, exist_ok=True)
    row_bytes = image_hw * image_hw * 3 * np.dtype(dtype).itemsize
    n = max(512, int(virtual_gb * 1e9 // row_bytes))
    rows_per_shard = max(1, min(n // 8, 65536))
    shard_rows = []
    rng = np.random.default_rng(0)
    off = 0
    while off < n:
        rows = min(rows_per_shard, n - off)
        s = len(shard_rows)
        np.save(os.path.join(root, _shard_file(s, "label")),
                rng.integers(0, classes, size=rows).astype(np.int32))
        # open_memmap writes a valid .npy header then truncates to full
        # size — a sparse file until pages are actually written.
        mm = np.lib.format.open_memmap(
            os.path.join(root, _shard_file(s, "features")), mode="w+",
            dtype=np.dtype(dtype), shape=(rows, image_hw, image_hw, 3))
        del mm
        shard_rows.append(rows)
        off += rows
    offsets = np.concatenate([[0], np.cumsum(shard_rows)]).tolist()
    with open(os.path.join(root, "manifest.json"), "w") as f:
        json.dump({
            "version": 1,
            "num_rows": int(offsets[-1]),
            "columns": {
                "features": {"dtype": dtype,
                             "shape": [image_hw, image_hw, 3]},
                "label": {"dtype": "int32", "shape": []},
            },
            "shard_rows": [int(r) for r in shard_rows],
            "shard_offsets": [int(o) for o in offsets[:-1]],
        }, f)


def augment(feats: np.ndarray, labels: np.ndarray, rng: np.random.Generator):
    """Standard ImageNet training augmentation as a training-time transform
    (``Trainer(transform=...)``): per-image random horizontal flip + random
    crop from 4-pixel-padded. Runs host-side during staging, deterministic in
    (seed, round, worker) — out-of-core stores get per-epoch randomized
    augmentation that ingest-time transforms cannot express.

    Feed-bandwidth rules (docs/PERFORMANCE.md "Feed overlap", measured):

    * stay in the store dtype — a uint8 batch leaves here as uint8 and is
      normalized to the compute dtype ON DEVICE (``workers.make_local_loop``
      treats uint8 features as raw image bytes: ``x/255`` in-graph), so
      host->device traffic is 4x smaller than shipping float32;
    * no per-row Python: the random crop is one strided gather
      (``sliding_window_view``), not an ``np.stack`` loop over rows (the
      loop alone cost ~1.3 s per 256-row round at 224x224).
    """
    n, h, w, _ = feats.shape
    out = np.where(
        (rng.random(n) < 0.5)[:, None, None, None], feats[:, :, ::-1], feats)
    pad = 4
    padded = np.pad(out, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    ys = rng.integers(0, 2 * pad + 1, size=n)
    xs = rng.integers(0, 2 * pad + 1, size=n)
    # [n, 2p+1, 2p+1, h, w, c] strided view; one fancy-index gathers every
    # row's crop without materializing the windows.
    windows = np.lib.stride_tricks.sliding_window_view(
        padded, (h, w), axis=(1, 2))
    out = windows[np.arange(n), ys, xs].transpose(0, 2, 3, 1)
    return np.ascontiguousarray(out), labels


def measure_feed(sdf, model, batch_size: int, window: int,
                 device_augment: bool = False) -> dict:
    """Feed-overlap measurement at the out-of-core augmented shape
    (VERDICT r4 missing #3): does disk gather + crop/flip + device_put stay
    behind device compute?

    Three numbers per round, printed as one JSON line:

    * ``wall_per_round`` — the real run (RoundFeeder lookahead staging);
    * ``device_per_round`` — the same executable on a pre-staged batch
      (probe_steady protocol: unfenced dispatches, one fence);
    * ``stage_per_round`` — gather+transform+device_put alone.

    ``hidden_frac`` = 1 - max(0, wall - device)/wall: 1.0 means staging is
    fully hidden behind compute. ``feed_waits`` is the engines' always-on
    per-round consumer-block diagnostic (engine.feed_wait_seconds)."""
    import time

    import jax

    from distkeras_tpu.data.batching import make_batches
    from distkeras_tpu.ops.augment import flip_crop_transform
    from distkeras_tpu.parallel.engine import probe_steady, stage_round
    from distkeras_tpu.parallel.sync import SyncEngine
    from distkeras_tpu.runtime.mesh import data_mesh

    engine = SyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                        data_mesh(), learning_rate=0.01,
                        compute_dtype="bfloat16",
                        device_transform=(flip_crop_transform()
                                          if device_augment else None))
    plan = make_batches(sdf, "features", "label", batch_size,
                        num_workers=engine.num_workers, window=window,
                        num_epoch=1,
                        transform=None if device_augment else augment,
                        seed=0)
    R = plan.num_rounds

    # Compile + warm the gather path outside every timed window.
    xs, ys = stage_round(engine, plan, 0)
    state = engine.init_state()
    state, loss = engine._round_fn(state, xs, ys)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    state, _ = engine.run(plan, state=state)
    wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    for r in range(R):
        host_batch = plan.round(r)  # gather + transform, no device_put
    host_s = (time.perf_counter() - t0) / R
    round_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                      for a in host_batch)
    t0 = time.perf_counter()
    for r in range(R):
        xs, ys = stage_round(engine, plan, r)
    jax.block_until_ready(xs)
    stage_s = (time.perf_counter() - t0) / R

    def dispatch():
        nonlocal state
        state, loss = engine._round_fn(state, xs, ys)
        return loss

    device_s = probe_steady(dispatch, n=min(R, 10))
    wall_r = wall / R
    rec = {
        "metric": "imagenet_disk_feed_hidden_frac",
        "augment": "device" if device_augment else "host",
        "value": round(1.0 - max(0.0, wall_r - device_s) / wall_r, 4),
        "unit": "fraction of staging hidden behind device compute",
        "rounds": R,
        "wall_per_round_ms": round(wall_r * 1e3, 2),
        "device_per_round_ms": round(device_s * 1e3, 2),
        "stage_per_round_ms": round(stage_s * 1e3, 2),
        "stage_host_ms": round(host_s * 1e3, 2),  # gather+transform only
        "stage_h2d_ms": round((stage_s - host_s) * 1e3, 2),
        "round_bytes_mb": round(round_bytes / 1e6, 1),
        "feed_waits_ms": [round(w * 1e3, 2)
                          for w in getattr(engine, "feed_waits", [])],
    }
    print(json.dumps(rec))
    return rec


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--virtual-gb", type=float, default=0.05,
                   help="logical dataset size (sparse on disk); try 150")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--image-hw", type=int, default=64)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "uint8"],
                   help="on-disk feature dtype (uint8 = raw-bytes ImageNet)")
    p.add_argument("--measure-feed", action="store_true",
                   help="measure staging overlap instead of training "
                        "(docs/PERFORMANCE.md 'Feed overlap')")
    p.add_argument("--augment", default="host", choices=["host", "device"],
                   help="crop/flip on the host during staging (transform=) "
                        "or on-device inside the jitted step "
                        "(device_transform=, ops/augment.py)")
    p.add_argument("--window", type=int, default=2)
    p.add_argument("--store", default=None,
                   help="shard dir (default: a temp dir)")
    args = p.parse_args()

    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.resnet import ResNet

    root = args.store or tempfile.mkdtemp(prefix="imagenet_virtual_")
    print(f"building virtual {args.virtual_gb:g} GB store in {root} ...")
    build_virtual_store(root, args.virtual_gb, args.image_hw, classes=1000,
                        dtype=args.dtype)
    du = sum(os.stat(os.path.join(root, f)).st_blocks * 512
             for f in os.listdir(root))
    sdf = dk.ShardedDataFrame(root)
    logical_gb = (sdf.count() * args.image_hw ** 2 * 3
                  * np.dtype(args.dtype).itemsize / 1e9)
    print(f"logical rows: {sdf.count():,} ({logical_gb:.1f} GB logical); "
          f"actual disk use: {du / 1e6:.1f} MB")

    if args.measure_feed:
        from distkeras_tpu.models.resnet import resnet50, tiny_resnet

        on_tpu = jax.default_backend() == "tpu"
        model = (resnet50() if on_tpu and args.image_hw == 224
                 else Model.build(
                     ResNet(stage_sizes=(1, 1, 1, 1), base_features=16,
                            num_outputs=1000, groups=8),
                     np.zeros((1, args.image_hw, args.image_hw, 3),
                              np.float32), seed=0))
        measure_feed(sdf, model, args.batch_size, args.window,
                     device_augment=args.augment == "device")
        return

    model = Model.build(
        ResNet(stage_sizes=(1, 1, 1, 1), base_features=16, num_outputs=1000,
               groups=8),
        np.zeros((1, args.image_hw, args.image_hw, 3), np.float32), seed=0)
    workers = jax.device_count()
    device_aug = args.augment == "device"
    if device_aug:
        from distkeras_tpu.ops.augment import flip_crop_transform

        aug_kw = dict(device_transform=flip_crop_transform())
    else:
        aug_kw = dict(transform=augment)
    trainer = dk.SynchronousDistributedTrainer(
        model, loss="sparse_categorical_crossentropy", num_workers=workers,
        batch_size=args.batch_size, num_epoch=1, learning_rate=0.01,
        steps_per_program=2, compute_dtype="bfloat16", **aug_kw,
        on_round=lambda r, loss: print(f"round {r}: loss {float(loss):.4f}"))
    print(f"training ResNet sync-DP on {workers} worker(s) with random "
          f"crop/flip augmentation ({args.augment}-side); one epoch streams "
          "the full logical dataset from disk ...")
    trainer.train(sdf)
    h = trainer.get_history()
    print(f"done: {len(h)} rounds, loss {h[0]:.4f} -> {h[-1]:.4f}")


if __name__ == "__main__":
    main()
