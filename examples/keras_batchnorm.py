"""Keras BatchNorm models under the distributed trainers (carry mode).

The reference's 2016-era notebooks define stock Keras models — BatchNorm
included — and hand them to a trainer. Same flow here: build a Keras-3 model
(JAX backend), ingest with ``batchnorm="carry"``, and train under any
discipline. Running statistics thread through the training window as mutable
state and are cross-replica averaged at every fold — deterministic, unlike the
reference's raced socket commits.

Run anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        KERAS_BACKEND=jax python examples/keras_batchnorm.py
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np

import distkeras_tpu as dk
from distkeras_tpu.models.keras_adapter import from_keras


def main():
    import keras

    # Deliberately unnormalized features: BatchNorm has real work to do.
    rng = np.random.default_rng(0)
    n, d, c = 4096, 16, 4
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = ((centers[y] + rng.normal(scale=0.5, size=(n, d))) * 25 + 11).astype(np.float32)
    df = dk.DataFrame({"features": x, "label": y.astype(np.int32)})
    train, test = df.randomSplit([0.8, 0.2], seed=0)

    keras_model = keras.Sequential([
        keras.layers.Input((d,)),
        keras.layers.Dense(64),
        keras.layers.BatchNormalization(momentum=0.9),
        keras.layers.Activation("relu"),
        keras.layers.Dense(64),
        keras.layers.BatchNormalization(momentum=0.9),
        keras.layers.Activation("relu"),
        keras.layers.Dense(c),
    ])
    model = from_keras(keras_model, sample_input=np.zeros((1, d), np.float32),
                       batchnorm="carry")
    print(f"ingested Keras model: {model.num_params:,} trainable params, "
          f"state collections: {model.state_collections}")

    trainer = dk.ADAG(
        model, loss="sparse_categorical_crossentropy",
        num_workers=dk.device_count(), batch_size=32, num_epoch=6,
        communication_window=4, learning_rate=0.05,
    )
    trained = trainer.train(train, shuffle=True)
    print(f"trained in {trainer.get_training_time():.1f}s; "
          f"loss {trainer.get_history()[0]:.3f} -> {trainer.get_history()[-1]:.3f}")

    logits = np.asarray(trained.predict(np.asarray(test["features"])))
    acc = float((logits.argmax(-1) == test["label"]).mean())
    print(f"held-out accuracy: {acc:.3f}")

    # The trained model (params + BN running stats) round-trips as one blob.
    blob = dk.serialize_model(trained)
    back = dk.deserialize_model(blob)
    assert np.allclose(np.asarray(back.predict(np.asarray(test["features"][:8]))),
                       logits[:8], rtol=1e-5, atol=1e-5)
    print(f"serialized model: {len(blob):,} bytes (params + BN statistics)")


if __name__ == "__main__":
    main()
