"""Distributed ingest: N writers stream disjoint row ranges, one merge.

Spark's data plane wrote partitions in parallel from every executor; the
TPU-side equivalent is a ``ShardWriter(part=k)`` per writer (no cross-writer
coordination — each streams into its own subdirectory on any filesystem)
followed by ONE ``merge_manifests`` call that splices the parts into the
global shard sequence by rename and publishes the root manifest. The merge
is journaled: a crash at any point resumes instead of corrupting the store.

Here the "writers" are processes in a pool on one machine; on a pod each
host runs its own writer over its slice of the source data, then process 0
merges behind a barrier (see ``tests/multihost_predict_worker.py`` for the
real 2-process version).

    python examples/distributed_ingest.py
"""

import multiprocessing as mp
import os
import tempfile

import numpy as np


def write_part(args):
    root, part, lo, hi = args
    # Each writer re-derives its slice of the (deterministic) source — on a
    # real cluster this is "read your own files / your own table range".
    from distkeras_tpu import ShardWriter

    rng = np.random.default_rng(7)
    n, d = 4096, 16
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, 3, size=n).astype(np.int32)
    with ShardWriter(root, rows_per_shard=256, part=part) as w:
        for s in range(lo, hi, 300):  # ragged chunks cross shard bounds
            e = min(s + 300, hi)
            w.append(features=x[s:e], label=y[s:e])
    return part


def main():
    import distkeras_tpu as dk

    root = tempfile.mkdtemp(prefix="dk_ingest_")
    n, writers = 4096, 4
    bounds = [(root, k, k * n // writers, (k + 1) * n // writers)
              for k in range(writers)]
    with mp.Pool(writers) as pool:
        done = pool.map(write_part, bounds)
    print(f"{len(done)} writers done -> merging ...")
    manifest = dk.merge_manifests(root)
    print(f"store: {manifest['num_rows']} rows in "
          f"{len(manifest['shard_rows'])} shards at {root}")

    sdf = dk.ShardedDataFrame(root)
    assert sdf.count() == n
    # Train straight off the merged store (out-of-core path).
    import jax.numpy as jnp

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP

    model = Model.build(MLP(hidden=(32,), num_outputs=3), jnp.zeros((1, 16)))
    trainer = dk.ADAG(model, num_workers=1, batch_size=64,
                      communication_window=4, num_epoch=1,
                      loss="sparse_categorical_crossentropy")
    trainer.train(sdf)
    h = trainer.get_history()
    print(f"trained from merged store: loss {h[0]:.4f} -> {h[-1]:.4f}")
    assert h[-1] < h[0]


if __name__ == "__main__":
    main()
