"""Long-context training: ring attention shards the sequence across chips.

The reference tops out at an IMDB LSTM on one executor; this is the
long-context path the TPU rebuild treats as first-class (SURVEY.md §5):
the sequence axis is sharded over the ``seq`` mesh axis and K/V blocks rotate
around the ring via ``ppermute`` — peak attention memory per chip is
O((L/seq)^2), so context length scales with the mesh instead of HBM.

Dry-run anywhere (8 virtual chips, 2x4 data x seq mesh):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context_ring.py --seq-len 1024
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.transformer import TransformerLM
from distkeras_tpu.parallel.spmd import SPMDEngine
from distkeras_tpu.runtime.mesh import DATA_AXIS, SEQ_AXIS, hybrid_mesh


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--seq-shards", type=int, default=4)
    args = p.parse_args()

    n = jax.device_count()
    sp = min(args.seq_shards, n)
    mesh = hybrid_mesh({DATA_AXIS: n // sp, SEQ_AXIS: sp})
    print(f"mesh: {dict(mesh.shape)} — each chip owns "
          f"{args.seq_len // sp} of {args.seq_len} tokens")

    arch = dict(vocab_size=args.vocab, num_layers=args.layers,
                d_model=args.d_model, num_heads=4, d_ff=4 * args.d_model,
                max_seq_len=args.seq_len)
    model = Model.build(TransformerLM(**arch),
                        jnp.zeros((1, args.seq_len), jnp.int32))
    # Same params, ring-attention twin for the sharded step.
    model = Model(module=TransformerLM(**arch, seq_axis=SEQ_AXIS,
                                       attn_impl="ring"),
                  params=model.params)
    engine = SPMDEngine(model, "adam", "sparse_categorical_crossentropy",
                        mesh, tp_rules=(), learning_rate=3e-4)
    state = engine.init_state()

    rng = np.random.default_rng(0)
    B = 2 * mesh.shape[DATA_AXIS]
    toks = rng.integers(0, args.vocab, size=(B, args.seq_len))
    x = jax.device_put(jnp.asarray(toks, jnp.int32), engine.batch_sharding())
    t = jax.device_put(jnp.asarray(np.roll(toks, -1, 1), jnp.int32),
                       engine.batch_sharding())

    for step in range(args.steps):
        state, loss = engine.step(state, x, t)
        if step % 2 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  loss {float(loss):.4f}")
    print("ring-attention training step runs; context sharded across the mesh")


if __name__ == "__main__":
    main()
