"""BASELINE config #4: IMDB LSTM sentiment under DynSGD (staleness-aware folds).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/imdb_dynsgd.py --workers 4 --epochs 2
"""

import argparse

import distkeras_tpu as dk
from distkeras_tpu.datasets import imdb
from distkeras_tpu.evaluators import AccuracyEvaluator
from distkeras_tpu.models.lstm import imdb_lstm
from distkeras_tpu.predictors import ClassPredictor


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--window", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.002)
    p.add_argument("--rows", type=int, default=8192)
    p.add_argument("--vocab", type=int, default=2000)
    p.add_argument("--seq-len", type=int, default=80)
    p.add_argument("--data-dir", default=None)
    args = p.parse_args()

    df = imdb(n=args.rows, vocab_size=args.vocab, seq_len=args.seq_len,
              data_dir=args.data_dir)
    train_df, test_df = df.split(0.9, seed=1)

    model = imdb_lstm(vocab_size=args.vocab, embed_dim=64, hidden_size=64,
                      seq_len=args.seq_len)
    trainer = dk.DynSGD(
        model, worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        batch_size=args.batch_size, num_epoch=args.epochs,
        num_workers=args.workers, communication_window=args.window,
        learning_rate=args.lr,
    )
    trained = trainer.train(train_df, shuffle=True)
    h = trainer.get_history()
    print(f"DynSGD: loss {h[0]:.4f} -> {h[-1]:.4f} in {trainer.get_training_time():.1f}s")

    pred = ClassPredictor(trained, features_col="features",
                          output_col="prediction").predict(test_df)
    print("test accuracy:", AccuracyEvaluator(prediction_col="prediction",
                                              label_col="label").evaluate(pred))


if __name__ == "__main__":
    main()
