"""North-star accuracy leg (BASELINE.md #3; VERDICT r4 missing #2).

The gate demands >= 90 % linear scaling *at ADAG-equivalent final accuracy*.
The scaling half is bounded analytically and test-pinned
(``tests/test_scaling_model.py``); THIS script closes the accuracy half on
the gate's own model: the bench CIFAR-10 CNN (``models/cnn.py::cifar10_cnn``)
trained to convergence under **ADAG**, **AEASGD** (the north-star
discipline), and **sync-DP**, with matched sample budgets, at a W=8
multiplexed-on-one-chip topology (window 8, global batch 1024; the
throughput bench retuned its B separately — architecture and discipline
are what the accuracy claim needs), across >= 3 seeds — final held-out
accuracy must agree within epsilon. One chip suffices: this is an
accuracy claim, not a scaling claim.

Writes ``ACCURACY_r05.json`` (the committed artifact) and prints it. The
CIFAR-10 source is ``datasets.cifar10``: real data when present in
``--data-dir``, otherwise the structured synthetic stand-in — flagged in
the artifact via ``synthetic`` (this build environment has no egress;
BASELINE.md's provenance rules apply).

A CPU-sized twin of the same comparison is pinned in
``tests/test_accuracy_gate.py``.

    PYTHONPATH=.:/root/.axon_site python accuracy_gate.py
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


EPSILON = 0.02  # max allowed |acc(discipline) - acc(ADAG)| on seed means


def run_gate(seeds=(0, 1, 2), n_train=40960, n_eval=8192, num_workers=8,
             window=8, batch_size=128, num_epoch=3, learning_rate=0.05,
             data_dir=None):
    import jax
    import jax.numpy as jnp

    import distkeras_tpu as dk
    from distkeras_tpu.datasets import cifar10
    from distkeras_tpu.models.cnn import cifar10_cnn

    df_all = cifar10(n=n_train + n_eval, data_dir=data_dir)
    x = np.asarray(df_all["features"])
    y = np.asarray(df_all["label"])
    # Fixed split; shuffle before so synthetic class structure can't align
    # with the worker-contiguous partitioning.
    perm = np.random.default_rng(123).permutation(len(x))
    x, y = x[perm], y[perm]
    train = dk.DataFrame({"features": x[:n_train], "label": y[:n_train]})
    te_x, te_y = x[n_train:], y[n_train:]

    common = dict(loss="sparse_categorical_crossentropy",
                  num_workers=num_workers, batch_size=batch_size,
                  num_epoch=num_epoch, learning_rate=learning_rate,
                  compute_dtype="bfloat16")

    def make(disc, model, seed):
        if disc == "adag":
            return dk.ADAG(model, communication_window=window, seed=seed,
                           **common)
        if disc == "aeasgd":
            # Elastic rate: the center fold adds SUM_w alpha*(w - center),
            # so stability needs W*alpha < 1 (Zhang et al.'s beta = W*alpha
            # = 0.4 sizing). rho = alpha/lr -> alpha = 0.05, W*alpha = 0.4.
            return dk.AEASGD(model, communication_window=window, seed=seed,
                             rho=0.05 / learning_rate, **common)
        if disc == "sync":
            return dk.SynchronousDistributedTrainer(
                model, steps_per_program=window, seed=seed, **common)
        raise KeyError(disc)

    def accuracy(model):
        preds = []
        for s in range(0, len(te_x), 2048):
            preds.append(np.asarray(
                model.predict(jnp.asarray(te_x[s:s + 2048]))).argmax(-1))
        return float((np.concatenate(preds) == te_y).mean())

    out: dict = {
        "metric": "cifar10_cnn_final_accuracy_gap_aeasgd_vs_adag",
        "unit": "abs difference of seed-mean held-out accuracy",
        "epsilon": EPSILON,
        "synthetic": bool(getattr(df_all, "synthetic", True)),
        "config": {"num_workers": num_workers, "window": window,
                   "batch_size_per_worker": batch_size,
                   "global_batch": batch_size * num_workers,
                   "num_epoch": num_epoch, "learning_rate": learning_rate,
                   "n_train": n_train, "n_eval": n_eval,
                   "samples_budget": n_train * num_epoch,
                   "seeds": list(seeds),
                   "model": "cifar10_cnn (bench config #3 architecture)"},
        "disciplines": {},
    }
    for disc in ("adag", "aeasgd", "sync"):
        accs, losses = [], []
        for seed in seeds:
            t0 = time.perf_counter()
            trainer = make(disc, cifar10_cnn(seed=seed), seed)
            trained = trainer.train(train, shuffle=True)
            accs.append(accuracy(trained))
            h = trainer.get_history()
            losses.append([float(h[0]), float(h[-1])])
            print(f"[gate] {disc} seed {seed}: acc {accs[-1]:.4f} "
                  f"loss {h[0]:.3f}->{h[-1]:.3f} "
                  f"({time.perf_counter() - t0:.0f}s)", flush=True)
        out["disciplines"][disc] = {
            "accuracies": [round(a, 4) for a in accs],
            "mean": round(float(np.mean(accs)), 4),
            "std": round(float(np.std(accs)), 4),
            "loss_first_last": losses,
        }
    adag = out["disciplines"]["adag"]["mean"]
    out["gaps_vs_adag"] = {
        d: round(abs(out["disciplines"][d]["mean"] - adag), 4)
        for d in ("aeasgd", "sync")}
    out["value"] = out["gaps_vs_adag"]["aeasgd"]
    out["passes"] = bool(out["value"] < EPSILON)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=os.environ.get("CIFAR10_DIR"))
    p.add_argument("--out", default="ACCURACY_r05.json")
    args = p.parse_args()
    rec = run_gate(data_dir=args.data_dir)
    # The synthetic stand-in saturates at matched full budgets (every
    # discipline -> 1.0), which makes the epsilon comparison vacuous. A
    # budget-starved twin (1/10 the samples, 1 epoch) stops short of
    # saturation, so the disciplines' PARTIAL-convergence accuracies have
    # to agree too — a strictly harder equivalence.
    low = run_gate(n_train=8192, n_eval=4096, num_epoch=1, batch_size=32,
                   data_dir=args.data_dir)
    rec["low_budget"] = {
        "config": low["config"],
        "disciplines": low["disciplines"],
        "gaps_vs_adag": low["gaps_vs_adag"],
        "passes": low["passes"],
    }
    rec["note"] = (
        "The synthetic CIFAR stand-in (datasets.cifar10, linearly-"
        "separable-ish class blocks) saturates every discipline to 1.0 "
        "held-out accuracy even at the 1/15-budget pass, so the gaps are "
        "trivially zero; the per-seed loss_first_last curves record the "
        "distinct optimization trajectories. On real CIFAR-10 (drop the "
        "pickle batches in --data-dir) the same protocol produces the "
        "non-saturated comparison; no real data is available in this "
        "egress-less environment (BASELINE.md provenance).")
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps({k: rec[k] for k in
                      ("metric", "value", "epsilon", "passes", "synthetic")}
                     | {"low_budget_gaps": low["gaps_vs_adag"]}))


if __name__ == "__main__":
    main()
