"""On-device augmentation (``ops/augment.py`` + ``device_transform=``).

The host ``transform=`` hook's jitted sibling: crop/flip runs inside the
round program, so out-of-core image pipelines stage raw uint8 and the chip
does the rest (docs/PERFORMANCE.md "Feed overlap").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.ops.augment import flip_crop_transform, random_flip_crop


def _images(n=8, hw=16, dtype=np.uint8, seed=0):
    rng = np.random.default_rng(seed)
    if dtype == np.uint8:
        return rng.integers(0, 256, size=(n, hw, hw, 3)).astype(np.uint8)
    return rng.random((n, hw, hw, 3)).astype(dtype)


def test_random_flip_crop_shapes_dtype_and_determinism():
    x = jnp.asarray(_images())
    k = jax.random.key(0)
    out1 = random_flip_crop(k, x)
    out2 = random_flip_crop(k, x)
    assert out1.shape == x.shape and out1.dtype == x.dtype
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # A different key gives a different augmentation.
    out3 = random_flip_crop(jax.random.key(1), x)
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))


def test_random_flip_crop_content_is_a_crop_of_pad_or_flip():
    """Every output row must equal SOME (flip, y, x) crop of its input row —
    the transform can distort nothing, only translate/mirror."""
    x = _images(n=4, hw=8)
    out = np.asarray(random_flip_crop(jax.random.key(3), jnp.asarray(x)))
    pad = 4
    for i in range(len(x)):
        candidates = []
        for flip in (False, True):
            img = x[i, :, ::-1] if flip else x[i]
            padded = np.pad(img, ((pad, pad), (pad, pad), (0, 0)),
                            mode="reflect")
            for yy in range(2 * pad + 1):
                for xx in range(2 * pad + 1):
                    candidates.append(padded[yy:yy + 8, xx:xx + 8])
        assert any(np.array_equal(out[i], c) for c in candidates), i


def test_device_transform_trains_from_uint8_store():
    """End-to-end: uint8 features + device_transform crop/flip + in-graph
    /255 normalization under both engines; finite decreasing loss."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import SimpleCNN

    rng = np.random.default_rng(0)
    n, hw, c = 256, 16, 3
    y = rng.integers(0, c, size=n).astype(np.int32)
    # Class-dependent brightness so the tiny CNN can learn from uint8.
    x = (rng.integers(0, 60, size=(n, hw, hw, 3))
         + y[:, None, None, None] * 80).clip(0, 255).astype(np.uint8)
    df = dk.DataFrame({"features": x, "label": y})
    model = Model.build(SimpleCNN(conv_features=(8,), dense=(16,),
                                  num_outputs=c),
                        jnp.zeros((1, hw, hw, 3), jnp.float32))
    from distkeras_tpu.ops.augment import flip_crop_transform as fct

    for make in (
        lambda: dk.SynchronousDistributedTrainer(
            model, loss="sparse_categorical_crossentropy", num_workers=2,
            batch_size=8, num_epoch=2, learning_rate=0.05,
            steps_per_program=2, device_transform=fct(pad=2)),
        lambda: dk.ADAG(
            model, loss="sparse_categorical_crossentropy", num_workers=2,
            batch_size=8, num_epoch=2, learning_rate=0.05,
            communication_window=2, device_transform=fct(pad=2)),
    ):
        t = make()
        t.train(df)
        h = t.get_history()
        assert np.isfinite(h).all()
        assert h[-1] < h[0], h


def test_checkpoint_resume_exact_under_device_transform(tmp_path):
    """The augmentation rng rides the engine's carried key chain, so a
    checkpointed run resumes to EXACTLY the uninterrupted run's weights —
    per-round augmentations included."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.cnn import SimpleCNN
    from distkeras_tpu.ops.augment import flip_crop_transform

    pytest.importorskip("orbax.checkpoint")
    rng = np.random.default_rng(0)
    n, hw, c = 256, 12, 3
    y = rng.integers(0, c, size=n).astype(np.int32)
    x = (rng.integers(0, 60, size=(n, hw, hw, 3))
         + y[:, None, None, None] * 80).clip(0, 255).astype(np.uint8)
    df = dk.DataFrame({"features": x, "label": y})

    def model():
        return Model.build(SimpleCNN(conv_features=(8,), dense=(16,),
                                     num_outputs=c),
                           jnp.zeros((1, hw, hw, 3), jnp.float32))

    common = dict(loss="sparse_categorical_crossentropy", num_workers=2,
                  batch_size=8, communication_window=2, learning_rate=0.05,
                  device_transform=flip_crop_transform(pad=2))
    full = dk.ADAG(model(), num_epoch=4, **common)
    m_full = full.train(df)

    ck = str(tmp_path / "ck")
    a = dk.ADAG(model(), num_epoch=2, checkpoint_dir=ck, checkpoint_every=1,
                **common)
    a.train(df)
    b = dk.ADAG(model(), num_epoch=4, checkpoint_dir=ck, checkpoint_every=1,
                resume=True, **common)
    m_b = b.train(df)
    for p, q in zip(jax.tree.leaves(m_full.params),
                    jax.tree.leaves(m_b.params)):
        np.testing.assert_allclose(np.asarray(p), np.asarray(q), atol=1e-5)


def test_uint8_predict_matches_float_predict():
    """Train/inference parity for raw-byte stores: Model.predict and
    ModelPredictor on uint8 features == the same features pre-divided by
    255 — the skew guard for the uint8 rule in make_local_loop."""
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import ModelPredictor
    import distkeras_tpu as dk

    rng = np.random.default_rng(0)
    x8 = rng.integers(0, 256, size=(16, 8)).astype(np.uint8)
    xf = x8.astype(np.float32) / 255.0
    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    np.testing.assert_allclose(
        np.asarray(model.predict(jnp.asarray(x8))),
        np.asarray(model.predict(jnp.asarray(xf))), rtol=1e-6)
    out8 = ModelPredictor(model).predict(dk.DataFrame({"features": x8}))
    outf = ModelPredictor(model).predict(dk.DataFrame({"features": xf}))
    np.testing.assert_allclose(np.asarray(out8["prediction"]),
                               np.asarray(outf["prediction"]), rtol=1e-6)


def test_uint8_features_normalized_in_graph():
    """make_local_loop's uint8 rule: a uint8 batch trains identically to
    the same batch pre-divided by 255 as float32."""
    import optax

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.workers import make_local_loop

    rng = np.random.default_rng(0)
    x8 = rng.integers(0, 256, size=(2, 4, 8)).astype(np.uint8)
    xf = x8.astype(np.float32) / 255.0
    y = rng.integers(0, 3, size=(2, 4)).astype(np.int32)
    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    from distkeras_tpu.ops.losses import get_loss

    tx = optax.sgd(0.1)
    loop = make_local_loop(model.module,
                           get_loss("sparse_categorical_crossentropy"), tx)
    opt = tx.init(model.params)
    p_a, _, _, loss_a = loop(model.params, opt, jnp.asarray(x8),
                             jnp.asarray(y), jax.random.key(0), None)
    p_b, _, _, loss_b = loop(model.params, opt, jnp.asarray(xf),
                             jnp.asarray(y), jax.random.key(0), None)
    np.testing.assert_allclose(np.asarray(loss_a), np.asarray(loss_b),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p_a), jax.tree.leaves(p_b)):
        # atol floors the comparison for near-zero weights: the in-graph
        # x/255 and the precomputed float batch take different fusion paths,
        # so single-ulp (~1e-9) wobble on ~1e-4 params is expected.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6,
                                   atol=1e-8)


def test_normalize_uint8_opt_out_threads_through_surfaces():
    """ADVICE r5: byte-valued NON-image features must be able to opt out of
    the silent /255 rule. The flag lives on the Model and threads through
    Trainer and ModelPredictor; when the rule DOES fire, it warns once."""
    import warnings

    import distkeras_tpu as dk
    from distkeras_tpu.models import base as mbase
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import ModelPredictor

    rng = np.random.default_rng(0)
    x8 = rng.integers(0, 4, size=(16, 8)).astype(np.uint8)  # byte categorial
    opted = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32),
                        normalize_uint8=False)
    # Opted out: the bytes reach the module raw (promotion, no /255).
    np.testing.assert_allclose(
        np.asarray(opted.predict(jnp.asarray(x8))),
        np.asarray(opted.predict(jnp.asarray(x8.astype(np.float32)))),
        rtol=1e-6)
    # ModelPredictor inherits the model's flag.
    p = ModelPredictor(opted)
    assert p.normalize_uint8 is False
    out = p.predict(dk.DataFrame({"features": x8}))
    np.testing.assert_allclose(
        np.asarray(out["prediction"]),
        np.asarray(opted.predict(jnp.asarray(x8.astype(np.float32)))),
        rtol=1e-5, atol=1e-6)
    # The Trainer kwarg rebinds the model, so engines/remote loop see it.
    t = dk.ADAG(opted, normalize_uint8=False)
    assert t.model.normalize_uint8 is False
    on = Model.build(MLP(hidden=(8,), num_outputs=3),
                     jnp.zeros((1, 8), jnp.float32))
    t2 = dk.ADAG(on, normalize_uint8=False)
    assert t2.model.normalize_uint8 is False and on.normalize_uint8 is True
    # One-time warning when the rule fires (reset the once-flag for
    # determinism — other tests may already have tripped it).
    mbase._uint8_warned[0] = False
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        mbase.normalize_features(np.zeros(3, np.uint8))
        mbase.normalize_features(np.zeros(3, np.uint8))
    assert len([w for w in caught
                if "normalize_uint8" in str(w.message)]) == 1
    # Opt-out never warns (and never rescales).
    mbase._uint8_warned[0] = False
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = mbase.normalize_features(np.full(3, 255, np.uint8),
                                       normalize_uint8=False)
    assert not caught and out.dtype == np.uint8
