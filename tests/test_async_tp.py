"""Async disciplines x tensor parallelism (VERDICT r3 weak #5 / next #7).

The composition the flat 1-D engine could not express: each async worker is
itself a tp submesh. Pinned here: (a) on a TP-invariant model the (W=2, tp=2)
run matches the flat W=2 run discipline-for-discipline (sharding never
changes math); (b) a transformer genuinely tensor-shards under it and trains;
(c) the reference-shaped trainer surface accepts ``parallel={'model': n}``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.data.batching import make_batches
from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.parallel.async_tp import AsyncTPEngine
from distkeras_tpu.parallel.disciplines import get_discipline
from distkeras_tpu.parallel.engine import AsyncEngine
from distkeras_tpu.parallel.sharding import TRANSFORMER_TP_RULES
from distkeras_tpu.runtime.mesh import data_mesh, hybrid_mesh

import envcaps


def _blob_df(n=512, d=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=3.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    return DataFrame({"features": x, "label": y.astype(np.int32)})


@pytest.mark.parametrize("disc_name", ["aeasgd", "adag", "dynsgd"])
@envcaps.skip_unless_key_sharding()
def test_tp_async_matches_flat_worker_run(disc_name):
    """(W=2, tp=2) == flat W=2 on a TP-invariant model: same worker ids,
    same rngs, same commits — sharding must not change the math."""
    df = _blob_df()
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    W, window = 2, 2

    def disc():
        return (get_discipline("aeasgd", alpha=0.05) if disc_name == "aeasgd"
                else get_discipline(disc_name))

    plan = make_batches(df, "features", "label", batch_size=8, num_workers=W,
                        window=window, num_epoch=2)
    flat = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                       disc(), data_mesh(num_workers=W), window=window,
                       learning_rate=0.05)
    tp = AsyncTPEngine(model, "sgd", "sparse_categorical_crossentropy",
                       disc(), hybrid_mesh({"data": W, "model": 2}),
                       window=window, rules=TRANSFORMER_TP_RULES,
                       learning_rate=0.05)
    state_flat, losses_flat = flat.run(plan)
    state_tp, losses_tp = tp.run(plan)
    np.testing.assert_allclose(losses_tp, losses_flat, rtol=2e-5, atol=1e-6)
    # Final centers agree (engines are deterministic given the plan).
    for a, b in zip(jax.tree.leaves(jax.device_get(state_tp.center)),
                    jax.tree.leaves(jax.device_get(state_flat.center))):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


@envcaps.skip_unless_key_sharding()
def test_transformer_tensor_shards_and_trains_under_aeasgd():
    """The composition in anger: a TransformerLM whose per-worker replicas
    are genuinely tp-sharded (param leaves carry the 'model' axis) trains
    under AEASGD with a decreasing loss."""
    from distkeras_tpu.models.transformer import TransformerLM

    L, V = 16, 64
    model = Model.build(
        TransformerLM(vocab_size=V, num_layers=2, d_model=32, num_heads=2,
                      d_ff=64, max_seq_len=L),
        jnp.zeros((1, L), jnp.int32))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, size=(512, L))
    df = DataFrame({"features": toks.astype(np.int32),
                    "label": np.roll(toks, -1, 1).astype(np.int32)})
    W, window = 2, 2
    plan = make_batches(df, "features", "label", batch_size=8, num_workers=W,
                        window=window, num_epoch=2)
    engine = AsyncTPEngine(
        model, "adam", "sparse_categorical_crossentropy",
        get_discipline("aeasgd", alpha=0.05),
        hybrid_mesh({"data": W, "model": 2}), window=window,
        rules=TRANSFORMER_TP_RULES, learning_rate=1e-3)
    state = engine.init_state()

    # The per-worker stacked replicas really shard over BOTH axes: worker
    # axis 'data' on dim 0, tp axis 'model' on the rule-matched param dim.
    flat = jax.tree_util.tree_flatten_with_path(state.locals_)[0]
    tp_leaves = [
        (path, leaf) for path, leaf in flat
        if "mlp_up" in "/".join(str(getattr(p, "key", p)) for p in path)
        and "kernel" in "/".join(str(getattr(p, "key", p)) for p in path)]
    assert tp_leaves, "no mlp_up kernels found in stacked state"
    for _, leaf in tp_leaves:
        spec = leaf.sharding.spec
        assert spec[0] == "data" and "model" in tuple(spec), spec

    state, losses = engine.run(plan, state)
    assert np.isfinite(losses).all()
    assert np.mean(losses[-2:]) < np.mean(losses[:2])


@envcaps.skip_unless_key_sharding()
def test_trainer_surface_accepts_parallel_model():
    """Reference-shaped call: AEASGD(model, num_workers=2,
    parallel={'model': 2}).train(df) -> trained model."""
    import distkeras_tpu as dk

    df = _blob_df()
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    tr = dk.AEASGD(model, num_workers=2, parallel={"model": 2},
                   batch_size=8, communication_window=2, num_epoch=2,
                   loss="sparse_categorical_crossentropy", learning_rate=0.05)
    trained = tr.train(df)
    x = np.asarray(df["features"])
    acc = (np.asarray(trained.predict(jnp.asarray(x))).argmax(-1)
           == np.asarray(df["label"])).mean()
    assert acc > 0.85, acc
    assert len(tr.get_history()) == plan_rounds(512, 2, 2, 8) * 2


def plan_rounds(n, W, K, B):
    return n // (W * K * B)


@envcaps.skip_unless_key_sharding()
def test_checkpoint_resume_under_tp_async(tmp_path):
    """The full trainer surface holds for the composed engine: a
    checkpointed W=2 x tp=2 AEASGD run resumes to exactly the
    uninterrupted run's weights (shared init/adopt sharding hooks)."""
    pytest.importorskip("orbax.checkpoint")
    import distkeras_tpu as dk

    df = _blob_df()

    def model():
        return Model.build(MLP(hidden=(16,), num_outputs=3),
                           jnp.zeros((1, 8), jnp.float32))

    ck = str(tmp_path / "ck")
    common = dict(loss="sparse_categorical_crossentropy", num_workers=2,
                  parallel={"model": 2}, batch_size=8,
                  communication_window=2, learning_rate=0.05)
    t_full = dk.AEASGD(model(), num_epoch=4, **common)
    m_full = t_full.train(df)

    t_a = dk.AEASGD(model(), num_epoch=2, checkpoint_dir=ck,
                    checkpoint_every=1, **common)
    t_a.train(df)
    t_b = dk.AEASGD(model(), num_epoch=4, checkpoint_dir=ck,
                    checkpoint_every=1, resume=True, **common)
    m_b = t_b.train(df)

    assert (len(t_b.get_history())
            == len(t_full.get_history()) - len(t_a.get_history()))
    for a, b in zip(jax.tree.leaves(m_full.params),
                    jax.tree.leaves(m_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def _lm_df(L=16, V=64, n=512, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, V, size=(n, L))
    return DataFrame({"features": toks.astype(np.int32),
                      "label": np.roll(toks, -1, 1).astype(np.int32)})


def _lm_plan(df, W=2, window=2, batch=8, epochs=2):
    return make_batches(df, "features", "label", batch_size=batch,
                        num_workers=W, window=window, num_epoch=epochs)


def _transformer(attn_impl="dense", seq_axis=None, L=16, V=64, seed=0):
    from distkeras_tpu.models.transformer import TransformerLM

    model = Model.build(
        TransformerLM(vocab_size=V, num_layers=2, d_model=32, num_heads=2,
                      d_ff=64, max_seq_len=L, attn_impl=attn_impl),
        jnp.zeros((1, L), jnp.int32), seed=seed)
    if seq_axis is not None:
        # Seq-sharded modules trace axis_index(seq) — init dense, rebind.
        model = model.with_module(model.module.clone(seq_axis=seq_axis))
    return model


@pytest.mark.parametrize("disc_name", ["aeasgd", "adag"])
@envcaps.skip_unless_key_sharding()
def test_flash_attention_under_async_tp(disc_name):
    """The r4 gap (VERDICT r4 missing #1): the flagship flash-attention
    transformer trains under the async disciplines with tp>1. The Mosaic
    kernel self-manualizes over the auto 'model' axis inside the engine's
    partially-manual shard_map; losses must match the dense twin (flash is
    exact attention) and decrease."""
    df = _lm_df()
    W, window = 2, 2
    losses = {}
    for impl in ("dense", "flash"):
        disc = (get_discipline("aeasgd", alpha=0.05) if disc_name == "aeasgd"
                else get_discipline(disc_name))
        engine = AsyncTPEngine(
            _transformer(attn_impl=impl), "adam",
            "sparse_categorical_crossentropy", disc,
            hybrid_mesh({"data": W, "model": 2}), window=window,
            rules=TRANSFORMER_TP_RULES, learning_rate=1e-3)
        _, losses[impl] = engine.run(_lm_plan(df, W, window))
    np.testing.assert_allclose(losses["flash"], losses["dense"], rtol=2e-3)
    assert np.mean(losses["flash"][-2:]) < np.mean(losses["flash"][:2])


@envcaps.skip_unless_key_sharding()
def test_sequence_parallel_under_async_tp():
    """Sequence parallelism composes with the async disciplines: a
    seq-sharded ring-attention worker (sp=2 x tp=2 submesh per worker)
    matches the flat dense W=2 run — ring attention is exact and the
    per-step seq-pmean keeps replicas identical across seq shards."""
    df = _lm_df()
    W, window = 2, 2
    flat = AsyncEngine(
        _transformer(), "adam", "sparse_categorical_crossentropy",
        get_discipline("aeasgd", alpha=0.05), data_mesh(num_workers=W),
        window=window, learning_rate=1e-3)
    _, losses_flat = flat.run(_lm_plan(df, W, window))
    sp = AsyncTPEngine(
        _transformer(attn_impl="ring", seq_axis="seq"), "adam",
        "sparse_categorical_crossentropy",
        get_discipline("aeasgd", alpha=0.05),
        hybrid_mesh({"data": W, "seq": 2, "model": 2}), window=window,
        rules=TRANSFORMER_TP_RULES, learning_rate=1e-3)
    _, losses_sp = sp.run(_lm_plan(df, W, window))
    np.testing.assert_allclose(losses_sp, losses_flat, rtol=2e-3, atol=1e-5)


@envcaps.skip_unless_key_sharding()
def test_trainer_surface_accepts_parallel_seq():
    """Reference-shaped call with the composed mesh: AEASGD(transformer,
    num_workers=2, parallel={'model': 2, 'seq': 2}).train(df)."""
    import distkeras_tpu as dk

    df = _lm_df(n=128)
    tr = dk.AEASGD(_transformer(attn_impl="ring", seq_axis="seq"),
                   num_workers=2, parallel={"model": 2, "seq": 2},
                   batch_size=8, communication_window=2, num_epoch=1,
                   loss="sparse_categorical_crossentropy",
                   worker_optimizer="adam", learning_rate=1e-3)
    tr.train(df)
    hist = tr.get_history()
    assert len(hist) == 4 and np.isfinite(hist).all()


def test_async_tp_rejects_seq_model_without_seq_axis():
    with pytest.raises(ValueError, match="seq_axis"):
        AsyncTPEngine(
            _transformer(), "adam", "sparse_categorical_crossentropy",
            get_discipline("adag"),
            hybrid_mesh({"data": 2, "seq": 2, "model": 2}), window=2,
            rules=TRANSFORMER_TP_RULES)
    with pytest.raises(ValueError, match="no 'seq' axis"):
        AsyncTPEngine(
            _transformer(attn_impl="ring", seq_axis="seq"), "adam",
            "sparse_categorical_crossentropy", get_discipline("adag"),
            hybrid_mesh({"data": 2, "model": 2}), window=2,
            rules=TRANSFORMER_TP_RULES)


def test_parallel_rejects_unknown_axes_and_multiplex():
    import distkeras_tpu as dk

    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    with pytest.raises(ValueError, match="only {'model': n}"):
        dk.AEASGD(model, num_workers=2, parallel={"pipe": 2},
                  batch_size=8)._tp_engine()


def test_non_communicating_trainers_reject_parallel_with_guidance():
    """VERDICT r4 weak #5: parallel= on Averaging/Ensemble/Sync must raise
    a targeted error naming ParallelTrainer, not a bare TypeError."""
    import distkeras_tpu as dk

    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    for cls in (dk.AveragingTrainer, dk.EnsembleTrainer,
                dk.SynchronousDistributedTrainer, dk.SingleTrainer):
        with pytest.raises(ValueError, match="ParallelTrainer"):
            cls(model, parallel={"model": 2}, batch_size=8)
