"""BatchNorm "carry" support: mutable state through every engine.

The reference's 2016-era notebooks use stock Keras BatchNorm layers; SURVEY.md
flagged the adapter's rejection as a parity gap. Carry mode threads the
non-trainable state through the training window and cross-replica-pmeans it at
every fold — deterministic running statistics, vs the reference's raced socket
overwrites.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import linen as nn

import distkeras_tpu as dk
from distkeras_tpu.models import Model
from distkeras_tpu.models.base import DKModule, register_model


@register_model
class BNMLP(DKModule):
    """Tiny flax model with real BatchNorm running statistics."""

    hidden: int = 16
    num_outputs: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(self.hidden)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_outputs)(x)


def blob_df(n=640, d=4, c=3, seed=0, scale=10.0, shift=5.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))) * scale + shift
    return dk.DataFrame({"features": x.astype(np.float32),
                         "label": y.astype(np.int32)})


def bn_model(d=4, c=3, seed=0):
    m = Model.build(BNMLP(num_outputs=c), jnp.zeros((1, d), jnp.float32), seed=seed)
    assert m.state is not None and "batch_stats" in m.state
    return m


def accuracy(model, df):
    logits = np.asarray(model.predict(jnp.asarray(df["features"])))
    return float((logits.argmax(-1) == df["label"]).mean())


COMMON = dict(loss="sparse_categorical_crossentropy", batch_size=16, num_epoch=4,
              learning_rate=0.05)


def test_bn_single_trainer_updates_stats_and_converges():
    df = blob_df()
    m = bn_model()
    init_stats = jax.tree.map(np.asarray, m.state)
    t = dk.SingleTrainer(m, **COMMON)
    trained = t.train(df)
    # running stats moved toward the (shifted, scaled) data statistics
    assert trained.state is not None
    moved = jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        init_stats, trained.state)
    assert max(jax.tree.leaves(moved)) > 0.1, moved
    # inference (running-average mode) is accurate: stats really are trained
    assert accuracy(trained, df) > 0.9


@pytest.mark.parametrize("cls,kwargs", [
    (dk.SynchronousDistributedTrainer, {}),
    (dk.ADAG, dict(communication_window=4)),
    (dk.AEASGD, dict(communication_window=4, rho=2.0, num_epoch=6)),
])
def test_bn_distributed_trainers(cls, kwargs):
    df = blob_df()
    merged = {**COMMON, **kwargs}
    t = cls(bn_model(), num_workers=4, **merged)
    trained = t.train(df, shuffle=True)
    assert trained.state is not None
    assert accuracy(trained, df) > 0.85, f"{cls.__name__} BN failed to converge"


def test_bn_state_serialization_roundtrip():
    df = blob_df(n=320)
    trained = dk.SingleTrainer(bn_model(), **COMMON).train(df)
    blob = dk.serialize_model(trained)
    back = dk.deserialize_model(blob)
    np.testing.assert_allclose(
        np.asarray(back.predict(jnp.asarray(df["features"][:16]))),
        np.asarray(trained.predict(jnp.asarray(df["features"][:16]))),
        rtol=1e-5, atol=1e-6)


def test_keras_batchnorm_carry():
    keras = pytest.importorskip("keras")
    from distkeras_tpu.models.keras_adapter import from_keras

    km = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(16),
        keras.layers.BatchNormalization(momentum=0.8),
        keras.layers.Activation("relu"),
        keras.layers.Dense(3),
    ])
    df = blob_df()
    model = from_keras(km, sample_input=np.zeros((1, 4), np.float32),
                       batchnorm="carry")
    assert model.state is not None
    init_state = jax.tree.map(np.asarray, model.state)
    t = dk.SynchronousDistributedTrainer(model, num_workers=4,
                                         **{**COMMON, "num_epoch": 6})
    trained = t.train(df, shuffle=True)
    moved = max(jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        init_state, trained.state)))
    assert moved > 0.1, "BN running stats never updated"
    assert accuracy(trained, df) > 0.85


def test_bn_ensemble_members_keep_own_stats():
    """EnsembleFold must NOT pmean state: each member's running statistics
    have to match its own (independently initialized + trained) params."""
    df = blob_df()
    t = dk.EnsembleTrainer(bn_model(), num_workers=4, **COMMON)
    models = t.train(df, shuffle=True)
    stats = [np.concatenate([np.ravel(l) for l in jax.tree.leaves(m.state)])
             for m in models]
    diffs = [np.abs(stats[0] - s).max() for s in stats[1:]]
    assert max(diffs) > 1e-4, "ensemble members share identical BN stats"


def test_keras_carry_rejects_stateful_seeds():
    keras = pytest.importorskip("keras")
    from distkeras_tpu.models.keras_adapter import from_keras

    km = keras.Sequential([
        keras.layers.Input((4,)),
        keras.layers.Dense(8),
        keras.layers.BatchNormalization(),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(3),
    ])
    with pytest.raises(ValueError, match="carry"):
        from_keras(km, sample_input=np.zeros((1, 4), np.float32),
                   batchnorm="carry")
