"""Fleet control plane: gang placement, quotas, preemption-driven
shrink/expand with floor guarantees, forced-preemption chaos, crashed-
worker restarts, per-tenant telemetry attribution, and the per-host port
pool. The scheduler is driven tick-by-tick with synthetic runtimes for
determinism; one integration test runs real elastic training through a
netps parameter server."""

import threading
import time

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.fleet import (
    DONE,
    FAILED,
    QUEUED,
    RUNNING,
    FleetJob,
    FleetScheduler,
    PortPool,
    parse_quotas,
)
from distkeras_tpu.fleet.ports import reserve_port
from distkeras_tpu.resilience.faults import FaultPlan, set_net_plan


class FakeRuntime:
    """Synthetic work: ``total`` claimable steps, one per ``step_s``."""

    def __init__(self, total=1000, step_s=0.002, crash_first=0):
        self.total = int(total)
        self.step_s = float(step_s)
        self.n = 0
        self.lock = threading.Lock()
        self.revoked: list = []
        self.closed = False
        self.started = 0
        self._crashes_left = int(crash_first)

    def ensure_started(self):
        self.started += 1

    def worker_main(self, wid, should_run):
        with self.lock:
            if self._crashes_left > 0:
                self._crashes_left -= 1
                raise RuntimeError("injected worker crash")
        while should_run():
            with self.lock:
                if self.n >= self.total:
                    return
                self.n += 1
            time.sleep(self.step_s)

    def progress(self):
        return self.n

    def done(self):
        return self.n >= self.total

    def revoke(self, wid):
        self.revoked.append(wid)

    def close(self):
        self.closed = True


def drive(sched, until, timeout=20.0, tick_sleep=0.002):
    """Tick the scheduler on this thread until ``until()`` or timeout."""
    deadline = time.monotonic() + timeout
    while not until():
        assert time.monotonic() < deadline, "scheduler scenario timed out"
        sched.tick()
        time.sleep(tick_sleep)


def teardown(sched):
    sched.close()
    assert sched.floor_violations == 0


# ---------------------------------------------------------------------------
# Gang placement, FIFO, quotas
# ---------------------------------------------------------------------------

def test_gang_placement_waits_for_min_gang():
    sched = FleetScheduler(capacity=4, tick_s=0.01)
    big = sched.submit(FleetJob("big", "a", FakeRuntime(total=40),
                                min_gang=4, max_workers=4))
    late = sched.submit(FleetJob("late", "b", FakeRuntime(total=10),
                                 min_gang=2, max_workers=2))
    sched.tick()
    # The whole pool went to the 4-gang; the 2-gang must WAIT (no partial
    # placement, no slot theft) until capacity frees.
    assert big.state == RUNNING and late.state == QUEUED
    drive(sched, lambda: big.state == DONE)
    drive(sched, lambda: late.state in (RUNNING, DONE))
    drive(sched, lambda: late.state == DONE)
    teardown(sched)


def test_min_gang_that_can_never_fit_is_rejected_at_submit():
    sched = FleetScheduler(capacity=2, tick_s=0.01)
    with pytest.raises(ValueError, match="exceeds pool capacity"):
        sched.submit(FleetJob("x", "a", FakeRuntime(), min_gang=3,
                              max_workers=3))
    with pytest.raises(ValueError, match="exceeds tenant quota"):
        FleetScheduler(capacity=8, quotas={"a": 1}).submit(
            FleetJob("x", "a", FakeRuntime(), min_gang=2, max_workers=2))
    teardown(sched)


def test_tenant_quota_caps_grants_and_expansion():
    sched = FleetScheduler(capacity=6, quotas={"capped": 2}, tick_s=0.01)
    job = sched.submit(FleetJob("j", "capped", FakeRuntime(total=60),
                                min_gang=1, max_workers=6))
    free = sched.submit(FleetJob("k", "free", FakeRuntime(total=60),
                                 min_gang=1, max_workers=6))
    peak = {"capped": 0, "free": 0}

    def watch():
        s = sched.stats()
        peak["capped"] = max(peak["capped"], s["capped/j"]["granted"])
        peak["free"] = max(peak["free"], s["free/k"]["granted"])
        return job.state == DONE and free.state == DONE

    drive(sched, watch)
    assert peak["capped"] == 2, "quota must cap the tenant at 2 slots"
    assert peak["free"] >= 4, "the unquota'd tenant takes the leftovers"
    teardown(sched)


def test_quota_blocked_head_does_not_starve_other_tenants():
    sched = FleetScheduler(capacity=6, quotas={"acme": 2}, tick_s=0.01)
    j1 = sched.submit(FleetJob("j1", "acme", FakeRuntime(total=200),
                               min_gang=2, max_workers=2))
    sched.tick()
    assert j1.state == RUNNING
    j2 = sched.submit(FleetJob("j2", "acme", FakeRuntime(total=10),
                               min_gang=1, max_workers=1))
    j3 = sched.submit(FleetJob("j3", "bidco", FakeRuntime(total=10),
                               min_gang=2, max_workers=2))
    sched.tick()
    # j2 is quota-blocked (acme at its cap) — waiting gains it nothing
    # (only acme's own jobs finishing frees headroom), so it must be
    # SKIPPED, not allowed to head-block bidco out of 4 free slots.
    assert j2.state == QUEUED and j3.state == RUNNING
    drive(sched, lambda: all(j.state == DONE for j in (j1, j2, j3)))
    teardown(sched)


def test_fifo_within_priority():
    sched = FleetScheduler(capacity=2, tick_s=0.01)
    first = sched.submit(FleetJob("first", "a", FakeRuntime(total=25),
                                  min_gang=2, max_workers=2))
    second = sched.submit(FleetJob("second", "a", FakeRuntime(total=5),
                                   min_gang=2, max_workers=2))
    sched.tick()
    assert first.state == RUNNING and second.state == QUEUED
    # The shorter job behind it must not jump the queue (head-blocking).
    drive(sched, lambda: second.state in (RUNNING, DONE))
    assert first.runtime.done(), "second placed before first finished"
    drive(sched, lambda: second.state == DONE)
    teardown(sched)


# ---------------------------------------------------------------------------
# Preemption: shrink, floor, full drain + requeue, re-expansion
# ---------------------------------------------------------------------------

def test_priority_preempts_by_shrinking_victims_to_floor_then_reexpands():
    sched = FleetScheduler(capacity=4, tick_s=0.01)
    victim = sched.submit(FleetJob("victim", "lo", FakeRuntime(total=250),
                                   priority=0, min_gang=2, max_workers=4))
    drive(sched, lambda: len([w for w in sched._granted[victim]]) == 4)
    hot = sched.submit(FleetJob("hot", "hi", FakeRuntime(total=30),
                                priority=5, min_gang=2, max_workers=2))
    # The victim shrinks to its floor (4 -> 2), never below; the hot gang
    # places as the released threads exit.
    drive(sched, lambda: hot.state == RUNNING)
    s = sched.stats()
    assert s["lo/victim"]["active"] == 2
    assert victim.shrinks == 2 and victim.preemptions == 2
    assert victim.state == RUNNING  # shrunk, not drained
    assert sorted(victim.runtime.revoked) == [2, 3]  # lease revocation fired
    # Hot finishes -> the victim re-expands toward max_workers.
    drive(sched, lambda: hot.state == DONE)
    drive(sched, lambda: sched.stats()["lo/victim"]["active"] == 4)
    assert victim.expands >= 2
    assert victim.debt == 0  # re-expansion paid the preemption debt back
    drive(sched, lambda: victim.state == DONE)
    teardown(sched)


def test_full_preemption_drains_gracefully_and_requeues_with_progress():
    sched = FleetScheduler(capacity=2, tick_s=0.01)
    victim = sched.submit(FleetJob("victim", "lo", FakeRuntime(total=120),
                                   priority=0, min_gang=2, max_workers=2))
    drive(sched, lambda: victim.state == RUNNING)
    drive(sched, lambda: victim.runtime.progress() >= 10)
    progress_at_preemption = victim.runtime.progress()
    hot = sched.submit(FleetJob("hot", "hi", FakeRuntime(total=20),
                                priority=5, min_gang=2, max_workers=2))
    # The victim is AT its floor: shrink is illegal, so it is fully
    # drained (graceful: release flag + revocation) and re-queued.
    drive(sched, lambda: hot.state == RUNNING)
    assert victim.state == QUEUED and victim.requeues == 1
    assert victim.preemptions == 2
    drive(sched, lambda: hot.state == DONE)
    drive(sched, lambda: victim.state == DONE)
    # Progress survived the preemption: the runtime kept its state.
    assert victim.runtime.progress() >= progress_at_preemption
    assert sched.stats()["lo/victim"]["debt"] == 0
    teardown(sched)


def test_forced_preempt_fault_kind_fires_on_commit_crossing():
    plan = FaultPlan.parse_net("preempt@5:2")
    set_net_plan(plan)
    try:
        sched = FleetScheduler(capacity=4, tick_s=0.01)
        job = sched.submit(FleetJob("j", "t", FakeRuntime(total=150),
                                    min_gang=2, max_workers=4))
        drive(sched, lambda: job.shrinks >= 2 or job.state == DONE)
        # The drill shrank 2 workers once progress crossed commit 5.
        assert job.shrinks == 2 and job.preemptions == 2
        assert job.state == RUNNING
        drive(sched, lambda: job.state == DONE)
        teardown(sched)
    finally:
        set_net_plan(None)


def test_forced_preempt_at_floor_drains_and_requeues():
    plan = FaultPlan.parse_net("preempt@5")
    set_net_plan(plan)
    try:
        sched = FleetScheduler(capacity=2, tick_s=0.01)
        job = sched.submit(FleetJob("j", "t", FakeRuntime(total=120),
                                    min_gang=2, max_workers=2))
        drive(sched, lambda: job.requeues >= 1 or job.state == DONE)
        assert job.requeues == 1, "at the floor, the drill must drain"
        drive(sched, lambda: job.state == DONE)
        teardown(sched)
    finally:
        set_net_plan(None)


# ---------------------------------------------------------------------------
# Crash restarts
# ---------------------------------------------------------------------------

def test_crashed_worker_is_restarted_within_budget():
    sched = FleetScheduler(capacity=2, tick_s=0.01, max_restarts=3)
    job = sched.submit(FleetJob("j", "t", FakeRuntime(total=30,
                                                      crash_first=2),
                                min_gang=1, max_workers=1))
    drive(sched, lambda: job.state == DONE)
    assert job.restarts == 2
    teardown(sched)


def test_restart_budget_exhaustion_fails_the_job():
    sched = FleetScheduler(capacity=2, tick_s=0.01, max_restarts=1)
    job = sched.submit(FleetJob("j", "t", FakeRuntime(total=30,
                                                      crash_first=10),
                                min_gang=1, max_workers=1))
    drive(sched, lambda: job.state == FAILED)
    assert job.restarts == 1
    assert isinstance(job.error, RuntimeError)
    assert job.runtime.closed
    teardown(sched)


# ---------------------------------------------------------------------------
# Telemetry attribution
# ---------------------------------------------------------------------------

def test_scoped_labels_qualify_names_and_events():
    assert telemetry.label_suffix() == ""
    with telemetry.scoped_labels(tenant="acme corp", job="j.0"):
        assert telemetry.label_suffix() == ".acme-corp.j-0"
        assert telemetry.current_labels() == {"tenant": "acme corp",
                                              "job": "j.0"}
        with telemetry.scoped_labels(job="inner"):
            assert telemetry.label_suffix() == ".acme-corp.inner"
        telemetry.event("labeled_probe", {"x": 1})
    assert telemetry.label_suffix() == ""
    ev = [e for e in telemetry.get().events() if e["kind"] == "labeled_probe"]
    assert ev and ev[-1]["tenant"] == "acme corp" and ev[-1]["job"] == "j.0"
    assert ev[-1]["x"] == 1


def test_report_fleet_attribution_groups_by_tenant_and_job(tmp_path):
    from distkeras_tpu.telemetry.report import build_report

    reg = telemetry.get()
    reg.counter("fleet.commits.tenA.job1").add(7)
    reg.counter("fleet.preemptions.tenA.job1").add(2)
    reg.counter("fleet.restarts.tenB.job2").add(1)
    reg.gauge("fleet.preempt_debt.tenA.job1").set(1.0)
    reg.gauge("fleet.staleness_mean.tenB.job2").set(0.5)
    with reg.span("fleet.round.tenA.job1"):
        time.sleep(0.001)
    path = tmp_path / "fleet.jsonl"
    telemetry.write_jsonl(reg, str(path))
    rows = build_report(str(path))["fleet"]
    by_key = {(r["tenant"], r["job"]): r for r in rows}
    a = by_key[("tenA", "job1")]
    assert a["commits"] == 7 and a["preemptions"] == 2
    assert a["preempt_debt"] == 1.0 and a["round_mean_s"] > 0
    # Throughput numerator is COMMITS, not span attempts (the one span
    # recorded here includes no commit, so c/s must still reflect 7).
    assert a["commits_per_sec"] == round(7 / a["round_total_s"], 3)
    b = by_key[("tenB", "job2")]
    assert b["restarts"] == 1 and b["staleness_mean"] == 0.5


def test_supervision_events_carry_job_and_tenant_labels():
    import subprocess
    import sys

    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="lbl", script="s.py", hosts=["localhost"],
                   tenant="acme")
    job = Job(pc)
    assert job._labels() == {"job": "lbl", "tenant": "acme"}
    # Drive supervise's restart branch directly: host 0 exits 1 once, the
    # restarted command exits 0 — the host_restart event must carry the
    # punchcard's job/tenant attribution.
    job._procs = [subprocess.Popen(
        [sys.executable, "-c", "import sys; sys.exit(1)"])]
    job._cmds = [f"{sys.executable} -c pass"]
    job.restarts = [0]
    rcs = job.supervise(timeout=15.0, grace=0.0, max_restarts=1,
                        restart_backoff=0.0)
    assert rcs == [0]
    ev = [e for e in telemetry.get().events() if e["kind"] == "host_restart"]
    assert ev and ev[-1]["job"] == "lbl" and ev[-1]["tenant"] == "acme"


# ---------------------------------------------------------------------------
# Port pool
# ---------------------------------------------------------------------------

def test_port_pool_reserves_distinct_probed_ports():
    pool = PortPool(lo=21000, hi=21100)
    ports = [pool.reserve() for _ in range(10)]
    assert len(set(ports)) == 10
    assert all(21000 <= p < 21100 for p in ports)
    # A port something else is squatting on is skipped by the bind probe.
    import socket

    squat = socket.socket()
    squat.bind(("127.0.0.1", 0))
    busy = squat.getsockname()[1]
    busy_pool = PortPool(lo=busy, hi=busy + 50)
    got = busy_pool.reserve()
    assert got != busy
    squat.close()
    # Released ports become reusable.
    pool.release(ports[0])
    assert ports[0] not in pool.reserved()


def test_punchcard_allocates_noncolliding_ports_and_threads_endpoints():
    from distkeras_tpu.job_deployment import Job, Punchcard

    a = Punchcard(job_name="a", script="t.py", hosts=["localhost"], ps={})
    b = Punchcard(job_name="b", script="t.py", hosts=["localhost"], ps={})
    ea, eb = a.ps_endpoint(), b.ps_endpoint()
    assert ea != eb, "two jobs on one host must get distinct PS ports"
    # Sticky: later calls and the launch command agree with the first.
    assert a.ps_endpoint() == ea
    assert f"--port {a.ps['port']}" in Job(a).render_ps_command()
    for cmd in Job(a).launch(dry_run=True):
        assert f"DKTPU_PS_ENDPOINT={ea}" in cmd
    # Coordinator ports are pool-allocated too (the fixed 8476 default
    # broke the second job on a host) — and distinct between jobs.
    ca, cb = a.resolved_coordinator_port(), b.resolved_coordinator_port()
    assert ca != cb
    assert a.resolved_coordinator_port() == ca
    assert f":{ca}" in Job(a).render_commands()[0]
    # Explicit ports are always honored untouched.
    pinned = Punchcard(job_name="p", script="t.py", hosts=["h"],
                       coordinator_port=8476, ps={"port": 7077})
    assert pinned.ps_endpoint() == "h:7077"
    assert pinned.resolved_coordinator_port() == 8476
    # Standby ports come from the pool as well (not primary + 1).
    sb = Punchcard(job_name="s", script="t.py", hosts=["h"],
                   ps={"standby_host": "h2"})
    ep = sb.ps_endpoint()
    assert "," in ep and str(sb.ps["standby_port"]) in ep.split(",")[1]


def test_job_teardown_releases_pool_allocated_ports():
    from distkeras_tpu.fleet import ports as port_mod
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="rel", script="t.py", hosts=["localhost"],
                   ps={})
    ep_port = int(pc.ps_endpoint().rsplit(":", 1)[1])
    coord = pc.resolved_coordinator_port()
    assert {ep_port, coord} <= port_mod._POOL.reserved()
    Job(pc).kill()  # no procs launched: teardown is just the release
    assert not ({ep_port, coord} & port_mod._POOL.reserved()), (
        "teardown must return pool-allocated ports")
    pc.release_ports()  # idempotent
    # Explicit ports are never touched by release.
    pinned = Punchcard(job_name="pin", script="t.py", hosts=["h"],
                       ps={"port": 7077})
    pinned.ps_endpoint()
    pinned.release_ports()
    assert pinned.ps["port"] == 7077


def test_max_workers_beyond_runtime_slots_rejected_at_submit():
    class SlottedRuntime(FakeRuntime):
        worker_slots = 4

    sched = FleetScheduler(capacity=8, tick_s=0.01)
    with pytest.raises(ValueError, match="worker_slots"):
        sched.submit(FleetJob("x", "t", SlottedRuntime(), min_gang=2,
                              max_workers=8))
    # At or below the layout is fine (FakeRuntime without the attribute
    # is exercised by every other test).
    sched.submit(FleetJob("ok", "t", SlottedRuntime(total=5), min_gang=1,
                          max_workers=4))
    drive(sched, lambda: sched.all_terminal())
    teardown(sched)


def test_parse_quotas():
    assert parse_quotas("") == {}
    assert parse_quotas("a=2; b=3") == {"a": 2, "b": 3}
    with pytest.raises(ValueError, match="tenant=N"):
        parse_quotas("bogus")


def test_reserve_port_is_process_unique_even_for_remote_hosts():
    p1 = reserve_port("remote-host-a")
    p2 = reserve_port("remote-host-a")
    assert p1 != p2


# ---------------------------------------------------------------------------
# Elastic training integration (real netps PS under the scheduler)
# ---------------------------------------------------------------------------

def test_elastic_training_survives_shrink_expand_and_converges():
    import jax.numpy as jnp

    from distkeras_tpu import DataFrame
    from distkeras_tpu.data.batching import make_batches
    from distkeras_tpu.fleet import ElasticTraining
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.ops.optimizers import get_optimizer

    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4.0, size=(3, 4))
    y = rng.integers(0, 3, size=512)
    x = (centers[y] + rng.normal(scale=0.5, size=(512, 4))).astype(
        np.float32)
    df = DataFrame({"features": x, "label": y.astype(np.int32)})
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32), seed=0)
    plan = make_batches(df, "features", "label", batch_size=16,
                        num_workers=4, window=4, num_epoch=4, shuffle=True,
                        seed=0)
    rt = ElasticTraining(model=model, tx=get_optimizer("sgd", 0.1),
                         loss_fn=get_loss("sparse_categorical_crossentropy"),
                         plan=plan, discipline="adag", seed=0, lease_s=5.0,
                         timeout=2.0, retries=5, backoff=0.02)
    sched = FleetScheduler(capacity=4, tick_s=0.01)
    # Mid-run squeeze via the chaos drill: once the fleet's commit count
    # crosses 2, forcibly preempt 2 workers — the job shrinks to its
    # floor and must re-expand afterwards.
    set_net_plan(FaultPlan.parse_net("preempt@2:2"))
    try:
        job = sched.submit(FleetJob("train", "acme", rt, min_gang=2,
                                    max_workers=4))
        stats = sched.run(timeout=240)["acme/train"]
    finally:
        set_net_plan(None)
    sched.close()
    assert job.state == DONE
    assert stats["preemptions"] >= 2 and job.shrinks >= 2
    assert job.expands >= 2, "the squeezed job must re-expand"
    assert sched.floor_violations == 0
    # Exactly-once on the per-job PS, across revocation + rejoin churn.
    seen = set()
    for wid, seq, _st in rt.server.commit_log:
        assert (wid, seq) not in seen
        seen.add((wid, seq))
    # Every planned (round, slice) work item committed exactly once.
    assert rt.done()
    assert rt.progress() == plan.num_rounds * plan.num_workers
    assert not np.isnan(rt.losses).any(), "a planned slice never trained"
    trained = rt.result()
    acc = float((np.asarray(trained.predict(jnp.asarray(x))).argmax(-1)
                 == y).mean())
    assert acc > 0.9, f"elastic run failed to converge: {acc}"
