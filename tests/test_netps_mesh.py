"""The mesh transport dialect: a same-runtime client folds its deltas
straight into a device-resident center — zero wire bytes, the Pallas
compressed-domain fold running inside a ``shard_map`` collective — while
every PR 7/8 guarantee (dedup, epoch fencing, durable journal, bounded
staleness) rides through the host-side journal tail unchanged.

The contract pinned here:

* **Negotiation is live, not static** — the ``mesh`` caps bit is only
  honoured when the server's advertised ``proc`` matches this process's
  ``local_mesh_id()``; a TCP client against the same server never sees
  the dialect, and a mesh client negotiates the shm ring TOO (it is the
  demotion target).
* **Bit-identical parity** — on CPU the exact two-program fold makes a
  mesh server's center equal a plain server's byte for byte, for every
  codec (none/bf16/int8): the device-resident center is an optimisation,
  never a numerics fork.
* **Demotion is one strike and exactly-once** — an injected
  ``mesh_down`` mid-run sweeps the dialect, the SAME seq retransmits on
  the negotiated shm/TCP path, and the run's final center still matches
  the no-fault reference bit for bit with ``commits_total == n``.
* **One plan, two fabrics** — ``PartitionPlan.to_partition_specs``
  translates the wire-shard plan into mesh ``PartitionSpec`` rules, so
  the rows a shard server owns are the rows a device owns.
"""

import warnings

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.netps import PSClient, PSServer, wire
from distkeras_tpu.netps import mesh as _mesh
from distkeras_tpu.netps.client import (
    _BAD_KNOB_COMBOS_WARNED,
    _validate_knob_combo,
)
from distkeras_tpu.netps.shards.plan import SPLIT, PartitionPlan
from distkeras_tpu.resilience import faults

FAST = dict(timeout=1.0, retries=3, backoff=0.01)


def leaves():
    rng = np.random.default_rng(7)
    return [rng.normal(size=(4, 3)).astype(np.float32),
            rng.normal(size=(8,)).astype(np.float32)]


def drive_commits(endpoint, n, *, compress="none", worker_id=0, **kw):
    """Join + fold ``n`` deterministic commits; returns the final client
    (still open — callers close it) and its view of (center, updates)."""
    rng = np.random.default_rng(worker_id + 1)
    c = PSClient(endpoint, worker_id=worker_id, compress=compress,
                 **dict(FAST, **kw))
    center, upd = c.join(init=leaves())
    for _ in range(n):
        delta = [rng.normal(scale=0.1, size=a.shape).astype(np.float32)
                 for a in center]
        c.commit(delta, upd)
        center, upd = c.pull()
    return c, center, upd


# ---------------------------------------------------------------------------
# Negotiation + observability
# ---------------------------------------------------------------------------

def test_mesh_negotiation_upgrades_and_stats_expose_backend():
    """A same-process mesh client upgrades (and negotiates shm as its
    demotion target); the server's stats answer names the resolved fold
    backend ``mesh``. A plain TCP client against the SAME server never
    sees the dialect — old peers are unaffected by construction."""
    telemetry.reset()
    srv = PSServer(discipline="adag", transport="mesh").start()
    try:
        c, center, _ = drive_commits(srv.endpoint, 3, transport="mesh")
        try:
            assert c.active_transport == "mesh"
            assert c.mesh_info is not None
            assert c.mesh_info["proc"] == _mesh.local_mesh_id()
            assert c.shm_info is not None, \
                "a mesh client must negotiate its shm demotion target"
            assert c.stats()["fold_backend"] == "mesh"
        finally:
            c.close()
        # The device-resident center and the client's pulled view agree.
        for a, b in zip(srv.center(), center):
            assert a.tobytes() == b.tobytes()
        reg = telemetry.get()
        assert reg.counter("netps.mesh.upgrades").value == 1
        assert reg.counter("netps.mesh.folds").value >= 3
        # TCP client: no mesh advert honoured, plain dialect, still folds.
        t = PSClient(srv.endpoint, worker_id=1, transport="tcp", **FAST)
        try:
            _, upd = t.join()
            assert t.active_transport == "tcp"
            assert t.mesh_info is None
            res = t.commit([np.ones_like(a) for a in srv.center()], upd)
            assert res.applied
        finally:
            t.close()
    finally:
        srv.close()


def test_mesh_advert_refused_across_process_boundary(monkeypatch):
    """A forged/stale mesh advert whose ``proc`` is not THIS runtime is
    ignored: the client stays on its negotiated socket dialect rather
    than dispatching into a mesh that does not exist here."""
    import types

    from distkeras_tpu.netps import client as client_mod
    srv = PSServer(discipline="adag", transport="mesh").start()
    try:
        # Patch only the CLIENT's view of the runtime identity — the
        # server (same process here) keeps advertising its real one, so
        # the advert now looks like it came from another process.
        monkeypatch.setattr(
            client_mod, "_mesh",
            types.SimpleNamespace(local_mesh_id=lambda: "other:0",
                                  dispatch=_mesh.dispatch))
        c = PSClient(srv.endpoint, worker_id=0, transport="mesh", **FAST)
        try:
            c.join(init=leaves())
            assert c.mesh_info is None
            assert c.active_transport in ("shm", "tcp")
        finally:
            c.close()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Parity: the device-resident center is not a numerics fork
# ---------------------------------------------------------------------------

@pytest.mark.filterwarnings("ignore:measured-bad knob combination")
@pytest.mark.parametrize("compress", ["none", "bf16", "int8"])
def test_mesh_parity_bit_identical_across_codecs(compress):
    """THE parity pin: the same deterministic commit sequence against a
    mesh server and a plain server ends in byte-for-byte equal centers —
    for f32 and both compressed-domain codecs. On CPU the folder's exact
    two-program formulation rounds between multiply and add exactly as
    numpy does."""
    assert compress in wire.CODECS
    ref_srv = PSServer(discipline="adag", transport="tcp").start()
    mesh_srv = PSServer(discipline="adag", transport="mesh").start()
    try:
        rc, ref_center, _ = drive_commits(
            ref_srv.endpoint, 8, compress=compress, transport="tcp")
        rc.close()
        mc, mesh_center, _ = drive_commits(
            mesh_srv.endpoint, 8, compress=compress, transport="mesh")
        try:
            assert mc.active_transport == "mesh"
        finally:
            mc.close()
        assert mesh_srv.commits_total == ref_srv.commits_total == 8
        for i, (a, b) in enumerate(zip(ref_srv.center(),
                                       mesh_srv.center())):
            assert a.tobytes() == b.tobytes(), \
                f"tensor {i} diverged under codec {compress!r}"
        for a, b in zip(ref_center, mesh_center):
            assert a.tobytes() == b.tobytes()
    finally:
        mesh_srv.close()
        ref_srv.close()


# ---------------------------------------------------------------------------
# Demotion: one strike, exactly-once, no numerics fork
# ---------------------------------------------------------------------------

def test_mesh_down_demotes_midrun_exactly_once():
    """The device-loss drill: ``mesh_down`` fires mid-run, the dispatch
    raises as a lost device mesh would, the client sweeps the dialect
    (ONE strike) and retransmits the SAME seq on its negotiated shm ring.
    Exactly-once: every commit folds once, and the final center matches
    the no-fault reference bit for bit."""
    n = 8
    ref_srv = PSServer(discipline="adag", transport="tcp").start()
    try:
        rc, _, _ = drive_commits(ref_srv.endpoint, n, transport="tcp")
        rc.close()
        ref = ref_srv.center()
        ref_total = ref_srv.commits_total
    finally:
        ref_srv.close()

    telemetry.reset()
    # Client _seq starts at -1: the 5th commit carries seq 4.
    faults.set_net_plan(faults.FaultPlan.parse_net("mesh_down@4"))
    srv = PSServer(discipline="adag", transport="mesh").start()
    try:
        c, center, _ = drive_commits(srv.endpoint, n, transport="mesh")
        try:
            assert c.mesh_info is None, "mesh_down must sweep the dialect"
            assert c.active_transport == "shm", \
                "demotion lands on the negotiated shm ring, not a rejoin"
        finally:
            c.close()
        assert srv.commits_total == ref_total == n, \
            "the retransmitted seq must fold exactly once"
        for i, (a, b) in enumerate(zip(ref, srv.center())):
            assert a.tobytes() == b.tobytes(), \
                f"tensor {i} diverged across the demotion"
        for a, b in zip(ref, center):
            assert a.tobytes() == b.tobytes()
        reg = telemetry.get()
        assert reg.counter("netps.mesh.demotions").value == 1
        whys = [e["why"] for e in reg.events()
                if e["kind"] == "netps_mesh_demotion"]
        assert len(whys) == 1 and "ConnectionError" in whys[0]
    finally:
        srv.close()
        faults.set_net_plan(None)


# ---------------------------------------------------------------------------
# One plan, two fabrics
# ---------------------------------------------------------------------------

def test_to_partition_specs_mirrors_row_splits():
    """Row-split tensors shard axis 0 over the mesh axis; pinned and
    balanced tensors replicate. The rule patterns are exact-match
    anchored, so ``param_1`` never swallows ``param_10``."""
    from jax.sharding import PartitionSpec as P
    plan = PartitionPlan.build(
        ["emb", "bias"], [(16, 4), (8,)], 2, rules=[("^emb$", SPLIT)])
    specs = dict(plan.to_partition_specs("fold"))
    assert specs["^emb$"] == P("fold")
    assert specs["^bias$"] == P()
    # Default axis name matches the mesh dialect's.
    assert dict(plan.to_partition_specs())["^emb$"] == P(_mesh.MESH_AXIS)


def test_mesh_folder_honours_plan_specs():
    """A MeshFolder built with a plan shards exactly the tensors the plan
    row-splits — the wire plan IS the mesh plan — and still folds
    bit-identically to numpy in exact mode."""
    import jax
    rng = np.random.default_rng(3)
    rows = max(2 * len(jax.devices()), 8)
    center = [rng.normal(size=(rows, 3)).astype(np.float32),
              rng.normal(size=(5,)).astype(np.float32)]
    plan = PartitionPlan.build(
        ["big", "small"], [(rows, 3), (5,)], 2, rules=[("^big$", SPLIT)])
    folder = _mesh.MeshFolder([a.copy() for a in center], plan=plan)
    try:
        delta = [rng.normal(scale=0.1, size=a.shape).astype(np.float32)
                 for a in center]
        folder.fold(delta, 0.5)
        want = [c + np.float32(0.5) * d for c, d in zip(center, delta)]
        for a, b in zip(folder.center_host(), want):
            assert a.tobytes() == b.tobytes()
    finally:
        folder.close()


# ---------------------------------------------------------------------------
# Fold-parity gate: the fused collective vs the numpy oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", ["none", "bf16", "int8"])
def test_mesh_fused_interpret_fold_matches_numpy_oracle(codec):
    """The fold-parity job's mesh arm: the FUSED formulation — the Pallas
    dequant-fused kernel inside the shard_map collective body, interpret
    mode on this CPU (compiled on TPU) — against the pure-numpy
    reference, at the kernel parity suite's own allclose bar."""
    from distkeras_tpu.netps.fold import fold_compressed_numpy

    rng = np.random.default_rng(11)
    center = [rng.normal(size=(16, 4)).astype(np.float32),
              rng.normal(size=(8,)).astype(np.float32)]
    folder = _mesh.MeshFolder([a.copy() for a in center], interpret=True)
    try:
        ref = [a.copy() for a in center]
        scale = 0.25
        for _ in range(3):
            raw = [rng.normal(scale=0.2, size=a.shape).astype(np.float32)
                   for a in center]
            entries = []
            for a, r in zip(raw, ref):
                if codec == "none":
                    entries.append(a)
                    r += np.float32(scale) * a
                else:
                    q, spec = wire.codec_encode(a, codec)
                    entries.append((q, spec))
                    fold_compressed_numpy(r, q, spec, scale)
            folder.fold(entries, scale)
        for i, (got, want) in enumerate(zip(folder.center_host(), ref)):
            np.testing.assert_allclose(
                got, want, rtol=1e-6, atol=1e-7,
                err_msg=f"tensor {i} diverged under codec {codec!r}")
    finally:
        folder.close()


# ---------------------------------------------------------------------------
# Satellite: knob-combo validation covers the mesh dialect
# ---------------------------------------------------------------------------

def test_mesh_knob_combos_warn_once_per_process():
    _BAD_KNOB_COMBOS_WARNED.clear()
    telemetry.reset()
    with pytest.warns(RuntimeWarning, match="int8\\+mesh"):
        _validate_knob_combo("int8", "mesh", 1)
    with pytest.warns(RuntimeWarning, match="shards>1\\+mesh"):
        _validate_knob_combo("none", "mesh", 2)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _validate_knob_combo("int8", "mesh", 4)  # dedup: silent
        _validate_knob_combo("none", "mesh", 1)  # good pairing: silent
        _validate_knob_combo("bf16", "mesh", 1)  # bf16+mesh is measured-OK
    reg = telemetry.get()
    assert reg.counter("tuner.knob_warnings").value == 2
    combos = [e["combo"] for e in reg.events()
              if e["kind"] == "netps_knob_warning"]
    assert combos == ["int8+mesh", "shards>1+mesh"]
    _BAD_KNOB_COMBOS_WARNED.clear()
