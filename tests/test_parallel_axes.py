"""Sequence-parallel + tensor-parallel tests: ring attention, gather-SP, TP rules.

Each parallel attention implementation is checked for numerical equivalence against
the dense single-device computation on the same inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.models import Model, small_transformer_lm
from distkeras_tpu.models.transformer import TransformerLM
from distkeras_tpu.ops.collectives import shard_map
from distkeras_tpu.ops.ring_attention import ring_attention
from distkeras_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
    param_path_specs,
    param_shardings,
)
from distkeras_tpu.runtime.mesh import hybrid_mesh

import envcaps

B, L, H, D = 2, 32, 2, 8  # global seq L sharded over 4 chips -> 8 per chip


def dense_causal(q, k, v):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    L_ = q.shape[1]
    mask = jnp.tril(jnp.ones((L_, L_), bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def test_ring_attention_matches_dense():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
               for _ in range(3))
    mesh = hybrid_mesh({"seq": 4})

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    out = ring(q, k, v)
    expect = dense_causal(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


def test_transformer_seq_parallel_matches_dense():
    """Full TransformerLM forward, sequence-sharded (gather + ring) == dense."""
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 32)), jnp.int32)

    dense_model = small_transformer_lm(vocab_size=64, num_layers=1, d_model=16,
                                       num_heads=2, d_ff=32, max_seq_len=32, seq_len=32)
    expect = dense_model.predict(tokens)

    mesh = hybrid_mesh({"seq": 4})
    for impl in ("gather", "ring"):
        sp_module = TransformerLM(
            vocab_size=64, num_layers=1, d_model=16, num_heads=2, d_ff=32,
            max_seq_len=32, seq_axis="seq", attn_impl=impl,
        )
        fwd = shard_map(
            lambda p, t: sp_module.apply({"params": p}, t, train=False),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
        out = fwd(dense_model.params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=3e-4,
                                   err_msg=f"attn_impl={impl}")


def test_tp_rules_cover_transformer_params():
    model = small_transformer_lm(vocab_size=64, num_layers=2, d_model=16,
                                 num_heads=2, d_ff=32, max_seq_len=32)
    specs = param_path_specs(model.params, TRANSFORMER_TP_RULES)
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    by_name = {"/".join(str(getattr(p, "key", p)) for p in path): spec
               for path, spec in flat}
    assert by_name["block_0/attn/query/kernel"] == P(None, "model", None)
    assert by_name["block_0/mlp_up/kernel"] == P(None, "model")
    assert by_name["block_0/mlp_down/kernel"] == P("model", None)
    assert by_name["tok_embed/embedding"] == P(None, "model")
    # norms replicated
    assert by_name["block_0/ln_attn/scale"] == P()


def test_tp_sharded_forward_matches_dense():
    """pjit with TP shardings == unsharded forward (GSPMD inserts collectives)."""
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 16)), jnp.int32)
    model = small_transformer_lm(vocab_size=64, num_layers=1, d_model=16,
                                 num_heads=2, d_ff=32, max_seq_len=32, seq_len=16)
    expect = model.predict(tokens)

    mesh = hybrid_mesh({"data": 4, "model": 2})
    shardings = param_shardings(model.params, mesh, TRANSFORMER_TP_RULES)
    sharded_params = jax.device_put(model.params, shardings)
    tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P("data")))

    fwd = jax.jit(lambda p, t: model.module.apply({"params": p}, t, train=False))
    out = fwd(sharded_params, tok_sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=3e-4)


@envcaps.skip_unless_key_sharding()
def test_flash_attention_under_tensor_parallelism():
    """attn_impl='flash' on a dp x tp mesh: the Mosaic kernel is manualized
    over the model axis by a nested shard_map (heads are independent), so
    flash + TP compose. Must match the dense twin."""
    from distkeras_tpu.models.transformer import TransformerLM
    from distkeras_tpu.parallel.spmd import SPMDEngine
    from distkeras_tpu.runtime.mesh import hybrid_mesh

    arch = dict(vocab_size=128, num_layers=2, d_model=64, num_heads=4,
                d_ff=128, max_seq_len=32)
    model = Model.build(TransformerLM(**arch), jnp.zeros((1, 32), jnp.int32))
    mesh = hybrid_mesh({"data": 2, "seq": 1, "model": 4})
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 128, size=(4, 32)), jnp.int32)
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1), jnp.int32)

    losses = {}
    for impl in ("dense", "flash"):
        m = Model(module=TransformerLM(**arch, attn_impl=impl),
                  params=model.params)
        eng = SPMDEngine(m, "sgd", "sparse_categorical_crossentropy", mesh,
                         TRANSFORMER_TP_RULES, learning_rate=0.1)
        state = eng.init_state()
        x = jax.device_put(tokens, eng.batch_sharding())
        y = jax.device_put(targets, eng.batch_sharding())
        state, l0 = eng.step(state, x, y)
        state, l1 = eng.step(state, x, y)
        losses[impl] = (float(l0), float(l1))
    np.testing.assert_allclose(losses["flash"], losses["dense"], rtol=2e-3)
    assert losses["flash"][1] < losses["flash"][0]


def test_gspmd_engine_rejects_flash_and_seq_axis_at_init():
    """Unsupported combos must fail at construction with a pointer to
    SPMDEngine, not as an opaque TPU trace-time mesh failure (the CPU
    interpret mode would even mask it entirely)."""
    import pytest

    from distkeras_tpu.models.transformer import TransformerLM
    from distkeras_tpu.parallel.gspmd import GSPMDEngine
    from distkeras_tpu.runtime.mesh import hybrid_mesh

    arch = dict(vocab_size=128, num_layers=1, d_model=32, num_heads=2,
                d_ff=64, max_seq_len=16)
    mesh = hybrid_mesh({"data": 4, "model": 2})
    flash = Model.build(TransformerLM(**arch, attn_impl="flash"),
                        jnp.zeros((1, 1), jnp.int32))
    with pytest.raises(ValueError, match="SPMDEngine"):
        GSPMDEngine(flash, "sgd", "sparse_categorical_crossentropy", mesh,
                    TRANSFORMER_TP_RULES)
    ringy = Model.build(TransformerLM(**arch), jnp.zeros((1, 1), jnp.int32))
    ringy = Model(module=TransformerLM(**arch, seq_axis="seq",
                                       attn_impl="ring"),
                  params=ringy.params)
    with pytest.raises(ValueError, match="SPMDEngine"):
        GSPMDEngine(ringy, "sgd", "sparse_categorical_crossentropy", mesh,
                    TRANSFORMER_TP_RULES)
