"""CI serving-chaos smoke (not a pytest module — run directly).

Offered load against a 2-replica serving set while chaos happens to it:

* ``serve_slow@F:S`` holds one reply mid-stream (tail-latency injection);
* ``serve_drop@F`` kills one request's connection pre-admission (the
  client walks to the surviving endpoint and retries);
* a replica is **killed** mid-load (no drain, no typed replies) — HA is
  the client's endpoint walk, nothing else;
* a new checkpoint step lands mid-load and every live replica's registry
  hot-swaps to it between batches (sha256-verified restore + warmup
  probe), after which replies must carry the new version AND the new
  weights' outputs.

Asserted invariants, in the order the ISSUE states them:

* **p99 bound holds** — client-observed p99 stays under the bound even
  with the slow-hold and the replica kill in the window;
* **zero dropped accepted requests** — every request sent is answered
  with a result or a *typed* shed/deadline error: no silent losses, no
  untyped failures;
* **the swapped model actually answers** — post-swap replies carry the
  new step as their version and the constant-parameter outputs prove the
  weights changed;
* **no retrace after warmup** — the jit compile count per replica equals
  its warmed bucket programs; ragged live traffic must never add one.

    DKTPU_NET_FAULTS="serve_slow@20:0.3;serve_drop@35;seed=3" \\
        python tests/smoke_serving_chaos.py

All seeds are pinned (request rng, fault plan), so reruns schedule the
same chaos.
"""

import os
import sys

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# Tight-but-survivable budgets: a killed replica must cost a walk, not the
# production 30 s deadline.
os.environ.setdefault("DKTPU_NET_TIMEOUT", "2.0")
os.environ.setdefault("DKTPU_NET_RETRIES", "8")
os.environ.setdefault("DKTPU_NET_BACKOFF", "0.02")
os.environ.setdefault(
    "DKTPU_NET_FAULTS", "serve_slow@20:0.3;serve_drop@35;seed=3")

import tempfile  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402

#: client-observed p99 latency bound (seconds). Generous against CI boxes
#: but tight against real pathologies: the serve_slow hold is 0.3 s and a
#: replica-kill failover costs one walk + backoff — a queue meltdown or a
#: mid-load retrace would blow straight through it.
P99_BOUND_S = 1.5

LOAD_THREADS = 4
REQUESTS_PER_THREAD = 60
KILL_AFTER = 40          # total oks before the replica kill
SWAP_AFTER = 80          # total oks before the new checkpoint lands
SWAP_STEP = 5
SWAP_SCALE = 0.25


def main() -> int:
    import jax
    from flax import linen as nn

    from distkeras_tpu import telemetry
    from distkeras_tpu.checkpoint import Checkpointer
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.serving import ServeClient, ServingReplicaSet
    from distkeras_tpu.serving.errors import (
        DeadlineExceededError,
        OverloadedError,
    )
    from distkeras_tpu.serving.frontend import reset_request_index

    telemetry.reset()
    reset_request_index()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

    model = Model.build(MLP(), np.zeros((2, 4), np.float32), seed=0)
    ckpt_dir = tempfile.mkdtemp(prefix="dktpu-serve-smoke-")
    rs = ServingReplicaSet(model, n=2, buckets=(1, 4, 16),
                           directory=ckpt_dir, poll_s=0.1,
                           max_wait_s=0.003, watch=True).start()
    endpoints = rs.endpoints()
    print(f"[smoke] 2 replicas up: {endpoints}; faults="
          f"{os.environ['DKTPU_NET_FAULTS']}")

    lock = threading.Lock()
    lat: list[float] = []
    versions: list[int] = []
    ok = [0]
    shed = [0]
    hard = []         # untyped failures — must stay empty
    killed = [False]
    swapped = [False]

    def chaos_driver():
        """Kill replica 0 and land the hot-swap checkpoint at pinned
        points in the accepted-request stream."""
        while not killed[0] or not swapped[0]:
            with lock:
                n = ok[0]
            if not killed[0] and n >= KILL_AFTER:
                rs.kill(0)
                killed[0] = True
                print(f"[smoke] replica 0 KILLED at ok={n}")
            if not swapped[0] and n >= SWAP_AFTER:
                params = jax.tree.map(
                    lambda a: np.zeros_like(np.asarray(a)) + SWAP_SCALE,
                    model.params)
                ckpt = Checkpointer(ckpt_dir)
                assert ckpt.save(SWAP_STEP, params, wait=True,
                                 meta={"step": SWAP_STEP})
                ckpt.close()
                swapped[0] = True
                print(f"[smoke] checkpoint step {SWAP_STEP} saved at ok={n}")
            time.sleep(0.01)

    def load(k: int):
        client = ServeClient(endpoints)
        rng = np.random.default_rng(100 + k)
        for _ in range(REQUESTS_PER_THREAD):
            rows = int(rng.integers(1, 5))
            x = rng.standard_normal((rows, 4)).astype(np.float32)
            t0 = time.monotonic()
            try:
                out, version = client.infer(x)
                dt = time.monotonic() - t0
                assert out.shape == (rows, 3), out.shape
                with lock:
                    ok[0] += 1
                    lat.append(dt)
                    versions.append(version)
            except (OverloadedError, DeadlineExceededError):
                with lock:
                    shed[0] += 1  # typed shed: the contract's escape hatch
            except Exception as e:  # noqa: BLE001 - any other loss is a FAIL
                with lock:
                    hard.append(repr(e))
        client.close()

    driver = threading.Thread(target=chaos_driver, daemon=True)
    driver.start()
    threads = [threading.Thread(target=load, args=(k,))
               for k in range(LOAD_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    driver.join(timeout=10.0)

    # The swap landed mid-load; make sure we observe the new version even
    # if every in-window request raced ahead of the pollers.
    client = ServeClient(rs.endpoints())
    deadline = time.monotonic() + 15.0
    version = -1
    while version != SWAP_STEP:
        assert time.monotonic() < deadline, \
            f"hot-swap to step {SWAP_STEP} never observed (at {version})"
        out, version = client.infer(np.ones((2, 4), np.float32))
        time.sleep(0.05)
    # Constant parameters => every logit identical: the swapped model
    # really is the one answering, not just a bumped version label.
    assert np.allclose(out, out.reshape(-1)[0]), out
    client.close()

    sent = LOAD_THREADS * REQUESTS_PER_THREAD
    assert not hard, f"untyped request losses: {hard[:5]}"
    assert ok[0] + shed[0] == sent, (ok[0], shed[0], sent)
    assert ok[0] > 0.9 * sent, \
        f"shed {shed[0]}/{sent}: load level should mostly be admitted"

    lat.sort()
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    assert p99 <= P99_BOUND_S, \
        f"p99 {p99 * 1e3:.1f}ms blew the {P99_BOUND_S * 1e3:.0f}ms bound"

    snap = telemetry.get().snapshot()
    counters = snap["counters"]
    events = telemetry.get().events()
    fired = {e.get("fault") for e in events if e.get("kind") ==
             "fault_injected"}
    assert "serve_slow" in fired and "serve_drop" in fired, fired
    assert counters.get("serving.client_failovers", 0) >= 1, \
        "the replica kill (and serve_drop) must have forced a walk"
    assert counters.get("serving.retrace_after_warmup", 0) == 0, \
        "ragged live traffic retraced a warmed replica"
    assert counters.get("serving.swaps", 0) >= 1

    rs.close()
    print(f"[smoke] OK: sent={sent} ok={ok[0]} shed={shed[0]} "
          f"p50={p50 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms "
          f"swaps={counters.get('serving.swaps', 0):.0f} "
          f"failovers={counters.get('serving.client_failovers', 0):.0f} "
          f"retraces=0 versions_seen={sorted(set(versions))}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
