"""Data-plane tests: DataFrame, transformers, batch planning."""

import numpy as np
import pytest

from distkeras_tpu.data import (
    DataFrame,
    DenseTransformer,
    LabelIndexTransformer,
    MinMaxTransformer,
    OneHotTransformer,
    ReshapeTransformer,
    make_batches,
)


def _df(n=100, d=4):
    rng = np.random.default_rng(0)
    return DataFrame(
        {"features": rng.normal(size=(n, d)).astype(np.float32),
         "label": rng.integers(0, 3, size=n)}
    )


def test_dataframe_basics():
    df = _df(10)
    assert df.count() == 10
    assert set(df.columns) == {"features", "label"}
    df2 = df.with_column("x2", df["features"] * 2)
    assert "x2" in df2 and "x2" not in df
    assert df2.select("x2").columns == ["x2"]
    a, b = df.split(0.7, seed=1)
    assert a.count() == 7 and b.count() == 3


def test_dataframe_column_mismatch():
    with pytest.raises(ValueError):
        DataFrame({"a": np.zeros(3), "b": np.zeros(4)})


def test_shuffle_is_permutation():
    df = _df(50)
    sh = df.shuffle(seed=3)
    assert sorted(sh["label"].tolist()) == sorted(df["label"].tolist())
    assert not np.array_equal(sh["features"], df["features"])


def test_label_index_transformer():
    df = DataFrame({"label": np.array(["cat", "dog", "cat", "bird"])})
    t = LabelIndexTransformer(input_col="label", output_col="idx")
    out = t.transform(df)
    assert out["idx"].dtype == np.int32
    assert out["idx"][0] == out["idx"][2]
    assert len(set(out["idx"].tolist())) == 3


def test_one_hot_transformer():
    df = DataFrame({"label": np.array([0, 2, 1])})
    out = OneHotTransformer(3, input_col="label", output_col="oh").transform(df)
    np.testing.assert_array_equal(
        out["oh"], [[1, 0, 0], [0, 0, 1], [0, 1, 0]]
    )
    with pytest.raises(ValueError):
        OneHotTransformer(2, input_col="label").transform(df)


def test_min_max_transformer():
    df = DataFrame({"features": np.array([[0.0], [255.0]], np.float32)})
    out = MinMaxTransformer(0.0, 1.0, input_col="features", output_col="n").transform(df)
    np.testing.assert_allclose(out["n"], [[0.0], [1.0]])


def test_reshape_transformer():
    df = DataFrame({"features": np.zeros((5, 784), np.float32)})
    out = ReshapeTransformer("features", "img", (28, 28, 1)).transform(df)
    assert out["img"].shape == (5, 28, 28, 1)


def test_dense_transformer_object_column():
    rows = np.empty(3, object)
    for i in range(3):
        rows[i] = [float(i), float(i + 1)]
    df = DataFrame({"features": rows})
    out = DenseTransformer(input_col="features", output_col="d").transform(df)
    assert out["d"].shape == (3, 2) and out["d"].dtype == np.float32


def test_make_batches_layout():
    df = _df(100, d=4)
    plan = make_batches(df, "features", "label", batch_size=3, num_workers=4,
                        window=2, num_epoch=2)
    # per round = 4*2*3 = 24 rows; 100//24 = 4 rounds/epoch, 2 epochs
    assert plan.index.shape == (8, 4, 2, 3)
    fx, fy = plan.round(0)
    assert fx.shape == (4, 2, 3, 4) and fy.shape == (4, 2, 3)
    assert plan.num_rounds == 8
    assert plan.rows_used == 2 * 96
    # worker-major: round 0, worker 1's first row is global row 6 (no shuffle)
    np.testing.assert_array_equal(fx[1, 0, 0], df["features"][6])


def test_make_batches_too_small():
    with pytest.raises(ValueError):
        make_batches(_df(10), "features", "label", batch_size=8, num_workers=4, window=2)


def test_make_batches_shuffle_differs_by_epoch():
    df = _df(48, d=2)
    plan = make_batches(df, "features", "label", batch_size=2, num_workers=2,
                        window=2, num_epoch=2, shuffle=True, seed=0)
    half = plan.num_rounds // 2
    assert not np.array_equal(plan.index[:half], plan.index[half:])


def test_make_batches_stores_one_copy():
    df = _df(96, d=4)
    plan = make_batches(df, "features", "label", batch_size=4, num_workers=4,
                        window=2, num_epoch=50)
    # 50 epochs must not copy the dataset 50x: only indices scale with epochs
    assert plan.x.shape == (96, 4)
    assert plan.index.shape[0] == 3 * 50


def test_random_split_spark_parity():
    import numpy as np
    from distkeras_tpu.data import DataFrame

    df = DataFrame({"x": np.arange(100, dtype=np.float32)})
    parts = df.random_split([0.6, 0.2, 0.2], seed=3)
    assert [len(p) for p in parts] == [60, 20, 20]
    merged = np.sort(np.concatenate([p["x"] for p in parts]))
    np.testing.assert_array_equal(merged, np.arange(100))
    # Spark-spelled alias used by the reference notebooks
    a, b = df.randomSplit([0.8, 0.2], seed=0)
    assert len(a) == 80 and len(b) == 20


def test_top_level_parity_exports():
    import distkeras_tpu as dk

    for name in ("MinMaxTransformer", "OneHotTransformer", "ReshapeTransformer",
                 "LabelIndexTransformer", "DenseTransformer", "ModelPredictor",
                 "ClassPredictor", "AccuracyEvaluator"):
        assert hasattr(dk, name), name
