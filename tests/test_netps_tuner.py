"""The self-tuning data plane: join-time probes, guardrailed retunes,
mid-run renegotiation, and the fleet's marginal-throughput expansion gate.

The contract under test, in the module docstring of
``netps/tuner/controller.py``: floors are never violated, the retune rate
is bounded (interval/cooldown/budget), oscillation falls back to the
static knobs, failover defers adoption rather than losing it — and a
retune with commits in flight changes NOTHING about exactly-once (a
retransmit keeps its seq and is answered by the dedup table either way).
"""

import warnings

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.netps import PSClient, PSServer, wire
from distkeras_tpu.netps.client import (
    _BAD_KNOB_COMBOS_WARNED,
    _validate_knob_combo,
)
from distkeras_tpu.netps.tuner import (
    MarginalThroughputPolicy,
    Tuner,
    TunerConfig,
    TunerState,
    best_codec,
    probe_codecs,
    recommended_topology,
)

FAST = dict(timeout=1.0, retries=3, backoff=0.01)


def make_server(**kw):
    kw.setdefault("discipline", "adag")
    return PSServer(**kw).start()


def leaves(*shapes):
    rng = np.random.default_rng(0)
    return [rng.normal(size=s).astype(np.float32) for s in shapes]


def cfg(**over):
    """A deterministic unit-test TunerConfig (no env coupling)."""
    base = dict(interval=1, cooldown=1, probes=1, max_retunes=8,
                osc_limit=3, hier_fanin=4, min_gain=0.1,
                hidden_floor=0.5, stale_ceiling=4.0)
    base.update(over)
    return TunerConfig(**base)


# ---------------------------------------------------------------------------
# Satellite: knob-combo validation at client init
# ---------------------------------------------------------------------------

def test_measured_bad_knob_combo_warns_once_per_process():
    _BAD_KNOB_COMBOS_WARNED.clear()
    telemetry.reset()
    with pytest.warns(RuntimeWarning, match="int8\\+shm"):
        _validate_knob_combo("int8", "shm", 1)
    with pytest.warns(RuntimeWarning, match="shards>1\\+shm"):
        _validate_knob_combo("none", "shm", 2)
    # Same combos again: silent (a fleet must not scream N times).
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _validate_knob_combo("int8", "shm", 4)
    reg = telemetry.get()
    assert reg.counter("tuner.knob_warnings").value == 2
    combos = [e["combo"] for e in reg.events()
              if e["kind"] == "netps_knob_warning"]
    assert combos == ["int8+shm", "shards>1+shm"]
    # Measured-GOOD pairings never warn.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        _validate_knob_combo("none", "shm", 1)
        _validate_knob_combo("int8", "tcp", 4)


def test_client_init_routes_through_combo_validation():
    _BAD_KNOB_COMBOS_WARNED.clear()
    srv = make_server()
    try:
        with pytest.warns(RuntimeWarning, match="int8\\+shm"):
            c = PSClient(srv.endpoint, worker_id=0, compress="int8",
                         transport="shm", **FAST)
        c.close()
    finally:
        srv.close()
    _BAD_KNOB_COMBOS_WARNED.clear()


# ---------------------------------------------------------------------------
# Join-time micro A/B probes
# ---------------------------------------------------------------------------

def test_probe_none_against_capability_less_server(monkeypatch):
    """Old peers are unaffected by construction: no ``tuner`` caps bit
    means no probe traffic at all — the sweep is empty, the static knobs
    stand."""
    monkeypatch.setattr(wire, "CAPS", {})
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = leaves((8,))
            c.join(init=init)
            assert c.probe(init) is None
            assert probe_codecs(c, init) == []
            assert best_codec([]) is None
    finally:
        srv.close()


def test_probe_pays_decode_but_never_touches_server_state():
    """The probe op decodes exactly like a commit (the timing must include
    the dequantize cost) but must not move the fold, the journal, the
    dedup table, or the update counter."""
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = leaves((16, 3), (5,))
            _, upd = c.join(init=init)
            assert c.commit([np.ones_like(a) for a in init], upd).applied
            center_before, upd_before = c.pull()
            log_before = list(srv.commit_log)
            seq_before = dict(srv._last_seq)
            for codec in wire.CODECS:
                hdr = c.probe(init, codec=codec)
                assert hdr is not None and hdr["ok"]
                # probe_bytes is the LOGICAL f32 payload, codec-independent.
                assert hdr["probe_bytes"] == sum(a.nbytes for a in init)
                assert hdr["decode_s"] >= 0.0
            center_after, upd_after = c.pull()
            assert srv.commit_log == log_before
            assert dict(srv._last_seq) == seq_before
            assert upd_after == upd_before
            for a, b in zip(center_before, center_after):
                np.testing.assert_array_equal(a, b)
    finally:
        srv.close()


def test_probe_sweep_scores_and_picks_a_winner():
    telemetry.reset()
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = leaves((64, 8))
            c.join(init=init)
            results = probe_codecs(c, init, probes=2)
            assert [r.codec for r in results] == list(wire.CODECS)
            assert all(r.score > 0 and r.probes == 2 for r in results)
            assert best_codec(results) in wire.CODECS
        reg = telemetry.get()
        assert reg.counter("tuner.probes").value == 2 * len(wire.CODECS)
        assert [e["codec"] for e in reg.events()
                if e["kind"] == "tuner_probe"] == list(wire.CODECS)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Mid-run renegotiation: exactly-once and torn-pull safety
# ---------------------------------------------------------------------------

def test_retune_with_commits_in_flight_preserves_exactly_once():
    """THE mid-run retune acceptance scenario: a commit folded under the
    old dialect is retransmitted AFTER the codec retune — the dedup table
    answers it (duplicate, no second fold), and the next commit folds
    normally under the new dialect."""
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = [np.zeros(6, np.float32)]
            _, upd = c.join(init=init)
            assert c.commit([np.ones(6, np.float32)], upd).applied  # seq 0
            changed = c.retune(codec="int8")
            assert changed == {"codec": ("none", "int8")}
            assert c._residual is None  # error feedback restarts
            # The retransmit of seq 0 arrives after the retune (its reply
            # was "lost"); it carries the ORIGINAL seq and dialect.
            hdr, _ = c._rpc("commit", {"seq": 0, "pulled": 0},
                            [np.ones(6, np.float32)])
            assert hdr["duplicate"] is True
            _, upd = c.pull()
            assert c.commit([np.full(6, 2.0, np.float32)], upd).applied
            assert [s for _w, s, _st in srv.commit_log] == [0, 1]
            # One fold of +1.0 and one int8-quantized fold of ~+2.0.
            np.testing.assert_allclose(srv.center()[0], 3.0, atol=0.05)
    finally:
        srv.close()


def test_retune_survives_rejoin_with_the_retuned_preference():
    """A failover/eviction rejoin renegotiates from the RETUNED codec,
    not the construction-time one — a walk must not undo the controller."""
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = leaves((4,))
            c.join(init=init)
            c.retune(codec="bf16")
            assert c.requested_codec == "bf16"
            c.join()  # an explicit rejoin renegotiates the dialect
            assert c.codec == "bf16"
    finally:
        srv.close()


def test_striping_retune_midrun_without_torn_pull():
    """Flipping the stripe count mid-run: every pull before and after the
    change reassembles the same center an unstriped observer sees, and
    each logical commit still folds exactly once."""
    srv = make_server(discipline="downpour")
    try:
        init = leaves((40, 3), (7,), (2, 2), (90,))
        with PSClient(srv.endpoint, worker_id=0, shards=2, **FAST) as c, \
                PSClient(srv.endpoint, worker_id=1, **FAST) as plain:
            _, upd = c.join(init=init)
            plain.join()
            assert c.active_shards == 2
            assert c.commit([np.ones_like(a) for a in init], upd).applied
            changed = c.retune(shards=1, template=init)
            assert changed == {"shards": (2, 1)}
            striped_off, u1 = c.pull()
            ref, u2 = plain.pull()
            assert u1 == u2
            for a, b in zip(striped_off, ref):
                np.testing.assert_array_equal(a, b)
            _, upd = c.pull()
            assert c.commit([np.ones_like(a) for a in init], upd).applied
            changed = c.retune(shards=2, template=init)
            assert changed == {"shards": (1, 2)}
            striped_on, u3 = c.pull()
            ref, u4 = plain.pull()
            assert u3 == u4
            for a, b, i in zip(striped_on, ref, init):
                np.testing.assert_array_equal(a, b)
                np.testing.assert_allclose(a, i + 2.0, rtol=1e-6)
        assert [(w, s) for w, s, _ in srv.commit_log] == [(0, 0), (0, 1)]
    finally:
        srv.close()


def test_retune_clamps_unknown_codec_and_out_of_range_shards():
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = leaves((4,))
            c.join(init=init)
            assert c.retune(codec="zstd") == {}  # never advertised
            assert c.codec == "none"
            # One connection: a 4-way stripe target clamps to 1 (no-op).
            assert c.retune(shards=4, template=init) == {}
            assert c.active_shards == 1
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Controller guardrails (pure unit tests — no server)
# ---------------------------------------------------------------------------

def test_apply_to_during_failover_walk_is_deferred_not_lost():
    class FakeClient:
        walk_count = 0

        def __init__(self):
            self.calls = []

        def retune(self, codec=None, shards=None, template=None):
            self.calls.append((codec, shards))
            return {"codec": (None, codec)}

    telemetry.reset()
    t = Tuner(4, cfg=cfg())
    assert t.propose("codec", "none", "int8", "test", 0)
    assert t.generation == 1
    fc, st = FakeClient(), TunerState()
    fc.walk_count = 2  # the endpoint walker moved since st.walks == 0
    assert t.apply_to(fc, [], st) is None
    assert fc.calls == [] and st.generation == 0  # deferred...
    assert t.deferred == 1
    assert telemetry.get().counter("tuner.deferred").value == 1
    # ...and retried next round (no further walk): the generation lands.
    assert t.apply_to(fc, [], st) == {"codec": (None, "int8")}
    assert fc.calls == [("int8", None)] and st.generation == 1
    assert t.apply_to(fc, [], st) is None  # nothing left to adopt


def test_floor_violating_proposal_is_dropped_and_counted():
    telemetry.reset()
    t = Tuner(4, inflight=2, cfg=cfg())
    assert not t.propose("inflight", 2, 0, "test", 0)  # below floor
    assert t.inflight == 2
    t.peer_codecs = ("none", "bf16")
    assert not t.propose("codec", "none", "int8", "test", 0)  # unadvertised
    assert t.codec is None
    assert telemetry.get().counter("tuner.floor_violations").value == 2


def test_cooldown_and_budget_bound_the_retune_rate():
    t = Tuner(4, inflight=1, cfg=cfg(cooldown=5, max_retunes=2))
    assert t.propose("inflight", 1, 2, "test", 0)
    assert not t.propose("inflight", 2, 3, "test", 2)   # inside cooldown
    assert t.propose("inflight", 2, 3, "test", 5)       # budget now spent
    assert not t.propose("inflight", 3, 4, "test", 20)  # over max_retunes
    assert t.inflight == 3 and t.retunes == 2


def test_oscillation_freezes_the_knob_at_its_static_initial():
    telemetry.reset()
    t = Tuner(4, inflight=1, cfg=cfg(osc_limit=2, max_retunes=100))
    assert t.propose("inflight", 1, 2, "a", 0)
    assert t.propose("inflight", 2, 1, "b", 10)   # flip 1
    assert t.propose("inflight", 1, 2, "c", 20)   # flip 2 -> freeze
    assert t.inflight == 1  # restored to the static initial
    assert t.fallbacks == 1
    assert not t.propose("inflight", 1, 3, "d", 40)  # frozen for the run
    reg = telemetry.get()
    assert reg.counter("tuner.oscillation_fallbacks").value == 1
    falls = [e for e in reg.events() if e["kind"] == "tuner_fallback"]
    assert len(falls) == 1 and falls[0]["knob"] == "inflight"
    assert falls[0]["restored"] == 1


def test_recommended_topology_flips_at_the_fan_in_crossover():
    assert recommended_topology(3, crossover=4) == "flat"
    assert recommended_topology(4, crossover=4) == "hier"
    assert recommended_topology(2) == "flat"   # env default crossover: 4
    assert recommended_topology(8) == "hier"
    t = Tuner(8, cfg=cfg())
    assert t.choose_topology() == "hier"
    assert t.decisions[-1].knob == "topology"
    assert t.decisions[-1].old is None  # chosen, not changed


# ---------------------------------------------------------------------------
# Fleet: marginal-throughput expansion gate
# ---------------------------------------------------------------------------

def test_marginal_throughput_policy_blocks_flat_growth():
    telemetry.reset()
    p = MarginalThroughputPolicy(min_gain=0.1)
    assert p.allow_expand("t/j", 1)  # no evidence: never starve a cold job
    p.observe("t/j", 1, 0, now=0.0)
    p.observe("t/j", 1, 100, now=1.0)   # rate 100 at 1 worker
    p.observe("t/j", 2, 100, now=1.0)   # grant grew: seal + re-anchor
    p.observe("t/j", 2, 205, now=2.0)   # rate 105 at 2 workers
    # 105 < 100 * 1.1: the second worker did not move the needle.
    assert not p.allow_expand("t/j", 2)
    reg = telemetry.get()
    assert reg.counter("tuner.expand_blocked").value == 1
    blocked = [e for e in reg.events() if e["kind"] == "tuner_expand_blocked"]
    assert blocked and blocked[0]["job"] == "t/j"
    # The rate recovers (straggler healed): expansion re-opens.
    p.observe("t/j", 2, 350, now=3.0)   # rate 125 at 2 workers
    assert p.allow_expand("t/j", 2)


def test_scheduler_expansion_gate_holds_grant_without_floor_violations():
    import test_fleet as tf
    from distkeras_tpu.fleet import DONE, FleetJob, FleetScheduler

    class Deny:
        def __init__(self):
            self.asked = []

        def observe(self, label, workers, progress, now=None):
            pass

        def allow_expand(self, label, workers):
            self.asked.append((label, workers))
            return False

    policy = Deny()
    sched = FleetScheduler(capacity=4, tick_s=0.01,
                           expansion_policy=policy)
    job = sched.submit(FleetJob("solo", "a", tf.FakeRuntime(total=40),
                                min_gang=1, max_workers=4))
    tf.drive(sched, lambda: job.state == DONE)
    sched.close()
    # The gang floor was honored (the job ran and finished), expansion
    # beyond it was withheld every tick, and withholding an EXPANSION can
    # never read as a floor violation.
    assert policy.asked and all(w >= 1 for _l, w in policy.asked)
    assert job.expands == 0
    assert sched.floor_violations == 0
