"""Driver-contract tests: entry() jits; dryrun_multichip runs dp x sp x tp."""

import importlib.util
import os

import jax

import envcaps


def _load_entry():
    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_forward_jits():
    mod = _load_entry()
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 128, 8192)


@envcaps.skip_unless_key_sharding()
def test_dryrun_multichip_8():
    _load_entry().dryrun_multichip(8)


def test_dryrun_multichip_2():
    _load_entry().dryrun_multichip(2)
