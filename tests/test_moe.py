"""Mixture-of-Experts tests: routing semantics, EP sharding, training."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distkeras_tpu.models.moe import MoEMLP, small_moe_lm
from distkeras_tpu.parallel.gspmd import GSPMDEngine
from distkeras_tpu.parallel.sharding import MOE_RULES, param_path_specs
from distkeras_tpu.runtime.mesh import hybrid_mesh


def test_moe_mlp_routing_and_aux_loss():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 16, 8)).astype(np.float32))
    module = MoEMLP(num_experts=4, d_model=8, d_ff=16, capacity_factor=2.0)
    variables = module.init(jax.random.key(0), x)
    out, state = module.apply(variables, x, mutable=["intermediates"])
    assert out.shape == x.shape
    aux = state["intermediates"]["aux_loss"][0]
    # perfectly balanced routing gives aux = 1; anything sane is within [0.5, 4]
    assert 0.5 < float(aux) < 4.0
    # expert bank is stacked [E, ...]
    assert variables["params"]["experts"]["up"]["kernel"].shape == (4, 8, 16)


def test_moe_capacity_drops_overflow():
    """With capacity_factor ~0, (almost) all tokens are dropped -> output ~ 0."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1, 32, 8)).astype(np.float32))
    module = MoEMLP(num_experts=2, d_model=8, d_ff=16, capacity_factor=0.04)
    variables = module.init(jax.random.key(0), x)
    out = module.apply(variables, x)
    # capacity C=1 per expert: at most 2 of 32 token outputs nonzero
    nonzero_rows = np.abs(np.asarray(out)).reshape(32, 8).sum(-1) > 1e-6
    assert nonzero_rows.sum() <= 2


def test_moe_rules_shard_expert_bank():
    model = small_moe_lm(num_layers=1, num_experts=4, d_model=16, num_heads=2,
                         d_ff=32, vocab_size=64, max_seq_len=32)
    specs = param_path_specs(model.params, MOE_RULES)
    flat = {"/".join(str(getattr(p, "key", p)) for p in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(
                specs, is_leaf=lambda x: isinstance(x, P))[0]}
    assert flat["block_0/moe/experts/up/kernel"] == P("expert", None, None)
    assert flat["block_0/moe/router/kernel"] == P()  # router replicated
    assert flat["block_0/attn/query/kernel"] == P(None, "model", None)


def test_moe_ep_sharded_forward_matches_dense():
    model = small_moe_lm(num_layers=1, num_experts=4, d_model=16, num_heads=2,
                         d_ff=32, vocab_size=64, max_seq_len=32, seq_len=32)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 32)), jnp.int32)
    expect = model.predict(tokens)

    mesh = hybrid_mesh({"data": 2, "expert": 4})
    from distkeras_tpu.parallel.sharding import param_shardings

    sharded = jax.device_put(model.params,
                             param_shardings(model.params, mesh, MOE_RULES))
    tok = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    out = jax.jit(lambda p, t: model.module.apply({"params": p}, t, train=False))(
        sharded, tok)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=3e-4)


def _expert_fractions(model, params, tokens):
    _, mut = model.module.apply({"params": params}, tokens, train=False,
                                mutable=["intermediates"])
    flat = jax.tree_util.tree_flatten_with_path(mut["intermediates"])[0]
    return [np.asarray(leaf) for path, leaf in flat
            if any(str(getattr(p, "key", p)) == "expert_fraction" for p in path)]


def _train_moe(aux_loss_weight, steps=40):
    model = small_moe_lm(num_layers=1, num_experts=4, d_model=16, num_heads=2,
                         d_ff=32, vocab_size=64, max_seq_len=32, seq_len=32)
    mesh = hybrid_mesh({"data": 2, "expert": 4})
    engine = GSPMDEngine(model, "adam", "sparse_categorical_crossentropy", mesh,
                         rules=MOE_RULES, learning_rate=1e-2,
                         aux_loss_weight=aux_loss_weight)
    state = engine.init_state()
    rng = np.random.default_rng(3)
    tokens = np.asarray(rng.integers(0, 64, size=(8, 32)), np.int32)
    x = jax.device_put(jnp.asarray(tokens), engine.batch_sharding())
    y = jax.device_put(jnp.asarray(np.roll(tokens, -1, 1)), engine.batch_sharding())
    for _ in range(steps):
        state, loss = engine.step(state, x, y)
    assert np.isfinite(float(loss))
    frac = _expert_fractions(model, jax.device_get(state.params),
                             jnp.asarray(tokens))[0]
    return frac


def test_aux_loss_keeps_experts_balanced():
    """The engine-applied Switch aux loss must actually shape training: expert
    token fractions stay near uniform (1/E = 0.25) with it, and are measurably
    more skewed without it. This is what makes EP trainable-to-quality, not
    just shardable."""
    frac_off = _train_moe(aux_loss_weight=0.0)
    frac_on = _train_moe(aux_loss_weight=0.1)
    assert frac_on.max() < 0.31, f"aux-weighted routing skewed: {frac_on}"
    assert frac_on.max() < frac_off.max(), (
        f"aux loss had no balancing effect: on={frac_on} off={frac_off}"
    )


def test_moe_ep_training_step_decreases_loss():
    model = small_moe_lm(num_layers=2, num_experts=4, d_model=16, num_heads=2,
                         d_ff=32, vocab_size=64, max_seq_len=32, seq_len=32)
    mesh = hybrid_mesh({"data": 2, "expert": 4})
    engine = GSPMDEngine(model, "adam", "sparse_categorical_crossentropy", mesh,
                         rules=MOE_RULES, learning_rate=3e-3)
    state = engine.init_state()
    rng = np.random.default_rng(3)
    tokens = np.asarray(rng.integers(0, 64, size=(8, 32)), np.int32)
    x = jax.device_put(jnp.asarray(tokens), engine.batch_sharding())
    y = jax.device_put(jnp.asarray(np.roll(tokens, -1, 1)), engine.batch_sharding())
    losses = []
    for _ in range(8):
        state, loss = engine.step(state, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_top2_routing():
    """GShard top-2: each token reaches its two highest-prob experts with
    renormalized gates; combine mass sums to ~1 when nothing is dropped."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 16, 8)).astype(np.float32))
    module = MoEMLP(num_experts=4, d_model=8, d_ff=16, capacity_factor=4.0,
                    num_selected=2)
    variables = module.init(jax.random.key(0), x)
    out, state = module.apply(variables, x, mutable=["intermediates"])
    assert out.shape == x.shape
    assert np.all(np.isfinite(np.asarray(out)))
    # ample capacity: the POST-capacity combine mass per token is ~1 — the
    # renormalized top-2 gates survive dispatch without drops
    mass = float(state["intermediates"]["combine_mass"][0])
    np.testing.assert_allclose(mass, 1.0, atol=1e-5)
    aux = float(state["intermediates"]["aux_loss"][0])
    assert 0.5 < aux < 4.0
    # tight capacity: drops must show up as lost combine mass
    tight = MoEMLP(num_experts=4, d_model=8, d_ff=16, capacity_factor=0.1,
                   num_selected=2)
    vt = tight.init(jax.random.key(0), x)
    _, st = tight.apply(vt, x, mutable=["intermediates"])
    assert float(st["intermediates"]["combine_mass"][0]) < 0.9


def test_moe_top2_ep_sharded_matches_dense():
    model = small_moe_lm(num_layers=1, num_experts=4, d_model=16, num_heads=2,
                         d_ff=32, vocab_size=64, max_seq_len=32, seq_len=32,
                         num_selected=2, capacity_factor=2.0)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 32)), jnp.int32)
    expect = model.predict(tokens)
    mesh = hybrid_mesh({"data": 2, "expert": 4})
    from distkeras_tpu.parallel.sharding import param_shardings

    sharded = jax.device_put(model.params,
                             param_shardings(model.params, mesh, MOE_RULES))
    tok = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    out = jax.jit(lambda p, t: model.module.apply({"params": p}, t, train=False))(
        sharded, tok)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=3e-4)


def test_moe_top2_training_step():
    from distkeras_tpu.parallel.gspmd import GSPMDEngine

    model = small_moe_lm(num_layers=1, num_experts=4, d_model=32, num_heads=2,
                         d_ff=64, vocab_size=128, max_seq_len=16, seq_len=16,
                         num_selected=2)
    mesh = hybrid_mesh({"data": 2, "expert": 4})
    engine = GSPMDEngine(model, "adam", "sparse_categorical_crossentropy", mesh,
                         rules=MOE_RULES, learning_rate=1e-3,
                         aux_loss_weight=0.01)
    state = engine.init_state()
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, size=(4, 16))
    x = jax.device_put(jnp.asarray(tokens, jnp.int32), engine.batch_sharding())
    y = jax.device_put(jnp.asarray(np.roll(tokens, -1, 1), jnp.int32),
                       engine.batch_sharding())
    state, l0 = engine.step(state, x, y)
    for _ in range(10):
        state, loss = engine.step(state, x, y)
    assert float(loss) < float(l0)
