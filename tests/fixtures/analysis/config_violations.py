"""Planted DK3xx violations for tests/test_analysis.py (parsed, never run)."""

import os


def telemetry_enabled():
    return os.environ.get("DKTPU_TELEMETRY", "") != "0"  # PLANT: DK301


def native_disabled():
    return os.getenv("DKTPU_NO_NATIVE") == "1"  # PLANT: DK301


FEATURE_FLAG = "DKTPU_EXPERIMENTAL_FOO"  # PLANT: DK302


def fetch_secret():
    return os.environ["DKTPU_SECRET_KNOB"]  # PLANT: DK301 DK302


def documented_and_registered() -> str:
    """Negative control: a registered name in a docstring (DKTPU_FAULTS)
    plus a registry accessor read is exactly the sanctioned pattern."""
    from distkeras_tpu.runtime import config

    return config.env_str("DKTPU_FAULTS")


def stale_marker():
    return 1  # dk: disable=DK301  # PLANT: DK001


def dynamic_env_names(suffix):
    key = f"DKTPU_TUNE_{suffix}"  # PLANT: DK302
    prefix = "DKTPU_" + suffix  # PLANT: DK302
    return key, prefix
