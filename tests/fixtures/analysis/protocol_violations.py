"""Planted DK4xx violations for tests/test_analysis.py (parsed, never run).

Importing ``distkeras_tpu.netps`` puts this module on the wire plane, which
is what scopes DK401/DK402/DK403 onto it.
"""

import struct

from distkeras_tpu.netps import wire


def dispatch(srv, op, hdr, reply):
    if op == "comit":  # PLANT: DK401
        return None
    if hdr["op"] == "fence":  # PLANT: DK401
        return None
    if op == wire.OP_PULL:  # negative control: the declared constant
        return hdr.get("worker_id")  # negative control: declared key
    if hdr.get("branch_id"):  # PLANT: DK402
        return reply["wrong_key"]  # PLANT: DK402
    if reply.get("error") == "not_an_error":  # PLANT: DK402
        return srv._err("nonsense", "boom")  # PLANT: DK402
    return srv._err("protocol", "ok")  # negative control: declared kind


def send(client, hdr):
    client._rpc("join", hdr)  # PLANT: DK401
    frame = {"op": "pull"}  # PLANT: DK401
    return frame


OP_FROB = "frob"  # PLANT: DK401


def pack_ad_hoc(n):
    header = struct.pack("<I", n)  # PLANT: DK403
    return header + wire.U32.pack(n)  # negative control: wire's layout
