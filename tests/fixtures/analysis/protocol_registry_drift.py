"""Planted OP_REGISTRY drift (the wire.py half of DK401; parsed, never
run): an undeclared constant, a ghost registry key, and an undeclared cap
gate."""

from typing import NamedTuple


class OpSpec(NamedTuple):
    cap: str
    reply_keys: tuple


CAPS = {"base": True}

OP_ALPHA = "alpha"
OP_BETA = "beta"  # PLANT: DK401
OP_DELTA = "delta"

OP_REGISTRY = {  # PLANT: DK401
    OP_ALPHA: OpSpec("base", ()),
    "gamma": OpSpec("base", ()),
    OP_DELTA: OpSpec("ghost_cap", ()),  # PLANT: DK401
}
