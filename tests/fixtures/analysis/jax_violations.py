"""Planted DK1xx violations for tests/test_analysis.py.

Each violating line carries a ``# PLANT: <rules>`` marker; the test asserts
every rule fires exactly on its marked lines and nowhere else. This module
is parsed by the analyzer, never imported — names need not resolve.
"""

import os
import time
from functools import partial

import jax
from jax import lax

from distkeras_tpu import telemetry


@jax.jit
def env_inside_jit(x):
    flag = os.environ.get("DKTPU_TELEMETRY", "")  # PLANT: DK101 DK301
    return x if flag else -x


def clock_body(carry, _):
    now = time.perf_counter()  # PLANT: DK101
    return carry + now, None


def run_scan(xs):
    return lax.scan(clock_body, 0.0, xs)


@partial(jax.jit, static_argnums=(1,))
def prints_while_tracing(x, k):
    print("tracing", k)  # PLANT: DK102
    return x * k


@jax.jit
def reads_file(x):
    with open("/tmp/stats.txt") as f:  # PLANT: DK102
        f.read()
    return x


@jax.jit
def telemetry_module_call(x):
    telemetry.get()  # PLANT: DK103
    return x


tele = telemetry.get()


@jax.jit
def telemetry_handle_call(x):
    tele.counter("rounds").add(1)  # PLANT: DK103
    return x


def windowed(x, sizes=[4, 8]):
    return x


jitted_windowed = jax.jit(windowed, static_argnums=(1,))  # PLANT: DK104


@partial(jax.jit, static_argnames=("mode",))  # PLANT: DK104
def decorated_static(x, mode={"train": True}):
    return x


_history = []


@jax.jit
def appends_to_module_list(x):
    _history.append(x)  # PLANT: DK105
    return x


_step = 0


@jax.jit
def rebinds_global(x):
    global _step  # PLANT: DK105
    _step = _step + 1
    return x + _step


class Stateful:
    def make_traced(self):
        @jax.jit
        def inner(x):
            self.cache = x  # PLANT: DK105
            return x
        return inner


@jax.jit
def clean_control(x):
    """Pure traced code: locals mutate freely, no findings."""
    parts = []
    parts.append(x)
    total = {"x": x}
    total["x"] = x + 1
    return parts[0] + total["x"]
