"""Planted DK5xx violations (parsed, never run): the minimized PR 6
resolve-backend-under-center-lock repro and the ACK-before-journal shape
the OffsetJournal discipline forbids."""

import os
import threading
import time

from distkeras_tpu.netps.fold import resolve_backend


class MiniCenter:
    """PR 6 in miniature: ``self._lock`` guards the center, and the fold
    path resolves the accelerator backend while holding it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._center = None
        self._updates = 0

    def fold(self, delta):
        with self._lock:
            resolve_backend()  # PLANT: DK501
            self._center = list(delta)

    def snooze(self):
        with self._lock:
            time.sleep(0.1)  # PLANT: DK501
            self._center = None

    def fold_resolved(self, delta, backend):
        resolve_backend()  # negative control: resolve BEFORE the lock
        with self._lock:
            self._center = list(delta)


class AckFirstIngest:
    def ingest(self, client, journal, wid, seq, offset):
        client.commit(offset)  # PLANT: DK502
        journal.intent(wid, seq, offset)

    def persist(self, sock, fh):
        sock.sendall(b"ok")  # PLANT: DK502
        os.fsync(fh.fileno())

    def ingest_properly(self, client, journal, wid, seq, offset):
        journal.intent(wid, seq, offset)  # negative: intent-before-RPC
        client.commit(offset)


def stale_suppressed():
    return 1  # dk: disable=DK501  # PLANT: DK001
