"""Planted DK2xx violations for tests/test_analysis.py.

``# PLANT:`` markers pin line-exact findings; DK201's finding line depends
on graph traversal order, so it uses the file-level ``# PLANT-FILE:``
marker (exact count, any line). This module is also *executed* by
``test_static_graph_matches_witnessed_order`` — importing it only defines
locks/classes; the planted thread leaks live in functions no test calls.
"""
# PLANT-FILE: DK201=2

import threading

_lock_a = threading.Lock()
_lock_b = threading.Lock()


def forward():
    with _lock_a:
        with _lock_b:
            pass


def backward():  # inverted order vs forward(): the DK201 cycle
    with _lock_b:
        with _lock_a:
            pass


class Pool:
    """Second DK201: the inversion is only visible through a call edge."""

    def __init__(self):
        self._alloc = threading.Lock()
        self._free = threading.Lock()

    def take(self):
        with self._alloc:
            self._refill()  # acquires _free while holding _alloc

    def _refill(self):
        with self._free:
            pass

    def drain(self):
        with self._free:
            with self._alloc:
                pass


class Buffer:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self.total = 0

    def put(self, x):
        with self._lock:
            self.items.append(x)
            self.total += 1

    def fast_put(self, x):  # races put(): same attrs, no lock
        self.items.append(x)  # PLANT: DK202
        self.total += 1  # PLANT: DK202


def spawn(target):
    worker = threading.Thread(target=target)  # PLANT: DK203
    worker.start()


class Owner:
    def start(self, fn):
        self._t = threading.Thread(target=fn)  # PLANT: DK203
        self._t.start()


def swallowing_loop(q):
    while True:
        try:
            q.get()
        except:  # PLANT: DK204
            pass


def swallowing_drain(q):
    for _ in range(10):
        try:
            q.get()
        except BaseException:  # PLANT: DK204
            continue


def reraising(q):  # negative control: re-raise is not swallowing
    try:
        q.get()
    except BaseException:
        raise


def surfacing(q, errors):  # negative control: the bound exc is surfaced
    try:
        q.get()
    except BaseException as e:
        errors.append(e)


def suppressed(q):
    try:
        q.get()
    except:  # dk: disable=DK204 - fixture: suppression must silence this
        pass
