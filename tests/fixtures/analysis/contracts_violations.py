"""Planted DK6xx violations for tests/test_analysis.py (parsed, never
run): telemetry names outside telemetry/registry.py's declarations."""

from distkeras_tpu import telemetry


def record(step, shard):
    telemetry.counter("training.not_a_metric").add(1)  # PLANT: DK601
    telemetry.histogram(f"made.up.{step}").observe(0.1)  # PLANT: DK601
    telemetry.gauge(f"fleet.round.{shard}").set(1)  # PLANT: DK601
    telemetry.counter("netps.commits").add(1)  # negative: declared
    with telemetry.span(f"netps.rpc.{step}"):  # negative: dynamic prefix
        pass
