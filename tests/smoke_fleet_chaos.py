"""CI fleet-chaos smoke (not a pytest module — run directly).

Three tenants' training jobs on ONE worker pool, driven by the
:class:`~distkeras_tpu.fleet.FleetScheduler`, each surviving a different
leg of the chaos matrix — the ROADMAP's "heavy traffic = many tenants,
not one run" story, exercised end to end on every PR:

* ``acme/alpha``  (prio 0): in-process PS; loses a worker to the
  ``evict`` drill (sleeps past its lease, rejoins mid-run) and a slot or
  two to preemption when the high-priority tenant arrives.
* ``bidco/beta``  (prio 0): its PS sits behind a :class:`ChaosProxy`
  injecting a partition; also a preemption victim.
* ``corp/gamma``  (prio 5, submitted mid-run): its arrival forces the
  scheduler to SHRINK the other tenants (lease revocation, floor at each
  victim's min gang); its PS is a real ``python -m distkeras_tpu.netps``
  subprocess with a state dir whose own fault plan SIGKILLs it mid-run
  (``ps_crash``) — a babysitter thread cold-restarts it and the workers'
  retransmits dedup exactly-once.

On top, the ambient plan schedules a ``preempt@R:N`` forced-preemption
drill against the scheduler itself. All three jobs must converge; the
victims must re-expand once capacity frees; exactly-once is asserted on
the in-process commit logs AND the subprocess's on-disk journal; the
shrink floor is never violated; and the telemetry report must attribute
throughput/preemptions/restarts per tenant. All seeds pinned.

    python tests/smoke_fleet_chaos.py
"""

import os
import sys

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# Tight-but-survivable budgets: the retry envelope must bridge the PS
# subprocess's crash + cold restart (~2 s), not just a flaky frame.
os.environ.setdefault("DKTPU_NET_TIMEOUT", "1.0")
os.environ.setdefault("DKTPU_NET_RETRIES", "12")
os.environ.setdefault("DKTPU_NET_BACKOFF", "0.05")
os.environ.setdefault(
    "DKTPU_NET_FAULTS",
    "evict@3:2.5;partition@18:0.8;preempt@30:2;seed=3")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distkeras_tpu import DataFrame, telemetry  # noqa: E402
from distkeras_tpu.data.batching import make_batches  # noqa: E402
from distkeras_tpu.fleet import (  # noqa: E402
    DONE,
    ElasticTraining,
    FleetJob,
    FleetScheduler,
)
from distkeras_tpu.models import Model  # noqa: E402
from distkeras_tpu.models.mlp import MLP  # noqa: E402
from distkeras_tpu.netps import ChaosProxy, PSServer  # noqa: E402
from distkeras_tpu.netps import state as netps_state  # noqa: E402
from distkeras_tpu.ops.losses import get_loss  # noqa: E402
from distkeras_tpu.ops.optimizers import get_optimizer  # noqa: E402
from distkeras_tpu.telemetry.report import build_report  # noqa: E402

#: the corp PS subprocess's own plan: SIGKILL just before folding commit 6
#: (mid-run for gamma's ~12 folds). Pinned, not random.
PS_FAULTS = os.environ.get("FLEET_SMOKE_PS_FAULTS", "ps_crash@6;seed=3")

LEASE_S = 1.0


def _dataset(seed):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(3, 4))
    y = rng.integers(0, 3, size=512)
    x = (centers[y] + rng.normal(scale=0.5, size=(512, 4))).astype(
        np.float32)
    return DataFrame({"features": x, "label": y.astype(np.int32)}), x, y


def _runtime(df, seed, max_workers, num_epoch, **kw):
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32), seed=seed)
    plan = make_batches(df, "features", "label", batch_size=16,
                        num_workers=max_workers, window=4,
                        num_epoch=num_epoch, shuffle=True, seed=seed)
    return ElasticTraining(
        model=model, tx=get_optimizer("sgd", 0.1),
        loss_fn=get_loss("sparse_categorical_crossentropy"),
        plan=plan, discipline="adag", seed=seed, lease_s=LEASE_S, **kw)


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_ps(port, state_dir, faults_state):
    import subprocess

    # The smoke's own chaos plan must not leak into the server subprocess:
    # it gets its OWN plan (ps_crash) + fired-state journal so the crash
    # stays one-shot across the restart it causes.
    drop = {"DKTPU_NET_FAULTS", "DKTPU_FAULTS_STATE"}
    env = {k: v for k, v in os.environ.items() if k not in drop}
    env.update({"JAX_PLATFORMS": "cpu",
                "DKTPU_NET_FAULTS": PS_FAULTS,
                "DKTPU_FAULTS_STATE": faults_state})
    return subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.netps", "--host", "127.0.0.1",
         "--port", str(port), "--discipline", "adag",
         "--lease", str(LEASE_S),
         "--state-dir", state_dir, "--snapshot-every", "10"],
        env=env)


def _accuracy(runtime, x, y):
    trained = runtime.result()
    return float((np.asarray(trained.predict(jnp.asarray(x))).argmax(-1)
                  == y).mean())


def _assert_exactly_once(pairs, label):
    seen = set()
    for key in pairs:
        assert key not in seen, f"{label}: commit {key} folded twice"
        seen.add(key)
    return len(seen)


def main() -> int:
    import shutil
    import subprocess
    import threading
    import time

    state_dir = os.environ.get("DKTPU_FLEET_SMOKE_STATE",
                               "/tmp/dktpu-fleet-ps-state")
    shutil.rmtree(state_dir, ignore_errors=True)
    os.makedirs(state_dir, exist_ok=True)
    faults_state = os.path.join(state_dir, "faults.journal")

    df_a, xa, ya = _dataset(10)
    df_b, xb, yb = _dataset(11)
    df_c, xc, yc = _dataset(12)

    # Work volume: each job trains rounds x max_workers claim-queue items
    # (the plan's full schedule), so epochs are kept small for CI wall
    # time while leaving enough commits for every chaos index to land.
    # acme/alpha: plain in-process PS.
    rt_a = _runtime(df_a, seed=0, max_workers=4, num_epoch=3)
    # bidco/beta: in-process PS behind the chaos proxy (the partition
    # fault hits beta's wire; revocation still lands on the real server).
    srv_b = PSServer(discipline="adag", lease_s=LEASE_S).start()
    proxy = ChaosProxy(srv_b.endpoint).start()  # ambient DKTPU_NET_FAULTS
    rt_b = _runtime(df_b, seed=1, max_workers=4, num_epoch=3,
                    endpoint=proxy.endpoint, server=srv_b)
    # corp/gamma: external PS subprocess (state dir + ps_crash) + babysitter.
    ps_port = _free_port()
    primary = _launch_ps(ps_port, state_dir, faults_state)
    procs = [primary]
    restarts = [0]
    stop = threading.Event()

    def babysit():
        # Job.supervise's PS-restart duty, inlined: cold-restart the killed
        # primary on the same state dir + port.
        nonlocal primary
        while not stop.is_set():
            if primary.poll() is not None and primary.returncode != 0:
                restarts[0] += 1
                primary = _launch_ps(ps_port, state_dir, faults_state)
                procs.append(primary)
            time.sleep(0.1)

    threading.Thread(target=babysit, daemon=True).start()
    rt_c = _runtime(df_c, seed=2, max_workers=3, num_epoch=2,
                    endpoint=f"127.0.0.1:{ps_port}")

    sched = FleetScheduler(capacity=6, tick_s=0.02, preempt_grace=0.0)
    job_a = sched.submit(FleetJob("alpha", "acme", rt_a,
                                  priority=0, min_gang=2, max_workers=4))
    job_b = sched.submit(FleetJob("beta", "bidco", rt_b,
                                  priority=0, min_gang=2, max_workers=4))
    sched.start()
    try:
        # The high-priority tenant arrives once the pool is warm: its gang
        # only fits by preempting the incumbents down to their floors.
        deadline = time.monotonic() + 120
        while rt_a.progress() + rt_b.progress() < 4:
            assert time.monotonic() < deadline, "fleet warmup stalled"
            time.sleep(0.05)
        job_c = sched.submit(FleetJob("gamma", "corp", rt_c,
                                      priority=5, min_gang=2,
                                      max_workers=3))
        assert sched.wait(timeout=420), (
            f"fleet did not finish: {sched.stats()}")
    finally:
        stop.set()
        sched.close()
        proxy.close()
        crashed = any(p.poll() not in (0, None) for p in procs)
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)

    stats = sched.stats()
    for job in (job_a, job_b, job_c):
        assert job.state == DONE, f"{job.job_id} ended {job.state}"
    assert sched.floor_violations == 0, "a job was shrunk below its floor"

    # The chaos actually bit: every drill left its fingerprint.
    events = telemetry.get().events()
    fired = {e.get("fault") for e in events if e["kind"] == "fault_injected"}
    assert "evict" in fired, "the worker-kill drill never fired"
    assert "partition" in fired, "the partition drill never fired"
    assert "preempt" in fired, "the forced-preemption drill never fired"
    assert crashed and restarts[0] >= 1, (
        "ps_crash never killed + restarted the corp PS")

    # Preemption-driven shrink at corp/gamma's arrival, floors held,
    # victims re-expanded once capacity freed.
    victims = stats["acme/alpha"], stats["bidco/beta"]
    total_preempt = sum(v["preemptions"] for v in victims)
    assert total_preempt >= 2, f"incumbents were never preempted: {stats}"
    assert any(v["expands"] >= 1 for v in victims), (
        "no victim ever re-expanded")
    reg = telemetry.get()
    assert reg.counter("netps.revocations").value >= 2, (
        "preemption never revoked a lease")

    # Convergence per tenant.
    accs = {"acme/alpha": _accuracy(rt_a, xa, ya),
            "bidco/beta": _accuracy(rt_b, xb, yb),
            "corp/gamma": _accuracy(rt_c, xc, yc)}
    for jid, acc in accs.items():
        assert acc > 0.85, f"{jid} collapsed under fleet chaos: {acc}"

    # Exactly-once: in-process commit logs for alpha/beta; the on-disk
    # journal (the only view a subprocess leaves) for gamma — which must
    # also show nondecreasing epochs (cold restart keeps epoch 0).
    n_a = _assert_exactly_once(
        [(w, s) for w, s, _ in rt_a.server.commit_log], "alpha")
    n_b = _assert_exactly_once(
        [(w, s) for w, s, _ in rt_b.server.commit_log], "beta")
    records = netps_state.read_journal(state_dir)
    n_c = _assert_exactly_once(
        [(int(r["wid"]), int(r["seq"])) for r in records], "gamma")
    last_epoch = -1
    for r in records:
        assert int(r["e"]) >= last_epoch, "journal epoch went backwards"
        last_epoch = int(r["e"])
    assert n_c >= 5, "gamma's journal is implausibly short"

    # Per-tenant attribution through the report CLI path.
    jsonl = os.path.join(state_dir, "fleet_run.jsonl")
    telemetry.write_jsonl(reg, jsonl)
    rows = build_report(jsonl)["fleet"]
    by_tenant = {}
    for r in rows:
        by_tenant.setdefault(r["tenant"], []).append(r)
    assert set(by_tenant) >= {"acme", "bidco", "corp"}, (
        f"report lost a tenant: {sorted(by_tenant)}")
    for tenant, trows in by_tenant.items():
        assert sum(r.get("commits", 0) for r in trows) > 0, (
            f"{tenant} shows no throughput in the report")
    attributed_preempts = sum(
        r.get("preemptions", 0) for t in ("acme", "bidco")
        for r in by_tenant[t])
    assert attributed_preempts >= 2, (
        "preemptions were not attributed to the victim tenants")

    print("fleet chaos run: "
          + " ".join(f"{jid}: acc={acc:.4f}" for jid, acc in accs.items())
          + f" commits={n_a}/{n_b}/{n_c}"
          + f" preemptions={total_preempt}"
          + f" ps_restarts={restarts[0]}"
          + f" revocations={reg.counter('netps.revocations').value:.0f}"
          + f" floor_violations={sched.floor_violations}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
