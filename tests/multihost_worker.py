"""Worker script for the 2-process DCN bootstrap test (launched by ``Job``).

Each process pins the CPU platform with 2 virtual devices, joins the
``jax.distributed`` coordination service over loopback (the DCN path of
SURVEY.md §5's distributed-backend row), and runs one synchronous-DP training
job over the resulting 4-device *global* mesh through the real user-facing
``SynchronousDistributedTrainer`` API. Results land in ``$DK_OUT/proc<i>.json``
for the parent test to cross-check.

Run only via ``tests/test_multihost.py`` (it renders the env through
``job_deployment.Job`` — the same machinery a real pod launch uses).
"""

import json
import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    from distkeras_tpu import DataFrame, SynchronousDistributedTrainer
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.runtime.mesh import distributed_initialize

    # The Job/Punchcard launcher renders these for every host (job_deployment.py).
    coordinator = os.environ["JAX_COORDINATOR_ADDRESS"]
    num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    process_id = int(os.environ["JAX_PROCESS_ID"])

    distributed_initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes, (
        f"expected {num_processes} processes, got {jax.process_count()}"
    )

    # Identical deterministic data on every process: global device_put of a
    # host array to a sharded layout requires per-process agreement, which a
    # deterministic plan gives for free (the multi-host data-plane contract).
    rng = np.random.default_rng(0)
    # n=1024 -> 4 fold rounds on the 4-device mesh (batch 16, window 8,
    # 2 epochs): enough for the fault test to kill at round 2 with a complete
    # checkpoint behind it.
    n, d, c = 1024, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = centers[y] + rng.normal(scale=0.5, size=(n, d))

    # DK_SHARD_DIR switches the data plane to the on-disk sharded store (the
    # out-of-core path); DK_DISJOINT=1 additionally restricts THIS process to
    # the shard files its own workers consume — hard-linked into a private
    # dir, so any read outside the local partition fails with
    # FileNotFoundError instead of silently using global data.
    shard_dir = os.environ.get("DK_SHARD_DIR")
    if shard_dir:
        from distkeras_tpu.data.shards import (
            ShardStore, ShardedDataFrame, worker_partition)

        if os.environ.get("DK_DISJOINT") == "1":
            store = ShardStore.open(shard_dir)
            pid = jax.process_index()
            if os.environ.get("DK_TRAINER") == "parallel":
                # Step engines: locality unit = dp RANK on an N-D (data,
                # model) mesh; model-parallel peers of a rank share rows.
                # Use the engine's own mapping on the actual mesh so test
                # and trainer can never drift.
                from distkeras_tpu.parallel.runner import local_dp_ranks
                from distkeras_tpu.runtime.mesh import hybrid_mesh

                W = int(os.environ.get("DK_DP", "2"))
                local_workers = local_dp_ranks(
                    hybrid_mesh({"data": W, "model": -1}))
            else:
                # Data-parallel trainers: logical workers per chip, matching
                # parallel/engine.local_worker_ids — W <= chips puts worker w
                # on chip w (submesh); beyond the chip count multiplexes m
                # per chip as [c*m, (c+1)*m).
                W = int(os.environ.get("DK_NUM_WORKERS", jax.device_count()))
                if W <= jax.device_count():
                    local_workers = [
                        w for w, dev in enumerate(jax.devices()[:W])
                        if dev.process_index == pid]
                else:
                    m = W // jax.device_count()
                    local_workers = [c * m + j
                                     for c, dev in enumerate(jax.devices())
                                     if dev.process_index == pid
                                     for j in range(m)]
            parts = worker_partition(store.count(), W)
            needed = set()
            for w in local_workers:
                needed.update(store.shards_for_rows(*parts[w]))
            priv = os.path.join(os.environ["DK_OUT"],
                                f"shards_proc{process_id}")
            os.makedirs(priv, exist_ok=True)
            os.link(os.path.join(shard_dir, "manifest.json"),
                    os.path.join(priv, "manifest.json"))
            for s in sorted(needed):
                for col in store.columns:
                    fn = f"shard-{s:05d}.{col}.npy"
                    os.link(os.path.join(shard_dir, fn),
                            os.path.join(priv, fn))
            shard_dir = priv
        df = ShardedDataFrame(shard_dir)
    else:
        df = DataFrame({"features": x.astype(np.float32),
                        "label": y.astype(np.int32)})

    model = Model.build(MLP(hidden=(16,), num_outputs=c),
                        np.zeros((1, d), np.float32), seed=0)

    # Fault injection (the elastic-recovery test): DK_DIE_AT_ROUND makes the
    # process with id DK_DIE_PROC abort hard — no cleanup, like a preempted or
    # OOM-killed pod host — after that fold round completes.
    die_at = os.environ.get("DK_DIE_AT_ROUND")
    die_proc = int(os.environ.get("DK_DIE_PROC", "1"))

    def fault(r, loss):
        if die_at is not None and process_id == die_proc and r == int(die_at):
            os._exit(17)

    common = dict(
        loss="sparse_categorical_crossentropy",
        # Default: one worker per chip of the global mesh; DK_NUM_WORKERS
        # overrides (beyond the chip count = multiplexed workers).
        num_workers=int(os.environ.get("DK_NUM_WORKERS", jax.device_count())),
        batch_size=16, num_epoch=2, learning_rate=0.1,
        checkpoint_dir=os.environ.get("DK_CKPT_DIR") or None,
        checkpoint_every=int(os.environ.get("DK_CKPT_EVERY", "0")),
        resume=os.environ.get("DK_RESUME") == "1",
        on_round=fault,
    )
    # DK_TRAINER selects the path: "sync" (default) exercises the
    # per-step-pmean engine, "adag" the async center-variable fold,
    # "parallel" the ParallelTrainer step engines (dp x tp mesh) — all must
    # work across a multi-process DCN mesh.
    if os.environ.get("DK_TRAINER") == "adag":
        from distkeras_tpu import ADAG

        trainer = ADAG(model, communication_window=4, **common)
    elif os.environ.get("DK_TRAINER") == "adag_tp":
        # AsyncTPEngine across processes (ADVICE r4 medium): each of W=2
        # workers is a tp=2 submesh; with 2 devices per process the tp pair
        # lives inside one process and the worker fold crosses DCN. The [W]
        # loss history must be replicated (fully addressable) on every
        # process — the exact crash the engine's out_spec P() prevents.
        from distkeras_tpu import ADAG

        kw = dict(common)
        kw["num_workers"] = 2
        trainer = ADAG(model, communication_window=4,
                       parallel={"model": 2}, **kw)
    elif os.environ.get("DK_TRAINER") == "parallel":
        from distkeras_tpu import ParallelTrainer

        dp = int(os.environ.get("DK_DP", "2"))
        trainer = ParallelTrainer(
            model, parallel={"data": dp, "model": -1},
            worker_optimizer=common.get("worker_optimizer", "sgd"),
            loss=common["loss"], batch_size=common["batch_size"] * 2,
            num_epoch=common["num_epoch"],
            learning_rate=common["learning_rate"],
            steps_per_program=4,
            checkpoint_dir=common["checkpoint_dir"],
            checkpoint_every=common["checkpoint_every"],
            resume=common["resume"], on_round=common["on_round"])
    else:
        trainer = SynchronousDistributedTrainer(model, **common)
    trained = trainer.train(df)

    logits = np.asarray(trained.predict(np.asarray(x, np.float32)))
    acc = float((logits.argmax(-1) == y).mean())
    out = {
        "process": process_id,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "history": [float(v) for v in trainer.get_history()],
        "accuracy": acc,
    }
    path = os.path.join(os.environ["DK_OUT"], f"proc{process_id}.json")
    with open(path, "w") as f:
        json.dump(out, f)
    print(f"proc {process_id}: acc={acc:.3f} devices={jax.device_count()}")


if __name__ == "__main__":
    main()
