"""Telemetry subsystem tests (ISSUE 1): span nesting/timing, JSONL +
Prometheus round-trips, straggler flagging, staleness gauges vs the
disciplines' deterministic rotation, MetricsLogger context-manager behavior,
and the acceptance path — a report rendered from JSONLs produced by REAL
SynchronousDistributedTrainer and ADAG runs."""

import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.telemetry.core import Telemetry
from distkeras_tpu.telemetry.exporters import (
    parse_prometheus,
    prometheus_text,
    read_jsonl,
    write_jsonl,
)
from distkeras_tpu.telemetry.report import build_report, render_report
from distkeras_tpu.telemetry.training import (
    DisciplineMonitor,
    dynsgd_scales,
    flag_stragglers,
    staleness_schedule,
)


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset()
    yield
    telemetry.reset()


# -- core primitives --------------------------------------------------------
def test_span_records_duration():
    t = Telemetry()
    with t.span("work"):
        time.sleep(0.01)
    h = t.histogram("work")
    assert h.count == 1
    assert 0.005 < h.total < 1.0


def test_span_nesting_paths_and_containment():
    t = Telemetry()
    with t.span("outer"):
        with t.span("inner"):
            time.sleep(0.005)
        with t.span("inner"):
            pass
    snap = t.snapshot()["spans"]
    assert set(snap) == {"outer", "outer/inner"}
    assert snap["outer/inner"]["count"] == 2
    # Parent wall time contains the children's.
    assert snap["outer"]["total"] >= snap["outer/inner"]["total"]


def test_span_nesting_is_per_thread():
    import threading

    t = Telemetry()
    done = threading.Event()

    def worker():
        with t.span("bg"):
            done.wait(1.0)

    th = threading.Thread(target=worker)
    with t.span("fg"):
        th.start()
        time.sleep(0.01)
    done.set()
    th.join()
    # The background span must NOT nest under the foreground one.
    assert "bg" in t.snapshot()["spans"]
    assert "fg/bg" not in t.snapshot()["spans"]


def test_counter_gauge_histogram_aggregates():
    t = Telemetry()
    t.counter("c").add(2)
    t.counter("c").add(3)
    for v in (1.0, 2.0, 3.0):
        t.gauge("g").set(v)
    for v in (0.001, 0.01, 0.1):
        t.histogram("h").observe(v)
    snap = t.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == {
        "value": 3.0, "count": 3, "mean": 2.0, "min": 1.0, "max": 3.0}
    assert snap["spans"]["h"]["count"] == 3
    assert abs(snap["spans"]["h"]["total"] - 0.111) < 1e-9


def test_disabled_registry_is_noop():
    t = Telemetry(enabled=False)
    with t.span("x"):
        pass
    t.counter("c").add(1)
    t.gauge("g").set(1)
    t.histogram("h").observe(1)
    t.event("e", {"a": 1})
    snap = t.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "spans": {}}
    assert t.events() == []


def test_span_overhead_is_small():
    """The instrumentation-cost bound underlying the <=2% overhead budget:
    a span costs a few µs; hot paths (fold rounds, native gathers) are
    hundreds of µs to ms. Generous bound so CI boxes can't flake."""
    t = Telemetry()
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        with t.span("hot"):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 100e-6, f"span cost {per_span * 1e6:.1f}us"


# -- exporters --------------------------------------------------------------
def test_jsonl_round_trip(tmp_path):
    t = Telemetry()
    t.counter("rounds").add(4)
    t.event("custom", {"round": -1, "tag": "x"})
    with t.span("phase"):
        pass
    path = str(tmp_path / "t.jsonl")
    write_jsonl(t, path, extra={"run": "r1"})
    recs = read_jsonl(path)
    summary = [r for r in recs if r.get("kind") == "telemetry_summary"]
    assert len(summary) == 1
    assert summary[0]["counters"]["rounds"] == 4
    assert summary[0]["spans"]["phase"]["count"] == 1
    assert summary[0]["run"] == "r1"
    assert any(r.get("kind") == "custom" for r in recs)
    # Append-only: a second dump adds a second summary, clobbers nothing.
    write_jsonl(t, path)
    assert len([r for r in read_jsonl(path)
                if r.get("kind") == "telemetry_summary"]) == 2


def test_prometheus_round_trip():
    t = Telemetry()
    t.counter("native.gather_calls").add(7)
    t.gauge("feeder.queue_depth").set(2)
    for v in (0.001, 0.25, 0.25):
        t.histogram("dispatch[blocked]").observe(v)
    text = prometheus_text(t)
    parsed = parse_prometheus(text)
    assert parsed["dktpu_counter_total"][
        (("name", "native_gather_calls"),)] == 7
    assert parsed["dktpu_gauge"][(("name", "feeder_queue_depth"),)] == 2
    label = ("span", "dispatch_blocked_")
    assert parsed["dktpu_span_seconds_count"][(label,)] == 3
    assert abs(parsed["dktpu_span_seconds_sum"][(label,)] - 0.501) < 1e-9
    # Cumulative buckets: the +Inf bucket equals the count.
    inf = parsed["dktpu_span_seconds_bucket"][(label, ("le", "+Inf"))]
    assert inf == 3
    # A mid bucket holds the 0.001 observation but not the 0.25 pair.
    le_01 = [v for k, v in parsed["dktpu_span_seconds_bucket"].items()
             if k[0] == label and k[1][1] not in ("+Inf",)
             and float(k[1][1]) >= 0.001 and float(k[1][1]) < 0.25]
    assert le_01 and all(v >= 1 for v in le_01)


# -- straggler heuristic ----------------------------------------------------
def test_flag_stragglers_synthetic():
    times = [1.0, 1.1, 0.9, 1.0, 5.0, 1.05, 2.3]
    assert flag_stragglers(times, k=2.0) == [4, 6]
    assert flag_stragglers(times, k=4.0) == [4]
    assert flag_stragglers([1.0, 9.0]) == []  # too few samples to anchor
    assert flag_stragglers([0.0, 0.0, 0.0]) == []  # degenerate median


# -- staleness vs disciplines.py -------------------------------------------
def test_staleness_schedule_matches_dynsgd_commit_scale():
    """The host-side schedule must reproduce DynSGDFold.commit's scale
    1/(((worker_id + fold_state) % W) + 1) exactly, for every (round, worker).
    """
    from distkeras_tpu.parallel.disciplines import DynSGDFold

    W = 5
    disc = DynSGDFold()
    center = {"w": jnp.zeros(3)}
    local = {"w": jnp.ones(3)}
    for r in range(2 * W):
        stale = staleness_schedule(disc, r, W)
        scales = dynsgd_scales(stale)
        for i in range(W):
            commit, _ = disc.commit(
                center, local, jnp.asarray(r, jnp.int32),
                worker_id=jnp.asarray(i, jnp.int32), window=4, num_workers=W)
            # delta == 1, so the commit value IS the fold scale.
            np.testing.assert_allclose(
                np.asarray(commit["w"][0]), scales[i], rtol=1e-6)
            assert stale[i] == (i + r) % W


def test_staleness_schedule_non_communicating_is_none():
    from distkeras_tpu.parallel.disciplines import EnsembleFold

    assert staleness_schedule(EnsembleFold(), 0, 4) is None
    assert staleness_schedule(None, 0, 4) is None


def test_discipline_monitor_fields_and_gauges():
    from distkeras_tpu.parallel.disciplines import DynSGDFold

    t = Telemetry()
    mon = DisciplineMonitor(DynSGDFold(), num_workers=4, telemetry=t)
    loss = np.array([1.0, 2.0, 3.0, 4.0])
    fields = mon.round_fields(1, loss, round_seconds=0.1)
    assert fields["staleness"] == [1, 2, 3, 0]
    np.testing.assert_allclose(
        fields["dynsgd_scale"], [1 / 2, 1 / 3, 1 / 4, 1 / 1], atol=1e-6)
    np.testing.assert_allclose(
        fields["loss_divergence"], [-1.5, -0.5, 0.5, 1.5])
    assert t.gauge("discipline.staleness_mean").value == 1.5
    assert t.gauge("discipline.loss_divergence_max").value == 1.5


def test_discipline_monitor_flags_live_stragglers():
    t = Telemetry()
    mon = DisciplineMonitor(None, num_workers=1, telemetry=t)
    loss = np.float32(1.0)
    for r, dt in enumerate([0.1, 0.1, 0.1, 0.1]):
        assert "straggler" not in mon.round_fields(r, loss, round_seconds=dt)
    assert mon.round_fields(4, loss, round_seconds=0.5)["straggler"] is True
    assert t.counter("discipline.straggler_rounds").value == 1


def test_discipline_monitor_ignores_burst_tails():
    """Blocked/auto execution delivers burst-tail callbacks; callers pass
    round_seconds=None for them (MetricsLogger derives the signal from the
    engine's state contract) — tails must not poison the straggler median
    or be flagged, while genuinely slow blocks still flag."""
    t = Telemetry()
    mon = DisciplineMonitor(None, num_workers=1, telemetry=t)
    loss = np.float32(1.0)
    # 4 blocks of R=4: one real timing boundary + 3 burst tails per block.
    for block in range(4):
        fields = mon.round_fields(block * 4, loss, round_seconds=0.2)
        assert "straggler" not in fields, f"block {block} flagged"
        for j in (1, 2, 3):
            fields = mon.round_fields(block * 4 + j, loss,
                                      round_seconds=None)
            assert "straggler" not in fields
    assert t.counter("discipline.straggler_rounds").value == 0
    # A genuinely slow block still flags against the block-time median.
    assert mon.round_fields(16, loss, round_seconds=1.0)["straggler"] is True


# -- MetricsLogger ----------------------------------------------------------
def test_metrics_logger_context_manager_and_idempotent_close(tmp_path):
    from distkeras_tpu.metrics import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path, samples_per_round=8) as logger:
        logger(0, np.float32(1.0))
        logger(1, np.float32(0.5))
        assert logger._file is not None
    assert logger._file is None  # __exit__ closed it
    logger.close()  # idempotent: second close is a no-op
    logger.close()
    recs = read_jsonl(path)
    rounds = [r for r in recs if "round" in r and "kind" not in r]
    assert [r["round"] for r in rounds] == [0, 1]
    # close() appended the registry summary — one file serves the report.
    assert any(r.get("kind") == "telemetry_summary" for r in recs)


def test_metrics_logger_feeds_telemetry(tmp_path):
    from distkeras_tpu.metrics import MetricsLogger

    t = Telemetry()
    with MetricsLogger(str(tmp_path / "m.jsonl"), telemetry=t) as logger:
        logger(0, np.float32(2.0))
    snap = t.snapshot()
    assert snap["counters"]["rounds"] == 1
    assert snap["gauges"]["loss"]["value"] == 2.0
    assert snap["spans"]["round_seconds"]["count"] == 1


def test_metrics_logger_burst_attribution_blocked_contract(tmp_path):
    """The wired path: run_blocked fires callback bursts where the FIRST
    call of a block absorbs the whole block's wall time in dt but only the
    LAST call carries a state. The logger must mark boundaries as
    first-after-a-state-bearing-call — NOT the state-bearing calls
    themselves — or the straggler median anchors on JSONL-write jitter and
    a genuinely slow block never flags."""
    from distkeras_tpu.metrics import MetricsLogger
    from distkeras_tpu.telemetry.training import DisciplineMonitor

    t = Telemetry()
    mon = DisciplineMonitor(None, num_workers=1, telemetry=t)
    with MetricsLogger(str(tmp_path / "b.jsonl"), telemetry=t,
                       monitor=mon) as logger:
        state = object()
        R = 4
        for block in range(5):
            # The slow block's wall lands on j=0's dt. 0.8s: far above any
            # load-induced pause a busy CI box can inject into the fast
            # blocks' boundary dts (a 0.25s gap flaked under parallel load).
            if block == 4:
                time.sleep(0.8)
            for j in range(R):
                logger(block * R + j, np.float32(1.0),
                       state if j == R - 1 else None)
    recs = logger.records
    # Block-first records are boundaries; everything else is a tail —
    # including the state-bearing block-final records. The marker is
    # explicit on EVERY record (False on boundaries), so readers never fall
    # back to the dt threshold for new-format files.
    for i, r in enumerate(recs):
        assert r.get("burst_tail") is (i % R != 0), f"record {i} mismarked"
    # The slow block flags on its FIRST record (where its wall time lives).
    assert recs[16].get("straggler") is True
    assert not any(r.get("straggler") for r in recs[:16])


# -- report CLI -------------------------------------------------------------
def _write_rounds(path, rows):
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_report_straggler_table_and_segments(tmp_path):
    path = str(tmp_path / "r.jsonl")
    rows = [
        {"round": r, "loss": 1.0, "round_seconds": 0.1,
         "samples_per_sec": 100.0}
        for r in range(6)
    ]
    rows[3]["round_seconds"] = 0.9  # the planted straggler
    _write_rounds(path, rows)
    rep = build_report(path)
    assert rep["rounds"] == 6
    assert [s["round"] for s in rep["stragglers"]] == [3]
    assert rep["stragglers"][0]["x_median"] == 9.0
    text = render_report(rep)
    assert "Stragglers" in text and "Throughput segments" in text


def test_report_stragglers_exclude_burst_tails(tmp_path):
    """Offline twin of the live-monitor rule: blocked-run JSONLs (µs
    burst-tail rounds) must not flag every block-final round."""
    path = str(tmp_path / "blocked.jsonl")
    rows = []
    for block in range(5):
        rows.append({"round": block * 4, "loss": 1.0, "round_seconds": 0.2})
        rows += [{"round": block * 4 + j, "loss": 1.0, "round_seconds": 2e-6}
                 for j in (1, 2, 3)]
    rows.append({"round": 20, "loss": 1.0, "round_seconds": 0.9})  # real one
    _write_rounds(path, rows)
    rep = build_report(path)
    assert [s["round"] for s in rep["stragglers"]] == [20]


def test_telemetry_mark_delta_windows_runs():
    """Sequential runs share the process registry; a mark window must
    report only the second run's activity (counters/spans subtract, events
    slice)."""
    t = Telemetry()
    t.counter("rounds").add(5)
    with t.span("dispatch"):
        pass
    t.event("bench_config", {"run": 1})
    m = t.mark()
    t.counter("rounds").add(3)
    with t.span("dispatch"):
        pass
    with t.span("dispatch"):
        pass
    t.event("bench_config", {"run": 2})
    summary, events = t.delta(m)
    assert summary["counters"] == {"rounds": 3.0}
    assert summary["spans"]["dispatch"]["count"] == 2
    assert [e["run"] for e in events] == [2]
    # An untouched metric does not appear in the window at all.
    assert "loss" not in summary["gauges"]


def test_metrics_logger_summary_is_per_run(tmp_path):
    """Two back-to-back MetricsLogger runs on the shared registry: run 2's
    JSONL summary must not re-attribute run 1's rounds."""
    from distkeras_tpu.metrics import MetricsLogger

    t = Telemetry()
    p1, p2 = str(tmp_path / "r1.jsonl"), str(tmp_path / "r2.jsonl")
    with MetricsLogger(p1, telemetry=t) as l1:
        for r in range(4):
            l1(r, np.float32(1.0))
    with MetricsLogger(p2, telemetry=t) as l2:
        l2(0, np.float32(1.0))
    s2 = [r for r in read_jsonl(p2) if r.get("kind") == "telemetry_summary"]
    assert s2[0]["counters"]["rounds"] == 1  # not 5
    assert s2[0]["spans"]["round_seconds"]["count"] == 1


def test_report_burst_grouping(tmp_path):
    # Blocked execution: one real timing boundary + burst tail of ~0s rounds.
    path = str(tmp_path / "b.jsonl")
    rows = [{"round": 0, "loss": 1.0, "round_seconds": 0.4,
             "samples_per_sec": 10.0}]
    rows += [{"round": r, "loss": 1.0, "round_seconds": 1e-6,
              "samples_per_sec": 4e6} for r in (1, 2, 3)]
    _write_rounds(path, rows)
    rep = build_report(path)
    assert len(rep["segments"]) == 1
    assert rep["segments"][0]["rounds"] == 4


def test_report_cli_main(tmp_path, capsys):
    from distkeras_tpu.telemetry.report import main

    path = str(tmp_path / "cli.jsonl")
    _write_rounds(path, [{"round": 0, "loss": 2.0, "round_seconds": 0.1}])
    assert main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "Telemetry report" in out
    assert main(["report", path, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["rounds"] == 1


# -- acceptance: real trainer runs -> report --------------------------------
def _toy_df(n=256, d=12, classes=3, seed=0):
    from distkeras_tpu.data.dataframe import DataFrame

    rng = np.random.default_rng(seed)
    return DataFrame({
        "features": rng.random((n, d), dtype=np.float32),
        "label": rng.integers(0, classes, n).astype(np.int32),
    })


def _toy_model(d=12, classes=3):
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.models.base import Model

    return Model.build(MLP(hidden=(16,), num_outputs=classes),
                       jnp.zeros((1, d), jnp.float32))


def test_report_from_real_sync_and_adag_runs(tmp_path):
    """Acceptance: ``telemetry report`` renders phase breakdown +
    staleness/straggler sections from JSONLs written by a real
    SynchronousDistributedTrainer run and a real ADAG run."""
    from distkeras_tpu.trainers import ADAG, SynchronousDistributedTrainer

    df = _toy_df()
    sync_path = str(tmp_path / "sync.jsonl")
    t1 = SynchronousDistributedTrainer(
        _toy_model(), loss="sparse_categorical_crossentropy",
        num_workers=4, batch_size=4, num_epoch=1, metrics_path=sync_path)
    t1.train(df)

    telemetry.reset()  # per-run aggregates for the ADAG report
    adag_path = str(tmp_path / "adag.jsonl")
    t2 = ADAG(_toy_model(), loss="sparse_categorical_crossentropy",
              num_workers=4, batch_size=4, communication_window=2,
              num_epoch=1, metrics_path=adag_path)
    t2.train(df)

    sync_rep = build_report(sync_path)
    assert sync_rep["rounds"] > 0
    spans = {p["span"] for p in sync_rep["phases"]}
    assert any("dispatch" in s for s in spans)
    assert "engine_run" in spans

    adag_rep = build_report(adag_path)
    assert adag_rep["rounds"] > 0
    # Discipline-aware sections: ADAG communicates -> staleness present.
    assert adag_rep["staleness"] is not None
    assert adag_rep["staleness"]["num_workers"] == 4
    assert adag_rep["staleness"]["per_worker_mean"] == [1.5, 1.5, 1.5, 1.5]
    assert "loss_divergence_rms" in adag_rep["staleness"]
    text = render_report(adag_rep)
    for section in ("Phase breakdown", "Throughput segments", "Staleness",
                    "Stragglers"):
        assert section in text
    # Input-stall accounting reached the registry via the run loop.
    assert "input_stall_seconds" in adag_rep["counters"]


def test_trainer_closes_metrics_file_on_failure(tmp_path):
    """The satellite leak fix: a run that raises mid-training must still
    close the metrics JSONL (close runs in the trainer's finally)."""
    from distkeras_tpu.trainers import SynchronousDistributedTrainer

    path = str(tmp_path / "fail.jsonl")
    boom = RuntimeError("boom")

    def exploding_on_round(r, loss):
        raise boom

    t = SynchronousDistributedTrainer(
        _toy_model(), loss="sparse_categorical_crossentropy",
        num_workers=4, batch_size=4, num_epoch=1, metrics_path=path,
        on_round=exploding_on_round)
    with pytest.raises(RuntimeError, match="boom"):
        t.train(_toy_df())
    # The logger was closed despite the failure: its summary record (written
    # by close()) is present in the file.
    assert any(r.get("kind") == "telemetry_summary"
               for r in read_jsonl(path))


def test_pipeline_engine_on_step_observation():
    """The pipeline engine's own observation point (satellite: it previously
    had none): on_step fires per step and the dispatch span records."""
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.transformer import TransformerLM
    from distkeras_tpu.parallel.pipeline_engine import PipelineEngine
    from distkeras_tpu.runtime.mesh import hybrid_mesh

    model = Model.build(
        TransformerLM(vocab_size=32, num_layers=2, d_model=16, num_heads=2,
                      d_ff=32, max_seq_len=8),
        jnp.zeros((1, 8), jnp.int32))
    mesh = hybrid_mesh({"data": 2, "pipe": 2})
    seen = []
    eng = PipelineEngine(model, "sgd", "sparse_categorical_crossentropy",
                         mesh, num_microbatches=2,
                         on_step=lambda i, loss: seen.append(i))
    state = eng.init_state()
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 32, (4, 8)), jnp.int32)
    tgts = jnp.asarray(np.roll(np.asarray(toks), -1, 1), jnp.int32)
    for _ in range(2):
        state, loss = eng.step(state, toks, tgts)
    assert seen == [0, 1]
    snap = telemetry.get().snapshot()["spans"]
    assert snap["pipeline.dispatch"]["count"] == 2
