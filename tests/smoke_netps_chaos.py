"""CI netps-chaos smoke (not a pytest module — run directly).

A loopback training run over the **networked parameter server** with
network faults injected by the chaos proxy: CI invokes it with
``DKTPU_NET_FAULTS`` scheduling a delay, a drop, one partition, and one
worker-kill-style eviction (the seeded worker goes silent past its lease
and must rejoin mid-run), and asserts the run converges and exits 0 —
the ROADMAP's "heavy traffic on a bad network" story, exercised end to
end on every PR.

    DKTPU_NET_FAULTS="delay@6:0.2;drop@11;partition@16:0.8;evict@4:2.2;seed=3" \
        python tests/smoke_netps_chaos.py

With ``DKTPU_NET_TRANSPORT=shm`` the data plane upgrades to the same-host
ring after the (proxied) join, so wire faults only see the TCP control
frames — schedule the ring's own faults instead (``shm_delay``/
``shm_corrupt``). With ``DKTPU_NET_HIER=1`` eviction/rejoin happen at the
in-process per-host aggregator, so those assertions read the telemetry
counters rather than the root server's attributes::

    DKTPU_NET_TRANSPORT=shm DKTPU_NET_HIER=1 DKTPU_PS_LEASE=1.0 \\
    DKTPU_NET_FAULTS="shm_delay@3:0.2;shm_corrupt@6;evict@4:2.2;seed=3" \\
        python tests/smoke_netps_chaos.py
"""

import os
import sys

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# Tight-but-survivable budgets: a dropped frame must not stall CI for the
# production 30 s deadline.
os.environ.setdefault("DKTPU_NET_TIMEOUT", "1.0")
os.environ.setdefault("DKTPU_NET_RETRIES", "8")
os.environ.setdefault("DKTPU_NET_BACKOFF", "0.02")
os.environ.setdefault(
    "DKTPU_NET_FAULTS",
    "delay@6:0.2;drop@11;partition@16:0.8;evict@4:2.2;seed=3")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distkeras_tpu import ADAG, DataFrame, telemetry  # noqa: E402
from distkeras_tpu.models import Model  # noqa: E402
from distkeras_tpu.models.mlp import MLP  # noqa: E402
from distkeras_tpu.netps import ChaosProxy, PSServer  # noqa: E402


def main() -> int:
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4.0, size=(3, 4))
    y = rng.integers(0, 3, size=1024)
    x = centers[y] + rng.normal(scale=0.5, size=(1024, 4))
    df = DataFrame({"features": x.astype(np.float32),
                    "label": y.astype(np.int32)})
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32), seed=0)
    server = PSServer(discipline="adag", lease_s=1.0).start()
    proxy = ChaosProxy(server.endpoint).start()  # ambient DKTPU_NET_FAULTS
    try:
        trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                       num_workers=4, batch_size=16, num_epoch=3,
                       learning_rate=0.1, communication_window=4,
                       remote=proxy.endpoint)
        trained = trainer.train(df, shuffle=True)
    finally:
        proxy.close()
        server.close()
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    reg = telemetry.get()
    retries = reg.counter("netps.retries").value
    injected = reg.counter("resilience.faults_injected").value
    from distkeras_tpu.runtime import config

    if config.env_bool("DKTPU_NET_HIER"):
        # Workers live behind the in-process per-host aggregator: eviction
        # and rejoin happen THERE (its monitor/join feed the same counters
        # the root's would), while the root sees one aggregator peer.
        evictions = reg.counter("netps.evictions").value
        rejoins = reg.counter("netps.rejoins").value
    else:
        evictions, rejoins = server.evictions, server.rejoins
    print(f"netps chaos run: acc={acc:.4f} commits={len(server.commit_log)} "
          f"evictions={evictions:.0f} rejoins={rejoins:.0f} "
          f"client_retries={retries:.0f} faults_injected={injected:.0f}")
    assert acc > 0.85, f"accuracy collapsed under network chaos: {acc}"
    assert evictions >= 1, "the worker-kill eviction never happened"
    assert rejoins >= 1, "the evicted worker never re-joined"
    assert retries >= 1, "no RPC ever retried — chaos did not bite"
    seen = set()
    for wid, seq, _st in server.commit_log:
        assert (wid, seq) not in seen, f"commit ({wid}, {seq}) folded twice"
        seen.add((wid, seq))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
