"""CI netps-chaos smoke (not a pytest module — run directly).

A loopback training run over the **networked parameter server** with
network faults injected by the chaos proxy: CI invokes it with
``DKTPU_NET_FAULTS`` scheduling a delay, a drop, one partition, and one
worker-kill-style eviction (the seeded worker goes silent past its lease
and must rejoin mid-run), and asserts the run converges and exits 0 —
the ROADMAP's "heavy traffic on a bad network" story, exercised end to
end on every PR.

    DKTPU_NET_FAULTS="delay@6:0.2;drop@11;partition@16:0.8;evict@4:2.2;seed=3" \
        python tests/smoke_netps_chaos.py

With ``DKTPU_NET_TRANSPORT=shm`` the data plane upgrades to the same-host
ring after the (proxied) join, so wire faults only see the TCP control
frames — schedule the ring's own faults instead (``shm_delay``/
``shm_corrupt``). With ``DKTPU_NET_HIER=1`` eviction/rejoin happen at the
in-process per-host aggregator, so those assertions read the telemetry
counters rather than the root server's attributes::

    DKTPU_NET_TRANSPORT=shm DKTPU_NET_HIER=1 DKTPU_PS_LEASE=1.0 \\
    DKTPU_NET_FAULTS="shm_delay@3:0.2;shm_corrupt@6;evict@4:2.2;seed=3" \\
        python tests/smoke_netps_chaos.py

**Kill-the-primary mode** (``DKTPU_PS_STATE_DIR`` set): the PS runs as a
real subprocess (``python -m distkeras_tpu.netps --state-dir ...``) whose
OWN fault plan SIGKILLs it mid-run (``ps_crash@R``), while this process's
plan keeps driving the proxy (partition etc.). Recovery is either the
cold restart (a babysitter thread relaunches the dead primary on the same
state dir + port — ``Job.supervise``'s role, inlined) or, with
``DKTPU_PS_STANDBY=1``, a warm standby subprocess that tails the journal,
promotes on lease lapse, and fences the epoch; the trainer's clients walk
the ``proxy,standby`` endpoint list. Exactly-once is asserted on the
on-disk journals (the only view a subprocess leaves behind), and journal
epochs must be nondecreasing — the zero-stale-epoch-folds evidence::

    DKTPU_PS_STATE_DIR=/tmp/ps-state \\
    DKTPU_NET_FAULTS="partition@16:0.8;seed=3" \\
        python tests/smoke_netps_chaos.py          # cold-restart path
    DKTPU_PS_STANDBY=1 DKTPU_PS_STATE_DIR=/tmp/ps-state ...  # failover path

**Kill-one-shard mode** (``NETPS_SMOKE_SHARDS=N`` + state dir): the
center is partitioned across N shard subprocesses (``--shard k/N``),
each with its own journal lineage AND its own warm standby; shard 1's
primary carries ``shard_crash@1:R`` in its fault plan and SIGKILLs
itself mid-run, its standby promotes and fences the epoch, and the
trainer's sharded clients walk only that shard's endpoint group — the
other shards never notice. Exactly-once is asserted on EVERY shard's
journal, epochs must be nondecreasing per lineage, and the victim
shard's standby must have promoted past epoch 0::

    NETPS_SMOKE_SHARDS=2 DKTPU_PS_STATE_DIR=/tmp/ps-state \\
        python tests/smoke_netps_chaos.py          # sharded failover path

**Mesh-demotion mode** (``NETPS_SMOKE_MESH=1``): the PS runs IN THIS
process (the mesh dialect is a same-runtime contract — a subprocess
cannot share the jax device mesh), workers negotiate the device-resident
center, and ``mesh_down@R`` severs the dispatch mid-run. The struck
worker demotes to its negotiated shm ring and retransmits the same seq;
exactly-once and zero lost windows are asserted on the on-disk journal::

    NETPS_SMOKE_MESH=1 DKTPU_NET_FAULTS="mesh_down@6;seed=3" \\
        python tests/smoke_netps_chaos.py          # mesh demotion path

**Region-partition tree mode** (``NETPS_SMOKE_TREE=1`` + state dir): a
2-region, 3-level aggregation tree (workers -> region ``TreeNode``
subprocesses -> root subprocess). Region 0's aggregator SIGKILLs itself
mid-run (``ps_crash`` in its own plan); its warm region-local
``TreeStandby`` promotes, fences the epoch, and the trainer's workers
re-parent via their ordinary endpoint walk. Region 1's UPLINK is
black-holed (``link_down@<link_key>``) past its deliberately tiny
ride-through buffer, so degradation must be counted and typed (the
``dropped_*`` ledger columns; ``silent_loss`` stays 0). Exactly-once is
asserted on EVERY journal (root, both region lineages), epochs must be
nondecreasing, and the run must still converge. A second, in-process
traced loopback tree then replays the partition and gates on simulator
parity: ``sim.calibrate.tree_parity`` re-fits the PR 16
``region_partition`` scenario to the live run's shape and the root
ingress cut + partition staleness spike must agree within
``DKTPU_SIM_BAND_PCT`` — the ``tree_parity`` block written into
``BENCH_SUMMARY.json``::

    NETPS_SMOKE_TREE=1 DKTPU_PS_STATE_DIR=/tmp/ps-state \\
        python tests/smoke_netps_chaos.py          # region-partition path

All seeds are pinned (data rng, trainer seed, fault-plan seeds, the
``ps_crash``/``shard_crash`` commit indices), so reruns schedule the
same chaos.
"""

import os
import sys

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# Tight-but-survivable budgets: a dropped frame must not stall CI for the
# production 30 s deadline.
os.environ.setdefault("DKTPU_NET_TIMEOUT", "1.0")
os.environ.setdefault("DKTPU_NET_RETRIES", "8")
os.environ.setdefault("DKTPU_NET_BACKOFF", "0.02")
os.environ.setdefault(
    "DKTPU_NET_FAULTS",
    "delay@6:0.2;drop@11;partition@16:0.8;evict@4:2.2;seed=3")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distkeras_tpu import ADAG, DataFrame, telemetry  # noqa: E402
from distkeras_tpu.models import Model  # noqa: E402
from distkeras_tpu.models.mlp import MLP  # noqa: E402
from distkeras_tpu.netps import ChaosProxy, PSServer  # noqa: E402
from distkeras_tpu.netps import state as netps_state  # noqa: E402

#: the primary subprocess's own fault plan: SIGKILL just before folding
#: commit 20 (mid-run: the full run folds ~48). Pinned, not random.
PS_FAULTS = os.environ.get("NETPS_SMOKE_PS_FAULTS", "ps_crash@20;seed=3")


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_ps(port, state_dir, extra_env, *extra_args):
    import subprocess

    # The smoke process's own chaos plan and PS-role env must not leak
    # into the server subprocess: it gets explicit flags + its OWN plan.
    drop = {"DKTPU_NET_FAULTS", "DKTPU_PS_STANDBY", "DKTPU_PS_STATE_DIR",
            "DKTPU_FAULTS_STATE"}
    env = {k: v for k, v in os.environ.items() if k not in drop}
    env.update({"JAX_PLATFORMS": "cpu", **extra_env})
    proc = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.netps", "--host", "127.0.0.1",
         "--port", str(port), "--discipline", "adag", "--lease", "1.0",
         "--state-dir", state_dir, "--snapshot-every", "10", *extra_args],
        env=env)
    return proc


def _assert_journal_invariants(state_dir, label):
    """The subprocess-visible exactly-once + zero-stale-epoch evidence:
    every (worker, seq) journaled at most once, fold indices strictly
    sequential per journal chain, epochs nondecreasing."""
    records = netps_state.read_journal(state_dir)
    seen = set()
    last_epoch = -1
    for r in records:
        key = (int(r["wid"]), int(r["seq"]))
        assert key not in seen, f"{label}: commit {key} folded twice"
        seen.add(key)
        assert int(r["e"]) >= last_epoch, (
            f"{label}: journal epoch went backwards at {key}")
        last_epoch = int(r["e"])
    return records, last_epoch


def _assert_trace_evidence(state_dir, standby_mode) -> None:
    """Trace-mode evidence (``DKTPU_TRACE=1`` on the failover drill): the
    collector-merged streams must show every accepted commit as one
    complete cross-process trace with no orphaned server-side spans, and
    the SIGKILLed primary's flight-recorder dump must agree with the
    on-disk journal it left behind. See docs/OBSERVABILITY.md
    ("Distributed tracing")."""
    import glob
    import json

    from distkeras_tpu.telemetry import tracing
    from distkeras_tpu.telemetry.tracing import analysis as trace_analysis

    trace_dir = tracing.trace_dir()
    assert trace_dir, "DKTPU_TRACE=1 but no DKTPU_TRACE_DIR to collect from"
    # The smoke process's own registry (chaos-proxy events + anything the
    # tap saw) joins the subprocess streams on disk before the merge.
    telemetry.write_jsonl(
        telemetry.get(),
        os.path.join(trace_dir, f"telemetry-trainer-{os.getpid()}.jsonl"))
    records = tracing.TelemetryCollector.from_dir(trace_dir).records()
    rep = tracing.trace_report(records)
    assert not rep["orphans"], (
        f"{len(rep['orphans'])} server-side trace(s) never joined a client "
        f"root: {rep['orphans'][:5]}")

    # Every journaled (= accepted) commit must map to a traced commit
    # carrying every always-on critical-path segment plus fsync (a state
    # dir is configured). ``replicate`` is deliberately NOT demanded:
    # commits folded by the promoted standby after the crash have nobody
    # left to replicate to, so a promotion legitimately ends that segment.
    base = set(trace_analysis.BASE_REQUIRED) | {"fsync"}
    traced = {}
    for _tid, t in trace_analysis.assemble_traces(records).items():
        root = t["root"]
        if root is not None and root.get("name") == "commit":
            traced[(int(root["wid"]), int(root["seq"]))] = (
                trace_analysis._segment_durs(t["spans"]))
    accepted = set()
    for d in [state_dir] + ([state_dir + ".standby"] if standby_mode else []):
        for r in netps_state.read_journal(d):
            accepted.add((int(r["wid"]), int(r["seq"])))
    untraced = sorted(k for k in accepted if k not in traced)
    assert not untraced, f"accepted commits left no trace: {untraced[:5]}"
    incomplete = sorted(k for k in accepted if not base <= set(traced[k]))
    assert not incomplete, (
        "accepted commits with gaps in the critical path: "
        f"{[(k, sorted(traced[k])) for k in incomplete[:5]]}")

    # The ps_crash dump: FaultPlan._fire wrote the flight ring BEFORE the
    # SIGKILL, so the primary's final seconds are on disk. Its fold tail
    # must agree with the journal the dead process left behind.
    dumps = sorted(glob.glob(os.path.join(trace_dir, "flight-ps-*.jsonl")))
    assert dumps, "the SIGKILLed primary left no flight-recorder dump"
    folds = []
    with open(dumps[-1], encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a crash-truncated tail line is legal
            if (rec.get("kind") == tracing.SPAN_KIND
                    and rec.get("name") == "commit.fold"):
                folds.append((int(rec["wid"]), int(rec["seq"])))
    assert folds, "the flight dump recorded no folds before the crash"
    # The journal rotates at every snapshot and prunes old generations,
    # so the on-disk journal is the TAIL of fold history — and the ring
    # saw more history than survived on disk. The journal writer is also
    # an ordered background thread with a bounded queue, so at the
    # SIGKILL the ring may lead the journal by up to that many folded-
    # but-unwritten commits (plus the fold in flight) — but it must
    # never DISAGREE: the journal must be a suffix of the ring's fold
    # sequence once that bounded lead is stripped.
    jkeys = [(int(r["wid"]), int(r["seq"]))
             for r in netps_state.read_journal(state_dir)]
    assert jkeys, "the crashed primary left no journal to corroborate"
    jset, lead = set(jkeys), 0
    while (folds and folds[-1] not in jset
           and lead <= netps_state._WRITE_QUEUE):
        folds.pop()
        lead += 1
    k = min(len(jkeys), len(folds))
    assert k >= 1 and folds[-k:] == jkeys[-k:], (
        f"flight-dump fold tail {folds[-k:]} disagrees with the on-disk "
        f"journal tail {jkeys[-k:]} (crash-lead stripped: {lead})")
    print(f"netps trace evidence: traces={rep['traces']} "
          f"commits={rep['commits']} accepted={len(accepted)} orphans=0 "
          f"flight_folds={len(folds)} processes={len(rep['processes'])}")


def _run_mesh(df, model) -> int:
    """Mesh-demotion mode (``NETPS_SMOKE_MESH=1``): the PS and the
    workers share THIS process's jax runtime, the data plane negotiates
    the mesh dialect (device-resident center, zero wire bytes), and
    ``mesh_down@R`` kills the device dispatch mid-run — the struck
    worker must demote to its negotiated shm ring (ONE strike, no
    rejoin) and retransmit the SAME seq, with exactly-once and zero
    lost windows proven on the on-disk journal."""
    import tempfile

    faults_spec = os.environ.get("DKTPU_NET_FAULTS", "")
    assert "mesh_down" in faults_spec, (
        "mesh mode expects a mesh_down@R entry in DKTPU_NET_FAULTS")
    # The workers request the dialect; the server resolves it live.
    os.environ["DKTPU_NET_TRANSPORT"] = "mesh"
    state_dir = (os.environ.get("DKTPU_PS_STATE_DIR")
                 or tempfile.mkdtemp(prefix="dktpu-mesh-smoke-"))
    server = PSServer(discipline="adag", lease_s=5.0, transport="mesh",
                      state_dir=state_dir, snapshot_every=10).start()
    try:
        trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                       num_workers=4, batch_size=16, num_epoch=3,
                       learning_rate=0.1, communication_window=4,
                       seed=0, remote=server.endpoint)
        trained = trainer.train(df, shuffle=True)
        assert server._mesh_folder is not None, (
            "the PS never resolved the mesh fold path")
        total = server.commits_total
        commit_log = list(server.commit_log)
        log_dropped = server._log_dropped
    finally:
        server.close()
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    reg = telemetry.get()
    upgrades = reg.counter("netps.mesh.upgrades").value
    folds = reg.counter("netps.mesh.folds").value
    demotions = reg.counter("netps.mesh.demotions").value
    # Exactly-once on the on-disk journal: no (wid, seq) folded twice,
    # epochs nondecreasing. Snapshot compaction bounds the journal to the
    # tail since the last snapshot, so contiguity is asserted within it.
    records, _ = _assert_journal_invariants(state_dir, "mesh")
    assert records, "mesh: the journal tail is empty"
    tail: dict = {}
    for r in records:
        tail.setdefault(int(r["wid"]), []).append(int(r["seq"]))
    for wid, seqs in sorted(tail.items()):
        assert seqs == list(range(seqs[0], seqs[0] + len(seqs))), (
            f"mesh: journal tail lost a window for worker {wid}: {seqs}")
    # Zero lost windows over the WHOLE run (the in-process commit log is
    # the full history): every worker's seqs are contiguous from 0 — the
    # demoted seq's retransmit landed exactly once, and no later window
    # vanished in the dialect switch.
    assert len(commit_log) + log_dropped == total
    per_worker: dict = {}
    for wid, seq, _st in commit_log:
        assert seq not in per_worker.setdefault(int(wid), set()), (
            f"mesh: commit ({wid}, {seq}) folded twice")
        per_worker[int(wid)].add(int(seq))
    for wid, seqs in sorted(per_worker.items()):
        assert seqs == set(range(max(seqs) + 1)), (
            f"mesh: worker {wid} lost a window: {sorted(seqs)}")
    print(f"netps mesh demotion: acc={acc:.4f} folds={total} "
          f"workers={len(per_worker)} mesh_upgrades={upgrades:.0f} "
          f"mesh_folds={folds:.0f} mesh_demotions={demotions:.0f}")
    assert acc > 0.85, f"accuracy collapsed across the demotion: {acc}"
    assert upgrades >= 1, "no worker ever negotiated the mesh dialect"
    assert folds >= 1, "the device collective never folded a commit"
    assert demotions >= 1, "mesh_down never bit — the drill is dead"
    return 0


def _run_failover(df, model) -> int:
    """Kill-the-primary mode: PS subprocess(es) + ps_crash, with either a
    babysitter cold restart or a warm-standby promotion riding it out."""
    import subprocess
    import threading
    import time

    state_dir = os.environ["DKTPU_PS_STATE_DIR"]
    standby_mode = bool(os.environ.get("DKTPU_PS_STANDBY"))
    port = _free_port()
    faults_state = os.path.join(state_dir, "faults.journal")
    os.makedirs(state_dir, exist_ok=True)
    primary = _launch_ps(port, state_dir,
                         {"DKTPU_NET_FAULTS": PS_FAULTS,
                          "DKTPU_FAULTS_STATE": faults_state})
    procs = [primary]
    restarts = [0]
    stop = threading.Event()
    standby_dir = state_dir + ".standby"

    def babysit():
        # Job.supervise's PS-restart duty, inlined: relaunch the killed
        # primary on the same state dir + port (cold recovery). The fired-
        # faults journal keeps ps_crash one-shot across the restart.
        nonlocal primary
        while not stop.is_set():
            if primary.poll() is not None and primary.returncode != 0:
                restarts[0] += 1
                primary = _launch_ps(
                    port, state_dir,
                    {"DKTPU_NET_FAULTS": PS_FAULTS,
                     "DKTPU_FAULTS_STATE": faults_state})
                procs.append(primary)
            time.sleep(0.1)

    standby = None
    if standby_mode:
        sb_port = _free_port()
        standby = _launch_ps(sb_port, standby_dir, {},
                             "--standby", f"127.0.0.1:{port}",
                             "--promote-after", "1.5")
        procs.append(standby)
    else:
        threading.Thread(target=babysit, daemon=True).start()
    proxy = ChaosProxy(f"127.0.0.1:{port}").start()  # ambient net faults
    endpoint = proxy.endpoint
    if standby_mode:
        endpoint = f"{endpoint},127.0.0.1:{sb_port}"
    if os.environ.get("DKTPU_TRACE"):
        # Label this process's spans in the merged timeline (the in-process
        # API, not DKTPU_TRACE_ROLE: the env var would leak into the PS
        # subprocesses and overwrite their own role stamps).
        from distkeras_tpu.telemetry import tracing
        tracing.set_role("trainer")
    try:
        trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                       num_workers=4, batch_size=16, num_epoch=3,
                       learning_rate=0.1, communication_window=4,
                       seed=0, remote=endpoint)
        trained = trainer.train(df, shuffle=True)
    finally:
        stop.set()
        proxy.close()
        # Crash evidence is read BEFORE teardown: the escalation below can
        # itself produce nonzero returncodes (SIGKILL on a wedged drain),
        # which must never masquerade as the injected ps_crash.
        crashed = any(p.poll() not in (0, None) for p in procs)
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    reg = telemetry.get()
    retries = reg.counter("netps.retries").value
    walks = reg.counter("netps.endpoint_walks").value
    records, last_epoch = _assert_journal_invariants(state_dir, "primary")
    mode = "standby" if standby_mode else "cold-restart"
    line = (f"netps kill-the-primary ({mode}): acc={acc:.4f} "
            f"journaled={len(records)} restarts={restarts[0]} "
            f"client_retries={retries:.0f} endpoint_walks={walks:.0f}")
    if standby_mode:
        sb_records, sb_epoch = _assert_journal_invariants(
            standby_dir, "standby")
        line += f" standby_journaled={len(sb_records)} epoch={sb_epoch}"
        assert sb_epoch >= 1, "the standby never promoted past epoch 0"
        assert walks >= 1, "no client ever walked the endpoint list"
    else:
        assert restarts[0] >= 1, "the primary was never killed + restarted"
        assert last_epoch == 0, "cold restart must not change the epoch"
    print(line)
    assert crashed, "ps_crash never fired — the drill tested nothing"
    assert acc > 0.85, f"accuracy collapsed across the PS crash: {acc}"
    assert retries >= 1, "no RPC ever retried — chaos did not bite"
    assert len(records) >= 10, "journal is implausibly short"
    if os.environ.get("DKTPU_TRACE"):
        _assert_trace_evidence(state_dir, standby_mode)
    return 0


def _run_sharded(df, model) -> int:
    """Kill-one-shard mode: N shard primaries + N warm standbys, shard 1
    SIGKILLed by its own ``shard_crash`` plan mid-run; its standby
    promotes while the other shards keep folding undisturbed."""
    import subprocess

    n = int(os.environ["NETPS_SMOKE_SHARDS"])
    base = os.environ["DKTPU_PS_STATE_DIR"]
    os.makedirs(base, exist_ok=True)
    victim = min(1, n - 1)
    shard_faults = os.environ.get(
        "NETPS_SMOKE_SHARD_FAULTS", f"shard_crash@{victim}:12;seed=3")
    groups, procs, primaries = [], [], []
    for k in range(n):
        p_port, s_port = _free_port(), _free_port()
        p_dir = os.path.join(base, f"shard-{k}")
        # Every primary carries the SAME plan: shard_crash@{victim} only
        # fires where the --shard index matches, so the non-victims parse
        # it and never trip. The fired-faults journal keeps it one-shot.
        primary = _launch_ps(
            p_port, p_dir,
            {"DKTPU_NET_FAULTS": shard_faults,
             "DKTPU_FAULTS_STATE": os.path.join(p_dir, "faults.journal")},
            "--shard", f"{k}/{n}")
        standby = _launch_ps(
            s_port, p_dir + ".standby", {},
            "--standby", f"127.0.0.1:{p_port}", "--promote-after", "1.5",
            "--shard", f"{k}/{n}")
        procs += [primary, standby]
        primaries.append(primary)
        groups.append(f"127.0.0.1:{p_port},127.0.0.1:{s_port}")
    endpoint = ";".join(groups)
    try:
        trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                       num_workers=4, batch_size=16, num_epoch=3,
                       learning_rate=0.1, communication_window=4,
                       seed=0, remote=endpoint)
        trained = trainer.train(df, shuffle=True)
    finally:
        # Crash evidence BEFORE teardown: the terminate/kill escalation
        # below must never masquerade as the injected shard_crash.
        victim_crashed = primaries[victim].poll() not in (0, None)
        bystanders_alive = all(primaries[k].poll() is None
                               for k in range(n) if k != victim)
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    reg = telemetry.get()
    retries = reg.counter("netps.retries").value
    walks = reg.counter("netps.endpoint_walks").value
    journaled = []
    for k in range(n):
        records, _ = _assert_journal_invariants(
            os.path.join(base, f"shard-{k}"), f"shard-{k}")
        journaled.append(len(records))
    sb_records, sb_epoch = _assert_journal_invariants(
        os.path.join(base, f"shard-{victim}.standby"),
        f"shard-{victim}-standby")
    print(f"netps kill-one-shard ({n} shards): acc={acc:.4f} "
          f"journaled={journaled} standby_journaled={len(sb_records)} "
          f"standby_epoch={sb_epoch} client_retries={retries:.0f} "
          f"endpoint_walks={walks:.0f}")
    assert victim_crashed, "shard_crash never fired — the drill tested nothing"
    assert bystanders_alive, "a non-victim shard died: the blast radius leaked"
    assert sb_epoch >= 1, (
        f"shard {victim}'s standby never promoted past epoch 0")
    assert walks >= 1, "no client ever walked the victim's endpoint group"
    assert acc >= 0.99, f"accuracy collapsed across the shard crash: {acc}"
    assert all(j >= 10 for j in journaled), (
        f"a shard journal is implausibly short: {journaled}")
    return 0


def _scrape_tree_stats(endpoint) -> dict:
    """One membership-free ledger scrape of a tree node subprocess."""
    from distkeras_tpu.netps import PSClient

    c = PSClient(endpoint, timeout=1.0, retries=5, backoff=0.1)
    try:
        return c.stats().get("tree") or {}
    finally:
        c.close()


def _run_tree_parity(repo_summary) -> dict:
    """Phase 2 of the tree drill: a live in-process loopback tree under a
    pinned mid-run partition, re-fitted through the simulator. The sim's
    ``region_partition`` scenario — re-shaped to THIS tree — must
    reproduce the measured root ingress cut and the partitioned region's
    staleness spike within the calibration band; the verdict lands in
    ``BENCH_SUMMARY.json`` under ``tree_parity``."""
    import json
    import time

    from distkeras_tpu.netps import PSClient, PSServer
    from distkeras_tpu.netps.tree import TreeSpec, build_tree
    from distkeras_tpu.resilience import faults
    from distkeras_tpu.sim.calibrate import tree_parity

    workers, rounds, work_s, part_s = 4, 30, 0.05, 1.0
    root = PSServer(discipline="adag",
                    center=[np.zeros(4, np.float32)], lease_s=30.0).start()
    tree = None
    clients = []
    try:
        tree = build_tree("region:2", root.endpoint, workers=workers,
                          buffer_windows=256, flush_interval=0.05,
                          probe_links=False)
        clients = [PSClient(tree.leaf_endpoint(r)) for r in range(workers)]
        for c in clients:
            c.join(init=[np.zeros(4, np.float32)])
        key = TreeSpec.link_key(0, 1)
        t0 = time.monotonic()
        part_t0 = None
        for rnd in range(rounds):
            if rnd == rounds // 3 and part_t0 is None:
                faults.set_net_plan(faults.FaultPlan.parse_net(
                    f"link_down@{key}:{part_s}"))
                part_t0 = time.monotonic() - t0
            for c in clients:
                _, pulled = c.pull()
                c.commit([np.ones(4, np.float32) * 0.001], pulled)
            time.sleep(work_s)
        wall = time.monotonic() - t0
        deadline = time.monotonic() + part_s + 5.0
        while time.monotonic() < deadline:  # heal + drain
            s1 = tree.node(0, 1).tree_stats()
            if s1["buffered_windows"] == 0 and not s1["link_down"]:
                break
            time.sleep(0.1)
        time.sleep(0.3)
        n0, n1 = tree.node(0, 0), tree.node(0, 1)
        s0, s1 = n0.tree_stats(), n1.tree_stats()
        assert s0["silent_loss"] == 0 and s1["silent_loss"] == 0, (
            "the traced loopback tree lost a window silently")
        assert s1["buffered_windows"] == 0, (
            "region 1 never drained its ride-through buffer after heal")
        absorbed = s0["absorbed"] + s1["absorbed"]
        part_stale = max(
            (st for wid, _seq, st in root.commit_log
             if wid == n1._up.worker_id), default=0)
        live = {
            "workers": workers, "fanouts": [2], "rounds": rounds,
            "work_s": wall / rounds, "flush_s": 0.05,
            "partition": [part_t0, part_t0 + part_s],
            "ingress_cut": absorbed / max(1, root.commits_total),
            "staleness_spike": int(part_stale),
        }
    finally:
        faults.reset()
        for c in clients:
            try:
                c.leave()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
            c.close()
        if tree is not None:
            tree.close()
        root.close()
    parity = tree_parity(live, band_pct=None)
    print(f"netps tree parity: ingress cut live="
          f"{parity['live']['ingress_cut']:.3f} sim="
          f"{parity['sim']['ingress_cut']:.3f} "
          f"(ratio {parity['ingress_cut_ratio']:.3f})  staleness spike "
          f"live={parity['live']['staleness_spike']} sim="
          f"{parity['sim']['staleness_spike']} "
          f"(ratio {parity['staleness_spike_ratio']:.3f})  band "
          f"{parity['band_pct']:.0f}%")
    assert parity["within_band"], (
        "the simulator's region_partition replay left the calibration "
        f"band: {json.dumps(parity, sort_keys=True)}")
    summary_path = os.environ.get("NETPS_SMOKE_SUMMARY", repo_summary)
    try:
        with open(summary_path, encoding="utf-8") as f:
            summary = json.load(f)
    except (OSError, ValueError):
        summary = {}
    summary["tree_parity"] = parity
    with open(summary_path, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
        f.write("\n")
    return parity


def _run_tree(df, model) -> int:
    """Region-partition tree mode: the 2-region / 3-level drill (see
    module docstring) followed by the simulator parity gate."""
    import subprocess
    import threading
    import time

    from distkeras_tpu.netps import PSClient
    from distkeras_tpu.netps.remote import _leaves
    from distkeras_tpu.netps.tree import TreeSpec

    base = os.environ["DKTPU_PS_STATE_DIR"]
    os.makedirs(base, exist_ok=True)
    tree_faults = os.environ.get("NETPS_SMOKE_TREE_FAULTS",
                                 "ps_crash@12;seed=3")
    link_key = TreeSpec.link_key(0, 1)
    link_faults = os.environ.get("NETPS_SMOKE_LINK_FAULTS",
                                 f"link_down@{link_key}:2.5;seed=3")
    root_port = _free_port()
    root_ep = f"127.0.0.1:{root_port}"
    root_dir = os.path.join(base, "root")
    procs = [_launch_ps(root_port, root_dir, {})]
    # Seed the root center with the model's leaves BEFORE any tree node
    # dials in: interior nodes join upstream with an empty init (their
    # center IS the root lineage's) and an uninitialized root would
    # reject them.
    init = [np.asarray(a, np.float32) for a in _leaves(model.params)]
    boot = PSClient(root_ep, timeout=1.0, retries=25, backoff=0.2)
    boot.join(init=init)
    boot.leave()
    boot.close()

    r0_port, s0_port, r1_port = _free_port(), _free_port(), _free_port()
    r0_dir = os.path.join(base, "tree-L0-g0")
    s0_dir = r0_dir + ".standby"
    r1_dir = os.path.join(base, "tree-L0-g1")
    tree_args = ("--tree-spec", "region:2", "--flush-interval", "0.2")
    # Region 0: the victim. Its OWN plan SIGKILLs it just before fold 12
    # (mid-run), no goodbye; the fired-faults journal keeps it one-shot.
    procs.append(_launch_ps(
        r0_port, r0_dir,
        {"DKTPU_NET_FAULTS": tree_faults,
         "DKTPU_FAULTS_STATE": os.path.join(r0_dir, "faults.journal")},
        "--upstream", root_ep, "--tree-level", "0", "--tree-group", "0",
        *tree_args))
    victim = procs[-1]
    # Its warm region-local standby: tails the journal, promotes on lease
    # lapse, fences, and takes over the uplink.
    procs.append(_launch_ps(
        s0_port, s0_dir, {},
        "--standby", f"127.0.0.1:{r0_port}", "--upstream", root_ep,
        "--tree-level", "0", "--tree-group", "0",
        "--promote-after", "1.5", *tree_args))
    # Region 1: healthy process, black-holed UPLINK — and a buffer bound
    # (2 windows) the 2.5 s outage must overrun, forcing typed drops.
    procs.append(_launch_ps(
        r1_port, r1_dir,
        {"DKTPU_NET_FAULTS": link_faults,
         "DKTPU_FAULTS_STATE": os.path.join(r1_dir, "faults.journal")},
        "--upstream", root_ep, "--tree-level", "0", "--tree-group", "1",
        "--tree-buffer", "2", "--fan-in", "1", *tree_args))

    stop = threading.Event()

    def region1_traffic():
        # Zero-delta commits: region 1 sees real windows, buffering, and
        # drops without perturbing the center the trainer is converging.
        # The node subprocess spends seconds importing before it listens,
        # so the join loops until it answers (or the drill ends).
        c = None
        deadline = time.monotonic() + 30.0
        while not stop.is_set() and time.monotonic() < deadline:
            try:
                c = PSClient(f"127.0.0.1:{r1_port}", timeout=1.0,
                             retries=3, backoff=0.1)
                c.join(init=init)
                break
            except Exception:  # noqa: BLE001 - still booting
                if c is not None:
                    c.close()
                c = None
                time.sleep(0.2)
        if c is None:
            return
        try:
            zeros = [np.zeros_like(a) for a in init]
            while not stop.is_set():
                _, pulled = c.pull()
                c.commit(zeros, pulled)
                stop.wait(0.05)
        finally:
            try:
                c.leave()
            except Exception:  # noqa: BLE001 - the drill may outlive it
                pass
            c.close()

    traffic = threading.Thread(target=region1_traffic, daemon=True)
    traffic.start()
    try:
        trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                       num_workers=4, batch_size=16, num_epoch=3,
                       learning_rate=0.1, communication_window=4,
                       seed=0, remote=f"127.0.0.1:{r0_port},"
                                      f"127.0.0.1:{s0_port}")
        trained = trainer.train(df, shuffle=True)
        # Region 1 must come back up and drain its survivors before the
        # ledger is read — ride-through, not ride-forever.
        r1_stats = {}
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            r1_stats = _scrape_tree_stats(f"127.0.0.1:{r1_port}")
            if (r1_stats and r1_stats["buffered_windows"] == 0
                    and not r1_stats["link_down"]):
                break
            time.sleep(0.2)
        sb_stats = _scrape_tree_stats(f"127.0.0.1:{s0_port}")
    finally:
        stop.set()
        traffic.join(timeout=5.0)
        # Crash evidence BEFORE teardown: the terminate/kill escalation
        # below must never masquerade as the injected ps_crash.
        victim_crashed = victim.poll() not in (0, None)
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    reg = telemetry.get()
    walks = reg.counter("netps.endpoint_walks").value
    journaled = {}
    for label, sdir in (("root", root_dir), ("region0", r0_dir),
                        ("region0-standby", s0_dir), ("region1", r1_dir)):
        records, last_epoch = _assert_journal_invariants(sdir, label)
        journaled[label] = (len(records), last_epoch)
    print(f"netps region-partition tree: acc={acc:.4f} "
          f"journaled={journaled} "
          f"dropped_windows={r1_stats.get('dropped_windows')} "
          f"dropped_commits={r1_stats.get('dropped_commits')} "
          f"silent_loss={r1_stats.get('silent_loss')} "
          f"endpoint_walks={walks:.0f}")
    assert victim_crashed, (
        "region 0's ps_crash never fired — the drill tested nothing")
    assert journaled["region0-standby"][1] >= 1, (
        "region 0's standby never promoted past epoch 0")
    assert sb_stats.get("forwarded", 0) >= 1, (
        "the promoted standby never flushed a combined window upstream")
    assert walks >= 1, "no client ever walked the region's endpoint list"
    assert r1_stats, "region 1's ledger was never scraped"
    assert r1_stats["link_downs"] >= 1, "region 1's link_down never fired"
    assert r1_stats["dropped_windows"] >= 1, (
        "the 2.5 s outage never overran the 2-window buffer: the "
        "typed-drop path went untested")
    assert r1_stats["buffered_windows"] == 0, (
        "region 1 never drained its buffer after the heal")
    assert r1_stats["silent_loss"] == 0, (
        f"window conservation violated: {r1_stats}")
    assert acc >= 0.99, f"accuracy collapsed across the region drill: {acc}"
    repo_summary = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_SUMMARY.json")
    _run_tree_parity(repo_summary)
    return 0


def main() -> int:
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4.0, size=(3, 4))
    y = rng.integers(0, 3, size=1024)
    x = centers[y] + rng.normal(scale=0.5, size=(1024, 4))
    df = DataFrame({"features": x.astype(np.float32),
                    "label": y.astype(np.int32)})
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32), seed=0)
    if os.environ.get("NETPS_SMOKE_MESH"):
        return _run_mesh(df, model)
    if os.environ.get("NETPS_SMOKE_TREE"):
        return _run_tree(df, model)
    if int(os.environ.get("NETPS_SMOKE_SHARDS") or 0) > 1:
        return _run_sharded(df, model)
    if os.environ.get("DKTPU_PS_STATE_DIR"):
        return _run_failover(df, model)
    server = PSServer(discipline="adag", lease_s=1.0).start()
    proxy = ChaosProxy(server.endpoint).start()  # ambient DKTPU_NET_FAULTS
    try:
        trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                       num_workers=4, batch_size=16, num_epoch=3,
                       learning_rate=0.1, communication_window=4,
                       seed=0, remote=proxy.endpoint)
        trained = trainer.train(df, shuffle=True)
    finally:
        proxy.close()
        server.close()
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    reg = telemetry.get()
    retries = reg.counter("netps.retries").value
    injected = reg.counter("resilience.faults_injected").value
    from distkeras_tpu.runtime import config

    if config.env_bool("DKTPU_NET_HIER"):
        # Workers live behind the in-process per-host aggregator: eviction
        # and rejoin happen THERE (its monitor/join feed the same counters
        # the root's would), while the root sees one aggregator peer.
        evictions = reg.counter("netps.evictions").value
        rejoins = reg.counter("netps.rejoins").value
    else:
        evictions, rejoins = server.evictions, server.rejoins
    print(f"netps chaos run: acc={acc:.4f} commits={len(server.commit_log)} "
          f"evictions={evictions:.0f} rejoins={rejoins:.0f} "
          f"client_retries={retries:.0f} faults_injected={injected:.0f}")
    assert acc > 0.85, f"accuracy collapsed under network chaos: {acc}"
    assert evictions >= 1, "the worker-kill eviction never happened"
    assert rejoins >= 1, "the evicted worker never re-joined"
    assert retries >= 1, "no RPC ever retried — chaos did not bite"
    seen = set()
    for wid, seq, _st in server.commit_log:
        assert (wid, seq) not in seen, f"commit ({wid}, {seq}) folded twice"
        seen.add((wid, seq))
    if config.env_bool("DKTPU_NET_AUTOTUNE"):
        # The self-tuning data plane under chaos: the controller must have
        # engaged (probes on TCP, the measured ring rule on shm, decisions
        # either way) and every retune it issued must have respected the
        # floors — a floor violation under faults means the guardrails,
        # not the chaos, are the bug.
        probes = reg.counter("tuner.probes").value
        floor_violations = reg.counter("tuner.floor_violations").value
        fallbacks = reg.counter("tuner.oscillation_fallbacks").value
        decisions = reg.counter("tuner.decisions").value
        runs = [e for e in reg.events() if e["kind"] == "tuner_run_summary"]
        print(f"netps autotune under chaos: probes={probes:.0f} "
              f"decisions={decisions:.0f} fallbacks={fallbacks:.0f} "
              f"floor_violations={floor_violations:.0f} converged="
              + (",".join(f"{k}={runs[-1].get(k)}" for k in
                          ("transport", "codec", "shards", "inflight"))
                 if runs else "none"))
        assert runs, "autotune on but the controller never exported a summary"
        assert decisions >= 1, "autotune on but the controller never decided"
        if runs[-1].get("transport") == "tcp":
            assert probes >= 1, (
                "TCP data plane but the controller never probed a codec")
        assert floor_violations == 0, (
            f"the controller violated a knob floor {floor_violations:.0f} "
            "times while retuning under chaos")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
