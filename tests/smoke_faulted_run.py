"""CI fault-injection smoke (not a pytest module — run directly).

An end-to-end, *env-driven* supervised run: CI invokes it with
``DKTPU_FAULTS="nan@1;stall@3:0.2;crash@5"`` set (see
``.github/workflows/tier1.yml``) and asserts the run completes, every
scheduled fault actually fired, and accuracy survived — the path a user
hits when they set ``DKTPU_FAULTS`` by hand, exercised without pytest
fixtures in the way.

    DKTPU_FAULTS="nan@1;stall@3:0.2;crash@5" python tests/smoke_faulted_run.py
"""

import os
import sys
import tempfile
import warnings

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distkeras_tpu import ADAG, DataFrame, Supervisor, telemetry  # noqa: E402
from distkeras_tpu.models import Model  # noqa: E402
from distkeras_tpu.models.mlp import MLP  # noqa: E402


def main() -> int:
    n_faults = len([e for e in os.environ.get("DKTPU_FAULTS", "").split(";")
                    if e.strip() and not e.strip().startswith("seed=")])
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4.0, size=(3, 4))
    y = rng.integers(0, 3, size=1024)
    x = centers[y] + rng.normal(scale=0.5, size=(1024, 4))
    df = DataFrame({"features": x.astype(np.float32),
                    "label": y.astype(np.int32)})
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32), seed=0)
    trainer = ADAG(model, loss="sparse_categorical_crossentropy",
                   num_workers=4, batch_size=16, num_epoch=3,
                   learning_rate=0.1, communication_window=4,
                   checkpoint_dir=tempfile.mkdtemp(prefix="dktpu-smoke-"),
                   checkpoint_every=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        trained = Supervisor(trainer, max_retries=3, backoff_s=0).train(
            df, shuffle=True)
    acc = float((np.asarray(trained.predict(jnp.asarray(
        df["features"]))).argmax(-1) == df["label"]).mean())
    injected = telemetry.get().counter("resilience.faults_injected").value
    print(f"supervised faulted run: acc={acc:.4f} "
          f"faults_injected={injected:.0f}/{n_faults}")
    assert acc > 0.85, f"accuracy collapsed under injected faults: {acc}"
    assert injected == n_faults, (injected, n_faults)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
