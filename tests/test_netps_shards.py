"""The sharded center plane: partition plans (rules, caps, row splits,
hash identity), the shared endpoint walker, the ShardedPSClient fan-out
protocol over in-process ShardSet gangs, the typed rejection paths, the
fleet gang placement, and the report/fault plumbing.

The headline guarantees pinned here:

* **Parity** — a 2-shard center driven by the same deterministic commits
  as a single PS ends bit-identical: sharding changes WHERE tensors live,
  never what is folded into them.
* **Never a silent mis-fold** — every way two peers can disagree about
  the plan (hash mismatch, plan-unaware peer, plain client on a shard
  server, shard claim on a plain server) answers a typed
  ``ShardPlanError`` at join, before any tensor moves.
* **Exactly-once per shard** — one logical seq per commit; a same-seq
  retransmit dedups on every shard that already folded it.
"""

import threading
import time

import numpy as np
import pytest

from distkeras_tpu.netps import wire
from distkeras_tpu.netps.client import PSClient
from distkeras_tpu.netps.endpoints import EndpointWalker, budget_left
from distkeras_tpu.netps.errors import ProtocolError, ShardPlanError
from distkeras_tpu.netps.server import PSServer
from distkeras_tpu.netps.shards import (
    PartitionPlan,
    ShardedPSClient,
    ShardSet,
    is_sharded_endpoint,
    make_ps_client,
    parse_rules,
    plan_for_model,
)
from distkeras_tpu.resilience.faults import FaultPlan

FAST = dict(timeout=2.0, retries=3, backoff=0.01)


def leaves():
    # No scalar () leaves here: the wire codec carries scalars as (1,)
    # (a pre-existing plain-PS limitation, not a sharding one); scalars
    # are covered by the in-process plan tests below.
    rng = np.random.default_rng(7)
    return [rng.normal(size=(8, 3)).astype(np.float32),
            rng.normal(size=(4,)).astype(np.float32),
            rng.normal(size=(2, 2)).astype(np.float32)]


# ---------------------------------------------------------------------------
# PartitionPlan
# ---------------------------------------------------------------------------

class TestPartitionPlan:
    def test_parse_rules(self):
        rules = parse_rules("kernel=0; bias = 1 ;embed=split")
        assert rules == [("kernel", 0), ("bias ", 1), ("embed", "split")]
        assert parse_rules("") == []

    @pytest.mark.parametrize("spec", [
        "kernel", "kernel=banana", "(=0",
    ])
    def test_parse_rules_rejects_malformed(self, spec):
        with pytest.raises(ShardPlanError):
            parse_rules(spec)

    def test_balanced_default_covers_everything_once(self):
        names = [f"t{i}" for i in range(7)]
        shapes = [(64, 8), (32, 8), (16, 8), (8, 8), (4,), (2,), ()]
        plan = PartitionPlan.build(names, shapes, 3)
        assert plan.num_shards == 3
        # Every tensor assigned exactly once, no splits without a reason.
        assert all(len(s) == 1 for s in plan.segments)
        assert sum(plan.loads) == sum(
            4 * max(1, int(np.prod(s))) for s in shapes)
        # Greedy largest-first keeps the byte skew bounded by the
        # dominant tensor (2048 B of 3868 B total here).
        assert plan.skew() < 2.0

    def test_pin_rule_wins_over_balance(self):
        plan = PartitionPlan.build(["a/kernel", "b/bias"], [(64, 8), (64,)],
                                   2, rules=[("kernel", 1)])
        assert plan.segments[0] == [(1, 0, 64)]

    def test_pin_rule_out_of_range(self):
        with pytest.raises(ShardPlanError):
            PartitionPlan.build(["a"], [(4, 4)], 2, rules=[("a", 5)])

    def test_split_rule_row_splits(self):
        plan = PartitionPlan.build(["big", "small"], [(10, 4), (3,)], 2,
                                   rules=[("big", "split")])
        segs = plan.segments[0]
        assert [k for k, _, _ in segs] == [0, 1]
        assert segs[0][1:] == (0, 5) and segs[1][1:] == (5, 10)
        # A scalar "split" degrades to the balanced default, never errors.
        p2 = PartitionPlan.build(["s"], [()], 2, rules=[("s", "split")])
        assert len(p2.segments[0]) == 1

    def test_cap_forces_split_and_rejects_overflow(self):
        # 10x4 f32 = 160 B: a 100 B cap forces the row split...
        plan = PartitionPlan.build(["big"], [(10, 4)], 2, cap_bytes=100)
        assert len(plan.segments[0]) == 2
        assert all(b <= 100 for b in plan.loads)
        # ...and a cap no split can satisfy is a typed error, not an OOM.
        with pytest.raises(ShardPlanError, match="per-shard cap"):
            PartitionPlan.build(["big"], [(10, 4)], 2, cap_bytes=50)

    def test_opt_factor_budgets_optimizer_state(self):
        # 160 B center fits a 200 B cap alone; with Adam's ~2x optimizer
        # shadow (480 B budgeted) one shard overflows, two carry it.
        PartitionPlan.build(["w"], [(10, 4)], 1, cap_bytes=200)
        with pytest.raises(ShardPlanError):
            PartitionPlan.build(["w"], [(10, 4)], 1, cap_bytes=200,
                                opt_factor=2.0)
        plan = PartitionPlan.build(["w"], [(10, 4)], 2, cap_bytes=250,
                                   opt_factor=2.0)
        assert len(plan.segments[0]) == 2

    def test_hash_roundtrip_and_identity(self):
        plan = PartitionPlan.build(["a", "b"], [(8, 3), (4,)], 2)
        again = PartitionPlan.from_json(plan.to_json())
        assert again == plan
        assert again.plan_hash == plan.plan_hash
        other = PartitionPlan.build(["a", "b"], [(8, 3), (4,)], 3)
        assert other.plan_hash != plan.plan_hash

    def test_from_dict_rejects_malformed(self):
        plan = PartitionPlan.build(["a"], [(4,)], 1)
        d = plan.to_dict()
        with pytest.raises(ShardPlanError):
            PartitionPlan.from_dict({**d, "version": 99})
        with pytest.raises(ShardPlanError):
            PartitionPlan.from_dict({"num_shards": 1})
        with pytest.raises(ShardPlanError):
            PartitionPlan.from_json("{not json")

    def test_scatter_assemble_roundtrip(self):
        rng = np.random.default_rng(0)
        tensors = [rng.normal(size=(9, 2)).astype(np.float32),
                   rng.normal(size=(5,)).astype(np.float32),
                   np.float32(3.0).reshape(())]
        plan = PartitionPlan.from_arrays(tensors, 3,
                                         rules=[("param_0000", "split")])
        back = plan.assemble(plan.scatter(tensors))
        for a, b in zip(tensors, back):
            np.testing.assert_array_equal(a, b)
        # shard_shapes agrees with what scatter actually produces.
        for k in range(3):
            got = [tuple(a.shape) for a in plan.shard_slice(tensors, k)]
            assert got == [tuple(s) for s in plan.shard_shapes(k)]

    def test_assemble_rejects_skew(self):
        plan = PartitionPlan.build(["a", "b"], [(4, 2), (3,)], 2)
        per_shard = plan.scatter([np.zeros((4, 2), np.float32),
                                  np.zeros((3,), np.float32)])
        with pytest.raises(ShardPlanError):
            plan.assemble(per_shard[:1])
        with pytest.raises(ShardPlanError):
            plan.assemble([per_shard[0], per_shard[1] + [np.zeros(1)]])

    def test_plan_for_model_env_overrides(self, monkeypatch):
        monkeypatch.setenv("DKTPU_PS_SHARD_OPT_FACTOR", "0")
        p0 = plan_for_model(leaves(), 2, opt_factor=2.0)
        monkeypatch.delenv("DKTPU_PS_SHARD_OPT_FACTOR")
        p1 = plan_for_model(leaves(), 2, opt_factor=2.0)
        # The env override (=0) zeroed the measured factor: loads differ.
        assert sum(p0.loads) < sum(p1.loads)


# ---------------------------------------------------------------------------
# EndpointWalker (the shared failover mechanics)
# ---------------------------------------------------------------------------

class TestEndpointWalker:
    def test_cas_walk_moves_one_step(self):
        w = EndpointWalker("a:1,b:2,c:3")
        assert w.current() == ("a", 1)
        seen = w.index
        assert w.walk(seen) is True
        # A sibling that saw the SAME failure does not double-advance.
        assert w.walk(seen) is False
        assert w.current() == ("b", 2)

    def test_single_endpoint_never_walks(self):
        w = EndpointWalker("a:1")
        assert w.walk(w.index) is False
        assert w.patience(lease_s=5.0, timeout=1.0) is None

    def test_walk_runs_teardown_only_on_win(self):
        w = EndpointWalker("a:1,b:2")
        calls = []
        w.walk(w.index, on_walk=lambda: calls.append("win"))
        w.walk(0, on_walk=lambda: calls.append("lose"))
        assert calls == ["win"]

    def test_advance_wraps(self):
        w = EndpointWalker("a:1,b:2")
        w.advance()
        w.advance()
        assert w.current() == ("a", 1)

    def test_patience_and_budget(self):
        w = EndpointWalker("a:1,b:2")
        deadline = w.patience(lease_s=0.5, timeout=0.25)
        assert deadline is not None
        assert deadline - time.monotonic() == pytest.approx(1.25, abs=0.1)
        assert budget_left(0, 3, None) is True
        assert budget_left(2, 3, None) is False
        assert budget_left(99, 3, time.monotonic() + 10) is True
        assert budget_left(99, 3, time.monotonic() - 1) is False

    def test_split_shard_endpoints(self):
        groups = wire.split_shard_endpoints("a:1,b:2;c:3;d:4,e:5")
        assert groups == ["a:1,b:2", "c:3", "d:4,e:5"]
        assert is_sharded_endpoint("a:1,b:2;c:3")
        assert not is_sharded_endpoint("a:1,b:2")


# ---------------------------------------------------------------------------
# ShardedPSClient end-to-end over an in-process ShardSet
# ---------------------------------------------------------------------------

def drive(client, n, *, worker_seed=1):
    """Join + fold ``n`` deterministic commits; returns the final pulled
    center (deltas depend only on ``worker_seed``, so a single-PS run and
    a sharded run fold identical streams)."""
    rng = np.random.default_rng(worker_seed)
    center, counter = client.join(init=leaves())
    for _ in range(n):
        delta = [rng.normal(scale=0.1, size=a.shape).astype(np.float32)
                 for a in center]
        res = client.commit(delta, counter)
        assert res.applied and not res.evicted
        center, counter = client.pull()
    return center


class TestShardedClient:
    def test_factory_routes_by_endpoint_shape(self):
        with ShardSet(2, center=leaves()) as ss:
            c = make_ps_client(ss.endpoint, **FAST)
            assert isinstance(c, ShardedPSClient)
            c.close()
        srv = PSServer(center=leaves()).start()
        try:
            c = make_ps_client(srv.endpoint, **FAST)
            assert isinstance(c, PSClient)
            c.close()
        finally:
            srv.close()

    def test_two_shard_parity_with_single_ps(self):
        # The same deterministic commit stream into a single PS and into
        # a 2-shard gang must end bit-identical: sharding changes WHERE
        # tensors live, never what is folded.
        srv = PSServer(center=leaves(), discipline="adag").start()
        try:
            c = PSClient(srv.endpoint, **FAST)
            single = drive(c, 4)
            c.leave()
            c.close()
        finally:
            srv.close()
        with ShardSet(2, center=leaves(), discipline="adag") as ss:
            c = ShardedPSClient(ss.endpoint, plan=ss.plan, **FAST)
            sharded = drive(c, 4)
            c.leave()
            c.close()
        for a, b in zip(single, sharded):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_join_shares_worker_id_and_counters_are_per_shard(self):
        with ShardSet(2, center=leaves()) as ss:
            c = ShardedPSClient(ss.endpoint, plan=ss.plan, **FAST)
            try:
                center, counters = c.join(init=leaves())
                assert isinstance(counters, tuple) and len(counters) == 2
                assert all(s.worker_id == c.worker_id for s in c._subs)
                for a, b in zip(center, leaves()):
                    assert np.asarray(a).shape == np.asarray(b).shape
            finally:
                c.close()

    def test_same_seq_retransmit_dedups_per_shard(self):
        with ShardSet(2, center=leaves()) as ss:
            c = ShardedPSClient(ss.endpoint, plan=ss.plan, **FAST)
            try:
                center, counters = c.join(init=leaves())
                delta = [np.ones_like(np.asarray(a)) for a in center]
                res = c.commit(delta, counters)
                assert res.applied
                # The reconciliation path's retransmit: the SAME logical
                # seq resent to a shard that already folded it dedups.
                slices = c.plan.scatter(delta)
                for k, sub in enumerate(c._subs):
                    res_k = sub.commit(slices[k], counters[k], seq=c._seq)
                    assert res_k.duplicate and not res_k.applied
                # And the fold happened exactly once.
                after, _ = c.pull()
                for a0, a1 in zip(leaves(), after):
                    np.testing.assert_allclose(
                        np.asarray(a1), np.asarray(a0) + 1.0, atol=1e-6)
            finally:
                c.leave()
                c.close()

    def test_observer_adopts_plan_without_init(self):
        with ShardSet(2, center=leaves()) as ss:
            c = ShardedPSClient(ss.endpoint, **FAST)  # no plan, no join
            try:
                center, counters = c.pull()
                assert c.plan is not None
                assert c.plan.plan_hash == ss.plan.plan_hash
                for a, b in zip(center, leaves()):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
            finally:
                c.close()

    def test_rejoin_resumes_seq_high_water_mark(self):
        with ShardSet(2, center=leaves()) as ss:
            c = ShardedPSClient(ss.endpoint, plan=ss.plan, **FAST)
            center, counters = c.join(init=leaves())
            delta = [np.zeros_like(np.asarray(a)) for a in center]
            for _ in range(3):
                c.commit(delta, counters)
            seq, wid = c._seq, c.worker_id
            c.close()
            c2 = ShardedPSClient(ss.endpoint, worker_id=wid,
                                 plan=ss.plan, **FAST)
            try:
                c2.join(init=leaves())
                # The next commit must be a seq no shard has folded.
                assert c2._seq >= seq
            finally:
                c2.leave()
                c2.close()


# ---------------------------------------------------------------------------
# Typed rejections: every way to disagree about the plan
# ---------------------------------------------------------------------------

class TestPlanRejections:
    def test_plan_hash_mismatch_is_typed(self):
        with ShardSet(2, center=leaves()) as ss:
            other = PartitionPlan.from_arrays(leaves(), 2,
                                              rules=[(".*", 0)])
            assert other.plan_hash != ss.plan.plan_hash
            c = ShardedPSClient(ss.endpoint, plan=other, **FAST)
            try:
                with pytest.raises(ShardPlanError):
                    c.join(init=leaves())
            finally:
                c.close()

    def test_plain_client_rejected_by_shard_server(self):
        with ShardSet(2, center=leaves()) as ss:
            ep0 = ss.endpoint.split(";")[0]
            c = PSClient(ep0, **FAST)
            try:
                with pytest.raises(ShardPlanError):
                    c.join(init=None)
            finally:
                c.close()

    def test_shard_claim_rejected_by_plain_server(self):
        srv = PSServer(center=leaves()).start()
        try:
            fake_matrix = f"{srv.endpoint};{srv.endpoint}"
            c = ShardedPSClient(fake_matrix,
                                plan=plan_for_model(leaves(), 2), **FAST)
            try:
                with pytest.raises(ShardPlanError):
                    c.join(init=leaves())
            finally:
                c.close()
        finally:
            srv.close()

    def test_pre_sharding_peer_rejected(self, monkeypatch):
        # An old build's caps have no "sharding" bit: the server must
        # refuse the join with a typed error, not mis-fold silently.
        old_caps = {k: v for k, v in wire.CAPS.items() if k != "sharding"}
        with ShardSet(1, center=leaves()) as ss:
            monkeypatch.setattr(wire, "CAPS", old_caps)
            c = PSClient(ss.endpoint, **FAST)
            try:
                with pytest.raises(ProtocolError):
                    c.join(init=None)
            finally:
                c.close()

    def test_plan_num_shards_must_match_matrix(self):
        with pytest.raises(ShardPlanError):
            ShardedPSClient("a:1;b:2;c:3",
                            plan=plan_for_model(leaves(), 2), **FAST)
        with pytest.raises(ValueError):
            ShardSet(3, plan=plan_for_model(leaves(), 2))


# ---------------------------------------------------------------------------
# Server-side plan persistence and identity
# ---------------------------------------------------------------------------

class TestServerPlanState:
    def test_plan_persisted_and_adopted_on_restart(self, tmp_path):
        plan = plan_for_model(leaves(), 2)
        state = str(tmp_path / "shard-1")
        srv = PSServer(shard_index=1, shard_count=2, shard_plan=plan,
                       state_dir=state).start()
        srv.close()
        assert (tmp_path / "shard-1" / "plan.json").exists()
        # A cold restart on the same dir recovers the shard identity and
        # plan WITHOUT being told — the plan file is authoritative.
        back = PSServer(state_dir=state)
        try:
            assert back.shard_index == 1 and back.shard_count == 2
            assert back.shard_plan.plan_hash == plan.plan_hash
        finally:
            back.close()

    def test_shard_index_range_checked(self):
        with pytest.raises(ValueError):
            PSServer(shard_index=2, shard_count=2)

    def test_cli_shard_arg_rejects_malformed(self):
        from distkeras_tpu.netps.__main__ import main

        for bad in ("bogus", "3/2", "2/2", "-1/2"):
            with pytest.raises(SystemExit):
                main(["--shard", bad, "--port", "0"])


# ---------------------------------------------------------------------------
# Faults, hier counter folding, fleet gang placement, report section
# ---------------------------------------------------------------------------

class TestShardCrashFault:
    def test_pending_is_non_consuming_peek(self):
        plan = FaultPlan.parse_net("shard_crash@1:12;seed=3")
        # The threshold poll: repeated peeks never burn the one-shot.
        assert plan.pending("shard_crash", 1) == 12.0
        assert plan.pending("shard_crash", 1) == 12.0
        assert plan.pending("shard_crash", 0) is None
        assert plan.fire("shard_crash", 1) == 12.0
        assert plan.pending("shard_crash", 1) is None


class TestHierCounterScalar:
    def test_min_over_per_shard_counters(self):
        from distkeras_tpu.netps.hier import _counter_scalar

        assert _counter_scalar(7) == 7
        assert _counter_scalar((5, 3, 9)) == 3
        assert _counter_scalar([4]) == 4


class TestGangPlacement:
    def _card(self, **ps):
        from distkeras_tpu.job_deployment import Punchcard

        return Punchcard("j", "train.py", ["localhost"], ps=ps)

    def test_endpoint_matrix_sticky_and_released(self):
        pc = self._card(shards=2, standby_host="localhost",
                        state_dir="/tmp/sd")
        ep = pc.ps_endpoint()
        groups = ep.split(";")
        assert len(groups) == 2 and all("," in g for g in groups)
        assert pc.ps_endpoint() == ep  # sticky: later renders agree
        assert pc.ps_standby_endpoint() is None  # standbys live in matrix
        ports = set(pc.ps["shard_ports"]) | set(pc.ps["standby_ports"])
        assert len(ports) == 4
        pc.release_ports()
        assert "shard_ports" not in pc.ps and "standby_ports" not in pc.ps

    def test_render_gang_commands(self):
        from distkeras_tpu.job_deployment import Job

        pc = self._card(shards=2, standby_host="localhost",
                        state_dir="/tmp/sd", lease=5)
        job = Job(pc)
        ps_cmds = job.render_ps_commands()
        sb_cmds = job.render_standby_commands()
        assert len(ps_cmds) == len(sb_cmds) == 2
        for k, cmd in enumerate(ps_cmds):
            assert f"--shard {k}/2" in cmd
            assert f"--state-dir /tmp/sd/shard-{k}" in cmd
            assert f"--port {pc.ps['shard_ports'][k]}" in cmd
        for k, cmd in enumerate(sb_cmds):
            assert f"--shard {k}/2" in cmd
            assert f"--state-dir /tmp/sd/shard-{k}.standby" in cmd
            assert "--standby localhost:" in cmd
        # The singular forms stay the unsharded card's exact contract.
        assert job.render_ps_command() == ps_cmds[0]
        pc.release_ports()

    def test_unsharded_card_unchanged(self):
        from distkeras_tpu.job_deployment import Job

        pc = self._card(port=7077, state_dir="/tmp/sd")
        job = Job(pc)
        cmd = job.render_ps_command()
        assert "--port 7077" in cmd and "--shard" not in cmd
        assert job.render_ps_commands() == [cmd]
        assert pc.ps_endpoint() == "localhost:7077"

    def test_explicit_shard_ports_length_checked(self):
        pc = self._card(shards=3, shard_ports=[7001, 7002])
        with pytest.raises(ValueError):
            pc.ps_endpoint()

    def test_ps_plane_roster_per_shard_roles(self):
        from distkeras_tpu.job_deployment import Job

        pc = self._card(shards=2, standby_host="localhost")
        job = Job(pc)
        job._shard_procs = [None, None]
        job._shard_standby_procs = [None, None]
        roles = [r for r, *_ in job._ps_plane()]
        assert roles == ["shard-0", "shard-1",
                         "shard-0-standby", "shard-1-standby"]
        pc.release_ports()


class TestShardReport:
    def test_shard_summary_and_render_section(self):
        from distkeras_tpu.telemetry.report import (
            render_report,
            shard_summary,
        )

        summary = {
            "counters": {"netps.shard.folds.0": 10.0,
                         "netps.shard.folds.1": 9.0,
                         "netps.shard.bytes.0": 4096.0,
                         "netps.shard.bytes.1": 4000.0,
                         "netps.shard.partial_commits": 1.0},
            "gauges": {"netps.shard.count": {"value": 2.0},
                       "netps.shard.skew": {"value": 1.02}},
        }
        sh = shard_summary(summary)
        assert sh["per_shard_folds"] == [10.0, 9.0]
        assert sh["per_shard_bytes"] == [4096.0, 4000.0]
        assert sh["shard_count"] == 2.0
        assert sh["plan_skew"] == 1.02
        assert sh["partial_commits"] == 1.0
        assert shard_summary({"counters": {}, "gauges": {}}) is None
        report = {
            "path": "x.jsonl", "rounds": 0, "total_round_seconds": 0.0,
            "phases": [], "counters": {}, "gauges": {}, "segments": [],
            "staleness": None, "stragglers": [], "fleet": [],
            "serving": None, "shards": sh, "losses": [],
        }
        text = render_report(report)
        assert "## Sharded center" in text
        assert "per-shard folds: [10, 9]" in text
        assert "plan byte skew: 1.020" in text


# ---------------------------------------------------------------------------
# Concurrency: multiple sharded committers, exactly-once totals
# ---------------------------------------------------------------------------

class TestConcurrentCommitters:
    def test_two_workers_all_folds_land_once(self):
        with ShardSet(2, center=leaves(), discipline="adag") as ss:
            n_commits, errors = 3, []

            def work(seed):
                try:
                    c = ShardedPSClient(ss.endpoint, plan=ss.plan, **FAST)
                    try:
                        drive(c, n_commits, worker_seed=seed)
                        c.leave()
                    finally:
                        c.close()
                except Exception as e:  # noqa: BLE001 - surfaced below
                    errors.append(e)

            threads = [threading.Thread(target=work, args=(s,))
                       for s in (1, 2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            # Every shard folded every worker's every commit exactly once.
            for srv in ss.servers:
                assert srv.commits_total == 2 * n_commits
