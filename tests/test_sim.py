"""Fleet-simulator tests (ISSUE 16): the deterministic event engine
(heap tie-break, seeded RNG, virtual clock), the scheduler/hub seams
(real FleetScheduler on zero OS threads, fed MetricsHub series, with the
production defaults pinned), counter-rule parity between SimCenter and
the netps fold functions, the trace-fitted TimingModel over a REAL
traced loopback run (the same stream bench #8's ``sim_drift`` block
fits), the calibration gates against the committed BENCH_SUMMARY
(held-out band + the flat->hier crossover at the measured W), the
bench-regression sentinel's nested ``sim_drift`` pickup, bit-identical
scenario determinism under a pinned seed, every scenario's invariant
checks at full scale, and the ``python -m distkeras_tpu.sim`` CLI exit
contract."""

import json
import os
import threading
import time

import numpy as np
import pytest

from distkeras_tpu.netps.fold import counter_staleness
from distkeras_tpu.sim import (
    SimCenter,
    SimEngine,
    SimJobRuntime,
    SimThreadFactory,
    TimingModel,
    hier_crossover,
    run_scenario,
    sim_drift,
)
from distkeras_tpu.sim.__main__ import main as sim_main
from distkeras_tpu.sim.calibrate import predict_throughput
from distkeras_tpu.sim.cluster import LinkClass, SimAggregator, TreeTopology

SUMMARY = os.path.join(os.path.dirname(__file__), os.pardir,
                       "BENCH_SUMMARY.json")


# -- the event engine -------------------------------------------------------

def test_engine_heap_orders_same_time_events_by_schedule_order():
    eng = SimEngine(0)
    seen = []
    for i in range(32):
        eng.at(1.0, seen.append, i)
    eng.run()
    assert seen == list(range(32))
    assert eng.now() == 1.0


def test_engine_past_is_clamped_and_until_advances_clock():
    eng = SimEngine(0)
    eng.at(5.0, lambda: eng.at(1.0, lambda: None))  # schedules "the past"
    eng.run(until=9.0)
    assert eng.now() == 9.0
    assert eng.pending() == 0


def test_engine_rng_is_seed_deterministic():
    a = SimEngine(7)
    b = SimEngine(7)
    assert [a.lognormal(0.0, 0.5) for _ in range(64)] \
        == [b.lognormal(0.0, 0.5) for _ in range(64)]
    assert SimEngine(8).lognormal(0.0, 0.5) != SimEngine(7).lognormal(0.0,
                                                                      0.5)


def test_engine_runaway_backstop_raises():
    eng = SimEngine(0)

    def rearm():
        eng.after(0.1, rearm)

    eng.after(0.0, rearm)
    with pytest.raises(RuntimeError, match="runaway"):
        eng.run(max_events=100)


# -- counter-rule parity: SimCenter vs the netps fold functions -------------

def test_sim_center_staleness_matches_counter_staleness():
    c = SimCenter(discipline="downpour")
    pulled = c.pull()
    for i in range(5):
        c.commit(wid=0, seq=i, pulled=pulled)  # stale pull held across
    # commit i saw i updates land since the pull: the fold rule verbatim
    assert [st for _w, _s, st in c.commit_log] \
        == [counter_staleness(i, 0) for i in range(5)]
    assert c.max_staleness == 4


def test_sim_center_sharded_pull_uses_min_rule():
    c = SimCenter(discipline="downpour", shards=3)
    pulled = c.pull()
    assert pulled == (0, 0, 0)
    c.commit(0, 0, pulled)
    res = c.commit(0, 1, pulled)  # one commit landed on every shard
    assert res["staleness"] == counter_staleness((1, 1, 1), pulled) == 1


def test_sim_center_dedup_and_value_witness():
    c = SimCenter(discipline="downpour")
    c.commit(0, 0, c.pull(), value=1.0)
    dup = c.commit(0, 0, c.pull(), value=1.0)  # retransmit
    assert dup == {"applied": False, "duplicate": True, "staleness": None}
    c.commit(1, 0, c.pull(), value=1.0)
    assert c.duplicates == 1
    assert c.exactly_once()
    assert c.center_value() == float(c.commits_total) == 2.0


def test_sim_center_promote_bumps_epoch_and_keeps_dedup():
    c = SimCenter()
    c.commit(0, 0, c.pull())
    assert c.promote() == 1
    assert c.epoch_history == [0, 1]
    assert c.commit(0, 0, c.pull())["duplicate"]  # dedup carried across


def test_aggregator_flush_policy_and_min_forwarding():
    agg = SimAggregator("a", fan_in=3, flush_s=10.0)
    assert agg.fold(0.0, 7, 1.0) is None
    assert agg.fold(0.1, 2, 1.0) is None
    out = agg.fold(0.2, 5, 1.0)  # fan-in trips
    assert out["count"] == 3 and out["value"] == 3.0
    assert out["pulled"] == 2  # the hier MIN rule
    # age-based flush: one lonely commit past the interval
    assert agg.fold(20.0, 9, 1.0) is None
    assert agg.fold(31.0, 9, 1.0)["count"] == 2
    assert agg.take(31.0) is None  # nothing pending


def test_tree_topology_paths_and_partitions():
    topo = TreeTopology(64, [("host", 8, LinkClass("h", 0.001)),
                             ("pool", 4, LinkClass("p", 0.002))])
    assert topo.group_of(63, 0) == 7 and topo.group_of(63, 1) == 1
    assert [a.name for a in topo.path(0)] == ["host-0", "pool-0"]
    topo.partition(1, 1, 2.0, 4.0)
    assert topo.link_down(1, 1, 3.0) and not topo.link_down(1, 0, 3.0)
    assert topo.heals_at(1, 1, 3.0) == 4.0
    assert topo.heals_at(1, 1, 5.0) == 5.0


# -- the seams --------------------------------------------------------------

def test_scheduler_seam_defaults_are_production():
    from distkeras_tpu.fleet.scheduler import FleetScheduler

    sched = FleetScheduler(capacity=2)
    assert sched._clock is time.monotonic
    assert sched._thread_factory is threading.Thread


def test_hub_feed_seam_series_and_liveness():
    from distkeras_tpu.telemetry.health.hub import MetricsHub

    eng = SimEngine(0)
    hub = MetricsHub(targets={}, interval=1.0, ring=64, down_after=3,
                     use_registry=False, clock=eng.clock())
    hub.feed("t0", "serving.latency", 0.2, role="serving")
    eng._now = 1.0
    hub.feed("t0", "serving.latency", 0.4, role="serving")
    assert hub.measure("serving.latency", stat="mean",
                       window_s=10.0) == pytest.approx(0.3)
    assert not hub.is_down("t0")
    for _ in range(3):
        eng._now += 1.0
        hub.feed_miss("t0", role="serving")
    assert hub.is_down("t0")
    hub.feed("t0", "serving.latency", 0.2, role="serving")
    assert not hub.is_down("t0")


def test_sim_thread_runs_scheduler_worker_synchronously():
    from distkeras_tpu.fleet.job import FleetJob
    from distkeras_tpu.fleet.scheduler import FleetScheduler

    eng = SimEngine(3)
    factory = SimThreadFactory(eng)
    rt = SimJobRuntime(eng, "tiny", lambda e, w: 0.1, rounds_target=40)
    sched = FleetScheduler(capacity=8, tick_s=0.5,
                           clock=eng.clock(), thread_factory=factory)
    job = sched.submit(FleetJob("tiny", "acme", rt, min_gang=2,
                                max_workers=8))

    def tick():
        sched.tick()
        if not sched.all_terminal():
            eng.after(0.5, tick)

    eng.after(0.0, tick)
    eng.run()
    sched.close()
    assert threading.active_count() == 1 or factory.created >= 8
    assert sched.stats()[job.job_id]["state"] == "done"
    assert rt.center.exactly_once()
    assert rt.rounds_done >= 40


def test_sim_runtime_crash_lose_ack_forces_deduped_retransmit():
    eng = SimEngine(1)
    rt = SimJobRuntime(eng, "j", lambda e, w: 0.2, rounds_target=10)
    th = SimThreadFactory(eng)(target=lambda: None)
    eng.current_thread = th
    rt.worker_main(0, lambda: True)
    eng.current_thread = None
    eng.run(until=1.05)  # ~4 commits land
    applied = rt.center.commits_total
    assert rt.crash(0, lose_ack=True)
    # respawn: the scheduler would re-run worker_main with a new thread
    eng.current_thread = SimThreadFactory(eng)(target=lambda: None)
    rt.worker_main(0, lambda: True)
    eng.current_thread = None
    eng.run()
    assert rt.center.duplicates == 1  # the resent seq was absorbed
    assert rt.center.exactly_once()
    assert rt.rounds_done == 10 == rt.center.commits_total
    assert rt.center.commits_total >= applied


# -- the timing model over a REAL traced loopback run -----------------------

@pytest.fixture(scope="module")
def traced_records(tmp_path_factory):
    """One real PSServer/PSClient loopback run with tracing on: the
    stream the timing model fits (same shape bench #8 feeds sim_drift).
    Returns (records, measured_commits_per_sec)."""
    from distkeras_tpu.netps.client import PSClient
    from distkeras_tpu.netps.server import PSServer
    from distkeras_tpu.telemetry.tracing import context as trace_context
    from distkeras_tpu.telemetry.tracing.collector import TelemetryCollector

    td = str(tmp_path_factory.mktemp("sim-traces"))
    saved = {k: os.environ.get(k) for k in ("DKTPU_TRACE",
                                            "DKTPU_TRACE_DIR")}
    os.environ["DKTPU_TRACE"] = "1"
    os.environ["DKTPU_TRACE_DIR"] = td
    trace_context._reset_stream()
    rounds = 12
    try:
        srv = PSServer(discipline="adag", host="127.0.0.1",
                       port=0).start()
        try:
            tmpl = [np.zeros(64, np.float32)]
            cl = PSClient(srv.endpoint, worker_id=0)
            cl.join(init=tmpl)
            t0 = time.perf_counter()
            for i in range(rounds):
                cl.commit([np.ones_like(a) for a in tmpl], i)
            dt = time.perf_counter() - t0
            cl.leave()
            cl.close()
        finally:
            srv.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        trace_context._reset_stream()
    return TelemetryCollector.from_dir(td).records(), rounds / dt


def test_timing_model_fits_lifecycle_segments(traced_records):
    records, _rate = traced_records
    model = TimingModel.from_records(records)
    assert model.commits >= 10
    assert {"wire", "fold", "ack"} <= set(model.segments)
    desc = model.describe()
    for info in desc["segments"].values():
        assert info["count"] > 0 and info["mean_s"] >= 0.0
    eng = SimEngine(0)
    assert model.sample_service(eng) >= 0.0
    assert model.sample_commit_client(eng) >= 0.0


def test_sim_drift_predicts_real_loopback_within_structure(traced_records):
    records, rate = traced_records
    out = sim_drift(records, measured_tokens_per_sec=rate,
                    tokens_per_round=1.0)
    assert out["metric"] == "sim_predicted_vs_measured_tokens_per_sec"
    assert out["workers"] == 1 and out["rounds"] >= 10
    assert out["predicted_tokens_per_sec"] > 0
    assert isinstance(out["within_band"], bool)
    # prediction is deterministic given the records and seed
    again = sim_drift(records, measured_tokens_per_sec=rate,
                      tokens_per_round=1.0)
    assert again["predicted_tokens_per_sec"] \
        == out["predicted_tokens_per_sec"]


def test_predict_throughput_infers_workers_and_rounds(traced_records):
    records, _rate = traced_records
    out = predict_throughput(records=records, tokens_per_round=128.0)
    assert out["workers"] == 1
    assert out["commits_per_sec"] > 0
    assert out["tokens_per_sec"] == pytest.approx(
        128.0 * out["commits_per_sec"])


# -- calibration gates vs the committed bench summary -----------------------

def test_hier_crossover_gate_against_bench_summary():
    out = hier_crossover(summary=SUMMARY)
    assert out["within_band"], out
    assert out["crossover_reproduced"], out
    assert out["predicted_crossover_workers"] \
        == out["measured_crossover_workers"] == 4
    held_out = [p for p in out["points"] if p["held_out"]]
    assert len(held_out) >= 2  # flat W=4 and at least one hier point
    assert all(p["error_pct"] <= out["band_pct"] for p in held_out)
    # the topology's point: the root-ingress cut at the crossover
    assert out["measured_ingress_cut"] >= 2.5


def test_hier_crossover_is_seed_deterministic():
    a = hier_crossover(summary=SUMMARY, seed=5)
    b = hier_crossover(summary=SUMMARY, seed=5)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_sentinel_picks_up_nested_sim_drift(tmp_path):
    from distkeras_tpu.telemetry.health.sentinels import Sentinels

    p = tmp_path / "BENCH_SUMMARY.json"
    p.write_text(json.dumps({"configs": [{
        "metric": "netps_loopback_aeasgd_tokens_per_sec_per_chip",
        "value": 100.0, "within_band": True,
        "sim_drift": {"metric": "sim_predicted_vs_measured_tokens_per_sec",
                      "value": 1.9, "within_band": False}}]}))
    regs = Sentinels.bench_regressions(str(p))
    assert [r["metric"] for r in regs] \
        == ["sim_predicted_vs_measured_tokens_per_sec"]
    # a healthy sim_drift stays silent
    p.write_text(json.dumps({"configs": [{
        "metric": "m", "value": 1.0, "within_band": True,
        "sim_drift": {"metric": "s", "value": 1.0, "within_band": True}}]}))
    assert Sentinels.bench_regressions(str(p)) == []


# -- scenario determinism + invariants --------------------------------------

def _canon(out: dict) -> str:
    return json.dumps(out, sort_keys=True)


def test_scenarios_are_bit_identical_per_seed():
    # round_s stretched so the small job is still running at BOTH
    # outages (the one_requeue_per_outage invariant needs a live job)
    a = run_scenario("failover_cascade", workers=24, seed=3, round_s=0.5)
    b = run_scenario("failover_cascade", workers=24, seed=3, round_s=0.5)
    assert _canon(a) == _canon(b)
    c = run_scenario("failover_cascade", workers=24, seed=4, round_s=0.5)
    assert _canon(a) != _canon(c)
    assert a["ok"] and c["ok"]  # every seed must satisfy the invariants


def test_alert_storm_determinism_and_invariants():
    a = run_scenario("alert_storm", seed=0)
    b = run_scenario("alert_storm", seed=0)
    assert _canon(a) == _canon(b)
    assert a["ok"], a["checks"]
    assert a["alerts"]["fired"] == a["alerts"]["cleared"]
    assert any(k.startswith("target_down:")
               for k in a["alerts"]["keys"])


def test_preemption_storm_full_scale_1000_workers():
    t0 = time.perf_counter()
    out = run_scenario("preemption_storm", workers=1000, seed=0)
    wall = time.perf_counter() - t0
    assert out["ok"], out["checks"]
    assert out["workers"] == 1000 and out["regions"] == 3
    assert wall < 60.0  # the acceptance bound, with huge margin
    assert out["checks"]["floors_never_violated"]
    assert out["checks"]["exactly_once"]
    assert out["alerts"]["fired"] >= 1


def test_failover_cascade_invariants():
    out = run_scenario("failover_cascade", seed=0)
    assert out["ok"], out["checks"]
    assert out["center"]["epochs"] == [0, 1, 2]
    assert out["center"]["value"] == float(out["center"]["commits"])
    assert out["center"]["duplicates"] >= 1


def test_region_partition_conserves_value_through_partition():
    out = run_scenario("region_partition", seed=0)
    assert out["ok"], out["checks"]
    st = out["staleness_by_region"]
    part = str(out["partitioned_region"])
    healthy = max(v for g, v in st.items() if g != part)
    assert st[part] > healthy


def test_unknown_scenario_is_a_typed_error():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("nope")


# -- the CLI ----------------------------------------------------------------

def test_cli_run_and_calibrate_exit_contract(capsys):
    assert sim_main(["run", "alert_storm", "--seed", "0"]) == 0
    assert "OK" in capsys.readouterr().out
    assert sim_main(["calibrate", "--summary", SUMMARY]) == 0
    out = capsys.readouterr().out
    assert "crossover" in out and "OK" in out


def test_cli_run_json_is_parseable(capsys):
    assert sim_main(["run", "alert_storm", "--seed", "0", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is True


def test_cli_report_renders_fitted_model(tmp_path, traced_records, capsys):
    # re-point report at a dir rebuilt from the fixture's records
    records, _rate = traced_records
    stream = tmp_path / "trace-test-1.jsonl"
    stream.write_text("\n".join(json.dumps(r) for r in records))
    assert sim_main(["report", "--trace-dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "timing model" in out and "fold" in out
