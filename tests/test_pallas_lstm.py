"""Pallas LSTM kernel vs flax OptimizedLSTMCell: values and gradients.

Runs the kernels through the Pallas interpreter on the CPU mesh (same pattern
as the flash-attention tests); the compiled path runs on real TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from distkeras_tpu.ops.pallas.lstm import lstm_seq, pack_lstm_params


@pytest.fixture(scope="module")
def ref_setup():
    B, T, E, H = 3, 7, 5, 6
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, T, E)).astype(np.float32))
    cell = nn.RNN(nn.OptimizedLSTMCell(H))
    variables = cell.init(jax.random.key(1), x)
    return x, cell, variables, (B, T, E, H)


def test_forward_matches_flax(ref_setup):
    x, cell, variables, _ = ref_setup
    ref = cell.apply(variables, x)
    wx, wh, b = pack_lstm_params(variables["params"]["cell"])
    got = lstm_seq(wx, wh, b, x, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_flax(ref_setup):
    x, cell, variables, _ = ref_setup

    def loss_ref(params, x):
        hs = cell.apply({"params": params}, x)
        return jnp.sum(jnp.tanh(hs[:, -1]) ** 2) + 0.1 * jnp.sum(hs)

    def loss_pal(params, x):
        wx, wh, b = pack_lstm_params(params["cell"])
        hs = lstm_seq(wx, wh, b, x, interpret=True)
        return jnp.sum(jnp.tanh(hs[:, -1]) ** 2) + 0.1 * jnp.sum(hs)

    gp_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(variables["params"], x)
    gp_pal, gx_pal = jax.grad(loss_pal, argnums=(0, 1))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(gx_pal), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)
    # tree_util spelling: jax.tree.leaves_with_path only exists on newer jax.
    flat_ref = jax.tree_util.tree_leaves_with_path(gp_ref)
    flat_pal = dict(jax.tree_util.tree_leaves_with_path(gp_pal))
    for path, leaf in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_pal[path]), np.asarray(leaf),
            rtol=1e-4, atol=1e-5, err_msg=str(path))


def test_batch_padding_path():
    """B not a multiple of 8 exercises the pad+slice path; padded rows must
    not contaminate gradients."""
    B, T, E, H = 5, 4, 3, 4
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(B, T, E)).astype(np.float32))
    cell = nn.RNN(nn.OptimizedLSTMCell(H))
    variables = cell.init(jax.random.key(0), x)
    wx, wh, b = pack_lstm_params(variables["params"]["cell"])
    ref = cell.apply(variables, x)
    got = lstm_seq(wx, wh, b, x, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # gradient wrt inputs and packed weights with a padded batch must match
    # the flax reference exactly — padded rows contribute nothing
    def loss_pal(wx_, x_):
        return jnp.sum(lstm_seq(wx_, wh, b, x_, interpret=True) ** 2)

    def loss_ref(params, x_):
        return jnp.sum(cell.apply({"params": params}, x_) ** 2)

    gw_pal, gx_pal = jax.grad(loss_pal, argnums=(0, 1))(wx, x)
    gp_ref, gx_ref = jax.grad(loss_ref, argnums=(0, 1))(variables["params"], x)
    from distkeras_tpu.ops.pallas.lstm import GATES
    gw_ref = jnp.concatenate(
        [gp_ref["cell"]["i" + g]["kernel"] for g in GATES], axis=1)
    np.testing.assert_allclose(np.asarray(gx_pal), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_pal), np.asarray(gw_ref),
                               rtol=1e-4, atol=1e-5)


def test_lstm_classifier_pallas_impl_trains():
    from distkeras_tpu.models.lstm import imdb_lstm

    model = imdb_lstm(vocab_size=50, embed_dim=8, hidden_size=8, seq_len=6,
                      cell_impl="pallas")
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 50, size=(4, 6)).astype(np.int32))
    out = model.predict(tokens)
    assert out.shape == (4, 2)
    assert np.all(np.isfinite(np.asarray(out)))
