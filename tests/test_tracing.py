"""Distributed-tracing tests (ISSUE 14): context propagation (ambient /
cross-thread / wire-header), the NTP-style clock estimator, the flight
recorder's ring + dump paths, the multi-stream collector (alignment,
generations, span dedup), the critical-path analysis + report CLI, and
the live loopback integrations — a traced PSClient commit yields one
complete cross-process trace, an untraced peer is sent zero new header
keys, and ``stats``/``scrape`` return a live snapshot over the wire."""

import json
import os
import threading
import time

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.telemetry import tracing
from distkeras_tpu.telemetry.tracing import analysis, clock, recorder
from distkeras_tpu.telemetry.tracing import context as trace_context
from distkeras_tpu.telemetry.tracing.context import SPAN_KIND


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("DKTPU_TRACE", "DKTPU_TRACE_DIR", "DKTPU_TRACE_ROLE",
                "DKTPU_TELEMETRY_ROTATE_MB"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    trace_context._reset_stream()
    recorder._reset()
    clock.reset()
    tracing.set_role("")
    yield
    trace_context._reset_stream()
    recorder._reset()
    clock.reset()
    tracing.set_role("")
    telemetry.reset()


def _on(monkeypatch, trace_dir=None):
    monkeypatch.setenv("DKTPU_TRACE", "1")
    if trace_dir is not None:
        monkeypatch.setenv("DKTPU_TRACE_DIR", str(trace_dir))


def _spans():
    return [e for e in telemetry.get().events()
            if e.get("kind") == SPAN_KIND]


# -- context ----------------------------------------------------------------

def test_trace_scope_roots_and_nests(monkeypatch):
    _on(monkeypatch)
    with tracing.trace_scope("commit", wid=3) as root:
        assert tracing.current() == root
        with tracing.trace_scope("commit.encode") as child:
            assert child.trace == root.trace
            assert child.span != root.span
    spans = {s["name"]: s for s in _spans()}
    assert set(spans) == {"commit", "commit.encode"}
    assert spans["commit.encode"]["parent"] == root.span
    assert "parent" not in spans["commit"]
    assert spans["commit"]["wid"] == 3
    assert spans["commit"]["dur"] >= spans["commit.encode"]["dur"]


def test_tracing_off_is_a_noop():
    with tracing.trace_scope("commit") as ctx:
        assert ctx is None
        assert tracing.wire_fields() == {}
    assert _spans() == []
    assert tracing.header_ctx({"trace": "abc"}) is None


def test_child_scope_never_roots_an_orphan(monkeypatch):
    _on(monkeypatch)
    with tracing.child_scope("commit.fold") as ctx:
        assert ctx is None
    assert _spans() == []
    with tracing.trace_scope("commit"):
        with tracing.child_scope("commit.fold") as ctx:
            assert ctx is not None
    assert {s["name"] for s in _spans()} == {"commit", "commit.fold"}


def test_adopt_crosses_threads(monkeypatch):
    _on(monkeypatch)
    seen = {}

    def stripe(ctx):
        with tracing.adopt(ctx):
            with tracing.child_scope("commit.wire", shard=1) as c:
                seen["ctx"] = c

    with tracing.trace_scope("commit") as root:
        t = threading.Thread(target=stripe, args=(tracing.current(),))
        t.start()
        t.join()
    assert seen["ctx"].trace == root.trace
    wire_span = next(s for s in _spans() if s["name"] == "commit.wire")
    assert wire_span["trace"] == root.trace
    assert wire_span["parent"] == root.span


def test_wire_fields_header_ctx_round_trip(monkeypatch):
    _on(monkeypatch)
    with tracing.trace_scope("commit") as root:
        header = dict({"op": "commit"}, **tracing.wire_fields())
        assert header["trace"] == root.trace
        assert header["parent"] == root.span
    ctx = tracing.header_ctx(header)
    assert ctx == tracing.TraceContext(root.trace, root.span)
    assert tracing.header_ctx({"op": "commit"}) is None


def test_emit_records_pretimed_child(monkeypatch):
    _on(monkeypatch)
    ctx = tracing.TraceContext("t" * 16, "p" * 16)
    tracing.emit("commit.queue", ctx, 123.0, 0.25, wid=1)
    tracing.emit("ignored", None, 0.0, 0.0)
    (span,) = _spans()
    assert (span["trace"], span["parent"]) == (ctx.trace, ctx.span)
    assert span["t0"] == 123.0 and span["dur"] == 0.25


# -- clock ------------------------------------------------------------------

def test_clock_offset_and_min_rtt_wins():
    # Client sends at ct0=0, server stamps 10/10, client receives at 1:
    # offset = ((10-0)+(10-1))/2 = 9.5, rtt = 1.
    clock.observe(0.0, 10.0, 10.0, 1.0)
    assert clock.offset() == pytest.approx(9.5)
    assert clock.rtt() == pytest.approx(1.0)
    # A higher-rtt (worse) sample must not displace the estimate.
    clock.observe(0.0, 50.0, 50.0, 4.0)
    assert clock.offset() == pytest.approx(9.5)
    # A lower-rtt (better) one does: ((20-0)+(20-0.5))/2 = 19.75.
    clock.observe(0.0, 20.0, 20.0, 0.5)
    assert clock.offset() == pytest.approx(19.75)
    assert clock.rtt() == pytest.approx(0.5)


def test_observe_reply_ignores_clockless_replies():
    clock.observe_reply(0.0, {"ok": True}, 1.0)
    assert clock.offset() == 0.0 and clock.rtt() is None
    clock.observe_reply(0.0, {"st1": 5.0, "st2": 5.0}, 1.0)
    assert clock.offset() == pytest.approx(4.5)


# -- flight recorder --------------------------------------------------------

def test_ring_feeds_from_events_and_dump_dedups(monkeypatch, tmp_path):
    _on(monkeypatch, tmp_path)
    tracing.set_role("ps")
    with tracing.trace_scope("commit"):
        pass
    telemetry.event("fault_injected", {"fault": "ps_crash", "at": 3})
    ring = tracing.ring_head(8)
    assert [r.get("kind") for r in ring][-1] == "fault_injected"
    path = tracing.flight_dump("fault:ps_crash")
    assert path is not None and os.path.basename(path).startswith(
        "flight-ps-")
    assert tracing.flight_dump("fault:ps_crash") is None, "per-reason dedup"
    recs = [json.loads(line) for line in open(path)]
    kinds = [r.get("kind") for r in recs]
    assert kinds[0] == tracing.PROCESS_INFO_KIND
    assert kinds[1] == "flight_dump"
    assert recs[1]["reason"] == "fault:ps_crash"
    assert any(k == "fault_injected" for k in kinds)


def test_flight_dump_noop_when_off(tmp_path, monkeypatch):
    monkeypatch.setenv("DKTPU_TRACE_DIR", str(tmp_path))
    telemetry.event("something", {})
    assert tracing.flight_dump("sigterm") is None
    assert list(tmp_path.iterdir()) == []


def test_ring_is_bounded(monkeypatch):
    _on(monkeypatch)
    r = tracing.FlightRecorder(size=4)
    for i in range(10):
        r.record({"i": i})
    assert [x["i"] for x in r.head(99)] == [6, 7, 8, 9]


# -- stream + rotation ------------------------------------------------------

def test_trace_stream_rotates_into_generations(monkeypatch, tmp_path):
    _on(monkeypatch, tmp_path)
    tracing.set_role("ps")
    monkeypatch.setenv("DKTPU_TELEMETRY_ROTATE_MB", "0.0002")  # ~210 bytes
    for _ in range(12):
        with tracing.trace_scope("commit"):
            pass
    base = os.path.join(str(tmp_path), f"trace-ps-{os.getpid()}.jsonl")
    gens = tracing.generations(base)
    assert len(gens) > 1, "tiny bound must have rotated at least once"
    assert gens[-1] == base and gens[0] == base + ".1"
    # The collector folds every generation back into one stream, keeping
    # all 12 roots exactly once.
    recs = tracing.TelemetryCollector([base]).records()
    roots = [r for r in recs if r.get("name") == "commit"]
    assert len(roots) == 12


# -- collector --------------------------------------------------------------

def _write_stream(path, role, offset, spans, rtt=0.001, extra=()):
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": tracing.PROCESS_INFO_KIND, "ts": 0.0, "host": "h",
            "pid": 1 if role == "worker" else 2, "role": role,
            "boot_id": "b", "clock_offset_s": offset,
            "clock_rtt_s": rtt}) + "\n")
        for rec in list(spans) + list(extra):
            f.write(json.dumps(rec) + "\n")


def test_collector_aligns_stamps_and_dedups(tmp_path, monkeypatch):
    _on(monkeypatch)
    span = {"kind": SPAN_KIND, "name": "commit", "trace": "t1",
            "span": "s1", "t0": 100.0, "dur": 0.5, "ts": 100.0}
    srv = {"kind": SPAN_KIND, "name": "commit.fold", "trace": "t1",
           "span": "s2", "parent": "s1", "t0": 95.2, "dur": 0.1,
           "ts": 95.2}
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    # The worker stream carries the commit span twice at two paths in
    # real life (event dump + trace stream) — model that with the same
    # span in both files.
    _write_stream(a, "worker", 0.0, [span])
    _write_stream(b, "ps", 5.0, [srv, dict(span)])
    recs = tracing.TelemetryCollector([a, b]).records()
    spans = [r for r in recs if r.get("kind") == SPAN_KIND]
    assert len(spans) == 2, "(trace, span) dedup keeps exactly one copy"
    fold = next(r for r in spans if r["name"] == "commit.fold")
    assert fold["t0"] == pytest.approx(100.2), "offset aligned onto t0"
    assert fold["role"] == "ps" and fold["stream"] == "b.jsonl"
    # Aligned ordering: the commit root (100.0) precedes the fold (100.2).
    assert [s["name"] for s in spans] == ["commit", "commit.fold"]


def test_collector_tolerates_torn_tail(tmp_path, monkeypatch):
    _on(monkeypatch)
    p = str(tmp_path / "t.jsonl")
    _write_stream(p, "ps", 0.0, [{"kind": SPAN_KIND, "name": "commit",
                                  "trace": "t", "span": "s", "t0": 1.0,
                                  "dur": 0.1, "ts": 1.0}])
    with open(p, "a") as f:
        f.write('{"kind": "trace_span", "trunc')  # SIGKILL mid-append
    recs = tracing.TelemetryCollector([p]).records()
    assert sum(r.get("kind") == SPAN_KIND for r in recs) == 1


# -- critical-path analysis -------------------------------------------------

def _commit_trace(tid, t0, segs, wid=0, seq=0):
    """Synthetic spans for one commit trace: a root + one child per
    (segment span name, dur)."""
    root_dur = max(0.001, sum(d for _n, d in segs) + 0.001)
    out = [{"kind": SPAN_KIND, "name": "commit", "trace": tid,
            "span": f"{tid}-r", "t0": t0, "dur": root_dur, "ts": t0,
            "wid": wid, "seq": seq}]
    for i, (name, dur) in enumerate(segs):
        out.append({"kind": SPAN_KIND, "name": name, "trace": tid,
                    "span": f"{tid}-{i}", "parent": f"{tid}-r",
                    "t0": t0 + 0.0001 * i, "dur": dur, "ts": t0})
    return out


_FULL = [("commit.encode", 0.001), ("commit.wire", 0.004),
         ("commit.queue", 0.0005), ("commit.fold", 0.002),
         ("commit.ack", 0.0002)]


def test_trace_report_completeness_is_config_aware():
    # Memory-only run: no fsync/replicate spans anywhere -> not required.
    recs = _commit_trace("aa", 1.0, _FULL) + _commit_trace("bb", 2.0, _FULL)
    rep = analysis.trace_report(recs)
    assert rep["commits"] == 2 and rep["complete"] == 2
    assert rep["completeness"] == 1.0
    assert "fsync" not in rep["required"]
    # A journaled run (any fsync span in the stream) raises the bar: the
    # trace missing its fsync is now incomplete.
    recs = (_commit_trace("aa", 1.0, _FULL + [("commit.fsync", 0.003)])
            + _commit_trace("bb", 2.0, _FULL))
    rep = analysis.trace_report(recs)
    assert "fsync" in rep["required"]
    assert (rep["commits"], rep["complete"]) == (2, 1)


def test_trace_report_segment_quantiles_and_stripe_max():
    # A striped commit: two parallel commit.wire spans — the segment must
    # take the slower stripe (the one the client actually waited on),
    # never the sum.
    recs = _commit_trace("aa", 1.0, _FULL)
    recs.append({"kind": SPAN_KIND, "name": "commit.wire", "trace": "aa",
                 "span": "aa-w2", "parent": "aa-r", "t0": 1.0,
                 "dur": 0.010, "ts": 1.0})
    rep = analysis.trace_report(recs)
    assert rep["segments"]["wire"]["max_s"] == pytest.approx(0.010)
    assert rep["segments"]["wire"]["count"] == 1  # one trace, one sample
    ex = rep["slowest"][0]
    assert ex["segments"]["wire"] == pytest.approx(0.010)


def test_trace_report_flags_orphans_and_skew():
    # An orphan: server-side fold span whose client half never arrived.
    orphan = [{"kind": SPAN_KIND, "name": "commit.fold", "trace": "dead",
               "span": "x", "parent": "gone", "t0": 5.0, "dur": 0.1,
               "ts": 5.0}]
    # A skewed trace: child starts 1s BEFORE its root after alignment.
    skewed = _commit_trace("sk", 10.0, _FULL)
    skewed[1]["t0"] = 9.0
    rep = analysis.trace_report(orphan + skewed)
    assert rep["orphans"] == ["dead"]
    assert rep["skew_violations"] == 1


def test_trace_report_correlates_chaos_with_slow_tail():
    recs = []
    for i in range(60):
        recs.extend(_commit_trace(f"t{i:02d}", float(i), _FULL))
    slow = _commit_trace("slow", 100.0, [("commit.encode", 0.001),
                                         ("commit.wire", 3.0),
                                         ("commit.queue", 0.0005),
                                         ("commit.fold", 0.002),
                                         ("commit.ack", 0.0002)])
    recs.extend(slow)
    recs.append({"kind": "fault_injected", "ts": 100.5,
                 "fault": "ps_crash", "at": 20, "role": "ps"})
    recs.append({"kind": "fault_injected", "ts": 500.0,
                 "fault": "stall", "at": 7, "role": "worker"})
    rep = analysis.trace_report(recs)
    by_detail = {c["detail"]: c for c in rep["chaos"]}
    assert by_detail["ps_crash"]["slow_traces"] == ["slow"]
    assert by_detail["stall"]["slow_traces"] == []
    text = analysis.render_trace_report(rep)
    assert "Chaos correlation" in text and "ps_crash" in text


def test_render_trace_report_sections():
    recs = _commit_trace("aa", 1.0, _FULL)
    text = analysis.render_trace_report(analysis.trace_report(recs))
    assert "Critical path" in text
    for seg in ("encode", "wire", "queue", "fold", "ack"):
        assert seg in text
    assert "complete: 1 (100.0%)" in text


# -- loopback integration ---------------------------------------------------

def _loopback(tmp_path, monkeypatch, **server_kw):
    from distkeras_tpu.netps.client import PSClient
    from distkeras_tpu.netps.server import PSServer

    _on(monkeypatch, tmp_path)
    srv = PSServer(discipline="adag", host="127.0.0.1", port=0,
                   **server_kw).start()
    client = PSClient(srv.endpoint, worker_id=0)
    return srv, client


def test_traced_commit_yields_complete_cross_process_trace(
        tmp_path, monkeypatch):
    srv, client = _loopback(tmp_path, monkeypatch,
                            state_dir=str(tmp_path / "state"))
    tmpl = [np.zeros((4, 3), np.float32)]
    try:
        client.join(init=tmpl)
        for i in range(3):
            client.commit([np.ones_like(a) for a in tmpl], i)
        client.leave()
    finally:
        srv.close()
    recs = tracing.TelemetryCollector.from_dir(str(tmp_path)).records()
    rep = analysis.trace_report(recs)
    assert rep["commits"] == 3
    assert rep["complete"] == 3, "every segment incl. fsync must appear"
    assert "fsync" in rep["required"]
    assert rep["orphans"] == [] and rep["skew_violations"] == 0


def test_untraced_peer_gets_zero_new_header_keys(tmp_path, monkeypatch):
    from distkeras_tpu.netps import wire

    srv, client = _loopback(tmp_path, monkeypatch)
    sent = []
    real_send = wire.send_frame

    def spy(sock, kind, header, arrays):
        if kind == wire.KIND_REQUEST:
            sent.append(dict(header))
        return real_send(sock, kind, header, arrays)

    tmpl = [np.zeros((2, 2), np.float32)]
    try:
        client.join(init=tmpl)
        # Simulate a pre-tracing peer: it never advertised the bit.
        client.peer_caps = {k: v for k, v in client.peer_caps.items()
                            if k != "tracing"}
        monkeypatch.setattr(wire, "send_frame", spy)
        client.commit([np.ones_like(a) for a in tmpl], 0)
        client.heartbeat()
        client.pull()
    finally:
        monkeypatch.setattr(wire, "send_frame", real_send)
        srv.close()
    assert sent, "spy must have seen the traced-side requests"
    for header in sent:
        for key in ("trace", "parent", "ct0"):
            assert key not in header, (
                f"{key!r} leaked to a peer without CAPS['tracing']")
    # And the server, never handed a context, emitted no server spans.
    recs = tracing.TelemetryCollector.from_dir(str(tmp_path)).records()
    names = {r.get("name") for r in recs if r.get("kind") == SPAN_KIND}
    assert "commit.queue" not in names and "commit.fold" not in names


def test_clock_estimate_rides_join_and_heartbeat(tmp_path, monkeypatch):
    srv, client = _loopback(tmp_path, monkeypatch)
    try:
        client.join(init=[np.zeros((2,), np.float32)])
        client.heartbeat()
    finally:
        srv.close()
    assert clock.rtt() is not None and clock.rtt() < 5.0
    assert abs(clock.offset()) < 5.0, "same host: offset must be tiny"


def test_stats_op_returns_live_snapshot_and_ring(tmp_path, monkeypatch):
    srv, client = _loopback(tmp_path, monkeypatch)
    try:
        client.join(init=[np.zeros((2,), np.float32)])
        client.commit([np.ones((2,), np.float32)], 0)
        hdr = client.stats(ring=16)
    finally:
        srv.close()
    assert hdr["ok"] is True
    assert hdr["caps"].get("tracing") is True
    assert hdr["commits_total"] == 1
    assert "counters" in hdr["snapshot"]
    assert any(r.get("kind") == SPAN_KIND for r in hdr["ring"]), (
        "the ring head must carry the commit's server-side spans")


def test_scrape_cli_needs_no_membership(tmp_path, monkeypatch, capsys):
    from distkeras_tpu.telemetry.report import main, scrape_stats

    srv, client = _loopback(tmp_path, monkeypatch)
    try:
        client.join(init=[np.zeros((2,), np.float32)])
        client.commit([np.ones((2,), np.float32)], 0)
        # The function: a raw socket, no join, no worker id.
        hdr = scrape_stats(srv.endpoint, ring=8)
        assert hdr["ok"] is True and hdr["commits_total"] == 1
        # The CLI wrapper prints it as JSON.
        assert main(["scrape", srv.endpoint, "--ring", "4"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["commits_total"] == 1
    finally:
        srv.close()


def test_report_cli_trace_over_merged_dir(tmp_path, monkeypatch, capsys):
    from distkeras_tpu.telemetry.report import main

    srv, client = _loopback(tmp_path, monkeypatch)
    try:
        client.join(init=[np.zeros((2,), np.float32)])
        for i in range(2):
            client.commit([np.ones((2,), np.float32)], i)
        client.leave()
    finally:
        srv.close()
    assert main(["report", str(tmp_path), "--trace"]) == 0
    text = capsys.readouterr().out
    assert "Critical path" in text and "commit traces: 2" in text
    assert main(["report", str(tmp_path), "--trace", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["commits"] == 2 and rep["complete"] == 2


def test_standby_replicate_span_joins_commit_trace(tmp_path, monkeypatch):
    from distkeras_tpu.netps.standby import StandbyServer

    srv, client = _loopback(tmp_path, monkeypatch,
                            state_dir=str(tmp_path / "state"))
    stb = StandbyServer(srv.endpoint, promote_after=30.0, host="127.0.0.1",
                        port=0, state_dir=str(tmp_path / "sb")).start()
    tmpl = [np.zeros((3,), np.float32)]
    try:
        client.join(init=tmpl)
        # Let the standby take its initial full sync first — commits a
        # snapshot absorbs wholesale carry no per-record trace ids, so
        # only incremental tailing produces replicate spans.
        deadline = time.monotonic() + 10.0
        while (stb.snapshot_syncs < 1
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert stb.snapshot_syncs >= 1
        for i in range(4):
            client.commit([np.ones_like(a) for a in tmpl], i)
        deadline = time.monotonic() + 10.0
        while stb.replicated < 4 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert stb.replicated >= 4
    finally:
        stb.close()
        srv.close()
        client.close()
    recs = tracing.TelemetryCollector.from_dir(str(tmp_path)).records()
    rep = analysis.trace_report(recs)
    assert "replicate" in rep["required"]
    assert rep["complete"] == 4, (
        "each commit trace must carry its standby replicate span")


def test_served_request_traces_end_to_end(tmp_path, monkeypatch):
    import flax.linen as nn

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.serving import (ModelRegistry, ServeClient,
                                       ServingFrontend)

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(x)

    _on(monkeypatch, tmp_path)
    model = Model.build(Tiny(), np.zeros((2, 4), np.float32))
    registry = ModelRegistry(model, (1, 4))
    frontend = ServingFrontend(registry, max_wait_s=0.002).start()
    sc = ServeClient(frontend.endpoint, timeout=5.0, retries=3,
                     backoff=0.01)
    try:
        for _ in range(3):
            out, version = sc.infer(np.ones((2, 4), np.float32))
            assert out.shape == (2, 3)
    finally:
        sc.close()
        frontend.close()
    spans = _spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert len(by_name["serve.request"]) == 3
    assert len(by_name["serve.queue"]) == 3
    assert len(by_name["serve.batch"]) == 3
    roots = {s["trace"] for s in by_name["serve.request"]}
    assert {s["trace"] for s in by_name["serve.queue"]} == roots, (
        "server-side queue spans must join the client's traces")
    rep = analysis.trace_report(spans)
    assert rep["serves"] == 3 and rep["orphans"] == []


# -- merged multi-process report sections -----------------------------------

def test_report_sections_over_collector_merged_streams(tmp_path):
    """fleet/serving/shards/tuner report sections built from a
    collector-merged multi-process stream, not a single registry."""
    from distkeras_tpu.telemetry.core import Telemetry
    from distkeras_tpu.telemetry.exporters import write_jsonl
    from distkeras_tpu.telemetry.report import build_report

    # "Scheduler" process: fleet attribution counters + round span.
    t1 = Telemetry()
    t1.counter("fleet.commits.acme.train").add(40)
    t1.counter("fleet.preemptions.acme.train").add(2)
    with t1.span("fleet.round.acme.train"):
        time.sleep(0.002)
    write_jsonl(t1, str(tmp_path / "scheduler.jsonl"))
    # "Serving" process: request accounting + latency histogram.
    t2 = Telemetry()
    t2.counter("serving.accepted").add(9)
    t2.counter("serving.answered").add(9)
    t2.histogram("serving.latency").observe(0.004)
    write_jsonl(t2, str(tmp_path / "serving.jsonl"))
    # "Shard" process: per-shard fold/byte counters + plan gauges.
    t3 = Telemetry()
    for k in range(2):
        t3.counter(f"netps.shard.folds.{k}").add(10 + k)
        t3.counter(f"netps.shard.bytes.{k}").add(1000)
    t3.gauge("netps.shard.count").set(2.0)
    t3.gauge("netps.shard.skew").set(1.01)
    write_jsonl(t3, str(tmp_path / "shard.jsonl"))
    # "Worker" process: tuner decision + run summary events.
    t4 = Telemetry()
    t4.event("tuner_decision", {"knob": "codec", "from": "none",
                                "to": "int8", "trigger": "wire_share",
                                "round": 12})
    t4.event("tuner_run_summary", {"inflight": 2, "codec": "int8",
                                   "shards": 2, "transport": "tcp",
                                   "retunes": 1, "fallbacks": 0,
                                   "deferred": 0})
    write_jsonl(t4, str(tmp_path / "worker.jsonl"))

    merged = str(tmp_path / "merged.jsonl")
    n = tracing.TelemetryCollector.from_dir(str(tmp_path)).write(merged)
    assert n > 0
    rep = build_report(merged)
    assert rep["fleet"] and rep["fleet"][0]["tenant"] == "acme"
    assert rep["fleet"][0]["commits"] == 40
    assert rep["serving"]["accepted"] == 9
    assert rep["serving"]["latency_count"] == 1
    assert rep["shards"]["per_shard_folds"] == [10.0, 11.0]
    assert rep["shards"]["plan_skew"] == pytest.approx(1.01)
    assert rep["tuner"]["decisions"][0]["knob"] == "codec"
    assert rep["tuner"]["converged"]["codec"] == "int8"
