"""Worker script for the 2-process distributed-ingest + sharded-predict test.

Each process (a) streams ITS OWN row range into the store via a ``part=k``
:class:`ShardWriter` — the Spark-executor-parallel write — after which process
0 splices the parts with ``merge_manifests``; then (b) runs the multi-process
out-of-core predict: disjoint shard ranges, process-local forward, manifest
committed by every process behind a global barrier. Results land in
``$DK_OUT/proc<i>.json`` for the parent test to cross-check against the
single-writer + single-process reference.

Run only via ``tests/test_multihost.py``.
"""

import json
import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    from jax.experimental import multihost_utils

    from distkeras_tpu.data.shards import (
        ShardWriter,
        ShardedDataFrame,
        merge_manifests,
    )
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import ClassPredictor
    from distkeras_tpu.runtime.mesh import distributed_initialize

    distributed_initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )
    pid, nproc = jax.process_index(), jax.process_count()

    # The same deterministic blobs the parent test generates (seed 0).
    rng = np.random.default_rng(0)
    n, d, c = 512, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n).astype(np.int32)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)

    # (a) Distributed ingest: process p streams rows [p*n/P, (p+1)*n/P) in
    # ragged 50-row chunks (exercises cross-chunk shard buffering).
    store_dir = os.path.join(os.environ["DK_OUT"], "store")
    lo, hi = pid * n // nproc, (pid + 1) * n // nproc
    with ShardWriter(store_dir, rows_per_shard=64, part=pid) as w:
        for s in range(lo, hi, 50):
            e = min(s + 50, hi)
            w.append(features=x[s:e], label=y[s:e])
    multihost_utils.sync_global_devices("dk_test_ingest_done")
    if pid == 0:
        merge_manifests(store_dir)
    multihost_utils.sync_global_devices("dk_test_merged")

    # (b) Multi-process sharded predict over the merged store.
    sdf = ShardedDataFrame(store_dir)
    model = Model.build(MLP(hidden=(16,), num_outputs=c),
                        np.zeros((1, d), np.float32), seed=0)
    out = ClassPredictor(model, output_col="pred", chunk_size=64).predict(sdf)

    # Predict AGAIN into the same column: exercises the agreed fresh
    # versioned physical name across processes.
    out = ClassPredictor(model, output_col="pred", chunk_size=64).predict(out)

    preds = np.concatenate(
        [ch["pred"] for ch in out.iter_column_chunks("pred")])
    feats = np.concatenate(
        [ch["features"] for ch in out.iter_column_chunks("features")])
    res = {
        "process": pid,
        "num_rows": int(sdf.count()),
        "shard_rows": list(out.store.manifest["shard_rows"]),
        "pred_file": out.store.columns["pred"].get("file", "pred"),
        "preds": [int(v) for v in preds],
        "features_ok": bool(np.array_equal(feats, x)),
    }
    with open(os.path.join(os.environ["DK_OUT"], f"proc{pid}.json"), "w") as f:
        json.dump(res, f)
    print(f"proc {pid}: ingest+predict ok, {len(preds)} predictions")


if __name__ == "__main__":
    main()
