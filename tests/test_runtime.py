"""Core runtime tests: mesh construction, model serialization round-trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import (
    DATA_AXIS,
    data_mesh,
    deserialize_model,
    hybrid_mesh,
    serialize_model,
)
from distkeras_tpu.models import mnist_mlp, mnist_cnn
from distkeras_tpu.models.base import uniform_weights


def test_virtual_mesh_has_8_devices():
    assert jax.device_count() == 8  # conftest forced the CPU mesh


def test_data_mesh_num_workers():
    mesh = data_mesh(num_workers=4)
    assert mesh.shape == {DATA_AXIS: 4}
    full = data_mesh()
    assert full.shape == {DATA_AXIS: 8}


def test_data_mesh_too_many_workers():
    with pytest.raises(ValueError):
        data_mesh(num_workers=99)


def test_hybrid_mesh_inference():
    mesh = hybrid_mesh({"data": -1, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}


def test_model_serialization_roundtrip():
    model = mnist_mlp(hidden=(16, 8))
    blob = model.serialize()
    assert isinstance(blob, bytes)
    restored = deserialize_model(blob)
    assert type(restored.module).__name__ == "MLP"
    assert restored.module.hidden == (16, 8)
    x = jnp.ones((2, 784), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(model.predict(x)), np.asarray(restored.predict(x)), rtol=1e-6
    )


def test_serialized_model_predicts_after_reinit():
    model = mnist_cnn()
    restored = deserialize_model(serialize_model(model))
    x = jnp.ones((2, 28, 28, 1), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(model.predict(x)), np.asarray(restored.predict(x)), rtol=1e-6
    )


def test_uniform_weights_bounds():
    model = mnist_mlp(hidden=(8,))
    model = uniform_weights(model, bounds=(-0.1, 0.1), seed=1)
    for leaf in jax.tree.leaves(model.params):
        arr = np.asarray(leaf)
        assert arr.min() >= -0.1 and arr.max() <= 0.1


def test_num_params_counts():
    model = mnist_mlp(hidden=(16,))
    # 784*16 + 16 + 16*10 + 10
    assert model.num_params == 784 * 16 + 16 + 16 * 10 + 10
