"""Fault-matrix tests for the resilience subsystem (ISSUE 2).

Every recovery path is exercised against a *deterministically injected*
fault, not asserted: NaN/Inf rounds against the on-device round skip,
feeder stalls/errors against the watchdog + stage retry, corrupt and
sidecar-less checkpoints against the fallback restore, in-process crashes
against the Supervisor's retry-with-resume, and host failures against
``Job``'s SIGTERM→SIGKILL escalation, wait-expiry teardown, per-host
restart, and straggler kill.
"""

import os
import subprocess
import time
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu import ADAG, DataFrame, Supervisor, resilience, telemetry
from distkeras_tpu.data.prefetch import RoundFeeder
from distkeras_tpu.job_deployment import Job, Punchcard
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.resilience import FaultPlan
from distkeras_tpu.resilience import integrity
from distkeras_tpu.resilience.errors import FeederStalledError, InjectedFault

N, DIM, C = 1024, 4, 3
#: ADAG config: 4 workers x window 4 x batch 16 over 1024 rows x 3 epochs
#: = 12 fold rounds — enough room for the r=3 / r=5 / r=7 fault schedule.
COMMON = dict(loss="sparse_categorical_crossentropy", batch_size=16,
              num_epoch=3, learning_rate=0.1, num_workers=4,
              communication_window=4)
NUM_ROUNDS = 12


@pytest.fixture(autouse=True)
def _fault_hygiene(monkeypatch):
    """Fresh ambient fault-plan state per test; no env leakage."""
    for var in ("DKTPU_FAULTS", "DKTPU_FAULTS_STATE", "DKTPU_NAN_GUARD",
                "DKTPU_FEEDER_TIMEOUT", "DKTPU_FEEDER_WARN",
                "DKTPU_FEEDER_RETRIES", "DKTPU_DIVERGENCE_RESET"):
        monkeypatch.delenv(var, raising=False)
    resilience.reset()
    yield
    resilience.reset()


def blob_df(seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(C, DIM))
    y = rng.integers(0, C, size=N)
    x = centers[y] + rng.normal(scale=0.5, size=(N, DIM))
    return DataFrame({"features": x.astype(np.float32),
                      "label": y.astype(np.int32)})


def tiny_model(seed=0):
    return Model.build(MLP(hidden=(16,), num_outputs=C),
                       jnp.zeros((1, DIM), jnp.float32), seed=seed)


def accuracy(model, df):
    logits = np.asarray(model.predict(jnp.asarray(df["features"])))
    return float((logits.argmax(-1) == df["label"]).mean())


def counter(name):
    return telemetry.get().counter(name).value


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------

def test_fault_plan_parse_and_one_shot():
    plan = FaultPlan.parse("nan@3;stall@5:0.25;crash@7;kill@9;seed=11")
    assert plan.seed == 11
    assert plan.batch_fault(2) is None
    assert plan.batch_fault(3) == "nan"
    assert plan.batch_fault(3) is None  # one-shot: never re-fires
    assert plan.feeder_stall(5) == 0.25
    assert plan.feeder_stall(5) == 0.0
    assert plan.crash(7) and not plan.crash(7)
    assert plan.kill(9) is True  # query only; nobody dies here
    # seeded worker choice is deterministic
    assert plan.poison_worker(3, 4) == FaultPlan.parse(
        "nan@3;seed=11").poison_worker(3, 4)


def test_fault_plan_rejects_bad_specs():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("frobnicate@3")
    with pytest.raises(ValueError, match="expected kind@round"):
        FaultPlan.parse("nan3")


def test_fault_plan_state_file_survives_restart(tmp_path):
    state = str(tmp_path / "fired")
    plan = FaultPlan.parse("kill@7", state_file=state)
    assert plan.kill(7) is True
    # a "restarted process" re-parses the same spec + state file
    plan2 = FaultPlan.parse("kill@7", state_file=state)
    assert plan2.kill(7) is False


# ---------------------------------------------------------------------------
# NaN/Inf guard (on-device round skip)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["nan", "inf"])
def test_poisoned_round_skipped_accuracy_parity(monkeypatch, kind):
    df = blob_df()
    clean = ADAG(tiny_model(), **COMMON)
    acc_clean = accuracy(clean.train(df, shuffle=True), df)

    resilience.reset()
    monkeypatch.setenv("DKTPU_FAULTS", f"{kind}@2")
    before = counter("resilience.nonfinite_rounds")
    t = ADAG(tiny_model(), **COMMON)
    trained = t.train(df, shuffle=True)
    h = t.get_history()
    # the poisoned round is visible in the history...
    assert not np.isfinite(h[2]), h
    # ...but the state skipped it: training continues and converges
    assert np.isfinite(h[3:]).all(), h
    acc = accuracy(trained, df)
    assert acc > 0.85 and abs(acc - acc_clean) < 0.05, (acc, acc_clean)
    assert counter("resilience.nonfinite_rounds") - before >= 1


def test_nan_guard_disabled_poisons_the_run(monkeypatch):
    """The counterfactual: without the guard, one worker's NaN round
    contaminates the psum'd center forever — proof the guard is load-bearing,
    not decorative."""
    monkeypatch.setenv("DKTPU_NAN_GUARD", "0")
    monkeypatch.setenv("DKTPU_FAULTS", "nan@1")
    t = ADAG(tiny_model(), **COMMON)
    t.train(blob_df(), shuffle=True)
    h = t.get_history()
    assert np.isfinite(h[0])
    assert not np.isfinite(h[1:]).any(), h


def test_blocked_mode_poisoned_round_also_skipped(monkeypatch):
    """rounds_per_program > 1: the fault lands inside a compiled block and
    the in-scan guard still skips exactly that round."""
    monkeypatch.setenv("DKTPU_FAULTS", "nan@2")
    t = ADAG(tiny_model(), rounds_per_program=4, **COMMON)
    trained = t.train(blob_df(), shuffle=True)
    h = t.get_history()
    assert not np.isfinite(h[2]) and np.isfinite(h[3:]).all(), h
    assert accuracy(trained, blob_df()) > 0.85


# ---------------------------------------------------------------------------
# Feeder: stall watchdog + stage retry
# ---------------------------------------------------------------------------

def test_feeder_stall_watchdog_warns(monkeypatch):
    monkeypatch.setenv("DKTPU_FAULTS", "stall@1:0.4")
    before = counter("resilience.feeder_stall_warnings")
    feeder = RoundFeeder(3, lambda r: r, stall_warn=0.05, stall_timeout=10.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = [r for r, _ in feeder]
    assert got == [0, 1, 2]
    assert counter("resilience.feeder_stall_warnings") - before >= 1


def test_feeder_stall_timeout_declares_pipeline_dead():
    def stage(r):
        if r == 1:
            time.sleep(2.0)
        return r

    feeder = RoundFeeder(3, stage, stall_warn=0.05, stall_timeout=0.3)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(FeederStalledError, match="stall_timeout"):
            list(feeder)
    feeder.close()


def test_feeder_error_retry_recovers(monkeypatch):
    monkeypatch.setenv("DKTPU_FAULTS", "feeder_error@1")
    before = counter("resilience.feeder_retries")
    feeder = RoundFeeder(3, lambda r: r, stage_retries=1)
    got = [r for r, _ in feeder]
    assert got == [0, 1, 2]  # the one-shot fault consumed by the retry
    assert counter("resilience.feeder_retries") - before == 1


def test_feeder_persistent_error_still_propagates():
    def stage(r):
        if r == 1:
            raise ValueError("disk on fire")
        return r

    feeder = RoundFeeder(3, stage, stage_retries=2, retry_backoff_s=0.01)
    with pytest.raises(ValueError, match="disk on fire"):
        list(feeder)
    feeder.close()


# ---------------------------------------------------------------------------
# Checkpoint integrity + fallback
# ---------------------------------------------------------------------------

def test_tree_digest_detects_tamper():
    tree = {"w": np.arange(8, dtype=np.float32), "b": np.zeros(3)}
    digest = integrity.tree_digest(tree)
    assert integrity.matches(tree, digest)
    tampered = {"w": tree["w"].copy(), "b": tree["b"]}
    tampered["w"][3] += 1e-3
    assert not integrity.matches(tampered, digest)
    # dtype drift is damage too
    assert not integrity.matches(
        {"w": tree["w"].astype(np.float64), "b": tree["b"]}, digest)


def _train_with_checkpoints(tmp_path, **extra):
    df = blob_df()
    t = ADAG(tiny_model(), checkpoint_dir=str(tmp_path / "ck"),
             checkpoint_every=1, **COMMON, **extra)
    t.train(df, shuffle=True)
    return df, t


def test_corrupt_checkpoint_falls_back_to_previous_step(tmp_path,
                                                        monkeypatch):
    pytest.importorskip("orbax.checkpoint")
    # ckpt_corrupt@11 fires right after the final round's save lands.
    monkeypatch.setenv("DKTPU_FAULTS", f"ckpt_corrupt@{NUM_ROUNDS - 1}")
    df, _ = _train_with_checkpoints(tmp_path)
    resilience.reset()
    monkeypatch.delenv("DKTPU_FAULTS")

    before = counter("resilience.ckpt_fallback_steps")
    t2 = ADAG(tiny_model(), checkpoint_dir=str(tmp_path / "ck"),
              checkpoint_every=1, resume=True, **COMMON)
    with pytest.warns(UserWarning, match="falling back to the previous"):
        t2.train(df, shuffle=True)
    assert counter("resilience.ckpt_fallback_steps") - before >= 1
    # resumed from step 10 (round 10) -> exactly one round left to run
    assert len(t2.get_history()) == 1


def test_missing_meta_sidecar_falls_back_to_intact_step(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    df, _ = _train_with_checkpoints(tmp_path)
    from distkeras_tpu.checkpoint import Checkpointer

    latest = Checkpointer(str(tmp_path / "ck")).latest_step()
    os.remove(tmp_path / "ck" / "meta" / f"{latest}.json")

    t2 = ADAG(tiny_model(), checkpoint_dir=str(tmp_path / "ck"),
              checkpoint_every=1, resume=True, **COMMON)
    with pytest.warns(UserWarning, match="intact sidecar"):
        t2.train(df, shuffle=True)
    # resumed from the previous step's recorded round, not from scratch and
    # not from the raw latest step
    assert len(t2.get_history()) == NUM_ROUNDS - latest


# ---------------------------------------------------------------------------
# Supervisor: retry-with-resume
# ---------------------------------------------------------------------------

def test_supervisor_resumes_after_crash(tmp_path, monkeypatch):
    pytest.importorskip("orbax.checkpoint")
    monkeypatch.setenv("DKTPU_FAULTS", "crash@7")
    df = blob_df()
    before = counter("resilience.supervisor_retries")
    t = ADAG(tiny_model(), checkpoint_dir=str(tmp_path / "ck"),
             checkpoint_every=1, **COMMON)
    sup = Supervisor(t, max_retries=2, backoff_s=0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        trained = sup.train(df, shuffle=True)
    assert sup.attempts == 2
    assert counter("resilience.supervisor_retries") - before == 1
    assert accuracy(trained, df) > 0.85
    # the resumed attempt picked up mid-run, it did not replay from round 0
    assert len(t.get_history()) < NUM_ROUNDS


def test_supervisor_budget_is_bounded(monkeypatch):
    monkeypatch.setenv("DKTPU_FAULTS", "crash@0;crash@1")
    t = ADAG(tiny_model(), **COMMON)  # no checkpoint_dir: restart from 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sup = Supervisor(t, max_retries=1, backoff_s=0)
        with pytest.raises(InjectedFault):
            sup.train(blob_df(), shuffle=True)
    assert sup.attempts == 2


def test_supervised_fault_matrix_accuracy_parity(tmp_path, monkeypatch):
    """The acceptance scenario: NaN round at r=3, feeder stall at r=5, crash
    at r=7 — a supervised ADAG run completes, resumes from checkpoint within
    the retry budget, and final accuracy matches the fault-free run."""
    pytest.importorskip("orbax.checkpoint")
    df = blob_df()
    clean = ADAG(tiny_model(), **COMMON)
    acc_clean = accuracy(clean.train(df, shuffle=True), df)

    resilience.reset()
    monkeypatch.setenv("DKTPU_FAULTS", "nan@3;stall@5:0.2;crash@7")
    monkeypatch.setenv("DKTPU_FEEDER_WARN", "0.05")
    c0 = {k: counter(k) for k in ("resilience.nonfinite_rounds",
                                  "resilience.supervisor_retries",
                                  "resilience.faults_injected")}
    t = ADAG(tiny_model(), checkpoint_dir=str(tmp_path / "ck"),
             checkpoint_every=1, **COMMON)
    sup = Supervisor(t, max_retries=3, backoff_s=0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        trained = sup.train(df, shuffle=True)
    acc = accuracy(trained, df)
    assert acc > 0.85 and abs(acc - acc_clean) < 0.05, (acc, acc_clean)
    assert sup.attempts == 2  # one crash, one resume
    assert counter("resilience.nonfinite_rounds") - c0[
        "resilience.nonfinite_rounds"] >= 1
    # NOT asserted: feeder_stall_warnings. Whether the 0.2s stall surfaces
    # as a consumer-visible wait depends on how fast the run loop drains the
    # lookahead queue (a slow round hides the stall entirely — the
    # feed-overlap design working as intended). The watchdog's warning path
    # is covered deterministically by test_feeder_stall_watchdog_warns.
    assert counter("resilience.supervisor_retries") - c0[
        "resilience.supervisor_retries"] == 1
    assert counter("resilience.faults_injected") - c0[
        "resilience.faults_injected"] == 3


# ---------------------------------------------------------------------------
# Divergent-worker reset
# ---------------------------------------------------------------------------

def test_reset_workers_readopts_center():
    from distkeras_tpu.parallel.disciplines import ADAGFold
    from distkeras_tpu.parallel.engine import AsyncEngine
    from distkeras_tpu.runtime.mesh import data_mesh

    eng = AsyncEngine(tiny_model(), "sgd", "sparse_categorical_crossentropy",
                      ADAGFold(), data_mesh(num_workers=4), window=4)
    st = eng.init_state()
    drifted = st._replace(
        locals_=jax.tree.map(lambda a: a + 1.0, st.locals_))
    mask = np.array([True, False, False, False])
    st2 = eng.reset_workers(drifted, mask)
    for loc, cen in zip(jax.tree.leaves(jax.device_get(st2.locals_)),
                        jax.tree.leaves(jax.device_get(st2.center))):
        np.testing.assert_allclose(loc[0], cen)       # reset: re-adopted
        np.testing.assert_allclose(loc[1], cen + 1.0)  # untouched drift


def test_reset_workers_edge_masks():
    """Edge masks PR 2 never exercised: all-True re-adopts every worker,
    all-False is an exact no-op, and both behave on a single-worker mesh."""
    from distkeras_tpu.parallel.disciplines import ADAGFold
    from distkeras_tpu.parallel.engine import AsyncEngine
    from distkeras_tpu.runtime.mesh import data_mesh

    def leaves(tree):
        return jax.tree.leaves(jax.device_get(tree))

    for W in (4, 1):
        eng = AsyncEngine(tiny_model(), "sgd",
                          "sparse_categorical_crossentropy", ADAGFold(),
                          data_mesh(num_workers=W), window=4)
        st = eng.init_state()
        drifted = st._replace(
            locals_=jax.tree.map(lambda a: a + 1.0, st.locals_),
            opt_state=jax.tree.map(lambda a: a + 3.0, st.opt_state))
        # all-False: nothing moves — locals, optimizer state, center.
        noop = eng.reset_workers(drifted, np.zeros(W, bool))
        for field in ("locals_", "opt_state", "center"):
            for a, b in zip(leaves(getattr(noop, field)),
                            leaves(getattr(drifted, field))):
                np.testing.assert_array_equal(a, b)
        # all-True: every worker re-adopts the center with a fresh optimizer.
        fresh = eng.reset_workers(drifted, np.ones(W, bool))
        for loc, cen in zip(leaves(fresh.locals_), leaves(fresh.center)):
            for w in range(W):
                np.testing.assert_allclose(loc[w], cen)
        for opt, init in zip(leaves(fresh.opt_state),
                             leaves(jax.tree.map(
                                 lambda a: jnp.broadcast_to(
                                     a, (W,) + a.shape),
                                 eng.tx.init(jax.device_get(st.center))))):
            np.testing.assert_allclose(opt, init)
        # center and rng are untouched either way (the contract).
        for a, b in zip(leaves(fresh.center), leaves(drifted.center)):
            np.testing.assert_array_equal(a, b)
        # wrong-shaped mask is a loud error, not silent broadcasting.
        with pytest.raises(ValueError, match="worker_mask"):
            eng.reset_workers(drifted, np.ones(W + 1, bool))


def test_divergent_worker_reset_fires_on_poisoned_worker(monkeypatch):
    """One worker's loss goes non-finite (the round itself is skipped by the
    NaN guard); the divergence policy re-adopts the center for exactly that
    worker and training converges."""
    monkeypatch.setenv("DKTPU_FAULTS", "nan@2")
    before = counter("resilience.worker_resets")
    t = ADAG(tiny_model(), divergence_reset=1000.0, **COMMON)
    trained = t.train(blob_df(), shuffle=True)
    assert counter("resilience.worker_resets") - before == 1
    assert accuracy(trained, blob_df()) > 0.85


# ---------------------------------------------------------------------------
# Restart backoff: full jitter (shared with the netps client)
# ---------------------------------------------------------------------------

def test_full_jitter_bounds_and_decorrelation():
    """The shared retry-delay rule: every draw lands in [0, cap) where cap
    is the exponential envelope min(max, base * 2**attempt) — and the draws
    actually vary (that is the anti-restart-storm point)."""
    from distkeras_tpu.resilience.backoff import backoff_cap, full_jitter

    rng = np.random.default_rng(0)
    for attempt in range(8):
        cap = backoff_cap(0.5, attempt, max_s=10.0)
        assert cap == min(10.0, 0.5 * 2 ** attempt)
        draws = [full_jitter(0.5, attempt, max_s=10.0, rng=rng)
                 for _ in range(200)]
        assert all(0.0 <= d < cap for d in draws), (attempt, min(draws),
                                                    max(draws), cap)
        # Decorrelated: the herd must not sleep in lockstep.
        assert np.std(draws) > 0.05 * cap
    # Degenerate bases short-circuit to zero (tests use backoff 0).
    assert full_jitter(0.0, 3) == 0.0
    assert backoff_cap(0.0, 3) == 0.0
    # Supervisor and Job.supervise draw from this same rule.
    import inspect

    from distkeras_tpu import job_deployment
    from distkeras_tpu.resilience import supervisor
    assert "full_jitter" in inspect.getsource(supervisor.Supervisor.train)
    assert "full_jitter" in inspect.getsource(job_deployment.Job.supervise)


# ---------------------------------------------------------------------------
# Job: kill escalation, wait teardown, restart, stragglers
# ---------------------------------------------------------------------------

def _job(script, tmp_path, hosts=1, args=()):
    return Job(Punchcard(job_name="resilience-test", script=str(script),
                         hosts=["localhost"] * hosts, args=list(args)))


def _wait_for(path, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.02)
    return False


def test_job_kill_escalates_for_sigterm_ignorers(tmp_path):
    script = tmp_path / "stubborn.py"
    script.write_text(
        "import signal, sys, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "open(sys.argv[1], 'w').write('up')\n"
        "time.sleep(60)\n")
    ready = tmp_path / "ready"
    job = _job(script, tmp_path, args=[str(ready)])
    job.launch(dry_run=False)
    assert _wait_for(ready), "child never came up"
    t0 = time.monotonic()
    job.kill(grace=0.5)
    assert time.monotonic() - t0 < 10.0
    assert job.poll() == [-9]  # SIGTERM ignored -> escalated to SIGKILL


def test_job_wait_timeout_kills_stragglers(tmp_path):
    script = tmp_path / "sleeper.py"
    script.write_text("import time\ntime.sleep(60)\n")
    job = _job(script, tmp_path)
    job.launch(dry_run=False)
    with pytest.raises(subprocess.TimeoutExpired):
        job.wait(timeout=0.5)
    # the expired wait tore the straggler down instead of leaving it running
    assert all(rc is not None for rc in job.poll())


def test_job_supervise_restarts_failed_host(tmp_path):
    script = tmp_path / "flaky.py"
    script.write_text(
        "import sys\n"
        "from pathlib import Path\n"
        "marker, done = Path(sys.argv[1]), Path(sys.argv[2])\n"
        "if not marker.exists():\n"
        "    marker.write_text('x'); sys.exit(1)\n"
        "done.write_text('done'); sys.exit(0)\n")
    marker, done = tmp_path / "marker", tmp_path / "done"
    job = _job(script, tmp_path, args=[str(marker), str(done)])
    job.launch(dry_run=False)
    rcs = job.supervise(timeout=60, max_restarts=1, restart_backoff=0.01)
    assert rcs == [0]
    assert job.restarts == [1]
    assert done.exists()


def test_job_supervise_kills_stragglers(tmp_path):
    script = tmp_path / "skewed.py"
    script.write_text(
        "import os, time\n"
        "if os.environ.get('JAX_PROCESS_ID') != '0':\n"
        "    time.sleep(60)\n")
    job = _job(script, tmp_path, hosts=2)
    job.launch(dry_run=False)
    t0 = time.monotonic()
    rcs = job.supervise(timeout=60, straggler_timeout=0.5)
    assert time.monotonic() - t0 < 30.0
    assert rcs[0] == 0 and rcs[1] not in (None, 0), rcs


# ---------------------------------------------------------------------------
# Telemetry JSONL crash tolerance (exporters satellite)
# ---------------------------------------------------------------------------

def test_read_jsonl_tolerates_truncated_tail(tmp_path):
    from distkeras_tpu.telemetry.exporters import read_jsonl

    path = tmp_path / "run.jsonl"
    path.write_text('{"round": 0, "loss": 1.0}\n'
                    '{"round": 1, "loss": 0.5}\n'
                    '{"round": 2, "lo')  # killed mid-append
    assert len(read_jsonl(str(path))) == 2
    # strict mode still tolerates the torn tail...
    assert len(read_jsonl(str(path), strict=True)) == 2
    # ...but an interior malformed line is real damage
    path.write_text('{"round": 0}\nGARBAGE\n{"round": 1}\n')
    with pytest.warns(UserWarning, match="malformed interior"):
        assert len(read_jsonl(str(path))) == 2
    with pytest.raises(ValueError, match="malformed JSONL"):
        read_jsonl(str(path), strict=True)
