"""Model zoo shape/forward tests (tiny sizes — CI runs on a 2-core CPU)."""

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models import (
    small_transformer_lm,
)
from distkeras_tpu.models.cnn import cifar10_cnn, mnist_cnn
from distkeras_tpu.models.lstm import imdb_lstm
from distkeras_tpu.models.mlp import mnist_mlp
from distkeras_tpu.models.resnet import tiny_resnet


def test_mlp_forward():
    m = mnist_mlp(hidden=(16,))
    out = m.predict(jnp.ones((4, 784)))
    assert out.shape == (4, 10)


def test_cnn_forward():
    m = mnist_cnn()
    assert m.predict(jnp.ones((2, 28, 28, 1))).shape == (2, 10)
    m = cifar10_cnn()
    assert m.predict(jnp.ones((2, 32, 32, 3))).shape == (2, 10)


def test_lstm_forward():
    m = imdb_lstm(vocab_size=50, embed_dim=8, hidden_size=8, seq_len=12)
    tokens = jnp.zeros((3, 12), jnp.int32)
    assert m.predict(tokens).shape == (3, 2)


def test_resnet_forward():
    m = tiny_resnet()
    assert m.predict(jnp.ones((2, 32, 32, 3))).shape == (2, 10)


def test_transformer_forward_and_causality():
    m = small_transformer_lm(vocab_size=64, num_layers=1, d_model=32, num_heads=2,
                             d_ff=64, max_seq_len=32, seq_len=16)
    tokens = jnp.zeros((2, 16), jnp.int32)
    out = m.predict(tokens)
    assert out.shape == (2, 16, 64)
    # Causality: changing a late token must not affect early logits.
    t2 = tokens.at[:, 10].set(5)
    out2 = m.predict(t2)
    np.testing.assert_allclose(np.asarray(out[:, :10]), np.asarray(out2[:, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(out[:, 10:]), np.asarray(out2[:, 10:]))
