"""Model zoo shape/forward tests (tiny sizes — CI runs on a 2-core CPU)."""

import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models import (
    small_transformer_lm,
)
from distkeras_tpu.models.cnn import cifar10_cnn, mnist_cnn
from distkeras_tpu.models.lstm import imdb_lstm
from distkeras_tpu.models.mlp import mnist_mlp
from distkeras_tpu.models.resnet import tiny_resnet


def test_mlp_forward():
    m = mnist_mlp(hidden=(16,))
    out = m.predict(jnp.ones((4, 784)))
    assert out.shape == (4, 10)


def test_cnn_forward():
    m = mnist_cnn()
    assert m.predict(jnp.ones((2, 28, 28, 1))).shape == (2, 10)
    m = cifar10_cnn()
    assert m.predict(jnp.ones((2, 32, 32, 3))).shape == (2, 10)


def test_lstm_forward():
    m = imdb_lstm(vocab_size=50, embed_dim=8, hidden_size=8, seq_len=12)
    tokens = jnp.zeros((3, 12), jnp.int32)
    assert m.predict(tokens).shape == (3, 2)


def test_resnet_forward():
    m = tiny_resnet()
    assert m.predict(jnp.ones((2, 32, 32, 3))).shape == (2, 10)


def test_resnet_legacy_param_remap():
    """A pre-round-3 auto-named param tree remaps onto the explicit
    stage{i}_block{j}/GN_k layout and predicts identically."""
    from distkeras_tpu.models.resnet import (
        detect_legacy_layout, remap_legacy_params)

    m = tiny_resnet()  # stage_sizes=(1, 1)
    order = ["stage0_block0", "stage1_block0"]

    def to_legacy(params):  # inverse of the rename, for test fixture only
        out = {}
        for k, v in params.items():
            if k in order:
                out[f"BottleneckBlock_{order.index(k)}"] = {
                    ik.replace("GN_", "GroupNorm_", 1): iv
                    for ik, iv in v.items()}
            elif k.startswith("GN_"):
                out[k.replace("GN_", "GroupNorm_", 1)] = v
            else:
                out[k] = v
        return out

    legacy = to_legacy(m.params)
    assert detect_legacy_layout(legacy) and not detect_legacy_layout(m.params)
    remapped = remap_legacy_params(legacy, stage_sizes=(1, 1))
    assert jax_tree_equal(remapped, m.params)
    x = jnp.ones((2, 32, 32, 3))
    np.testing.assert_array_equal(
        np.asarray(m.with_params(remapped).predict(x)),
        np.asarray(m.predict(x)))


def jax_tree_equal(a, b) -> bool:
    import jax

    if jax.tree.structure(a) != jax.tree.structure(b):
        return False
    return all(np.array_equal(x, y)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_transformer_remat_training_step_matches_dense():
    """remat=True must be a pure memory/FLOPs trade: identical forward AND
    identical one-step SGD update (jax.checkpoint recomputes, never changes
    math)."""
    import jax
    import optax

    from distkeras_tpu.models.transformer import TransformerLM
    from distkeras_tpu.models import Model
    from distkeras_tpu.ops.losses import get_loss

    arch = dict(vocab_size=64, num_layers=2, d_model=32, num_heads=2, d_ff=64,
                max_seq_len=16)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)),
                         jnp.int32)
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1), jnp.int32)
    base = Model.build(TransformerLM(**arch), jnp.zeros((1, 16), jnp.int32))
    loss_fn = get_loss("sparse_categorical_crossentropy")
    tx = optax.sgd(0.1)

    def one_step(module):
        def loss_of(p):
            return loss_fn(module.apply({"params": p}, tokens, train=True,
                                        rngs={"dropout": jax.random.key(0)}),
                           targets)

        loss, grads = jax.jit(jax.value_and_grad(loss_of))(base.params)
        updates, _ = tx.update(grads, tx.init(base.params), base.params)
        return loss, optax.apply_updates(base.params, updates)

    loss_d, params_d = one_step(TransformerLM(**arch))
    loss_r, params_r = one_step(TransformerLM(**arch, remat=True))
    np.testing.assert_allclose(float(loss_d), float(loss_r), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(params_d), jax.tree.leaves(params_r)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_transformer_forward_and_causality():
    m = small_transformer_lm(vocab_size=64, num_layers=1, d_model=32, num_heads=2,
                             d_ff=64, max_seq_len=32, seq_len=16)
    tokens = jnp.zeros((2, 16), jnp.int32)
    out = m.predict(tokens)
    assert out.shape == (2, 16, 64)
    # Causality: changing a late token must not affect early logits.
    t2 = tokens.at[:, 10].set(5)
    out2 = m.predict(t2)
    np.testing.assert_allclose(np.asarray(out[:, :10]), np.asarray(out2[:, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(out[:, 10:]), np.asarray(out2[:, 10:]))
