"""CI health-chaos smoke (not a pytest module — run directly).

The fleet health plane watching a small fleet while chaos happens to it,
then the same fleet fault-free as a false-positive control:

**Faulted leg** — 2 in-process serving replicas under ``serve_slow``
(three 0.35 s reply holds among ~40 requests) and 1 parameter-server
subprocess carrying ``ps_crash@8`` in its own fault plan, all scraped by
one :class:`MetricsHub` with a page-severity p99 SLO:

* the **p99 SLO alert fires within one fast window** of the holds (the
  windowed span-diff quantile sees them; both burn windows confirm);
* the page alert **drops a flight-recorder dump** whose reason names it;
* the SIGKILLed PS flips to ``target_down`` within one fast window of
  the crash, and the alert **CLEARS** after a babysitter relaunches the
  server on the same port (clear hysteresis: two calm sweeps);
* ``telemetry health --json`` against the recovered fleet exits 0.

**Control leg** — the identical fleet, SLOs, and load with zero faults:
the run must end with **zero alerts fired** (a sentinel that cries wolf
is worse than none).

    python tests/smoke_health_chaos.py

All seeds and fault indices are pinned, so reruns schedule the same
chaos.
"""

import os
import sys

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.append(_REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("DKTPU_NET_TIMEOUT", "1.0")
os.environ.setdefault("DKTPU_NET_RETRIES", "3")
os.environ.setdefault("DKTPU_NET_BACKOFF", "0.02")
# Trace on: the page alert must prove it dumped the flight ring.
os.environ.setdefault("DKTPU_TRACE", "1")

import glob  # noqa: E402
import json  # noqa: E402
import socket  # noqa: E402
import subprocess  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402

#: serving requests per leg, and the (pinned) global request indices the
#: frontend holds for HOLD_S — 3/40 > 1%, so the windowed p99 sees them.
REQUESTS = 40
SLOW_AT = (10, 14, 18)
HOLD_S = 0.35

#: the PS subprocess's own plan: SIGKILL just before folding commit 8.
PS_CRASH_AT = 8
PS_COMMITS = 16

SLO_SPECS = [
    {"name": "serve-p99", "metric": "serving.latency", "stat": "p99",
     "max": 0.08, "fast_s": 2.0, "slow_s": 4.0, "severity": "page",
     "target": "serve*", "labels": {"tenant": "acme", "job": "serve"}},
]

HUB_INTERVAL = 0.2
DOWN_AFTER = 2


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_ps(port: int, state_dir: str, faults: str = ""):
    """One PS subprocess with ITS OWN fault plan (never the smoke's)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("DKTPU_NET_FAULTS", "DKTPU_FAULTS_STATE")}
    env["JAX_PLATFORMS"] = "cpu"
    # The smoke chdirs to a scratch dir; the child must still import the
    # checkout.
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if faults:
        env["DKTPU_NET_FAULTS"] = faults
    return subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.netps", "--host", "127.0.0.1",
         "--port", str(port), "--discipline", "adag", "--lease", "2.0",
         "--state-dir", state_dir], env=env)


def _wait(predicate, timeout: float, what: str) -> float:
    t0 = time.monotonic()
    while not predicate():
        elapsed = time.monotonic() - t0
        assert elapsed < timeout, f"timed out after {timeout}s: {what}"
        time.sleep(0.05)
    return time.monotonic() - t0


def _build_fleet(trace_dir: str, ps_faults: str):
    """(replica set, ps proc, ps endpoint, hub, engine, alerts)."""
    from flax import linen as nn

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.serving import ServingReplicaSet
    from distkeras_tpu.telemetry.health import (AlertManager, MetricsHub,
                                                Sentinels, SloEngine,
                                                parse_slo_specs,
                                                register_target)

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

    model = Model.build(MLP(), np.zeros((2, 4), np.float32), seed=0)
    rs = ServingReplicaSet(model, n=2, buckets=(1, 4),
                           max_wait_s=0.003).start()  # registers serve0/1
    port = _free_port()
    state_dir = tempfile.mkdtemp(prefix="dktpu-health-ps-")
    proc = _launch_ps(port, state_dir, faults=ps_faults)
    endpoint = f"127.0.0.1:{port}"
    register_target(endpoint, "ps")

    alerts = AlertManager(clear_after=2)
    engine = SloEngine(parse_slo_specs(json.dumps(SLO_SPECS)),
                       alerts=alerts)
    # Hermetic bench paths: the repo's own BENCH_* files are not under
    # test here, and the control leg pins zero alerts.
    sentinels = Sentinels(
        alerts=alerts,
        bench_summary=os.path.join(trace_dir, "no-summary.json"),
        bench_pin=os.path.join(trace_dir, "no-pin.json"))
    hub = MetricsHub(interval=HUB_INTERVAL, down_after=DOWN_AFTER,
                     timeout=0.5)
    hub.on_sweep(engine.evaluate)
    hub.on_sweep(sentinels.evaluate)
    hub.start()
    return rs, proc, endpoint, hub, engine, alerts


def _drive_load(rs, endpoint: str) -> tuple:
    """The two tenants' load: serving inference + PS training commits.
    Returns (answered, commits_before_crash_or_done)."""
    from distkeras_tpu.netps.client import PSClient
    from distkeras_tpu.serving import ServeClient

    client = ServeClient(rs.endpoints(), timeout=3.0, retries=3,
                         backoff=0.02)
    rng = np.random.default_rng(11)
    answered = 0
    for _ in range(REQUESTS):
        rows = int(rng.integers(1, 5))
        out, _v = client.infer(
            rng.standard_normal((rows, 4)).astype(np.float32))
        assert out.shape == (rows, 3)
        answered += 1
    client.close()

    ps = PSClient(endpoint, worker_id=0)
    tmpl = [np.zeros((4,), np.float32)]
    commits = 0
    try:
        ps.join(init=tmpl)
        for i in range(PS_COMMITS):
            ps.commit([np.ones_like(a) for a in tmpl], i)
            commits += 1
            time.sleep(0.02)
        ps.leave()
    except Exception:
        pass  # ps_crash mid-commit: the crash is the point
    finally:
        try:
            ps.close()
        except Exception:
            pass
    return answered, commits


def _teardown(rs, proc, hub) -> None:
    hub.close()
    rs.close()
    if proc.poll() is None:
        proc.terminate()
        proc.wait(timeout=10.0)


def faulted_leg(trace_dir: str) -> None:
    from distkeras_tpu import telemetry
    from distkeras_tpu.serving.frontend import reset_request_index
    from distkeras_tpu.telemetry.report import main as report_main

    telemetry.reset()
    reset_request_index()
    rs, proc, endpoint, hub, engine, alerts = _build_fleet(
        trace_dir, ps_faults=f"ps_crash@{PS_CRASH_AT};seed=1")
    print(f"[smoke] faulted leg: replicas={rs.endpoints()} ps={endpoint} "
          f"faults={os.environ['DKTPU_NET_FAULTS']} + "
          f"ps_crash@{PS_CRASH_AT}")
    try:
        _wait(lambda: not hub.is_down(endpoint) and hub.target("ps")
              and hub.target("ps").ever_up, 15.0, "PS never came up")
        answered, commits = _drive_load(rs, endpoint)
        assert answered == REQUESTS, (answered, REQUESTS)
        assert commits >= PS_CRASH_AT - 1, (
            f"PS died too early: {commits} commits")

        # (1) The slow holds must page the p99 SLO within one fast window.
        lat = _wait(lambda: alerts.is_active("slo:serve-p99"),
                    SLO_SPECS[0]["fast_s"] + 3.0,
                    "p99 SLO alert never fired")
        print(f"[smoke] p99 page alert fired {lat:.2f}s after load "
              f"(fast window {SLO_SPECS[0]['fast_s']}s)")
        alert = alerts.active()["slo:serve-p99"]
        assert alert.severity == "page"
        assert alert.labels == {"tenant": "acme", "job": "serve"}

        # (2) The page alert dropped a flight dump naming itself.
        def page_dump():
            for path in glob.glob(os.path.join(trace_dir, "flight-*")):
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if rec.get("reason") == "health:slo:serve-p99":
                            return path
            return None

        _wait(lambda: page_dump() is not None, 10.0,
              "page alert left no flight dump")
        print(f"[smoke] flight dump for the page: {page_dump()}")

        # (3) The crashed PS flips to target_down within one fast window.
        _wait(lambda: proc.poll() is not None, 30.0,
              "ps_crash never killed the PS subprocess")
        t_crash = time.monotonic()
        _wait(lambda: alerts.is_active("target_down:ps"),
              DOWN_AFTER * HUB_INTERVAL + SLO_SPECS[0]["fast_s"] + 3.0,
              "target_down:ps never fired")
        det = time.monotonic() - t_crash
        assert hub.is_down("ps") and hub.is_down(endpoint)
        down = alerts.active()["target_down:ps"]
        assert down.severity == "page"
        print(f"[smoke] target_down:ps fired {det:.2f}s after the SIGKILL")

        # (4) The babysitter restarts the PS on the SAME port; the alert
        # clears after two calm sweeps, never by hand.
        port = int(endpoint.rsplit(":", 1)[1])
        state_dir = tempfile.mkdtemp(prefix="dktpu-health-ps2-")
        proc = _launch_ps(port, state_dir)
        _wait(lambda: not alerts.is_active("target_down:ps"), 30.0,
              "target_down:ps never cleared after the restart")
        assert not hub.is_down("ps")
        cleared = [e for e in telemetry.get().events()
                   if e.get("kind") == "health_clear"
                   and e.get("alert") == "target_down:ps"]
        assert cleared, "no health_clear event for the recovery"
        print("[smoke] target_down:ps CLEARED after babysitter restart")

        # (5) The operator CLI agrees with the in-process plane.
        hub.close()  # one reader at a time on the sockets
        rc = report_main(["health", "--targets",
                          f"ps={endpoint};{rs.endpoints()}",
                          "--samples", "2", "--gap", "0.3", "--json"])
        assert rc == 0, "recovered fleet must scrape healthy (exit 0)"
        fired = alerts.fired_total
        assert fired >= 2, f"expected p99 + target_down fires, saw {fired}"
    finally:
        _teardown(rs, proc, hub)


def control_leg(trace_dir: str) -> None:
    from distkeras_tpu import telemetry
    from distkeras_tpu.resilience import faults
    from distkeras_tpu.serving.frontend import reset_request_index

    os.environ.pop("DKTPU_NET_FAULTS", None)
    faults.set_net_plan(None)
    telemetry.reset()
    reset_request_index()
    rs, proc, endpoint, hub, engine, alerts = _build_fleet(trace_dir,
                                                           ps_faults="")
    print(f"[smoke] control leg: replicas={rs.endpoints()} ps={endpoint} "
          f"(no faults)")
    try:
        _wait(lambda: hub.target("ps") and hub.target("ps").ever_up,
              15.0, "PS never came up")
        answered, commits = _drive_load(rs, endpoint)
        assert answered == REQUESTS and commits == PS_COMMITS
        # Let both burn windows close over the healthy data.
        time.sleep(SLO_SPECS[0]["slow_s"] + 2 * HUB_INTERVAL)
        assert alerts.fired_total == 0, (
            f"fault-free control fired {alerts.fired_total} alert(s): "
            f"{[h for h in alerts.history if h['event'] == 'fired']}")
        assert not alerts.active()
        print(f"[smoke] control: {answered} requests, {commits} commits, "
              f"0 alerts")
    finally:
        _teardown(rs, proc, hub)


def main() -> int:
    trace_dir = tempfile.mkdtemp(prefix="dktpu-health-smoke-")
    # Scratch cwd: the CLI's sentinels read BENCH_* files relative to
    # cwd, and a checkout's real bench results must not leak in.
    os.chdir(trace_dir)
    os.environ.setdefault("DKTPU_TRACE_DIR", trace_dir)
    os.environ.setdefault(
        "DKTPU_NET_FAULTS",
        ";".join(f"serve_slow@{i}:{HOLD_S}" for i in SLOW_AT) + ";seed=7")
    faulted_leg(os.environ["DKTPU_TRACE_DIR"])
    control_leg(os.environ["DKTPU_TRACE_DIR"])
    print("[smoke] OK: p99 page within the fast window + flight dump, "
          "target_down fired and cleared across the PS restart, "
          "control leg fired zero alerts")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
