"""N-level aggregation trees (``netps.tree``): spec grammar, topology
math, partition ride-through with typed drops, standby promotion with
exactly-once journals, and the placement/launch rendering that puts the
gang on real hosts.

The depth-3 staleness parity and chaos-parity runs live in
``tests/test_netps.py``; the subprocess region-partition drill is the
``NETPS_SMOKE_TREE`` mode of ``tests/smoke_netps_chaos.py``.
"""

import time

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.netps.client import PSClient
from distkeras_tpu.netps.server import PSServer
from distkeras_tpu.netps import state as netps_state
from distkeras_tpu.netps.tree import (TreeNode, TreeSpec, TreeStandby,
                                      build_tree)
from distkeras_tpu.resilience import faults

FAST = dict(timeout=1.0, retries=3, backoff=0.01)


def _root(n=4, **kw):
    kw.setdefault("discipline", "adag")
    return PSServer(center=[np.zeros(n, np.float32)], **kw).start()


# ---------------------------------------------------------------------------
# TreeSpec: grammar + topology math
# ---------------------------------------------------------------------------

def test_tree_spec_parse_render_roundtrip():
    spec = TreeSpec.parse("host:8,pool:4,region:2:int8")
    assert spec.depth == 3
    assert [l.name for l in spec.levels] == ["host", "pool", "region"]
    assert [l.fanout for l in spec.levels] == [8, 4, 2]
    assert [l.codec for l in spec.levels] == [None, None, "int8"]
    assert spec.render() == "host:8,pool:4,region:2:int8"
    assert TreeSpec.parse(spec.render()) == spec
    # Whitespace and empty segments are tolerated (env-var ergonomics).
    assert TreeSpec.parse(" host:2 ,, region:2 ").render() == "host:2,region:2"


@pytest.mark.parametrize("bad", [
    "host",                  # no fanout
    "host:xyz",              # non-integer fanout
    "host:0",                # fanout < 1
    "host:2:zstd9",          # unknown codec
    "host:2,host:4",         # duplicate level name
    "9bad:2",                # bad level name
    "host:2:int8:extra",     # too many fields
    "",                      # no levels at all
])
def test_tree_spec_rejects_bad_grammar(bad):
    with pytest.raises(ValueError):
        TreeSpec.parse(bad)


def test_tree_spec_topology_math():
    spec = TreeSpec.parse("host:2,region:3")
    # group_of: contiguous, stride = prod(fanouts[:k+1]).
    assert [spec.group_of(r, 0) for r in range(6)] == [0, 0, 1, 1, 2, 2]
    assert [spec.group_of(r, 1) for r in range(6)] == [0, 0, 0, 0, 0, 0]
    assert spec.group_of(6, 1) == 1
    # nodes_at: ceil-divide, partial subtrees still get a node.
    assert spec.nodes_at(0, 6) == 3
    assert spec.nodes_at(0, 7) == 4
    assert spec.nodes_at(1, 6) == 1
    assert spec.nodes_at(1, 7) == 2
    # parent_group chains levels; the top interior level has no parent.
    assert spec.parent_group(0, 2) == 0
    assert spec.parent_group(0, 3) == 1
    with pytest.raises(ValueError):
        spec.parent_group(1, 0)


def test_tree_link_key_encoding():
    key = TreeSpec.link_key(2, 7)
    assert key == 2007
    assert TreeSpec.split_link_key(key) == (2, 7)
    assert TreeSpec.split_link_key(TreeSpec.link_key(0, 0)) == (0, 0)
    for level, group in [(-1, 0), (0, -1), (0, 1000)]:
        with pytest.raises(ValueError):
            TreeSpec.link_key(level, group)


# ---------------------------------------------------------------------------
# Partition ride-through: bounded buffer, typed drops, zero silent loss
# ---------------------------------------------------------------------------

def test_tree_partition_buffers_then_drops_typed():
    """A black-holed uplink buffers up to ``buffer_windows`` combined
    windows and degrades PAST the bound by counted, typed drops naming
    their constituents — never silent divergence, never deadlock — then
    drains the survivors in order on heal."""
    telemetry.reset()
    root = _root()
    node = None
    try:
        node = TreeNode(root.endpoint, level=0, group=0,
                        spec="region:2", fan_in=1, buffer_windows=3,
                        flush_interval=3600.0, probe_links=False,
                        **FAST).start()
        faults.set_net_plan(faults.FaultPlan.parse_net("link_down@0:2.5"))
        with PSClient(node.endpoint, **FAST) as c:
            c.join(init=[np.zeros(4, np.float32)])
            for _ in range(10):
                _, pulled = c.pull()
                c.commit([np.ones(4, np.float32)], pulled)
                node._flush_once(force=True)
            stats = c.stats()["tree"]  # the ledger rides the stats op
        assert stats["absorbed"] == 10
        assert stats["link_down"] is True
        assert stats["buffered_windows"] == 3
        assert stats["dropped_windows"] == 7
        assert stats["dropped_commits"] == 7
        assert stats["forwarded_commits"] == 0
        assert stats["silent_loss"] == 0

        # Heal: the buffered survivors drain, in order, exactly once.
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            node._flush_once(force=True)
            if node.tree_stats()["buffered_windows"] == 0:
                break
            time.sleep(0.1)
        stats = node.tree_stats()
        assert stats["buffered_windows"] == 0
        assert stats["forwarded_commits"] == 3
        assert stats["dropped_commits"] == 7
        assert stats["silent_loss"] == 0
        assert root.commits_total == 3

        # The drop event names every lost constituent (wid, seq).
        drops = [e for e in telemetry.get().events()
                 if e["kind"] == "netps_tree_window_drop"]
        assert drops, "no netps_tree_window_drop event emitted"
        assert all(e["reason"] == "buffer_overflow" for e in drops)
        pairs = [tuple(p) for e in drops for p in e["constituents"]]
        assert len(pairs) == 7
        assert len(set(pairs)) == 7
        downs = [e for e in telemetry.get().events()
                 if e["kind"] == "netps_tree_link_down"]
        assert downs and downs[0]["seconds"] == 2.5
    finally:
        faults.reset()
        if node is not None:
            node.close()
        root.close()
        telemetry.reset()


# ---------------------------------------------------------------------------
# Standby promotion: fence, re-parent, exactly-once journals
# ---------------------------------------------------------------------------

def test_tree_standby_promotes_fences_and_dedups(tmp_path):
    """Killing a region aggregator promotes its warm region-local
    standby: epoch bumps past the dead lineage, children re-parent via
    their ordinary endpoint walk, and no (wid, seq) ever folds twice in
    either lineage's journal."""
    telemetry.reset()
    root = _root(lease_s=30.0)
    node = standby = None
    try:
        node = TreeNode(root.endpoint, level=0, group=0, spec="region:2",
                        fan_in=1, flush_interval=0.05, lease_s=2.0,
                        state_dir=str(tmp_path / "node"),
                        probe_links=False, **FAST).start()
        standby = TreeStandby(node.endpoint, upstream=root.endpoint,
                              level=0, group=0, spec="region:2",
                              fan_in=1, flush_interval=0.05,
                              promote_after=0.6,
                              state_dir=str(tmp_path / "standby"),
                              probe_links=False, **FAST).start()
        served = f"{node.endpoint},{standby.endpoint}"
        with PSClient(served, timeout=1.0, retries=10, backoff=0.05) as c:
            c.join(init=[np.zeros(4, np.float32)])
            for _ in range(4):
                _, pulled = c.pull()
                c.commit([np.ones(4, np.float32)], pulled)
            deadline = time.monotonic() + 5.0
            while node.forwarded < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert node.forwarded >= 1, "primary never flushed upstream"

            # SIGKILL-equivalent: stop serving without any goodbye.
            node._stop.set()
            node._listener.close()
            deadline = time.monotonic() + 8.0
            while not standby.promoted and time.monotonic() < deadline:
                time.sleep(0.05)
            assert standby.promoted, "standby never promoted"
            assert standby.epoch >= 1

            for _ in range(4):  # the endpoint walk re-parents the child
                _, pulled = c.pull()
                c.commit([np.ones(4, np.float32)], pulled)
        deadline = time.monotonic() + 5.0
        while standby.forwarded < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert standby.absorbed >= 4
        assert standby.forwarded >= 1

        # Exactly-once evidence, per lineage journal.
        for label, sdir in (("node", tmp_path / "node"),
                            ("standby", tmp_path / "standby")):
            records = netps_state.read_journal(str(sdir))
            seen = set()
            last_epoch = -1
            for r in records:
                key = (int(r["wid"]), int(r["seq"]))
                assert key not in seen, f"{label}: {key} folded twice"
                seen.add(key)
                assert int(r["e"]) >= last_epoch
                last_epoch = int(r["e"])
        sb_records = netps_state.read_journal(str(tmp_path / "standby"))
        assert max(int(r["e"]) for r in sb_records) >= 1
        # The root saw both lineages' uplinks, each pair exactly once.
        seen = set()
        for wid, seq, _st in root.commit_log:
            assert (wid, seq) not in seen
            seen.add((wid, seq))
        assert standby.tree_stats()["silent_loss"] == 0
    finally:
        if standby is not None:
            standby.close()
        if node is not None:
            try:
                node.close()
            except Exception:
                pass
        root.close()
        telemetry.reset()


# ---------------------------------------------------------------------------
# In-process assembly
# ---------------------------------------------------------------------------

def test_build_tree_shape_and_leaf_routing():
    root = _root()
    tree = None
    try:
        tree = build_tree("host:2,region:2", root.endpoint, workers=4,
                          flush_interval=0.05, probe_links=False, **FAST)
        assert set(tree.nodes[0]) == {0, 1}
        assert set(tree.nodes[1]) == {0}
        # Leaves route to their own host-level node.
        assert tree.leaf_endpoint(0) == tree.node(0, 0).endpoint
        assert tree.leaf_endpoint(1) == tree.node(0, 0).endpoint
        assert tree.leaf_endpoint(2) == tree.node(0, 1).endpoint
        # Level-0 nodes flush into the region node, which flushes to root.
        assert tree.node(0, 0).upstream == tree.node(1, 0).endpoint
        assert tree.node(1, 0).upstream == root.endpoint
        # Caps advertise the tree coordinates to any client that dials in.
        with PSClient(tree.leaf_endpoint(0), **FAST) as c:
            c.join(init=[np.zeros(4, np.float32)])
            hdr = c.stats()["tree"]
            assert (hdr["level"], hdr["group"]) == (0, 0)
            assert hdr["spec"] == "host:2,region:2"
    finally:
        if tree is not None:
            tree.close()
        root.close()


# ---------------------------------------------------------------------------
# Gang placement + launch rendering
# ---------------------------------------------------------------------------

def test_place_tree_port0_plan_region_local_standbys():
    from distkeras_tpu.fleet.placement import place_tree

    plan = place_tree("host:2,region:2", workers=4,
                      hosts=["h0", "h1", "h2", "h3"],
                      root_endpoint="root:7077", reserve=False)
    n00, n01, n10 = plan.node(0, 0), plan.node(0, 1), plan.node(1, 0)
    # Each node on the FIRST host of its subtree, standby on the NEXT
    # distinct host of the SAME subtree — region-local by construction.
    assert (n00.host, n00.standby_host) == ("h0", "h1")
    assert (n01.host, n01.standby_host) == ("h2", "h3")
    assert (n10.host, n10.standby_host) == ("h0", "h1")
    assert all(n.port == 0 for n in plan)  # dry plan consumes no pool
    # Endpoint-complete: children dial the parent's failover list.
    assert n10.upstream == "root:7077"
    assert n00.upstream == n10.served_endpoint == "h0:0,h1:0"
    assert plan.leaf_endpoint(3) == n01.served_endpoint == "h2:0,h3:0"
    assert n00.link_key == TreeSpec.link_key(0, 0)
    assert sorted(plan.all_state_labels()) == sorted([
        "tree-L0-g0", "tree-L0-g0.standby",
        "tree-L0-g1", "tree-L0-g1.standby",
        "tree-L1-g0", "tree-L1-g0.standby"])


def test_place_tree_no_standbys_and_ring_fallback():
    from distkeras_tpu.fleet.placement import place_tree

    plan = place_tree("host:2", workers=2, hosts=["h0", "h1"],
                      root_endpoint="r:1", standbys=False, reserve=False)
    n = plan.node(0, 0)
    assert n.standby_host is None and n.standby_endpoint is None
    assert n.served_endpoint == n.endpoint  # no comma, nothing to walk
    # A 1-host subtree falls back to the ring neighbor for its standby.
    plan = place_tree("host:1,region:2", workers=2, hosts=["a", "b"],
                      root_endpoint="r:1", reserve=False)
    assert plan.node(0, 0).host == "a"
    assert plan.node(0, 0).standby_host == "b"
    # Callable reserve routes allocation through the caller.
    taken = []

    def take(host):
        taken.append(host)
        return 9000 + len(taken)

    plan = place_tree("host:2", workers=2, hosts=["h0", "h1"],
                      root_endpoint="r:1", reserve=take)
    assert plan.node(0, 0).port == 9001
    assert plan.node(0, 0).standby_port == 9002
    assert taken == ["h0", "h1"]


def test_punchcard_tree_plan_and_launch_lines():
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="tree-job", script="train.py",
                   hosts=["h0", "h1", "h2", "h3"], coordinator_port=8476,
                   ps={"tree": "host:2,region:2", "host": "h0",
                       "port": 7171, "discipline": "dynsgd",
                       "tree_buffer": 5, "state_dir": "/var/dk"})
    try:
        plan = pc.tree_plan()
        assert pc.tree_plan() is plan  # sticky, like every port pin
        assert all(n.port > 0 for n in plan)  # gang ports are real
        job = Job(pc)
        cmds = job.render_tree_commands()
        assert len(cmds) == 6  # 3 nodes + 3 standbys, standby after node
        assert all("python -m distkeras_tpu.netps" in c for c in cmds)
        assert all("--tree-spec host:2,region:2" in c for c in cmds)
        assert all("--tree-buffer 5" in c for c in cmds)
        assert sum("--standby " in c for c in cmds) == 3
        assert "--tree-level 0 --tree-group 0" in cmds[0]
        assert f"--upstream {plan.node(1, 0).served_endpoint}" in cmds[0]
        assert "--state-dir /var/dk/tree-L0-g0" in cmds[0]
        assert "--state-dir /var/dk/tree-L0-g0.standby" in cmds[1]
        # The top node flushes into the ROOT's endpoint, not another node.
        top = [c for c in cmds if "--tree-level 1" in c][0]
        assert "--upstream h0:7171" in top
        # Workers dial their OWN level-0 node and mirror the spec.
        worker_cmds = job.render_commands()
        assert f"DKTPU_PS_ENDPOINT={plan.leaf_endpoint(0)}" in worker_cmds[0]
        assert f"DKTPU_PS_ENDPOINT={plan.leaf_endpoint(2)}" in worker_cmds[2]
        assert "DKTPU_TREE_SPEC=host:2,region:2" in worker_cmds[0]
    finally:
        pc.release_ports()
