"""The networked parameter server: wire hardening, exactly-once commits,
lease-based elastic membership, graceful drain, and network-fault chaos.

The fast tests drive every guarded edge deterministically through the
in-process :class:`ChaosProxy`; the slow chaos-parity test trains the same
model/data through netps-over-loopback under injected network faults and
through the in-process raced PS, asserting final-accuracy parity at the
``test_raced_ps.py`` tolerance — the fold is literally the same function
(``netps/fold.py``), so the parity claim transfers transport-for-transport.
"""

import socket
import threading
import time

import numpy as np
import pytest

from distkeras_tpu.netps import (
    ChaosProxy,
    PSClient,
    PSServer,
    ProtocolError,
    RPCTimeoutError,
    ServerClosedError,
    ServerDrainingError,
    commit_scale,
    fold_delta,
)
from distkeras_tpu.netps import wire
from distkeras_tpu.resilience.faults import FaultPlan

FAST = dict(timeout=1.0, retries=3, backoff=0.01)


def make_server(**kw):
    kw.setdefault("discipline", "adag")
    return PSServer(**kw).start()


def leaves(*shapes):
    rng = np.random.default_rng(0)
    return [rng.normal(size=s).astype(np.float32) for s in shapes]


# ---------------------------------------------------------------------------
# Wire protocol hardening
# ---------------------------------------------------------------------------

def test_wire_roundtrip_header_and_arrays():
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.array(7, dtype=np.int64)]  # 0-d array too
    raw = wire.encode_frame(wire.KIND_REQUEST, {"op": "pull", "req": 3},
                            arrays)
    kind, header, out = wire.decode_frame(raw)
    assert kind == wire.KIND_REQUEST
    assert header["op"] == "pull" and header["req"] == 3
    np.testing.assert_array_equal(out[0], arrays[0])
    assert out[1] == 7


def test_wire_rejects_bad_magic_version_and_oversize():
    raw = wire.encode_frame(wire.KIND_REPLY, {"ok": True}, [])
    with pytest.raises(ProtocolError, match="magic"):
        wire.decode_frame(b"XX" + raw[2:])
    with pytest.raises(ProtocolError, match="version"):
        wire.decode_frame(raw[:2] + b"\x7f" + raw[3:])
    big = wire.encode_frame(wire.KIND_REPLY, {},
                            [np.zeros(1024, np.float32)])
    with pytest.raises(ProtocolError, match="exceeds"):
        wire.parse_prefix(big[:wire.PREFIX_SIZE], max_frame=64)


def test_wire_checksum_catches_corruption_and_truncation():
    raw = bytearray(wire.encode_frame(
        wire.KIND_REPLY, {"ok": True}, [np.ones(8, np.float32)]))
    raw[-2] ^= 0xFF  # bit-flip inside an array buffer
    with pytest.raises(ProtocolError, match="checksum"):
        wire.decode_frame(bytes(raw))
    whole = wire.encode_frame(wire.KIND_REPLY, {"ok": True},
                              [np.ones(8, np.float32)])
    with pytest.raises(ProtocolError):
        wire.decode_frame(whole[: len(whole) // 2])


def test_fold_is_shared_between_raced_and_networked_ps():
    """One fold function, two transports: the raced-parity evidence
    transfers because there is literally nothing transport-specific left
    to diverge."""
    import distkeras_tpu.racelab as racelab
    from distkeras_tpu.netps import fold as netfold

    assert racelab.fold_delta is netfold.fold_delta
    assert commit_scale("dynsgd", 3) == pytest.approx(0.25)
    assert commit_scale("adag", 3) == 1.0
    center = [np.zeros(4, np.float32)]
    fold_delta(center, [np.full(4, 2.0, np.float32)], "dynsgd", staleness=1)
    np.testing.assert_allclose(center[0], 1.0)


# ---------------------------------------------------------------------------
# The fast data plane: codecs, striping, zero-copy frames
# ---------------------------------------------------------------------------

def test_codec_roundtrip_properties():
    """bf16 = exact top-16-bit truncation; int8 = per-tensor scale with a
    bounded one-step error; non-f32 and non-finite tensors pass through."""
    rng = np.random.default_rng(0)
    a = rng.normal(size=(33, 5)).astype(np.float32)
    w16, ex = wire.codec_encode(a, "bf16")
    assert w16.dtype == np.uint16 and ex == {"codec": "bf16"}
    back = wire.codec_decode(w16, ex)
    # truncation error <= one bf16 ulp (2^-7 relative), elementwise
    assert (np.abs(back - a) <= np.abs(a) * 2.0 ** -7 + 1e-9).all()
    w8, ex8 = wire.codec_encode(a, "int8")
    assert w8.dtype == np.int8 and ex8["codec"] == "int8"
    back8 = wire.codec_decode(w8, ex8)
    assert np.abs(back8 - a).max() <= ex8["scale"] * 0.5 + 1e-7
    # integer tensors and non-finite tensors ship as-is
    ints = np.arange(4, dtype=np.int32)
    assert wire.codec_encode(ints, "int8")[1] == {}
    bad = np.array([np.nan, 1.0], np.float32)
    enc, ex = wire.codec_encode(bad, "int8")
    assert ex == {} and enc.dtype == np.float32
    # a codec'd frame decodes back to f32 transparently
    raw = wire.encode_frame(wire.KIND_REQUEST, {"op": "commit"},
                            [wire.codec_encode(a, "int8")])
    _k, _h, out = wire.decode_frame(raw)
    np.testing.assert_allclose(out[0], back8)


def test_zero_copy_send_frame_equals_encode_frame():
    """The sendmsg scatter-gather path must put the byte-identical frame on
    the wire that encode_frame builds (crc computed incrementally over the
    same views)."""
    import socket as _socket

    arrays = [np.arange(10, dtype=np.float32),
              np.array(3, np.int64),  # 0-d
              wire.codec_encode(np.ones(7, np.float32), "bf16")]
    expect = wire.encode_frame(wire.KIND_REQUEST, {"op": "x", "req": 9},
                               arrays)
    a, b = _socket.socketpair()
    try:
        n = wire.send_frame(a, wire.KIND_REQUEST, {"op": "x", "req": 9},
                            arrays)
        assert n == len(expect)
        got = wire.recv_exact(b, n)
        assert got == expect
        # Zero-size leaves carry no wire bytes: sendmsg must skip them
        # (a trailing empty view used to spin the advance loop forever)
        # and the decode side rebuilds them from the header's shape.
        empties = [np.ones(2, np.float32), np.zeros((0, 4), np.float32)]
        n2 = wire.send_frame(a, wire.KIND_REQUEST, {"op": "y", "req": 10},
                             empties)
        k2, h2, out2 = wire.read_frame(b)
        assert h2["req"] == 10 and out2[1].shape == (0, 4)
        np.testing.assert_array_equal(out2[0], empties[0])
        assert n2 == len(wire.encode_frame(
            wire.KIND_REQUEST, {"op": "y", "req": 10}, empties))
    finally:
        a.close()
        b.close()


def test_codec_negotiation_falls_back_on_capability_less_server(monkeypatch):
    """A PR 4 server never advertises caps: the client must speak the PR 4
    dialect (f32, one connection) no matter what was requested."""
    monkeypatch.setattr(wire, "CAPS", {})  # the server replies with this
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, shards=4,
                      compress="int8", **FAST) as c:
            init = leaves((8,), (3, 2))
            c.join(init=init)
            assert c.codec == "none" and c.active_shards == 1
            _, upd = c.pull()
            res = c.commit([np.ones_like(a) for a in init], upd)
            assert res.applied
            center, _ = c.pull()
            np.testing.assert_allclose(center[0], init[0] + 1.0)
    finally:
        srv.close()


def test_striped_pull_and_commit_match_unsharded():
    srv = make_server(discipline="downpour")
    try:
        init = leaves((40, 3), (7,), (2, 2), (90,))
        with PSClient(srv.endpoint, worker_id=0, shards=3, **FAST) as c:
            center, upd = c.join(init=init)
            assert c.active_shards == 3 and c._stripes is not None
            # stripes partition the tensor indices exactly
            flat = sorted(i for s in c._stripes for i in s)
            assert flat == list(range(len(init)))
            res = c.commit([np.full_like(a, 2.0) for a in init], upd)
            assert res.applied and res.staleness == 0
            striped_center, upd2 = c.pull()
        with PSClient(srv.endpoint, worker_id=1, **FAST) as plain:
            plain.join()
            plain_center, upd3 = plain.pull()
        assert upd2 == upd3 == 1
        for a, b, i in zip(striped_center, plain_center, init):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_allclose(a, i + 2.0)
        assert srv.commit_log == [(0, 0, 0)]
    finally:
        srv.close()


def test_striped_commit_with_dropped_ack_folds_exactly_once():
    """THE striping acceptance scenario: one logical commit striped over 2
    connections, one stripe's ACK lost — the retransmitted stripe must be
    answered by dedup/pending, and the commit folds EXACTLY once."""
    # frame 0 = join; frames 1,2 = the two commit stripes (either order).
    srv, px, c = chaos_pair("drop_r@2", timeout=0.4, retries=6, shards=2)
    try:
        init = [np.zeros(3, np.float32), np.zeros(5, np.float32)]
        _, upd = c.join(init=init)
        assert c.active_shards == 2
        res = c.commit([np.ones(3, np.float32), np.ones(5, np.float32)], upd)
        assert res.applied or res.duplicate
        assert srv.commit_log == [(0, 0, 0)], srv.commit_log
        np.testing.assert_allclose(srv.center()[0], 1.0)  # folded ONCE
        np.testing.assert_allclose(srv.center()[1], 1.0)
        assert not srv._pending  # nothing half-assembled left behind
    finally:
        c.close()
        px.close()
        srv.close()


def test_int8_error_feedback_residual_bounds_drift():
    """K identical commits under int8: WITH error feedback the accumulated
    center error stays within one quantization step (the residual carries
    each round's error into the next), instead of growing linearly."""
    K = 20
    base = (np.random.default_rng(3).normal(size=(64,)) * 0.01
            ).astype(np.float32)
    srv = make_server(discipline="downpour")
    try:
        with PSClient(srv.endpoint, worker_id=0, compress="int8",
                      **FAST) as c:
            _, upd = c.join(init=[np.zeros(64, np.float32)])
            assert c.codec == "int8"
            for _ in range(K):
                _, upd = c.pull()
                c.commit([base], upd)
            center, _ = c.pull()
        one_step = float(np.abs(base).max()) / 127.0
        drift = float(np.abs(center[0] - K * base).max())
        assert drift <= 1.5 * one_step, (drift, one_step)
    finally:
        srv.close()


def test_remote_overlap_inflight_trains_and_reports_hidden_fraction(
        monkeypatch):
    """DKTPU_NET_INFLIGHT=2 + compression + striping: the double-buffered
    worker loop converges, stays exactly-once, and exports the overlap
    hidden-fraction gauge and realized-staleness histogram."""
    from distkeras_tpu import ADAG, DataFrame, telemetry

    monkeypatch.setenv("DKTPU_NET_TIMEOUT", "2.0")
    monkeypatch.setenv("DKTPU_NET_INFLIGHT", "2")
    monkeypatch.setenv("DKTPU_NET_COMPRESS", "int8")
    monkeypatch.setenv("DKTPU_NET_SHARDS", "2")
    telemetry.reset()
    x, y = _blob_data()
    df = DataFrame({"features": x, "label": y})
    srv = make_server()
    try:
        t = ADAG(_mlp_model(), loss="sparse_categorical_crossentropy",
                 num_workers=2, batch_size=16, num_epoch=2,
                 learning_rate=0.1, communication_window=4,
                 remote=srv.endpoint)
        trained = t.train(df, shuffle=True)
        assert _acc(trained, x, y) > 0.85
        seen = set()
        for wid, seq, _st in srv.commit_log:
            assert (wid, seq) not in seen, f"({wid},{seq}) folded twice"
            seen.add((wid, seq))
        snap = telemetry.get().snapshot()
        assert "netps.overlap.hidden_fraction" in snap["gauges"]
        assert snap["spans"]["netps.commit.staleness"]["count"] > 0
        assert snap["counters"]["netps.bytes_precompress"] > 0
        # int8 deltas: commit bytes shrink vs the f32 pre-compression size
        # (pull replies are still f32, so compare the commit-side counter).
    finally:
        srv.close()
        telemetry.reset()


def test_int8_trains_to_parity_with_none(monkeypatch):
    """Acceptance: the int8+error-feedback path reaches final-accuracy
    parity with the uncompressed path at the raced-parity tolerance."""
    from distkeras_tpu import ADAG, DataFrame

    monkeypatch.setenv("DKTPU_NET_TIMEOUT", "2.0")
    x, y = _blob_data()
    df = DataFrame({"features": x, "label": y})
    accs = {}
    for codec in ("none", "int8"):
        monkeypatch.setenv("DKTPU_NET_COMPRESS", codec)
        srv = make_server()
        try:
            t = ADAG(_mlp_model(), loss="sparse_categorical_crossentropy",
                     num_workers=2, batch_size=16, num_epoch=2,
                     learning_rate=0.1, communication_window=4,
                     remote=srv.endpoint)
            accs[codec] = _acc(t.train(df, shuffle=True), x, y)
        finally:
            srv.close()
    assert accs["int8"] > 0.85, accs
    assert abs(accs["int8"] - accs["none"]) < 0.05, accs


# ---------------------------------------------------------------------------
# Server + client happy path
# ---------------------------------------------------------------------------

def test_join_pull_commit_heartbeat_leave_roundtrip():
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            init = leaves((3, 2), (4,))
            center, upd = c.join(init=init)
            assert upd == 0
            for a, b in zip(center, init):
                np.testing.assert_array_equal(a, b)
            res = c.commit([np.ones_like(a) for a in init], upd)
            assert res.applied and not res.duplicate and not res.evicted
            assert res.staleness == 0
            center2, upd2 = c.pull()
            assert upd2 == 1
            np.testing.assert_allclose(center2[0], init[0] + 1.0)
            assert c.heartbeat() == 1
            c.leave()
        assert srv.commit_log == [(0, 0, 0)]
    finally:
        srv.close()


def test_second_join_adopts_existing_center_and_assigns_ids():
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c0:
            init = leaves((4,))
            c0.join(init=init)
            with PSClient(srv.endpoint, **FAST) as c1:  # no worker_id
                other = [np.full(4, 9.0, np.float32)]
                center, _upd = c1.join(init=other)  # late init is ignored
                assert c1.worker_id == 1
                np.testing.assert_array_equal(center[0], init[0])
        # Closing a socket is not leaving: membership is by lease, not by
        # connection, so both ids are still members until their leases lapse.
        assert srv.members() == [0, 1]
    finally:
        srv.close()


def test_join_without_init_on_empty_server_is_typed_error():
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            with pytest.raises(Exception, match="uninitialized"):
                c.join()
    finally:
        srv.close()


def test_staleness_matches_counter_semantics():
    """DynSGD's staleness = server updates since the committer's pull —
    exactly the counter rule the raced twin records."""
    srv = make_server(discipline="dynsgd")
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as a, \
                PSClient(srv.endpoint, worker_id=1, **FAST) as b:
            init = [np.zeros(2, np.float32)]
            _, upd_a = a.join(init=init)
            _, upd_b = b.join()
            res_a = a.commit([np.ones(2, np.float32)], upd_a)
            assert res_a.staleness == 0
            # b pulled at 0 but commits after a's fold landed: staleness 1,
            # so DynSGD folds it at 1/2.
            res_b = b.commit([np.ones(2, np.float32)], upd_b)
            assert res_b.staleness == 1
            center, _ = a.pull()
            np.testing.assert_allclose(center[0], 1.0 + 0.5)
        assert [s for (_w, _q, s) in srv.commit_log] == [0, 1]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Chaos: every fault kind, per direction
# ---------------------------------------------------------------------------

def chaos_pair(spec, discipline="downpour", lease_s=None, **client_kw):
    srv = PSServer(discipline=discipline, lease_s=lease_s).start()
    px = ChaosProxy(srv.endpoint, plan=FaultPlan.parse_net(spec)).start()
    kw = dict(FAST)
    kw.update(client_kw)
    return srv, px, PSClient(px.endpoint, worker_id=0, **kw)


def test_retried_commit_after_dropped_ack_folds_exactly_once():
    """THE exactly-once scenario: the server applies the commit, the ACK is
    lost (chaos ``drop_r``), the client times out and retransmits with the
    SAME seq, the server answers duplicate — one fold in the commit log."""
    # frame 0 = join; frame 1 = the commit whose reply is dropped.
    srv, px, c = chaos_pair("drop_r@1", timeout=0.3, retries=4)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        res = c.commit([np.ones(3, np.float32)], upd)
        assert res.duplicate and not res.applied  # answered by the dedup
        assert srv.commit_log == [(0, 0, 0)], srv.commit_log
        np.testing.assert_allclose(srv.center()[0], 1.0)  # folded ONCE
    finally:
        c.close()
        px.close()
        srv.close()


def test_duplicated_commit_frame_is_deduped_and_stream_stays_sane():
    srv, px, c = chaos_pair("dup@1")
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        res = c.commit([np.ones(3, np.float32)], upd)  # delivered twice
        assert res.applied
        assert srv.commit_log == [(0, 0, 0)]
        np.testing.assert_allclose(srv.center()[0], 1.0)
        # The duplicate's reply is still in flight/buffered: the req-id echo
        # must keep the next RPC correctly matched.
        center, upd2 = c.pull()
        assert upd2 == 1
        np.testing.assert_allclose(center[0], 1.0)
    finally:
        c.close()
        px.close()
        srv.close()


def test_truncate_delay_and_drop_are_survived_by_retry():
    spec = "truncate@1;delay@2:0.05;drop@3"
    srv, px, c = chaos_pair(spec, timeout=0.3, retries=5)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        res = c.commit([np.ones(3, np.float32)], upd)  # truncated, retried
        assert res.applied or res.duplicate
        c.pull()       # delayed 50ms, inside the deadline
        c.pull()       # dropped, then retried
        assert len(srv.commit_log) == 1  # chaos never double-folded
    finally:
        c.close()
        px.close()
        srv.close()


def test_partition_is_ridden_out_by_jittered_retries():
    srv, px, c = chaos_pair("partition@1:0.5", timeout=0.3, retries=10,
                            backoff=0.05)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        t0 = time.monotonic()
        center, _ = c.pull()  # triggers the partition, retries through it
        assert time.monotonic() - t0 > 0.3
        np.testing.assert_array_equal(center[0], np.zeros(3))
    finally:
        c.close()
        px.close()
        srv.close()


def test_retry_budget_is_bounded():
    """A dead endpoint exhausts the budget and raises the typed error with
    the attempt count — it does not retry forever."""
    sock = socket.create_server(("127.0.0.1", 0))  # accepts, never answers
    port = sock.getsockname()[1]
    try:
        c = PSClient(f"127.0.0.1:{port}", worker_id=0, timeout=0.1,
                     retries=2, backoff=0.01)
        with pytest.raises(RPCTimeoutError) as ei:
            c.pull()
        assert ei.value.attempts == 3
        c.close()
    finally:
        sock.close()


# ---------------------------------------------------------------------------
# Leases, eviction, rejoin, drain
# ---------------------------------------------------------------------------

def test_lease_eviction_and_mid_run_rejoin():
    srv = make_server(lease_s=0.3)
    try:
        c = PSClient(srv.endpoint, worker_id=0, **FAST)
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        res = c.commit([np.ones(3, np.float32)], upd)
        assert res.applied
        deadline = time.monotonic() + 5.0
        while srv.members() and time.monotonic() < deadline:
            time.sleep(0.05)  # monitor evicts once the lease lapses
        assert srv.members() == []
        assert srv.evictions == 1
        # The next pull transparently re-joins and returns the live center.
        center, _upd = c.pull()
        assert c.rejoin_count == 1 and srv.rejoins == 1
        assert srv.members() == [0]
        np.testing.assert_allclose(center[0], 1.0)
        c.close()
    finally:
        srv.close()


def test_evicted_commit_is_discarded_and_reports_evicted():
    srv = make_server(lease_s=0.3)
    try:
        c = PSClient(srv.endpoint, worker_id=0, **FAST)
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        deadline = time.monotonic() + 5.0
        while srv.members() and time.monotonic() < deadline:
            time.sleep(0.05)
        res = c.commit([np.ones(3, np.float32)], upd)
        assert res.evicted and not res.applied
        assert srv.commit_log == []          # the stale window was discarded
        assert srv.members() == [0]          # ...and the client re-joined
        c.close()
    finally:
        srv.close()


def test_pre_eviction_retransmit_still_deduped_after_rejoin():
    """last_seq survives eviction: a commit applied just before the lease
    lapsed cannot re-fold when its retransmit arrives after the rejoin."""
    srv = make_server(lease_s=0.3)
    try:
        c = PSClient(srv.endpoint, worker_id=0, **FAST)
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        res = c.commit([np.ones(3, np.float32)], upd)
        assert res.applied
        deadline = time.monotonic() + 5.0
        while srv.members() and time.monotonic() < deadline:
            time.sleep(0.05)
        c.pull()  # rejoin
        # Hand-craft the retransmit of seq 0 (the client normally only does
        # this inside one commit's retry loop).
        hdr, _ = c._rpc("commit", {"seq": 0, "pulled": 0},
                        [np.ones(3, np.float32)])
        assert hdr["duplicate"] is True
        assert srv.commit_log == [(0, 0, 0)]
        c.close()
    finally:
        srv.close()


def test_administrative_lease_revocation_evicts_now():
    """`PSServer.revoke` — the fleet scheduler's preemption primitive —
    evicts immediately (no lease lapse to wait for), purges pending
    stripe state, and leaves dedup state intact."""
    srv = make_server(lease_s=60.0)  # lease never lapses on its own
    try:
        c = PSClient(srv.endpoint, worker_id=0, **FAST)
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        assert c.commit([np.ones(3, np.float32)], upd).applied
        assert srv.revoke(0) is True
        assert srv.members() == [] and srv.evictions == 1
        assert srv.revoke(0) is False  # not a member anymore: no-op
        # The revoked worker's next commit is the discarded-window path;
        # the client rejoins and the NEXT commit folds, seq intact.
        res = c.commit([np.ones(3, np.float32)], upd)
        assert res.evicted and not res.applied
        _, upd = c.pull()
        assert c.commit([np.ones(3, np.float32)], upd).applied
        assert [seq for (_w, seq, _s) in srv.commit_log] == [0, 2]
        c.close()
    finally:
        srv.close()


def test_revocation_shrink_then_expand_cycle_exactly_once():
    """The fleet's elastic cycle at wire level: W workers commit, W/2 are
    preempted via lease revocation mid-run (in-flight windows discarded),
    then re-expand through the mid-run rejoin path and keep committing.
    Exactly-once holds across the whole cycle, center progress (the
    update counter) never regresses, and nobody's sequence double-folds."""
    W = 4
    srv = make_server(lease_s=60.0)
    clients = [PSClient(srv.endpoint, worker_id=w, **FAST)
               for w in range(W)]
    progress = []

    def commit_round():
        for c in clients:
            _, upd = c.pull()
            res = c.commit([np.full(3, 0.1, np.float32)], upd)
            assert res.applied or res.evicted
            progress.append(srv.updates)

    try:
        for c in clients:
            c.join(init=[np.zeros(3, np.float32)])
        commit_round()               # everyone contributes
        commit_round()
        # Shrink: the scheduler preempts workers 2 and 3.
        for w in (2, 3):
            assert srv.revoke(w)
        assert srv.members() == [0, 1]
        evicted = 0
        for c in clients[2:]:
            _, upd = c.pull()  # transparently re-joins (expand half)...
            res = c.commit([np.full(3, 0.1, np.float32)], upd)
            # ...so this commit either folds (rejoin happened at the
            # pull) or reports the discarded window; both are legal,
            # neither double-folds.
            evicted += int(res.evicted)
            progress.append(srv.updates)
        # Expand: the survivors AND the rejoined pair all commit again.
        commit_round()
        assert srv.rejoins == 2 and sorted(srv.members()) == [0, 1, 2, 3]
        # Nondecreasing center progress across the whole cycle.
        assert progress == sorted(progress)
        assert srv.updates == len(srv.commit_log)
        # Exactly-once: no (worker, seq) folded twice, no seq gaps abused.
        seen = set()
        for wid, seq, _st in srv.commit_log:
            assert (wid, seq) not in seen, f"({wid}, {seq}) folded twice"
            seen.add((wid, seq))
        # Every survivor committed 3 times; the preempted pair lost at
        # most the one discarded window each.
        per_worker = {w: sum(1 for (wid, _s, _x) in srv.commit_log
                             if wid == w) for w in range(W)}
        assert per_worker[0] == 3 and per_worker[1] == 3
        assert per_worker[2] >= 2 and per_worker[3] >= 2
        assert srv.updates == sum(per_worker.values())
    finally:
        for c in clients:
            c.close()
        srv.close()


def test_restarted_worker_resumes_commit_sequence():
    """A restarted worker process (fresh client, seq counter back at -1,
    same worker_id — the Job.supervise restart scenario) must keep
    contributing: join hands back the server's last folded seq so the new
    incarnation's commits are not deduped away as retransmits."""
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c1:
            _, upd = c1.join(init=[np.zeros(3, np.float32)])
            for _ in range(3):
                _, upd = c1.pull()
                assert c1.commit([np.ones(3, np.float32)], upd).applied
        # "Host restart": a brand-new client claims the same worker_id.
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c2:
            _, upd = c2.join()
            res = c2.commit([np.ones(3, np.float32)], upd)
            assert res.applied and not res.duplicate, res
        assert [seq for (_w, seq, _s) in srv.commit_log] == [0, 1, 2, 3]
        np.testing.assert_allclose(srv.center()[0], 4.0)
    finally:
        srv.close()


def test_wire_rejects_malformed_array_specs_as_protocol_errors():
    """Untrusted header bytes can only fail typed: negative dims and junk
    dtypes must become ProtocolError, never a raw numpy ValueError that
    would kill a handler thread outside the typed taxonomy."""
    import json
    import struct
    import zlib

    def frame_with_spec(spec):
        hjson = json.dumps({"op": "x", "arrays": [spec]}).encode()
        body = struct.pack("!I", len(hjson)) + hjson + b"\0" * 16
        return (wire.MAGIC + bytes([wire.VERSION, wire.KIND_REQUEST])
                + struct.pack("!II", zlib.crc32(body), len(body)) + body)

    with pytest.raises(ProtocolError, match="negative"):
        wire.decode_frame(frame_with_spec({"dtype": "<f4", "shape": [-4]}))
    with pytest.raises(ProtocolError, match="bad array spec"):
        wire.decode_frame(frame_with_spec({"dtype": "not-a-dtype",
                                           "shape": [2]}))
    with pytest.raises(ProtocolError, match="bad array spec"):
        wire.decode_frame(frame_with_spec({"dtype": "<f4"}))  # no shape


def test_drain_rejects_commits_typed_but_serves_final_pull():
    srv = make_server()
    c = PSClient(srv.endpoint, worker_id=0, **FAST)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        c.commit([np.ones(3, np.float32)], upd)
        srv.drain()
        with pytest.raises(ServerDrainingError):
            c.commit([np.ones(3, np.float32)], upd)
        center, _ = c.pull()  # departing workers may fetch the final center
        np.testing.assert_allclose(center[0], 1.0)
        with pytest.raises(ServerDrainingError):
            PSClient(srv.endpoint, worker_id=9, **FAST).join(
                init=[np.zeros(3, np.float32)])
    finally:
        c.close()
        srv.close()


def test_close_joins_every_server_thread():
    before = {t.name for t in threading.enumerate()}
    srv = make_server()
    with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
        c.join(init=[np.zeros(2, np.float32)])
        assert any(t.name.startswith("netps-")
                   for t in threading.enumerate())
    srv.close()
    after = {t.name for t in threading.enumerate()}
    lingering = [n for n in after - before if n.startswith("netps-")]
    assert not lingering, lingering


def test_client_use_after_close_is_typed():
    srv = make_server()
    try:
        c = PSClient(srv.endpoint, worker_id=0, **FAST)
        c.join(init=[np.zeros(2, np.float32)])
        c.close()
        with pytest.raises(ServerClosedError):
            c.pull()
    finally:
        srv.close()


def test_rpc_telemetry_spans_and_counters_recorded():
    from distkeras_tpu import telemetry

    telemetry.reset()
    srv = make_server()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            _, upd = c.join(init=[np.zeros(2, np.float32)])
            c.commit([np.ones(2, np.float32)], upd)
            c.pull()
        snap = telemetry.get().snapshot()
        assert snap["spans"]["netps.rpc.commit"]["count"] == 1
        assert snap["spans"]["netps.server.pull"]["count"] >= 1
        assert snap["counters"]["netps.commits"] == 1
        assert snap["counters"]["netps.bytes_sent"] > 0
        assert snap["counters"]["netps.bytes_received"] > 0
    finally:
        srv.close()
        telemetry.reset()


def test_drain_vs_eviction_race_no_resurrection_no_deadlock():
    """Lease expiry firing while ``drain()`` is mid-flight must not
    resurrect an evicted worker or deadlock the monitor thread — the
    drain flag and the eviction sweep share ONE lock, witnessed live per
    DK201. Draining deliberately rejects commits BEFORE the lease renewal
    would run, and a draining join answers typed, so the only door back
    in is closed both ways."""
    import time as _time

    from distkeras_tpu.analysis import witness

    with witness() as w:
        srv = make_server(lease_s=0.15)
        keeper = PSClient(srv.endpoint, worker_id=0, **FAST)
        sleeper = PSClient(srv.endpoint, worker_id=1, auto_rejoin=False,
                           **FAST)
        try:
            _, upd = keeper.join(init=[np.zeros(4, np.float32)])
            sleeper.join()
            stop = threading.Event()

            def drainer():
                # drain() repeatedly while the monitor's eviction sweep
                # races it over the same lock.
                while not stop.is_set():
                    srv.drain()
                    _time.sleep(0.01)

            t = threading.Thread(target=drainer)
            t.start()
            deadline = _time.monotonic() + 5.0
            while 1 in srv.members() and _time.monotonic() < deadline:
                _time.sleep(0.02)
            stop.set()
            t.join()
            assert 1 not in srv.members(), "eviction lost to the drain race"
            assert srv.evictions >= 1
            # The evicted worker cannot be resurrected through a draining
            # server: join is typed-rejected, commit never renews.
            with pytest.raises(ServerDrainingError):
                sleeper.join()
            assert 1 not in srv.members()
            with pytest.raises(ServerDrainingError):
                keeper.commit([np.ones(4, np.float32)], upd)
            closer = threading.Thread(target=srv.close)
            closer.start()
            closer.join(timeout=10.0)
            assert not closer.is_alive(), (
                "close() deadlocked against the monitor thread")
        finally:
            keeper.close()
            sleeper.close()
    w.assert_no_inversions()


# ---------------------------------------------------------------------------
# Lock discipline: the witness over genuinely racing handler threads
# ---------------------------------------------------------------------------

def test_server_handler_threads_under_lock_witness():
    """The runtime lock-order witness over the server's per-connection
    handler threads (plus the lease monitor): no inversion across racing
    commits, and every witnessed edge involving netps locks exists in the
    static DK201 graph."""
    import os

    import distkeras_tpu
    from distkeras_tpu.analysis import core, witness
    from distkeras_tpu.analysis.rules_concurrency import build_lock_graph

    with witness() as w:
        srv = make_server(lease_s=5.0)
        errors = []

        def worker(wid):
            try:
                c = PSClient(srv.endpoint, worker_id=wid, **FAST)
                _, upd = c.join(init=[np.zeros(8, np.float32)])
                for _ in range(5):
                    center, upd = c.pull()
                    c.commit([np.ones(8, np.float32)], upd)
                c.leave()
                c.close()
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        srv.close()
    assert not errors, errors
    assert len(srv.commit_log) == 20
    w.assert_no_inversions()
    pkg = os.path.dirname(os.path.abspath(distkeras_tpu.__file__))
    modules, _ = core.parse_modules([pkg])
    static_edges, _, _ = build_lock_graph(modules)
    netps_edges = {e for e in w.edges()
                   if "server.PSServer" in e[0] or "server.PSServer" in e[1]}
    assert netps_edges <= static_edges, netps_edges - static_edges


# ---------------------------------------------------------------------------
# Remote training: trainers over the wire
# ---------------------------------------------------------------------------

def _blob_data(seed=0, n=512, dim=4, classes=3):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(classes, dim))
    y = rng.integers(0, classes, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, dim))).astype(np.float32)
    return x, y.astype(np.int32)


def _mlp_model(seed=0, dim=4, classes=3):
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    return Model.build(MLP(hidden=(16,), num_outputs=classes),
                       np.zeros((1, dim), np.float32), seed=seed)


def _acc(model, x, y):
    return float((np.asarray(model.predict(x)).argmax(-1) == y).mean())


def test_remote_trainer_trains_over_loopback(monkeypatch):
    """`remote="host:port"` on an async trainer: the worker loop runs
    pull -> K jitted local steps -> commit through the hardened client,
    and the final model is the server's center."""
    from distkeras_tpu import ADAG

    monkeypatch.setenv("DKTPU_NET_TIMEOUT", "2.0")
    x, y = _blob_data()
    from distkeras_tpu import DataFrame

    df = DataFrame({"features": x, "label": y})
    srv = make_server()
    try:
        t = ADAG(_mlp_model(), loss="sparse_categorical_crossentropy",
                 num_workers=2, batch_size=16, num_epoch=2,
                 learning_rate=0.1, communication_window=4,
                 remote=srv.endpoint)
        trained = t.train(df, shuffle=True)
        assert _acc(trained, x, y) > 0.85
        assert len(srv.commit_log) > 0
        assert t.get_history() is not None
        assert t.get_worker_histories().shape[0] == 2
    finally:
        srv.close()


def test_remote_endpoint_from_env_and_parallel_conflict(monkeypatch):
    from distkeras_tpu import ADAG

    t = ADAG(_mlp_model(), num_workers=2)
    assert t._remote_endpoint() is None
    monkeypatch.setenv("DKTPU_PS_ENDPOINT", "ps-host:7077")
    assert t._remote_endpoint() == "ps-host:7077"
    with pytest.raises(ValueError, match="remote"):
        ADAG(_mlp_model(), remote="h:1", parallel={"model": 2})


def test_punchcard_ps_launch_rendering():
    """Job/Punchcard learn the PS: a `ps` field renders the server launch
    line and threads the endpoint to every worker via DKTPU_PS_ENDPOINT."""
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="j", script="train.py",
                   hosts=["10.0.0.1", "10.0.0.2"],
                   ps={"discipline": "dynsgd", "port": 7171, "lease": 5.0})
    assert pc.ps_endpoint() == "10.0.0.1:7171"
    job = Job(pc)
    ps_cmd = job.render_ps_command()
    assert "python -m distkeras_tpu.netps" in ps_cmd
    assert "--discipline dynsgd" in ps_cmd and "--port 7171" in ps_cmd
    assert "--lease 5.0" in ps_cmd
    for cmd in job.launch(dry_run=True):
        assert "DKTPU_PS_ENDPOINT=10.0.0.1:7171" in cmd
    # JSON round-trip keeps the ps block (the punchcard is the job card).
    assert Punchcard.from_json(pc.to_json()).ps == pc.ps
    # No ps: nothing rendered, no endpoint injected.
    bare = Job(Punchcard(job_name="j", script="s.py", hosts=["h"]))
    assert bare.render_ps_command() is None
    assert "DKTPU_PS_ENDPOINT" not in bare.launch(dry_run=True)[0]


def test_tree_depth3_staleness_is_min_of_constituents():
    """N-level MIN-reduction parity: a 3-level path (worker -> host node
    -> region node -> root) charges the root the OLDEST constituent's
    staleness — the same number the flat topology charges that
    constituent at the same counter, and the same MIN the 2-level
    aggregator already forwards (the interior hop must not launder it)."""
    from distkeras_tpu.netps import build_tree

    flat = make_server(discipline="dynsgd")
    root = make_server(discipline="dynsgd")
    tree = None
    try:
        # Advance both counters to 2 through a direct worker.
        for srv in (flat, root):
            with PSClient(srv.endpoint, worker_id=7, **FAST) as direct:
                _, u = direct.join(init=[np.zeros(4, np.float32)])
                direct.commit([np.ones(4, np.float32)], u)
                _, u = direct.pull()
                direct.commit([np.ones(4, np.float32)], u)
        # Flat reference at counter 2: the stale commit (pulled=0) is
        # charged staleness 2 — the oldest-constituent number the tree's
        # combined window must carry to the root.
        with PSClient(flat.endpoint, worker_id=1, **FAST) as fb:
            fb.join()
            hdr, _ = fb._rpc("commit", {"seq": 0, "pulled": 0},
                             [np.ones(4, np.float32)])
            assert hdr["applied"]
        flat_oldest = max(st for w, _s, st in flat.commit_log if w != 7)
        assert flat_oldest == 2
        # Depth-3: host level (fan 2, both workers) under a region level
        # (fan 1: the single host node). A long flush_interval keeps the
        # host window open until BOTH constituents are in — the flush is
        # fan-in-driven, so min(pulled) is a real two-element MIN.
        tree = build_tree("host:2,region:2", root.endpoint, workers=2,
                          discipline="dynsgd", flush_interval=5.0)
        a = PSClient(tree.leaf_endpoint(0), worker_id=0, **FAST)
        b = PSClient(tree.leaf_endpoint(1), worker_id=1, **FAST)
        try:
            _, ua = a.join()
            b.join()
            assert ua == 2  # root-lineage counter served at the leaf
            a.commit([np.ones(4, np.float32)], ua)     # fresh: pulled=2
            hdr, _ = b._rpc("commit", {"seq": 0, "pulled": 0},
                            [np.ones(4, np.float32)])  # stale: pulled=0
            assert hdr["applied"]
        finally:
            a.close()
            b.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if any(w != 7 for w, _s, _st in root.commit_log):
                break
            time.sleep(0.02)
        tree_folds = [e for e in root.commit_log if e[0] != 7]
        # ONE combined window traversed both levels; the root charges it
        # the oldest constituent's staleness, exactly the flat number.
        assert len(tree_folds) == 1
        assert tree_folds[0][2] == flat_oldest == 2
        # The interior region hop saw the same MIN on its own books (the
        # 2-level reading): its fold of the host's combined commit was
        # charged updates(2) - min_pulled(0) = 2 as well.
        region = tree.node(1, 0)
        assert [st for _w, _s, st in region.commit_log] == [2]
    finally:
        if tree is not None:
            tree.close()
        flat.close()
        root.close()


@pytest.mark.slow
def test_netps_chaos_parity_with_raced_ps(monkeypatch):
    """THE acceptance scenario: the same model/data trained (a) through
    netps over loopback with chaos injecting delay/drop/duplicate, a lost
    commit ACK, and one mid-run worker eviction + rejoin — with the FULL
    fast data plane enabled (compute/comms overlap, int8+error-feedback
    deltas, 2-way striping) — and (b) through the in-process raced PS:
    final accuracies agree at the raced-parity tolerance, and the lost-ACK
    retransmit folded exactly once (one logical commit striped over 2
    connections still folds once)."""
    import test_raced_ps as rp
    from distkeras_tpu import ADAG, DataFrame
    from distkeras_tpu.resilience import faults

    monkeypatch.setenv("DKTPU_NET_TIMEOUT", "1.0")
    monkeypatch.setenv("DKTPU_NET_RETRIES", "8")
    monkeypatch.setenv("DKTPU_NET_BACKOFF", "0.02")
    # The PR 5 data plane, all knobs on: the hardening guarantees must hold
    # with overlap + compression + striping active, not only in the PR 4
    # serial/f32/one-socket dialect.
    monkeypatch.setenv("DKTPU_NET_INFLIGHT", "2")
    monkeypatch.setenv("DKTPU_NET_COMPRESS", "int8")
    monkeypatch.setenv("DKTPU_NET_SHARDS", "2")
    raced_accs, net_accs = [], []
    for seed in (0, 1):
        acc_r, _ = rp._raced_accuracy(seed, "adag")
        raced_accs.append(acc_r)
        srv = PSServer(discipline="adag", lease_s=1.0).start()
        # One ambient plan (DKTPU_NET_FAULTS) drives BOTH consumers: the
        # proxy takes the wire kinds, the remote worker loop takes `evict`.
        # Frames: 0..W-1 are joins; commits/pulls interleave after. The
        # indices land on whatever RPC is in flight — chaos does not need
        # to aim, it needs to be survived. evict@4 puts one seeded worker
        # to sleep past its lease mid-run (the worker-kill analogue),
        # drop_r@9 is a lost ACK (commit or pull — either must be safe).
        faults.reset()  # fresh one-shot state per seed
        monkeypatch.setenv(
            "DKTPU_NET_FAULTS",
            "delay@6:0.1;drop@11;dup@14;drop_r@9;evict@4:2.2;seed=3")
        px = ChaosProxy(srv.endpoint).start()
        try:
            x, y = rp._blobs(seed)
            df = DataFrame({"features": x, "label": y})
            t = rp._TRAINERS["adag"](rp._model(seed))
            t.remote = px.endpoint
            trained = t.train(df, shuffle=True)
            net_accs.append(rp._accuracy(trained.predict, x, y))
            assert srv.evictions >= 1, "eviction chaos never fired"
            assert srv.rejoins >= 1, "evicted worker never re-joined"
            # Exactly-once under chaos: seqs folded at most once per worker.
            seen = set()
            for wid, seq, _st in srv.commit_log:
                assert (wid, seq) not in seen, (
                    f"commit ({wid}, {seq}) folded twice")
                seen.add((wid, seq))
        finally:
            px.close()
            srv.close()
            faults.reset()
    raced_accs, net_accs = np.asarray(raced_accs), np.asarray(net_accs)
    assert (raced_accs > 0.85).all(), raced_accs
    assert (net_accs > 0.85).all(), (
        f"chaos netps run failed to converge: {net_accs}")
    assert abs(raced_accs.mean() - net_accs.mean()) < 0.05, (
        raced_accs, net_accs)
