"""Environment-capability probes behind the tier-1 skip triage.

Tier-1's contract is *failures mean bugs*. A test that fails because this
jax/jaxlib/orbax build lacks a capability — not because the code under
test regressed — poisons that signal, so each such prerequisite is probed
ONCE here (a concrete reproduction, not a version guess) and the affected
tests skip with a reason naming exactly what is missing. On an
environment that has the capability, the probe passes and the tests run —
nothing is permanently retired.

Probes, and the failure they reproduce:

* ``key_arrays_shardable_with_auto_axes`` — lowering a typed PRNG key
  array (trailing ``u32[2]``) through ``shard_map`` with a GSPMD ``auto``
  subgroup axis. jaxlib 0.4.36's SPMD partitioner rejects it ("Number of
  tile assignment dimensions ... is different than the input rank",
  ``input_shape=u32[2]``), which kills every AsyncTP/SPMD-engine program
  (manual data/seq axes + auto model axis).
* ``xla_combines_all_reduces`` — whether XLA's AllReduceCombiner folds
  several small psums into one fused all-reduce on this backend. The HLO
  property tests pin "one fused fold per round"; a build whose combiner
  is inactive reports one all-reduce *per parameter tensor* and the
  property is untestable.
"""

from __future__ import annotations

import functools
import re

import numpy as np
import pytest


@functools.lru_cache(maxsize=None)
def key_arrays_shardable_with_auto_axes() -> bool:
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.ops.collectives import shard_map

    if jax.device_count() < 4:
        return False
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))

    def f(k):
        return jax.random.fold_in(k, jax.lax.axis_index("data"))

    try:
        jax.block_until_ready(jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(), out_specs=P(), check_rep=False,
            auto=frozenset({"model"})))(jax.random.key(0)))
        return True
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def xla_combines_all_reduces() -> bool:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.ops.collectives import shard_map

    mesh = Mesh(np.array(jax.devices()), ("data",))

    def f(xs):
        return [jax.lax.psum(x, "data") for x in xs]

    xs = [jnp.ones((4, 4)), jnp.ones((8,)), jnp.ones((2, 2))]
    try:
        hlo = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(), out_specs=P(),
            check_rep=False)).lower(xs).compile().as_text()
    except Exception:
        return False
    return len(re.findall(r"all-reduce(?:-start)?\(", hlo)) <= 1


def skip_unless_key_sharding():
    return pytest.mark.skipif(
        not key_arrays_shardable_with_auto_axes(),
        reason="missing prerequisite: a jaxlib whose SPMD partitioner can "
               "shard typed PRNG key arrays (u32[2] trailer) through "
               "shard_map with a GSPMD `auto` subgroup axis — this build "
               "rejects the sharding (tile-assignment rank error), so no "
               "AsyncTP/SPMD-engine program can compile here")


def skip_unless_allreduce_combiner():
    return pytest.mark.skipif(
        not xla_combines_all_reduces(),
        reason="missing prerequisite: an XLA build with an active "
               "AllReduceCombiner on this backend — without it every "
               "parameter tensor keeps its own all-reduce and the "
               "one-fused-fold HLO property is untestable")
