"""Compiled-HLO regression tests: the collective structure the design promises.

The whole point of the rebuild is that the reference's parameter-server
traffic becomes ONE fused collective per fold round riding ICI (SURVEY.md §7).
These tests pin that property in the compiled executable so a refactor that
silently splits or multiplies the collectives fails CI, not a pod run.
"""

import re

import jax
import jax.numpy as jnp
import pytest

from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.parallel.disciplines import get_discipline
from distkeras_tpu.parallel.engine import AsyncEngine
from distkeras_tpu.parallel.sync import SyncEngine
from distkeras_tpu.runtime.mesh import data_mesh

import envcaps


def _compiled_text(fn, *args):
    return jax.jit(fn).lower(*args).compile().as_text()


def _count(hlo, op):
    return len(re.findall(rf"{op}[-.\w]*\(", hlo))


@pytest.fixture(scope="module")
def setup():
    mesh = data_mesh()
    model = Model.build(MLP(hidden=(32,), num_outputs=3),
                        jnp.zeros((1, 6), jnp.float32))
    xs = jnp.zeros((8, 4, 16, 6), jnp.float32)
    ys = jnp.zeros((8, 4, 16), jnp.int32)
    return mesh, model, xs, ys


@pytest.mark.parametrize("disc", ["downpour", "adag", "dynsgd", "aeasgd"])
@envcaps.skip_unless_allreduce_combiner()
def test_async_round_is_one_fused_all_reduce(setup, disc, request):
    mesh, model, xs, ys = setup
    fold = get_discipline(disc) if disc != "aeasgd" else get_discipline(
        "aeasgd", alpha=0.1)
    eng = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy", fold,
                      mesh, window=4, learning_rate=0.1)
    hlo = _compiled_text(eng._round_core, eng.init_state(), xs, ys)
    n = _count(hlo, "all-reduce")
    # one fused all-reduce for the param fold (the loss gather may fuse into
    # it or add one more op at most — never one per parameter tensor)
    assert 1 <= n <= 2, f"{disc}: expected one fused fold, got {n} all-reduces"


@envcaps.skip_unless_allreduce_combiner()
def test_sync_round_is_one_fused_all_reduce_per_step(setup):
    mesh, model, xs, ys = setup
    eng = SyncEngine(model, "sgd", "sparse_categorical_crossentropy", mesh,
                     learning_rate=0.1)
    hlo = _compiled_text(eng._round_core, eng.init_state(), xs, ys)
    # the window scan contains the per-step gradient pmean: the loop body
    # must carry a single fused all-reduce, not one per layer
    n = _count(hlo, "all-reduce")
    assert 1 <= n <= 3, f"expected fused per-step pmean, got {n} all-reduces"


def test_async_round_has_no_host_transfers(setup):
    """The round program must not bounce through the host (infeed/outfeed
    beyond the obvious arg/result transfers)."""
    mesh, model, xs, ys = setup
    eng = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                      get_discipline("adag"), mesh, window=4, learning_rate=0.1)
    hlo = _compiled_text(eng._round_core, eng.init_state(), xs, ys)
    assert _count(hlo, "infeed") == 0
    assert _count(hlo, "outfeed") == 0
