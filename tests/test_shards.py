"""Sharded / out-of-core data plane (VERDICT r2 missing #1).

The reference's Spark DataFrame was partitioned across executors and spillable
to disk; these tests pin the TPU-side replacement: ``.npy`` shard files +
manifest, memmapped gathers that touch only the rows they index, a
worker-contiguous schedule that keeps every row host-local, and engine staging
that feeds a training run identical to the in-RAM path.
"""

import os

import numpy as np
import pytest

import jax

from distkeras_tpu.data.batching import BatchPlan, make_batches
from distkeras_tpu.data.dataframe import DataFrame
from distkeras_tpu.data.shards import (
    ShardStore,
    ShardWriter,
    ShardedDataFrame,
    make_sharded_batches,
    worker_major_index,
    worker_partition,
    write_shards,
)


def _blobs(n=512, d=4, c=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    return x, y.astype(np.int32)


# ---------------------------------------------------------------- store I/O


def test_write_shards_roundtrip(tmp_path):
    x, y = _blobs(n=100)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=32)
    store = ShardStore.open(tmp_path)
    assert store.count() == 100
    assert store.num_shards == 4  # 32+32+32+4
    idx = np.array([[0, 99], [31, 32]])  # spans shard boundaries, 2-D idx
    np.testing.assert_array_equal(store.gather("features", idx), x[idx])
    np.testing.assert_array_equal(store.gather("label", idx), y[idx])


def test_shard_writer_streaming_matches_oneshot(tmp_path):
    """Appending in uneven chunks produces the same store as one-shot write."""
    x, y = _blobs(n=90)
    w = ShardWriter(tmp_path / "stream", rows_per_shard=25)
    for lo, hi in [(0, 10), (10, 60), (60, 90)]:
        w.append(features=x[lo:hi], label=y[lo:hi])
    m = w.close()
    assert m["num_rows"] == 90
    assert m["shard_rows"] == [25, 25, 25, 15]
    store = ShardStore.open(tmp_path / "stream")
    np.testing.assert_array_equal(
        store.gather("features", np.arange(90)), x)


def test_writer_context_manager_and_random_chunking(tmp_path):
    """`with ShardWriter(...)` publishes on clean exit (and not on error);
    arbitrary random append chunkings round-trip exactly."""
    rng = np.random.default_rng(5)
    x, y = _blobs(n=333, seed=5)
    with ShardWriter(tmp_path / "ok", rows_per_shard=37) as w:
        off = 0
        while off < 333:
            k = int(rng.integers(1, 50))
            w.append(features=x[off:off + k], label=y[off:off + k])
            off += k
    store = ShardStore.open(tmp_path / "ok")
    assert store.count() == 333
    np.testing.assert_array_equal(store.gather("features", np.arange(333)), x)
    np.testing.assert_array_equal(store.gather("label", np.arange(333)), y)

    with pytest.raises(RuntimeError, match="boom"):
        with ShardWriter(tmp_path / "bad", rows_per_shard=8) as w:
            w.append(features=x[:16], label=y[:16])
            raise RuntimeError("boom")
    with pytest.raises(FileNotFoundError):  # no manifest published
        ShardStore.open(tmp_path / "bad")

    # Explicit close() inside the block (to grab the manifest) is tolerated.
    with ShardWriter(tmp_path / "manual", rows_per_shard=8) as w:
        w.append(features=x[:16], label=y[:16])
        manifest = w.close()
    assert manifest["num_rows"] == 16


def test_part_writers_merge_identical_to_single_writer(tmp_path):
    """Distributed ingest: N part-ShardWriters + merge_manifests must read
    byte-identically to one writer fed the concatenated stream (per-part
    shard boundaries line up here: 128-row halves of 64-row shards)."""
    from distkeras_tpu.data.shards import merge_manifests

    x, y = _blobs(n=256)
    single = tmp_path / "single"
    write_shards(single, {"features": x, "label": y}, rows_per_shard=64)

    multi = tmp_path / "multi"
    for part in range(2):
        lo, hi = part * 128, (part + 1) * 128
        with ShardWriter(multi, rows_per_shard=64, part=part) as w:
            for s in range(lo, hi, 50):  # ragged chunks cross shard bounds
                w.append(features=x[s:min(s + 50, hi)],
                         label=y[s:min(s + 50, hi)])
    with pytest.raises(FileNotFoundError):
        ShardStore.open(multi)  # unreadable until merged (no root manifest)
    manifest = merge_manifests(multi)

    ref = ShardStore.open(single)
    got = ShardStore.open(multi)
    assert manifest["shard_rows"] == ref.manifest["shard_rows"]
    assert manifest["columns"] == ref.manifest["columns"]
    ids = np.arange(256)
    np.testing.assert_array_equal(got.gather("features", ids),
                                  ref.gather("features", ids))
    np.testing.assert_array_equal(got.gather("label", ids),
                                  ref.gather("label", ids))
    assert not any(f.startswith("part-") for f in os.listdir(multi))


def test_merge_manifests_resumes_after_crash(tmp_path, monkeypatch):
    """A crash mid-splice must NOT corrupt the store on retry: the journaled
    plan replays idempotently instead of restarting the shard counter over
    already-moved files (r4 review finding)."""
    import os as _os

    from distkeras_tpu.data import shards as shards_mod
    from distkeras_tpu.data.shards import merge_manifests

    x, y = _blobs(n=256)
    single = tmp_path / "single"
    write_shards(single, {"features": x, "label": y}, rows_per_shard=64)
    multi = tmp_path / "multi"
    for part in range(2):
        lo, hi = part * 128, (part + 1) * 128
        with ShardWriter(multi, rows_per_shard=64, part=part) as w:
            w.append(features=x[lo:hi], label=y[lo:hi])

    real_replace = _os.replace
    calls = {"n": 0}

    def flaky(src, dst):
        calls["n"] += 1
        if calls["n"] == 4:  # after the journal write + some shard moves
            raise OSError("simulated crash mid-merge")
        return real_replace(src, dst)

    monkeypatch.setattr(shards_mod.os, "replace", flaky)
    with pytest.raises(OSError, match="simulated crash"):
        merge_manifests(multi)
    monkeypatch.setattr(shards_mod.os, "replace", real_replace)
    assert (multi / ".merge.journal.json").exists()

    manifest = merge_manifests(multi)  # resume
    ref = ShardStore.open(single)
    got = ShardStore.open(multi)
    assert manifest["shard_rows"] == ref.manifest["shard_rows"]
    ids = np.arange(256)
    np.testing.assert_array_equal(got.gather("features", ids),
                                  ref.gather("features", ids))
    np.testing.assert_array_equal(got.gather("label", ids),
                                  ref.gather("label", ids))
    assert not (multi / ".merge.journal.json").exists()


def test_merge_manifests_rejects_schema_mismatch(tmp_path):
    from distkeras_tpu.data.shards import merge_manifests

    x, y = _blobs(n=64)
    with ShardWriter(tmp_path, rows_per_shard=32, part=0) as w:
        w.append(features=x, label=y)
    with ShardWriter(tmp_path, rows_per_shard=32, part=1) as w:
        w.append(features=x.astype(np.float64), label=y)  # drifted dtype
    with pytest.raises(ValueError, match="different column schema"):
        merge_manifests(tmp_path)


def test_merge_manifests_skips_empty_parts(tmp_path):
    from distkeras_tpu.data.shards import merge_manifests

    x, y = _blobs(n=64)
    with ShardWriter(tmp_path, rows_per_shard=32, part=0) as w:
        w.append(features=x, label=y)
    ShardWriter(tmp_path, rows_per_shard=32, part=1).close()  # saw no rows
    manifest = merge_manifests(tmp_path)
    assert manifest["num_rows"] == 64 and len(manifest["shard_rows"]) == 2


def test_writer_rejects_schema_drift(tmp_path):
    w = ShardWriter(tmp_path, rows_per_shard=8)
    w.append(features=np.zeros((4, 3), np.float32))
    with pytest.raises(ValueError, match="columns changed"):
        w.append(labels=np.zeros(4))
    with pytest.raises(ValueError, match="expected float32"):
        w.append(features=np.zeros((4, 3), np.float64))


def test_gather_out_of_range(tmp_path):
    x, y = _blobs(n=20)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=10)
    store = ShardStore.open(tmp_path)
    with pytest.raises(IndexError):
        store.gather("features", np.array([20]))


def test_locality_missing_shards_fail_only_when_touched(tmp_path):
    """A host holding a subset of the shard files serves every row it owns
    and fails loudly on rows it does not — the per-host residency contract."""
    x, y = _blobs(n=80)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=20)
    # Simulate a host that owns only shards 0-1 (rows 0..39).
    for s in (2, 3):
        os.remove(tmp_path / f"shard-{s:05d}.features.npy")
        os.remove(tmp_path / f"shard-{s:05d}.label.npy")
    store = ShardStore.open(tmp_path)
    np.testing.assert_array_equal(
        store.gather("features", np.arange(40)), x[:40])
    with pytest.raises(FileNotFoundError):
        store.gather("features", np.array([45]))


def test_store_bounds_open_memmaps(tmp_path):
    """The memmap cache is LRU-bounded: a store with more shards than the cap
    never holds more than ``max_open_maps`` file descriptors."""
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    write_shards(tmp_path, {"features": x}, rows_per_shard=4)  # 16 shards
    store = ShardStore(tmp_path, max_open_maps=3)
    np.testing.assert_array_equal(
        store.gather("features", np.arange(64)), x)  # touches all 16 shards
    assert len(store._maps) <= 3
    store.close()
    assert not store._maps


# ------------------------------------------------------------- the schedule


def test_worker_major_index_partition_locality():
    """Every round's rows for worker w stay inside w's contiguous partition —
    the invariant that makes disjoint per-host shards possible at all."""
    n, W, K, B = 512, 4, 2, 8
    idx = worker_major_index(n, W, K, B, num_epoch=3, shuffle=True, seed=7)
    parts = worker_partition(n, W)
    assert idx.shape[1:] == (W, K, B)
    for w, (lo, hi) in enumerate(parts):
        rows = idx[:, w]
        assert rows.min() >= lo and rows.max() < hi
    # Within one epoch, no row is repeated for a worker (a true permutation).
    rounds_per_epoch = idx.shape[0] // 3
    epoch0 = idx[:rounds_per_epoch, 0].reshape(-1)
    assert len(np.unique(epoch0)) == len(epoch0)


def test_worker_major_index_deterministic():
    a = worker_major_index(256, 2, 2, 4, shuffle=True, seed=3)
    b = worker_major_index(256, 2, 2, 4, shuffle=True, seed=3)
    np.testing.assert_array_equal(a, b)


def test_dropped_rows_warn_with_exact_counts():
    """The schedule silently used to drop up to W-1 remainder rows plus each
    worker's tail beyond full rounds (VERDICT r3 weak #4) — now it warns with
    the exact counts, and stays silent when everything fits."""
    import warnings

    # n=103, W=4 -> rpw=25, remainder 3; K*B=8 -> 3 rounds/worker uses 24,
    # truncating 1 row x 4 workers. Dropped = 3 + 4 = 7.
    with pytest.warns(UserWarning, match=r"uses 96 of 103 rows") as rec:
        idx = worker_major_index(103, 4, 2, 4)
    assert idx.shape == (3, 4, 2, 4)
    msg = str(rec[0].message)
    assert "3 to the worker remainder" in msg
    assert "4 to round truncation" in msg

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # exact fit must NOT warn
        worker_major_index(128, 4, 2, 4)


def test_sharded_plan_round_matches_local(tmp_path):
    x, y = _blobs(n=256)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=64)
    plan = make_sharded_batches(
        ShardedDataFrame(tmp_path), "features", "label",
        batch_size=8, num_workers=4, window=2, shuffle=True, seed=1)
    xs, ys = plan.round(0)
    assert xs.shape == (4, 2, 8, 4)
    xl, yl = plan.round_local(0, [1, 2])
    np.testing.assert_array_equal(xl, xs[1:3])
    np.testing.assert_array_equal(yl, ys[1:3])
    # local_shards: worker partitions map to whole shards (64 rows each here).
    assert plan.local_shards([0]) == [0]
    assert plan.local_shards([2, 3]) == [2, 3]


# ------------------------------------------------- training-time transforms


def _minmax(x, lo, hi):
    return ((x - lo) / (hi - lo)).astype(np.float32)


def test_train_time_normalization_matches_ingest(tmp_path):
    """Normalizing at training time (transform=) must produce EXACTLY the
    batches an ingest-time-normalized store produces — the lazy half of the
    Spark pipeline (VERDICT r3 missing #1)."""
    x, y = _blobs(n=256)
    lo, hi = float(x.min()), float(x.max())
    write_shards(tmp_path / "raw", {"features": x, "label": y},
                 rows_per_shard=64)
    write_shards(tmp_path / "norm",
                 {"features": _minmax(x, lo, hi), "label": y},
                 rows_per_shard=64)

    def train_time_norm(feats, labels, rng):
        return _minmax(feats, lo, hi), labels

    kw = dict(batch_size=8, num_workers=4, window=2, num_epoch=2,
              shuffle=True, seed=5)
    plan_raw = make_sharded_batches(ShardedDataFrame(tmp_path / "raw"),
                                    "features", "label",
                                    transform=train_time_norm, **kw)
    plan_ing = make_sharded_batches(ShardedDataFrame(tmp_path / "norm"),
                                    "features", "label", **kw)
    for r in range(plan_raw.num_rounds):
        xa, ya = plan_raw.round(r)
        xb, yb = plan_ing.round(r)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_transform_rng_deterministic_per_seed_and_round(tmp_path):
    """Random augmentation: same (seed, round) -> identical batches across
    plan rebuilds; different rounds and different seeds -> different draws."""
    x, y = _blobs(n=256)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=64)

    def jitter(feats, labels, rng):
        return feats + rng.normal(size=feats.shape).astype(np.float32), labels

    def plan(seed):
        return make_sharded_batches(
            ShardedDataFrame(tmp_path), "features", "label", batch_size=8,
            num_workers=4, window=2, num_epoch=2, seed=seed,
            transform=jitter)

    a, b = plan(3), plan(3)
    np.testing.assert_array_equal(a.round(0)[0], b.round(0)[0])
    np.testing.assert_array_equal(a.round(3)[0], b.round(3)[0])
    # Same underlying rows (no shuffle, epochs repeat the schedule), fresh
    # rng per round: epoch-0 and epoch-1 passes over a row differ.
    rounds_per_epoch = a.num_rounds // 2
    assert not np.array_equal(a.round(0)[0], a.round(rounds_per_epoch)[0])
    assert not np.array_equal(a.round(0)[0], plan(4).round(0)[0])


def test_transform_round_local_matches_full_round(tmp_path):
    """Disjoint per-host staging must transform identically to full staging:
    the rng is seeded by GLOBAL worker id, so round_local(r, ws) ==
    round(r)[ws] even for randomized transforms."""
    x, y = _blobs(n=256)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=64)

    def aug(feats, labels, rng):
        flip = rng.random(len(feats)) < 0.5
        out = feats.copy()
        out[flip] = -out[flip]
        return out, labels

    plan = make_sharded_batches(
        ShardedDataFrame(tmp_path), "features", "label", batch_size=8,
        num_workers=4, window=2, shuffle=True, seed=9, transform=aug)
    xs, ys = plan.round(1)
    xl, yl = plan.round_local(1, [1, 2])
    np.testing.assert_array_equal(xl, xs[1:3])
    np.testing.assert_array_equal(yl, ys[1:3])


def test_in_ram_plan_transform_applies_and_is_deterministic():
    """The same transform hook works on in-RAM plans (Trainer(transform=...)
    is dataframe-type-agnostic): the transformed round equals
    apply_round_transform of the untransformed round, rebuild-stable."""
    from distkeras_tpu.data.batching import apply_round_transform

    x, y = _blobs(n=256)
    df = DataFrame({"features": x, "label": y})

    def jitter(feats, labels, rng):
        return feats + rng.normal(size=feats.shape).astype(np.float32), labels

    kw = dict(batch_size=8, num_workers=4, window=2, seed=11)
    plain = make_batches(df, "features", "label", **kw)
    a = make_batches(df, "features", "label", transform=jitter, **kw)
    b = make_batches(df, "features", "label", transform=jitter, **kw)
    for r in (0, 1):
        xs, ys = plain.round(r)
        ex, ey = apply_round_transform(jitter, 11, r, range(4), xs, ys)
        np.testing.assert_array_equal(a.round(r)[0], ex)
        np.testing.assert_array_equal(a.round(r)[0], b.round(r)[0])
        np.testing.assert_array_equal(a.round(r)[1], ey)


def test_trainer_accepts_transform_on_sharded_store(tmp_path):
    """End-to-end: Trainer(transform=...) threads into the plan; an identity
    transform trains bit-identically to no transform."""
    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    import jax.numpy as jnp

    x, y = _blobs(n=512)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=128)
    model = Model.build(MLP(hidden=(8,), num_outputs=3), jnp.zeros((1, 4)))

    def run(transform):
        tr = dk.ADAG(model, num_workers=2, batch_size=8,
                     communication_window=2, num_epoch=1,
                     loss="sparse_categorical_crossentropy",
                     transform=transform)
        trained = tr.train(ShardedDataFrame(tmp_path))
        return jax.tree.leaves(trained.params)

    ident = run(lambda f, l, rng: (f, l))
    plain = run(None)
    for a, b in zip(ident, plain):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_dataframe_blocks_in_ram_ops(tmp_path):
    x, y = _blobs(n=64)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=32)
    sdf = ShardedDataFrame(tmp_path)
    assert sdf.count() == 64 and "features" in sdf
    with pytest.raises(AttributeError, match="ingest time"):
        sdf.shuffle()


# ----------------------------------------------------- training equivalence


def _train_sync(df, num_workers=4, rounds_per_program=1):
    from distkeras_tpu import SynchronousDistributedTrainer
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)
    t = SynchronousDistributedTrainer(
        model, loss="sparse_categorical_crossentropy",
        num_workers=num_workers, batch_size=8, num_epoch=2,
        learning_rate=0.1, steps_per_program=4,
        rounds_per_program=rounds_per_program)
    trained = t.train(df)
    return trained, t


def test_sharded_training_matches_in_ram_same_schedule(tmp_path):
    """A sharded-store run must produce bit-equal training to an in-RAM run
    with the identical index matrix: staging path changes, semantics don't."""
    x, y = _blobs(n=512)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=64)
    sdf = ShardedDataFrame(tmp_path)

    trained_s, ts = _train_sync(sdf)

    # In-RAM plan with the same worker-major schedule, run via the engine.
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.parallel.sync import SyncEngine
    from distkeras_tpu.runtime.mesh import data_mesh

    plan_s = make_sharded_batches(sdf, "features", "label", batch_size=8,
                                  num_workers=4, window=4, num_epoch=2)
    ram_plan = BatchPlan(x=x, y=y, index=plan_s.index, num_workers=4,
                         window=4, batch_size=8, rows_total=512 * 2)
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)
    eng = SyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                     data_mesh(num_workers=4), learning_rate=0.1)
    state, losses = eng.run(ram_plan)

    for a, b in zip(jax.tree.leaves(trained_s.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(ts.get_history(), np.asarray(losses), rtol=1e-6)


def test_sharded_training_blocked_matches_per_round(tmp_path):
    """rounds_per_program>1 must stage blocked sharded batches identically."""
    x, y = _blobs(n=512)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=128)
    t1 = _train_sync(ShardedDataFrame(tmp_path), rounds_per_program=1)[1]
    t4 = _train_sync(ShardedDataFrame(tmp_path), rounds_per_program=4)[1]
    np.testing.assert_allclose(t1.get_history(), t4.get_history(), rtol=1e-6)


def test_async_trainer_on_sharded_store_converges(tmp_path):
    from distkeras_tpu import ADAG
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    x, y = _blobs(n=1024)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=256)
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)
    t = ADAG(model, loss="sparse_categorical_crossentropy", num_workers=4,
             batch_size=8, num_epoch=3, learning_rate=0.1,
             communication_window=4)
    trained = t.train(ShardedDataFrame(tmp_path))
    logits = np.asarray(trained.predict(x))
    assert (logits.argmax(-1) == y).mean() > 0.85
    assert t.get_history()[-1] < t.get_history()[0]


# ------------------------------------------------- out-of-core inference


def test_sharded_predict_and_evaluate(tmp_path):
    """End-to-end out-of-core inference: predictions stream to disk as a new
    store column (one shard in RAM at a time), and evaluators reduce over
    the stream — matching the in-RAM path exactly."""
    from distkeras_tpu import (AccuracyEvaluator, ClassPredictor,
                               F1Evaluator, LossEvaluator, ModelPredictor)
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    x, y = _blobs(n=200)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=64)
    sdf = ShardedDataFrame(tmp_path)
    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)

    out_sdf = ModelPredictor(model, chunk_size=32).predict(sdf)
    assert "prediction" in out_sdf
    # The prediction column landed as shard files next to the data.
    assert (tmp_path / "shard-00000.prediction.npy").exists()
    ram = DataFrame({"features": x, "label": y})
    out_ram = ModelPredictor(model, chunk_size=32).predict(ram)
    np.testing.assert_allclose(
        out_sdf.store.gather("prediction", np.arange(200)),
        np.asarray(out_ram["prediction"]), rtol=1e-5, atol=1e-6)

    # class-id variant writes int classes
    cls_sdf = ClassPredictor(model, output_col="cls").predict(out_sdf)
    np.testing.assert_array_equal(
        cls_sdf.store.gather("cls", np.arange(200)),
        out_sdf.store.gather("prediction", np.arange(200)).argmax(-1))

    # streaming evaluators == in-RAM evaluators
    for ev in (AccuracyEvaluator(), F1Evaluator(),
               LossEvaluator("sparse_categorical_crossentropy")):
        a = ev.evaluate(out_sdf)
        b = ev.evaluate(out_ram)
        assert a == pytest.approx(b, rel=1e-5), type(ev).__name__


def test_sharded_repredict_versions_column(tmp_path):
    """Re-predicting an existing output column writes FRESH physical files
    and swaps the manifest atomically — a crash mid-stream can never mix two
    models' outputs under one column."""
    from distkeras_tpu import ModelPredictor
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    x, y = _blobs(n=64)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=32)
    m1 = Model.build(MLP(hidden=(8,), num_outputs=3),
                     np.zeros((1, 4), np.float32), seed=0)
    m2 = Model.build(MLP(hidden=(8,), num_outputs=3),
                     np.zeros((1, 4), np.float32), seed=1)
    s1 = ModelPredictor(m1).predict(ShardedDataFrame(tmp_path))
    v1 = s1.store.gather("prediction", np.arange(64))
    s2 = ModelPredictor(m2).predict(s1)
    v2 = s2.store.gather("prediction", np.arange(64))
    assert not np.allclose(v1, v2)  # new model's outputs are live
    # the second version lives under a versioned physical file name
    spec = s2.store.columns["prediction"]
    assert spec.get("file", "prediction") != "prediction"
    np.testing.assert_allclose(v2, np.asarray(m2.predict(x)), rtol=1e-5,
                               atol=1e-6)


def test_predict_stream_handles_empty_microbatches():
    from distkeras_tpu.predictors import StreamingPredictor
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    x, _ = _blobs(n=24)
    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)
    p = StreamingPredictor(model, chunk_size=16)
    source = [x[:8], x[:0], x[8:24], np.empty((0,), np.float32)]
    outs = list(p.predict_stream(iter(source)))
    assert [len(o) for o in outs] == [8, 0, 16, 0]
    np.testing.assert_allclose(np.concatenate([o for o in outs if len(o)]),
                               np.asarray(model.predict(x)), rtol=1e-5,
                               atol=1e-6)


def test_sharded_predict_buffers_across_small_shards(tmp_path):
    """Shards smaller than chunk_size buffer into full compute chunks — only
    the final partial chunk is padded (no per-shard FLOP multiplication) —
    and outputs still land on exact shard boundaries."""
    from distkeras_tpu import ModelPredictor
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    x, y = _blobs(n=100)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=16)
    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)
    p = ModelPredictor(model, chunk_size=64)
    calls = []
    orig = p._predict_array
    p._predict_array = lambda arr: calls.append(len(arr)) or orig(arr)
    out = p.predict(ShardedDataFrame(tmp_path))
    # 100 rows at chunk 64: one full 64-row chunk + one 36-row tail — not
    # seven 16-row shard calls each padded to 64.
    assert calls == [64, 36], calls
    np.testing.assert_allclose(
        out.store.gather("prediction", np.arange(100)),
        np.asarray(orig(x)), rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- out-of-core


def test_memmap_dataframe_stays_on_disk(tmp_path):
    """The single-host out-of-core path: a DataFrame over memmap columns goes
    through make_batches without copying the data (views all the way down)."""
    x, y = _blobs(n=256)
    np.save(tmp_path / "x.npy", x)
    np.save(tmp_path / "y.npy", y)
    mx = np.load(tmp_path / "x.npy", mmap_mode="r")
    my = np.load(tmp_path / "y.npy", mmap_mode="r")
    df = DataFrame({"features": mx, "label": my})
    plan = make_batches(df, "features", "label", batch_size=8, num_workers=4,
                        window=2)
    assert np.shares_memory(plan.x, mx)  # no hidden materialization
    xs, _ = plan.round(0)
    np.testing.assert_array_equal(xs, x[plan.index[0]])


def test_virtual_huge_dataset_feeds_from_disk(tmp_path):
    """An ImageNet-shaped virtual dataset (sparse file, 64 GiB logical) feeds
    training rounds while only the touched rows' pages ever materialize —
    the BASELINE #5 shape that broke the full-RAM contract."""
    n, h, w, c = 70_000, 224, 224, 3  # ~42 GiB of float32 features
    feat_path = str(tmp_path / "feat.dat")
    feats = np.memmap(feat_path, np.float32, mode="w+", shape=(n, h, w, c))
    # Write only a handful of rows; the rest stay unallocated (sparse).
    touched = [0, 1, 69_999]
    for i in touched:
        feats[i, 0, 0, 0] = float(i)
    feats.flush()
    labels = np.zeros(n, np.int32)
    # The file is sparse: logical size huge, allocated blocks tiny.
    st = os.stat(feat_path)
    assert st.st_size == n * h * w * c * 4
    assert st.st_blocks * 512 < 64 * 1024 * 1024, "file unexpectedly dense"

    df = DataFrame({"features": np.memmap(feat_path, np.float32, mode="r",
                                          shape=(n, h, w, c)),
                    "label": labels})
    plan = make_batches(df, "features", "label", batch_size=2, num_workers=4,
                        window=1)
    xs, ys = plan.round(0)  # gathers 8 rows = ~4.6 MB, not 42 GiB
    assert xs.shape == (4, 1, 2, h, w, c)
    assert xs[0, 0, 0, 0, 0, 0] == 0.0 and ys.shape == (4, 1, 2)


def test_repredict_defers_deletion_and_vacuum_reclaims(tmp_path):
    """ADVICE r5 reader contract: a re-predict must NOT unlink the
    superseded physical column (concurrent readers holding the old manifest
    race to FileNotFoundError) — it goes on the manifest's ``garbage`` list
    and is reclaimed by the NEXT predict run or an explicit vacuum()."""
    import os

    from distkeras_tpu import ModelPredictor
    from distkeras_tpu.data.shards import ShardStore, _shard_file
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import vacuum

    x, y = _blobs(n=64)
    write_shards(tmp_path, {"features": x, "label": y}, rows_per_shard=32)
    models = [Model.build(MLP(hidden=(8,), num_outputs=3),
                          np.zeros((1, 4), np.float32), seed=s)
              for s in range(3)]
    s1 = ModelPredictor(models[0]).predict(ShardedDataFrame(tmp_path))
    old_store = ShardStore.open(str(tmp_path))  # a concurrent reader
    s2 = ModelPredictor(models[1]).predict(s1)
    # v1's files ("prediction" physical) are still on disk: the old reader
    # can gather rows it never memmapped before the swap.
    v1 = old_store.gather("prediction", np.arange(64))
    np.testing.assert_allclose(v1, np.asarray(models[0].predict(x)),
                               rtol=1e-5, atol=1e-6)
    garbage = s2.store.manifest.get("garbage", [])
    assert garbage == ["prediction"], garbage
    # The NEXT predict run reclaims what the previous publish deferred...
    s3 = ModelPredictor(models[2]).predict(s2)
    for s in range(s3.store.num_shards):
        assert not os.path.exists(
            os.path.join(str(tmp_path), _shard_file(s, "prediction")))
    # ...and records the new superseded version in its place.
    garbage3 = s3.store.manifest.get("garbage", [])
    old_physical = s2.store.columns["prediction"]["file"]
    assert garbage3 == [old_physical]
    # vacuum() reclaims immediately and clears the list.
    removed = vacuum(str(tmp_path))
    assert removed == s3.store.num_shards
    fresh = ShardStore.open(str(tmp_path))
    assert "garbage" not in fresh.manifest
    for s in range(fresh.num_shards):
        assert not os.path.exists(
            os.path.join(str(tmp_path), _shard_file(s, old_physical)))
    # the live column still reads
    v3 = fresh.gather("prediction", np.arange(64))
    np.testing.assert_allclose(v3, np.asarray(models[2].predict(x)),
                               rtol=1e-5, atol=1e-6)
