"""Predictors, evaluators, checkpoint/resume, metrics tests."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import DataFrame, DOWNPOUR
from distkeras_tpu.evaluators import AccuracyEvaluator, F1Evaluator, LossEvaluator
from distkeras_tpu.metrics import MetricsLogger, scaling_efficiency
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.predictors import (
    ClassPredictor,
    ModelPredictor,
    ProbabilityPredictor,
    StreamingClassPredictor,
    StreamingPredictor,
)


def tiny_model(d=4, c=3, seed=0):
    return Model.build(MLP(hidden=(8,), num_outputs=c), jnp.zeros((1, d), jnp.float32),
                       seed=seed)


def small_df(n=70, d=4, c=3):
    rng = np.random.default_rng(0)
    return DataFrame({"features": rng.normal(size=(n, d)).astype(np.float32),
                      "label": rng.integers(0, c, size=n).astype(np.int32)})


def test_model_predictor_appends_logits_all_rows():
    df = small_df(n=70)
    model = tiny_model()
    out = ModelPredictor(model, output_col="pred", chunk_size=32).predict(df)
    assert out["pred"].shape == (70, 3)  # padding trimmed
    # chunked result == direct forward
    direct = np.asarray(model.predict(jnp.asarray(df["features"])))
    np.testing.assert_allclose(out["pred"], direct, rtol=1e-5, atol=1e-5)


def test_probability_and_class_predictors():
    df = small_df(n=16)
    model = tiny_model()
    probs = ProbabilityPredictor(model, output_col="p").predict(df)["p"]
    np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-5)
    cls = ClassPredictor(model, output_col="c").predict(df)["c"]
    assert cls.dtype == np.int32 and set(np.unique(cls)) <= {0, 1, 2}


def test_streaming_predictor_matches_batch_predict():
    """Streaming over ragged microbatches ≡ one-shot dataframe predict (the
    Kafka streaming-inference example's correctness contract)."""
    df = small_df(n=203)
    model = tiny_model()
    expect = np.asarray(ModelPredictor(model, chunk_size=64).predict(df)["prediction"])

    x = np.asarray(df["features"])
    rng = np.random.default_rng(7)
    cuts = np.sort(rng.choice(np.arange(1, len(x)), size=11, replace=False))
    microbatches = np.split(x, cuts)  # ragged sizes, incl. ones crossing chunks

    sp = StreamingPredictor(model, chunk_size=64)
    outs = list(sp.predict_stream(iter(microbatches)))
    assert [len(o) for o in outs] == [len(m) for m in microbatches]  # in order
    np.testing.assert_allclose(np.concatenate(outs, axis=0), expect, rtol=1e-5)


def test_streaming_class_predictor_small_trickle():
    """Single-record microbatches, total smaller than one chunk: everything
    flushes at end-of-stream and class ids match ClassPredictor."""
    df = small_df(n=9)
    model = tiny_model()
    expect = np.asarray(ClassPredictor(model, chunk_size=64).predict(df)["prediction"])
    sp = StreamingClassPredictor(model, chunk_size=64)
    outs = list(sp.predict_stream(row[None] for row in np.asarray(df["features"])))
    assert len(outs) == 9 and all(len(o) == 1 for o in outs)
    np.testing.assert_array_equal(np.concatenate(outs), expect)


def test_streaming_empty_polls_have_output_tail_shape():
    """Empty microbatches (empty stream polls) must yield zero-row blocks with
    the predictor's OUTPUT tail shape/dtype — including before any row has
    been computed — so concatenating all stream outputs works (r3 advisor)."""
    df = small_df(n=5)
    model = tiny_model()
    x = np.asarray(df["features"])
    empty = x[:0]
    source = [empty, x[:2], empty, x[2:], empty]  # leading/mid/trailing polls

    sp = StreamingPredictor(model, chunk_size=64)
    outs = list(sp.predict_stream(iter(source)))
    assert [len(o) for o in outs] == [0, 2, 0, 3, 0]
    assert all(o.shape[1:] == (3,) for o in outs)  # logits tail, even empties
    cat = np.concatenate(outs, axis=0)  # the advisor's failing operation
    expect = np.asarray(model.predict(jnp.asarray(x)))
    np.testing.assert_allclose(cat, expect, rtol=1e-5, atol=1e-5)

    # Class predictor: empties must be () tail int — postprocess applies.
    scp = StreamingClassPredictor(model, chunk_size=64)
    outs = list(scp.predict_stream(iter([empty, x])))
    assert outs[0].shape == (0,) and outs[0].dtype == np.int32
    assert np.concatenate(outs).shape == (5,)


def test_accuracy_evaluator_mixed_representations():
    logits = np.array([[2.0, 0.1, 0.0], [0.0, 3.0, 0.1], [0.1, 0.0, 1.0]])
    df = DataFrame({"prediction": logits, "label": np.array([0, 1, 0])})
    assert AccuracyEvaluator().evaluate(df) == pytest.approx(2 / 3)
    # integer predictions work too
    df2 = DataFrame({"prediction": np.array([0, 1, 2]), "label": np.array([0, 1, 1])})
    assert AccuracyEvaluator().evaluate(df2) == pytest.approx(2 / 3)


def test_f1_evaluator_perfect_and_degenerate():
    df = DataFrame({"prediction": np.array([0, 1, 1, 0]), "label": np.array([0, 1, 1, 0])})
    assert F1Evaluator().evaluate(df) == pytest.approx(1.0)
    df2 = DataFrame({"prediction": np.array([1, 1, 1, 1]), "label": np.array([0, 1, 1, 0])})
    assert F1Evaluator().evaluate(df2) < 0.5


def test_loss_evaluator():
    df = DataFrame({"prediction": np.array([[10.0, 0.0], [0.0, 10.0]]),
                    "label": np.array([0, 1])})
    assert LossEvaluator().evaluate(df) < 0.01


def test_metrics_logger_writes_jsonl(tmp_path):
    import time

    path = str(tmp_path / "m.jsonl")
    logger = MetricsLogger(path, samples_per_round=128, num_chips=4)
    logger(0, 1.5)
    time.sleep(0.002)  # distinct timing segments (see _BURST_EPS_S)
    logger(1, 1.2)
    logger.close()
    import json

    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["loss"] == 1.5 and lines[1]["round"] == 1
    assert "samples_per_sec_per_chip" in lines[1]
    assert logger.mean_throughput() > 0


def test_scaling_efficiency():
    assert scaling_efficiency(800, 100, 8) == pytest.approx(1.0)
    assert scaling_efficiency(400, 100, 8) == pytest.approx(0.5)


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Train 4 epochs straight vs 2 epochs + checkpoint + resume: same rounds run."""
    pytest.importorskip("orbax.checkpoint")
    df = small_df(n=256)
    ck = str(tmp_path / "ck")

    # uninterrupted reference run
    t_full = DOWNPOUR(tiny_model(), loss="sparse_categorical_crossentropy",
                      num_workers=4, batch_size=8, communication_window=2,
                      num_epoch=4, learning_rate=0.05)
    m_full = t_full.train(df)

    # interrupted run: same schedule, checkpointing every round; then resume
    t_a = DOWNPOUR(tiny_model(), loss="sparse_categorical_crossentropy",
                   num_workers=4, batch_size=8, communication_window=2,
                   num_epoch=2, learning_rate=0.05,
                   checkpoint_dir=ck, checkpoint_every=1)
    t_a.train(df)

    t_b = DOWNPOUR(tiny_model(), loss="sparse_categorical_crossentropy",
                   num_workers=4, batch_size=8, communication_window=2,
                   num_epoch=4, learning_rate=0.05,
                   checkpoint_dir=ck, checkpoint_every=1, resume=True)
    m_b = t_b.train(df)

    # resumed run skipped the first half
    assert len(t_b.get_history()) == len(t_full.get_history()) - len(t_a.get_history())
    # and lands on the same weights as the uninterrupted run (deterministic folds)
    for a, b in zip(jax.tree.leaves(m_full.params), jax.tree.leaves(m_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_elastic_resume_different_worker_count(tmp_path):
    """A checkpoint written at W=4 resumes at W=2 (pod resize): the center
    variable carries over exactly — rejoining workers pull it, reference PS
    semantics — and training continues to convergence."""
    import distkeras_tpu as dk
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    rng = np.random.default_rng(0)
    n, d, c = 640, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    df = dk.DataFrame({"features": x, "label": y.astype(np.int32)})

    def model():
        return Model.build(MLP(hidden=(16,), num_outputs=c),
                           jnp.zeros((1, d), jnp.float32))

    ck = str(tmp_path / "ck")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16,
                  learning_rate=0.1, communication_window=2,
                  checkpoint_dir=ck, checkpoint_every=2)
    t1 = dk.ADAG(model(), num_workers=4, num_epoch=2, **common)
    first = t1.train(df)

    # Resume at HALF the workers, doubling epochs: data progress (not the
    # raw round counter) carries over, so the W=2 plan resumes exactly where
    # the W=4 run's samples left off — round 20 of 40.
    t2 = dk.ADAG(model(), num_workers=2, num_epoch=4, resume=True, **common)
    resumed = t2.train(df)
    logits = np.asarray(resumed.predict(jnp.asarray(x)))
    acc = float((logits.argmax(-1) == y).mean())
    assert acc > 0.9, f"elastic-resumed model failed to converge: {acc}"
    assert len(t2.get_history()) == 20  # rounds 20..39, not a restart
    # The resumed run continued, not restarted: its first-round loss is far
    # below a cold start's (the W=4 model already fit the data).
    assert t2.get_history()[0] < t1.get_history()[0] * 0.5


def test_checkpointer_save_decline_signals(tmp_path):
    """Orbax declines saves at step <= latest_step; Checkpointer.save must
    return False, warn, and leave no stale meta sidecar (ADVICE r2)."""
    pytest.importorskip("orbax.checkpoint")
    from distkeras_tpu.checkpoint import Checkpointer

    ck = Checkpointer(str(tmp_path / "ck"))
    state = {"w": np.arange(4, dtype=np.float32)}
    assert ck.save(5, state, wait=True, meta={"round": 5}) is True
    with pytest.warns(UserWarning, match="declined"):
        assert ck.save(3, state, wait=True, meta={"round": 3}) is False
    assert ck.latest_step() == 5
    assert ck.meta(3) is None  # no sidecar for the unwritten step
    assert ck.meta(5) == {"round": 5}
    ck.close()


def test_elastic_resume_scale_up_keeps_checkpointing(tmp_path):
    """Scale-UP resume maps the resume round BELOW the saved Orbax step;
    without monotonic step numbering every post-resize save is silently
    declined (ADVICE r2, medium). Verify post-resize checkpoints persist and
    a subsequent resume continues from post-resize progress."""
    import warnings as _warnings

    import distkeras_tpu as dk
    from distkeras_tpu.checkpoint import Checkpointer
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    rng = np.random.default_rng(0)
    n, d, c = 640, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    df = dk.DataFrame({"features": x, "label": y.astype(np.int32)})

    def model():
        return Model.build(MLP(hidden=(16,), num_outputs=c),
                           jnp.zeros((1, d), jnp.float32))

    ck = str(tmp_path / "ck")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16,
                  learning_rate=0.1, communication_window=2,
                  checkpoint_dir=ck, checkpoint_every=2)
    # W=2: 20 rounds (640/(2*2*16)=10 per epoch x 2); last save at round 19.
    t1 = dk.ADAG(model(), num_workers=2, num_epoch=2, **common)
    t1.train(df)

    # Scale UP to W=4: resume round = (19+1)*2//4 = 10 < 19 — the resumed
    # run's rounds 10..19 would all be declined without the step offset.
    with _warnings.catch_warnings():
        _warnings.simplefilter("error", UserWarning)  # a declined save warns
        # Orbax 0.7.x emits an informational UserWarning when restore args
        # carry no sharding ("Couldn't find sharding info...") — unrelated
        # to the declined-save signal this filter is hunting.
        _warnings.filterwarnings(
            "ignore", message="Couldn't find sharding info")
        t2 = dk.ADAG(model(), num_workers=4, num_epoch=4, resume=True,
                     **common)
        t2.train(df)
    assert len(t2.get_history()) == 10  # rounds 10..19 of the W=4 plan

    reader = Checkpointer(ck)
    latest = reader.latest_step()
    assert latest > 19  # post-resize saves persisted past the W=2 steps
    meta = reader.meta(latest)
    reader.close()
    assert meta["num_workers"] == 4
    assert meta["round"] == 19  # true round recorded, decoupled from step

    # A further same-topology resume starts AFTER the post-resize progress —
    # nothing to replay (round 19 was the final round of the W=4 plan).
    t3 = dk.ADAG(model(), num_workers=4, num_epoch=4, resume=True, **common)
    t3.train(df)
    assert len(t3.get_history()) == 0


def test_fresh_run_into_nonempty_checkpoint_dir_still_saves(tmp_path):
    """resume=False into a dir holding prior checkpoints: rounds restart at 0
    but saves must not be declined (steps offset past the existing ones)."""
    import warnings as _warnings

    import distkeras_tpu as dk
    from distkeras_tpu.checkpoint import Checkpointer

    df = small_df(n=256)
    ck = str(tmp_path / "ck")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=8,
                  learning_rate=0.05, num_workers=4, num_epoch=2,
                  communication_window=2, checkpoint_dir=ck,
                  checkpoint_every=2)
    dk.DOWNPOUR(tiny_model(), **common).train(df)  # 8 rounds; last save r=7
    reader = Checkpointer(ck)
    first_latest = reader.latest_step()
    reader.close()

    with _warnings.catch_warnings():
        _warnings.simplefilter("error", UserWarning)
        dk.DOWNPOUR(tiny_model(), **common).train(df)
    reader = Checkpointer(ck)
    assert reader.latest_step() > first_latest
    assert reader.meta(reader.latest_step())["round"] == 7
    reader.close()


def test_sync_resume_resized_rescales_data_progress(tmp_path):
    """SyncEngine state is W-independent, so a resized resume restores
    exactly — but data progress must rescale (with a warning), not restart
    from the raw round counter (ADVICE r2, low)."""
    import distkeras_tpu as dk

    df = small_df(n=256)
    ck = str(tmp_path / "ck")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=8,
                  learning_rate=0.05, checkpoint_dir=ck, checkpoint_every=1)
    # W=4: 256/(4*8*8)=1 round/epoch at window 8 -> use steps_per_program=2:
    # samples/round = 4*2*8 = 64 -> 4 rounds/epoch; 2 epochs = 8 rounds.
    t1 = dk.SynchronousDistributedTrainer(
        tiny_model(), num_workers=4, num_epoch=2, steps_per_program=2,
        **common)
    t1.train(df)
    assert len(t1.get_history()) == 8

    # Resume at W=2 with 4 epochs: samples/round = 2*2*8 = 32 -> 8 rounds/
    # epoch, 32 total; data progress 8 rounds * 64 samples = 16 W=2 rounds.
    with pytest.warns(UserWarning, match="rescaled"):
        t2 = dk.SynchronousDistributedTrainer(
            tiny_model(), num_workers=2, num_epoch=4, steps_per_program=2,
            resume=True, **common)
        t2.train(df)
    assert len(t2.get_history()) == 32 - 16


def test_elastic_resume_rejects_ensemble(tmp_path):
    """EnsembleFold trains only the per-worker replicas; pull-the-center
    elastic resume would silently discard them — must refuse loudly."""
    import distkeras_tpu as dk
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    rng = np.random.default_rng(1)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    y = rng.integers(0, 3, size=256).astype(np.int32)
    df = dk.DataFrame({"features": x, "label": y})

    def model():
        return Model.build(MLP(hidden=(8,), num_outputs=3),
                           jnp.zeros((1, 4), jnp.float32))

    ck = str(tmp_path / "ck")
    common = dict(loss="sparse_categorical_crossentropy", batch_size=16,
                  learning_rate=0.1, communication_window=2,
                  checkpoint_dir=ck, checkpoint_every=2)
    dk.EnsembleTrainer(model(), num_workers=4, num_epoch=2, **common).train(df)
    with pytest.raises(ValueError, match="elastically"):
        dk.EnsembleTrainer(model(), num_workers=2, num_epoch=2, resume=True,
                           **common).train(df)
