"""The same-host fast path and hierarchical folds: shm ring dialect
negotiation (boot-id check + caps fallback matrix), ring-level chaos,
exactly-once/eviction guarantees on the ring, compressed-domain folds,
and the per-host aggregator's flat-topology parity."""

import threading
import time

import numpy as np
import pytest

from distkeras_tpu.netps import (
    AggregatorServer,
    PSClient,
    PSServer,
)
from distkeras_tpu.netps import shm, wire
from distkeras_tpu.netps import fold as netfold
from distkeras_tpu.resilience import faults
from distkeras_tpu.resilience.faults import FaultPlan

FAST = dict(timeout=1.0, retries=3, backoff=0.01)


def leaves(*shapes):
    rng = np.random.default_rng(0)
    return [rng.normal(size=s).astype(np.float32) for s in shapes]


def shm_pair(**kw):
    srv = PSServer(discipline=kw.pop("discipline", "adag"),
                   lease_s=kw.pop("lease_s", None), transport="shm").start()
    client_kw = dict(FAST)
    client_kw.update(kw)
    return srv, PSClient(srv.endpoint, worker_id=0, transport="shm",
                         **client_kw)


# ---------------------------------------------------------------------------
# Ring dialect: negotiation + roundtrip
# ---------------------------------------------------------------------------

def test_shm_join_pull_commit_roundtrip_and_transport_label():
    from distkeras_tpu import telemetry

    telemetry.reset()
    srv, c = shm_pair()
    try:
        init = leaves((3, 2), (4,))
        center, upd = c.join(init=init)
        assert c.active_transport == "shm"
        for a, b in zip(center, init):
            np.testing.assert_array_equal(a, b)
        res = c.commit([np.ones_like(a) for a in init], upd)
        assert res.applied and res.staleness == 0
        center2, upd2 = c.pull()
        assert upd2 == 1
        np.testing.assert_allclose(center2[0], init[0] + 1.0)
        assert c.heartbeat() == 1
        snap = telemetry.get().snapshot()
        # RPC + server spans carry the transport dialect label (join went
        # over TCP — negotiation precedes the upgrade).
        assert snap["spans"]["netps.rpc.commit.shm"]["count"] == 1
        assert snap["spans"]["netps.server.commit.shm"]["count"] == 1
        assert snap["spans"]["netps.rpc.join"]["count"] == 1
        # the commit exported the fold-throughput gauge
        assert snap["gauges"]["netps.fold.tensors_per_sec"]["value"] > 0
        c.leave()
    finally:
        c.close()
        srv.close()
        telemetry.reset()


def test_shm_striped_commit_keeps_exactly_once():
    srv, c = shm_pair(discipline="downpour", shards=2)
    try:
        init = leaves((40, 3), (7,), (90,))
        _, upd = c.join(init=init)
        assert c.active_transport == "shm" and c.active_shards == 2
        res = c.commit([np.full_like(a, 2.0) for a in init], upd)
        assert res.applied
        center, _ = c.pull()
        for a, i in zip(center, init):
            np.testing.assert_allclose(a, i + 2.0)
        assert srv.commit_log == [(0, 0, 0)]
    finally:
        c.close()
        srv.close()


def test_shm_retransmit_is_deduped():
    """The ring's exactly-once half: a hand-crafted retransmit of an
    already-folded seq over the ring is answered by dedup, not re-folded."""
    srv, c = shm_pair()
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        assert c.commit([np.ones(3, np.float32)], upd).applied
        hdr, _ = c._rpc("commit", {"seq": 0, "pulled": 0},
                        [np.ones(3, np.float32)])
        assert hdr["duplicate"] is True
        assert srv.commit_log == [(0, 0, 0)]
        np.testing.assert_allclose(srv.center()[0], 1.0)
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# Caps-negotiation fallback matrix: everything lands on TCP, silently
# ---------------------------------------------------------------------------

def test_new_client_old_server_falls_back_to_tcp(monkeypatch):
    """A PR 5 server advertises no shm endpoint: the shm-requesting client
    must speak TCP with every guarantee intact."""
    monkeypatch.setattr(wire, "CAPS",
                        {"codecs": list(wire.CODECS), "striping": True})
    srv = PSServer(discipline="adag").start()  # tcp: no ring listener
    try:
        with PSClient(srv.endpoint, worker_id=0, transport="shm",
                      **FAST) as c:
            init = leaves((8,))
            _, upd = c.join(init=init)
            assert c.active_transport == "tcp" and c.shm_info is None
            assert c.commit([np.ones(8, np.float32)], upd).applied
            center, _ = c.pull()
            np.testing.assert_allclose(center[0], init[0] + 1.0)
    finally:
        srv.close()


def test_old_client_new_server_stays_on_tcp():
    """A tcp-mode client against a ring-serving server ignores the shm
    advert entirely (the PR 4/PR 5 client behavior: unknown caps keys are
    just ignored)."""
    srv = PSServer(discipline="adag", transport="shm").start()
    try:
        with PSClient(srv.endpoint, worker_id=0, transport="tcp",
                      **FAST) as c:
            init = leaves((8,))
            _, upd = c.join(init=init)
            assert c.active_transport == "tcp"
            assert c.commit([np.ones(8, np.float32)], upd).applied
    finally:
        srv.close()


def test_cross_host_boot_id_mismatch_falls_back_to_tcp(monkeypatch):
    """Boot ids disagree (a cross-host pair that both set
    DKTPU_NET_TRANSPORT=shm): the client must silently stay on TCP."""
    srv = PSServer(discipline="adag", transport="shm").start()
    # The server snapshotted its boot id at construction; patching the
    # module now changes only what the CLIENT computes for the check.
    monkeypatch.setattr(shm, "local_boot_id", lambda: "some-other-host")
    try:
        with PSClient(srv.endpoint, worker_id=0, transport="shm",
                      **FAST) as c:
            init = leaves((8,))
            _, upd = c.join(init=init)
            assert c.active_transport == "tcp" and c.shm_info is None
            assert c.commit([np.ones(8, np.float32)], upd).applied
            center, _ = c.pull()
            np.testing.assert_allclose(center[0], init[0] + 1.0)
    finally:
        srv.close()


def test_invisible_uds_path_falls_back_to_tcp(monkeypatch):
    """Colocated containers share a boot id but not a mount namespace: an
    advertised doorbell path this process cannot see must keep the client
    on TCP instead of burning retries on an unconnectable socket."""
    srv = PSServer(discipline="adag", transport="shm").start()
    monkeypatch.setattr(shm, "endpoint_visible", lambda path: False)
    try:
        with PSClient(srv.endpoint, worker_id=0, transport="shm",
                      **FAST) as c:
            _, upd = c.join(init=leaves((8,)))
            assert c.active_transport == "tcp" and c.shm_info is None
            assert c.commit([np.ones(8, np.float32)], upd).applied
    finally:
        srv.close()


def test_dead_ring_endpoint_falls_back_to_tcp():
    """A ring endpoint that stops answering (server restarted TCP-only,
    segment dir wiped) must not wedge the client: after two consecutive
    ring failures the call falls back to TCP — which the server always
    serves — instead of burning the whole retry budget on the doorbell."""
    srv, c = shm_pair(timeout=0.3, retries=4)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        assert c.active_transport == "shm"
        # Simulate the endpoint dying: point the negotiated info at a
        # socket nobody serves and drop the live connections.
        c.shm_info = dict(c.shm_info, uds=c.shm_info["uds"] + ".gone")
        for conn in c._conns:
            c._disconnect(conn)
        center, _ = c.pull()  # succeeds over TCP within the retry budget
        np.testing.assert_array_equal(center[0], np.zeros(3))
        assert c.active_transport == "tcp"
    finally:
        c.close()
        srv.close()


def test_unknown_transport_is_typed_error():
    with pytest.raises(ValueError, match="transport"):
        PSClient("h:1", transport="carrier-pigeon")
    with pytest.raises(ValueError, match="transport"):
        PSServer(transport="carrier-pigeon")


# ---------------------------------------------------------------------------
# Ring-level chaos: shm_delay / shm_corrupt
# ---------------------------------------------------------------------------

def test_shm_corrupt_is_survived_and_folds_exactly_once():
    """THE ring chaos scenario: the commit's slot crc is flipped after the
    write (``shm_corrupt``), the server rejects the frame and tears the
    connection down, the client reconnects with FRESH segments and
    retransmits under the same seq — one fold."""
    srv, c = shm_pair(timeout=0.4, retries=5)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        shm.reset_frames()
        faults.set_net_plan(FaultPlan.parse_net("shm_corrupt@0"))
        res = c.commit([np.ones(3, np.float32)], upd)
        assert res.applied or res.duplicate
        assert srv.commit_log == [(0, 0, 0)], srv.commit_log
        np.testing.assert_allclose(srv.center()[0], 1.0)  # folded ONCE
        assert c.active_transport == "shm"  # recovered on the ring
    finally:
        faults.set_net_plan(None)
        faults.reset()
        c.close()
        srv.close()


def test_shm_delay_is_ridden_out():
    srv, c = shm_pair(timeout=1.0, retries=3)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        shm.reset_frames()
        faults.set_net_plan(FaultPlan.parse_net("shm_delay@0:0.2"))
        t0 = time.monotonic()
        center, _ = c.pull()
        assert time.monotonic() - t0 >= 0.2
        np.testing.assert_array_equal(center[0], np.zeros(3))
    finally:
        faults.set_net_plan(None)
        faults.reset()
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# Leases / eviction / rejoin on the ring
# ---------------------------------------------------------------------------

def test_shm_lease_eviction_and_rejoin():
    srv, c = shm_pair(lease_s=0.3)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        assert c.commit([np.ones(3, np.float32)], upd).applied
        deadline = time.monotonic() + 5.0
        while srv.members() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv.members() == [] and srv.evictions == 1
        center, _ = c.pull()  # transparently re-joins, still on the ring
        assert c.rejoin_count == 1 and srv.rejoins == 1
        assert c.active_transport == "shm"
        np.testing.assert_allclose(center[0], 1.0)
    finally:
        c.close()
        srv.close()


def test_shm_close_joins_every_thread():
    before = {t.name for t in threading.enumerate()}
    srv, c = shm_pair()
    c.join(init=[np.zeros(2, np.float32)])
    c.pull()
    c.close()
    srv.close()
    after = {t.name for t in threading.enumerate()}
    lingering = [n for n in after - before if n.startswith("netps-")]
    assert not lingering, lingering


def test_dead_ring_with_zero_retries_falls_back_on_next_rpc():
    """A fail-fast client (retries=0) whose ring endpoint died must not
    ride the dead ring forever: the final (= only) attempt engages the
    TCP fallback, so THIS rpc fails but the next one lands on TCP."""
    from distkeras_tpu.netps.errors import NetPSError

    srv, c = shm_pair(retries=0, timeout=0.5)
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        c.pull()
        assert c.active_transport == "shm"
        for conn in c._conns:  # kill the ring: dead doorbell endpoint
            c._disconnect(conn)
        c.shm_info = dict(c.shm_info, uds="/nonexistent-dknetps.sock")
        with pytest.raises(NetPSError):
            c.pull()
        assert c.shm_info is None  # fallback engaged on the final attempt
        center, _ = c.pull()  # and the next rpc speaks TCP
        assert c.active_transport == "tcp"
        np.testing.assert_array_equal(center[0], np.zeros(3))
    finally:
        c.close()
        srv.close()


def test_concurrent_shm_clients_isolated_and_one_ring_dies_mid_call():
    """Two frontends (clients) attached to ONE PS host over the ring: each
    client owns its own slot pair, so concurrent commits from both can
    never interleave inside a frame (per-client slot isolation — the
    center ends at the exact sum of both streams, exactly-once intact);
    and when ONE client's ring dies mid-call, that client alone falls back
    to TCP while the sibling keeps speaking shm — ring death is a
    per-connection event, not a host event."""
    srv = PSServer(discipline="downpour", transport="shm").start()
    c0 = PSClient(srv.endpoint, worker_id=0, transport="shm", **FAST)
    c1 = PSClient(srv.endpoint, worker_id=1, transport="shm", **FAST)
    try:
        init = [np.zeros(5, np.float32)]
        _, upd0 = c0.join(init=init)
        _, upd1 = c1.join(init=init)
        assert c0.active_transport == "shm" and c1.active_transport == "shm"

        commits_each = 8
        errs: list = []

        def pump(client, upd, delta):
            try:
                u = upd
                for _ in range(commits_each):
                    res = client.commit([np.full(5, delta, np.float32)], u)
                    assert res.applied
                    _, u = client.pull()
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        t0 = threading.Thread(target=pump, args=(c0, upd0, 1.0))
        t1 = threading.Thread(target=pump, args=(c1, upd1, 10.0))
        t0.start()
        t1.start()
        t0.join()
        t1.join()
        assert not errs, errs
        center, _ = c0.pull()
        # Slot isolation: both streams folded exactly once each — any
        # cross-client frame interleave would break this exact total.
        np.testing.assert_allclose(
            center[0], commits_each * 1.0 + commits_each * 10.0)
        assert {wid for wid, _s, _t in srv.commit_log} == {0, 1}

        # Kill ONLY c0's ring mid-flight: its next rpc rides the retry
        # budget onto TCP; c1 stays on shm untouched.
        for conn in c0._conns:
            c0._disconnect(conn)
        c0.shm_info = dict(c0.shm_info, uds="/nonexistent-dknetps.sock")
        center0, _ = c0.pull()  # retried onto TCP inside the budget
        assert c0.active_transport == "tcp"
        np.testing.assert_allclose(center0[0], 88.0)
        center1, _ = c1.pull()
        assert c1.active_transport == "shm", \
            "the sibling's ring must survive its neighbor's death"
        np.testing.assert_allclose(center1[0], 88.0)
    finally:
        c0.close()
        c1.close()
        srv.close()


def test_accept_attach_closes_fds_when_slot_ctor_raises(monkeypatch):
    """A Slot ctor failure (e.g. mmap ENOMEM under memory pressure) mid
    attach must close BOTH received fds — each failed attach would
    otherwise leak 2 fds + a mapping until the server hits EMFILE."""
    import os
    import socket as pysock

    a, b = pysock.socketpair(pysock.AF_UNIX, pysock.SOCK_STREAM)
    s1, s2 = shm.create_slot(), shm.create_slot()
    try:
        pysock.send_fds(a, [b"DKATTACH"], [s1.fd, s2.fd])
        real = shm.Slot
        calls = []

        def second_ctor_raises(fd, size=None):
            if calls:
                raise OSError("synthetic ENOMEM")
            calls.append(1)
            return real(fd, size)

        monkeypatch.setattr(shm, "Slot", second_ctor_raises)
        before = len(os.listdir("/proc/self/fd"))
        with pytest.raises(OSError):
            shm.accept_attach(b)
        assert len(os.listdir("/proc/self/fd")) == before
    finally:
        s1.close()
        s2.close()
        a.close()
        b.close()


def test_slot_ops_after_close_raise_retryable_taxonomy():
    """The shm->TCP fallback closes EVERY connection's ring, including one
    a sibling stripe thread is mid-operation on: ops on a closed slot must
    raise ConnectionError (which ``_rpc`` retries) — never the raw mmap
    ``ValueError``, which would escape the retry loop and kill the worker."""
    slot = shm.create_slot()
    slot.write_frame(wire.KIND_REQUEST, {"op": "x"})
    slot.close()
    slot.close()  # idempotent
    with pytest.raises(ConnectionError):
        slot.write_frame(wire.KIND_REQUEST, {"op": "x"})
    with pytest.raises(ConnectionError):
        slot.read_frame(wire.PREFIX_SIZE + 8)
    with pytest.raises(ConnectionError):
        slot.corrupt_crc()


# ---------------------------------------------------------------------------
# Compressed-domain folds
# ---------------------------------------------------------------------------

def test_compressed_domain_fold_matches_decode_then_fold_within_quant_step():
    """The server folds int8 deltas without a decode-to-f32 pass; K
    error-feedback commits must land within one quantization step of the
    decode-then-fold reference (the PR 5 acceptance bound, now hit through
    the fused path)."""
    K = 20
    base = (np.random.default_rng(3).normal(size=(64,)) * 0.01
            ).astype(np.float32)
    srv = PSServer(discipline="downpour").start()
    try:
        with PSClient(srv.endpoint, worker_id=0, compress="int8",
                      **FAST) as c:
            _, upd = c.join(init=[np.zeros(64, np.float32)])
            assert c.codec == "int8"
            for _ in range(K):
                _, upd = c.pull()
                c.commit([base], upd)
            center, _ = c.pull()
        one_step = float(np.abs(base).max()) / 127.0
        drift = float(np.abs(center[0] - K * base).max())
        assert drift <= 1.5 * one_step, (drift, one_step)
    finally:
        srv.close()


def test_bad_join_init_spec_is_counted_teardown_not_thread_death():
    """A join whose init arrays carry a bad codec spec reaches
    decode_entry only now that handlers read frames decode=False: the TCP
    handler must count it and tear the connection down (like the shm
    handler's outer guard) — not die with an unhandled traceback. The
    server must keep serving afterward."""
    from distkeras_tpu import telemetry
    from distkeras_tpu.netps.errors import NetPSError

    telemetry.reset()
    srv = PSServer(discipline="adag").start()
    try:
        with pytest.raises(NetPSError):
            with PSClient(srv.endpoint, worker_id=0, timeout=0.3,
                          retries=1, backoff=0.01) as bad:
                bad._rpc("join", {},
                         [(np.ones(2, np.int8), {"codec": "xyz"})])
        snap = telemetry.get().snapshot()
        assert snap["counters"]["netps.protocol_errors"] >= 1
        with PSClient(srv.endpoint, worker_id=1, **FAST) as ok:
            _, upd = ok.join(init=[np.zeros(2, np.float32)])
            assert ok.commit([np.ones(2, np.float32)], upd).applied
    finally:
        srv.close()
        telemetry.reset()


def test_shm_upgrade_is_not_counted_as_reconnect():
    """The routine post-join TCP->ring upgrade on a healthy run must land
    in netps.shm_upgrades, not netps.reconnects (documented as failure
    evidence); a genuine ring re-attach still counts as a reconnect."""
    from distkeras_tpu import telemetry

    telemetry.reset()
    srv, c = shm_pair()
    try:
        _, upd = c.join(init=[np.zeros(3, np.float32)])
        c.pull()  # first ring attach = the upgrade
        snap = telemetry.get().snapshot()["counters"]
        assert snap.get("netps.reconnects", 0) == 0
        assert snap["netps.shm_upgrades"] == 1
        shm.reset_frames()
        faults.set_net_plan(FaultPlan.parse_net("shm_corrupt@0"))
        assert c.commit([np.ones(3, np.float32)], upd).applied
        snap = telemetry.get().snapshot()["counters"]
        assert snap["netps.reconnects"] >= 1  # ring re-attach IS evidence
    finally:
        faults.set_net_plan(None)
        faults.reset()
        c.close()
        srv.close()
        telemetry.reset()


def test_bad_codec_spec_is_typed_error_and_never_partially_folds():
    """The decode=False path must not lose the wire layer's spec
    validation: an unknown codec or a scale-less int8 spec is answered
    with the typed protocol error BEFORE any fold or bookkeeping — a
    mid-fold failure would leave the commit's earlier tensors applied
    with no commit_log entry, and the retransmit would fold them twice.
    A scale-less spec must also never silently fold as zero."""
    from distkeras_tpu.netps.errors import ProtocolError

    srv = PSServer(discipline="adag").start()
    try:
        with PSClient(srv.endpoint, worker_id=0, **FAST) as c:
            _, upd = c.join(init=[np.zeros(3, np.float32),
                                  np.zeros(2, np.float32)])
            good = np.ones(3, np.float32)
            for bad in ({"codec": "xyz"}, {"codec": "int8"},
                        {"codec": "int8", "scale": "nan-ish"}):
                with pytest.raises(ProtocolError):
                    c._rpc("commit", {"seq": 0, "pulled": int(upd)},
                           [good, (np.ones(2, np.int8), bad)])
            assert srv.commit_log == []  # nothing folded, nothing logged
            np.testing.assert_array_equal(srv.center()[0], 0.0)
            # seq 0 is still virgin: the valid retransmit folds exactly once
            res = c.commit([good, np.full(2, 2.0, np.float32)], upd)
            assert res.applied
            assert srv.commit_log == [(0, 0, 0)]
            np.testing.assert_allclose(srv.center()[0], 1.0)
    finally:
        srv.close()


def test_codec_commit_resolves_fold_backend_outside_server_lock():
    """The first compressed-domain fold may import jax / init its backend
    (seconds): the server must resolve the backend BEFORE taking the
    center lock, or every other member's lease renewal queues behind the
    import and a short lease evicts the lot."""
    from distkeras_tpu.netps import server as server_mod

    calls = []
    real = server_mod.resolve_backend
    srv = PSServer(discipline="downpour").start()

    def spy():
        # A non-reentrant Lock held by THIS thread would deadlock here:
        # acquiring proves the handler called us before taking it.
        assert srv._lock.acquire(timeout=1.0), "center lock held by caller"
        srv._lock.release()
        calls.append(1)
        return real()

    server_mod.resolve_backend = spy
    try:
        with PSClient(srv.endpoint, worker_id=0, compress="int8",
                      **FAST) as c:
            _, upd = c.join(init=[np.zeros(8, np.float32)])
            assert c.commit([np.full(8, 0.5, np.float32)], upd).applied
        assert calls, "codec'd commit never resolved the fold backend"
    finally:
        server_mod.resolve_backend = real
        srv.close()


def test_fold_delta_accepts_wire_pairs_and_matches_plain():
    """One fold, two entry forms: (array, spec) wire pairs fold to the
    same center (within a quant step) as pre-decoded plain arrays."""
    rng = np.random.default_rng(1)
    d = (rng.normal(size=(33, 5)) * 0.01).astype(np.float32)
    for codec in ("int8", "bf16"):
        enc, spec = wire.codec_encode(d, codec)
        dec = wire.codec_decode(enc, spec)
        plain = [np.zeros_like(d)]
        paired = [np.zeros_like(d)]
        netfold.fold_delta(plain, [dec], "adag", 0)
        netfold.fold_delta(paired, [(enc, spec)], "adag", 0)
        np.testing.assert_allclose(paired[0], plain[0], atol=1e-6)
    # dynsgd's staleness scale applies in the compressed domain too
    enc, spec = wire.codec_encode(d, "int8")
    c = [np.zeros_like(d)]
    netfold.fold_delta(c, [(enc, spec)], "dynsgd", 1)
    np.testing.assert_allclose(c[0], 0.5 * wire.codec_decode(enc, spec),
                               atol=1e-6)


# ---------------------------------------------------------------------------
# Hierarchical two-level folds
# ---------------------------------------------------------------------------

def test_hier_matches_flat_topology_exactly():
    """Scale-1 disciplines: folding the aggregator's combined commit at
    the root produces the SAME center as folding each worker commit flat
    — additivity makes the topologies equivalent."""
    init = [np.zeros(6, np.float32), np.zeros((2, 2), np.float32)]
    deltas = [leaves((6,), (2, 2)) for _ in range(3)]
    flat = PSServer(discipline="adag").start()
    root = PSServer(discipline="adag").start()
    try:
        with PSClient(flat.endpoint, worker_id=0, **FAST) as fc:
            _, u = fc.join(init=[a.copy() for a in init])
            for d in deltas:
                fc.commit(d, u)
        agg = AggregatorServer(upstream=root.endpoint,
                               init=[a.copy() for a in init],
                               discipline="adag", fan_in=3, **FAST)
        agg.start()
        clients = [PSClient(agg.endpoint, worker_id=w, **FAST)
                   for w in range(3)]
        try:
            pulls = [c.join()[1] for c in clients]
            for c, d, u in zip(clients, deltas, pulls):
                assert c.commit(d, u).applied
        finally:
            for c in clients:
                c.close()
            agg.close()
        for a, b in zip(flat.center(), root.center()):
            np.testing.assert_allclose(a, b, rtol=1e-6)
        # Root ingress cut by the fan-in: 3 worker commits -> 1 combined.
        assert len(root.commit_log) == 1 and agg.absorbed == 3
        assert len(flat.commit_log) == 3
    finally:
        flat.close()
        root.close()


def test_hier_combined_commit_staleness_is_min_pulled():
    """The combined commit's pull counter is the MIN of its constituents':
    the root charges it the staleness of the oldest constituent — the
    conservative reading of the existing counter rule."""
    root = PSServer(discipline="dynsgd").start()
    try:
        # Advance the root counter by 2 through a direct worker first.
        with PSClient(root.endpoint, worker_id=7, **FAST) as direct:
            _, u = direct.join(init=[np.zeros(4, np.float32)])
            direct.commit([np.ones(4, np.float32)], u)
            _, u = direct.pull()
            direct.commit([np.ones(4, np.float32)], u)
        agg = AggregatorServer(upstream=root.endpoint, discipline="dynsgd",
                               fan_in=2, **FAST)
        agg.start()
        a0 = PSClient(agg.endpoint, worker_id=0, **FAST)
        a1 = PSClient(agg.endpoint, worker_id=1, **FAST)
        try:
            _, u0 = a0.join()
            _, u1 = a1.join()
            assert u0 == u1 == 2  # root-lineage counters served locally
            a0.commit([np.ones(4, np.float32)], u0)
            a1.commit([np.ones(4, np.float32)], u1)
        finally:
            a0.close()
            a1.close()
            agg.close()
        # Root saw ONE combined commit with pulled=min(2,2)=2 at counter 2:
        # staleness 0 per the counter rule.
        agg_commits = [e for e in root.commit_log if e[0] != 7]
        assert len(agg_commits) == 1
        assert agg_commits[0][2] == 0
    finally:
        root.close()


def test_hier_exactly_once_at_both_levels():
    """Worker retransmits dedup at the aggregator; the aggregator's own
    combined commits dedup at the root."""
    root = PSServer(discipline="adag").start()
    try:
        agg = AggregatorServer(upstream=root.endpoint, discipline="adag",
                               init=[np.zeros(3, np.float32)], fan_in=1,
                               **FAST)
        agg.start()
        with PSClient(agg.endpoint, worker_id=0, **FAST) as c:
            _, u = c.join()
            assert c.commit([np.ones(3, np.float32)], u).applied
            # hand-crafted retransmit of seq 0 at the aggregator
            hdr, _ = c._rpc("commit", {"seq": 0, "pulled": int(u)},
                            [np.ones(3, np.float32)])
            assert hdr["duplicate"] is True
        agg.close()
        assert agg.commit_log == [(0, 0, 0)]
        assert len(root.commit_log) == 1
        np.testing.assert_allclose(root.center()[0], 1.0)  # folded ONCE
    finally:
        root.close()


def test_hier_idle_stretch_keeps_root_lease():
    """The flusher's between-flush heartbeat must fire even when
    flush_interval exceeds the root lease: an idle stretch (no commits, so
    the flush cv is never notified) must not let the aggregator's lease
    lapse and the next healthy window land evicted as a lost window."""
    root = PSServer(discipline="adag", lease_s=0.5).start()
    agg = AggregatorServer(upstream=root.endpoint, discipline="adag",
                           init=[np.zeros(3, np.float32)], fan_in=1,
                           flush_interval=10.0, **FAST)
    agg.start()
    try:
        with PSClient(agg.endpoint, worker_id=0, **FAST) as c:
            _, u = c.join()
            assert c.commit([np.ones(3, np.float32)], u).applied
            time.sleep(1.6)  # > 3 lease periods of worker silence
            _, u = c.pull()
            assert c.commit([np.ones(3, np.float32)], u).applied
        deadline = time.monotonic() + 5.0
        while agg.forwarded + agg.lost_windows < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        agg.close()
        root.close()
    assert agg.lost_windows == 0
    assert agg.forwarded == 2 and root.evictions == 0


def test_hier_lost_window_is_counted_not_swallowed():
    """A final flush against a dead root must not vanish silently: the
    window is counted in lost_windows (and close() still completes)."""
    root = PSServer(discipline="adag").start()
    agg = AggregatorServer(upstream=root.endpoint, discipline="adag",
                           init=[np.zeros(3, np.float32)], fan_in=8,
                           flush_interval=30.0, timeout=0.2, retries=1,
                           backoff=0.01)
    agg.start()
    try:
        with PSClient(agg.endpoint, worker_id=0, **FAST) as c:
            _, u = c.join()
            assert c.commit([np.ones(3, np.float32)], u).applied
    finally:
        root.close()  # root dies with the window still accumulated
        agg.close()
    assert agg.lost_windows == 1 and agg.forwarded == 0
    assert agg.absorbed == 1


def test_hier_trainer_over_shm_converges(monkeypatch):
    """End to end: ADAG over the networked PS with DKTPU_NET_HIER=1 and
    the shm ring — the worker loop joins the per-host aggregator, the
    root sees only combined commits, training converges."""
    from distkeras_tpu import ADAG, DataFrame, telemetry

    monkeypatch.setenv("DKTPU_NET_TIMEOUT", "2.0")
    monkeypatch.setenv("DKTPU_NET_HIER", "1")
    monkeypatch.setenv("DKTPU_NET_TRANSPORT", "shm")
    telemetry.reset()
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4.0, size=(3, 4))
    y = rng.integers(0, 3, size=512)
    x = (centers[y] + rng.normal(scale=0.5, size=(512, 4))
         ).astype(np.float32)
    df = DataFrame({"features": x, "label": y.astype(np.int32)})
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP

    model = Model.build(MLP(hidden=(16,), num_outputs=3),
                        np.zeros((1, 4), np.float32), seed=0)
    srv = PSServer(discipline="adag").start()
    try:
        t = ADAG(model, loss="sparse_categorical_crossentropy",
                 num_workers=2, batch_size=16, num_epoch=2,
                 learning_rate=0.1, communication_window=4,
                 remote=srv.endpoint)
        trained = t.train(df, shuffle=True)
        acc = float((np.asarray(trained.predict(x)).argmax(-1) == y).mean())
        assert acc > 0.85, acc
        # root ingress: one aggregator worker, not 2 raw workers
        assert srv.members() == []  # aggregator left cleanly
        wids = {wid for wid, _s, _t in srv.commit_log}
        assert len(wids) == 1, wids
        snap = telemetry.get().snapshot()
        assert snap["counters"]["netps.hier.worker_commits"] >= \
            snap["counters"]["netps.hier.combined_commits"]
    finally:
        srv.close()
        telemetry.reset()
