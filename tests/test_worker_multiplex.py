"""Worker multiplexing: num_workers beyond the chip count (VERDICT r2 weak #7).

The reference's ``num_workers`` was a Spark-executor count — 8 workers on a
laptop was the normal case — so the TPU rebuild must not cap it at the chip
count. ``workers_per_chip`` stacks m logical workers per chip on the worker
axis. The golden property: the SAME logical worker schedule run multiplexed
(m workers/chip on fewer chips) equals the spread run (one worker/chip) —
same data, same worker ids, same folds; only the device placement differs.
"""

import numpy as np
import pytest

import jax

from distkeras_tpu import (ADAG, DataFrame, EnsembleTrainer,
                           SynchronousDistributedTrainer)
from distkeras_tpu.data.batching import make_batches
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.parallel.disciplines import get_discipline
from distkeras_tpu.parallel.engine import AsyncEngine
from distkeras_tpu.parallel.sync import SyncEngine
from distkeras_tpu.runtime.mesh import data_mesh

N, DIM, C = 512, 4, 3


def _blobs(seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(C, DIM))
    y = rng.integers(0, C, size=N)
    x = (centers[y] + rng.normal(scale=0.5, size=(N, DIM))).astype(np.float32)
    return x, y.astype(np.int32)


def _model():
    return Model.build(MLP(hidden=(16,), num_outputs=C),
                       np.zeros((1, DIM), np.float32), seed=0)


def _run_async(discipline, mesh_workers, m, plan_w=4, window=4):
    x, y = _blobs()
    df = DataFrame({"features": x, "label": y})
    plan = make_batches(df, "features", "label", batch_size=8,
                        num_workers=plan_w, window=window, num_epoch=2)
    disc = (get_discipline(discipline, alpha=0.05)
            if discipline == "aeasgd" else get_discipline(discipline))
    eng = AsyncEngine(_model(), "sgd", "sparse_categorical_crossentropy",
                      disc, data_mesh(num_workers=mesh_workers),
                      window=window, learning_rate=0.1, workers_per_chip=m)
    assert eng.num_workers == plan_w
    state, losses = eng.run(plan)
    return state, np.asarray(losses)


@pytest.mark.parametrize("discipline", ["adag", "dynsgd", "aeasgd"])
def test_multiplexed_equals_spread(discipline):
    """W=4 on 4 chips == W=4 as 2x2 multiplexed on 2 chips, to float assoc
    tolerance (the psum sums the same per-worker commits either way)."""
    spread, l_spread = _run_async(discipline, mesh_workers=4, m=1)
    muxed, l_muxed = _run_async(discipline, mesh_workers=2, m=2)
    np.testing.assert_allclose(l_muxed, l_spread, rtol=2e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(spread.center),
                    jax.tree.leaves(muxed.center)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=1e-6)


def test_sync_multiplexed_equals_spread():
    x, y = _blobs()
    df = DataFrame({"features": x, "label": y})

    def run(mesh_workers, m):
        plan = make_batches(df, "features", "label", batch_size=8,
                            num_workers=4, window=4, num_epoch=2)
        eng = SyncEngine(_model(), "sgd", "sparse_categorical_crossentropy",
                         data_mesh(num_workers=mesh_workers),
                         learning_rate=0.1, workers_per_chip=m)
        assert eng.num_workers == 4
        state, losses = eng.run(plan)
        return state, np.asarray(losses)

    spread, l_spread = run(4, 1)
    muxed, l_muxed = run(2, 2)
    np.testing.assert_allclose(l_muxed, l_spread, rtol=2e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(spread.params),
                    jax.tree.leaves(muxed.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=1e-6)


def test_trainer_num_workers_beyond_devices():
    """The reference-notebook case: ADAG(num_workers=16) on an 8-device mesh
    trains, converges, and reports 16 per-worker histories."""
    x, y = _blobs()
    df = DataFrame({"features": x, "label": y})
    t = ADAG(_model(), loss="sparse_categorical_crossentropy", num_workers=16,
             batch_size=4, num_epoch=3, learning_rate=0.1,
             communication_window=2)
    trained = t.train(df, shuffle=True)
    acc = (np.asarray(trained.predict(x)).argmax(-1) == y).mean()
    assert acc > 0.85, acc
    assert t.get_worker_histories().shape[0] == 16


def test_trainer_indivisible_num_workers_raises():
    x, y = _blobs()
    with pytest.raises(ValueError, match="divide evenly"):
        SynchronousDistributedTrainer(
            _model(), loss="sparse_categorical_crossentropy", num_workers=13,
            batch_size=4).train(DataFrame({"features": x, "label": y}))


def test_ensemble_multiplexed_members_independent():
    """EnsembleTrainer with more members than chips: every member trains its
    own params (per-worker init preserved through the multiplex)."""
    x, y = _blobs()
    df = DataFrame({"features": x, "label": y})
    t = EnsembleTrainer(_model(), loss="sparse_categorical_crossentropy",
                        num_workers=16, batch_size=4, num_epoch=1,
                        learning_rate=0.1, communication_window=2)
    models = t.train(df)
    assert len(models) == 16
    p0 = jax.tree.leaves(models[0].params)[0]
    p9 = jax.tree.leaves(models[9].params)[0]
    assert not np.allclose(np.asarray(p0), np.asarray(p9))


def test_dynsgd_staleness_uses_global_worker_id():
    """DynSGD's staleness rotation must key on the GLOBAL worker id under
    multiplexing — the fold-equalization property (every worker sees every
    staleness over W rounds) holds exactly when ids are global."""
    spread, _ = _run_async("dynsgd", mesh_workers=4, m=1, window=2)
    muxed, _ = _run_async("dynsgd", mesh_workers=1, m=4, window=2)
    for a, b in zip(jax.tree.leaves(spread.center),
                    jax.tree.leaves(muxed.center)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=1e-6)
