"""Feed-overlap instrumentation + staging-overlap regression guards
(VERDICT r4 missing #3).

Two layers: (a) RoundFeeder's lookahead genuinely overlaps staging with
consumption (deterministic sleep-based timing — if someone serializes the
feeder, wall time doubles and this fails); (b) the engine run loops expose
``feed_wait_seconds``/``feed_waits`` — the always-on consumer-block
diagnostic docs/PERFORMANCE.md's "Feed overlap" section measures in anger
on the real chip via ``examples/imagenet_disk.py --measure-feed``.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from distkeras_tpu.data.prefetch import RoundFeeder


def test_round_feeder_overlaps_staging_with_consumption():
    """10 rounds of 30 ms staging against 30 ms consumption must take
    ~max(stage, consume) per round, not their sum — and the recorded
    consumer waits past the warmup round must be near zero."""
    stage_s, consume_s, rounds = 0.03, 0.03, 10

    def stage(r):
        time.sleep(stage_s)
        return r

    feeder = RoundFeeder(rounds, stage)
    t0 = time.perf_counter()
    seen = []
    for r, batch in feeder:
        time.sleep(consume_s)
        seen.append(r)
    wall = time.perf_counter() - t0
    assert seen == list(range(rounds))
    serialized = rounds * (stage_s + consume_s)
    # Generous bound (CI jitter): must be clearly below full serialization.
    assert wall < serialized * 0.8, (
        f"feeder serialized: wall {wall:.3f}s vs serialized {serialized:.3f}s")
    assert len(feeder.waits) == rounds
    # Past the first round the feeder's lookahead has the next batch staged
    # before the consumer asks for it.
    assert sum(list(feeder.waits)[1:]) < rounds * stage_s * 0.5, feeder.waits


def test_round_feeder_reports_stall_when_staging_dominates():
    """The inverse: staging 3x slower than consumption must SHOW in the
    waits — the diagnostic must not hide a feed-bound pipeline."""
    feeder = RoundFeeder(6, lambda r: time.sleep(0.03) or r)
    for r, _ in feeder:
        time.sleep(0.01)
    # Consumer blocked roughly (stage - consume) per round after warmup.
    assert sum(list(feeder.waits)[1:]) > 0.03, feeder.waits


def test_round_feeder_waits_are_bounded_but_sum_is_not():
    """An open-ended stream must not grow ``waits`` without bound: the
    per-round record is a deque capped at WAITS_KEEP, while the running
    ``wait_seconds`` total keeps counting evicted entries."""
    from distkeras_tpu.data import prefetch

    old_keep = prefetch.WAITS_KEEP
    prefetch.WAITS_KEEP = 8
    try:
        feeder = prefetch.RoundFeeder(50, lambda r: r)
        total = 0.0
        for r, _ in feeder:
            pass
        assert len(feeder.waits) == 8  # capped, not 50
        total = feeder.wait_seconds
        assert total >= sum(feeder.waits)  # the sum survived eviction
    finally:
        prefetch.WAITS_KEEP = old_keep


def test_engine_exposes_feed_wait_metric():
    """Every engine run attaches the feed diagnostic (per-round + sum)."""
    from distkeras_tpu.data.batching import make_batches
    from distkeras_tpu.data.dataframe import DataFrame
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.parallel.sync import SyncEngine
    from distkeras_tpu.runtime.mesh import data_mesh

    rng = np.random.default_rng(0)
    df = DataFrame({"features": rng.normal(size=(256, 8)).astype(np.float32),
                    "label": rng.integers(0, 3, size=256).astype(np.int32)})
    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 8), jnp.float32))
    engine = SyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                        data_mesh(num_workers=2), learning_rate=0.05)
    plan = make_batches(df, "features", "label", batch_size=8,
                        num_workers=2, window=4, num_epoch=1)
    for rpp in (1, 2):  # per-round and blocked paths both instrument
        engine.run(plan, rounds_per_program=rpp)
        assert np.isfinite(engine.feed_wait_seconds)
        assert len(engine.feed_waits) >= 1
        assert all(w >= 0 for w in engine.feed_waits)


@pytest.mark.slow
def test_augmented_outofcore_feed_smoke(tmp_path):
    """The measured path end-to-end at CPU scale: uint8 virtual store +
    crop/flip transform through measure_feed — the JSON record must carry
    all protocol fields and a sane hidden fraction."""
    import importlib.util
    import os
    import sys

    spec = importlib.util.spec_from_file_location(
        "imagenet_disk", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples", "imagenet_disk.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["imagenet_disk"] = mod
    spec.loader.exec_module(mod)

    import distkeras_tpu as dk
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.resnet import ResNet

    root = str(tmp_path / "store")
    mod.build_virtual_store(root, 0.004, 32, classes=10, dtype="uint8")
    sdf = dk.ShardedDataFrame(root)
    model = Model.build(
        ResNet(stage_sizes=(1, 1), base_features=8, num_outputs=10, groups=4),
        np.zeros((1, 32, 32, 3), np.float32), seed=0)
    rec = mod.measure_feed(sdf, model, batch_size=16, window=2)
    assert 0.0 <= rec["value"] <= 1.0
    assert rec["rounds"] >= 2
    assert rec["stage_per_round_ms"] > 0
    assert len(rec["feed_waits_ms"]) == rec["rounds"]
