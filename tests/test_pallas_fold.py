"""Dequant-fused fold kernel parity: Pallas (interpret on CPU CI, compiled
on TPU) against the pure-numpy reference in ``netps/fold.py`` — the CI
fold-parity gate for the compressed-domain server fold."""

import jax
import numpy as np
import pytest

from distkeras_tpu.netps import fold as netfold
from distkeras_tpu.netps import wire
from distkeras_tpu.ops.pallas import fold as pfold

INTERPRET = jax.default_backend() != "tpu"


@pytest.mark.parametrize("codec", ["int8", "bf16"])
@pytest.mark.parametrize("shape", [(7,), (128,), (33, 5), (257, 129),
                                   (2, 3, 64),
                                   # > one 512-row block and NOT divisible
                                   # by it: exercises the multi-block grid
                                   # padding (a whole-tensor block would
                                   # blow VMEM on chip)
                                   (70_001,)])
@pytest.mark.parametrize("scale", [1.0, 0.5, 1.0 / 3.0])
def test_kernel_matches_numpy_reference(codec, shape, scale):
    rng = np.random.default_rng(hash((codec, shape, scale)) % 2**31)
    d = (rng.normal(size=shape) * 0.01).astype(np.float32)
    center = rng.normal(size=shape).astype(np.float32)
    enc, spec = wire.codec_encode(d, codec)
    assert spec.get("codec") == codec
    ref = center.copy()
    netfold.fold_compressed_numpy(ref, enc, spec, scale)
    out = pfold.fold_compressed(center, enc, spec, scale,
                                interpret=INTERPRET)
    assert out.shape == center.shape and out.dtype == np.float32
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-7)


def test_kernel_matches_decode_then_fold_within_quant_step():
    """The acceptance bound: fused dequant-fold vs decode-then-fold agree
    within one int8 quantization step (associativity of the two scale
    multiplies is the only difference)."""
    rng = np.random.default_rng(0)
    d = (rng.normal(size=(513,)) * 0.02).astype(np.float32)
    center = rng.normal(size=(513,)).astype(np.float32)
    enc, spec = wire.codec_encode(d, "int8")
    decode_then_fold = center + 1.0 * wire.codec_decode(enc, spec)
    fused = pfold.fold_compressed(center, enc, spec, 1.0,
                                  interpret=INTERPRET)
    one_step = float(spec["scale"])
    assert np.abs(fused - decode_then_fold).max() <= one_step


def test_zero_scale_and_empty_edges():
    enc, spec = wire.codec_encode(np.zeros((4,), np.float32), "int8")
    assert spec["scale"] == 0.0
    c = np.ones(4, np.float32)
    out = pfold.fold_compressed(c, enc, spec, 1.0, interpret=INTERPRET)
    np.testing.assert_array_equal(out, c)
    empty = np.zeros((0,), np.float32)
    assert wire.codec_encode(empty, "bf16")[1] == {}  # empty: passthrough
    # ...so build the spec by hand to exercise the kernel's empty guard.
    out_e = pfold.fold_compressed(empty, np.zeros((0,), np.uint16),
                                  {"codec": "bf16"}, 1.0,
                                  interpret=INTERPRET)
    assert out_e.size == 0


def test_unknown_codec_is_typed():
    with pytest.raises(ValueError, match="codec"):
        pfold.fold_compressed(np.ones(4, np.float32),
                              np.ones(4, np.int8), {"codec": "zstd"}, 1.0,
                              interpret=INTERPRET)


def test_missing_int8_scale_raises_in_both_backends():
    """Backend parity on bad input too: a scale-less int8 spec raises in
    the kernel dispatch exactly like the numpy oracle — neither may
    silently fold zero while the other raises."""
    c = np.ones(4, np.float32)
    q = np.ones(4, np.int8)
    with pytest.raises(KeyError):
        pfold.fold_compressed(c, q, {"codec": "int8"}, 1.0,
                              interpret=INTERPRET)
    with pytest.raises(KeyError):
        netfold.fold_compressed_numpy(c.copy(), q, {"codec": "int8"}, 1.0)
