"""Fleet health-plane tests (ISSUE 15): target parsing + the in-process
registry, SLO spec parsing and burn-rate math, the multi-window rule over
doctored hub rings, AlertManager fire/clear hysteresis (events, counters,
page -> flight dump), windowed span quantiles and reset-safe rate
derivation, the anomaly sentinels (target_down against a real PS,
drift/shed, bench-regression vs a doctored BENCH_SUMMARY), readiness over
the stats op (PS primary vs standby, serving warmup) and the
readiness-aware ``ServeClient`` walk, the ``health``/``top``/``scrape``
CLIs (typed errors, ``--json``), the ``report --trace`` exit contract,
process vitals, and the Job/FleetScheduler liveness hooks."""

import json
import os
import socket
import threading
import time
from collections import deque

import numpy as np
import pytest

from distkeras_tpu import telemetry
from distkeras_tpu.telemetry.core import BUCKET_BOUNDS
from distkeras_tpu.telemetry.health import (
    AlertManager,
    MetricsHub,
    Sentinels,
    SloEngine,
    SloSpec,
    TargetState,
    parse_slo_specs,
    parse_targets,
    register_target,
    registered_targets,
    unregister_target,
)
from distkeras_tpu.telemetry.health import hub as hub_mod
from distkeras_tpu.telemetry.report import main as report_main
from distkeras_tpu.telemetry.tracing import recorder
from distkeras_tpu.telemetry.tracing import context as trace_context


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("DKTPU_HEALTH_TARGETS", "DKTPU_HEALTH_SLO",
                "DKTPU_TRACE", "DKTPU_TRACE_DIR", "DKTPU_VITALS_S"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    with hub_mod._registry_lock:
        hub_mod._registry.clear()
    trace_context._reset_stream()
    recorder._reset()
    yield
    with hub_mod._registry_lock:
        hub_mod._registry.clear()
    trace_context._reset_stream()
    recorder._reset()
    telemetry.reset()


def _events(kind):
    return [e for e in telemetry.get().events() if e.get("kind") == kind]


def _counters():
    return telemetry.get().snapshot()["counters"]


# ---------------------------------------------------------------------------
# Targets: parsing + the in-process registry
# ---------------------------------------------------------------------------

def test_parse_targets_named_bare_and_separators():
    spec = "ps=10.0.0.1:7077; serve0=10.0.0.2:9000 ,10.0.0.3:9001;;"
    assert parse_targets(spec) == {
        "ps": "10.0.0.1:7077",
        "serve0": "10.0.0.2:9000",
        "10.0.0.3:9001": "10.0.0.3:9001",
    }
    assert parse_targets("") == {}


def test_registry_register_update_unregister():
    assert register_target("h:1", "a") == "a"
    assert register_target("h:2") == "h:2"  # bare endpoint names itself
    register_target("h:9", "a")  # re-register moves the endpoint
    assert registered_targets() == {"a": "h:9", "h:2": "h:2"}
    unregister_target("a")  # by name
    unregister_target("h:2")  # by endpoint
    assert registered_targets() == {}


def test_env_targets_feed_the_hub(monkeypatch):
    monkeypatch.setenv("DKTPU_HEALTH_TARGETS", "adhoc=127.0.0.1:1")
    hub = MetricsHub(targets={"static": "127.0.0.1:2"})
    register_target("127.0.0.1:3", "registered")
    assert hub._known_targets() == {
        "adhoc": "127.0.0.1:1", "static": "127.0.0.1:2",
        "registered": "127.0.0.1:3"}
    # use_registry=False pins the hub to its explicit targets only.
    hermetic = MetricsHub(targets={"static": "127.0.0.1:2"},
                          use_registry=False)
    assert hermetic._known_targets() == {"static": "127.0.0.1:2"}


# ---------------------------------------------------------------------------
# SLO specs: parsing + burn math
# ---------------------------------------------------------------------------

def test_slo_parse_inline_file_and_single_object(tmp_path):
    inline = ('[{"name": "p99", "metric": "serving.latency", '
              '"stat": "p99", "max": 0.25, "severity": "page", '
              '"labels": {"tenant": "B"}}]')
    (spec,) = parse_slo_specs(inline)
    assert (spec.name, spec.stat, spec.max, spec.severity) == (
        "p99", "p99", 0.25, "page")
    assert spec.labels == {"tenant": "B"}
    # A single object (no list) and a file path both parse.
    assert parse_slo_specs('{"name": "x", "metric": "m", "min": 1}')[0].min == 1
    path = tmp_path / "slo.json"
    path.write_text(inline)
    assert parse_slo_specs(str(path))[0].name == "p99"
    # Default source is DKTPU_HEALTH_SLO; empty -> no specs.
    assert parse_slo_specs() == []


def test_slo_parse_rejections(tmp_path):
    with pytest.raises(ValueError, match="exactly one of max/min"):
        parse_slo_specs('{"name": "x", "metric": "m", "max": 1, "min": 1}')
    with pytest.raises(ValueError, match="exactly one of max/min"):
        parse_slo_specs('{"name": "x", "metric": "m"}')
    with pytest.raises(ValueError, match="severity"):
        parse_slo_specs(
            '{"name": "x", "metric": "m", "max": 1, "severity": "loud"}')
    with pytest.raises(ValueError, match="fast_s"):
        parse_slo_specs(
            '{"name": "x", "metric": "m", "max": 1, "fast_s": 60, '
            '"slow_s": 30}')
    with pytest.raises(ValueError, match="unknown keys"):
        parse_slo_specs('{"name": "x", "metric": "m", "max": 1, "oops": 2}')
    with pytest.raises(ValueError, match="name\\+metric"):
        parse_slo_specs('{"metric": "m", "max": 1}')
    with pytest.raises(ValueError, match="not found"):
        parse_slo_specs(str(tmp_path / "missing.json"))


def test_burn_rate_math_and_zero_guards():
    cap = SloSpec(name="c", metric="m", max=2.0)
    assert cap.burn(None) is None  # no data is not a breach
    assert cap.burn(1.0) == pytest.approx(0.5)
    assert cap.burn(4.0) == pytest.approx(2.0)
    degenerate = SloSpec(name="d", metric="m", max=0.0)
    assert degenerate.burn(0.0) == 0.0
    assert degenerate.burn(0.1) == float("inf")
    floor = SloSpec(name="f", metric="m", min=10.0)
    assert floor.burn(20.0) == pytest.approx(0.5)
    assert floor.burn(5.0) == pytest.approx(2.0)
    assert floor.burn(0.0) == float("inf")


# ---------------------------------------------------------------------------
# Doctored-ring hub math: windows, spans, rates
# ---------------------------------------------------------------------------

def _bare_hub(**kw):
    kw.setdefault("targets", {})
    kw.setdefault("use_registry", False)
    return MetricsHub(**kw)


def _inject(hub, name="t0", role=None):
    t = TargetState(name=name, endpoint="127.0.0.1:1", role=role,
                    ever_up=True)
    hub._targets[name] = t
    return t


def test_multiwindow_rule_fast_breach_needs_slow_confirmation():
    hub = _bare_hub()
    t = _inject(hub)
    now = time.time()
    ring = t.gauges["stale"] = deque(maxlen=64)
    for i in range(10):  # established normal, outside the fast window
        ring.append((now - 250 + i * 20, 0.2))
    for dt in (10.0, 5.0):  # a fresh spike
        ring.append((now - dt, 5.0))
    spec = SloSpec(name="stale", metric="stale", stat="mean",
                   max=1.0, fast_s=30.0, slow_s=300.0)
    engine = SloEngine([spec], alerts=AlertManager())
    out = engine.evaluate(hub)["stale"]
    # Fast window burns hot but the slow window vetoes the blip.
    assert out["burn_fast"] > 1.0 and out["burn_slow"] <= 1.0
    assert not out["breaching"]
    assert not engine.alerts.active()
    for i in range(10):  # the spike persists -> slow window confirms
        ring.append((now - 2 - i * 0.1, 5.0))
    out = engine.evaluate(hub)["stale"]
    assert out["burn_fast"] > 1.0 and out["burn_slow"] > 1.0
    assert out["breaching"] and engine.alerts.is_active("slo:stale")
    # Attainment counted evaluations-with-data; both breached fast.
    assert engine.attainment()["stale"] == 0.0


def test_measure_stats_globs_roles_and_absence():
    hub = _bare_hub()
    a = _inject(hub, "serveA", role="serving")
    b = _inject(hub, "serveB", role="serving")
    now = time.time()
    for t, v in ((a, 2.0), (b, 4.0)):
        t.gauges["serving.queue_depth"] = deque([(now - 1, v)])
    assert hub.measure("serving.queue_depth", stat="mean") == pytest.approx(3.0)
    assert hub.measure("serving.queue_depth", stat="max") == pytest.approx(4.0)
    assert hub.measure("serving.*", stat="value",
                       target="serveB") == pytest.approx(4.0)
    assert hub.measure("serving.*", stat="value",
                       target="serving") == pytest.approx(3.0)  # role glob
    assert hub.measure("serving.queue_depth", stat="value",
                       target="nomatch") is None
    assert hub.measure("absent.metric") is None
    names = hub.metric_names()
    assert "serving.queue_depth" in names["gauges"]


def test_span_window_quantile_is_windowed_not_since_boot():
    hub = _bare_hub()
    t = _inject(hub)
    now = time.time()
    lo_i, hi_i = 2, 10
    base = [0] * (len(BUCKET_BOUNDS) + 1)
    base[lo_i] = 100
    head = list(base)
    head[hi_i] = 10
    t.spans["serving.latency"] = deque([
        (now - 100, 100, 10.0, tuple(base)),   # before the fast window
        (now - 5, 110, 12.0, tuple(head)),     # inside it
    ])
    # Fast window diff = 10 slow requests only -> p99 lands in the high
    # bucket; the since-boot view (no base inside) is dominated by the
    # 100 fast ones.
    assert hub.measure("serving.latency", stat="p99",
                       window_s=30) == pytest.approx(BUCKET_BOUNDS[hi_i])
    assert hub.measure("serving.latency", stat="p50",
                       window_s=300) == pytest.approx(BUCKET_BOUNDS[lo_i])
    assert hub.measure("serving.latency", stat="span_mean",
                       window_s=30) == pytest.approx(0.2)


def test_rate_points_are_reset_safe():
    hub = _bare_hub()
    t = _inject(hub)
    t0 = time.time()
    hub._rate_point(t, "c", t0, 10.0)
    hub._rate_point(t, "c", t0 + 1.0, 20.0)
    hub._rate_point(t, "c", t0 + 2.0, 5.0)   # process restart: reset
    hub._rate_point(t, "c", t0 + 3.0, 8.0)   # re-based, not negative
    rates = [v for _, v in t.rates["c"]]
    assert rates == [pytest.approx(10.0), pytest.approx(3.0)]
    assert all(r >= 0 for r in rates)


# ---------------------------------------------------------------------------
# AlertManager: hysteresis, events, page -> flight dump
# ---------------------------------------------------------------------------

def test_alert_fire_and_clear_hysteresis():
    am = AlertManager(clear_after=2)
    assert am.update("k", True, message="hot",
                     labels={"tenant": "A"}) == "fired"
    assert am.update("k", True) is None  # still breaching: no re-fire
    assert am.is_active("k")
    assert am.update("k", False) is None  # first calm eval: held
    assert am.is_active("k")
    assert am.update("k", False) == "cleared"  # second calm eval: cleared
    assert not am.is_active("k")
    assert am.update("k", False) is None  # clearing a clear is a no-op
    assert (am.fired_total, am.cleared_total) == (1, 1)
    (fired,) = _events("health_alert")
    assert fired["alert"] == "k" and fired["tenant"] == "A"
    (cleared,) = _events("health_clear")
    assert cleared["alert"] == "k"
    snap = _counters()
    assert snap["health.alerts_fired"] == 1
    assert snap["health.alerts_cleared"] == 1
    # A breach mid-calm-streak resets the hysteresis counter.
    am.update("j", True)
    am.update("j", False)
    am.update("j", True)
    assert am.update("j", False) is None, "calm streak must restart"
    assert am.is_active("j")


def test_page_alert_drops_a_flight_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("DKTPU_TRACE", "1")
    monkeypatch.setenv("DKTPU_TRACE_DIR", str(tmp_path))
    recorder._reset()
    am = AlertManager()
    am.update("tick", True, severity="ticket")
    assert list(tmp_path.glob("flight-*")) == [], "tickets never dump"
    am.update("slo:p99", True, severity="page")
    (dump,) = list(tmp_path.glob("flight-*"))
    recs = [json.loads(line) for line in open(dump)]
    assert any(r.get("reason") == "health:slo:p99" for r in recs)
    # The alert's own event made it into the dumped ring.
    assert any(r.get("kind") == "health_alert" for r in recs)


# ---------------------------------------------------------------------------
# Sentinels
# ---------------------------------------------------------------------------

def _sentinels(tmp_path, **kw):
    kw.setdefault("bench_summary", str(tmp_path / "no-summary.json"))
    kw.setdefault("bench_pin", str(tmp_path / "no-pin.json"))
    return Sentinels(**kw)


def test_drift_sentinel_fires_on_staleness_creep(tmp_path):
    hub = _bare_hub()
    t = _inject(hub)
    now = time.time()
    ring = t.gauges["netps.staleness_mean"] = deque(maxlen=64)
    for i in range(10):
        ring.append((now - 280 + i * 25, 1.5))  # steady, above the floor
    sn = _sentinels(tmp_path, alerts=AlertManager(clear_after=1))
    sn.evaluate(hub)
    assert not sn.alerts.is_active("staleness_creep"), "flat is healthy"
    for i in range(5):
        ring.append((now - 10 + i * 2, 9.0))  # recent >> established
    sn.evaluate(hub)
    assert sn.alerts.is_active("staleness_creep")


def test_shed_spike_fires_against_a_calm_baseline(tmp_path):
    hub = _bare_hub()
    t = _inject(hub)
    now = time.time()
    ring = t.rates["serving.shed"] = deque(maxlen=64)
    for i in range(6):
        ring.append((now - 280 + i * 40, 0.0))  # calm: no sheds
    sn = _sentinels(tmp_path, alerts=AlertManager(clear_after=1))
    sn.evaluate(hub)
    assert not sn.alerts.is_active("shed_spike")
    ring.append((now - 1, 2.0))  # sheds out of nowhere
    sn.evaluate(hub)
    assert sn.alerts.is_active("shed_spike")


def test_bench_regression_sentinel_vs_doctored_summary(tmp_path):
    summary = tmp_path / "BENCH_SUMMARY.json"
    summary.write_text(json.dumps({"configs": [
        {"metric": "tok_per_sec", "value": 70.0, "pin": 100.0,
         "within_band": False, "vs_baseline": 0.7},
        {"metric": "fine", "value": 99.0, "pin": 100.0,
         "within_band": True},
    ]}))
    hub = _bare_hub()
    sn = _sentinels(tmp_path, alerts=AlertManager(clear_after=1),
                    bench_summary=str(summary))
    sn.evaluate(hub)
    assert sn.alerts.is_active("bench_regression:tok_per_sec")
    assert not sn.alerts.is_active("bench_regression:fine")
    # Repairing the summary clears the alert instead of leaving it latched.
    summary.write_text(json.dumps({"configs": [
        {"metric": "tok_per_sec", "value": 99.0, "pin": 100.0,
         "within_band": True}]}))
    sn.evaluate(hub)
    assert not sn.alerts.is_active("bench_regression:tok_per_sec")


def test_bench_regression_sentinel_vs_live_pins(tmp_path):
    pin = tmp_path / "BENCH_PIN.json"
    pin.write_text(json.dumps({"weather_band_pct": 10,
                               "configs": {"tp": {"pin": 100.0}}}))
    hub = _bare_hub()
    t = _inject(hub)
    t.gauges["bench.tp"] = deque([(time.time() - 1, 80.0)])
    sn = _sentinels(tmp_path, alerts=AlertManager(clear_after=1),
                    bench_pin=str(pin))
    sn.evaluate(hub)
    assert sn.alerts.is_active("bench_regression:live:tp")
    t.gauges["bench.tp"].append((time.time(), 95.0))  # inside the band
    sn.evaluate(hub)
    assert not sn.alerts.is_active("bench_regression:live:tp")


# ---------------------------------------------------------------------------
# Live integration: hub vs a real PS, target_down fire + clear
# ---------------------------------------------------------------------------

def _ps(**kw):
    from distkeras_tpu.netps.server import PSServer

    kw.setdefault("discipline", "adag")
    kw.setdefault("host", "127.0.0.1")
    kw.setdefault("port", 0)
    return PSServer(**kw).start()


def test_hub_scrapes_ps_gauges_rates_and_clock():
    from distkeras_tpu.netps.client import PSClient

    srv = _ps()
    hub = _bare_hub(targets={"ps": srv.endpoint}, interval=30)
    client = PSClient(srv.endpoint, worker_id=0)
    sweeps = []
    hub.on_sweep(lambda h: sweeps.append(h.sweeps))
    try:
        tmpl = [np.zeros((2,), np.float32)]
        client.join(init=tmpl)
        assert hub.scrape_once() == 1
        for i in range(3):
            client.commit([np.ones_like(a) for a in tmpl], i)
        time.sleep(0.05)
        assert hub.scrape_once() == 1
        client.leave()
    finally:
        srv.close()
        hub.close()
    assert sweeps == [1, 2]
    t = hub.target("ps")
    assert t.status() == "UP" and t.ready is True and t.ever_up
    assert t.clock_offset_s is not None and abs(t.clock_offset_s) < 5.0
    assert hub.measure("stats.commits_total", stat="value") == 3.0
    # The commits landed between the two sweeps -> a positive rate.
    assert hub.measure("stats.commits_total", stat="rate",
                       window_s=60) > 0.0
    assert not hub.is_down("ps")


def test_target_down_fires_for_silent_ps_and_clears_on_return(tmp_path):
    srv = _ps()
    hub = _bare_hub(targets={"ps": srv.endpoint}, down_after=2,
                    timeout=0.5, interval=30)
    sn = _sentinels(tmp_path, alerts=AlertManager(clear_after=1))
    try:
        hub.scrape_once()
        sn.evaluate(hub)
        assert not sn.alerts.active()
        srv.close()
        hub.scrape_once()
        sn.evaluate(hub)
        assert not hub.is_down("ps"), "one miss is not an outage"
        hub.scrape_once()
        sn.evaluate(hub)
        assert hub.is_down("ps") and hub.is_down(srv.endpoint)
        assert hub.target("ps").status() == "DOWN"
        alert = sn.alerts.active()["target_down:ps"]
        assert alert.severity == "page" and alert.labels == {"target": "ps"}
        # The babysitter restarts the PS (new port); re-pointing the
        # target and answering one scrape clears the page.
        srv = _ps()
        hub.add_target(srv.endpoint, "ps")
        hub.scrape_once()
        sn.evaluate(hub)
        assert not sn.alerts.active()
        assert not hub.is_down("ps")
        (cleared,) = _events("health_clear")
        assert cleared["alert"] == "target_down:ps"
    finally:
        srv.close()
        hub.close()


def test_never_reached_target_is_pending_not_down():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    hub = _bare_hub(targets={"ghost": f"127.0.0.1:{port}"}, down_after=1)
    hub.scrape_once()
    t = hub.target("ghost")
    assert t.down and not t.ever_up
    assert t.status() == "DOWN" or t.status() == "PENDING"
    # is_down (the supervisor trigger) must stay False: never-up targets
    # are still binding, and shooting them would be a restart loop.
    assert not hub.is_down("ghost")
    assert hub.down_targets() == []


def test_standby_is_scraped_as_not_ready(tmp_path):
    from distkeras_tpu.netps.client import PSClient
    from distkeras_tpu.netps.standby import StandbyServer

    srv = _ps(state_dir=str(tmp_path / "state"))
    stb = StandbyServer(srv.endpoint, promote_after=30.0, host="127.0.0.1",
                        port=0, state_dir=str(tmp_path / "sb")).start()
    hub = _bare_hub(targets={"primary": srv.endpoint,
                             "standby": stb.endpoint}, interval=30)
    client = PSClient(srv.endpoint, worker_id=0)
    try:
        client.join(init=[np.zeros((2,), np.float32)])
        assert hub.scrape_once() == 2
        assert hub.target("primary").ready is True
        assert hub.target("standby").ready is False
        assert hub.target("standby").status() == "NOT-READY"
        assert not hub.is_down("standby"), "not-ready is not down"
    finally:
        stb.close()
        srv.close()
        hub.close()


# ---------------------------------------------------------------------------
# Readiness over the stats op + the readiness-aware ServeClient walk
# ---------------------------------------------------------------------------

def test_serving_readiness_and_prefer_ready_walk():
    from flax import linen as nn

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.netps.endpoints import EndpointWalker
    from distkeras_tpu.serving import (ModelRegistry, ServeClient,
                                       ServingFrontend)

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

    model = Model.build(TinyMLP(), np.zeros((2, 4), np.float32))
    reg_a = ModelRegistry(model, (1, 4))
    reg_b = ModelRegistry(model, (1, 4))
    a = ServingFrontend(reg_a, max_wait_s=0.002).start()
    b = ServingFrontend(reg_b, max_wait_s=0.002).start()
    client = ServeClient(f"{a.endpoint},{b.endpoint}",
                         timeout=2.0, retries=3, backoff=0.01)
    try:
        assert a.ready and b.ready
        # Replica a starts a hot swap: mid-warmup it reports not-ready
        # over the stats op, and the health-aware walk sinks it.
        reg_a.warming = True
        assert not a.ready
        hub = _bare_hub(targets={"a": a.endpoint, "b": b.endpoint})
        hub.scrape_once()
        assert hub.target("a").ready is False
        assert hub.target("a").status() == "NOT-READY"
        assert hub.target("b").ready is True
        order = client.prefer_ready(probe_timeout=0.5)
        assert order[0] == client._walker.endpoints[0]
        assert f"{order[0][0]}:{order[0][1]}" == b.endpoint
        assert f"{order[1][0]}:{order[1][1]}" == a.endpoint
        out, _ = client.infer(np.zeros((1, 4), np.float32))
        assert out.shape == (1, 3)
        # Swap done: both ready again. prefer_ready preserves relative
        # order WITHIN each class, so the walker stays on [b, a] — a
        # probe pass never shuffles healthy replicas for fun.
        reg_a.warming = False
        order = client.prefer_ready(probe_timeout=0.5)
        assert [f"{h}:{p}" for h, p in order] == [b.endpoint, a.endpoint]
        # reorder() is permutation-only: dropping an endpoint must raise.
        walker = EndpointWalker("h:1,h:2,h:3")
        walker.reorder(list(reversed(walker.endpoints)))
        assert walker.current() == ("h", 3)
        with pytest.raises(ValueError, match="permutation"):
            walker.reorder(walker.endpoints[:2])
    finally:
        client.close()
        a.close()
        b.close()
        reg_a.close()
        reg_b.close()


def test_serving_replica_set_registers_targets():
    from flax import linen as nn

    from distkeras_tpu.models.base import Model
    from distkeras_tpu.serving.replica import ServingReplicaSet

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

    model = Model.build(TinyMLP(), np.zeros((2, 4), np.float32))
    rs = ServingReplicaSet(model, n=2, buckets=(1, 4), max_wait_s=0.002)
    try:
        rs.start()
        regs = registered_targets()
        assert "serve0" in regs and "serve1" in regs
        # A deliberate stop unregisters (must not page); a crash would
        # keep the registration so target_down can catch it.
        rs.stop_replica(0)
        assert "serve0" not in registered_targets()
        assert "serve1" in registered_targets()
    finally:
        rs.close()


# ---------------------------------------------------------------------------
# CLIs: health / top / scrape / report --trace
# ---------------------------------------------------------------------------

def test_health_cli_one_shot_text_json_and_exit_codes(
        tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)  # hermetic vs repo BENCH_* files
    srv = _ps()
    try:
        rc = report_main(["health", "--targets", f"ps={srv.endpoint}",
                          "--samples", "2", "--gap", "0.05"])
        text = capsys.readouterr().out
        assert rc == 0, "healthy fleet -> exit 0"
        assert "fleet health: 1/1 targets up" in text
        assert "ps" in text and "yes" in text
        # --json: same structure, machine-readable.
        rc = report_main(["health", "--targets", f"ps={srv.endpoint}",
                          "--samples", "1", "--json"])
        snap = json.loads(capsys.readouterr().out)
        assert rc == 0
        (target,) = snap["targets"]
        assert target["name"] == "ps" and target["status"] == "UP"
        assert target["ready"] is True
        # An impossible floor SLO breaches in both windows -> exit 1,
        # and the alert carries its labels into the summary.
        slo = json.dumps({"name": "commits", "metric": "stats.commits_total",
                          "stat": "value", "min": 1e9,
                          "labels": {"tenant": "acme"}})
        rc = report_main(["health", "--targets", f"ps={srv.endpoint}",
                          "--samples", "2", "--gap", "0.05",
                          "--slo", slo, "--json"])
        snap = json.loads(capsys.readouterr().out)
        assert rc == 1, "active alerts -> exit 1"
        (alert,) = snap["alerts"]
        assert alert["key"] == "slo:commits" and alert["tenant"] == "acme"
        assert snap["slos"]["commits"]["attainment"] == 0.0
    finally:
        srv.close()


def test_top_cli_bounded_iterations(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    srv = _ps()
    try:
        rc = report_main(["top", "--targets", f"ps={srv.endpoint}",
                          "--interval", "0.05", "--iterations", "2",
                          "--no-clear"])
    finally:
        srv.close()
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("fleet health:") == 2, "one frame per iteration"
    assert "\x1b[2J" not in out, "--no-clear must not emit ANSI clears"


def test_scrape_cli_json_is_one_line(capsys):
    srv = _ps()
    try:
        assert report_main(["scrape", srv.endpoint, "--json"]) == 0
    finally:
        srv.close()
    out = capsys.readouterr().out
    assert out.count("\n") == 1, "--json is a single compact line"
    assert json.loads(out)["ok"] is True


def test_scrape_cli_typed_connection_refused(capsys):
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    rc = report_main(["scrape", f"127.0.0.1:{port}"])
    captured = capsys.readouterr()
    assert rc == 2
    assert captured.out == ""
    assert captured.err.count("\n") == 1, "one line, not a traceback"
    assert captured.err.startswith(
        f"scrape error: connection_refused: 127.0.0.1:{port}")


def test_scrape_cli_typed_timeout(capsys):
    silent = socket.socket()
    silent.bind(("127.0.0.1", 0))
    silent.listen(1)  # accepts the connect, never answers
    port = silent.getsockname()[1]
    try:
        rc = report_main(["scrape", f"127.0.0.1:{port}",
                          "--timeout", "0.2"])
    finally:
        silent.close()
    captured = capsys.readouterr()
    assert rc == 2
    assert captured.err.startswith(
        f"scrape error: timeout: 127.0.0.1:{port}")


def test_report_trace_exit_contract_on_missing_and_empty(
        tmp_path, capsys):
    # Nonexistent path: operator error -> one stderr line, exit 2.
    missing = tmp_path / "never-made"
    assert report_main(["report", str(missing), "--trace"]) == 2
    captured = capsys.readouterr()
    assert captured.err.strip() == (
        f"trace report: no such file or directory: {missing}")
    # An existing dir with no records is a valid, boring answer: exit 0.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert report_main(["report", str(empty), "--trace"]) == 0
    assert report_main(["report", str(empty), "--trace", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert rep["commits"] == 0


def test_report_trace_discovers_rotated_only_streams(tmp_path, capsys):
    from distkeras_tpu.telemetry.tracing import TelemetryCollector

    # A stream whose live file was rotated away before the process died
    # exists only as `<base>.jsonl.N` — discovery must still find it.
    rotated = tmp_path / "rot"
    rotated.mkdir()
    (rotated / "ps.jsonl.1").write_text(
        json.dumps({"kind": "note", "ts": 1.0}) + "\n")
    (rotated / "ps.jsonl.2").write_text(
        json.dumps({"kind": "note", "ts": 2.0}) + "\n")
    recs = TelemetryCollector.from_dir(str(rotated)).records()
    assert [r["ts"] for r in recs] == [1.0, 2.0], "generations in order"
    assert all(r["stream"] == "ps.jsonl" for r in recs)
    assert report_main(["report", str(rotated), "--trace"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Process vitals
# ---------------------------------------------------------------------------

def test_vitals_sample_and_lifecycle(monkeypatch):
    from distkeras_tpu.telemetry import vitals

    out = vitals.sample_vitals()
    assert out["runtime.rss_mb"] > 1.0
    assert out["runtime.open_fds"] >= 3
    gauges = telemetry.get().snapshot()["gauges"]
    assert gauges["runtime.rss_mb"]["value"] == out["runtime.rss_mb"]
    assert gauges["runtime.open_fds"]["value"] == out["runtime.open_fds"]
    # Zero interval (the default) and the telemetry kill-switch are no-ops.
    assert vitals.start_vitals(0) is False
    assert vitals.start_vitals() is False, "DKTPU_VITALS_S defaults to off"
    monkeypatch.setattr(telemetry, "enabled", lambda: False)
    assert vitals.start_vitals(0.01) is False
    monkeypatch.undo()
    try:
        assert vitals.start_vitals(0.01) is True
        assert vitals.start_vitals(0.01) is True, "idempotent"
    finally:
        vitals.stop_vitals()
    vitals.stop_vitals()  # double-stop is fine


# ---------------------------------------------------------------------------
# Supervisor hooks: Job PS-plane mapping + FleetScheduler requeue
# ---------------------------------------------------------------------------

def test_job_maps_ps_roles_to_scrape_endpoints():
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="hp", script="t.py", hosts=["localhost"],
                   tenant="acme",
                   ps={"host": "127.0.0.1", "port": 7611,
                       "standby_host": "127.0.0.1", "standby_port": 7612})
    job = Job(pc)
    assert job._ps_endpoint_for_role("primary") == "127.0.0.1:7611"
    assert job._ps_endpoint_for_role("standby") == "127.0.0.1:7612"
    assert job._ps_endpoint_for_role("shard-0") is None
    # Nothing launched yet -> nothing registered.
    assert job.register_health_targets() == {}

    sharded = Job(Punchcard(
        job_name="hp2", script="t.py", hosts=["localhost"],
        ps={"host": "127.0.0.1", "shards": 2,
            "shard_ports": [7621, 7622]}))
    assert sharded._ps_endpoint_for_role("shard-0") == "127.0.0.1:7621"
    assert sharded._ps_endpoint_for_role("shard-1") == "127.0.0.1:7622"
    assert sharded._ps_endpoint_for_role("shard-0-standby") is None
    assert sharded._ps_endpoint_for_role("shard-9") is None
    assert sharded._ps_endpoint_for_role("primary") is None

    assert Job(Punchcard(job_name="nops", script="t.py",
                         hosts=["localhost"]))._ps_endpoint_for_role(
        "primary") is None


class _FakeProc:
    def __init__(self):
        self.killed = False

    def poll(self):
        return None if not self.killed else -9

    def kill(self):
        self.killed = True


class _Hook:
    """Duck-typed stand-in for MetricsHub.is_down."""

    def __init__(self):
        self.down = set()

    def is_down(self, endpoint):
        return endpoint in self.down


def test_job_liveness_kill_shoots_only_the_down_ps():
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="lk", script="t.py", hosts=["localhost"],
                   tenant="acme",
                   ps={"host": "127.0.0.1", "port": 7631,
                       "standby_host": "127.0.0.1", "standby_port": 7632})
    job = Job(pc)
    job._ps_proc = _FakeProc()
    job._standby_proc = _FakeProc()
    hook = _Hook()
    job._liveness_kill(hook)
    assert not job._ps_proc.killed and not job._standby_proc.killed
    hook.down.add("127.0.0.1:7631")
    job._liveness_kill(hook)
    assert job._ps_proc.killed, "the wedged primary gets SIGKILLed"
    assert not job._standby_proc.killed, "the healthy standby is spared"
    assert _counters()["resilience.liveness_kills"] == 1
    (ev,) = _events("liveness_kill")
    assert ev["role"] == "primary" and ev["endpoint"] == "127.0.0.1:7631"
    assert ev["tenant"] == "acme"
    # Registration names are tenant-prefixed <job>.<role>.
    regs = job.register_health_targets()
    assert regs == {"acme.lk.primary": "127.0.0.1:7631",
                    "acme.lk.standby": "127.0.0.1:7632"}
    assert registered_targets()["acme.lk.primary"] == "127.0.0.1:7631"


def test_fleet_scheduler_health_hook_requeues_once_per_outage():
    from distkeras_tpu.fleet import FleetJob, FleetScheduler
    from distkeras_tpu.fleet.job import RUNNING

    class EndpointRuntime:
        endpoint = "127.0.0.1:7641"

        def __init__(self):
            self.n = 0
            self.closed = False

        def ensure_started(self):
            pass

        def worker_main(self, wid, should_run):
            while should_run() and self.n < 100000:
                self.n += 1
                time.sleep(0.002)

        def progress(self):
            return self.n

        def done(self):
            return self.n >= 100000

        def revoke(self, wid):
            pass

        def close(self):
            self.closed = True

    def drive(sched, until, timeout=20.0):
        deadline = time.monotonic() + timeout
        while not until():
            assert time.monotonic() < deadline, "scenario timed out"
            sched.tick()
            time.sleep(0.002)

    hook = _Hook()
    sched = FleetScheduler(capacity=2, tick_s=0.01, health_hook=hook)
    rt = EndpointRuntime()
    job = sched.submit(FleetJob("svc", "acme", rt, min_gang=1,
                                max_workers=1))
    try:
        drive(sched, lambda: job.state == RUNNING)
        sched.tick()
        # A RUNNING job's endpoint is kept registered for scraping.
        assert registered_targets()["fleet.acme.svc"] == rt.endpoint
        hook.down.add(rt.endpoint)
        drive(sched, lambda: _counters().get(
            "fleet.liveness_requeues") == 1.0)
        (ev,) = _events("fleet_liveness_requeue")
        assert ev["tenant"] == "acme" and ev["endpoint"] == rt.endpoint
        # Still down across later ticks: one requeue per outage, not per
        # tick (the job re-places and keeps running meanwhile).
        for _ in range(8):
            sched.tick()
            time.sleep(0.002)
        assert _counters()["fleet.liveness_requeues"] == 1.0
        # Recovery then a SECOND outage earns its own requeue.
        hook.down.clear()
        drive(sched, lambda: job.state == RUNNING)
        sched.tick()
        hook.down.add(rt.endpoint)
        drive(sched, lambda: _counters().get(
            "fleet.liveness_requeues") == 2.0)
        hook.down.clear()
        drive(sched, lambda: job.state == RUNNING)
        assert job.requeues >= 2
    finally:
        sched.close()
    assert sched.floor_violations == 0


# ---------------------------------------------------------------------------
# bench.py health summary
# ---------------------------------------------------------------------------

def test_bench_health_summary_block():
    import bench

    telemetry.event("health_alert", {"alert": "slo:p99", "severity": "page",
                                     "message": "hot", "value": 0.5,
                                     "tenant": "acme"})
    telemetry.event("health_clear", {"alert": "slo:p99",
                                     "severity": "page"})
    telemetry.event("unrelated", {"x": 1})
    results = [
        {"metric": "tok", "value": 70.0, "within_band": False,
         "vs_baseline": 0.7},
        {"metric": "fine", "value": 99.0, "within_band": True},
        {"metric": "unpinned", "value": 1.0},
    ]
    block = bench._health_summary(telemetry.get(), results)
    assert block["alerts_raised"] == 1
    assert block["alerts_cleared"] == 1
    (alert,) = block["alerts"]
    assert alert["alert"] == "slo:p99" and alert["tenant"] == "acme"
    (reg,) = block["bench_regressions"]
    assert reg["metric"] == "tok" and reg["vs_baseline"] == 0.7
