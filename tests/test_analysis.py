"""dk-check suite: fixture corpus (every rule fires exactly on its planted
line), real-package cleanliness, suppressions, the env registry, and the
runtime lock-order witness (incl. static-graph/runtime agreement)."""

import os
import re
import threading

import pytest

import distkeras_tpu
from distkeras_tpu.analysis import core, run, witness
from distkeras_tpu.analysis.rules_concurrency import build_lock_graph
from distkeras_tpu.runtime import config

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")
PKG_DIR = os.path.dirname(os.path.abspath(distkeras_tpu.__file__))
_PLANT_RE = re.compile(r"#\s*PLANT:\s*([A-Z0-9 ]+)")
_PLANT_FILE_RE = re.compile(r"#\s*PLANT-FILE:\s*(DK\d+)=(\d+)")


def _expected(path):
    """(line-pinned {(line, rule)}, file-level {rule: count}) from markers."""
    pinned, counts = set(), {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            m = _PLANT_RE.search(line)
            if m:
                for rule in m.group(1).split():
                    pinned.add((lineno, rule))
            m = _PLANT_FILE_RE.search(line)
            if m:
                counts[m.group(1)] = int(m.group(2))
    return pinned, counts


@pytest.mark.parametrize("fixture", sorted(
    f for f in os.listdir(FIXTURES) if f.endswith(".py")))
def test_fixture_rules_fire_exactly_on_planted_lines(fixture):
    path = os.path.join(FIXTURES, fixture)
    pinned, counts = _expected(path)
    assert pinned or counts, f"{fixture} has no PLANT markers"
    findings = run([path])
    got_pinned = {(f.line, f.rule) for f in findings
                  if f.rule not in counts}
    assert got_pinned == pinned, (
        f"{fixture}: planted vs fired mismatch\n"
        f"  missing: {sorted(pinned - got_pinned)}\n"
        f"  extra:   {sorted(got_pinned - pinned)}")
    for rule, n in counts.items():
        fired = [f for f in findings if f.rule == rule]
        assert len(fired) == n, (
            f"{fixture}: expected {n}x {rule}, got "
            f"{[(f.line, f.message) for f in fired]}")


def test_real_package_is_clean():
    """The acceptance gate: dk-check exits 0 on the swept package."""
    findings = run([PKG_DIR])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_every_rule_family_is_exercised():
    """The corpus proves each family both fires (fixtures) and stays quiet
    (package): >=2 planted findings per DK1xx/DK2xx/DK3xx family."""
    findings = run([FIXTURES])
    by_family = {}
    for f in findings:
        by_family.setdefault(f.rule[:3], []).append(f.rule)
    for family in ("DK1", "DK2", "DK3"):
        assert len(by_family.get(family, [])) >= 2, by_family


def test_suppression_comment_silences_rule(tmp_path):
    src = (
        "def f(q):\n"
        "    try:\n"
        "        q.get()\n"
        "    except:  # dk: disable=DK204 - intentional\n"
        "        pass\n"
        "def g(q):\n"
        "    try:\n"
        "        q.get()\n"
        "    except:\n"
        "        pass\n")
    p = tmp_path / "supp.py"
    p.write_text(src)
    findings = run([str(p)])
    assert [f.line for f in findings if f.rule == "DK204"] == [9]
    p.write_text("# dk: disable-file=DK204\n" + src)
    assert run([str(p)]) == []


def test_select_ignore_and_syntax_error(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = run([str(p)])
    assert [f.rule for f in findings] == ["DK000"]
    fix = os.path.join(FIXTURES, "config_violations.py")
    only_302 = run([fix], select=["DK302"])
    assert only_302 and all(f.rule == "DK302" for f in only_302)
    no_3xx = run([fix], ignore=["DK3"])
    assert no_3xx == []


def test_cli_roundtrip(tmp_path, capsys):
    import json

    from distkeras_tpu.analysis.__main__ import main

    fix = os.path.join(FIXTURES, "config_violations.py")
    assert main([fix, "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["count"] == len(payload["findings"]) > 0
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert main([str(clean)]) == 0
    assert main([str(tmp_path / "nope.py")]) == 2
    assert main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rule in ("DK101", "DK201", "DK301"):
        assert rule in listed


# -- env registry ----------------------------------------------------------

def test_env_registry_typed_accessors(monkeypatch):
    monkeypatch.delenv("DKTPU_TELEMETRY", raising=False)
    assert config.env_bool("DKTPU_TELEMETRY") is True
    monkeypatch.setenv("DKTPU_TELEMETRY", "0")
    assert config.env_bool("DKTPU_TELEMETRY") is False
    monkeypatch.setenv("DKTPU_NO_NATIVE", "1")
    assert config.env_bool("DKTPU_NO_NATIVE") is True
    monkeypatch.delenv("DKTPU_FEEDER_TIMEOUT", raising=False)
    assert config.env_float("DKTPU_FEEDER_TIMEOUT") == 300.0
    monkeypatch.setenv("DKTPU_FEEDER_TIMEOUT", "2.5")
    assert config.env_float("DKTPU_FEEDER_TIMEOUT") == 2.5
    assert config.env_float("DKTPU_DIVERGENCE_RESET") is None
    assert config.env_int("DKTPU_FEEDER_RETRIES") == 0
    assert config.env_str("DKTPU_FAULTS") == ""
    with pytest.raises(KeyError):
        config.env_bool("DKTPU_NOT_A_THING")
    with pytest.raises(TypeError):
        config.env_int("DKTPU_TELEMETRY")  # registered as bool


def test_env_docs_render_and_splice():
    table = config.render_env_table("resilience")
    assert "`DKTPU_FAULTS`" in table and "DKTPU_TELEMETRY" not in table
    doc = "x\n<!-- dk-env:begin category=resilience -->\nstale\n<!-- dk-env:end -->\ny"
    spliced = config.splice_env_docs(doc)
    assert "stale" not in spliced and "`DKTPU_NAN_GUARD`" in spliced
    with pytest.raises(ValueError):
        config.splice_env_docs("no markers here", path_hint="f.md")


def test_rule_catalog_documented():
    core._load_rules()
    docs = os.path.join(os.path.dirname(PKG_DIR), "docs", "ANALYSIS.md")
    with open(docs) as f:
        text = f.read()
    for rule in core.RULE_CATALOG:
        assert rule in text, f"{rule} missing from docs/ANALYSIS.md"


# -- lock-order witness ----------------------------------------------------

def test_witness_detects_inversion():
    with witness() as w:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert w.edges()
    with pytest.raises(AssertionError, match="inversion"):
        w.assert_no_inversions()


def test_witness_clean_order_passes():
    with witness() as w:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    w.assert_no_inversions()
    assert len(w.edges()) == 1


def test_witness_ignores_preexisting_locks():
    before = threading.Lock()
    with witness() as w:
        with before:
            pass
    assert w.edges() == set()


def test_static_graph_matches_witnessed_order():
    """The DK201 graph and the runtime witness must agree on the fixture:
    every dynamically observed edge is in the static graph, and the planted
    inversion is visible to both."""
    path = os.path.join(FIXTURES, "concurrency_violations.py")
    modules, errs = core.parse_modules([path])
    assert not errs
    static_edges, _, _ = build_lock_graph(modules)
    with open(path) as f:
        src = f.read()
    ns = {}
    with witness() as w:
        exec(compile(src, path, "exec"), ns)  # defines locks under witness
        ns["forward"]()
        ns["backward"]()
        pool = ns["Pool"]()
        pool.take()
        pool.drain()
    observed = {e for e in w.edges()
                if e[0].startswith("concurrency_violations.")}
    assert observed, "witness saw no fixture lock nesting"
    assert observed <= static_edges, observed - static_edges
    assert w.cycles(), "planted inversion must be dynamically visible"


def test_package_lock_graph_is_acyclic_and_witnessed_subset():
    """No DK201 cycles in the real package, and a live telemetry+feeder
    burst under the witness observes no inversion and no edge the static
    graph lacks (for locks it can name)."""
    modules, _ = core.parse_modules([PKG_DIR])
    static_edges, _, _ = build_lock_graph(modules)
    from distkeras_tpu.analysis.rules_concurrency import _find_cycles

    assert _find_cycles(static_edges) == []
    from distkeras_tpu.telemetry.core import Telemetry

    with witness() as w:
        tele = Telemetry(enabled=True)

        def worker():
            for i in range(50):
                tele.counter("c").add(1)
                tele.gauge("g").set(i)
                with tele.span("s"):
                    tele.histogram("h").observe(0.001)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tele.snapshot()
    w.assert_no_inversions()
    pkg_bases = {os.path.splitext(f)[0] for f in ("core.py",)}
    observed = {e for e in w.edges()
                if e[0].split(".")[0] in pkg_bases
                or e[1].split(".")[0] in pkg_bases}
    assert observed <= static_edges, observed - static_edges
