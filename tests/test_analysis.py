"""dk-check suite: fixture corpus (every rule fires exactly on its planted
line), real-package cleanliness, suppressions, the env registry, and the
runtime lock-order witness (incl. static-graph/runtime agreement)."""

import os
import re
import threading

import pytest

import distkeras_tpu
from distkeras_tpu.analysis import core, run, witness
from distkeras_tpu.analysis.rules_concurrency import build_lock_graph
from distkeras_tpu.runtime import config

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")
PKG_DIR = os.path.dirname(os.path.abspath(distkeras_tpu.__file__))
_PLANT_RE = re.compile(r"#\s*PLANT:\s*([A-Z0-9 ]+)")
_PLANT_FILE_RE = re.compile(r"#\s*PLANT-FILE:\s*(DK\d+)=(\d+)")


def _expected(path):
    """(line-pinned {(line, rule)}, file-level {rule: count}) from markers."""
    pinned, counts = set(), {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            m = _PLANT_RE.search(line)
            if m:
                for rule in m.group(1).split():
                    pinned.add((lineno, rule))
            m = _PLANT_FILE_RE.search(line)
            if m:
                counts[m.group(1)] = int(m.group(2))
    return pinned, counts


@pytest.mark.parametrize("fixture", sorted(
    f for f in os.listdir(FIXTURES) if f.endswith(".py")))
def test_fixture_rules_fire_exactly_on_planted_lines(fixture):
    path = os.path.join(FIXTURES, fixture)
    pinned, counts = _expected(path)
    assert pinned or counts, f"{fixture} has no PLANT markers"
    findings = run([path])
    got_pinned = {(f.line, f.rule) for f in findings
                  if f.rule not in counts}
    assert got_pinned == pinned, (
        f"{fixture}: planted vs fired mismatch\n"
        f"  missing: {sorted(pinned - got_pinned)}\n"
        f"  extra:   {sorted(got_pinned - pinned)}")
    for rule, n in counts.items():
        fired = [f for f in findings if f.rule == rule]
        assert len(fired) == n, (
            f"{fixture}: expected {n}x {rule}, got "
            f"{[(f.line, f.message) for f in fired]}")


def test_real_package_is_clean():
    """The acceptance gate: dk-check exits 0 on the swept package."""
    findings = run([PKG_DIR])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_every_rule_family_is_exercised():
    """The corpus proves each family both fires (fixtures) and stays quiet
    (package): >=2 planted findings per DK1xx/DK2xx/DK3xx family."""
    findings = run([FIXTURES])
    by_family = {}
    for f in findings:
        by_family.setdefault(f.rule[:3], []).append(f.rule)
    for family in ("DK0", "DK1", "DK2", "DK3", "DK4", "DK5", "DK6"):
        assert len(by_family.get(family, [])) >= 2, by_family


def test_suppression_comment_silences_rule(tmp_path):
    src = (
        "def f(q):\n"
        "    try:\n"
        "        q.get()\n"
        "    except:  # dk: disable=DK204 - intentional\n"
        "        pass\n"
        "def g(q):\n"
        "    try:\n"
        "        q.get()\n"
        "    except:\n"
        "        pass\n")
    p = tmp_path / "supp.py"
    p.write_text(src)
    findings = run([str(p)])
    assert [f.line for f in findings if f.rule == "DK204"] == [9]
    p.write_text("# dk: disable-file=DK204\n" + src)
    assert run([str(p)]) == []


def test_select_ignore_and_syntax_error(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = run([str(p)])
    assert [f.rule for f in findings] == ["DK000"]
    fix = os.path.join(FIXTURES, "config_violations.py")
    only_302 = run([fix], select=["DK302"])
    assert only_302 and all(f.rule == "DK302" for f in only_302)
    # DK001 (the stale-suppression meta-rule) survives ignore=DK3:
    # staleness is a property of the code, not of the filter view.
    no_3xx = run([fix], ignore=["DK3"])
    assert [f.rule for f in no_3xx] == ["DK001"]
    assert run([fix], ignore=["DK3", "DK0"]) == []


def test_cli_roundtrip(tmp_path, capsys):
    import json

    from distkeras_tpu.analysis.__main__ import main

    fix = os.path.join(FIXTURES, "config_violations.py")
    assert main([fix, "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["count"] == len(payload["findings"]) > 0
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert main([str(clean)]) == 0
    assert main([str(tmp_path / "nope.py")]) == 2
    assert main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for rule in ("DK101", "DK201", "DK301"):
        assert rule in listed


# -- env registry ----------------------------------------------------------

def test_env_registry_typed_accessors(monkeypatch):
    monkeypatch.delenv("DKTPU_TELEMETRY", raising=False)
    assert config.env_bool("DKTPU_TELEMETRY") is True
    monkeypatch.setenv("DKTPU_TELEMETRY", "0")
    assert config.env_bool("DKTPU_TELEMETRY") is False
    monkeypatch.setenv("DKTPU_NO_NATIVE", "1")
    assert config.env_bool("DKTPU_NO_NATIVE") is True
    monkeypatch.delenv("DKTPU_FEEDER_TIMEOUT", raising=False)
    assert config.env_float("DKTPU_FEEDER_TIMEOUT") == 300.0
    monkeypatch.setenv("DKTPU_FEEDER_TIMEOUT", "2.5")
    assert config.env_float("DKTPU_FEEDER_TIMEOUT") == 2.5
    assert config.env_float("DKTPU_DIVERGENCE_RESET") is None
    assert config.env_int("DKTPU_FEEDER_RETRIES") == 0
    assert config.env_str("DKTPU_FAULTS") == ""
    with pytest.raises(KeyError):
        config.env_bool("DKTPU_NOT_A_THING")
    with pytest.raises(TypeError):
        config.env_int("DKTPU_TELEMETRY")  # registered as bool


def test_env_docs_render_and_splice():
    table = config.render_env_table("resilience")
    assert "`DKTPU_FAULTS`" in table and "DKTPU_TELEMETRY" not in table
    doc = "x\n<!-- dk-env:begin category=resilience -->\nstale\n<!-- dk-env:end -->\ny"
    spliced = config.splice_env_docs(doc)
    assert "stale" not in spliced and "`DKTPU_NAN_GUARD`" in spliced
    with pytest.raises(ValueError):
        config.splice_env_docs("no markers here", path_hint="f.md")


def test_rule_catalog_documented():
    core._load_rules()
    docs = os.path.join(os.path.dirname(PKG_DIR), "docs", "ANALYSIS.md")
    with open(docs) as f:
        text = f.read()
    for rule in core.RULE_CATALOG:
        assert rule in text, f"{rule} missing from docs/ANALYSIS.md"


# -- lock-order witness ----------------------------------------------------

def test_witness_detects_inversion():
    with witness() as w:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert w.edges()
    with pytest.raises(AssertionError, match="inversion"):
        w.assert_no_inversions()


def test_witness_clean_order_passes():
    with witness() as w:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    w.assert_no_inversions()
    assert len(w.edges()) == 1


def test_witness_ignores_preexisting_locks():
    before = threading.Lock()
    with witness() as w:
        with before:
            pass
    assert w.edges() == set()


def test_static_graph_matches_witnessed_order():
    """The DK201 graph and the runtime witness must agree on the fixture:
    every dynamically observed edge is in the static graph, and the planted
    inversion is visible to both."""
    path = os.path.join(FIXTURES, "concurrency_violations.py")
    modules, errs = core.parse_modules([path])
    assert not errs
    static_edges, _, _ = build_lock_graph(modules)
    with open(path) as f:
        src = f.read()
    ns = {}
    with witness() as w:
        exec(compile(src, path, "exec"), ns)  # defines locks under witness
        ns["forward"]()
        ns["backward"]()
        pool = ns["Pool"]()
        pool.take()
        pool.drain()
    observed = {e for e in w.edges()
                if e[0].startswith("concurrency_violations.")}
    assert observed, "witness saw no fixture lock nesting"
    assert observed <= static_edges, observed - static_edges
    assert w.cycles(), "planted inversion must be dynamically visible"


def test_package_lock_graph_is_acyclic_and_witnessed_subset():
    """No DK201 cycles in the real package, and a live telemetry+feeder
    burst under the witness observes no inversion and no edge the static
    graph lacks (for locks it can name)."""
    modules, _ = core.parse_modules([PKG_DIR])
    static_edges, _, _ = build_lock_graph(modules)
    from distkeras_tpu.analysis.rules_concurrency import _find_cycles

    assert _find_cycles(static_edges) == []
    from distkeras_tpu.telemetry.core import Telemetry

    with witness() as w:
        tele = Telemetry(enabled=True)

        def worker():
            for i in range(50):
                tele.counter("c").add(1)
                tele.gauge("g").set(i)
                with tele.span("s"):
                    tele.histogram("h").observe(0.001)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tele.snapshot()
    w.assert_no_inversions()
    pkg_bases = {os.path.splitext(f)[0] for f in ("core.py",)}
    observed = {e for e in w.edges()
                if e[0].split(".")[0] in pkg_bases
                or e[1].split(".")[0] in pkg_bases}
    assert observed <= static_edges, observed - static_edges


# -- DK001 stale suppressions ----------------------------------------------

def test_stale_suppression_fires_and_live_one_does_not(tmp_path):
    p = tmp_path / "stale.py"
    p.write_text(
        "def f(q):\n"
        "    try:\n"
        "        q.get()\n"
        "    except:  # dk: disable=DK204\n"      # live: DK204 fires here
        "        pass\n"
        "x = 1  # dk: disable=DK204\n")           # stale: it cannot
    findings = run([str(p)])
    assert [(f.line, f.rule) for f in findings] == [(6, "DK001")]


def test_stale_file_suppression_points_at_its_comment(tmp_path):
    p = tmp_path / "stale_file.py"
    p.write_text("x = 1\n# dk: disable-file=DK301\ny = 2\n")
    findings = run([str(p)])
    assert [(f.line, f.rule) for f in findings] == [(2, "DK001")]


def test_blanket_suppression_is_exempt_from_dk001(tmp_path):
    p = tmp_path / "blanket.py"
    p.write_text("x = 1  # dk: disable\n")
    assert run([str(p)]) == []


# -- metric registry -------------------------------------------------------

def test_metric_registry_declares_and_renders():
    from distkeras_tpu.telemetry import registry

    assert registry.declared("counter", "netps.commits")
    assert not registry.declared("gauge", "netps.commits")  # kind-checked
    assert not registry.declared("counter", "netps.nope")
    assert registry.declared_prefix("span", "netps.rpc.")
    assert not registry.declared_prefix("counter", "made.up.")
    table = registry.render_metric_table("netps")
    assert "`netps.commits`" in table and "`netps.rpc.*`" in table
    doc = ("<!-- dk-metric:begin category=netps -->\nOUTDATED\n"
           "<!-- dk-metric:end -->")
    spliced = registry.splice_metric_docs(doc)
    assert "OUTDATED" not in spliced and "`netps.commits`" in spliced
    with pytest.raises(ValueError):
        registry.splice_metric_docs("no markers", path_hint="f.md")


def test_metric_docs_drift_is_a_finding(tmp_path, monkeypatch):
    """DK602 fires when a docs metric block goes stale (checked against a
    scratch docs tree so the real one stays untouched)."""
    from distkeras_tpu.analysis import rules_contracts

    reg_path = os.path.join(PKG_DIR, "telemetry", "registry.py")
    modules, errs = core.parse_modules([reg_path])
    assert not errs
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "OBS.md").write_text(
        "<!-- dk-metric:begin category=netps -->\nstale\n"
        "<!-- dk-metric:end -->\n")
    monkeypatch.setattr(rules_contracts, "_docs_dir_for",
                        lambda _p: str(docs))
    findings = rules_contracts.check_metric_docs(modules)
    assert any("stale vs the registry" in f.message for f in findings)
    assert any("registered but appears in no docs" in f.message
               for f in findings)


def test_fault_kind_drift_is_a_finding(tmp_path, monkeypatch):
    """DK603 both directions: an undocumented code kind and a documented
    ghost row."""
    from distkeras_tpu.analysis import rules_contracts

    faults_path = os.path.join(PKG_DIR, "resilience", "faults.py")
    modules, errs = core.parse_modules([faults_path])
    assert not errs
    assert rules_contracts.check_fault_kinds(modules) == []  # real docs ok
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "RESILIENCE.md").write_text(
        "| `preempt@R` | x | y |\n| `ghost_fault@R` | x | y |\n")
    monkeypatch.setattr(rules_contracts, "_docs_dir_for",
                        lambda _p: str(docs))
    findings = rules_contracts.check_fault_kinds(modules)
    assert any("has no row" in f.message for f in findings)
    assert any("ghost_fault" in f.message and "stale docs row" in f.message
               for f in findings)


# -- interleaving checker --------------------------------------------------

def test_explorer_enumerates_exact_schedule_count():
    """2 threads x 2 steps = C(4,2) = 6 complete schedules; with crash
    points every proper non-empty prefix adds one crashed run."""
    from distkeras_tpu.analysis import interleave

    class Tiny(interleave.Scenario):
        name = "tiny"

        def build(self, factory):
            self.log = []

            def script(tag):
                def gen():
                    self.log.append((tag, 0))
                    yield
                    self.log.append((tag, 1))
                return gen
            factory(target=script("a"), name="a")
            factory(target=script("b"), name="b")

    res = interleave.explore(Tiny)
    assert (res.complete, res.crashed) == (6, 0)
    res = interleave.explore(Tiny, crash_points=True)
    # one crash per distinct non-empty proper prefix: 2 + 4 + 6 = 12
    assert (res.complete, res.crashed) == (6, 12)
    assert res.violations == []


def test_interleave_scenarios_hold_invariants():
    from distkeras_tpu.analysis import interleave

    results = interleave.run_suite()
    by_name = {r.name: r for r in results}
    assert by_name["dedup"].complete == 924       # 12!/(6!6!)
    assert by_name["fence"].complete == 11550     # 11!/(4!4!3!)
    assert by_name["journal"].complete == 924
    assert by_name["journal"].crashed > 2000      # crash at every prefix
    total = sum(r.schedules for r in results)
    assert total >= 10_000
    for r in results:
        assert r.violations == [], r.violations[:3]


def test_interleave_catches_seeded_dedup_mutation():
    """A server that forgets its dedup table must produce exactly-once
    violations — the checker's own regression test."""
    from distkeras_tpu.analysis import interleave

    res = interleave.explore(
        lambda: interleave.DedupScenario(interleave._NoDedupServer),
        max_schedules=50)
    assert res.violations, "mutated server not caught"
    assert any("folded" in v.message or "duplicate fold" in v.message
               for v in res.violations)


def test_interleave_cli(capsys):
    from distkeras_tpu.analysis import interleave

    assert interleave.main(["--scenario", "dedup"]) == 0
    out = capsys.readouterr().out
    assert "924 complete schedules" in out and "state space" in out
    assert interleave.main(["--scenario", "dedup", "--mutate"]) == 0
    assert "CAUGHT" in capsys.readouterr().out
