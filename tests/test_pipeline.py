"""Pipeline-parallel tests: gpipe schedule correctness, dp x pp training."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distkeras_tpu.models.base import Model
from distkeras_tpu.models.transformer import TransformerLM
from distkeras_tpu.ops.collectives import shard_map
from distkeras_tpu.parallel.pipeline import gpipe, last_stage_broadcast
from distkeras_tpu.parallel.pipeline_engine import (
    PipelineEngine,
    merge_transformer_params,
    split_transformer_params,
)
from distkeras_tpu.runtime.mesh import hybrid_mesh


def test_gpipe_matches_sequential():
    """4-stage pipeline of affine stages == sequential composition."""
    S, M, D = 4, 8, 16
    rng = np.random.default_rng(0)
    # stage s: x -> x * w[s] + b[s]  (stacked params sharded over pipe)
    w = jnp.asarray(rng.uniform(0.5, 1.5, size=(S, D)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(S, D)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(M, 4, D)).astype(np.float32))

    mesh = hybrid_mesh({"pipe": S})

    def run(w, b, x):
        def stage_fn(p, h):
            return h * p[0][0] + p[1][0]

        y = gpipe(stage_fn, (w, b), x, "pipe")
        return last_stage_broadcast(y, "pipe")

    y = shard_map(run, mesh=mesh,
                  in_specs=(P("pipe"), P("pipe"), P()),
                  out_specs=P(), check_vma=False)(w, b, x)

    expect = x
    for s in range(S):
        expect = expect * w[s] + b[s]
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect), rtol=1e-5)


def _tiny_lm(num_layers=4):
    arch = dict(vocab_size=64, num_layers=num_layers, d_model=32, num_heads=2,
                d_ff=64, max_seq_len=16)
    return Model.build(TransformerLM(**arch), jnp.zeros((1, 16), jnp.int32))


def test_split_merge_roundtrip():
    model = _tiny_lm()
    rep, stage = split_transformer_params(model.params, num_stages=2)
    merged = merge_transformer_params(rep, stage)
    for (pa, a), (pb, b) in zip(
        sorted(jax.tree_util.tree_flatten_with_path(model.params)[0], key=str),
        sorted(jax.tree_util.tree_flatten_with_path(merged)[0], key=str),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_forward_matches_dense():
    """dp x pp pipelined forward == the plain single-device forward."""
    model = _tiny_lm(num_layers=4)
    mesh = hybrid_mesh({"data": 2, "pipe": 4})
    engine = PipelineEngine(model, "sgd", "sparse_categorical_crossentropy", mesh,
                            num_microbatches=2)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 16)), jnp.int32)

    rep, stage = split_transformer_params(model.params, engine.num_stages)

    def fwd(rep, stage, tokens):
        logits = engine._forward(rep, stage, tokens, jax.random.key(0))
        return last_stage_broadcast(logits, "pipe")

    logits = shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P("pipe"), P("data")),
        out_specs=P("data"), check_vma=False,
    )(rep, stage, tokens)

    expect = model.predict(tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(expect),
                               atol=2e-4, rtol=1e-4)


def test_pipeline_training_matches_single_device():
    """One dp x pp SGD step == one single-device SGD step on the same batch."""
    import optax

    from distkeras_tpu.ops.losses import get_loss

    model = _tiny_lm(num_layers=2)
    mesh = hybrid_mesh({"data": 2, "pipe": 2})
    lr = 0.1
    engine = PipelineEngine(model, "sgd", "sparse_categorical_crossentropy", mesh,
                            num_microbatches=2, learning_rate=lr)
    state = engine.init_state()

    rng = np.random.default_rng(2)
    tokens = np.asarray(rng.integers(0, 64, size=(4, 16)), np.int32)
    targets = np.asarray(np.roll(tokens, -1, 1), np.int32)
    tj = jax.device_put(jnp.asarray(tokens), engine.batch_sharding())
    gj = jax.device_put(jnp.asarray(targets), engine.batch_sharding())

    state, loss = engine.step(state, tj, gj)
    piped = engine.export_params(state)

    # manual single-device step
    loss_fn = get_loss("sparse_categorical_crossentropy")

    def loss_of(p):
        logits = model.module.apply({"params": p}, jnp.asarray(tokens), train=False)
        return loss_fn(logits, jnp.asarray(targets))

    ref_loss, grads = jax.value_and_grad(loss_of)(model.params)
    tx = optax.sgd(lr)
    updates, _ = tx.update(grads, tx.init(model.params), model.params)
    expect = jax.tree.map(jnp.add, model.params, updates)

    assert abs(float(loss) - float(ref_loss)) < 2e-4
    for a, b in zip(jax.tree.leaves(piped), jax.tree.leaves(expect)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    # training continues: a few more steps should reduce loss on this batch
    losses = [float(loss)]
    for _ in range(5):
        state, loss = engine.step(state, tj, gj)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
