"""Datasets, job deployment, and example-workflow smoke tests."""

import sys

import numpy as np

from distkeras_tpu.datasets import cifar10, imdb, mnist, synthetic_lm
from distkeras_tpu.job_deployment import Job, Punchcard

import envcaps


def test_mnist_shapes():
    df = mnist(n=256)
    assert df["features"].shape == (256, 28, 28, 1)
    assert df["features"].min() >= 0 and df["features"].max() <= 1
    assert set(np.unique(df["label"])) <= set(range(10))
    assert df.synthetic is True
    flat = mnist(n=64, flat=True)
    assert flat["features"].shape == (64, 784)


def test_cifar10_shapes():
    df = cifar10(n=128)
    assert df["features"].shape == (128, 32, 32, 3)


def test_imdb_shapes_and_signal():
    df = imdb(n=512, vocab_size=500, seq_len=40)
    assert df["features"].shape == (512, 40)
    assert df["features"].max() < 500
    # sentiment token ranges must differ by class (learnable signal)
    pos = df["features"][df["label"] == 1]
    neg = df["features"][df["label"] == 0]
    pos_frac = ((pos >= 10) & (pos < 60)).mean()
    neg_frac = ((neg >= 10) & (neg < 60)).mean()
    assert pos_frac > neg_frac + 0.1


def test_synthetic_lm_is_predictable():
    df = synthetic_lm(n=64, vocab_size=32, seq_len=16)
    assert df["features"].shape == (64, 15)
    assert df["label"].shape == (64, 15)
    np.testing.assert_array_equal(df["features"][:, 1:], df["label"][:, :-1])


def test_dataset_determinism():
    a, b = mnist(n=32), mnist(n=32)
    np.testing.assert_array_equal(a["features"], b["features"])


def test_punchcard_roundtrip_and_job_render():
    pc = Punchcard(job_name="train", script="train.py",
                   hosts=["10.0.0.1", "10.0.0.2"], env={"FOO": "bar"},
                   args=["--epochs", "3"], coordinator_port=8476)
    pc2 = Punchcard.from_json(pc.to_json())
    assert pc2.hosts == ["10.0.0.1", "10.0.0.2"]

    cmds = Job(pc).launch(dry_run=True)
    assert len(cmds) == 2
    assert "JAX_COORDINATOR_ADDRESS=10.0.0.1:8476" in cmds[0]
    assert "JAX_PROCESS_ID=0" in cmds[0] and "JAX_PROCESS_ID=1" in cmds[1]
    assert "JAX_NUM_PROCESSES=2" in cmds[1]
    assert "FOO=bar" in cmds[0] and "--epochs 3" in cmds[0]


def _run_example(monkeypatch, module_name, argv):
    import importlib

    monkeypatch.setattr(sys, "argv", argv)
    sys.path.insert(0, "examples")
    try:
        mod = importlib.import_module(module_name)
        mod.main()
    finally:
        sys.path.remove("examples")


def test_mnist_workflow_example(monkeypatch, capsys):
    _run_example(monkeypatch, "mnist_workflow",
                 ["x", "--trainer", "adag", "--workers", "4", "--epochs", "1",
                  "--rows", "1024", "--batch-size", "16", "--window", "4"])
    out = capsys.readouterr().out
    assert "test accuracy" in out


@envcaps.skip_unless_key_sharding()
def test_transformer_spmd_example(monkeypatch, capsys):
    _run_example(monkeypatch, "transformer_spmd",
                 ["x", "--steps", "4", "--layers", "1", "--d-model", "32",
                  "--seq-len", "16", "--vocab", "64", "--batch-per-dp", "2"])
    out = capsys.readouterr().out
    assert "loss" in out
