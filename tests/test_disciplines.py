"""Discipline-fold semantics tests.

Strategy (SURVEY.md §4 "build consequence"): each fold rule is verified against a
hand-rolled numpy/optax re-execution of the same schedule — the kind of
numerical-equivalence testing the reference never had.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distkeras_tpu.data import DataFrame, make_batches
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.parallel.disciplines import (
    ADAGFold,
    AEASGDFold,
    DownpourFold,
    DynSGDFold,
    EnsembleFold,
)
from distkeras_tpu.parallel.engine import AsyncEngine
from distkeras_tpu.parallel.sync import SyncEngine
from distkeras_tpu.runtime.mesh import data_mesh

W, K, B, D, C = 4, 2, 4, 6, 3  # workers, window, batch, features, classes


def tiny_model(seed=0):
    module = MLP(hidden=(8,), num_outputs=C)
    return Model.build(module, jnp.zeros((1, D), jnp.float32), seed=seed)


def tiny_df(n=W * K * B * 3):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, D)).astype(np.float32)
    y = rng.integers(0, C, size=n).astype(np.int32)
    return DataFrame({"features": x, "label": y})


def manual_local_steps(module, params, xs, ys, lr):
    """Reference re-implementation of the worker hot loop with plain optax sgd."""
    loss_fn = get_loss("sparse_categorical_crossentropy")
    tx = optax.sgd(lr)
    opt = tx.init(params)

    def loss_of(p, x, y):
        return loss_fn(module.apply({"params": p}, x, train=True), y)

    for k in range(xs.shape[0]):
        grads = jax.grad(loss_of)(params, xs[k], ys[k])
        updates, opt = tx.update(grads, opt, params)
        params = optax.apply_updates(params, updates)
    return params


def run_one_round(discipline, lr=0.05):
    model = tiny_model()
    mesh = data_mesh(num_workers=W)
    engine = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                         discipline, mesh, window=K, learning_rate=lr)
    df = tiny_df()
    plan = make_batches(df, "features", "label", B, num_workers=W, window=K)
    state = engine.init_state()
    new_state, _ = engine._round_fn(state, *engine._put_batch(*plan.round(0)))
    return model, plan, new_state, lr


def per_worker_deltas(model, plan, lr):
    fx, fy = plan.round(0)
    deltas = []
    for i in range(W):
        local = manual_local_steps(model.module, model.params, fx[i], fy[i], lr)
        deltas.append(jax.tree.map(lambda a, b: a - b, local, model.params))
    return deltas


def tree_close(a, b, atol=1e-5):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol, rtol=1e-4)


def test_downpour_fold_sums_deltas():
    model, plan, state, lr = run_one_round(DownpourFold())
    deltas = per_worker_deltas(model, plan, lr)
    expect = model.params
    for d in deltas:
        expect = jax.tree.map(jnp.add, expect, d)
    tree_close(state.center, expect)
    # pull semantics: every local replica equals the new center
    for i in range(W):
        tree_close(jax.tree.map(lambda a: a[i], state.locals_), state.center)


def test_adag_fold_normalizes_by_window():
    model, plan, state, lr = run_one_round(ADAGFold())
    deltas = per_worker_deltas(model, plan, lr)
    expect = model.params
    for d in deltas:
        expect = jax.tree.map(lambda e, x: e + x / K, expect, d)
    tree_close(state.center, expect)


def test_dynsgd_fold_staleness_weights():
    model, plan, state, lr = run_one_round(DynSGDFold())
    deltas = per_worker_deltas(model, plan, lr)
    expect = model.params
    for i, d in enumerate(deltas):
        expect = jax.tree.map(lambda e, x, w=1.0 / (i + 1): e + w * x, expect, d)
    tree_close(state.center, expect)


def test_dynsgd_staleness_rotates_across_rounds():
    """Worker i's staleness at round r is (i + r) mod W — the serialized commit
    order rotates so no data shard is permanently down-weighted."""
    lr = 0.05
    model = tiny_model()
    mesh = data_mesh(num_workers=W)
    engine = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                         DynSGDFold(), mesh, window=K, learning_rate=lr)
    df = tiny_df()
    plan = make_batches(df, "features", "label", B, num_workers=W, window=K)
    state = engine.init_state()
    state, _ = engine._round_fn(state, *engine._put_batch(*plan.round(0)))
    center_r0 = jax.device_get(state.center)
    state, _ = engine._round_fn(state, *engine._put_batch(*plan.round(1)))

    # Manual round 1: every worker pulls center_r0, runs K steps on its round-1
    # shard; commit i is weighted 1/(((i + 1) % W) + 1).
    fx, fy = plan.round(1)
    center_r0_t = jax.tree.map(jnp.asarray, center_r0)
    expect = center_r0_t
    for i in range(W):
        local = manual_local_steps(model.module, center_r0_t, fx[i], fy[i], lr)
        d = jax.tree.map(lambda a, b: a - b, local, center_r0_t)
        w = 1.0 / (((i + 1) % W) + 1)
        expect = jax.tree.map(lambda e, x, w=w: e + w * x, expect, d)
    tree_close(state.center, expect)
    # fairness: over W rounds each shard sees every staleness level exactly once
    sched = [[(i + r) % W for i in range(W)] for r in range(W)]
    for i in range(W):
        assert sorted(row[i] for row in sched) == list(range(W))


def test_aeasgd_fold_elastic_symmetry():
    rho = 0.25
    model, plan, state, lr = run_one_round(AEASGDFold(alpha=rho))
    deltas = per_worker_deltas(model, plan, lr)
    # center' = center + rho * sum_i (local_i - center)
    expect_center = model.params
    for d in deltas:
        expect_center = jax.tree.map(lambda e, x: e + rho * x, expect_center, d)
    tree_close(state.center, expect_center)
    # local_i' = local_i - rho*(local_i - center): moved toward old center
    for i, d in enumerate(deltas):
        local_after = jax.tree.map(lambda p, x: p + x, model.params, d)
        expect_local = jax.tree.map(lambda l, x: l - rho * x, local_after, d)
        tree_close(jax.tree.map(lambda a: a[i], state.locals_), expect_local)


def test_per_worker_init_diversifies_replicas():
    """Ensemble/averaging replicas must start from DIFFERENT init draws
    (reference: per-executor deserialization + uniform_weights re-init)."""
    model = tiny_model()
    mesh = data_mesh(num_workers=W)
    engine = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                         EnsembleFold(), mesh, window=K, learning_rate=0.05,
                         per_worker_init=True)
    locals_ = jax.device_get(engine.init_state().locals_)
    # pick a weight matrix (biases are zero-init under every draw)
    kernels = next(a for a in jax.tree.leaves(locals_) if a.ndim >= 3)
    for i in range(W):
        for j in range(i + 1, W):
            assert not np.allclose(kernels[i], kernels[j]), (i, j)


def test_reinit_params_fallback_without_sample_spec():
    """Models without a recorded sample spec (deserialized / Keras-ingested) get
    the distribution-preserving permutation fallback."""
    model = tiny_model()
    stripped = Model(module=model.module, params=model.params)  # no sample_spec
    p1 = stripped.reinit_params(1)
    p2 = stripped.reinit_params(2)

    def kernel(tree):  # first weight matrix; biases are permutation fixed points
        return next(a for a in jax.tree.leaves(tree) if np.ndim(a) >= 2)

    k0, k1, k2 = kernel(model.params), kernel(p1), kernel(p2)
    assert not np.allclose(k1, k2)
    # permutation preserves the multiset of values exactly
    np.testing.assert_allclose(np.sort(np.ravel(k0)), np.sort(np.ravel(k1)), rtol=1e-7)


def test_ensemble_fold_no_communication():
    model, plan, state, lr = run_one_round(EnsembleFold())
    tree_close(state.center, model.params)  # center untouched
    deltas = per_worker_deltas(model, plan, lr)
    for i, d in enumerate(deltas):
        expect_local = jax.tree.map(lambda p, x: p + x, model.params, d)
        tree_close(jax.tree.map(lambda a: a[i], state.locals_), expect_local)


def test_sync_engine_matches_large_batch_sgd():
    """W-chip sync DP ≡ single-chip SGD on the W-times-larger batch (SURVEY.md §4)."""
    lr = 0.1
    model = tiny_model()
    df = tiny_df()
    mesh = data_mesh(num_workers=W)
    engine = SyncEngine(model, "sgd", "sparse_categorical_crossentropy", mesh,
                        learning_rate=lr)
    plan = make_batches(df, "features", "label", B, num_workers=W, window=K)
    state, _ = engine.run(plan)

    # Manual: same schedule, global batch = concat over workers per step.
    params = model.params
    loss_fn = get_loss("sparse_categorical_crossentropy")
    tx = optax.sgd(lr)
    opt = tx.init(params)

    def loss_of(p, x, y):
        return loss_fn(model.module.apply({"params": p}, x, train=True), y)

    for r in range(plan.num_rounds):
        fx, fy = plan.round(r)
        for k in range(K):
            x = fx[:, k].reshape(-1, D)
            y = fy[:, k].reshape(-1)
            grads = jax.grad(loss_of)(params, x, y)
            updates, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(params, updates)
    tree_close(state.params, params, atol=1e-4)


def test_downpour_single_worker_equals_sgd():
    """With W=1, DOWNPOUR's fold (center += delta) is exactly plain SGD."""
    lr = 0.05
    model = tiny_model()
    df = tiny_df()
    mesh = data_mesh(num_workers=1)
    engine = AsyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                         DownpourFold(), mesh, window=K, learning_rate=lr)
    plan = make_batches(df, "features", "label", B, num_workers=1, window=K)
    state, _ = engine.run(plan)

    params = model.params
    for r in range(plan.num_rounds):
        fx, fy = plan.round(r)
        params = manual_local_steps(model.module, params, fx[0], fy[0], lr)
    tree_close(state.center, params, atol=1e-4)
