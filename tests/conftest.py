"""Test bootstrap: simulate an 8-device TPU mesh on CPU.

The reference's only multi-worker test harness was Spark ``local[N]`` (SURVEY.md §4);
ours is XLA's host-platform device-count flag — every collective and sharding path runs
as a real 8-device program in CI, no TPU needed.

A pytest plugin in this environment imports jax before conftest runs, so setting env
vars alone is not enough — jax.config snapshots JAX_PLATFORMS at import. The backend
itself initializes lazily (first device access), so ``jax.config.update`` here still
wins as long as no test-collection code touched devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["KERAS_BACKEND"] = "jax"  # ~/.keras/keras.json says tensorflow
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

assert jax.device_count() == 8, (
    f"virtual CPU mesh not active (got {jax.device_count()} devices on "
    f"{jax.default_backend()}); a plugin initialized the jax backend before conftest"
)
