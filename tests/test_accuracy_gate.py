"""CPU-sized twin of the north-star accuracy gate (VERDICT r4 missing #2).

``accuracy_gate.py`` runs the real thing on the chip (bench CIFAR-10 CNN,
W=8, window 8, 3 seeds) and commits ``ACCURACY_r05.json``; this twin pins
the same comparison — ADAG vs AEASGD vs sync-DP at matched sample budgets
on the same ``cifar10_cnn``-family architecture over the same synthetic
CIFAR distribution — at a size the 2-core CI box can afford, asserting the
AEASGD-vs-ADAG accuracy gap stays under the gate's epsilon.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import distkeras_tpu as dk
from distkeras_tpu.datasets import cifar10
from distkeras_tpu.models.base import Model
from distkeras_tpu.models.cnn import SimpleCNN

EPSILON = 0.03  # CPU twin: fewer samples/seeds -> slightly wider than chip


def _small_cifar_cnn(seed):
    # The bench architecture's shape, scaled for 2 CPU cores: same conv/
    # dense stack family as cifar10_cnn, fewer features.
    return Model.build(
        SimpleCNN(conv_features=(8, 16), dense=(32,), num_outputs=10),
        jnp.zeros((1, 32, 32, 3), jnp.float32), seed=seed)


@pytest.mark.slow
def test_aeasgd_reaches_adag_equivalent_accuracy_on_cifar_cnn():
    n_train, n_eval = 2048, 512
    df_all = cifar10(n=n_train + n_eval)
    x = np.asarray(df_all["features"])
    y = np.asarray(df_all["label"])
    perm = np.random.default_rng(123).permutation(len(x))
    x, y = x[perm], y[perm]
    train = dk.DataFrame({"features": x[:n_train], "label": y[:n_train]})
    te_x, te_y = x[n_train:], y[n_train:]

    common = dict(loss="sparse_categorical_crossentropy", num_workers=8,
                  batch_size=8, num_epoch=2, learning_rate=0.05)

    def acc_of(trainer):
        trained = trainer.train(train, shuffle=True)
        preds = np.asarray(trained.predict(jnp.asarray(te_x))).argmax(-1)
        return float((preds == te_y).mean())

    means = {}
    for disc in ("adag", "aeasgd", "sync"):
        accs = []
        for seed in (0, 1):
            if disc == "adag":
                t = dk.ADAG(_small_cifar_cnn(seed), communication_window=4,
                            seed=seed, **common)
            elif disc == "aeasgd":
                # W*alpha = 0.4 < 1 (Zhang et al. beta sizing): the fold
                # adds the SUM of the W elastic terms, so the default
                # rho=5.0 at lr=0.05 (alpha=0.25, W*alpha=2) overshoots
                # the center and diverges — same rho the chip gate uses.
                t = dk.AEASGD(_small_cifar_cnn(seed), communication_window=4,
                              rho=1.0, seed=seed, **common)
            else:
                t = dk.SynchronousDistributedTrainer(
                    _small_cifar_cnn(seed), steps_per_program=4, seed=seed,
                    **common)
            accs.append(acc_of(t))
        means[disc] = float(np.mean(accs))

    # Every discipline converges on the synthetic class structure...
    for disc, m in means.items():
        assert m > 0.85, f"{disc} failed to converge: {means}"
    # ...and the north-star discipline matches ADAG within epsilon.
    assert abs(means["aeasgd"] - means["adag"]) < EPSILON, means
    assert abs(means["sync"] - means["adag"]) < EPSILON, means
