"""Streaming continual training: exactly-once ingest, drift handling,
and the claim-queue generalization (PR 17).

Layers under test, smallest to largest: the offset journal's two-phase
exactly-once protocol; the stream sources (file tail + socket, with
resume and chaos); the new `feed_gap`/`drift` fault kinds; the shared
WorkQueue (bounded ElasticTraining parity + open streaming mode); the
engines' epoch-free `run_stream` loop; DriftWatch paging/recovery and
the rollback-on-regression gate; the registry's freshness-at-swap; and
StreamingTraining end-to-end — including Supervisor retry-with-resume
interplay, where the crash-restart run must replay ZERO committed
offsets (cross-checked against the PS commit log)."""

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from distkeras_tpu import telemetry
from distkeras_tpu.resilience import faults
from distkeras_tpu.streaming import (
    DriftWatch, FileTailSource, OffsetJournal, SocketSource, StreamProducer,
    StreamFileWriter, StreamingSession, StreamingTraining, WindowedEval,
    WorkQueue, decode_record, encode_record)
from distkeras_tpu.streaming.journal import replayed_offsets


@pytest.fixture(autouse=True)
def _fresh_ambient():
    telemetry.reset()
    faults.reset()
    yield
    faults.reset()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Offset journal
# ---------------------------------------------------------------------------

def test_journal_roundtrip_frontier_and_ahead(tmp_path):
    path = str(tmp_path / "offsets.json")
    j = OffsetJournal(path)
    j.intent(0, 1, 0)
    j.committed(0, 0, event_ts=100.0)
    # Out-of-order commit parks in `ahead`, absorbed when the gap closes.
    j.committed(1, 2, event_ts=102.0)
    assert j.frontier == 1 and j.skip_offsets() == frozenset({2})
    j.committed(0, 1, event_ts=101.0)
    assert j.frontier == 3 and j.skip_offsets() == frozenset()
    j.set_meta(drift_from=7)

    j2 = OffsetJournal(path)
    assert j2.load() is True
    assert j2.frontier == 3
    assert j2.items_committed == 3
    assert j2.last_event_ts == 102.0
    assert j2.meta == {"drift_from": 7}
    assert j2.committed_offsets_upto(10) == {0, 1, 2}
    assert os.path.exists(path + ".sha256")


def test_journal_corruption_falls_back_to_previous_generation(tmp_path):
    path = str(tmp_path / "offsets.json")
    j = OffsetJournal(path)
    j.committed(0, 0)
    j.committed(0, 1)  # generation 2; generation 1 (frontier=1) is .prev
    with open(path, "ab") as f:
        f.write(b"torn")
    j2 = OffsetJournal(path)
    assert j2.load() is True, "must fall back to .prev, not to zero"
    assert j2.frontier == 1, "the previous generation's frontier"


def test_journal_resolve_landed_vs_unlanded_intents(tmp_path):
    j = OffsetJournal(str(tmp_path / "offsets.json"))
    j.committed(0, 0)
    j.intent(0, 5, 1)   # will have landed (PS folded seq 5, ACK lost)
    j.intent(1, 9, 2)   # never reached the PS
    landed = j.resolve({0: 5, 1: 8})
    assert landed == [1], "seq<=last_seq means the fold landed"
    assert j.frontier == 2, "landed offset is committed, never re-read"
    assert j.skip_offsets() == frozenset()
    assert j.start_offset() == 2, "offset 2 will be re-read and re-sent"
    # Both intents are gone either way.
    j2 = OffsetJournal(j.path)
    assert j2.load() and j2._intents == {}


def test_replayed_offsets_helper():
    assert replayed_offsets({0, 1, 2}, [3, 4]) == set()
    assert replayed_offsets({0, 1, 2}, [2, 3]) == {2}


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------

def _feed_arrays(i, k=1, b=4, feat=3):
    xs = np.full((k, b, feat), float(i), np.float32)
    ys = np.full((k, b), i % 3, np.int32)
    return xs, ys


def test_record_codec_roundtrip():
    xs, ys = _feed_arrays(7)
    frame = encode_record(xs, ys, 123.5)
    rec = decode_record(frame[4:], index=7)
    assert rec.index == 7 and rec.ts == 123.5
    np.testing.assert_array_equal(rec.xs, xs)
    np.testing.assert_array_equal(rec.ys, ys)


def test_file_tail_source_reads_resumes_and_skips(tmp_path):
    path = str(tmp_path / "feed.bin")
    w = StreamFileWriter(path)
    for i in range(6):
        w.append(*_feed_arrays(i), ts=float(i))
    w.end()

    src = FileTailSource(path, poll_s=0.01)
    got = list(src.read())
    assert [r.index for r in got] == list(range(6))
    assert all(float(r.xs[0, 0, 0]) == r.index for r in got)

    # Resume: start at the frontier, skip the out-of-order-committed set.
    src2 = FileTailSource(path, poll_s=0.01)
    got2 = [r.index for r in src2.read(start_index=2, skip=frozenset({4}))]
    assert got2 == [2, 3, 5]


def test_socket_source_survives_connection_kill():
    prod = StreamProducer()
    try:
        for i in range(10):
            prod.feed(*_feed_arrays(i))
        src = SocketSource(prod.endpoint, reconnect_s=5.0)
        seen = []
        it = src.read()
        for _ in range(4):
            seen.append(next(it).index)
        prod.kill_connections()  # the source-kill drill, mid-stream
        prod.end()
        seen.extend(r.index for r in it)
        assert seen == list(range(10)), "retransmits only: no loss, no dup"
        assert src.reconnects >= 1
    finally:
        prod.close()


# ---------------------------------------------------------------------------
# feed_gap / drift fault kinds
# ---------------------------------------------------------------------------

def test_feed_gap_and_drift_parse_and_one_shot():
    plan = faults.FaultPlan.parse("feed_gap@3:0.25;drift@5")
    assert plan.feed_gap(2) == 0.0
    assert plan.feed_gap(3) == 0.25
    assert plan.feed_gap(3) == 0.0, "one-shot"
    assert plan.drift(4) is False
    assert plan.drift(5) is True
    assert plan.drift(5) is False, "one-shot"


def test_drift_fault_shifts_labels_permanently(tmp_path):
    path = str(tmp_path / "feed.bin")
    w = StreamFileWriter(path)
    for i in range(6):
        xs = np.zeros((1, 4, 2), np.float32)
        ys = np.full((1, 4), 1, np.int32)
        w.append(xs, ys)
    w.end()
    faults.set_plan(faults.FaultPlan.parse("drift@3"))
    src = FileTailSource(path, poll_s=0.01, drift_classes=3)
    got = list(src.read())
    for r in got:
        if r.index < 3:
            assert not r.drifted and int(r.ys[0, 0]) == 1
        else:
            # (1 + 1) % 3 — the shift persists past the one-shot trigger.
            assert r.drifted and int(r.ys[0, 0]) == 2
    assert src.drift_from == 3


def test_feed_gap_fault_delays_delivery(tmp_path):
    path = str(tmp_path / "feed.bin")
    w = StreamFileWriter(path)
    for i in range(3):
        w.append(*_feed_arrays(i))
    w.end()
    faults.set_plan(faults.FaultPlan.parse("feed_gap@1:0.3"))
    src = FileTailSource(path, poll_s=0.01)
    t0 = time.perf_counter()
    assert [r.index for r in src.read()] == [0, 1, 2]
    assert time.perf_counter() - t0 >= 0.3, "record 1 was held back"


# ---------------------------------------------------------------------------
# WorkQueue (shared claim discipline)
# ---------------------------------------------------------------------------

def test_work_queue_bounded_mode_matches_elastic_semantics():
    q = WorkQueue(total=4)
    run = lambda: True
    assert q.claim(run) == 0 and q.claim(run) == 1
    q.requeue(0)
    assert q.claim(run) == 0, "retry queue wins over the frontier"
    for _ in range(2):
        q.commit_one()
    assert not q.done()
    assert q.claim(run) == 2 and q.claim(run) == 3
    q.commit_one()
    q.commit_one()
    assert q.done()
    assert q.claim(run) is None


def test_work_queue_bounded_claim_blocks_while_peers_in_flight():
    q = WorkQueue(total=2)
    a = q.claim(lambda: True)
    b = q.claim(lambda: True)
    got = []

    def late_claim():
        got.append(q.claim(lambda: True))

    t = threading.Thread(target=late_claim)
    t.start()
    time.sleep(0.05)
    q.requeue(a)  # eviction path: the requeued item must find the claimant
    t.join(timeout=5.0)
    assert got == [a]
    q.commit_one()
    q.commit_one()
    assert q.done()


def test_work_queue_open_mode_backpressure_and_done():
    q = WorkQueue(max_pending=2)
    assert q.put("a") and q.put("b")
    blocked = []

    def put_c():
        blocked.append(q.put("c"))

    t = threading.Thread(target=put_c)
    t.start()
    time.sleep(0.05)
    assert not blocked, "put blocks at max_pending (backpressure)"
    item = q.claim(lambda: True)
    t.join(timeout=5.0)
    assert blocked == [True] and item == "a"
    assert not q.done()
    q.commit_one()
    q.close_intake()
    assert not q.done(), "pending items remain"
    assert q.claim(lambda: True) == "b"
    q.commit_one()
    assert q.claim(lambda: True) == "c"
    q.commit_one()
    assert q.done()
    assert q.claim(lambda: True) is None
    assert q.put("d") is False, "intake closed"


# ---------------------------------------------------------------------------
# RoundFeeder items mode + engine run_stream
# ---------------------------------------------------------------------------

def test_round_feeder_accepts_item_iterables():
    from distkeras_tpu.data.prefetch import RoundFeeder

    items = ["a", "b", "c"]
    feeder = RoundFeeder(iter(items), stage=str.upper, start_round=5)
    assert list(feeder) == [(5, "A"), (6, "B"), (7, "C")]


def test_run_stream_trains_sync_engine_without_epoch_schedule():
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.parallel.sync import SyncEngine
    from distkeras_tpu.runtime.mesh import data_mesh

    rng = np.random.default_rng(0)
    model = Model.build(MLP(hidden=(8,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32))
    engine = SyncEngine(model, "sgd", "sparse_categorical_crossentropy",
                        data_mesh(num_workers=2), learning_rate=0.05)

    def batches():
        while True:  # endless — max_items must bound it
            xs = rng.normal(size=(2, 2, 8, 4)).astype(np.float32)
            ys = rng.integers(0, 3, size=(2, 2, 8)).astype(np.int32)
            yield xs, ys

    seen = []
    state, losses = engine.run_stream(
        batches(), on_item=lambda i, loss, st: seen.append(i),
        max_items=6)
    assert losses.size == 6, "one loss per consumed item"
    assert np.all(np.isfinite(losses))
    assert seen == list(range(6))
    assert engine.feed_wait_seconds >= 0.0


# ---------------------------------------------------------------------------
# Windowed eval, drift watch, regression gate
# ---------------------------------------------------------------------------

def test_drift_watch_pages_then_clears_with_recovery_time():
    watch = DriftWatch(window=WindowedEval(fast=4, slow=16),
                       drift_factor=2.0, floor=0.05)
    drifts, recoveries = [], []
    watch.on_drift = lambda fast, slow: drifts.append((fast, slow))
    watch.on_recover = lambda s: recoveries.append(s)
    for _ in range(16):
        assert watch.update(0.1) is None, "healthy baseline never pages"
    fired = [watch.update(10.0) for _ in range(4)]
    assert "fired" in fired
    assert watch.paging and watch.drift_events == 1 and len(drifts) == 1
    cleared = [watch.update(0.1) for _ in range(16)]
    assert "cleared" in cleared
    assert not watch.paging
    assert recoveries and watch.last_recovery_s is not None
    snap = telemetry.get().snapshot()
    assert snap["counters"]["stream.drift_events"] == 1
    assert "stream.recovery_seconds" in snap["gauges"]


def test_drift_watch_warmup_never_pages():
    watch = DriftWatch(window=WindowedEval(fast=8, slow=64),
                       drift_factor=2.0, floor=0.05)
    # Huge losses during warmup: both windows track each other — no page.
    for _ in range(8):
        assert watch.update(50.0) is None
    assert not watch.paging


def test_regression_gate_refuses_regressed_candidate():
    watch = DriftWatch(window=WindowedEval(fast=4, slow=8))
    losses = {"good": 1.0, "better": 0.8, "regressed": 1.5}
    gate = watch.regression_gate(lambda name: losses[name],
                                 regress_floor=0.25)
    assert gate("good", 1) is True
    assert gate("better", 2) is True
    assert gate("regressed", 3) is False, "1.5 > 0.8 * 1.25"
    assert gate("good", 4) is True, "1.0 <= 0.8 * 1.25"
    events = [e["kind"] for e in telemetry.get().events()]
    assert "stream_swap_rolled_back" in events


def test_registry_quality_gate_and_freshness(tmp_path):
    import jax

    from distkeras_tpu.checkpoint import Checkpointer
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.serving.registry import ModelRegistry

    model = Model.build(MLP(hidden=(4,), num_outputs=3),
                        jnp.zeros((1, 4), jnp.float32))
    directory = str(tmp_path)

    def save(step, event_age_s):
        ckpt = Checkpointer(directory)
        params = jax.tree.map(lambda a: np.asarray(a), model.params)
        assert ckpt.save(step, params, wait=True,
                         meta={"streaming": True,
                               "event_ts": time.time() - event_age_s})
        ckpt.close()

    verdicts = iter([True, False])
    registry = ModelRegistry(model, (1, 4), directory=directory,
                             poll_s=30.0,
                             quality_gate=lambda cand, step: next(verdicts))
    try:
        save(1, event_age_s=5.0)
        assert registry.poll_once() is True and registry.version == 1
        snap = telemetry.get().snapshot()
        # Freshness at swap: now - the newest folded event's timestamp.
        assert snap["gauges"]["serving.freshness_s"]["value"] >= 4.0
        assert snap["spans"]["serving.freshness"]["count"] == 1

        save(2, event_age_s=0.0)
        assert registry.poll_once() is False, "gate refused the candidate"
        assert registry.version == 1, "incumbent keeps serving"
        snap = telemetry.get().snapshot()
        assert snap["counters"]["serving.swap_rejected_regression"] == 1
        assert registry.poll_once() is False, "refusal is remembered"
    finally:
        registry.close()


# ---------------------------------------------------------------------------
# StreamingTraining end to end
# ---------------------------------------------------------------------------

def _build_model(seed=0):
    from distkeras_tpu.models.base import Model
    from distkeras_tpu.models.mlp import MLP

    return Model.build(MLP(hidden=(16,), num_outputs=3),
                       jnp.zeros((1, 4), jnp.float32), seed=seed)


def _stream_file(tmp_path, n, seed=0, k=2, b=8):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(3, 4))
    path = str(tmp_path / "feed.bin")
    w = StreamFileWriter(path)
    for i in range(n):
        y = rng.integers(0, 3, size=(k, b))
        x = (centers[y] + rng.normal(scale=0.5, size=(k, b, 4))).astype(
            np.float32)
        w.append(x, y.astype(np.int32), ts=float(i))
    w.end()
    return path


def _make_runtime(tmp_path, path, **kw):
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.ops.optimizers import get_optimizer

    kw.setdefault("journal", str(tmp_path / "offsets.json"))
    kw.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
    kw.setdefault("checkpoint_every", 4)
    return StreamingTraining(
        model=_build_model(), tx=get_optimizer("sgd", 0.1),
        loss_fn=get_loss("sparse_categorical_crossentropy"),
        source=FileTailSource(path, poll_s=0.01, drift_classes=3), **kw)


def test_streaming_training_exactly_once_in_process(tmp_path):
    n = 12
    path = _stream_file(tmp_path, n)
    rt = _make_runtime(tmp_path, path, num_workers=2)
    sess = StreamingSession(lambda resume: rt, num_workers=2,
                            checkpoint_dir=rt.checkpoint_dir,
                            checkpoint_every=rt.checkpoint_every)
    model = sess.train()
    assert model is not None
    assert rt.progress() == n
    assert rt.done()

    # Exactly-once against the PS commit log: one applied fold per record,
    # no (wid, seq) ever folded twice.
    log = rt.server.commit_log
    assert len(log) == n
    assert len({(wid, seq) for wid, seq, _ in log}) == n

    # The journal agrees, and agrees durably (reload from disk).
    j = OffsetJournal(str(tmp_path / "offsets.json"))
    assert j.load() is True
    assert j.frontier == n and j.items_committed == n
    assert j.committed_offsets_upto(n) == set(range(n))

    # Checkpoints landed with the freshness anchor in their meta.
    from distkeras_tpu import checkpoint as ckpt_mod

    ckpt_dir = str(tmp_path / "ckpt")
    steps = ckpt_mod.scan_steps(ckpt_dir)
    assert steps, "interval checkpoints must exist"
    meta = ckpt_mod.read_meta(ckpt_dir, steps[0])
    assert meta["streaming"] is True and meta["event_ts"] is not None


class _RecordingSource:
    """Wrap a source, logging every delivered index — the replay probe."""

    def __init__(self, inner, log):
        self._inner = inner
        self.log = log

    @property
    def drift_from(self):
        return self._inner.drift_from

    @drift_from.setter
    def drift_from(self, v):
        self._inner.drift_from = v

    def read(self, start_index=0, skip=frozenset()):
        for rec in self._inner.read(start_index, skip):
            self.log.append(rec.index)
            yield rec

    def close(self):
        self._inner.close()


def test_supervisor_resume_replays_zero_committed_items(tmp_path):
    """The resume-interplay drill: crash mid-stream under the Supervisor,
    resume from offset journal + checkpoint, and prove the restarted run
    re-reads NOTHING the journal holds as committed — while the PS commit
    log shows every record folded exactly once across both attempts."""
    from distkeras_tpu.netps.server import PSServer
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.ops.optimizers import get_optimizer
    from distkeras_tpu.resilience.errors import InjectedFault
    from distkeras_tpu.resilience.supervisor import Supervisor

    n = 10
    path = _stream_file(tmp_path, n)
    jpath = str(tmp_path / "offsets.json")
    ckpt_dir = str(tmp_path / "ckpt")
    # The PS outlives the crash (the in-process analogue of the durable
    # netps subprocess the chaos smoke uses).
    server = PSServer(discipline="adag", host="127.0.0.1", port=0).start()
    faults.set_plan(faults.FaultPlan.parse("crash@5"))
    committed_before = {}
    delivered = {}
    attempt = [0]

    def factory(resume):
        attempt[0] += 1
        if resume:
            probe = OffsetJournal(jpath)
            assert probe.load() is True
            committed_before["set"] = probe.committed_offsets_upto(n)
        log = []
        delivered[attempt[0]] = log
        return StreamingTraining(
            model=_build_model(), tx=get_optimizer("sgd", 0.1),
            loss_fn=get_loss("sparse_categorical_crossentropy"),
            source=_RecordingSource(
                FileTailSource(path, poll_s=0.01, drift_classes=3), log),
            num_workers=1, journal=jpath, endpoint=server.endpoint,
            checkpoint_dir=ckpt_dir, checkpoint_every=2, resume=resume)

    sess = StreamingSession(factory, num_workers=1,
                            checkpoint_dir=ckpt_dir, checkpoint_every=2)
    sup = Supervisor(sess, max_retries=2, backoff_s=0.0,
                     retry_on=(InjectedFault,))
    try:
        with pytest.warns(UserWarning, match="supervised train attempt"):
            model = sup.train(None)
        assert model is not None
        assert sup.attempts == 2

        before = committed_before["set"]
        assert before == set(range(5)), "crash@5 landed after 5 commits"
        # THE exactly-once claim: zero replayed committed items...
        assert replayed_offsets(before, delivered[2]) == set()
        # ...and zero lost items: everything committed exactly once.
        j = OffsetJournal(jpath)
        assert j.load() and j.committed_offsets_upto(n) == set(range(n))
        log = server.commit_log
        assert len(log) == n, "one applied fold per record, both attempts"
        assert len({(wid, seq) for wid, seq, _ in log}) == n
    finally:
        server.close()


def test_streaming_stall_surfaces_as_feeder_error(tmp_path, monkeypatch):
    """A dried-up feed must become the Supervisor-visible typed error,
    not a silent hang: the reader runs through RoundFeeder's watchdog."""
    from distkeras_tpu.resilience.errors import FeederStalledError

    monkeypatch.setenv("DKTPU_FEEDER_TIMEOUT", "0.5")
    monkeypatch.setenv("DKTPU_FEEDER_WARN", "0.2")
    path = str(tmp_path / "feed.bin")
    w = StreamFileWriter(path)
    w.append(*_feed_arrays(0, k=2, b=8, feat=4))
    w.close()  # NO end(): the tail waits forever for a frame
    rt = _make_runtime(tmp_path, path, num_workers=1)
    sess = StreamingSession(lambda resume: rt, num_workers=1,
                            checkpoint_dir=rt.checkpoint_dir)
    with pytest.raises(FeederStalledError):
        sess.train()
