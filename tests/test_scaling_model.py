"""North-star scaling gate, bounded analytically (VERDICT r2 missing #3).

Real multi-chip runs can't happen here (one chip), so the >=90%@64-chips
gate is bounded by arithmetic whose inputs are MEASURED: the single-chip
fold-round time from the committed bench records and the actual model's
parameter bytes. The model (distkeras_tpu/roofline.py) is conservative —
one ICI ring direction, zero compute/comm overlap.
"""

import os

import pytest

from distkeras_tpu.roofline import FoldScalingModel, allreduce_seconds

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The bench AEASGD config (BASELINE #3): window 8, per-chip batch 1024.
_WINDOW, _BATCH = 8, 1024


def _measured_sps_per_chip() -> float:
    """samples/s/chip for cifar10_cnn_aeasgd from the latest committed bench
    record, via bench.py's own record reader (one parser, numeric round
    sort); falls back to the round-2 measurement if no record parses."""
    import sys

    sys.path.insert(0, _REPO)
    from bench import _prior_values

    for metric, value in _prior_values().items():
        if metric.startswith("cifar10_cnn_aeasgd") and value:
            return float(value)
    return 222_000.0  # round-2 floor (BENCH_r02.json)


def _model_bytes() -> float:
    from distkeras_tpu.models.cnn import cifar10_cnn

    m = cifar10_cnn()
    return m.num_params * 4  # f32 delta per round


def test_allreduce_seconds_shape():
    assert allreduce_seconds(1e6, 1) == 0.0
    # 2S(N-1)/N monotonically approaches 2S/link as N grows.
    t64 = allreduce_seconds(1e8, 64)
    t256 = allreduce_seconds(1e8, 256)
    assert t64 < t256 < 2 * 1e8 / 45e9


def test_north_star_efficiency_bound():
    """Predicted AEASGD scaling efficiency at 64 v5e chips >= 90%, with the
    model's inputs pinned from measured single-chip numbers."""
    sps = _measured_sps_per_chip()
    round_s = (_WINDOW * _BATCH) / sps  # one fold round of local compute
    model = FoldScalingModel(round_seconds=round_s, model_bytes=_model_bytes())
    eff64 = model.efficiency(64)
    assert eff64 >= 0.90, (
        f"predicted 64-chip efficiency {eff64:.3f} < 0.90 "
        f"(round {round_s*1e3:.1f} ms, comm {model.comm_seconds(64)*1e3:.2f} ms)")
    # And the gate holds with >5x margin on the comm estimate: even a 5x
    # slower effective link (stragglers, torus contention) stays above 90%.
    slow = FoldScalingModel(round_seconds=round_s,
                            model_bytes=_model_bytes(),
                            link_bytes_per_s=45e9 / 5)
    assert slow.efficiency(64) >= 0.90


def test_curve_is_monotone_and_bounded():
    m = FoldScalingModel(round_seconds=0.03, model_bytes=6e6)
    effs = [p["efficiency"] for p in m.curve()]
    assert all(0 < e <= 1 for e in effs)
    assert all(a >= b for a, b in zip(effs, effs[1:]))  # monotone down in N


def test_small_model_window_tradeoff():
    """The knob the reference exposed (communication_window) maps directly:
    doubling the window halves the fold's share, raising efficiency."""
    base = FoldScalingModel(round_seconds=0.01, model_bytes=1e8)
    wider = FoldScalingModel(round_seconds=0.02, model_bytes=1e8)
    assert wider.efficiency(64) > base.efficiency(64)


def test_dcn_hop_is_strictly_worse():
    """A fold whose slowest hop crosses DCN models as a slower link."""
    from distkeras_tpu.roofline import DCN_BYTES_PER_S

    ici = FoldScalingModel(round_seconds=0.02, model_bytes=1e8)
    dcn = FoldScalingModel(round_seconds=0.02, model_bytes=1e8,
                           link_bytes_per_s=DCN_BYTES_PER_S)
    assert dcn.efficiency(64) < ici.efficiency(64)


# ---------------------------------------------- BASELINE #5: ResNet-50 sync


def _resnet_sync_model(**kw):
    """Config #5's model from the committed bench record (the same basis the
    SCALING artifact commits — bench.resnet_sync_scaling_section)."""
    import sys

    sys.path.insert(0, _REPO)
    from bench import _prior_values
    from distkeras_tpu.roofline import SyncStepScalingModel

    sps = _prior_values().get("resnet50_sync_samples_per_sec_per_chip", 1980.4)
    # ResNet-50/1000-way param count (conv + GN affine + dense); pinned so
    # the test needs no model build. bench's eval_shape path recomputes it.
    grad_bytes = 4 * 25.6e6
    return SyncStepScalingModel(step_seconds=128 / sps,
                                grad_bytes=grad_bytes, **kw)


def test_resnet50_sync_gate_at_64_and_256():
    """BASELINE #5's gate: per-STEP ~100 MB f32 all-reduce (no window
    amortization) from the measured ~64 ms step still predicts >= 90%
    efficiency at 64 AND 256 chips on a single ICI slice."""
    m = _resnet_sync_model()
    assert m.efficiency(64) >= 0.90, m.curve()
    assert m.efficiency(256) >= 0.90, m.curve()


def test_resnet50_sync_multislice_dcn_hop():
    """v5e-256 as a 2x128 multislice: the cross-slice DCN exchange adds cost
    (strictly worse than single-slice ICI) but the gate still holds — the
    per-host NIC only carries each chip's reduce-scattered shard."""
    single = _resnet_sync_model()
    multi = _resnet_sync_model(chips_per_slice=128)
    assert multi.comm_seconds(256) > single.comm_seconds(256)
    assert multi.efficiency(256) >= 0.90, multi.curve()
    # Below the slice size the two models agree exactly (no DCN hop).
    assert multi.comm_seconds(128) == single.comm_seconds(128)


def test_resnet50_sync_levers():
    """The artifact's levers move the right way: bf16 grads halve the
    all-reduce bytes; grad_accum amortizes one all-reduce over A steps of
    compute. Both strictly raise predicted efficiency."""
    base = _resnet_sync_model()
    bf16 = _resnet_sync_model()
    bf16.grad_bytes /= 2
    accum = _resnet_sync_model(grad_accum=4)
    assert bf16.efficiency(256) > base.efficiency(256)
    assert accum.efficiency(256) > base.efficiency(256)
    assert bf16.comm_seconds(256) == pytest.approx(
        base.comm_seconds(256) / 2)


# --------------------------------------------- bench.py record-reading edges


def test_prior_values_skips_driver_record_with_null_parsed(tmp_path,
                                                           monkeypatch):
    """Driver-written BENCH_r*.json wraps the bench line under "parsed",
    which is null when that round's bench crashed before printing (the
    VERDICT r5 red-repo root cause): _prior_values must fall back to the
    next-most-recent round instead of raising."""
    import json
    import sys

    sys.path.insert(0, _REPO)
    import bench

    good = {"metric": "m_old", "value": 10.0,
            "configs": [{"metric": "cfg_a", "value": 2.5}]}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({"parsed": good}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"parsed": None}))
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    assert bench._prior_values() == {"m_old": 10.0, "cfg_a": 2.5}
    # An unreadable newest record falls back the same way.
    (tmp_path / "BENCH_r03.json").write_text("{not json")
    assert bench._prior_values() == {"m_old": 10.0, "cfg_a": 2.5}
    # Nothing readable at all -> empty dict, never an exception.
    for p in tmp_path.glob("BENCH_r0*.json"):
        p.write_text(json.dumps({"parsed": None}))
    assert bench._prior_values() == {}


def test_emit_summary_is_final_stdout_line_and_on_disk(tmp_path):
    """The driver machine-reads the LAST stdout line (BENCH_r05 landed
    ``"parsed": null`` when the tail was truncated): _emit_summary must
    print the summary as its own flushed final line AND leave the same
    JSON in BENCH_SUMMARY.json so a clipped stream still has a record."""
    import json
    import subprocess
    import sys

    code = (
        "import sys; sys.path.insert(0, sys.argv[2])\n"
        "import bench\n"
        "bench._REPO = sys.argv[1]\n"
        "print('preamble noise')\n"
        "bench._emit_summary({'metric': 'm', 'value': 1.5, 'configs': []})\n"
    )
    r = subprocess.run([sys.executable, "-c", code, str(tmp_path), _REPO],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    final = json.loads(r.stdout.strip().splitlines()[-1])
    assert final == {"metric": "m", "value": 1.5, "configs": []}
    with open(tmp_path / "BENCH_SUMMARY.json") as f:
        assert json.load(f) == final
