"""ParallelTrainer: the Trainer surface for the model-parallel engines
(VERDICT r2 missing #2). The beyond-reference engines (SPMD/GSPMD/Pipeline/
MoE) get the reference UX — ``train(dataframe)`` with checkpoint/resume,
metrics JSONL, and ``rounds_per_program`` — through the same ``_execute``
harness the data-parallel trainers use.
"""

import json

import numpy as np
import pytest

import jax

from distkeras_tpu import ParallelTrainer, TransformerTrainer
from distkeras_tpu.datasets import synthetic_lm
from distkeras_tpu.models.transformer import small_transformer_lm

import envcaps

SEQ = 32
VOCAB = 64


def _data(n=512, seed=0):
    return synthetic_lm(n=n, vocab_size=VOCAB, seq_len=SEQ + 1, seed=seed)


def _model(**kw):
    return small_transformer_lm(
        vocab_size=VOCAB, num_layers=2, d_model=32, num_heads=4, d_ff=64,
        max_seq_len=SEQ, seq_len=SEQ, **kw)


def _trainer(parallel, tmpdir=None, resume=False, every=0, **kw):
    return ParallelTrainer(
        _model(), parallel=parallel,
        worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        batch_size=16, num_epoch=1, learning_rate=3e-3,
        checkpoint_dir=str(tmpdir) if tmpdir else None,
        checkpoint_every=every, resume=resume, **kw)


def test_strategy_resolution():
    t = _trainer({"data": 2, "pipe": 4})
    assert t._resolve_strategy() == "pipeline"
    t = _trainer({"data": 2, "seq": 2, "model": 2})
    assert t._resolve_strategy() == "spmd"
    t = _trainer({"data": -1, "model": 2})
    assert t._resolve_strategy() == "gspmd"
    t = _trainer({"data": 2, "expert": 4})
    assert t._resolve_strategy() == "gspmd"
    with pytest.raises(ValueError, match="strategy"):
        _trainer({"data": -1}, strategy="nope")
    with pytest.raises(ValueError, match="grad_accum"):
        _trainer({"data": -1}, grad_accum=4)


def test_gspmd_tp_trains_and_logs_metrics(tmp_path):
    metrics = tmp_path / "m.jsonl"
    t = _trainer({"data": -1, "model": 2}, metrics_path=str(metrics))
    trained = t.train(_data())
    h = t.get_history()
    assert h[-1] < h[0]
    # Trained params flow back into a plain (unsharded) Model.
    assert trained.num_params == t.model.num_params
    lines = [json.loads(l) for l in metrics.read_text().splitlines()]
    recs = [l for l in lines if l.get("round") is not None]
    assert len(recs) == len(h)
    # samples/s/chip uses the real chip count (8), not plan workers (1).
    assert any(r.get("samples_per_sec_per_chip") for r in recs)


@envcaps.skip_unless_key_sharding()
def test_spmd_seq_axis_autobind():
    """A seq axis in `parallel` rebinds the module with seq_axis set, so
    positions/causality are computed globally; loss must still fall."""
    t = _trainer({"data": 2, "seq": 2, "model": 2})
    engine = t._build_engine()
    assert engine.inner.model.module.seq_axis == "seq"
    trained = t.train(_data())
    assert t.get_history()[-1] < t.get_history()[0]
    assert trained.module.seq_axis is None  # user's model config untouched


def test_spmd_inferred_seq_size_still_rebinds():
    """`seq: -1` resolves against the device count; the rebind guard must see
    the resolved size (2), not the sentinel, or the model silently trains
    with shard-local positions."""
    t = _trainer({"data": 2, "model": 2, "seq": -1})
    engine = t._build_engine()
    assert engine.mesh.shape["seq"] == 2
    assert engine.inner.model.module.seq_axis == "seq"


@envcaps.skip_unless_key_sharding()
def test_spmd_route_without_seq_axis_gets_unit_seq():
    """A flash/ring model on a dp×tp layout routes to SPMDEngine, which
    always shard_maps over (data, seq) — the trainer injects seq=1."""
    t = _trainer({"data": -1, "model": 2}, strategy="spmd")
    engine = t._build_engine()
    assert engine.mesh.shape["seq"] == 1
    trained_df = _data(n=128)
    t.train(trained_df)
    assert len(t.get_history())


def test_pipeline_trainer_matches_engine_semantics():
    """ParallelTrainer(pipe) ≡ hand-rolled PipelineEngine loop on the same
    schedule — the trainer adds harness, not different math."""
    from distkeras_tpu.data.batching import make_batches
    from distkeras_tpu.parallel.pipeline_engine import PipelineEngine
    from distkeras_tpu.runtime.mesh import hybrid_mesh

    df = _data()
    t = _trainer({"data": 2, "pipe": 2}, num_microbatches=2)
    trained = t.train(df)

    mesh = hybrid_mesh({"data": 2, "pipe": 2})
    eng = PipelineEngine(_model(), "adam", "sparse_categorical_crossentropy",
                         mesh, num_microbatches=2, learning_rate=3e-3)
    plan = make_batches(df, "features", "label", batch_size=16,
                        num_workers=1, window=4)
    state = eng.init_state()
    losses = []
    for r in range(plan.num_rounds):
        xs, ys = plan.round(r)
        for k in range(xs.shape[1]):
            state, loss = eng.step(state, jax.device_put(xs[0, k]),
                                   jax.device_put(ys[0, k]))
            losses.append(float(loss))
    window_means = np.asarray(losses).reshape(plan.num_rounds, -1).mean(1)
    np.testing.assert_allclose(t.get_history(), window_means, rtol=1e-5)
    ref = eng.export_params(state)
    for a, b in zip(jax.tree.leaves(trained.params), jax.tree.leaves(ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


@pytest.mark.parametrize("parallel", [
    {"data": -1, "model": 2},          # gspmd tp
    {"data": 2, "pipe": 2},            # pipeline
], ids=["gspmd", "pipeline"])
def test_checkpoint_resume_equals_uninterrupted(tmp_path, parallel):
    """Kill a run mid-training, resume from the checkpoint: the final model
    must equal the uninterrupted run exactly (the VERDICT's done-bar for the
    engine-trainer surface)."""
    df = _data()

    clean = _trainer(dict(parallel))
    clean_model = clean.train(df)

    class Boom(RuntimeError):
        pass

    def die(r, loss):
        if r == 3:
            raise Boom()

    ckpt = tmp_path / "ckpt"
    t1 = _trainer(dict(parallel), tmpdir=ckpt, every=2)
    t1.on_round = die
    with pytest.raises(Boom):
        t1.train(df)

    t2 = _trainer(dict(parallel), tmpdir=ckpt, every=2, resume=True)
    resumed_model = t2.train(df)

    for a, b in zip(jax.tree.leaves(resumed_model.params),
                    jax.tree.leaves(clean_model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # Resumed history is the tail of the clean history.
    tail = clean.get_history()[-len(t2.get_history()):]
    np.testing.assert_allclose(t2.get_history(), tail, rtol=1e-5)


@envcaps.skip_unless_key_sharding()
def test_checkpoint_resume_spmd(tmp_path):
    """Same resume-equivalence for the SPMDEngine (dp×sp×tp shard_map path)."""
    df = _data()
    parallel = {"data": 2, "seq": 2, "model": 2}

    clean = _trainer(dict(parallel))
    clean_model = clean.train(df)

    ckpt = tmp_path / "ckpt"
    t1 = _trainer(dict(parallel), tmpdir=ckpt, every=2)
    t1.on_round = lambda r, loss: (_ for _ in ()).throw(RuntimeError) if r == 3 else None
    with pytest.raises(RuntimeError):
        t1.train(df)

    t2 = _trainer(dict(parallel), tmpdir=ckpt, every=2, resume=True)
    resumed_model = t2.train(df)
    for a, b in zip(jax.tree.leaves(resumed_model.params),
                    jax.tree.leaves(clean_model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_moe_trainer_with_aux_loss():
    """Expert parallelism through the trainer: Switch-style MoE on a dp×ep
    mesh with the router load-balancing aux loss collected."""
    from distkeras_tpu.models.moe import small_moe_lm

    model = small_moe_lm(vocab_size=VOCAB, num_layers=2, d_model=32,
                         num_heads=4, d_ff=64, num_experts=4,
                         max_seq_len=SEQ, seq_len=SEQ)
    t = ParallelTrainer(
        model, parallel={"data": 2, "expert": 4},
        worker_optimizer="adam", loss="sparse_categorical_crossentropy",
        batch_size=16, num_epoch=1, learning_rate=3e-3, aux_loss_weight=0.01)
    t.train(_data())
    assert t.get_history()[-1] < t.get_history()[0]


def test_rounds_per_program_equivalence():
    """Blocked multi-round programs preserve the loss history exactly —
    dispatch amortization now works for the flagship engines too."""
    df = _data()
    t1 = _trainer({"data": -1, "model": 2})
    t1.train(df)
    t4 = _trainer({"data": -1, "model": 2}, rounds_per_program=4)
    t4.train(df)
    np.testing.assert_allclose(t1.get_history(), t4.get_history(), rtol=1e-5)


def test_transformer_trainer_alias():
    assert TransformerTrainer is ParallelTrainer


def test_rank_major_plan_merges_to_global_batch():
    """The Wp=dp worker-major batch stack (multi-process sharded staging)
    must be program-identical to the Wp=1 global batch when the rows match:
    the merge is a sharding-preserving reshape, not a different schedule."""
    engine = _trainer({"data": -1, "model": 2})._build_engine()
    dp = engine.dp_size
    rng = np.random.default_rng(0)
    K, B = 2, 16
    xs1 = rng.integers(0, VOCAB, size=(1, K, B, SEQ)).astype(np.int32)
    ys1 = rng.integers(0, VOCAB, size=(1, K, B, SEQ)).astype(np.int32)
    b = B // dp
    xs2 = np.stack([xs1[0, :, w * b:(w + 1) * b] for w in range(dp)])
    ys2 = np.stack([ys1[0, :, w * b:(w + 1) * b] for w in range(dp)])

    s1, l1 = engine._round_fn(engine.init_state(), *engine._put_batch(xs1, ys1))
    s2, l2 = engine._round_fn(engine.init_state(), *engine._put_batch(xs2, ys2))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, c in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-5,
                                   atol=1e-7)


def test_parallel_trainer_from_sharded_store(tmp_path):
    """Out-of-core flagship: a TransformerLM trains over a dp×tp mesh from a
    disk-backed sharded store (single-process; rows gathered per round)."""
    from distkeras_tpu.data.shards import ShardedDataFrame, write_shards

    df = _data(n=256)
    write_shards(tmp_path, {"features": np.asarray(df["features"]),
                            "label": np.asarray(df["label"])},
                 rows_per_shard=64)
    t = _trainer({"data": -1, "model": 2})
    t.train(ShardedDataFrame(tmp_path))
    assert t.get_history()[-1] < t.get_history()[0]
