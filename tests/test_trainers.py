"""Trainer API tests: parity surface + convergence of every discipline.

Convergence tests follow SURVEY.md §4's prescription: tiny MLP to a loss threshold
under each discipline — the check the reference's notebook-only testing never made.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distkeras_tpu import (
    ADAG,
    AEASGD,
    AveragingTrainer,
    DataFrame,
    DOWNPOUR,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    SingleTrainer,
    SynchronousDistributedTrainer,
)
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP


def blob_df(n=640, d=4, c=3, seed=0):
    """Linearly separable blobs — any sane trainer should crush this."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = centers[y] + rng.normal(scale=0.5, size=(n, d))
    return DataFrame({"features": x.astype(np.float32), "label": y.astype(np.int32)})


def tiny_model(d=4, c=3, seed=0):
    return Model.build(MLP(hidden=(16,), num_outputs=c),
                       jnp.zeros((1, d), jnp.float32), seed=seed)


COMMON = dict(loss="sparse_categorical_crossentropy", batch_size=16, num_epoch=3,
              learning_rate=0.1)


def accuracy(model, df):
    logits = np.asarray(model.predict(jnp.asarray(df["features"])))
    return float((logits.argmax(-1) == df["label"]).mean())


def test_single_trainer_converges():
    df = blob_df()
    t = SingleTrainer(tiny_model(), **COMMON)
    trained = t.train(df)
    assert t.get_training_time() > 0
    assert t.get_history() is not None and len(t.get_history()) > 0
    assert t.get_history()[-1] < t.get_history()[0]
    assert accuracy(trained, df) > 0.9


@pytest.mark.parametrize("cls,kwargs", [
    (SynchronousDistributedTrainer, {}),
    (DOWNPOUR, dict(communication_window=4, learning_rate=0.05)),
    (ADAG, dict(communication_window=4)),
    (DynSGD, dict(communication_window=4)),
    (AEASGD, dict(communication_window=4, rho=3.0)),  # alpha = rho*lr = 0.3
    (EAMSGD, dict(communication_window=4, rho=3.0, momentum=0.5)),
])
def test_distributed_trainers_converge(cls, kwargs):
    df = blob_df()
    merged = {**COMMON, **kwargs}
    t = cls(tiny_model(), num_workers=4, **merged)
    trained = t.train(df, shuffle=True)
    assert accuracy(trained, df) > 0.85, f"{cls.__name__} failed to converge"
    assert t.get_history()[-1] < t.get_history()[0]


def test_averaging_trainer():
    df = blob_df()
    t = AveragingTrainer(tiny_model(), num_workers=4, **COMMON)
    trained = t.train(df, shuffle=True)
    assert accuracy(trained, df) > 0.85


def test_ensemble_trainer_returns_distinct_models():
    df = blob_df()
    t = EnsembleTrainer(tiny_model(), num_workers=4, **COMMON)
    models = t.train(df, shuffle=True)
    assert len(models) == 4
    # independent data slices -> distinct weights
    a = np.asarray(next(iter(jnp.ravel(x) for x in [models[0].params["Dense_0"]["kernel"]])))
    b = np.asarray(models[1].params["Dense_0"]["kernel"]).ravel()
    assert not np.allclose(a, b)
    for m in models:
        assert accuracy(m, df) > 0.7


def test_trainer_does_not_mutate_input_model():
    df = blob_df(n=128)
    model = tiny_model()
    before = np.asarray(model.params["Dense_0"]["kernel"]).copy()
    SingleTrainer(model, **COMMON).train(df)
    np.testing.assert_array_equal(before, np.asarray(model.params["Dense_0"]["kernel"]))


def test_num_workers_defaults_to_all_devices():
    df = blob_df()
    t = DOWNPOUR(tiny_model(), communication_window=2, **COMMON)
    t.train(df)
    # mesh defaulted to all 8 virtual devices
    assert t.get_history() is not None


def test_legacy_socket_kwargs_accepted_and_ignored():
    """Reference notebooks pass master_port etc.; they must port by deleting
    imports only, not by editing every ctor call (accept-and-warn)."""
    with pytest.warns(DeprecationWarning, match="socket-era"):
        t = ADAG(tiny_model(), master_port=5000, master_host="driver", **COMMON)
    assert not hasattr(t, "master_port")
    with pytest.raises(TypeError, match="unexpected kwargs"):
        ADAG(tiny_model(), definitely_a_typo=1, **COMMON)


def test_per_worker_histories_surface():
    df = blob_df()
    t = ADAG(tiny_model(), num_workers=4, communication_window=4, **COMMON)
    t.train(df, shuffle=True)
    wh = t.get_worker_histories()
    assert wh is not None and wh.shape[0] == 4 and wh.shape[1] == len(t.get_history())
    np.testing.assert_allclose(wh.mean(axis=0), t.get_history(), rtol=1e-5)
    # different data shards -> (generically) different loss curves
    assert not np.allclose(wh[0], wh[1])
    # sync trainers have no divergent replicas to report
    ts = SingleTrainer(tiny_model(), **COMMON)
    ts.train(df)
    assert ts.get_worker_histories() is None


def test_run_config_backs_trainer_kwargs():
    """The kwargs-first surface normalizes into a frozen RunConfig and the
    legacy attribute names stay live (read AND write) over it."""
    t = DynSGD(tiny_model(), batch_size=64, communication_window=7,
               learning_rate=0.02, num_workers=2, **{
                   k: v for k, v in COMMON.items()
                   if k not in ("batch_size", "learning_rate")})
    assert t.config.batch_size == 64 and t.batch_size == 64
    assert t.config.communication_window == 7 and t.communication_window == 7
    assert t.config.num_workers == 2 and t.num_workers == 2
    t.batch_size = 32  # assignment must write through to the config
    assert t.config.batch_size == 32


def test_rounds_per_program_equivalence():
    """R rounds per dispatched program must produce the identical loss history
    and identical trained params as the one-round-per-dispatch path."""
    df = blob_df()
    results = []
    for rpp in (1, 3):
        t = ADAG(tiny_model(), num_workers=4, communication_window=2,
                 rounds_per_program=rpp, **COMMON)
        trained = t.train(df)
        results.append((t.get_history(), np.asarray(trained.predict(
            jnp.asarray(df["features"][:16])))))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-6)
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-5, atol=1e-6)


def test_sync_rounds_per_program_equivalence():
    df = blob_df()
    histories = []
    for rpp in (1, 4):
        t = SynchronousDistributedTrainer(tiny_model(), num_workers=4,
                                          rounds_per_program=rpp, **COMMON)
        t.train(df)
        histories.append(t.get_history())
    np.testing.assert_allclose(histories[0], histories[1], rtol=1e-6)


def test_rounds_per_program_auto_equivalence():
    """rounds_per_program='auto' (probe + self-sized blocks) must reproduce
    the fixed-R trajectory exactly — it only re-partitions dispatches."""
    df = blob_df()
    kw = {**COMMON, "num_epoch": 6}  # 640/(4*2*16)=5 rounds/epoch -> 30 > 16
    results = []
    for rpp in (1, "auto"):
        t = ADAG(tiny_model(), num_workers=4, communication_window=2,
                 rounds_per_program=rpp, **kw)
        trained = t.train(df)
        # past the 16-round probe head: blocked continuation + concat covered
        assert len(t.get_history()) == 30
        results.append((t.get_history(), np.asarray(trained.predict(
            jnp.asarray(df["features"][:16])))))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-6)
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="rounds_per_program"):
        ADAG(tiny_model(), rounds_per_program=0, **COMMON)


def test_rounds_per_program_auto_resume_past_end(tmp_path):
    """Resuming a completed run with rounds_per_program='auto' must return an
    empty history, not crash probing a round past the plan's end."""
    df = blob_df(n=256)
    ck = str(tmp_path / "ck")
    kw = dict(num_workers=4, communication_window=2, rounds_per_program="auto",
              checkpoint_dir=ck, checkpoint_every=1,
              metrics_path=str(tmp_path / "m.jsonl"), **COMMON)
    dk_t = ADAG(tiny_model(), **kw)
    dk_t.train(df)
    t2 = ADAG(tiny_model(), resume=True, **kw)
    t2.train(df)
    assert len(t2.get_history()) == 0


def test_rounds_per_program_auto_sync():
    df = blob_df()
    kw = {**COMMON, "num_epoch": 6}
    histories = []
    for rpp in (1, "auto"):
        t = SynchronousDistributedTrainer(tiny_model(), num_workers=4,
                                          steps_per_program=2,
                                          rounds_per_program=rpp, **kw)
        t.train(df)
        # 640/(4*2*16)=5 rounds/epoch x 6 = 30 > 16-round probe head
        assert len(t.get_history()) == 30
        histories.append(t.get_history())
    np.testing.assert_allclose(histories[0], histories[1], rtol=1e-6)


def test_bfloat16_compute_converges():
    """Mixed precision (bf16 fwd/bwd, fp32 master params) still converges."""
    df = blob_df()
    t = ADAG(tiny_model(), num_workers=4, communication_window=4,
             compute_dtype="bfloat16", **COMMON)
    trained = t.train(df)
    assert accuracy(trained, df) > 0.85


def test_rounds_per_program_partial_final_block():
    """num_rounds not divisible by R — including a 1-round remainder block —
    must still match the per-round path exactly."""
    df = blob_df(n=480)  # 480/(4*2*16) = 3.75 -> with window=2: 15 rounds
    ref = None
    for rpp in (1, 2, 4):  # 15 % 2 == 1 (1-round tail), 15 % 4 == 3
        t = ADAG(tiny_model(), num_workers=4, communication_window=2,
                 rounds_per_program=rpp, **COMMON)
        t.train(df)
        h = t.get_history()
        if ref is None:
            ref = h
        else:
            np.testing.assert_allclose(ref, h, rtol=1e-6)


def test_rounds_per_program_checkpoint_resume(tmp_path):
    """Checkpoints under blocked execution must resume to the identical result
    as an uninterrupted run (saves land only on block-final states)."""
    df = blob_df(n=480)
    kw = dict(num_workers=4, communication_window=2, rounds_per_program=2,
              **COMMON)
    t_full = ADAG(tiny_model(), **kw)
    full = t_full.train(df)

    ck = str(tmp_path / "ck")
    t1 = ADAG(tiny_model(), checkpoint_dir=ck, checkpoint_every=3, **kw)
    t1.train(df)
    # Resume from whatever step got saved and retrain the remainder.
    t2 = ADAG(tiny_model(), checkpoint_dir=ck, checkpoint_every=3, resume=True,
              **kw)
    resumed = t2.train(df)
    np.testing.assert_allclose(
        np.asarray(full.predict(jnp.asarray(df["features"][:32]))),
        np.asarray(resumed.predict(jnp.asarray(df["features"][:32]))),
        rtol=1e-5, atol=1e-6)


def test_grad_accum_equivalence():
    """grad_accum=A must produce the identical training trajectory to A=1
    (same mean gradient per optimizer step), at 1/A activation memory."""
    df = blob_df()
    histories = []
    for ga in (1, 4):
        t = ADAG(tiny_model(), num_workers=4, communication_window=2,
                 grad_accum=ga, **COMMON)
        trained = t.train(df)
        histories.append((t.get_history(),
                          np.asarray(trained.predict(jnp.asarray(df["features"][:16])))))
    np.testing.assert_allclose(histories[0][0], histories[1][0], rtol=1e-5)
    np.testing.assert_allclose(histories[0][1], histories[1][1], rtol=1e-4, atol=1e-6)


def test_grad_accum_sync_and_indivisible():
    df = blob_df()
    t = SynchronousDistributedTrainer(tiny_model(), num_workers=4, grad_accum=2,
                                      **COMMON)
    trained = t.train(df)
    assert accuracy(trained, df) > 0.85
    import pytest as _pytest
    bad = SynchronousDistributedTrainer(tiny_model(), num_workers=4,
                                        grad_accum=7, **COMMON)
    with _pytest.raises(ValueError, match="divisible"):
        bad.train(df)
