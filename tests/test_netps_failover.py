"""Surviving the parameter server: durable center state (journal +
snapshots + newest-intact-first recovery), warm-standby failover with
epoch fencing, client endpoint-list walking, and the PS-side chaos kinds.

The headline guarantees pinned here:

* **Bit-identical recovery** — a killed server relaunched on its state
  dir replays snapshot + journal to EXACTLY the pre-crash center (f32 and
  compressed-domain int8 commits alike), resumes the update counter, and
  answers joins with the last folded seq per worker so retransmits dedup.
* **Zero stale-epoch folds** — a promoted standby fences the old lineage:
  stale-epoch commits answer typed ``EpochFencedError`` and are never
  folded; a zombie ex-primary fences ITSELF on sight of a higher epoch.
* **Exactly-once across failover** — the replicated dedup table answers a
  pre-crash commit's retransmit ``duplicate=True`` on the new primary.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distkeras_tpu.netps import (
    EpochFencedError,
    NotPrimaryError,
    PSClient,
    PSServer,
    StandbyServer,
)
from distkeras_tpu.netps import state as netps_state
from distkeras_tpu.netps import wire
from distkeras_tpu.resilience.faults import FaultPlan

FAST = dict(timeout=1.0, retries=3, backoff=0.01)


def leaves():
    rng = np.random.default_rng(7)
    return [rng.normal(size=(4, 3)).astype(np.float32),
            rng.normal(size=(8,)).astype(np.float32)]


def drive_commits(endpoint, n, *, compress="none", worker_id=0, **kw):
    """Join + fold ``n`` deterministic commits; returns the client's view
    of the final (center, updates)."""
    rng = np.random.default_rng(worker_id + 1)
    c = PSClient(endpoint, worker_id=worker_id, compress=compress,
                 **dict(FAST, **kw))
    try:
        center, upd = c.join(init=leaves())
        for _ in range(n):
            delta = [rng.normal(scale=0.1, size=a.shape).astype(np.float32)
                     for a in center]
            c.commit(delta, upd)
            center, upd = c.pull()
        return center, upd
    finally:
        c.close()


# ---------------------------------------------------------------------------
# Durability: journal + snapshots + recovery
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("compress", ["none", "int8"])
def test_restart_replays_snapshot_plus_journal_bit_identically(
        tmp_path, compress):
    """THE parity pin: recovery replays journal records in their wire
    dtype with the recorded staleness, so the recovered center equals the
    pre-crash center bit for bit — including int8 compressed-domain folds,
    which must re-fold exactly as they first folded."""
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d, snapshot_every=4).start()
    try:
        drive_commits(srv.endpoint, 10, compress=compress)
        pre = srv.center()
        pre_updates, pre_total = srv.updates, srv.commits_total
        pre_seq = dict(srv._last_seq)
    finally:
        srv.close()
    srv2 = PSServer(discipline="adag", state_dir=d)
    try:
        post = srv2.center()
        assert srv2.updates == pre_updates
        assert srv2.commits_total == pre_total
        assert srv2._last_seq == pre_seq
        for a, b in zip(pre, post):
            assert a.tobytes() == b.tobytes(), "recovery is not bit-identical"
        # The commit-log bound invariant survives recovery too.
        assert len(srv2.commit_log) + srv2._log_dropped == srv2.commits_total
    finally:
        srv2.close()


def test_restarted_server_answers_join_with_last_seq_and_dedups(tmp_path):
    """In-flight commits retransmit exactly-once across a PS restart: the
    recovered dedup table answers the resumed worker's join with its last
    folded seq, and a retransmit of an already-folded seq never re-folds."""
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d).start()
    try:
        center, upd = drive_commits(srv.endpoint, 3)
    finally:
        srv.close()
    srv2 = PSServer(discipline="adag", state_dir=d).start()
    try:
        c = PSClient(srv2.endpoint, worker_id=0, **FAST)
        try:
            _, upd = c.join()
            assert c._seq == 2  # resumed past the server's folded history
            before = srv2.center()
            c._seq = 1  # retransmit of an ACKed pre-crash commit
            res = c.commit([np.ones_like(a) for a in before], upd)
            assert res.duplicate and not res.applied
            after = srv2.center()
            for a, b in zip(before, after):
                assert a.tobytes() == b.tobytes(), "dedup'd commit folded"
            res = c.commit([np.zeros_like(a) for a in before], upd)
            assert res.applied  # the NEXT seq folds normally
        finally:
            c.close()
    finally:
        srv2.close()


def test_torn_journal_tail_is_dropped_not_replayed(tmp_path):
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d, snapshot_every=0).start()
    try:
        drive_commits(srv.endpoint, 4)
    finally:
        srv.close()
    journals = sorted(p for p in os.listdir(d) if p.endswith(".dkj"))
    path = os.path.join(d, journals[-1])
    whole = open(path, "rb").read()
    open(path, "wb").write(whole[:-7])  # the crash-interrupted append
    srv2 = PSServer(discipline="adag", state_dir=d)
    try:
        # 1 base snapshot + 3 intact records; the torn 4th is detected by
        # the frame crc and dropped, never folded as garbage.
        assert srv2.updates == 3
    finally:
        srv2.close()


def test_torn_interior_journal_still_replays_the_anchored_chain(tmp_path):
    """TWO crashes between snapshots: the first leaves a torn tail in a
    journal that is no longer the last one by the time the second crash's
    recovery runs. The torn journal's valid prefix must still replay (it
    anchors the NEXT journal), and rotation must never truncate it —
    discarding it wholesale would regress the center to the snapshot,
    losing durably-written ACKed commits far beyond the documented
    bounded-writer window."""
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d, snapshot_every=4).start()
    try:
        drive_commits(srv.endpoint, 6)  # snapshot at 4; journal-4: u=4,5
    finally:
        srv.close()
    path = os.path.join(d, "journal-" + "4".zfill(12) + ".dkj")
    with open(path, "rb") as f:  # crash #1's tear: keep only u=4
        prefix = f.read(wire.PREFIX_SIZE)
        _k, _c, length = wire.parse_prefix(prefix)
        first = prefix + f.read(length)
    open(path, "wb").write(first + b"\x13torn")
    srv2 = PSServer(discipline="adag", state_dir=d).start()
    try:
        assert srv2.updates == 5  # snapshot 4 + journal-4's valid prefix
        drive_commits(srv2.endpoint, 2, worker_id=1)  # journal-5: u=5,6
        assert srv2.updates == 7
        pre = srv2.center()
    finally:
        srv2.close()  # crash #2: journal-4 still carries its torn tail
    srv3 = PSServer(discipline="adag", state_dir=d)
    try:
        assert srv3.updates == 7, (
            "torn interior journal cost the anchored chain after it")
        for a, b in zip(pre, srv3.center()):
            assert a.tobytes() == b.tobytes()
    finally:
        srv3.close()


def test_corrupt_snapshot_falls_back_to_previous_generation(tmp_path):
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d, snapshot_every=3).start()
    try:
        drive_commits(srv.endpoint, 7)
        pre = srv.center()
    finally:
        srv.close()
    snaps = sorted(p for p in os.listdir(d) if p.endswith(".dks"))
    assert len(snaps) == 2  # pruned to the newest two generations
    newest = os.path.join(d, snaps[-1])
    blob = bytearray(open(newest, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(newest, "wb").write(bytes(blob))
    srv2 = PSServer(discipline="adag", state_dir=d)
    try:
        # Digest sidecar rejects the newest; the previous snapshot plus a
        # LONGER journal replay still lands on the same center.
        assert srv2.updates == 7
        for a, b in zip(pre, srv2.center()):
            assert a.tobytes() == b.tobytes()
    finally:
        srv2.close()


def test_snapshot_compaction_bounds_disk_and_commit_log(tmp_path):
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d, snapshot_every=5,
                   commit_log_keep=6).start()
    try:
        drive_commits(srv.endpoint, 23)
        snaps = [p for p in os.listdir(d) if p.endswith(".dks")]
        journals = [p for p in os.listdir(d) if p.endswith(".dkj")]
        assert len(snaps) <= 2, snaps
        assert len(journals) <= 3, journals
        assert len(srv.commit_log) <= 2 * 6
        assert len(srv.commit_log) + srv._log_dropped == srv.commits_total
        assert srv.commits_total == 23  # the drain-time count stays exact
    finally:
        srv.close()


def test_read_journal_exposes_fold_order_evidence(tmp_path):
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d, snapshot_every=4).start()
    try:
        drive_commits(srv.endpoint, 6)
    finally:
        srv.close()
    records = netps_state.read_journal(d)
    assert [int(r["u"]) for r in records] == sorted(
        int(r["u"]) for r in records)
    seen = {(int(r["wid"]), int(r["seq"])) for r in records}
    assert len(seen) == len(records), "a commit was journaled twice"


def test_mesh_server_restart_recovers_bit_identically_and_dedups(tmp_path):
    """The device-resident center is as durable as the host one: every
    mesh fold journals its ``(wid, seq, staleness, epoch)`` tail before
    the ack, so a killed mesh server relaunched on its state dir — with
    or WITHOUT a device mesh — replays to exactly the pre-crash device
    center, and a pre-crash commit's retransmit answers duplicate=True
    over the mesh dialect itself."""
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", transport="mesh", state_dir=d,
                   snapshot_every=4).start()
    try:
        drive_commits(srv.endpoint, 10, transport="mesh")
        pre = srv.center()
        pre_updates, pre_total = srv.updates, srv.commits_total
        pre_seq = dict(srv._last_seq)
    finally:
        srv.close()
    # Recovery does not need the device mesh: a plain numpy replay lands
    # on the same bytes the device folds produced (the exact-mode pin).
    srv2 = PSServer(discipline="adag", state_dir=d)
    try:
        for a, b in zip(pre, srv2.center()):
            assert a.tobytes() == b.tobytes(), \
                "mesh-fold recovery is not bit-identical"
        assert srv2.updates == pre_updates
        assert srv2.commits_total == pre_total
        assert srv2._last_seq == pre_seq
    finally:
        srv2.close()
    # A mesh relaunch adopts the recovered center onto the device, the
    # recovered dedup table answers the resumed worker's retransmit, and
    # new folds keep going through the collective.
    srv3 = PSServer(discipline="adag", transport="mesh",
                    state_dir=d).start()
    try:
        c = PSClient(srv3.endpoint, worker_id=0, transport="mesh", **FAST)
        try:
            _, upd = c.join()
            assert c.active_transport == "mesh"
            assert c._seq == 9  # resumed past the recovered fold history
            before = srv3.center()
            c._seq = 5  # retransmit of an ACKed pre-crash commit
            res = c.commit([np.ones_like(a) for a in before], upd)
            assert res.duplicate and not res.applied
            for a, b in zip(before, srv3.center()):
                assert a.tobytes() == b.tobytes(), \
                    "a duplicate reached the device fold"
            c._seq = 9  # back to the resumed head: a FRESH commit folds
            res = c.commit([np.ones_like(a) for a in before], upd)
            assert res.applied and not res.duplicate
        finally:
            c.close()
        assert srv3.commits_total == pre_total + 1
    finally:
        srv3.close()


def test_sigkill_mid_mesh_run_restart_recovers_bit_identically(tmp_path):
    """The real thing: a mesh PS subprocess is SIGKILLed with folds
    behind it — no drain, no snapshot finalize — and a relaunch on its
    state dir replays the journal tail to the same center a never-killed
    reference server reaches from the identical commit sequence."""
    d = str(tmp_path / "state")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DKTPU_NET_TRANSPORT="mesh")
    proc = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.netps", "--host", "127.0.0.1",
         "--port", "0", "--state-dir", d, "--snapshot-every", "4"],
        stdout=subprocess.PIPE, env=env, text=True)
    try:
        ready = proc.stdout.readline()
        assert ready.startswith("NETPS_READY "), ready
        endpoint = ready.split()[1]
        # Cross-process: the client negotiates TCP (the mesh advert's
        # proc does not match), but the SERVER still folds on device.
        drive_commits(endpoint, 7)
        probe = PSClient(endpoint, worker_id=1, **FAST)
        try:
            assert probe.stats()["fold_backend"] == "mesh", \
                "subprocess PS did not resolve the mesh fold path"
        finally:
            probe.close()
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    ref_srv = PSServer(discipline="adag").start()
    try:
        drive_commits(ref_srv.endpoint, 7)
        ref = ref_srv.center()
    finally:
        ref_srv.close()
    srv2 = PSServer(discipline="adag", state_dir=d).start()
    try:
        assert srv2.commits_total == 7
        for a, b in zip(ref, srv2.center()):
            assert a.tobytes() == b.tobytes(), \
                "SIGKILL recovery diverged from the no-kill reference"
        c = PSClient(srv2.endpoint, worker_id=0, **FAST)
        try:
            _, upd = c.join()
            c._seq = 3  # retransmit of a pre-kill ACKed commit
            res = c.commit([np.ones_like(a) for a in ref], upd)
            assert res.duplicate and not res.applied
        finally:
            c.close()
    finally:
        srv2.close()


# ---------------------------------------------------------------------------
# Epoch fencing
# ---------------------------------------------------------------------------

def test_stale_epoch_commit_is_fenced_never_folded():
    srv = PSServer(discipline="adag").start()
    try:
        c = PSClient(srv.endpoint, worker_id=0, auto_rejoin=False, **FAST)
        try:
            center, upd = c.join(init=leaves())
            assert c.epoch == 0
            with srv._lock:
                srv.epoch = 3  # a promotion happened somewhere
            before = srv.center()
            with pytest.raises(EpochFencedError):
                c.commit([np.ones_like(a) for a in center], upd)
            for a, b in zip(before, srv.center()):
                assert a.tobytes() == b.tobytes(), "stale-epoch commit folded"
        finally:
            c.close()
        # auto_rejoin client: fenced reads like evicted — discard window,
        # re-join, adopt the new epoch, continue.
        c2 = PSClient(srv.endpoint, worker_id=1, **FAST)
        try:
            center, upd = c2.join()
            c2.epoch = 0  # stale lineage
            res = c2.commit([np.zeros_like(a) for a in center], upd)
            assert res.evicted and not res.applied
            assert c2.epoch == 3
            res = c2.commit([np.zeros_like(a) for a in center], upd)
            assert res.applied
        finally:
            c2.close()
    finally:
        srv.close()


def test_fence_op_and_higher_epoch_commit_both_fence_the_zombie():
    srv = PSServer(discipline="adag").start()
    try:
        c = PSClient(srv.endpoint, worker_id=0, auto_rejoin=False, **FAST)
        try:
            center, upd = c.join(init=leaves())
            # The passive fence: a commit carrying a HIGHER epoch is proof
            # of a promotion — the server fences itself on the spot.
            c.epoch = 5
            with pytest.raises(NotPrimaryError):
                c.commit([np.ones_like(a) for a in center], upd)
            assert srv._fenced
        finally:
            c.close()
    finally:
        srv.close()
    # The active fence: the replicate/fence op pair.
    srv2 = PSServer(discipline="adag").start()
    try:
        with socket.create_connection(
                wire.split_endpoint(srv2.endpoint), timeout=2.0) as s:
            wire.send_frame(s, wire.KIND_REQUEST,
                            {"op": "fence", "epoch": 2, "req": 1}, [])
            s.settimeout(2.0)
            _, hdr, _ = wire.read_frame(s)
            assert hdr.get("fenced")
        assert srv2._fenced
        with pytest.raises(NotPrimaryError):
            PSClient(srv2.endpoint, worker_id=1, auto_rejoin=False,
                     **FAST).join(init=leaves())
        # A fence that does NOT outrank the server is refused typed — the
        # fencer is the zombie, not us.
        srv3 = PSServer(discipline="adag", epoch=9).start()
        try:
            with socket.create_connection(
                    wire.split_endpoint(srv3.endpoint), timeout=2.0) as s:
                wire.send_frame(s, wire.KIND_REQUEST,
                                {"op": "fence", "epoch": 2, "req": 1}, [])
                s.settimeout(2.0)
                _, hdr, _ = wire.read_frame(s)
                assert hdr.get("error") == "epoch_fenced"
            assert not srv3._fenced
        finally:
            srv3.close()
    finally:
        srv2.close()


# ---------------------------------------------------------------------------
# Warm standby: replication, promotion, failover
# ---------------------------------------------------------------------------

def _wait(predicate, timeout=6.0, tick=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(tick)
    return False


def test_fenced_ex_primary_with_state_dir_restarts_fenced(tmp_path):
    """The fence is durable: a zombie ex-primary restarted from its state
    dir (e.g. by `Job._revive_ps`, minutes after the failover) must come
    back REFUSING to fold — a fresh client's join carries no epoch, so
    without the persisted marker it would happily join the old lineage
    and reopen the split brain the fence closed."""
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", state_dir=d).start()
    try:
        drive_commits(srv.endpoint, 2)
        with socket.create_connection(
                wire.split_endpoint(srv.endpoint), timeout=2.0) as s:
            wire.send_frame(s, wire.KIND_REQUEST,
                            {"op": "fence", "epoch": 3, "req": 1}, [])
            s.settimeout(2.0)
            _, hdr, _ = wire.read_frame(s)
            assert hdr.get("fenced")
    finally:
        srv.close()
    back = PSServer(discipline="adag", state_dir=d).start()
    try:
        assert back._fenced, "the fence did not survive the restart"
        with pytest.raises(NotPrimaryError):
            PSClient(back.endpoint, worker_id=7, auto_rejoin=False,
                     **FAST).join(init=leaves())
    finally:
        back.close()


def test_standby_replicates_bit_identically_and_serves_nothing():
    srv = PSServer(discipline="adag", lease_s=1.0).start()
    sb = StandbyServer(srv.endpoint, discipline="adag", lease_s=1.0,
                       promote_after=30.0).start()
    try:
        drive_commits(srv.endpoint, 6, compress="int8")
        assert _wait(lambda: sb.updates == srv.updates)
        for a, b in zip(srv.center(), sb.center()):
            assert a.tobytes() == b.tobytes(), "replication drifted"
        assert sb._last_seq == srv._last_seq
        # Pre-promotion it serves nothing: the typed walk signal.
        with pytest.raises(NotPrimaryError):
            PSClient(sb.endpoint, worker_id=9, auto_rejoin=False,
                     **FAST).join(init=leaves())
        assert not sb.promoted
    finally:
        sb.close()
        srv.close()


def test_kill_primary_standby_promotes_client_walks_exactly_once():
    """The in-process kill-the-primary drill: clients on the endpoint
    LIST ride through the primary's death — the standby promotes on lease
    lapse, fences the epoch, the client walks/re-joins/reconciles seq, and
    a pre-crash commit's retransmit dedups on the new primary."""
    srv = PSServer(discipline="adag", lease_s=0.5).start()
    sb = StandbyServer(srv.endpoint, discipline="adag", lease_s=0.5,
                       promote_after=0.6).start()
    endpoints = f"{srv.endpoint},{sb.endpoint}"
    c = PSClient(endpoints, worker_id=0, timeout=0.5, retries=10,
                 backoff=0.02)
    try:
        center, upd = c.join(init=leaves())
        rng = np.random.default_rng(3)
        for _ in range(5):
            delta = [rng.normal(scale=0.1, size=a.shape).astype(np.float32)
                     for a in center]
            c.commit(delta, upd)
            center, upd = c.pull()
        assert _wait(lambda: sb.updates == srv.updates)
        pre_crash = srv.center()
        srv.close()  # the primary dies mid-run
        assert _wait(lambda: sb.promoted)
        assert sb.epoch == 1
        # The standby starts from the primary's exact final center.
        for a, b in zip(pre_crash, sb.center()):
            assert a.tobytes() == b.tobytes()
        # The client's next RPC walks the list, re-joins, adopts epoch 1.
        center, upd = c.pull()
        assert c.epoch == 1 and c.rejoin_count >= 1
        # Retransmit of a pre-crash seq: the REPLICATED dedup table
        # answers duplicate — exactly-once rides through the failover.
        c._seq -= 1
        res = c.commit([np.ones_like(a) for a in center], upd)
        assert res.duplicate and not res.applied
        res = c.commit([np.zeros_like(a) for a in center], upd)
        assert res.applied
        seen = set()
        for wid, seq, _st in sb.commit_log:
            assert (wid, seq) not in seen, f"({wid},{seq}) folded twice"
            seen.add((wid, seq))
    finally:
        c.close()
        sb.close()


def test_promoted_standby_with_state_dir_restarts_fenced_forward(tmp_path):
    srv = PSServer(discipline="adag", lease_s=0.5).start()
    d = str(tmp_path / "sb-state")
    sb = StandbyServer(srv.endpoint, discipline="adag", lease_s=0.5,
                       promote_after=0.6, state_dir=d).start()
    try:
        drive_commits(srv.endpoint, 3)
        assert _wait(lambda: sb.updates == srv.updates)
        srv.close()
        assert _wait(lambda: sb.promoted)
        drive_commits(sb.endpoint, 2, worker_id=1)
        pre, pre_epoch, pre_updates = sb.center(), sb.epoch, sb.updates
    finally:
        sb.close()
    # A promoted-then-killed standby cold-restarts AT its promoted epoch
    # (the epoch.json marker), not the replicated one — the old lineage
    # stays fenced across the restart.
    back = PSServer(discipline="adag", state_dir=d)
    try:
        assert back.epoch == pre_epoch == 1
        assert back.updates == pre_updates
        for a, b in zip(pre, back.center()):
            assert a.tobytes() == b.tobytes()
    finally:
        back.close()


def test_client_endpoint_list_walks_past_dead_endpoints():
    # Reserve a port that is genuinely closed.
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    srv = PSServer(discipline="adag").start()
    try:
        c = PSClient(f"{dead},{srv.endpoint}", worker_id=0,
                     timeout=0.3, retries=4, backoff=0.01)
        try:
            center, upd = c.join(init=leaves())
            assert c.commit([np.zeros_like(a) for a in center], upd).applied
        finally:
            c.close()
        assert wire.split_endpoints(f"{dead},{srv.endpoint}") == [
            wire.split_endpoint(dead), wire.split_endpoint(srv.endpoint)]
        with pytest.raises(ValueError):
            wire.split_endpoints(" , ")
    finally:
        srv.close()


def test_standby_resyncs_when_restarted_primary_lost_its_tail(tmp_path):
    """A cold-restarted primary may have LOST the journal tail the standby
    already replicated (the bounded writer queue died with it) — fold
    indices line up again while the histories differ. The per-incarnation
    lineage token (and the ahead-of-primary snapshot sync) forces the
    standby to discard and re-adopt the PRIMARY's authoritative state
    instead of ever folding a divergent record."""
    d = str(tmp_path / "state")
    srv = PSServer(discipline="adag", lease_s=1.0, state_dir=d,
                   snapshot_every=0).start()
    port = int(srv.endpoint.rsplit(":", 1)[1])
    sb = StandbyServer(srv.endpoint, discipline="adag", lease_s=1.0,
                       promote_after=30.0).start()
    try:
        drive_commits(srv.endpoint, 5)
        assert _wait(lambda: sb.updates == srv.updates == 5)
        srv.close()
        # Doctor the dir: drop the last 2 journal records — the writer
        # tail that "died with the process".
        journals = sorted(p for p in os.listdir(d) if p.endswith(".dkj"))
        path = os.path.join(d, journals[-1])
        nrec, clean = netps_state._scan_journal(path)
        assert clean and nrec == 5
        keep = bytearray()
        with open(path, "rb") as f:
            for _ in range(3):
                prefix = f.read(wire.PREFIX_SIZE)
                _k, _c, length = wire.parse_prefix(prefix)
                keep += prefix + f.read(length)
        open(path, "wb").write(bytes(keep))
        # Cold restart on the same port: recovers at u=3, standby sits at 5.
        srv2 = PSServer(discipline="adag", lease_s=1.0, state_dir=d,
                        host="127.0.0.1", port=port).start()
        try:
            assert srv2.updates == 3
            # The standby must CONVERGE DOWN to the primary's state.
            assert _wait(lambda: sb.updates == 3 and sb._center is not None)
            for a, b in zip(srv2.center(), sb.center()):
                assert a.tobytes() == b.tobytes(), (
                    "standby diverged from the restarted primary")
            # The evidence accounting survives the lineage discard too.
            assert sb._log_dropped >= 0
            assert (len(sb.commit_log) + sb._log_dropped
                    == sb.commits_total)
            # And keep tracking the new lineage.
            drive_commits(srv2.endpoint, 2, worker_id=1)
            assert _wait(lambda: sb.updates == srv2.updates == 5)
            for a, b in zip(srv2.center(), sb.center()):
                assert a.tobytes() == b.tobytes()
        finally:
            srv2.close()
    finally:
        sb.close()


def test_failover_patience_bridges_promotion_beyond_retry_budget():
    """With standbys configured the retry budget alone must not decide
    survival: a client with retries=1 (whose strict budget is far shorter
    than the promotion window) keeps walking the endpoint list until the
    standby promotes, because multi-endpoint RPCs get the failover
    patience window (~2x lease) on top of the attempt budget."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    sb = StandbyServer(dead, discipline="adag", lease_s=1.0,
                       promote_after=1.0).start()
    try:
        t0 = time.monotonic()
        c = PSClient(f"{dead},{sb.endpoint}", worker_id=0,
                     timeout=0.3, retries=1, backoff=0.02)
        try:
            center, upd = c.join(init=leaves())
            took = time.monotonic() - t0
            assert sb.promoted
            assert c.commit([np.zeros_like(a) for a in center],
                            upd).applied
            # Sanity: this took longer than the strict 2-attempt budget
            # (~0.7 s) could ever have survived.
            assert took > 0.9, took
        finally:
            c.close()
    finally:
        sb.close()


def test_revive_ps_skips_clean_exit_restarts_crash(monkeypatch):
    from distkeras_tpu.job_deployment import Job, Punchcard

    job = Job(Punchcard(job_name="j", script="s.py", hosts=["localhost"],
                        ps={"state_dir": "/tmp/x"}))

    class Fake:
        def __init__(self, rc):
            self.returncode = rc

        def poll(self):
            return self.returncode

    spawned = []
    monkeypatch.setattr(job, "_spawn_cmd",
                        lambda host, cmd: spawned.append(cmd) or Fake(None))
    # Clean drain (rc 0): deliberate stop, never revived.
    job._ps_proc = Fake(0)
    job._revive_ps(max_restarts=3)
    assert job.ps_restarts == 0 and not spawned
    # Crash (rc -9): revived, bounded by the budget.
    job._ps_proc = Fake(-9)
    job._revive_ps(max_restarts=3)
    assert job.ps_restarts == 1 and len(spawned) == 1
    assert "--state-dir" in spawned[0]


# ---------------------------------------------------------------------------
# PS-side chaos kinds + CLI signal contract
# ---------------------------------------------------------------------------

def test_ps_crash_and_hang_fault_kinds_parse_and_hang_fires():
    plan = FaultPlan.parse_net("ps_crash@9;ps_hang@1:0.3;seed=2")
    assert plan.faults[("ps_crash", 9)] is None
    assert plan.faults[("ps_hang", 1)] == 0.3
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse_net("ps_reboot@3")
    from distkeras_tpu.resilience import faults as _faults

    srv = PSServer(discipline="adag").start()
    _faults.set_net_plan(plan)
    try:
        c = PSClient(srv.endpoint, worker_id=0, timeout=5.0, retries=0,
                     backoff=0.01)
        try:
            center, upd = c.join(init=leaves())
            c.commit([np.zeros_like(a) for a in center], upd)  # commit 0
            t0 = time.monotonic()
            c.commit([np.zeros_like(a) for a in center], upd)  # commit 1
            assert time.monotonic() - t0 >= 0.3, (
                "ps_hang did not wedge the server")
        finally:
            c.close()
    finally:
        _faults.set_net_plan(None)
        _faults.reset()
        srv.close()


@pytest.mark.slow
def test_cli_second_sigterm_force_exits_nonzero(tmp_path):
    """The __main__ signal contract: the FIRST SIGTERM prints
    NETPS_DRAINING at signal time and drains; a SECOND mid-drain
    force-exits nonzero instead of being swallowed — here the drain is
    genuinely wedged by a half-sent frame holding a handler thread."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.netps", "--host", "127.0.0.1",
         "--port", "0"],
        stdout=subprocess.PIPE, env=env, text=True)
    try:
        ready = proc.stdout.readline()
        assert ready.startswith("NETPS_READY "), ready
        endpoint = ready.split()[1]
        # Wedge a handler mid-frame: prefix promises a body that never
        # arrives, so close() blocks joining that thread (~30 s).
        s = socket.create_connection(wire.split_endpoint(endpoint))
        frame = wire.encode_frame(wire.KIND_REQUEST, {"op": "pull"}, [])
        s.sendall(frame[:wire.PREFIX_SIZE])
        proc.send_signal(signal.SIGTERM)
        line = proc.stdout.readline()
        assert line.strip() == "NETPS_DRAINING", line
        assert proc.poll() is None  # draining, not dead, not hung-silent
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=10)
        assert rc == 70, f"second SIGTERM did not force-exit: rc={rc}"
        s.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_punchcard_renders_the_ps_pair_and_endpoint_list():
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(job_name="j", script="train.py",
                   hosts=["10.0.0.1", "10.0.0.2"],
                   ps={"discipline": "adag", "port": 7171, "lease": 5.0,
                       "state_dir": "/var/dktpu/ps",
                       "standby_host": "10.0.0.2"})
    # The standby port is pool-allocated (the old primary+1 rule collided
    # across jobs) and pinned into the card: every render agrees.
    ep = pc.ps_endpoint()
    sb_port = pc.ps["standby_port"]
    assert ep == f"10.0.0.1:7171,10.0.0.2:{sb_port}"
    assert pc.ps_endpoint() == ep
    job = Job(pc)
    ps_cmd = job.render_ps_command()
    assert "--state-dir /var/dktpu/ps" in ps_cmd
    sb_cmd = job.render_standby_command()
    assert "--standby 10.0.0.1:7171" in sb_cmd
    assert f"--port {sb_port}" in sb_cmd
    assert "--state-dir /var/dktpu/ps.standby" in sb_cmd
    for cmd in job.launch(dry_run=True):
        assert f"DKTPU_PS_ENDPOINT={ep}" in cmd
    # No standby: single endpoint, no standby line — PR 4 behavior intact.
    bare = Job(Punchcard(job_name="j", script="s.py", hosts=["h"],
                         ps={"port": 7077}))
    assert bare.punchcard.ps_endpoint() == "h:7077"
    assert bare.render_standby_command() is None
