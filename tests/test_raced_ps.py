"""Race-validation of the async mapping (VERDICT r2 weak #6 / next #6).

The deterministic window-K folds claim "same aggregate semantics" as the
reference's raced socket parameter server. Here the SAME model trains on the
SAME data both ways — through ``racelab``'s genuinely-raced threaded PS (lock
+ numpy fold, commits in OS-scheduled order) and through the deterministic
engines — across >=3 seeds, and final accuracies must agree within noise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu import ADAG, DataFrame, DynSGD
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.racelab import run_raced

W = 4          # workers (threads / chips)
K = 4          # communication window
B = 16         # batch size
EPOCHS = 3
LR = 0.1
N, DIM, C = 1024, 4, 3


def _blobs(seed):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(C, DIM))
    y = rng.integers(0, C, size=N)
    x = (centers[y] + rng.normal(scale=0.5, size=(N, DIM))).astype(np.float32)
    return x, y.astype(np.int32)


def _model(seed):
    return Model.build(MLP(hidden=(16,), num_outputs=C),
                       np.zeros((1, DIM), np.float32), seed=seed)


def _accuracy(apply_fn, x, y):
    return float((np.asarray(apply_fn(x)).argmax(-1) == y).mean())


def _raced_accuracy(seed, discipline, overlap_first_round=False):
    """Train via the raced threaded PS on worker-contiguous shards."""
    x, y = _blobs(seed)
    model = _model(seed)
    leaves, treedef = jax.tree.flatten(
        jax.tree.map(np.asarray, model.params))

    loss_of = lambda p, xb, yb: -jnp.mean(
        jax.nn.log_softmax(model.module.apply({"params": p}, xb))[
            jnp.arange(xb.shape[0]), yb])

    @jax.jit
    def window_steps(flat, xb, yb):
        def step(i, flat):
            p = jax.tree.unflatten(treedef, flat)
            g = jax.grad(loss_of)(p, xb[i], yb[i])
            g = jax.tree.flatten(g)[0]
            return [a - LR * b for a, b in zip(flat, g)]
        return jax.lax.fori_loop(0, K, step, flat)

    def local_steps(flat, batch):
        xb, yb = batch
        return window_steps([jnp.asarray(a) for a in flat],
                            jnp.asarray(xb), jnp.asarray(yb))

    # Worker-contiguous shards; per-round [K, B] batches, like the engines.
    rpw = N // W
    rounds = (rpw // (K * B)) * EPOCHS
    batches = []
    for w in range(W):
        xs, ys = x[w * rpw:(w + 1) * rpw], y[w * rpw:(w + 1) * rpw]
        per = []
        rng = np.random.default_rng(seed * 97 + w)
        for _ in range(rounds):
            idx = rng.permutation(rpw)[:K * B].reshape(K, B)
            per.append((xs[idx], ys[idx]))
        batches.append(per)

    center, ps = run_raced(center=leaves, local_steps=local_steps,
                           worker_batches=batches, window=K,
                           discipline=discipline,
                           overlap_first_round=overlap_first_round)
    params = jax.tree.unflatten(treedef, [jnp.asarray(a) for a in center])
    acc = _accuracy(lambda xb: model.module.apply({"params": params}, xb), x, y)
    return acc, ps


def _window_accuracy(seed, trainer_cls):
    x, y = _blobs(seed)
    df = DataFrame({"features": x, "label": y})
    t = trainer_cls(_model(seed), loss="sparse_categorical_crossentropy",
                    num_workers=W, batch_size=B, num_epoch=EPOCHS,
                    learning_rate=LR, communication_window=K)
    trained = t.train(df, shuffle=True)
    return _accuracy(trained.predict, x, y)


@pytest.mark.slow
@pytest.mark.parametrize("discipline,trainer_cls", [
    ("adag", ADAG),
    ("dynsgd", DynSGD),
], ids=["adag", "dynsgd"])
def test_raced_ps_matches_window_folds(discipline, trainer_cls):
    """Accuracy parity within noise across 3 seeds — the mapping's claim."""
    raced, windowed = [], []
    for seed in (0, 1, 2):
        acc_r, _ = _raced_accuracy(seed, discipline)
        acc_w = _window_accuracy(seed, trainer_cls)
        raced.append(acc_r)
        windowed.append(acc_w)
    raced, windowed = np.asarray(raced), np.asarray(windowed)
    # Both converge on every seed...
    assert (raced > 0.85).all(), f"raced failed to converge: {raced}"
    assert (windowed > 0.85).all(), f"windowed failed to converge: {windowed}"
    # ...and mean accuracies agree within noise.
    assert abs(raced.mean() - windowed.mean()) < 0.05, (raced, windowed)


@pytest.mark.slow
def test_raced_dynsgd_staleness_is_real():
    """The harness produces genuine nonzero staleness: the first-round
    barrier guarantees the opening W commits race (deterministic even on a
    scheduler that would serialize free-running threads), so the realized
    distribution provably covers staleness >= 1."""
    _, ps = _raced_accuracy(0, "dynsgd", overlap_first_round=True)
    log = np.asarray(ps.commit_log)
    assert len(log) == (N // W // (K * B)) * EPOCHS * W
    assert (log >= 0).all()
    assert log.max() >= 1, "no staleness observed; race did not happen"
    # All W first-round pulls happened at counter 0 (barrier), so the last
    # first-round committer saw at least W-1 commits land since its pull —
    # regardless of how later rounds interleave into the commit order.
    assert log[0] == 0  # very first commit can never be stale
    assert log.max() >= W - 1, log[: 2 * W]
