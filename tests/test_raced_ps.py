"""Race-validation of the async mapping (VERDICT r2 weak #6 / next #6).

The deterministic window-K folds claim "same aggregate semantics" as the
reference's raced socket parameter server. Here the SAME model trains on the
SAME data both ways — through ``racelab``'s genuinely-raced threaded PS (lock
+ numpy fold, commits in OS-scheduled order) and through the deterministic
engines — across >=3 seeds, and final accuracies must agree within noise.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu import ADAG, AEASGD, DataFrame, DynSGD, EAMSGD
from distkeras_tpu.models import Model
from distkeras_tpu.models.mlp import MLP
from distkeras_tpu.racelab import run_raced

W = 4          # workers (threads / chips)
K = 4          # communication window
B = 16         # batch size
EPOCHS = 3
LR = 0.1
ALPHA = 0.05   # elastic rate (rho = ALPHA / LR for the trainer surface)
MOMENTUM = 0.5  # EAMSGD local momentum (raced twin must match the trainer's)
N, DIM, C = 1024, 4, 3


def _blobs(seed):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=4.0, size=(C, DIM))
    y = rng.integers(0, C, size=N)
    x = (centers[y] + rng.normal(scale=0.5, size=(N, DIM))).astype(np.float32)
    return x, y.astype(np.int32)


def _model(seed):
    return Model.build(MLP(hidden=(16,), num_outputs=C),
                       np.zeros((1, DIM), np.float32), seed=seed)


def _accuracy(apply_fn, x, y):
    return float((np.asarray(apply_fn(x)).argmax(-1) == y).mean())


def _raced_accuracy(seed, discipline, overlap_first_round=False):
    """Train via the raced threaded PS on worker-contiguous shards."""
    x, y = _blobs(seed)
    model = _model(seed)
    leaves, treedef = jax.tree.flatten(
        jax.tree.map(np.asarray, model.params))

    loss_of = lambda p, xb, yb: -jnp.mean(
        jax.nn.log_softmax(model.module.apply({"params": p}, xb))[
            jnp.arange(xb.shape[0]), yb])

    @jax.jit
    def window_steps(flat, xb, yb):
        def step(i, flat):
            p = jax.tree.unflatten(treedef, flat)
            g = jax.grad(loss_of)(p, xb[i], yb[i])
            g = jax.tree.flatten(g)[0]
            return [a - LR * b for a, b in zip(flat, g)]
        return jax.lax.fori_loop(0, K, step, flat)

    @jax.jit
    def window_steps_momentum(flat, vel, xb, yb):
        # optax.sgd(momentum) trace form: t = g + mu*t_prev; p -= LR*t —
        # the EAMSGD trainer's local optimizer, reproduced for the raced twin.
        def step(i, carry):
            flat, vel = carry
            p = jax.tree.unflatten(treedef, flat)
            g = jax.tree.flatten(jax.grad(loss_of)(p, xb[i], yb[i]))[0]
            vel = [gg + MOMENTUM * v for gg, v in zip(g, vel)]
            return ([a - LR * v for a, v in zip(flat, vel)], vel)
        return jax.lax.fori_loop(0, K, step, (flat, vel))

    def local_steps(flat, batch):
        xb, yb = batch
        return window_steps([jnp.asarray(a) for a in flat],
                            jnp.asarray(xb), jnp.asarray(yb))

    def local_steps_momentum(flat, batch, aux):
        xb, yb = batch
        if aux is None:
            aux = [jnp.zeros_like(jnp.asarray(a)) for a in flat]
        flat, aux = window_steps_momentum(
            [jnp.asarray(a) for a in flat], aux,
            jnp.asarray(xb), jnp.asarray(yb))
        return flat, aux

    # Worker-contiguous shards; per-round [K, B] batches, like the engines.
    rpw = N // W
    rounds = (rpw // (K * B)) * EPOCHS
    batches = []
    for w in range(W):
        xs, ys = x[w * rpw:(w + 1) * rpw], y[w * rpw:(w + 1) * rpw]
        per = []
        rng = np.random.default_rng(seed * 97 + w)
        for _ in range(rounds):
            idx = rng.permutation(rpw)[:K * B].reshape(K, B)
            per.append((xs[idx], ys[idx]))
        batches.append(per)

    center, ps = run_raced(
        center=leaves,
        local_steps=(local_steps_momentum if discipline == "eamsgd"
                     else local_steps),
        worker_batches=batches, window=K, discipline=discipline,
        overlap_first_round=overlap_first_round, alpha=ALPHA)
    params = jax.tree.unflatten(treedef, [jnp.asarray(a) for a in center])
    acc = _accuracy(lambda xb: model.module.apply({"params": params}, xb), x, y)
    return acc, ps


def _window_accuracy(seed, make_trainer):
    x, y = _blobs(seed)
    df = DataFrame({"features": x, "label": y})
    t = make_trainer(_model(seed))
    trained = t.train(df, shuffle=True)
    return _accuracy(trained.predict, x, y)


_COMMON = dict(loss="sparse_categorical_crossentropy", num_workers=W,
               batch_size=B, num_epoch=EPOCHS, learning_rate=LR,
               communication_window=K)

_TRAINERS = {
    "adag": lambda m: ADAG(m, **_COMMON),
    "dynsgd": lambda m: DynSGD(m, **_COMMON),
    # Elastic: trainer alpha = rho * learning_rate must equal the raced
    # harness's ALPHA; EAMSGD's local momentum likewise mirrored.
    "aeasgd": lambda m: AEASGD(m, rho=ALPHA / LR, **_COMMON),
    "eamsgd": lambda m: EAMSGD(m, rho=ALPHA / LR, momentum=MOMENTUM,
                               **_COMMON),
}


@pytest.mark.slow
@pytest.mark.parametrize("discipline",
                         ["adag", "dynsgd", "aeasgd", "eamsgd"])
def test_raced_ps_matches_window_folds(discipline):
    """Accuracy parity within noise across 3 seeds — the mapping's claim.
    The elastic ids close VERDICT r4 weak #3: AEASGD (the north-star
    discipline) and EAMSGD validated against the genuinely-raced threaded
    server, not just deterministic re-executions."""
    raced, windowed = [], []
    for seed in (0, 1, 2):
        acc_r, _ = _raced_accuracy(seed, discipline)
        acc_w = _window_accuracy(seed, _TRAINERS[discipline])
        raced.append(acc_r)
        windowed.append(acc_w)
    raced, windowed = np.asarray(raced), np.asarray(windowed)
    # Both converge on every seed...
    assert (raced > 0.85).all(), f"raced failed to converge: {raced}"
    assert (windowed > 0.85).all(), f"windowed failed to converge: {windowed}"
    # ...and mean accuracies agree within noise.
    assert abs(raced.mean() - windowed.mean()) < 0.05, (raced, windowed)


@pytest.mark.slow
@pytest.mark.parametrize("discipline", ["adag", "aeasgd"])
def test_window_folds_with_faults_match_raced(discipline, tmp_path,
                                              monkeypatch):
    """Accuracy parity survives injected faults (ISSUE 2 fault matrix): the
    windowed run takes a NaN-poisoned round (skipped by the on-device
    guard), a feeder stall, and a mid-run crash (resumed by the Supervisor
    from checkpoint) — and must still land within noise of the raced PS."""
    from distkeras_tpu import Supervisor, resilience

    raced, windowed = [], []
    for seed in (0, 1):
        acc_r, _ = _raced_accuracy(seed, discipline)
        resilience.reset()
        monkeypatch.setenv("DKTPU_FAULTS", "nan@1;stall@2:0.1;crash@3")
        x, y = _blobs(seed)
        df = DataFrame({"features": x, "label": y})
        t = _TRAINERS[discipline](_model(seed))
        t.checkpoint_dir = str(tmp_path / f"ck-{discipline}-{seed}")
        t.checkpoint_every = 1
        with pytest.warns(UserWarning):  # the supervisor's retry notice
            trained = Supervisor(t, max_retries=2, backoff_s=0).train(
                df, shuffle=True)
        resilience.reset()
        acc_w = _accuracy(trained.predict, x, y)
        raced.append(acc_r)
        windowed.append(acc_w)
    raced, windowed = np.asarray(raced), np.asarray(windowed)
    assert (raced > 0.85).all(), f"raced failed to converge: {raced}"
    assert (windowed > 0.85).all(), (
        f"faulted windowed run failed to converge: {windowed}")
    assert abs(raced.mean() - windowed.mean()) < 0.05, (raced, windowed)


@pytest.mark.slow
def test_raced_elastic_staleness_is_real():
    """The elastic race genuinely interleaves: with the first-round barrier,
    some AEASGD commit lands against a center that moved since its pull
    (staleness >= 1) — the interleaving the window-K fold serializes."""
    _, ps = _raced_accuracy(0, "aeasgd", overlap_first_round=True)
    log = np.asarray(ps.commit_log)
    assert len(log) == (N // W // (K * B)) * EPOCHS * W
    assert log[0] == 0 and log.max() >= W - 1, log[: 2 * W]


@pytest.mark.slow
def test_raced_dynsgd_staleness_is_real():
    """The harness produces genuine nonzero staleness: the first-round
    barrier guarantees the opening W commits race (deterministic even on a
    scheduler that would serialize free-running threads), so the realized
    distribution provably covers staleness >= 1."""
    _, ps = _raced_accuracy(0, "dynsgd", overlap_first_round=True)
    log = np.asarray(ps.commit_log)
    assert len(log) == (N // W // (K * B)) * EPOCHS * W
    assert (log >= 0).all()
    assert log.max() >= 1, "no staleness observed; race did not happen"
    # All W first-round pulls happened at counter 0 (barrier), so the last
    # first-round committer saw at least W-1 commits land since its pull —
    # regardless of how later rounds interleave into the commit order.
    assert log[0] == 0  # very first commit can never be stale
    assert log.max() >= W - 1, log[: 2 * W]


def test_raced_ps_close_makes_workers_exit_cleanly():
    """A closed server is typed-fatal, not silently absorbing: worker
    threads blocked in a commit/pull loop exit with `ServerClosedError`
    instead of folding into a dead center forever (the leaked-thread
    failure mode `close()` exists to kill)."""
    from distkeras_tpu.netps.errors import ServerClosedError
    from distkeras_tpu.racelab import RacedParameterServer

    rng = np.random.default_rng(0)
    ps = RacedParameterServer([rng.normal(size=(4, 3)).astype(np.float32)],
                              discipline="downpour")
    started = threading.Barrier(3)
    errors: list = []
    done: list = []

    def worker():
        try:
            started.wait()
            while True:  # the forever-committing leaked worker
                pulled, counter = ps.pull()
                ps.commit([0.01 * np.sign(a) for a in pulled], counter)
        except ServerClosedError:
            done.append(True)  # the typed exit path — clean
        except Exception as e:  # pragma: no cover - would fail the test
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    started.wait()
    time.sleep(0.05)  # let commits genuinely race first
    ps.close()
    for t in threads:
        t.join(timeout=10.0)
    assert not any(t.is_alive() for t in threads), "worker thread leaked"
    assert not errors, errors
    assert len(done) == 2  # both exited through the typed error
    assert len(ps.commit_log) > 0  # the race really ran before the close
    with pytest.raises(ServerClosedError):
        ps.commit([np.zeros((4, 3), np.float32)], 0)
    with pytest.raises(ServerClosedError):
        ps.pull()
    ps.center()  # the final center stays readable after close


def test_raced_ps_lock_order_witnessed():
    """The raced PS under the runtime lock-order witness: no inversion is
    observed across genuinely racing commit threads, and every witnessed
    edge involving racelab's lock exists in dk-check's static DK201 graph
    (i.e. the static model is sound for the code the paper's architecture
    actually races). Numpy-only local step: the witness targets the lock
    protocol, not the math."""
    import os

    import distkeras_tpu
    from distkeras_tpu.analysis import core, witness
    from distkeras_tpu.analysis.rules_concurrency import build_lock_graph

    rng = np.random.default_rng(0)
    center = [rng.normal(size=(4, 3)).astype(np.float32)]
    batches = [[(None, None)] * 6 for _ in range(4)]

    def local_steps(flat, batch):
        return [a - 0.01 * np.sign(a) for a in flat]

    with witness() as w:
        final, ps = run_raced(
            center=center, local_steps=local_steps,
            worker_batches=batches, window=K, discipline="dynsgd",
            overlap_first_round=True)
    w.assert_no_inversions()
    assert len(ps.commit_log) == 4 * 6
    pkg = os.path.dirname(os.path.abspath(distkeras_tpu.__file__))
    modules, _ = core.parse_modules([pkg])
    static_edges, _, _ = build_lock_graph(modules)
    raced = {e for e in w.edges() if "racelab" in e[0] or "racelab" in e[1]}
    assert raced <= static_edges, raced - static_edges
