"""Native C++ data-plane tests: build, gather/scale correctness, prefetcher."""

import numpy as np
import pytest

from distkeras_tpu.data.native_loader import gather_rows, get_lib, scale_f32
from distkeras_tpu.data.prefetch import RoundFeeder


def test_native_lib_builds():
    lib = get_lib()
    assert lib is not None, "g++ toolchain present in this image; build must succeed"


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(1000, 17)).astype(np.float32)
    idx = rng.integers(0, 1000, size=(4, 3, 5))
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_multidim_rows_and_int_dtype():
    rng = np.random.default_rng(1)
    src = rng.integers(0, 100, size=(50, 4, 4)).astype(np.int32)
    idx = rng.integers(0, 50, size=(7,))
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_out_of_range_raises():
    if get_lib() is None:
        pytest.skip("native lib unavailable")
    src = np.zeros((10, 3), np.float32)
    with pytest.raises(IndexError):
        gather_rows(src, np.array([0, 99]))


def test_scale_f32_matches_numpy():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(333, 7)).astype(np.float32)
    np.testing.assert_allclose(scale_f32(x, 0.5, 2.0), (x - 0.5) * 2.0, rtol=1e-6)


def test_scale_f32_bias_matches_numpy():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(257, 3)).astype(np.float32)
    got = scale_f32(x, 0.25, 3.0, bias=-1.5)
    np.testing.assert_allclose(got, (x - 0.25) * 3.0 + (-1.5), rtol=1e-6)


def test_scale_f32_bias_exact_at_range_endpoints():
    # The endpoints of a min-max rescale must be hit EXACTLY: (i_min - i_min) *
    # scale + o_min == o_min in float arithmetic regardless of scale magnitude.
    # (This is the catastrophic-cancellation case the separate bias exists for.)
    x = np.array([2.0, 2.0 + 1e-6], np.float32)
    scale = 1.0 / float(x[1] - x[0])  # huge scale from a degenerate range
    out = scale_f32(x, float(x[0]), scale, bias=5.0)
    assert out[0] == np.float32(5.0)


def test_native_abi_version_pinned_to_source():
    # The ctypes declarations are only valid for the exact C signatures they
    # were written against. dk_abi_version() pins them: this test fails if
    # loader.cc's version constant and the Python _ABI_VERSION ever diverge
    # (i.e. someone changed a signature on one side only).
    import re

    from distkeras_tpu.data import native_loader

    src = open(native_loader._SRC).read()
    m = re.search(r"int\s+dk_abi_version\(\)\s*\{\s*return\s+(\d+)\s*;", src)
    assert m, "loader.cc must define dk_abi_version()"
    assert int(m.group(1)) == native_loader._ABI_VERSION, (
        "native ABI version mismatch between loader.cc and native_loader.py — "
        "a signature changed on one side only"
    )
    lib = get_lib()
    if lib is not None:
        assert lib.dk_abi_version() == native_loader._ABI_VERSION


def test_min_max_semantics_through_native_path():
    # End-to-end guard for the data plane: MinMaxTransformer output must map
    # [i_min, i_max] -> [o_min, o_max] with exact endpoints via the native path.
    from distkeras_tpu.data import DataFrame
    from distkeras_tpu.data.transformers import MinMaxTransformer

    x = np.array([[0.0], [255.0], [51.0]], np.float32)
    df = DataFrame({"features": x})
    out = MinMaxTransformer(o_min=-1.0, o_max=1.0).transform(df)["features_normalized"]
    assert out[0, 0] == np.float32(-1.0)
    assert out[1, 0] == np.float32(1.0)
    np.testing.assert_allclose(out[2, 0], -1.0 + 2.0 * 51.0 / 255.0, rtol=1e-6)


def test_batch_plan_uses_gather(tmp_path):
    from distkeras_tpu.data import DataFrame, make_batches

    rng = np.random.default_rng(3)
    df = DataFrame({"features": rng.normal(size=(96, 5)).astype(np.float32),
                    "label": rng.integers(0, 3, size=96).astype(np.int32)})
    plan = make_batches(df, "features", "label", batch_size=4, num_workers=2,
                        window=3, shuffle=True, seed=7)
    fx, fy = plan.round(0)
    idx = plan.index[0]
    np.testing.assert_array_equal(fx, df["features"][idx])
    np.testing.assert_array_equal(fy, df["label"][idx])


def test_round_feeder_order_and_completion():
    staged = []
    feeder = RoundFeeder(5, lambda r: (staged.append(r), r * 10)[1], start_round=1)
    seen = list(feeder)
    assert seen == [(1, 10), (2, 20), (3, 30), (4, 40)]
    assert staged == [1, 2, 3, 4]


def test_round_feeder_propagates_errors():
    def stage(r):
        if r == 2:
            raise RuntimeError("boom")
        return r

    feeder = RoundFeeder(5, stage)
    with pytest.raises(RuntimeError, match="boom"):
        list(feeder)


def test_round_feeder_abandonment_stops_thread():
    """A consumer that dies mid-loop (OOM, tunnel flake) must not leave the
    feeder thread blocked on Queue.put holding staged batches forever."""
    import time
    import weakref

    class Batch:  # stand-in for a staged device array
        pass

    alive = []

    def stage(r):
        b = Batch()
        alive.append(weakref.ref(b))
        return b

    feeder = RoundFeeder(1000, stage, depth=2)

    def consume_then_die():
        for r, batch in feeder:
            if r == 3:
                raise RuntimeError("simulated mid-training failure")

    with pytest.raises(RuntimeError, match="mid-training"):
        consume_then_die()
    deadline = time.time() + 5
    while feeder._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not feeder._thread.is_alive(), "feeder thread leaked"
    # every staged batch the consumer never took has been dropped
    import gc

    gc.collect()
    assert all(ref() is None for ref in alive)


def test_round_feeder_close_idempotent_before_and_after_use():
    feeder = RoundFeeder(3, lambda r: r)
    assert list(feeder) == [(0, 0), (1, 1), (2, 2)]
    feeder.close()
    feeder.close()
