"""Native C++ data-plane tests: build, gather/scale correctness, prefetcher."""

import numpy as np
import pytest

from distkeras_tpu.data.native_loader import gather_rows, get_lib, scale_f32
from distkeras_tpu.data.prefetch import RoundFeeder


def test_native_lib_builds():
    lib = get_lib()
    assert lib is not None, "g++ toolchain present in this image; build must succeed"


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(1000, 17)).astype(np.float32)
    idx = rng.integers(0, 1000, size=(4, 3, 5))
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_multidim_rows_and_int_dtype():
    rng = np.random.default_rng(1)
    src = rng.integers(0, 100, size=(50, 4, 4)).astype(np.int32)
    idx = rng.integers(0, 50, size=(7,))
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_out_of_range_raises():
    if get_lib() is None:
        pytest.skip("native lib unavailable")
    src = np.zeros((10, 3), np.float32)
    with pytest.raises(IndexError):
        gather_rows(src, np.array([0, 99]))


def test_scale_f32_matches_numpy():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(333, 7)).astype(np.float32)
    np.testing.assert_allclose(scale_f32(x, 0.5, 2.0), (x - 0.5) * 2.0, rtol=1e-6)


def test_batch_plan_uses_gather(tmp_path):
    from distkeras_tpu.data import DataFrame, make_batches

    rng = np.random.default_rng(3)
    df = DataFrame({"features": rng.normal(size=(96, 5)).astype(np.float32),
                    "label": rng.integers(0, 3, size=96).astype(np.int32)})
    plan = make_batches(df, "features", "label", batch_size=4, num_workers=2,
                        window=3, shuffle=True, seed=7)
    fx, fy = plan.round(0)
    idx = plan.index[0]
    np.testing.assert_array_equal(fx, df["features"][idx])
    np.testing.assert_array_equal(fy, df["label"][idx])


def test_round_feeder_order_and_completion():
    staged = []
    feeder = RoundFeeder(5, lambda r: (staged.append(r), r * 10)[1], start_round=1)
    seen = list(feeder)
    assert seen == [(1, 10), (2, 20), (3, 30), (4, 40)]
    assert staged == [1, 2, 3, 4]


def test_round_feeder_propagates_errors():
    def stage(r):
        if r == 2:
            raise RuntimeError("boom")
        return r

    feeder = RoundFeeder(5, stage)
    with pytest.raises(RuntimeError, match="boom"):
        list(feeder)
