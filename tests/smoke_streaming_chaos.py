"""CI streaming-chaos smoke (not a pytest module — run directly).

Two legs closing the ISSUE's online loop — ingest -> train -> checkpoint
-> hot-swap -> serve — under chaos:

**Leg 1 (fleet colocation):** a :class:`StreamingTraining` tenant and a
batch :class:`ElasticTraining` tenant share ONE worker pool under the
:class:`FleetScheduler`, while a :class:`ModelRegistry` polls the
streaming tenant's checkpoint directory and hot-swaps candidates through
a :meth:`DriftWatch.regression_gate` quality gate. Chaos: a ``feed_gap``
holds the feed silent mid-run, ``drift@40`` rotates every label from
record 40 on (a real concept shift), and the producer's live connection
is severed mid-stream (``kill_connections`` — reconnect-and-resume).
Asserted: both tenants finish; the drift sentinel PAGES and then CLEARS
(recovery timed); the source reconnected; exactly-once on the in-process
commit log AND the offset journal; the served model answers the
*drifted* world (post-drift weights actually reached serving); the
event-to-served-weight freshness was measured; and the telemetry
report's Streaming section carries all of it.

**Leg 2 (SIGKILL durability):** a single-worker streaming trainer runs
as a child process against a durable ``python -m distkeras_tpu.netps``
subprocess (state dir + fold journal). The child's fault plan SIGKILLs
it mid-stream (``kill@8`` — no cleanup, no atexit). The restarted child
resumes from the offset journal + newest intact checkpoint and must
re-deliver ZERO offsets the journal already held as committed, finish
the stream, and leave a PS journal holding exactly one fold per record
— exactly-once proven against the only evidence a SIGKILL leaves: the
two on-disk journals.

    python tests/smoke_streaming_chaos.py
"""

import os
import sys

# Runs from a checkout without installation: sys.path[0] is tests/, so the
# repo root must be appended (an installed distkeras_tpu still wins).
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distkeras_tpu.models import Model  # noqa: E402
from distkeras_tpu.models.mlp import MLP  # noqa: E402
from distkeras_tpu.ops.losses import get_loss  # noqa: E402
from distkeras_tpu.ops.optimizers import get_optimizer  # noqa: E402
from distkeras_tpu.streaming import (  # noqa: E402
    DriftWatch,
    FileTailSource,
    OffsetJournal,
    SocketSource,
    StreamingTraining,
    StreamProducer,
    WindowedEval,
    replayed_offsets,
)

#: leg-1 stream schedule: 40 in-distribution records, then the injected
#: shift rotates every label from record DRIFT_AT on. Pinned, not random.
TOTAL_1 = 120
DRIFT_AT = 40
FAULTS_1 = "feed_gap@12:0.4;drift@%d;seed=3" % DRIFT_AT

#: leg-2: the child is SIGKILLed claiming record KILL_AT of TOTAL_2.
TOTAL_2 = 20
KILL_AT = 8
CLASSES = 3


def _build_model(seed=0):
    return Model.build(MLP(hidden=(16,), num_outputs=CLASSES),
                       jnp.zeros((1, 4), jnp.float32), seed=seed)


def _blob_batch(rng, centers, k, b):
    y = rng.integers(0, CLASSES, size=(k, b))
    x = (centers[y] + rng.normal(scale=0.5, size=(k, b, 4))).astype(
        np.float32)
    return x, y.astype(np.int32)


def _ce_loss(logits, y):
    logits = np.asarray(logits, np.float64)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    return float(-logp[np.arange(len(y)), y].mean())


# ---------------------------------------------------------------------------
# Leg 1: fleet-colocated streaming tenant + serving loop under chaos
# ---------------------------------------------------------------------------

def leg_fleet(base_dir) -> dict:
    import threading
    import time

    from distkeras_tpu import DataFrame, checkpoint as ckpt_mod, telemetry
    from distkeras_tpu.data.batching import make_batches
    from distkeras_tpu.fleet import (
        DONE,
        ElasticTraining,
        FleetJob,
        FleetScheduler,
    )
    from distkeras_tpu.resilience import faults
    from distkeras_tpu.resilience.faults import FaultPlan
    from distkeras_tpu.serving import ModelRegistry
    from distkeras_tpu.telemetry.report import build_report

    ckpt_dir = os.path.join(base_dir, "leg1-ckpt")
    journal_path = os.path.join(base_dir, "leg1-offsets.json")
    rng = np.random.default_rng(7)
    centers = rng.normal(scale=4.0, size=(CLASSES, 4))

    # Held-out eval set in the DRIFTED world: the regression gate scores
    # every hot-swap candidate on it, and the final serving check demands
    # the live model answers it — i.e. post-drift weights reached serving.
    xh, yh = _blob_batch(rng, centers, 1, 64)
    xh, yh = xh[0], yh[0]
    yh_drift = ((yh + 1) % CLASSES).astype(np.int32)

    faults.set_plan(FaultPlan.parse(FAULTS_1))
    prod = StreamProducer()

    watch = DriftWatch(window=WindowedEval(fast=8, slow=40))
    rt_stream = StreamingTraining(
        model=_build_model(seed=0), tx=get_optimizer("sgd", 0.1),
        loss_fn=get_loss("sparse_categorical_crossentropy"),
        source=SocketSource(prod.endpoint, drift_classes=CLASSES),
        num_workers=2, discipline="adag", seed=0,
        journal=journal_path, checkpoint_dir=ckpt_dir, checkpoint_every=10,
        drift_watch=watch, max_pending=8)

    def produce():
        # Trickle, throttled against training progress: event timestamps
        # track wall time (so freshness-at-swap means something) and the
        # feed is still live mid-run when the connection is severed.
        prng = np.random.default_rng(11)
        t0 = time.monotonic()
        for i in range(TOTAL_1):
            while (i - rt_stream.progress() > 24
                   and time.monotonic() - t0 < 300):
                time.sleep(0.02)
            xs, ys = _blob_batch(prng, centers, 2, 16)
            prod.feed(xs, ys)
        prod.end()

    threading.Thread(target=produce, daemon=True).start()

    # The colocated batch tenant: same pool, ordinary finite claim queue.
    df = DataFrame({"features": (centers[rng.integers(0, CLASSES, 256)]
                                 + rng.normal(scale=0.5, size=(256, 4))
                                 ).astype(np.float32),
                    "label": rng.integers(0, CLASSES, 256).astype(np.int32)})
    df = DataFrame({"features": df["features"], "label": df["label"]})
    plan = make_batches(df, "features", "label", batch_size=16,
                        num_workers=2, window=4, num_epoch=1, shuffle=True,
                        seed=5)
    rt_batch = ElasticTraining(
        model=_build_model(seed=1), tx=get_optimizer("sgd", 0.1),
        loss_fn=get_loss("sparse_categorical_crossentropy"),
        plan=plan, discipline="adag", seed=1)

    serve_model = _build_model(seed=0)
    gate = watch.regression_gate(
        lambda cand: _ce_loss(cand.infer((xh,)), yh_drift),
        regress_floor=0.5)
    registry = ModelRegistry(serve_model, (64,), directory=ckpt_dir,
                             poll_s=0.15, quality_gate=gate)
    registry.start()

    sched = FleetScheduler(capacity=3, tick_s=0.02)
    job_s = sched.submit(FleetJob("stream", "acme", rt_stream,
                                  priority=0, min_gang=1, max_workers=2))
    job_b = sched.submit(FleetJob("batch", "bidco", rt_batch,
                                  priority=0, min_gang=1, max_workers=2))
    sched.start()
    try:
        # Mid-stream source kill, after training is demonstrably flowing
        # and before the drift record lands.
        deadline = time.monotonic() + 240
        while rt_stream.progress() < 20:
            assert time.monotonic() < deadline, "streaming warmup stalled"
            time.sleep(0.05)
        prod.kill_connections()
        assert sched.wait(timeout=420), (
            f"fleet did not finish: {sched.stats()}")
    finally:
        sched.close()
        registry.close()
        prod.close()
        faults.reset()

    for job in (job_s, job_b):
        assert job.state == DONE, f"{job.job_id} ended {job.state}"
    assert not rt_stream.errors, rt_stream.errors[:3]

    # The chaos bit: gap + drift injected, connection survived severing.
    reg = telemetry.get()
    events = reg.events()
    kinds = {e["kind"] for e in events}
    fired = {e.get("fault") for e in events if e["kind"] == "fault_injected"}
    assert "feed_gap" in fired, "the feed-gap drill never fired"
    assert "drift" in fired, "the drift drill never fired"
    assert reg.counter("stream.source_reconnects").value >= 1, (
        "the severed feed connection never reconnected")

    # Drift sentinel paged, checkpoint-on-drift anchored, then CLEARED
    # with a measured recovery time (the model relearned the rotation).
    assert "stream_drift_detected" in kinds, "drift never paged"
    assert "stream_drift_recovered" in kinds, "the drift page never cleared"
    assert watch.last_recovery_s is not None and watch.last_recovery_s > 0
    assert not watch.paging, "still paging after the stream drained"

    # Exactly-once, both ledgers: every record folded into the PS center
    # exactly once, and the journal's committed set is the full stream.
    pairs = [(w, s) for w, s, _ in rt_stream.server.commit_log]
    assert len(pairs) == TOTAL_1, (
        f"{len(pairs)} folds for {TOTAL_1} records")
    assert len(set(pairs)) == len(pairs), "a (wid, seq) folded twice"
    journal = OffsetJournal(journal_path)
    assert journal.load(), "offset journal unreadable after the run"
    assert journal.committed_offsets_upto(TOTAL_1) == set(range(TOTAL_1))

    # The loop actually closed: the registry swapped a post-drift
    # checkpoint in (through the regression gate) and the live model
    # answers the drifted world.
    registry.poll_once()  # pick up the final checkpoint
    bm, version = registry.current()
    assert version > -1, "no checkpoint ever reached serving"
    meta = ckpt_mod.read_meta(ckpt_dir, version) or {}
    assert meta.get("items", 0) > DRIFT_AT, (
        f"served step {version} predates the drift: {meta}")
    assert meta.get("event_ts") is not None, "meta lost the event anchor"
    acc = float((np.asarray(bm.infer((xh,))).argmax(-1)
                 == yh_drift).mean())
    assert acc > 0.8, f"served model never adapted to the drift: {acc}"

    # Freshness was measured at swap, and the report attributes the run.
    jsonl = os.path.join(base_dir, "leg1-run.jsonl")
    telemetry.write_jsonl(reg, jsonl)
    strm = build_report(jsonl).get("streaming")
    assert strm, "report has no Streaming section"
    assert strm.get("items_committed", 0) >= TOTAL_1, strm
    assert strm.get("drift_events", 0) >= 1, strm
    assert strm.get("source_reconnects", 0) >= 1, strm
    assert strm.get("freshness_count", 0) >= 1, (
        f"no freshness measurement reached the report: {strm}")
    assert "recovery_s" in strm, strm
    assert "candidate_loss" in strm, "the quality gate never scored"
    fresh = strm.get("freshness_max_s")
    assert fresh is not None and fresh < 60.0, (
        f"event-to-served-weight freshness implausible: {fresh}")
    return {"acc": acc, "version": version, "recovery_s":
            round(watch.last_recovery_s, 3),
            "freshness_max_s": fresh}


# ---------------------------------------------------------------------------
# Leg 2: SIGKILL the trainer; resume must replay nothing, lose nothing
# ---------------------------------------------------------------------------

def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_stream(path):
    from distkeras_tpu.streaming import StreamFileWriter

    rng = np.random.default_rng(21)
    centers = rng.normal(scale=4.0, size=(CLASSES, 4))
    w = StreamFileWriter(path)
    for _ in range(TOTAL_2):
        xs, ys = _blob_batch(rng, centers, 1, 8)
        w.append(xs, ys)
    w.end()


def child_main() -> int:
    """One streaming trainer attempt against the durable external PS —
    run twice by the parent: attempt 1 dies to ``kill@8`` (journaled to
    DKTPU_FAULTS_STATE so the restart is not re-poisoned), attempt 2
    resumes and drains. Prints the delivered offsets so the parent can
    assert the zero-replay set."""
    base = os.environ["STREAM_SMOKE_DIR"]
    src = FileTailSource(os.path.join(base, "stream.bin"), poll_s=0.02,
                         drift_classes=CLASSES)
    delivered = []

    class Recorder:
        drift_from = None

        def read(self, start_index, skip):
            for rec in src.read(start_index, skip):
                delivered.append(rec.index)
                yield rec

        def close(self):
            src.close()

    rt = StreamingTraining(
        model=_build_model(seed=0), tx=get_optimizer("sgd", 0.1),
        loss_fn=get_loss("sparse_categorical_crossentropy"),
        source=Recorder(), num_workers=1, discipline="adag", seed=0,
        endpoint=os.environ["STREAM_SMOKE_ENDPOINT"],
        journal=os.path.join(base, "offsets.json"),
        checkpoint_dir=os.path.join(base, "ckpt"), checkpoint_every=5,
        resume=True)
    rt.ensure_started()
    rt.worker_main(0, lambda: True)
    rt.close()
    if rt.errors:
        raise rt.errors[0]
    print("STREAM_CHILD_DELIVERED " + ",".join(map(str, delivered)))
    print("STREAM_CHILD_OK committed=%d" % rt.journal.items_committed)
    return 0


def leg_sigkill(base_dir) -> dict:
    import signal
    import subprocess

    from distkeras_tpu.netps import state as netps_state

    state_dir = os.path.join(base_dir, "leg2-ps-state")
    work_dir = os.path.join(base_dir, "leg2")
    os.makedirs(state_dir, exist_ok=True)
    os.makedirs(work_dir, exist_ok=True)
    _write_stream(os.path.join(work_dir, "stream.bin"))

    # The durable PS: a real subprocess with a state dir + fold journal —
    # the only exactly-once evidence that survives the trainer's SIGKILL.
    port = _free_port()
    drop = {"DKTPU_FAULTS", "DKTPU_FAULTS_STATE"}
    ps_env = {k: v for k, v in os.environ.items() if k not in drop}
    ps_env["JAX_PLATFORMS"] = "cpu"
    ps = subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.netps", "--host", "127.0.0.1",
         "--port", str(port), "--discipline", "adag",
         # No compaction: the journal must retain EVERY fold of the run,
         # it is the exactly-once evidence this leg exists to check.
         "--state-dir", state_dir, "--snapshot-every", "100000"],
        env=ps_env, stdout=subprocess.PIPE, text=True)
    endpoint = None
    for line in ps.stdout:
        if line.startswith("NETPS_READY"):
            endpoint = line.split()[1]
            break
    assert endpoint, "netps subprocess never came up"

    child_env = dict(os.environ)
    child_env.update({
        "JAX_PLATFORMS": "cpu",
        "STREAM_SMOKE_ROLE": "child",
        "STREAM_SMOKE_DIR": work_dir,
        "STREAM_SMOKE_ENDPOINT": endpoint,
        "DKTPU_FAULTS": f"kill@{KILL_AT}",
        "DKTPU_FAULTS_STATE": os.path.join(work_dir, "faults.state"),
    })
    me = os.path.abspath(__file__)
    try:
        # Attempt 1: dies to the unmaskable mid-stream kill.
        r1 = subprocess.run([sys.executable, me], env=child_env,
                            capture_output=True, text=True, timeout=240)
        assert r1.returncode == -signal.SIGKILL, (
            f"attempt 1 should die to SIGKILL, got {r1.returncode}:\n"
            f"{r1.stdout}\n{r1.stderr}")

        # What the journal provably held at the moment of death.
        journal = OffsetJournal(os.path.join(work_dir, "offsets.json"))
        assert journal.load(), "no journal survived the SIGKILL"
        before = journal.committed_offsets_upto(TOTAL_2)
        assert before == set(range(KILL_AT)), (
            f"journal at death should hold 0..{KILL_AT - 1}: {before}")

        # Attempt 2: resume. Must drain the stream without re-delivering
        # a single already-committed offset.
        r2 = subprocess.run([sys.executable, me], env=child_env,
                            capture_output=True, text=True, timeout=240)
        assert r2.returncode == 0, (
            f"resumed attempt failed rc={r2.returncode}:\n"
            f"{r2.stdout}\n{r2.stderr}")
        delivered2 = []
        for line in r2.stdout.splitlines():
            if line.startswith("STREAM_CHILD_DELIVERED"):
                body = line.split(" ", 1)[1] if " " in line else ""
                delivered2 = [int(t) for t in body.split(",") if t]
        replay = replayed_offsets(before, delivered2)
        assert replay == set(), (
            f"resume replayed committed offsets: {sorted(replay)}")
        assert f"committed={TOTAL_2}" in r2.stdout, r2.stdout

        # Zero lost: the journal now holds the whole stream...
        journal = OffsetJournal(os.path.join(work_dir, "offsets.json"))
        assert journal.load()
        after = journal.committed_offsets_upto(TOTAL_2)
        assert after == set(range(TOTAL_2)), f"records lost: {after}"
    finally:
        ps.terminate()
        try:
            ps.wait(timeout=10)
        except subprocess.TimeoutExpired:
            ps.kill()
            ps.wait(timeout=10)

    # ...and the PS's on-disk journal shows exactly one fold per record,
    # across both attempts of the killed-and-resumed worker.
    records = netps_state.read_journal(state_dir)
    pairs = [(int(r["wid"]), int(r["seq"])) for r in records]
    assert len(pairs) == TOTAL_2, (
        f"{len(pairs)} folds journaled for {TOTAL_2} records")
    assert len(set(pairs)) == len(pairs), "a (wid, seq) folded twice"
    return {"delivered_after_resume": len(delivered2),
            "folds": len(pairs)}


def main() -> int:
    import shutil

    base_dir = os.environ.get("DKTPU_STREAM_SMOKE_DIR",
                              "/tmp/dktpu-stream-smoke")
    shutil.rmtree(base_dir, ignore_errors=True)
    os.makedirs(base_dir, exist_ok=True)

    r1 = leg_fleet(base_dir)
    r2 = leg_sigkill(base_dir)
    print("streaming chaos run: "
          f"served_acc={r1['acc']:.4f} served_step={r1['version']}"
          f" recovery_s={r1['recovery_s']}"
          f" freshness_max_s={r1['freshness_max_s']}"
          f" resume_delivered={r2['delivered_after_resume']}"
          f" ps_folds={r2['folds']}")
    return 0


if __name__ == "__main__":
    if os.environ.get("STREAM_SMOKE_ROLE") == "child":
        raise SystemExit(child_main())
    raise SystemExit(main())
