"""Keras-3 ingestion tests: the reference's Keras-model workflow end-to-end."""

import numpy as np
import pytest

import jax.numpy as jnp

keras = pytest.importorskip("keras")

from distkeras_tpu import DataFrame, DOWNPOUR, SingleTrainer  # noqa: E402
from distkeras_tpu.models.keras_adapter import from_keras  # noqa: E402
from distkeras_tpu.runtime.serialization import (  # noqa: E402
    deserialize_model,
    serialize_model,
)


def _keras_mlp(d=4, c=3):
    return keras.Sequential([
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(c),
    ])


def _df(n=512, d=4, c=3):
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    return DataFrame({"features": x, "label": y.astype(np.int32)})


def test_from_keras_wraps_and_predicts():
    model = from_keras(_keras_mlp(), sample_input=np.zeros((1, 4), np.float32))
    out = model.predict(jnp.ones((2, 4)))
    assert out.shape == (2, 3)
    assert model.num_params == 4 * 16 + 16 + 16 * 3 + 3


def test_keras_model_trains_with_single_trainer():
    df = _df()
    model = from_keras(_keras_mlp(), sample_input=np.zeros((1, 4), np.float32))
    t = SingleTrainer(model, worker_optimizer="adam",
                      loss="sparse_categorical_crossentropy", batch_size=32,
                      num_epoch=3, learning_rate=0.01)
    trained = t.train(df, shuffle=True)
    logits = np.asarray(trained.predict(jnp.asarray(df["features"])))
    assert (logits.argmax(-1) == df["label"]).mean() > 0.9


def test_keras_model_trains_distributed():
    df = _df()
    model = from_keras(_keras_mlp(), sample_input=np.zeros((1, 4), np.float32))
    t = DOWNPOUR(model, worker_optimizer="sgd",
                 loss="sparse_categorical_crossentropy", num_workers=4,
                 batch_size=16, communication_window=4, num_epoch=3,
                 learning_rate=0.05)
    trained = t.train(df, shuffle=True)
    logits = np.asarray(trained.predict(jnp.asarray(df["features"])))
    assert (logits.argmax(-1) == df["label"]).mean() > 0.85


def test_keras_model_serialization_roundtrip():
    model = from_keras(_keras_mlp(), sample_input=np.zeros((1, 4), np.float32))
    restored = deserialize_model(serialize_model(model))
    x = jnp.ones((2, 4))
    np.testing.assert_allclose(np.asarray(model.predict(x)),
                               np.asarray(restored.predict(x)), rtol=1e-6)


def _keras_bn_mlp(d=4, c=3):
    return keras.Sequential([
        keras.layers.Dense(16, activation="relu"),
        keras.layers.BatchNormalization(),
        keras.layers.Dense(c),
    ])


def test_batchnorm_model_rejected():
    m = keras.Sequential([
        keras.layers.Dense(8, activation="relu"),
        keras.layers.BatchNormalization(),
        keras.layers.Dense(2),
    ])
    with pytest.raises(ValueError, match="batchnorm='freeze'"):
        from_keras(m, sample_input=np.zeros((4, 4), np.float32))


def test_batchnorm_freeze_ingests_and_trains():
    """batchnorm='freeze': BN runs in inference mode (moving stats frozen) —
    the model becomes pure, ingests cleanly, and still trains to quality."""
    df = _df()
    model = from_keras(_keras_bn_mlp(), sample_input=np.zeros((1, 4), np.float32),
                       batchnorm="freeze")
    # frozen BN contributes no trainable params: gamma/beta moved out
    assert model.num_params == 4 * 16 + 16 + 16 * 3 + 3
    t = SingleTrainer(model, worker_optimizer="adam",
                      loss="sparse_categorical_crossentropy", batch_size=32,
                      num_epoch=3, learning_rate=0.01)
    trained = t.train(df, shuffle=True)
    logits = np.asarray(trained.predict(jnp.asarray(df["features"])))
    assert (logits.argmax(-1) == df["label"]).mean() > 0.9
