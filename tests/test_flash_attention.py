"""FlashAttention kernel tests (Pallas interpreter on the CPU mesh).

Forward and backward are checked against dense causal attention — values AND
gradients. The kernels use bf16 MXU operands with f32 accumulation (the same
numerics XLA's dense lowering uses on TPU), so tolerances are at the bf16 noise
floor rather than f32 exactness.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models import small_transformer_lm
from distkeras_tpu.models.transformer import TransformerLM
from distkeras_tpu.ops.pallas import flash_attention

B, L, H, D = 2, 64, 2, 16
BLOCK = 16


def dense_causal(q, k, v):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    mask = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _inputs(seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
                 for _ in range(3))


def test_flash_forward_matches_dense():
    q, k, v = _inputs()
    out = flash_attention(q, k, v, block_size=BLOCK, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_causal(q, k, v)),
                               atol=5e-2)


def test_flash_backward_matches_dense():
    q, k, v = _inputs(1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_size=BLOCK, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.35, rtol=0.02,
                                   err_msg=f"d{name} mismatch")


def test_asymmetric_blocks_match_dense():
    """block_k > block_q (the TPU-tuned shape) and the multi-chunk loop
    phases (full/masked) must be value-identical to dense."""
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 128, 1, 16)), jnp.float32)
               for _ in range(3))
    ref = dense_causal(q, k, v)
    for bq, bk in [(16, 64), (16, 128), (32, 64)]:
        out = flash_attention(q, k, v, block_size=bq, block_k=bk,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-2, err_msg=f"bq={bq} bk={bk}")
    # grads through the asymmetric path too
    gf = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, block_size=16, block_k=64, interpret=True) ** 2))(q)
    gd = jax.grad(lambda q: jnp.sum(dense_causal(q, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               atol=0.35, rtol=0.02)


def test_default_block_k_covers_all_blockable_lengths():
    """Every L the q-block accepts must get a valid default k-chunk —
    L=1280-style lengths (multiple of 128, not of 1024) must not regress."""
    rng = np.random.default_rng(4)
    for L in (80, 96, 160):  # multiples of 16, not all of 8*16
        q, k, v = (jnp.asarray(rng.normal(size=(1, L, 1, 16)), jnp.float32)
                   for _ in range(3))
        out = flash_attention(q, k, v, block_size=16, interpret=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(dense_causal(q, k, v)),
                                   atol=5e-2, err_msg=f"L={L}")


def test_transformer_flash_impl_matches_dense():
    tokens = jnp.asarray(np.random.default_rng(2).integers(0, 64, size=(2, 32)),
                         jnp.int32)
    dense_model = small_transformer_lm(vocab_size=64, num_layers=1, d_model=32,
                                       num_heads=2, d_ff=64, max_seq_len=32,
                                       seq_len=32)
    arch = dense_model.module.get_config()
    flash_module = TransformerLM(**{**arch, "attn_impl": "flash"})
    out_dense = dense_model.predict(tokens)
    out_flash = flash_module.apply({"params": dense_model.params}, tokens, train=False)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_dense),
                               atol=5e-2)
