"""Multi-host DCN bootstrap, exercised for real (VERDICT r1 weak #9).

Spawns 2 local processes through the actual ``job_deployment.Job`` launcher
(``hosts=['localhost','localhost']`` takes the non-ssh Popen path), each with 2
virtual CPU devices; they self-assemble via ``jax.distributed.initialize`` over
loopback and run one synchronous-DP training job across the 4-device global
mesh — the same code path a v5e pod uses over DCN (SURVEY.md §5
distributed-backend row; BASELINE config #5's pod story).
"""

import json
import os
import socket
import subprocess

import pytest

from distkeras_tpu.job_deployment import Job, Punchcard

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_sync_dp_over_loopback(tmp_path):
    hosts = ["localhost", "localhost"]
    card = Punchcard(
        job_name="pytest-2proc-syncdp",
        script=_WORKER,
        hosts=hosts,
        coordinator_port=_free_port(),
        env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "KERAS_BACKEND": "jax",
            "DK_OUT": str(tmp_path),
            "PYTHONPATH": _REPO,
        },
    )
    job = Job(card)

    # The rendered commands are exactly what a pod launch would ssh out.
    cmds = job.render_commands()
    assert len(cmds) == 2
    assert "JAX_PROCESS_ID=0" in cmds[0] and "JAX_PROCESS_ID=1" in cmds[1]
    assert f"JAX_NUM_PROCESSES={len(hosts)}" in cmds[0]

    job.launch(dry_run=False)
    try:
        rcs = job.wait(timeout=600)
    except subprocess.TimeoutExpired:
        job.kill()
        pytest.fail("2-process job did not finish within timeout")
    assert rcs == [0, 0], f"worker processes failed: rcs={rcs}"

    results = []
    for i in range(2):
        with open(tmp_path / f"proc{i}.json") as f:
            results.append(json.load(f))

    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
        assert r["accuracy"] > 0.85, f"proc {r['process']} failed to converge: {r}"

    # The replicated state is one logical program: both processes must observe
    # the identical loss history (any divergence = a broken collective).
    assert results[0]["history"] == pytest.approx(results[1]["history"], rel=1e-6)
    assert results[0]["history"][-1] < results[0]["history"][0]
