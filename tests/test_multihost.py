"""Multi-host DCN bootstrap, exercised for real (VERDICT r1 weak #9).

Spawns 2 local processes through the actual ``job_deployment.Job`` launcher
(``hosts=['localhost','localhost']`` takes the non-ssh Popen path), each with 2
virtual CPU devices; they self-assemble via ``jax.distributed.initialize`` over
loopback and run one synchronous-DP training job across the 4-device global
mesh — the same code path a v5e pod uses over DCN (SURVEY.md §5
distributed-backend row; BASELINE config #5's pod story).
"""

import json
import os
import socket

import pytest

from distkeras_tpu.job_deployment import Job, Punchcard

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_job(out_dir, extra_env, timeout, job_name="pytest-multihost",
                devices_per_proc=2, num_hosts=2):
    """Shared N-process launch: build the Punchcard, launch through Job, and
    supervise to completion (teardown on first failure or timeout)."""
    card = Punchcard(
        job_name=job_name,
        script=_WORKER,
        hosts=["localhost"] * num_hosts,
        coordinator_port=_free_port(),
        env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_proc}",
            "KERAS_BACKEND": "jax",
            "DK_OUT": str(out_dir),
            "PYTHONPATH": _REPO,
            **extra_env,
        },
    )
    job = Job(card)
    job.launch(dry_run=False)
    return job, job.supervise(timeout=timeout)


def _read_results(out_dir, n=2):
    results = []
    for i in range(n):
        with open(out_dir / f"proc{i}.json") as f:
            results.append(json.load(f))
    return results


@pytest.mark.slow
def test_two_process_sync_dp_over_loopback(tmp_path):
    job, rcs = _launch_job(tmp_path, {}, timeout=600,
                           job_name="pytest-2proc-syncdp")
    # The rendered commands are exactly what a pod launch would ssh out.
    cmds = job.render_commands()
    assert len(cmds) == 2
    assert "JAX_PROCESS_ID=0" in cmds[0] and "JAX_PROCESS_ID=1" in cmds[1]
    assert "JAX_NUM_PROCESSES=2" in cmds[0]
    assert rcs == [0, 0], f"worker processes failed: rcs={rcs}"

    results = _read_results(tmp_path)

    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
        assert r["accuracy"] > 0.85, f"proc {r['process']} failed to converge: {r}"

    # The replicated state is one logical program: both processes must observe
    # the identical loss history (any divergence = a broken collective).
    assert results[0]["history"] == pytest.approx(results[1]["history"], rel=1e-6)
    assert results[0]["history"][-1] < results[0]["history"][0]


@pytest.mark.slow
def test_two_process_async_discipline(tmp_path):
    """ADAG (async center-variable fold) across 2 processes: the stacked
    worker state and the fold's psum must behave identically over DCN."""
    _job, rcs = _launch_job(tmp_path, {"DK_TRAINER": "adag"}, timeout=600,
                            job_name="pytest-2proc-adag")
    assert rcs == [0, 0], f"worker processes failed: rcs={rcs}"
    results = _read_results(tmp_path)
    for r in results:
        assert r["accuracy"] > 0.85, r
    assert results[0]["history"] == pytest.approx(results[1]["history"], rel=1e-6)


@pytest.mark.slow
def test_two_process_async_tensor_parallel(tmp_path):
    """AsyncTPEngine on a multi-process mesh (ADVICE r4 medium): ADAG with
    W=2 workers, each a tp=2 submesh, over 2 processes x 2 devices. The
    per-worker [W] loss leaves the engine replicated, so both processes
    collect the identical history (a data-sharded loss would crash
    device_get on a non-fully-addressable array)."""
    _job, rcs = _launch_job(tmp_path, {"DK_TRAINER": "adag_tp"}, timeout=600,
                            job_name="pytest-2proc-adagtp")
    assert rcs == [0, 0], f"worker processes failed: rcs={rcs}"
    results = _read_results(tmp_path)
    for r in results:
        assert r["accuracy"] > 0.85, r
    assert results[0]["history"] == pytest.approx(results[1]["history"],
                                                  rel=1e-6)
    assert results[0]["history"][-1] < results[0]["history"][0]


@pytest.mark.slow
def test_four_process_sync_and_async(tmp_path):
    """W>2 process topologies (VERDICT r2 missing #4): 4 processes x 2
    virtual devices = an 8-worker global mesh. Exercises put_global's
    per-leaf callback indexing and the fold collectives where 2-process
    symmetry can hide index bugs. Both the per-step-pmean and the async
    center-fold paths must produce identical replicated histories on every
    process."""
    sync_dir = tmp_path / "sync"
    sync_dir.mkdir()
    _job, rcs = _launch_job(sync_dir, {}, timeout=900,
                            job_name="pytest-4proc-sync", num_hosts=4)
    assert rcs == [0, 0, 0, 0], f"sync workers failed: rcs={rcs}"
    results = _read_results(sync_dir, n=4)
    for r in results:
        assert r["process_count"] == 4
        assert r["global_devices"] == 8
        assert r["local_devices"] == 2
        assert r["accuracy"] > 0.85, r
    for r in results[1:]:
        assert r["history"] == pytest.approx(results[0]["history"], rel=1e-6)

    adag_dir = tmp_path / "adag"
    adag_dir.mkdir()
    _job, rcs = _launch_job(adag_dir, {"DK_TRAINER": "adag"}, timeout=900,
                            job_name="pytest-4proc-adag", num_hosts=4)
    assert rcs == [0, 0, 0, 0], f"adag workers failed: rcs={rcs}"
    results = _read_results(adag_dir, n=4)
    for r in results:
        assert r["accuracy"] > 0.85, r
    for r in results[1:]:
        assert r["history"] == pytest.approx(results[0]["history"], rel=1e-6)


@pytest.mark.slow
def test_elastic_resume_across_process_counts(tmp_path):
    """Pod resize across PROCESS counts: a 4-process (W=8) run dies after a
    checkpoint; a 2-process (W=4) relaunch resumes elastically — rejoining
    workers pull the restored center and data progress carries over. This is
    where elastic resume's round-index arithmetic and the every-process meta
    write earn their keep."""
    ckpt = tmp_path / "ckpt"

    # 4-proc ADAG run (window 4, batch 16: W=8 -> 512 samples/round, 4
    # rounds over 2 epochs), checkpoint every round, hard-killed during
    # round 1 — so exactly round 0's checkpoint lands.
    fault_dir = tmp_path / "fault"
    fault_dir.mkdir()
    _job, rcs = _launch_job(
        fault_dir,
        {"DK_TRAINER": "adag", "DK_CKPT_DIR": str(ckpt),
         "DK_CKPT_EVERY": "1", "DK_DIE_AT_ROUND": "1"},
        timeout=900, job_name="pytest-elastic-4to2", num_hosts=4)
    assert 17 in rcs, f"fault was not injected: rcs={rcs}"
    assert (ckpt / "meta").exists(), "no meta sidecar written"

    # Resume on HALF the topology (2 processes, W=4).
    rec_dir = tmp_path / "rec"
    rec_dir.mkdir()
    _job, rcs = _launch_job(
        rec_dir,
        {"DK_TRAINER": "adag", "DK_CKPT_DIR": str(ckpt),
         "DK_CKPT_EVERY": "1", "DK_RESUME": "1"},
        timeout=900, job_name="pytest-elastic-rec", num_hosts=2)
    assert rcs == [0, 0], f"elastic recovery failed: rcs={rcs}"
    results = _read_results(rec_dir, n=2)
    for r in results:
        assert r["global_devices"] == 4  # resized topology
        assert r["accuracy"] > 0.85, r
        # Data progress carried over: ADAG window 4, batch 16 -> W=4 runs 8
        # rounds total (256 samples/round over 2x1024); the W=8 checkpoint
        # covered one 512-sample round, so resume starts at round 2 and
        # trains exactly the remaining 6 — no replay, no skip.
        assert len(r["history"]) == 6, r["history"]
    assert results[0]["history"] == pytest.approx(results[1]["history"],
                                                  rel=1e-6)


@pytest.mark.slow
def test_two_process_disjoint_shards(tmp_path):
    """The out-of-core data plane across hosts (VERDICT r2 missing #1): each
    process holds ONLY the shard files its own workers consume (hard-linked
    into a private dir — reads outside it raise FileNotFoundError), and the
    run must match a replicated-store run exactly. This is the Spark
    partitioned-executor-data capability, re-designed: no host ever stages
    another host's rows."""
    import numpy as np

    from distkeras_tpu.data.shards import write_shards

    # Same deterministic blobs the worker script generates (seed 0).
    rng = np.random.default_rng(0)
    n, d, c = 1024, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    store = tmp_path / "store"
    # 256 rows/shard on a 4-worker mesh: shard w == worker w's partition.
    write_shards(store, {"features": x, "label": y.astype(np.int32)},
                 rows_per_shard=256)

    # Reference: both processes see the full store.
    full_dir = tmp_path / "full"
    full_dir.mkdir()
    _job, rcs = _launch_job(full_dir, {"DK_SHARD_DIR": str(store)},
                            timeout=600, job_name="pytest-shards-full")
    assert rcs == [0, 0], f"full-store run failed: rcs={rcs}"
    full = _read_results(full_dir)

    # Disjoint: each process hard-links only its own workers' shards.
    disj_dir = tmp_path / "disj"
    disj_dir.mkdir()
    _job, rcs = _launch_job(
        disj_dir, {"DK_SHARD_DIR": str(store), "DK_DISJOINT": "1"},
        timeout=600, job_name="pytest-shards-disjoint")
    assert rcs == [0, 0], f"disjoint-shard run failed: rcs={rcs}"
    disj = _read_results(disj_dir)

    # Each private dir holds exactly its workers' 2 shards (x2 columns) + manifest.
    for i in range(2):
        priv = disj_dir / f"shards_proc{i}"
        files = sorted(p.name for p in priv.iterdir())
        assert len(files) == 5, files  # manifest + 2 shards x 2 columns
    assert (disj_dir / "shards_proc0" / "shard-00000.features.npy").exists()
    assert (disj_dir / "shards_proc1" / "shard-00002.features.npy").exists()

    for r in full + disj:
        assert r["accuracy"] > 0.85, r
    # Disjoint-host staging must be semantically invisible.
    assert disj[0]["history"] == pytest.approx(full[0]["history"], rel=1e-6)
    assert disj[0]["history"] == pytest.approx(disj[1]["history"], rel=1e-6)


@pytest.mark.slow
def test_disjoint_shards_with_multiplexed_workers(tmp_path):
    """Sharded data plane x worker multiplexing: 8 logical workers on a
    4-chip 2-process mesh (m=2), hosts holding only their own workers'
    shard files. Locality must follow LOGICAL worker ids — chip c owns
    workers [2c, 2c+2) — or processes would stage other partitions' rows."""
    import numpy as np

    from distkeras_tpu.data.shards import write_shards

    rng = np.random.default_rng(0)
    n, d, c = 1024, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    store = tmp_path / "store"
    # 128 rows/shard on 8 logical workers: shard w == worker w's partition.
    write_shards(store, {"features": x, "label": y.astype(np.int32)},
                 rows_per_shard=128)

    env = {"DK_SHARD_DIR": str(store), "DK_NUM_WORKERS": "8"}
    full_dir = tmp_path / "full"
    full_dir.mkdir()
    _job, rcs = _launch_job(full_dir, env, timeout=600,
                            job_name="pytest-mux-full")
    assert rcs == [0, 0], f"full-store run failed: rcs={rcs}"
    full = _read_results(full_dir)

    disj_dir = tmp_path / "disj"
    disj_dir.mkdir()
    _job, rcs = _launch_job(disj_dir, {**env, "DK_DISJOINT": "1"},
                            timeout=600, job_name="pytest-mux-disjoint")
    assert rcs == [0, 0], f"disjoint multiplexed run failed: rcs={rcs}"
    disj = _read_results(disj_dir)

    # Each process links its 4 logical workers' shards (x2 columns) + manifest.
    for i in range(2):
        files = sorted(p.name for p in (disj_dir / f"shards_proc{i}").iterdir())
        assert len(files) == 9, files
    assert (disj_dir / "shards_proc1" / "shard-00004.features.npy").exists()

    for r in full + disj:
        assert r["accuracy"] > 0.85, r
    assert disj[0]["history"] == pytest.approx(full[0]["history"], rel=1e-6)


@pytest.mark.slow
def test_parallel_trainer_disjoint_shards(tmp_path):
    """Model-parallel x multi-host x out-of-core, composed: ParallelTrainer
    on a 2-process dp(2) x tp(2) mesh trains from per-host disjoint shard
    files — rows staged per dp RANK (model-parallel peers share rows), and
    the run must match the replicated-store run exactly."""
    import numpy as np

    from distkeras_tpu.data.shards import write_shards

    rng = np.random.default_rng(0)
    n, d, c = 1024, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    store = tmp_path / "store"
    # 512 rows/shard on dp=2: shard r == dp rank r's partition.
    write_shards(store, {"features": x, "label": y.astype(np.int32)},
                 rows_per_shard=512)
    env = {"DK_SHARD_DIR": str(store), "DK_TRAINER": "parallel", "DK_DP": "2"}

    full_dir = tmp_path / "full"
    full_dir.mkdir()
    _job, rcs = _launch_job(full_dir, env, timeout=900,
                            job_name="pytest-ptrainer-full")
    assert rcs == [0, 0], f"full-store run failed: rcs={rcs}"
    full = _read_results(full_dir)

    disj_dir = tmp_path / "disj"
    disj_dir.mkdir()
    _job, rcs = _launch_job(disj_dir, {**env, "DK_DISJOINT": "1"},
                            timeout=900, job_name="pytest-ptrainer-disjoint")
    assert rcs == [0, 0], f"disjoint run failed: rcs={rcs}"
    disj = _read_results(disj_dir)

    # Each process linked exactly its dp rank's shard (x2 columns) + manifest.
    for i in range(2):
        files = sorted(p.name for p in (disj_dir / f"shards_proc{i}").iterdir())
        assert len(files) == 3, files
    assert (disj_dir / "shards_proc1" / "shard-00001.features.npy").exists()

    for r in full + disj:
        assert r["accuracy"] > 0.85, r
    assert disj[0]["history"] == pytest.approx(full[0]["history"], rel=1e-6)
    assert disj[0]["history"] == pytest.approx(disj[1]["history"], rel=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [2, 3])
def test_multi_process_ingest_and_sharded_predict(tmp_path, nproc):
    """The two out-of-core corners the r3 verdict flagged as guarded-not-
    closed: (a) DISTRIBUTED INGEST — N part-ShardWriters + merge_manifests
    produce a store whose reads are byte-identical to one writer fed the same
    stream; (b) MULTI-PROCESS SHARDED PREDICT — disjoint shard ranges with a
    process-local forward equal the single-process predict, including a
    second predict over the same column (agreed versioned physical name).
    nproc=3 makes both the row split (512/3) and the shard split (8/3)
    uneven — the integer arithmetic 2/4-way symmetry would hide."""
    import numpy as np

    from distkeras_tpu.data.shards import (
        ShardStore, ShardedDataFrame, write_shards)
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import ClassPredictor

    card_worker = os.path.join(os.path.dirname(__file__),
                               "multihost_predict_worker.py")
    card = Punchcard(
        job_name=f"pytest-{nproc}proc-predict",
        script=card_worker,
        hosts=["localhost"] * nproc,
        coordinator_port=_free_port(),
        env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "KERAS_BACKEND": "jax",
            "DK_OUT": str(tmp_path),
            "PYTHONPATH": _REPO,
        },
    )
    job = Job(card)
    job.launch(dry_run=False)
    rcs = job.supervise(timeout=600)
    assert rcs == [0] * nproc, f"worker processes failed: rcs={rcs}"
    results = _read_results(tmp_path, n=nproc)

    # Single-writer + single-process reference on identical data.
    rng = np.random.default_rng(0)
    n, d, c = 512, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n).astype(np.int32)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    ref_store = tmp_path / "ref_store"
    write_shards(ref_store, {"features": x, "label": y}, rows_per_shard=64)
    model = Model.build(MLP(hidden=(16,), num_outputs=c),
                        np.zeros((1, d), np.float32), seed=0)
    ref = ClassPredictor(model, output_col="pred", chunk_size=64).predict(
        ShardedDataFrame(ref_store))
    ref_preds = np.concatenate(
        [ch["pred"] for ch in ref.iter_column_chunks("pred")])

    # (a) merged N-writer store == one-writer store, byte-identical READS.
    # Shard boundaries match exactly when the row split lands on shard
    # boundaries (nproc=2: 256 = 4x64); an uneven split (nproc=3) keeps
    # per-part tail shards, so only the row CONTENT is pinned there.
    merged = ShardStore.open(str(tmp_path / "store"))
    assert sum(merged.manifest["shard_rows"]) == n
    if nproc == 2:
        assert (merged.manifest["shard_rows"]
                == ref.store.manifest["shard_rows"])
    ids = np.arange(n)
    np.testing.assert_array_equal(merged.gather("features", ids),
                                  ref.store.gather("features", ids))
    np.testing.assert_array_equal(merged.gather("label", ids),
                                  ref.store.gather("label", ids))
    assert not any(f.startswith("part-") for f in os.listdir(tmp_path / "store"))

    # (b) multi-process predict over disjoint shard ranges == single-process.
    for r in results:
        assert r["num_rows"] == n and r["features_ok"], r
        assert r["preds"] == [int(v) for v in ref_preds], (
            "multi-process sharded predict diverged from single-process")
        # Second predict re-versioned the column's physical files.
        assert r["pred_file"] != "pred"
    assert results[0]["pred_file"] == results[1]["pred_file"]  # agreed name


@pytest.mark.slow
def test_residency_aware_sharded_predict(tmp_path):
    """VERDICT r4 missing #4: multi-process predict must run where the data
    LIVES. Two processes open per-process store directories (full manifest,
    disjoint UNEVEN shard subsets — a 3/5 split of 8 shards), simulating a
    pod with per-host disks. The shard assignment must follow residency
    (each process predicts exactly the shards its disk holds, predictions
    written beside their features), the union must equal the single-process
    reference, and a shard held by NO process must raise the documented
    contract error instead of FileNotFoundError."""
    import shutil

    import numpy as np

    from distkeras_tpu.data.shards import (
        ShardStore, ShardedDataFrame, _shard_file, write_shards)
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import ClassPredictor

    rng = np.random.default_rng(0)
    n, d, c = 512, 4, 3
    centers = rng.normal(scale=4.0, size=(c, d))
    y = rng.integers(0, c, size=n).astype(np.int32)
    x = (centers[y] + rng.normal(scale=0.5, size=(n, d))).astype(np.float32)
    full = tmp_path / "full_store"
    write_shards(full, {"features": x, "label": y}, rows_per_shard=64)
    S = ShardStore.open(str(full)).num_shards
    assert S == 8

    # Per-process "host disks": uneven disjoint split (p0: shards 0-2,
    # p1: shards 3-7); orphan stores: shard 4 on NO disk.
    owned = {0: list(range(0, 3)), 1: list(range(3, 8))}
    for p in (0, 1):
        for name, keep in (("store", owned[p]),
                           ("orphan", [s for s in owned[p] if s != 4])):
            dst = tmp_path / f"{name}_p{p}"
            dst.mkdir()
            shutil.copy(full / "manifest.json", dst / "manifest.json")
            for s in keep:
                for col in ("features", "label"):
                    shutil.copy(full / _shard_file(s, col),
                                dst / _shard_file(s, col))

    card = Punchcard(
        job_name="pytest-2proc-residency",
        script=os.path.join(os.path.dirname(__file__),
                            "multihost_residency_worker.py"),
        hosts=["localhost"] * 2,
        coordinator_port=_free_port(),
        env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "KERAS_BACKEND": "jax",
            "DK_OUT": str(tmp_path),
            "PYTHONPATH": _REPO,
        },
    )
    job = Job(card)
    job.launch(dry_run=False)
    rcs = job.supervise(timeout=600)
    assert rcs == [0, 0], f"worker processes failed: rcs={rcs}"
    results = _read_results(tmp_path)

    # Single-process reference predictions per shard.
    model = Model.build(MLP(hidden=(16,), num_outputs=c),
                        np.zeros((1, d), np.float32), seed=0)
    ref = ClassPredictor(model, output_col="pred", chunk_size=64).predict(
        ShardedDataFrame(str(full)))
    ref_preds = np.concatenate(
        [ch["pred"] for ch in ref.iter_column_chunks("pred")])
    offsets = ref.store.manifest["shard_offsets"]
    rows = ref.store.manifest["shard_rows"]

    for r in results:
        p = r["process"]
        # Assignment followed residency exactly: predictions beside features.
        assert r["local_feature_shards"] == owned[p], r
        assert r["local_pred_shards"] == owned[p], r
        for s in owned[p]:
            np.testing.assert_array_equal(
                np.asarray(r["preds"][str(s)]),
                ref_preds[offsets[s]:offsets[s] + rows[s]],
                err_msg=f"proc {p} shard {s} diverged from reference")
        # The orphaned shard produced the contract error, on every process.
        assert "residency contract" in r["orphan_error"], r["orphan_error"]
        assert "[4]" in r["orphan_error"], r["orphan_error"]
    assert results[0]["pred_file"] == results[1]["pred_file"]


@pytest.mark.slow
def test_fault_injection_checkpoint_recovery(tmp_path):
    """Kill one host mid-training (hard abort, no cleanup — a preempted pod
    host), then relaunch the job with resume: the recovered run must finish
    and match an uninterrupted run's final model exactly. This is the
    elastic-recovery story SURVEY.md §5 prescribes (checkpoint-restore over
    Orbax; the cluster manager relaunches, jax.distributed re-assembles)."""
    def launch(out_dir, extra_env, timeout):
        _job, rcs = _launch_job(out_dir, extra_env, timeout,
                                job_name="pytest-faulttest")
        return rcs

    ckpt = tmp_path / "ckpt"

    # 1. Uninterrupted reference run.
    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    rcs = launch(clean_dir, {}, timeout=600)
    assert rcs == [0, 0]
    with open(clean_dir / "proc0.json") as f:
        clean = json.load(f)

    # 2. Faulted run: host 1 dies hard after round 2; host 0 is torn down by
    #    the harness (the cluster manager's job). Checkpoints every 2 rounds.
    fault_dir = tmp_path / "fault"
    fault_dir.mkdir()
    rcs = launch(fault_dir, {"DK_CKPT_DIR": str(ckpt), "DK_CKPT_EVERY": "2",
                             "DK_DIE_AT_ROUND": "2"}, timeout=600)
    assert 17 in rcs, f"fault was not injected: rcs={rcs}"
    assert not (fault_dir / "proc0.json").exists()  # nobody finished

    # 3. Relaunch with resume: restores the last complete checkpoint and
    #    finishes the remaining rounds.
    rec_dir = tmp_path / "rec"
    rec_dir.mkdir()
    rcs = launch(rec_dir, {"DK_CKPT_DIR": str(ckpt), "DK_CKPT_EVERY": "2",
                           "DK_RESUME": "1"}, timeout=600)
    assert rcs == [0, 0], f"recovery run failed: rcs={rcs}"
    with open(rec_dir / "proc0.json") as f:
        rec = json.load(f)

    # Recovered model == uninterrupted model (deterministic engine): the
    # resumed history is the tail of the clean history, to float tolerance.
    assert rec["accuracy"] == pytest.approx(clean["accuracy"], abs=1e-6)
    tail = clean["history"][-len(rec["history"]):]
    assert rec["history"] == pytest.approx(tail, rel=1e-5)
