"""Fused GroupNorm Pallas kernel: numerics vs flax (interpreter on CPU CI).

The kernel's perf story is documented in docs/PERFORMANCE.md (on ResNet-50 it
LOSES to XLA's conv-epilogue fusion and is therefore not the default); these
tests pin that whichever impl is selected, the math is flax-equivalent —
including the lane-folded C<128 path and the fused-ReLU variant.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import linen as nn

from distkeras_tpu.ops.pallas.groupnorm import group_norm


def _ref(x, gamma, beta):
    mod = nn.GroupNorm(num_groups=_G, epsilon=1e-6)
    return mod.apply({"params": {"scale": gamma, "bias": beta}}, x)


_G = 16


@pytest.mark.parametrize("shape,groups", [
    ((3, 8, 8, 64), 16),    # C < 128: lane-folded path
    ((2, 4, 4, 256), 32),   # C >= 128: direct path
    ((2, 16, 128), 16),     # 3-D input (already [B, N, C])
])
@pytest.mark.parametrize("relu", [False, True])
def test_matches_flax(shape, groups, relu):
    global _G
    _G = groups
    rng = np.random.default_rng(0)
    C = shape[-1]
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=C), jnp.float32)
    beta = jnp.asarray(rng.normal(size=C), jnp.float32)

    def pallas_loss(args):
        x, g, b = args
        y = group_norm(x, g, b, groups=groups, relu=relu, interpret=True)
        return jnp.sum(jnp.sin(y))

    def ref_loss(args):
        x, g, b = args
        y = _ref(x, g, b)
        if relu:
            y = jax.nn.relu(y)
        return jnp.sum(jnp.sin(y))

    y = group_norm(x, gamma, beta, groups=groups, relu=relu, interpret=True)
    y_ref = _ref(x, gamma, beta)
    if relu:
        y_ref = jax.nn.relu(y_ref)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=2e-5)

    gp = jax.grad(pallas_loss)((x, gamma, beta))
    gr = jax.grad(ref_loss)((x, gamma, beta))
    for a, b, name in zip(gp, gr, ("x", "gamma", "beta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4,
                                   err_msg=f"grad {name}")


def test_bf16_input_f32_stats():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 128)), jnp.bfloat16)
    gamma = jnp.ones(128, jnp.float32)
    beta = jnp.zeros(128, jnp.float32)
    y = group_norm(x, gamma, beta, groups=32, interpret=True)
    assert y.dtype == jnp.bfloat16
    y_ref = _GroupNormRef(32)(np.asarray(x, np.float32))
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref, atol=2e-2)


def _GroupNormRef(G):
    def f(x):
        B = x.shape[0]
        C = x.shape[-1]
        xg = x.reshape(B, -1, G, C // G)
        mean = xg.mean(axis=(1, 3), keepdims=True)
        var = ((xg - mean) ** 2).mean(axis=(1, 3), keepdims=True)
        return ((xg - mean) / np.sqrt(var + 1e-6)).reshape(x.shape)
    return f


def test_unchunkable_shape_falls_back_to_xla():
    """When no aligned chunking keeps f32 temporaries under the hard
    scoped-VMEM line (r3 advisor: _num_chunks used to proceed unbounded),
    group_norm must route to the HLO impl — and still be flax-exact."""
    from distkeras_tpu.ops.pallas.groupnorm import _lane_fold, _num_chunks

    # N = 8 * odd prime: only nck=1 is aligned, and the f32 chunk
    # (N*C*4 ≈ 4.1 MB) is past the 2e6-byte hard line -> None.
    N, C = 8 * 1009, 128
    assert _lane_fold(N, C) == 1 and _num_chunks(N, C) is None
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(1, N, C)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=C), jnp.float32)
    beta = jnp.asarray(rng.normal(size=C), jnp.float32)
    global _G
    _G = 16
    y = group_norm(x, gamma, beta, groups=16, relu=True, interpret=True)
    y_ref = jax.nn.relu(_ref(x, gamma, beta))
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=2e-5)


def test_resnet50_slab_shapes_stay_fused():
    """Every GN slab shape ResNet-50 (224 input) actually produces must keep
    a valid chunking — the fallback is for pathological shapes only, not a
    silent deoptimization of the kernel's own target model."""
    from distkeras_tpu.ops.pallas.groupnorm import _lane_fold, _num_chunks

    slabs = [(112 * 112, 64), (56 * 56, 64), (56 * 56, 256),
             (28 * 28, 128), (28 * 28, 512), (14 * 14, 256),
             (14 * 14, 1024), (7 * 7, 512), (7 * 7, 2048)]
    for N, C in slabs:
        f = _lane_fold(N, C)
        assert _num_chunks(N // f, C * f) is not None, (N, C)


def test_indivisible_groups_raise():
    with pytest.raises(ValueError, match="divisible"):
        group_norm(jnp.zeros((1, 4, 4, 66)), jnp.ones(66), jnp.zeros(66),
                   groups=32, interpret=True)


def test_resnet_norm_impls_equivalent():
    """ResNet's GN module: 'pallas' and 'xla' impls share one param layout
    and produce the same forward values."""
    from distkeras_tpu.models.resnet import ResNet
    from distkeras_tpu.models.base import Model

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.random((2, 32, 32, 3)), jnp.float32)
    kw = dict(stage_sizes=(1, 1), base_features=8, num_outputs=10,
              stem_kernel=3, groups=4)
    m_xla = Model.build(ResNet(**kw), x, seed=0)
    m_pal = Model.build(ResNet(**kw, norm_impl="pallas"), x, seed=0)
    assert jax.tree.structure(m_xla.params) == jax.tree.structure(m_pal.params)
    y_xla = m_xla.predict(x)
    y_pal = ResNet(**kw, norm_impl="pallas").apply(
        {"params": m_xla.params}, x)
    np.testing.assert_allclose(np.asarray(y_xla), np.asarray(y_pal),
                               rtol=1e-4, atol=1e-5)


def test_resnet_remat_same_forward():
    from distkeras_tpu.models.resnet import ResNet
    from distkeras_tpu.models.base import Model

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.random((2, 32, 32, 3)), jnp.float32)
    kw = dict(stage_sizes=(1, 1), base_features=8, num_outputs=10,
              stem_kernel=3, groups=4)
    m = Model.build(ResNet(**kw), x, seed=0)
    m_remat = Model.build(ResNet(**kw, remat=True), x, seed=0)
    # Same seed -> same init; remat must be forward-invariant.
    np.testing.assert_allclose(np.asarray(m.predict(x)),
                               np.asarray(m_remat.predict(x)), rtol=1e-5)
