"""The online serving plane: micro-batcher admission/coalescing/deadlines,
bucketed-shape jit (zero retraces after warmup), the wire-protocol
frontend end to end, hot-swap via the checkpoint registry (including the
corrupt-candidate fallback), client endpoint failover, the fleet serving
tenant's never-fully-drained floor, pool-port hygiene, and the shared
``checkpoint.latest_step`` walk."""

import os
import threading
import time

import numpy as np
import pytest
from flax import linen as nn

from distkeras_tpu import telemetry
from distkeras_tpu.checkpoint import (
    latest_step,
    resume_candidates,
    scan_steps,
)
from distkeras_tpu.fleet import FleetJob, FleetScheduler
from distkeras_tpu.fleet.job import QUEUED, RUNNING
from distkeras_tpu.fleet.ports import _POOL
from distkeras_tpu.models.base import Model
from distkeras_tpu.netps.errors import RPCTimeoutError
from distkeras_tpu.serving import (
    BucketedModel,
    DeadlineExceededError,
    MicroBatcher,
    ModelRegistry,
    ModelUnavailableError,
    OverloadedError,
    ServeClient,
    ServingFrontend,
    bucket_for,
    parse_buckets,
)


class TinyMLP(nn.Module):
    out: int = 3

    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(self.out)(nn.relu(nn.Dense(8)(x)))


@pytest.fixture
def model():
    return Model.build(TinyMLP(), np.zeros((2, 4), np.float32))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


FAST = dict(timeout=2.0, retries=3, backoff=0.01)


# ---------------------------------------------------------------------------
# Buckets + batcher
# ---------------------------------------------------------------------------

def test_parse_buckets_and_bucket_for():
    assert parse_buckets("1,4,16") == (1, 4, 16)
    assert bucket_for(3, (1, 4, 16)) == 4
    assert bucket_for(16, (1, 4, 16)) == 16
    assert bucket_for(17, (1, 4, 16)) is None
    for bad in ("", "0,4", "4,2", "4,4", "a,b"):
        with pytest.raises(ValueError):
            parse_buckets(bad)


def test_batcher_sheds_before_accepting():
    b = MicroBatcher((1, 4), max_queue_rows=4, max_wait_s=10.0)
    b.submit((np.zeros((3, 2)),), 3)
    with pytest.raises(OverloadedError):
        b.submit((np.zeros((2, 2)),), 2)  # 3 + 2 > 4: shed, nothing queued
    assert b.depth_rows() == 3, "a shed request must leave the queue alone"
    # An accepted request still fits in the remaining row budget.
    b.submit((np.zeros((1, 2)),), 1)
    snap = telemetry.get().snapshot()["counters"]
    assert snap["serving.shed"] == 1
    assert snap["serving.accepted"] == 2
    b.close()


def test_batcher_rejects_oversized_request_up_front():
    b = MicroBatcher((1, 4), max_queue_rows=64, max_wait_s=0.0)
    with pytest.raises(OverloadedError, match="largest serving bucket"):
        b.submit((np.zeros((9, 2)),), 9)
    b.close()


def test_batcher_coalesces_concurrent_requests():
    b = MicroBatcher((1, 4, 16), max_queue_rows=64, max_wait_s=0.05)
    pendings = [b.submit((np.zeros((2, 2)),), 2) for _ in range(3)]
    batch = b.collect(poll_s=0.5)
    assert [p.rows for p in batch] == [2, 2, 2], "one coalesced batch"
    assert batch == pendings
    assert b.depth_rows() == 0
    b.close()


def test_batcher_deadline_drop_is_a_typed_answer():
    b = MicroBatcher((4,), max_queue_rows=64, max_wait_s=0.0,
                     deadline_s=0.01)
    p = b.submit((np.zeros((1, 2)),), 1)
    time.sleep(0.05)  # age it past its deadline before any dispatch
    assert b.collect(poll_s=0.1) == []
    assert p.event.is_set(), "expired request must be answered, not dropped"
    assert isinstance(p.error, DeadlineExceededError)
    snap = telemetry.get().snapshot()["counters"]
    assert snap["serving.deadline_drops"] == 1
    b.close()


def test_batcher_close_answers_the_queue_out():
    b = MicroBatcher((4,), max_queue_rows=64, max_wait_s=10.0)
    p = b.submit((np.zeros((1, 2)),), 1)
    b.close()
    assert p.event.is_set()
    assert isinstance(p.error, ModelUnavailableError)
    with pytest.raises(ModelUnavailableError):
        b.submit((np.zeros((1, 2)),), 1)


# ---------------------------------------------------------------------------
# Bucketed model: padding correctness + zero retraces after warmup
# ---------------------------------------------------------------------------

def test_bucketed_model_matches_direct_apply(model):
    bm = BucketedModel(model, (1, 4, 16))
    bm.warmup()
    x = np.random.default_rng(0).standard_normal((3, 4)).astype(np.float32)
    np.testing.assert_allclose(
        bm.infer((x,)), np.asarray(model.predict(x)), rtol=1e-5)


def test_no_retrace_after_warmup_across_ragged_sizes(model):
    bm = BucketedModel(model, (1, 4, 16))
    compiled = bm.warmup()
    assert compiled == 3, "one program per bucket"
    for rows in (1, 2, 3, 4, 5, 11, 16, 7, 1):
        out = bm.infer((np.zeros((rows, 4), np.float32),))
        assert out.shape == (rows, 3)
    assert bm.compiles() == 3, "ragged sizes must reuse bucket programs"
    snap = telemetry.get().snapshot()["counters"]
    assert "serving.retrace_after_warmup" not in snap


def test_retrace_after_warmup_is_counted(model):
    bm = BucketedModel(model, (4,))
    bm.warmup()
    # Force a non-bucket shape straight through the jitted forward — the
    # batcher/infer path can't produce this, which is the point: if it
    # ever did, the counter is the tripwire.
    bm._fwd(bm.params, np.zeros((2, 4), np.float32))
    snap = telemetry.get().snapshot()["counters"]
    assert snap["serving.retrace_after_warmup"] == 1


# ---------------------------------------------------------------------------
# checkpoint.latest_step / shared candidate walk (satellite)
# ---------------------------------------------------------------------------

def test_latest_step_prefers_intact_sidecars(tmp_path):
    root = str(tmp_path)
    for step in (3, 7, 9):
        os.makedirs(os.path.join(root, str(step)))
    os.makedirs(os.path.join(root, "9.orbax-checkpoint-tmp-123"))  # skipped
    meta = os.path.join(root, "meta")
    os.makedirs(meta)
    for step in (3, 7):
        with open(os.path.join(meta, f"{step}.json"), "w") as f:
            f.write("{}")
    with open(os.path.join(meta, "9.json"), "w") as f:
        f.write("{not json")  # corrupt sidecar -> step 9 not preferred
    assert scan_steps(root) == [9, 7, 3]
    assert latest_step(root) == 7, "newest step WITH an intact sidecar"
    # No sidecars at all: every step stays a candidate (metaless saves).
    assert resume_candidates([9, 7, 3], lambda s: False) == [9, 7, 3]
    assert latest_step(str(tmp_path / "missing")) is None


# ---------------------------------------------------------------------------
# Frontend end to end over the wire
# ---------------------------------------------------------------------------

@pytest.fixture
def served(model):
    registry = ModelRegistry(model, (1, 4, 16))
    frontend = ServingFrontend(registry, max_wait_s=0.005).start()
    try:
        yield registry, frontend
    finally:
        frontend.close()
        registry.close()


def test_frontend_answers_ragged_requests(served, model):
    _registry, frontend = served
    client = ServeClient(frontend.endpoint, **FAST)
    rng = np.random.default_rng(1)
    for rows in (1, 3, 7, 16):
        x = rng.standard_normal((rows, 4)).astype(np.float32)
        out, version = client.infer(x)
        assert version == -1, "nothing restored yet: build-time params"
        np.testing.assert_allclose(out, np.asarray(model.predict(x)),
                                   rtol=1e-5)
    stats = client.stats()
    assert stats["served"] == 4 and stats["compiles"] == 3
    assert stats["caps"]["serving"] is True
    client.close()
    snap = telemetry.get().snapshot()
    assert snap["counters"]["serving.answered"] == 4
    assert snap["spans"]["serving.latency"]["count"] == 4


def test_frontend_coalesces_concurrent_clients(served):
    _registry, frontend = served
    results = []

    def one(k):
        client = ServeClient(frontend.endpoint, **FAST)
        out, _ = client.infer(np.full((2, 4), float(k), np.float32))
        results.append(out)
        client.close()

    threads = [threading.Thread(target=one, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 4 and all(r.shape == (2, 3) for r in results)
    snap = telemetry.get().snapshot()["counters"]
    assert snap["serving.answered"] == 4
    assert snap["serving.batches"] <= 4  # some coalescing or at worst 1:1


def test_frontend_overload_is_a_typed_reply(model):
    registry = ModelRegistry(model, (1, 4))
    frontend = ServingFrontend(registry, max_wait_s=5.0,
                               max_queue_rows=1).start()
    blocker = ServeClient(frontend.endpoint, **FAST)

    def _block():
        # Parked in the never-dispatched queue; teardown answers it with
        # a typed unavailable/teardown error — either way, not our assert.
        try:
            blocker.infer(np.zeros((1, 4), np.float32))
        except Exception:
            pass

    t = threading.Thread(target=_block)
    t.start()
    try:
        deadline = time.monotonic() + 2.0
        while frontend.batcher.depth_rows() < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        client = ServeClient(frontend.endpoint, **FAST)
        with pytest.raises(OverloadedError):
            client.infer(np.zeros((4, 4), np.float32))
        client.close()
    finally:
        frontend.close()
        registry.close()
        t.join()
        blocker.close()
    snap = telemetry.get().snapshot()["counters"]
    assert snap["serving.shed"] == 1


def test_unknown_op_and_empty_infer_get_typed_errors(served):
    _registry, frontend = served
    client = ServeClient(frontend.endpoint, **FAST)
    from distkeras_tpu.serving.errors import ServingError

    with pytest.raises(ServingError, match="unknown serving op"):
        client._rpc({"op": "bogus"}, [])
    with pytest.raises(ServingError, match="no input arrays"):
        client._rpc({"op": "infer"}, [])
    client.close()


def test_client_walks_endpoints_on_replica_death(model):
    registry = ModelRegistry(model, (1, 4))
    a = ServingFrontend(registry, max_wait_s=0.002).start()
    b = ServingFrontend(registry, max_wait_s=0.002).start()
    client = ServeClient(f"{a.endpoint},{b.endpoint}", **FAST)
    try:
        out, _ = client.infer(np.zeros((1, 4), np.float32))
        assert out.shape == (1, 3)
        a.kill()  # crash the replica the client is connected to
        out, _ = client.infer(np.zeros((1, 4), np.float32))
        assert out.shape == (1, 3), "failover to the surviving replica"
        snap = telemetry.get().snapshot()["counters"]
        assert snap["serving.client_failovers"] >= 1
        # Both replicas gone: the typed retry-exhausted error, not a hang.
        b.kill()
        with pytest.raises(RPCTimeoutError):
            client.infer(np.zeros((1, 4), np.float32))
    finally:
        client.close()
        a.close()
        b.close()
        registry.close()


def test_frontend_port_comes_from_pool_and_is_released(model):
    registry = ModelRegistry(model, (1,), warmup=False)
    frontend = ServingFrontend(registry).start()
    port = frontend.port
    assert port in _POOL.reserved(), "bind-probed pool allocation"
    frontend.close()
    registry.close()
    assert port not in _POOL.reserved(), "released at teardown"


# ---------------------------------------------------------------------------
# Hot-swap registry
# ---------------------------------------------------------------------------

def _save_step(directory, model, step, scale):
    from distkeras_tpu.checkpoint import Checkpointer

    import jax

    ckpt = Checkpointer(directory)
    params = jax.tree.map(lambda a: np.asarray(a) * 0.0 + scale,
                          model.params)
    assert ckpt.save(step, params, wait=True, meta={"step": step})
    ckpt.close()
    return params


def test_registry_hot_swaps_verified_checkpoint(tmp_path, model):
    directory = str(tmp_path)
    registry = ModelRegistry(model, (1, 4), directory=directory,
                             poll_s=30.0)
    frontend = ServingFrontend(registry, max_wait_s=0.002).start()
    client = ServeClient(frontend.endpoint, **FAST)
    try:
        _, v0 = client.infer(np.ones((1, 4), np.float32))
        assert v0 == -1
        _save_step(directory, model, 5, scale=0.0)
        assert registry.poll_once() is True
        out, v1 = client.infer(np.ones((2, 4), np.float32))
        assert v1 == 5, "replies must carry the swapped version"
        np.testing.assert_allclose(out, 0.0, atol=1e-6), \
            "all-zero params answer zeros: the swap really took"
        assert registry.poll_once() is False, "same step: no re-swap"
        snap = telemetry.get().snapshot()["counters"]
        assert snap["serving.swaps"] == 1
    finally:
        client.close()
        frontend.close()
        registry.close()


def test_registry_rejects_corrupt_candidate_and_keeps_serving(
        tmp_path, model, monkeypatch):
    monkeypatch.setenv("DKTPU_CKPT_DIGEST", "1")
    directory = str(tmp_path)
    _save_step(directory, model, 3, scale=0.5)
    registry = ModelRegistry(model, (1, 4), directory=directory,
                             poll_s=30.0)
    assert registry.poll_once() is True and registry.version == 3
    # A newer step lands corrupt: scribble its payload after the digest.
    _save_step(directory, model, 8, scale=0.25)
    from distkeras_tpu.resilience import integrity

    integrity.corrupt_step_dir(os.path.join(directory, "8"))
    with pytest.warns(UserWarning, match="hot-swap candidate step 8"):
        assert registry.poll_once() is False
    assert registry.version == 3, "incumbent keeps serving"
    snap = telemetry.get().snapshot()["counters"]
    assert snap["serving.swap_failures"] == 1
    # The bad step is remembered: no retry storm on the next poll.
    assert registry.poll_once() is False
    assert snap["serving.swap_failures"] == 1
    registry.close()


# ---------------------------------------------------------------------------
# Fleet: serving tenant floor
# ---------------------------------------------------------------------------

class ParkedRuntime:
    """Synthetic runtime that parks workers until released (serving-like:
    no natural end)."""

    def __init__(self):
        self.revoked = []
        self.closed = False

    def ensure_started(self):
        pass

    def worker_main(self, wid, should_run):
        while should_run():
            time.sleep(0.002)

    def progress(self):
        return 0

    def done(self):
        return False

    def revoke(self, wid):
        self.revoked.append(wid)

    def close(self):
        self.closed = True


def test_job_kind_is_validated():
    with pytest.raises(ValueError, match="kind"):
        FleetJob("x", "t", ParkedRuntime(), kind="bogus")


def test_serving_job_shrinks_to_floor_but_is_never_drained():
    sched = FleetScheduler(capacity=4, tick_s=0.01)
    serve = sched.submit(FleetJob("web", "acme", ParkedRuntime(),
                                  kind="serving", priority=0,
                                  min_gang=2, max_workers=4))
    sched.tick()
    assert serve.state == RUNNING
    deadline = time.monotonic() + 5.0
    while sched.stats()["acme/web"]["granted"] < 4:
        assert time.monotonic() < deadline
        sched.tick()
        time.sleep(0.002)
    # A higher-priority training gang that needs the WHOLE pool: the
    # serving job may be shrunk to its floor (2) but never fully drained,
    # so the big gang cannot place and stays queued.
    train = sched.submit(FleetJob("train", "lab", ParkedRuntime(),
                                  priority=10, min_gang=4, max_workers=4))
    deadline = time.monotonic() + 5.0
    while sched.stats()["acme/web"]["granted"] > 2:
        assert time.monotonic() < deadline
        sched.tick()
        time.sleep(0.002)
    for _ in range(10):
        sched.tick()
    assert serve.state == RUNNING, "serving survives at its floor"
    assert sched.stats()["acme/web"]["granted"] == 2
    assert train.state == QUEUED, "the full-drain path refused serving"
    snap = telemetry.get().snapshot()["counters"]
    assert snap["fleet.serving_drains_refused"] >= 1
    sched.close()
    assert sched.floor_violations == 0
