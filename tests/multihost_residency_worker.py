"""Worker for the residency-aware multi-process sharded-predict test.

Each process opens a PER-PROCESS store directory (``$DK_OUT/store_p<i>``) that
holds the full manifest but ONLY the shard files its "host disk" owns — the
training plane's per-host residency contract (``shards.py`` module
docstring). The predict split must follow what each disk actually holds
(round-robin among each shard's holders — the unique holder when residency
is disjoint), write predictions beside their features, and
the union across processes must equal the single-process reference the
parent computes. A second store pair with one shard missing from EVERY disk
must produce the documented contract error, not a FileNotFoundError.

Run only via ``tests/test_multihost.py``.
"""

import json
import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    from distkeras_tpu.data.shards import ShardedDataFrame, _shard_file
    from distkeras_tpu.models import Model
    from distkeras_tpu.models.mlp import MLP
    from distkeras_tpu.predictors import ClassPredictor
    from distkeras_tpu.runtime.mesh import distributed_initialize

    distributed_initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )
    pid = jax.process_index()
    out = os.environ["DK_OUT"]
    store_dir = os.path.join(out, f"store_p{pid}")

    n, d, c = 512, 4, 3
    model = Model.build(MLP(hidden=(16,), num_outputs=c),
                        np.zeros((1, d), np.float32), seed=0)

    sdf = ShardedDataFrame(store_dir)
    res = ClassPredictor(model, output_col="pred", chunk_size=64).predict(sdf)
    store = res.store

    # Read back ONLY what this disk holds: predictions must sit beside their
    # features (same global shard ids, this directory).
    pred_file = store.columns["pred"].get("file", "pred")
    local_shards = [
        s for s in range(store.num_shards)
        if os.path.exists(os.path.join(store_dir, _shard_file(s, "features")))
    ]
    local_pred_shards = [
        s for s in range(store.num_shards)
        if os.path.exists(os.path.join(store_dir, _shard_file(s, pred_file)))
    ]
    preds = {str(s): np.load(os.path.join(
        store_dir, _shard_file(s, pred_file))).tolist()
        for s in local_pred_shards}

    # Orphaned-shard contract error (store with a shard on NO disk).
    orphan_error = ""
    try:
        ClassPredictor(model, output_col="pred", chunk_size=64).predict(
            ShardedDataFrame(os.path.join(out, f"orphan_p{pid}")))
    except ValueError as e:
        orphan_error = str(e)

    with open(os.path.join(out, f"proc{pid}.json"), "w") as f:
        json.dump({
            "process": pid,
            "local_feature_shards": local_shards,
            "local_pred_shards": local_pred_shards,
            "preds": preds,
            "pred_file": pred_file,
            "orphan_error": orphan_error,
        }, f)


if __name__ == "__main__":
    main()
