"""Resilience: fault injection, failure detection, and auto-recovery.

The reference delegated all fault handling to Spark task retry
(``job_deployment.py`` docstring) and our rebuild dropped even that; this
package is the missing robustness layer, in three parts:

* **Injection** (:mod:`~distkeras_tpu.resilience.faults`): a seeded,
  env-driven :class:`FaultPlan` (``DKTPU_FAULTS="nan@3;stall@5:0.5;crash@7"``)
  that deterministically poisons batches to NaN/Inf, stalls or errors the
  feeder, crashes/kills the process mid-run, and corrupts checkpoints —
  so every recovery path below is *tested*, not asserted.
* **Detection & policy**: an on-device NaN/Inf round skip in every engine
  round body (``DKTPU_NAN_GUARD=0`` disables), the feeder-stall watchdog +
  stage retry/backoff in :class:`~distkeras_tpu.data.prefetch.RoundFeeder`,
  the divergent-worker reset (:class:`~distkeras_tpu.resilience.guard.
  RoundGuard`, ``divergence_reset=thr``), and checkpoint hash sidecars
  (:mod:`~distkeras_tpu.resilience.integrity`).
* **Recovery** (:mod:`~distkeras_tpu.resilience.supervisor`): the
  :class:`Supervisor` retry-with-resume loop around ``Trainer.train``, and
  ``Job.supervise``'s per-host restart with backoff + straggler-timeout
  kill for the multi-host case.

Everything reports through ``resilience.*`` telemetry counters/events —
see docs/RESILIENCE.md for the full taxonomy and knobs.
"""

from __future__ import annotations

from distkeras_tpu.resilience.errors import (  # noqa: F401
    CheckpointCorruptError,
    FeederStalledError,
    InjectedFault,
    ResilienceError,
)
from distkeras_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    active_plan,
    set_plan,
)
from distkeras_tpu.resilience.guard import (  # noqa: F401
    RoundGuard,
    nan_guard_enabled,
    note_losses,
)
from distkeras_tpu.resilience.supervisor import (  # noqa: F401
    Supervisor,
    supervise,
)
from distkeras_tpu.resilience import faults as _faults


def reset() -> None:
    """Clear ambient fault-plan state (tests)."""
    _faults.reset()


__all__ = [
    "ResilienceError", "InjectedFault", "FeederStalledError",
    "CheckpointCorruptError",
    "FaultPlan", "active_plan", "set_plan",
    "RoundGuard", "nan_guard_enabled", "note_losses",
    "Supervisor", "supervise",
    "reset",
]
