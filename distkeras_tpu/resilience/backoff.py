"""Exponential backoff with full jitter — the one shared retry-delay rule.

Every retry loop in the framework (the netps client's RPC retries, the
Supervisor's in-process restarts, ``Job.supervise``'s per-host restarts)
draws its delay here. Full jitter (uniform over ``[0, cap]`` rather than
``cap`` itself) matters precisely when many actors fail *together*: W
workers cut off by one partition, or a pod of hosts killed by one OOM
sweep, would otherwise all sleep the identical deterministic delay and
retry in lockstep — a synchronized restart storm that re-creates the
overload that killed them. Jitter decorrelates the herd; the exponential
envelope still bounds total pressure.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def backoff_cap(base_s: float, attempt: int, max_s: float = 30.0) -> float:
    """The deterministic exponential envelope: ``min(max_s, base * 2**n)``.
    Exposed separately so tests can assert the jittered draw stays inside."""
    if base_s <= 0:
        return 0.0
    return float(min(max_s, base_s * (2.0 ** max(0, int(attempt)))))


def full_jitter(base_s: float, attempt: int, max_s: float = 30.0,
                rng: Optional[np.random.Generator] = None) -> float:
    """A delay drawn uniformly from ``[0, backoff_cap(base, attempt, max))``
    (AWS full-jitter). ``attempt`` counts from 0 (first retry). A dedicated
    ``rng`` makes tests deterministic; production callers share the module
    default, which is deliberately unseeded — decorrelation is the point."""
    cap = backoff_cap(base_s, attempt, max_s)
    if cap <= 0:
        return 0.0
    gen = rng if rng is not None else _DEFAULT_RNG
    return float(gen.uniform(0.0, cap))


_DEFAULT_RNG = np.random.default_rng()
