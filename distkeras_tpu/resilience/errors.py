"""Resilience exception taxonomy.

Every failure the subsystem *detects* (as opposed to merely propagates) is
raised as one of these, so the :class:`~distkeras_tpu.resilience.supervisor.
Supervisor` and tests can match on type instead of message strings.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for every resilience-layer failure."""


class InjectedFault(ResilienceError):
    """A fault deliberately injected by a :class:`FaultPlan` — raised so the
    recovery path under test sees a real exception, and so accidental
    production use of ``DKTPU_FAULTS`` is unmistakable in a traceback."""


class FeederStalledError(ResilienceError):
    """The input pipeline produced nothing for longer than the watchdog
    timeout — the run loop declares the data plane dead rather than hanging
    forever on an empty queue."""


class CheckpointCorruptError(ResilienceError):
    """A restored checkpoint failed its integrity check (hash sidecar
    mismatch). Callers fall back to the previous step."""
