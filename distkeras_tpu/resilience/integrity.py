"""Checkpoint integrity: content digests, sidecar files, and (for fault
injection) controlled corruption.

Orbax detects *some* on-disk damage (missing files, unreadable metadata) but
a bit-flipped array payload can restore to silent garbage. The digest
sidecar closes that hole: :class:`~distkeras_tpu.checkpoint.Checkpointer`
hashes the state at save time, and a verified restore re-hashes and
compares, falling back to the previous step on mismatch.

Digests are computed from the *encoded* tree (typed PRNG keys already
converted to raw data), leaf-by-leaf in ``jax.tree`` flatten order with
dtype and shape mixed in — a silent dtype/shape drift fails the check too.
Single-process only: hashing requires fully-addressable arrays; multi-host
runs skip the sidecar (Orbax's own coordination covers the write there).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

import numpy as np


def tree_digest(tree: Any) -> dict:
    """A JSON-able content digest of every leaf in ``tree``."""
    import jax

    h = hashlib.sha256()
    leaves = jax.tree.leaves(tree)
    total = 0
    for leaf in leaves:
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(f"{a.dtype.str}{a.shape}".encode())
        h.update(a.tobytes())
        total += a.nbytes
    return {"algo": "sha256", "hexdigest": h.hexdigest(),
            "leaves": len(leaves), "bytes": total}


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    """Streaming sha256 of a file's bytes — the sidecar check for on-disk
    blobs hashed as files rather than trees (the netps PS snapshots: the
    server is numpy + stdlib only, so no ``jax.tree`` walk is available
    there). Raises ``OSError`` if the file is unreadable."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def write_digest(path: str, digest: dict) -> None:
    """Atomic (tmp + rename) sidecar write."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(digest, f)
    os.replace(tmp, path)


def read_digest(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def matches(tree: Any, digest: Optional[dict]) -> bool:
    """Whether ``tree`` hashes to ``digest`` (vacuously True without one)."""
    if not digest or "hexdigest" not in digest:
        return True
    return tree_digest(tree)["hexdigest"] == digest["hexdigest"]


def corrupt_file(path: str, nbytes: int = 64) -> None:
    """Overwrite ``nbytes`` in the middle of ``path`` with inverted bits —
    the fault-injection primitive behind ``ckpt_corrupt@S``."""
    size = os.path.getsize(path)
    if size == 0:
        with open(path, "wb") as f:
            f.write(b"\xff" * nbytes)
        return
    off = max(0, size // 2 - nbytes // 2)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(min(nbytes, size - off))
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))


def corrupt_step_dir(directory: str) -> Optional[str]:
    """Corrupt the array payload of a checkpoint step directory. OCDBT
    keeps data chunks under ``d/`` directories — and may keep duplicate
    copies (a per-process staging dir plus the merged database), so EVERY
    chunk file is hit; damaging only one copy would leave the read path
    intact and inject nothing. Without any ``d/`` dir, the single largest
    file is corrupted instead. Returns the first path hit (None if the
    directory is empty)."""
    chunks: list[str] = []
    best, best_size = None, -1
    for root, _dirs, files in os.walk(directory):
        is_data = os.path.basename(root) == "d"
        for name in files:
            path = os.path.join(root, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if is_data:
                chunks.append(path)
            elif size > best_size:
                best, best_size = path, size
    targets = chunks or ([best] if best is not None else [])
    for path in targets:
        corrupt_file(path)
    return targets[0] if targets else None
