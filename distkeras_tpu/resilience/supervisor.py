"""The Supervisor: bounded retry-with-resume around ``Trainer.train``.

``Checkpointer`` has existed since v0.5 but nothing *restarted* from it —
a crashed run left a perfectly good checkpoint on disk and a dead process.
The Supervisor closes the loop::

    trainer = ADAG(model, checkpoint_dir="ckpt", checkpoint_every=1, ...)
    model = Supervisor(trainer, max_retries=3).train(df, shuffle=True)

On an exception from ``train`` it flips the trainer to ``resume=True``
(so the rebuilt engine restores the latest intact checkpoint — integrity
verified against the hash sidecar, falling back to the previous step when
corrupt — and continues from the recorded round), waits an exponentially
backed-off delay, and retries, up to ``max_retries`` times. The retry
budget is bounded: a deterministic crash re-raises after the budget, it
does not loop forever. ``Trainer.train`` rebuilds its engine and plan per
call, so re-entry is safe by construction.

This is the in-process half of recovery; the cross-process half (a host
hard-killed mid-run) is ``Job.supervise``'s per-host restart — the
restarted process lands in the same Supervisor-or-resume path via
``resume=True``.
"""

from __future__ import annotations

import time
import warnings
from typing import Tuple, Type

from distkeras_tpu.resilience.backoff import full_jitter


class Supervisor:
    """Wrap a trainer's ``train`` in a bounded retry-with-resume loop.

    Parameters
    ----------
    trainer:
        Any :class:`~distkeras_tpu.trainers.Trainer`. For resume (rather
        than retry-from-scratch) it must have ``checkpoint_dir`` and a
        nonzero ``checkpoint_every``.
    max_retries:
        Retries *after* the first attempt (3 → up to 4 attempts total).
    backoff_s / max_backoff_s:
        Exponential retry envelope: each retry sleeps a **full-jitter**
        draw from ``[0, min(max_backoff_s, backoff_s * 2**(attempt-1)))``
        (:func:`~distkeras_tpu.resilience.backoff.full_jitter` — the same
        rule the netps client uses), so simultaneously-crashed trainers
        don't retry in lockstep. Pass ``backoff_s=0`` for immediate
        retries (tests).
    retry_on:
        Exception types worth retrying. Defaults to ``Exception`` —
        ``KeyboardInterrupt``/``SystemExit`` always propagate.
    """

    def __init__(self, trainer, max_retries: int = 3, backoff_s: float = 1.0,
                 max_backoff_s: float = 60.0,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,)):
        self.trainer = trainer
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.retry_on = tuple(retry_on)
        #: attempts made by the most recent :meth:`train` call.
        self.attempts = 0
        if not getattr(trainer, "checkpoint_dir", None):
            warnings.warn(
                "Supervisor: trainer has no checkpoint_dir — retries will "
                "restart training from scratch instead of resuming",
                stacklevel=2)
        elif not getattr(trainer, "checkpoint_every", 0):
            warnings.warn(
                "Supervisor: trainer has checkpoint_every=0 — only the "
                "end-of-run checkpoint exists, so a mid-run crash resumes "
                "from round 0; set checkpoint_every for real resume points",
                stacklevel=2)

    def train(self, dataframe, shuffle: bool = False):
        from distkeras_tpu import telemetry

        self.attempts = 0
        with telemetry.span("resilience.supervised_train"):
            while True:
                self.attempts += 1
                try:
                    return self.trainer.train(dataframe, shuffle=shuffle)
                except self.retry_on as e:
                    retries = self.attempts - 1
                    if retries >= self.max_retries:
                        telemetry.counter(
                            "resilience.supervisor_exhausted").add(1)
                        raise
                    telemetry.counter("resilience.supervisor_retries").add(1)
                    # The event records HOW the retry recovers (resume vs
                    # scratch) and — fired under any ambient
                    # ``telemetry.scoped_labels`` scope, e.g. a fleet
                    # worker's — carries the tenant/job attribution
                    # automatically, so a multi-tenant report can separate
                    # whose training is churning.
                    telemetry.event("supervisor_retry", {
                        "attempt": self.attempts, "error": repr(e),
                        "resume": bool(self.trainer.checkpoint_dir)})
                    how = ("resuming from checkpoint"
                           if self.trainer.checkpoint_dir
                           else "restarting from scratch")
                    warnings.warn(
                        f"supervised train attempt {self.attempts} failed "
                        f"({type(e).__name__}: {e}); {how} "
                        f"({self.max_retries - retries} retries left)",
                        stacklevel=2)
                    if self.trainer.checkpoint_dir:
                        self.trainer.resume = True
                    delay = full_jitter(self.backoff_s, retries,
                                        self.max_backoff_s)
                    if delay > 0:
                        time.sleep(delay)


def supervise(trainer, dataframe, shuffle: bool = False, **kwargs):
    """One-call sugar: ``supervise(trainer, df)`` ==
    ``Supervisor(trainer, **kwargs).train(df)``."""
    return Supervisor(trainer, **kwargs).train(dataframe, shuffle=shuffle)
