"""Host-side per-round resilience hooks for the engine run loops.

Two layers of defense, split by cost:

* **On-device** (always on unless ``DKTPU_NAN_GUARD=0``): the round program
  itself checks ``isfinite`` over the replicated per-worker loss vector and,
  when any worker went non-finite, keeps the *previous* state — the poisoned
  round is skipped entirely, with zero host round-trips and one cheap
  ``where`` select per leaf. Lives in the engines' round bodies
  (``parallel/engine.py`` / ``parallel/sync.py``); this module only supplies
  the policy switch and the post-hoc accounting.

* **Host-side** (this module's :class:`RoundGuard`): fault injection
  (``crash@R`` / ``kill@R``) and the divergent-worker reset. The reset is
  opt-in (``divergence_reset=thr`` on the async trainers, or
  ``DKTPU_DIVERGENCE_RESET``) because it must fetch the loss every round —
  a fence the default path deliberately never pays, keeping the guards'
  no-fault overhead below run-to-run noise.
"""

from __future__ import annotations

import os
import signal
from typing import Optional

import numpy as np

from distkeras_tpu.resilience import faults
from distkeras_tpu.resilience.errors import InjectedFault
from distkeras_tpu.runtime import config


def nan_guard_enabled() -> bool:
    """Default for the engines' on-device NaN/Inf round skip."""
    return config.env_bool("DKTPU_NAN_GUARD")


class RoundGuard:
    """Per-run host-side guard, constructed by the engine run loops.

    Inactive (the common case: no faults configured, no divergence reset)
    every method is a branch-and-return — the run loop pays nothing.
    """

    def __init__(self, engine):
        self.engine = engine
        self.plan = faults.active_plan()
        thr = getattr(engine, "divergence_reset", None)
        if thr is None:
            thr = config.env_float("DKTPU_DIVERGENCE_RESET")
        disc = getattr(engine, "discipline", None)
        self.divergence_reset: Optional[float] = (
            float(thr)
            if thr is not None and disc is not None
            and getattr(disc, "communicates", False)
            and hasattr(engine, "reset_workers")
            else None)
        self._inject = self.plan is not None and bool(self.plan)

    def pre_round(self, round_idx: int) -> None:
        """Crash/kill injection, fired before the round is dispatched."""
        if not self._inject:
            return
        if self.plan.kill(round_idx):
            # The mid-run host kill: unmaskable, no cleanup — exactly what a
            # preempted/OOM-killed host looks like to Job.supervise.
            os.kill(os.getpid(), signal.SIGKILL)
        if self.plan.crash(round_idx):
            raise InjectedFault(
                f"crash injected at round {round_idx} (DKTPU_FAULTS)")

    def post_round(self, round_idx: int, loss, state,
                   host_loss=None):
        """Divergent-worker reset: when a worker's loss strays more than
        ``divergence_reset`` from the (finite) worker mean — or went
        non-finite while the round as a whole survived — re-adopt the
        center for that worker (the reference's rejoining-worker PS pull).
        Returns the (possibly replaced) state."""
        if self.divergence_reset is None:
            return state
        host = np.asarray(host_loss if host_loss is not None
                          else __import__("jax").device_get(loss))
        host = host.reshape(-1).astype(np.float64)
        if host.size < 2:
            return state
        finite = host[np.isfinite(host)]
        if finite.size == 0:
            return state  # whole round poisoned — the NaN skip handles it
        mask = (~np.isfinite(host)
                | (np.abs(host - finite.mean()) > self.divergence_reset))
        if not mask.any() or mask.all():
            # All-divergent has no healthy center estimate to re-adopt
            # against; leave it to the NaN skip / supervisor.
            return state
        from distkeras_tpu import telemetry

        telemetry.counter("resilience.worker_resets").add(int(mask.sum()))
        telemetry.event("worker_reset", {
            "round": round_idx,
            "workers": [int(i) for i in np.flatnonzero(mask)]})
        return self.engine.reset_workers(state, mask)


def note_losses(losses) -> None:
    """Post-hoc accounting over a run's host loss history: count rounds any
    worker reported a non-finite loss (the rounds the on-device guard
    skipped) into ``resilience.nonfinite_rounds``. Runs once per run on the
    already-fetched array — no extra fences."""
    arr = np.asarray(losses, dtype=np.float64)
    if arr.size == 0:
        return
    rows = arr.reshape(arr.shape[0], -1)
    bad = int((~np.isfinite(rows)).any(axis=1).sum())
    if bad:
        from distkeras_tpu import telemetry

        telemetry.counter("resilience.nonfinite_rounds").add(bad)
