"""Deterministic fault injection: the :class:`FaultPlan`.

The reference delegated all fault handling to Spark task retry and never
tested it (``job_deployment.py`` docstring); here every recovery path is
driven by *injected* faults so it is exercised, not asserted. A plan is a
set of ``kind@at[:arg]`` entries, parsed from the ``DKTPU_FAULTS`` env var
(or built programmatically), and each fault fires **exactly once** per
process — a resumed run re-executing the poisoned round must not be
re-poisoned, or no recovery loop could ever converge.

Syntax (``;``-separated entries)::

    DKTPU_FAULTS="nan@3;stall@5:0.5;crash@7;seed=11"

=================  ==========================================================
``nan@R``          poison round R's staged batch to NaN — the loss AND the
                   gradients of that round go non-finite through backprop
``inf@R``          same, with Inf
``stall@R:S``      the feeder thread sleeps S seconds while staging item R
                   (exercises the consumer-side stall watchdog)
``feeder_error@R`` the feeder's stage call raises :class:`InjectedFault`
                   once at item R (exercises the stage retry/backoff path)
``crash@R``        raise :class:`InjectedFault` in the run loop before
                   dispatching round R (exercises Supervisor retry-resume)
``kill@R``         SIGKILL this process before dispatching round R (the
                   mid-run host kill; exercises ``Job.supervise`` restart)
``ckpt_corrupt@S`` scribble over the checkpoint payload of Orbax step S
                   right after it is written (exercises the hash-sidecar
                   fallback restore)
``feed_gap@R:S``   the stream source goes silent for S seconds before
                   delivering item R (``streaming/source.py``) — upstream
                   of the staging thread, so the gap flows through the
                   RoundFeeder stall watchdog exactly like a real dried-up
                   feed
``drift@R``        distribution shift injected at stream item R: every
                   record from R onward has its labels deterministically
                   rotated (``streaming/source.py``), so windowed online
                   eval loss diverges and the drift sentinel must page
``seed=N``         seeds deterministic choices (which worker's batch rows
                   get poisoned)
=================  ==========================================================

Cross-process one-shot state: ``kill@R`` restarts the process, which would
re-fire the kill forever. Set ``DKTPU_FAULTS_STATE=/path/file`` and fired
faults are journaled there, surviving the restart.

Scheduling caveat: batch faults (``nan``/``inf``) fire at *staging* time,
and the RoundFeeder stages ``depth`` (default 2) rounds ahead of execution
— a crash/kill scheduled within that lookahead of a batch fault can
discard the already-poisoned staged batch, consuming the one-shot with no
observable effect. Keep batch faults at least ``depth + 1`` rounds away
from crash/kill faults (the shipped schedules use a gap of 4).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from distkeras_tpu.runtime import config

#: fault kinds and whether they take an argument.
_KINDS = frozenset({
    "nan", "inf", "stall", "feeder_error", "crash", "kill", "ckpt_corrupt",
    "feed_gap", "drift",
})

#: network fault kinds (``DKTPU_NET_FAULTS``), consumed by the netps chaos
#: proxy (``netps/chaos.py``), the shared-memory ring transport
#: (``netps/shm.py``), the netps server itself, and the remote worker
#: loop. ``at`` indexes
#: client->server *frames* for the wire kinds (TCP frames through the
#: proxy; ring frames for the ``shm_*`` kinds — no proxy can sit on a
#: memory ring, so the transport injects its own faults) and commit
#: *rounds* for ``evict``. The ``_r`` variants hit the reply
#: (server->client) direction of the same frame index — "per direction"
#: fault injection. ``shm_delay@F:S`` holds ring frame F for S seconds;
#: ``shm_corrupt@F`` flips frame F's slot crc so the server rejects it and
#: the connection dies (the ring's ``truncate``). ``ps_crash@R`` SIGKILLs
#: the netps SERVER process just before folding its R-th commit (the
#: kill-the-primary drill — recovery is the state-dir cold restart or the
#: warm standby's promotion); ``ps_hang@R:S`` wedges the server for S
#: seconds *holding its center lock* before commit R, so every member's
#: lease renewal queues behind a genuinely hung PS (what ``Job.supervise``
#: must tell apart from a draining one). Both are consumed by the server
#: process, never by the proxy — schedule them only in the PS process's
#: environment. ``preempt@R[:N]`` is the control-plane drill: when the
#: fleet's cumulative commit count crosses R, the ``FleetScheduler``
#: forcibly preempts N workers (default 1) from its lowest-priority
#: running job exactly as a capacity squeeze would — lease revocation,
#: shrink floor at the victim's min gang, full drain + requeue when the
#: floor is already reached (``distkeras_tpu/fleet/scheduler.py``).
#: ``serve_slow@F:S`` and ``serve_drop@F`` are consumed by the serving
#: frontend (``distkeras_tpu/serving/frontend.py``), indexing accepted
#: inference requests process-wide: ``serve_slow`` holds request F's
#: reply for S seconds (a wedged replica — clients must ride it out or
#: walk the replica list), ``serve_drop`` kills request F's connection
#: without a reply (the client sees a transport failure and fails over;
#: the shed-before-accept contract still answers every ACCEPTED request
#: whose connection survives). ``shard_crash@N:R`` is the sharded-center
#: drill: SIGKILL SHARD N of a sharded PS deployment once it has folded R
#: commits — the ``at`` slot selects the shard index (every shard process
#: consults its own plan instance, so the index is the only coordinate
#: they share), and the arg is the commit threshold. Consumed by the shard
#: server via the non-consuming :meth:`FaultPlan.pending` peek (shard
#: k != N must not burn the one-shot), fired in the killed shard's own
#: process. ``link_down@K:S`` black-holes ONE aggregation-tree uplink for
#: S seconds: the ``at`` slot carries the link key
#: ``TreeSpec.link_key(level, group) = level*1000 + group`` — the
#: (level, group) uplink packed into the one integer the grammar allows —
#: and is consumed by that tree node's own uplink transport
#: (``netps/tree.py``), because no chaos proxy can sit on every interior
#: hop. Commits keep flowing INTO the node; its flushes buffer (bounded by
#: ``DKTPU_TREE_BUFFER``, then counted typed drops) and its upstream
#: heartbeats stop, so the uplink lease genuinely lapses — the heal path
#: must re-prove membership before draining. ``link_flap@K:S`` is the
#: flappy variant: down S, up S, down S again — two outages from one
#: entry, exercising the drain->re-black-hole path. Schedule both in the
#: tree NODE's process environment.
#: ``mesh_down@R`` is the device-loss drill for the mesh transport
#: dialect (``DKTPU_NET_TRANSPORT=mesh``): the in-process mesh dispatch
#: raises ``ConnectionError`` when commit seq R crosses it, as a lost
#: device mesh would — the client must demote to its negotiated shm/TCP
#: dialect and retransmit the SAME seq, exactly-once riding through.
_NET_KINDS = frozenset({
    "delay", "drop", "dup", "truncate", "partition", "evict",
    "delay_r", "drop_r", "dup_r", "truncate_r",
    "shm_delay", "shm_corrupt",
    "ps_crash", "ps_hang", "preempt",
    "serve_slow", "serve_drop",
    "shard_crash", "link_down", "link_flap", "mesh_down",
})


class FaultPlan:
    """A seeded, deterministic schedule of injected faults.

    Thread-safe: the feeder thread (stall/feeder_error), the run loop
    (nan/crash/kill), and the checkpointer (ckpt_corrupt) all consult one
    plan concurrently.
    """

    def __init__(self, faults: Optional[dict] = None, seed: int = 0,
                 state_file: Optional[str] = None):
        #: {(kind, at): arg} — arg is None for argless kinds.
        self.faults: dict = dict(faults or {})
        self.seed = int(seed)
        self.state_file = state_file
        self._fired: set = set()
        self._lock = threading.Lock()
        if state_file and os.path.exists(state_file):
            with open(state_file) as f:
                self._fired = {tuple(line.strip().rsplit("@", 1))
                               for line in f if "@" in line}
            self._fired = {(k, int(at)) for k, at in self._fired}

    @classmethod
    def parse(cls, spec: str, state_file: Optional[str] = None,
              kinds: Optional[frozenset] = None) -> "FaultPlan":
        """Parse a ``kind@at[:arg]`` plan. ``kinds`` selects the grammar:
        the compute kinds (default, ``DKTPU_FAULTS``) or the network kinds
        (``_NET_KINDS``, ``DKTPU_NET_FAULTS`` via :meth:`parse_net`)."""
        kinds = _KINDS if kinds is None else kinds
        faults: dict = {}
        seed = 0
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                seed = int(entry[5:])
                continue
            if "@" not in entry:
                raise ValueError(
                    f"bad fault entry {entry!r}: expected "
                    "kind@round[:arg] or seed=N")
            kind, at = entry.split("@", 1)
            kind = kind.strip()
            if kind not in kinds:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: {sorted(kinds)}")
            arg: Optional[float] = None
            if ":" in at:
                at, args = at.split(":", 1)
                arg = float(args)
            faults[(kind, int(at))] = arg
        return cls(faults, seed=seed, state_file=state_file)

    @classmethod
    def parse_net(cls, spec: str,
                  state_file: Optional[str] = None) -> "FaultPlan":
        """Parse a network-fault plan (``DKTPU_NET_FAULTS`` grammar).
        ``state_file`` journals fired faults across a process restart —
        ``ps_crash@R`` restarts the very process consulting the plan, so
        without it the restarted server would re-crash at R forever (the
        ``kill@R`` problem, one subsystem over). The net and compute plans
        may share one file: their kind names never collide."""
        return cls.parse(spec, kinds=_NET_KINDS, state_file=state_file)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        spec = config.env_str("DKTPU_FAULTS")
        if not spec:
            return None
        return cls.parse(spec,
                         state_file=config.env_str("DKTPU_FAULTS_STATE")
                         or None)

    # ------------------------------------------------------------------
    def _fire(self, kind: str, at: int) -> Optional[float]:
        """The fault's arg if (kind, at) is scheduled and not yet fired;
        marks it fired (and journals it) as a side effect."""
        key = (kind, at)
        with self._lock:
            if key not in self.faults or key in self._fired:
                return None
            self._fired.add(key)
            arg = self.faults[key]
        if self.state_file:
            # Journal BEFORE the fault takes effect: kill@R must not re-fire
            # after the restart it causes.
            with open(self.state_file, "a") as f:
                f.write(f"{kind}@{at}\n")
        from distkeras_tpu import telemetry
        from distkeras_tpu.telemetry import tracing

        telemetry.counter("resilience.faults_injected").add(1)
        telemetry.event("fault_injected", {"fault": kind, "at": at})
        # Dump the flight ring BEFORE the fault takes effect — a kind
        # like ``ps_crash`` SIGKILLs this very process, and the ring is
        # the only record of what it was doing in its final seconds.
        tracing.flight_dump(f"fault:{kind}")
        return arg if arg is not None else 0.0

    def pending(self, kind: str, at: int) -> Optional[float]:
        """Non-consuming peek: the arg (0.0 when argless) if ``(kind, at)``
        is scheduled and NOT yet fired, else None. For conditional faults
        whose trigger is checked repeatedly before it holds (the shard
        server polls ``shard_crash`` every commit until the threshold) —
        :meth:`fire` there would burn the one-shot on the first look."""
        key = (kind, at)
        with self._lock:
            if key not in self.faults or key in self._fired:
                return None
            arg = self.faults[key]
        return arg if arg is not None else 0.0

    # -- queries (all one-shot) ----------------------------------------
    def fire(self, kind: str, at: int) -> Optional[float]:
        """Generic one-shot query: the fault's arg (0.0 when argless) if
        ``(kind, at)`` is scheduled and unfired, else None. The network
        kinds go through this — the chaos proxy and the remote worker loop
        ask by (kind, frame/round index) directly."""
        return self._fire(kind, at)

    def batch_fault(self, round_idx: int) -> Optional[str]:
        """``"nan"``/``"inf"`` if this round's batch should be poisoned."""
        for kind in ("nan", "inf"):
            if self._fire(kind, round_idx) is not None:
                return kind
        return None

    def feeder_stall(self, item: int) -> float:
        """Seconds the feeder should sleep staging ``item`` (0 = no fault)."""
        arg = self._fire("stall", item)
        return float(arg) if arg else 0.0

    def feeder_error(self, item: int) -> bool:
        return self._fire("feeder_error", item) is not None

    def crash(self, round_idx: int) -> bool:
        return self._fire("crash", round_idx) is not None

    def kill(self, round_idx: int) -> bool:
        return self._fire("kill", round_idx) is not None

    def ckpt_corrupt(self, step: int) -> bool:
        return self._fire("ckpt_corrupt", step) is not None

    def feed_gap(self, item: int) -> float:
        """Seconds the stream source should go silent before delivering
        ``item`` (0 = no fault) — the dried-up-feed drill, consumed by the
        source layer so the gap propagates through staging into the
        RoundFeeder stall watchdog."""
        arg = self._fire("feed_gap", item)
        return float(arg) if arg else 0.0

    def drift(self, item: int) -> bool:
        """Whether a distribution shift is scheduled to begin at stream
        ``item``. One-shot like every fault, but the *shift* is permanent:
        the source remembers the trigger and keeps transforming every
        subsequent record (a drifted world does not un-drift by itself)."""
        return self._fire("drift", item) is not None

    def poison_worker(self, round_idx: int, num_workers: int) -> int:
        """Deterministic (seeded) choice of which worker's rows to poison —
        one worker suffices: its non-finite commit contaminates the psum'd
        center for everyone, which is exactly the failure mode to test."""
        if num_workers <= 1:
            return 0
        return (self.seed * 1009 + round_idx) % num_workers

    def __bool__(self) -> bool:
        return bool(self.faults)

    def __repr__(self) -> str:
        items = ";".join(
            f"{k}@{at}" + (f":{arg}" if arg is not None else "")
            for (k, at), arg in sorted(self.faults.items()))
        return f"FaultPlan({items!r}, seed={self.seed})"


# -- ambient plan (env-driven, cached by spec) -----------------------------
_LOCK = threading.Lock()
_CACHED_SPEC: Optional[str] = None
_CACHED_PLAN: Optional[FaultPlan] = None
_EXPLICIT: Optional[FaultPlan] = None
_EXPLICIT_SET = False
_NET_CACHED_SPEC: Optional[str] = None
_NET_CACHED_PLAN: Optional[FaultPlan] = None
_NET_EXPLICIT: Optional[FaultPlan] = None
_NET_EXPLICIT_SET = False


def active_plan() -> Optional[FaultPlan]:
    """The process-ambient FaultPlan (None when no faults are configured).

    Re-parses when ``DKTPU_FAULTS`` changes (fresh fired-state), otherwise
    returns the cached plan so one-shot semantics hold across the run. An
    explicit :func:`set_plan` overrides the environment entirely."""
    global _CACHED_SPEC, _CACHED_PLAN
    if _EXPLICIT_SET:
        return _EXPLICIT
    spec = config.env_str("DKTPU_FAULTS")
    if not spec:
        return None
    with _LOCK:
        if spec != _CACHED_SPEC:
            _CACHED_PLAN = FaultPlan.parse(
                spec, state_file=config.env_str("DKTPU_FAULTS_STATE") or None)
            _CACHED_SPEC = spec
        return _CACHED_PLAN


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` as the ambient plan (tests; programmatic use).
    ``set_plan(None)`` forces no-faults regardless of the environment."""
    global _EXPLICIT, _EXPLICIT_SET
    with _LOCK:
        _EXPLICIT = plan
        _EXPLICIT_SET = True


def active_net_plan() -> Optional[FaultPlan]:
    """The process-ambient *network* FaultPlan (``DKTPU_NET_FAULTS``), with
    the same cache-by-spec one-shot semantics as :func:`active_plan`. The
    chaos proxy and the netps remote worker loop consult this."""
    global _NET_CACHED_SPEC, _NET_CACHED_PLAN
    if _NET_EXPLICIT_SET:
        return _NET_EXPLICIT
    spec = config.env_str("DKTPU_NET_FAULTS")
    if not spec:
        return None
    with _LOCK:
        if spec != _NET_CACHED_SPEC:
            # The same fired-state journal as the compute plan: `ps_crash`
            # restarts the process that consults this plan, exactly like
            # `kill@R` does — without the journal the restarted server
            # would re-crash at the same commit forever.
            _NET_CACHED_PLAN = FaultPlan.parse_net(
                spec, state_file=config.env_str("DKTPU_FAULTS_STATE")
                or None)
            _NET_CACHED_SPEC = spec
        return _NET_CACHED_PLAN


def set_net_plan(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` as the ambient network plan (tests)."""
    global _NET_EXPLICIT, _NET_EXPLICIT_SET
    with _LOCK:
        _NET_EXPLICIT = plan
        _NET_EXPLICIT_SET = True


def reset() -> None:
    """Clear the explicit plans and the env caches (the next
    :func:`active_plan` / :func:`active_net_plan` re-reads its env var with
    fresh fired-state)."""
    global _EXPLICIT, _EXPLICIT_SET, _CACHED_SPEC, _CACHED_PLAN
    global _NET_EXPLICIT, _NET_EXPLICIT_SET
    global _NET_CACHED_SPEC, _NET_CACHED_PLAN
    with _LOCK:
        _EXPLICIT = None
        _EXPLICIT_SET = False
        _CACHED_SPEC = None
        _CACHED_PLAN = None
        _NET_EXPLICIT = None
        _NET_EXPLICIT_SET = False
        _NET_CACHED_SPEC = None
        _NET_CACHED_PLAN = None
