"""Runtime lock-order witness: the dynamic complement of DK201.

The static lock graph (``rules_concurrency.build_lock_graph``) cannot see
cross-module acquisition chains (it resolves calls one level deep, same
module only). This witness closes that gap at test time: enable it around a
threaded scenario and every ``threading.Lock``/``RLock`` **created while it
is active** is wrapped so that each successful acquisition records
"acquired B while holding A" edges into one shared order graph. Tests then
assert the observed order is consistent (acyclic) and contained in the
statically derived graph:

    with witness() as w:
        run_raced(...)                      # or any threaded scenario
    w.assert_no_inversions()
    assert w.edges() <= static_edges        # static graph is sound

Locks created *before* the context manager are untouched (jax internals,
module-global locks imported earlier), so the witness only pays its ~µs
bookkeeping on the code under test. The wrapper is duck-compatible with
``threading.Condition``'s non-RLock fallback (it deliberately does NOT
expose ``_is_owned``/``_release_save``), so ``queue.Queue`` built during
the window keeps working.
"""

from __future__ import annotations

import contextlib
import linecache
import re
import sys
import threading
import _thread

_SELF_ATTR_RE = re.compile(r"self\.(\w+)\s*=")
_NAME_RE = re.compile(r"^\s*(\w+)\s*=")


def _creation_label() -> str:
    """Label the lock by its creation site, matching the static graph's ids
    (``modbase.Class.attr`` / ``modbase.NAME``) when the site is a simple
    ``self.X = Lock()`` / ``X = Lock()`` assignment."""
    frame = sys._getframe(2)
    while frame is not None:
        fn = frame.f_code.co_filename
        if "analysis/witness" not in fn.replace("\\", "/") and \
                "threading" not in fn and "queue" not in fn:
            break
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    modbase = frame.f_code.co_filename.rsplit("/", 1)[-1].removesuffix(".py")
    line = linecache.getline(frame.f_code.co_filename, frame.f_lineno)
    m = _SELF_ATTR_RE.search(line)
    if m is not None and "self" in frame.f_locals:
        cls = type(frame.f_locals["self"]).__name__
        return f"{modbase}.{cls}.{m.group(1)}"
    m = _NAME_RE.match(line)
    if m is not None:
        return f"{modbase}.{m.group(1)}"
    return f"{modbase}:{frame.f_lineno}"


class LockOrderWitness:
    """The shared order graph; one instance per :func:`witness` window."""

    def __init__(self):
        self._edges: dict = {}   # (a, b) -> first-seen (thread name, b site)
        self._meta_lock = _thread.allocate_lock()  # real lock: no recursion
        self._held = threading.local()

    # -- bookkeeping called by _WitnessLock --------------------------------
    def _stack(self) -> list:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _acquired(self, label: str) -> None:
        st = self._stack()
        with self._meta_lock:
            for held in st:
                if held != label:
                    self._edges.setdefault(
                        (held, label), threading.current_thread().name)
        st.append(label)

    def _released(self, label: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == label:
                del st[i]
                return

    # -- assertions --------------------------------------------------------
    def edges(self) -> set:
        with self._meta_lock:
            return set(self._edges)

    def cycles(self) -> list:
        graph: dict = {}
        for a, b in self.edges():
            graph.setdefault(a, set()).add(b)
        cycles, state = [], {}

        def dfs(n, stack):
            state[n] = 1
            for m in graph.get(n, ()):
                if state.get(m, 0) == 1:
                    cycles.append(stack[stack.index(m):] + [m])
                elif state.get(m, 0) == 0:
                    dfs(m, stack + [m])
            state[n] = 2

        for n in sorted(graph):
            if state.get(n, 0) == 0:
                dfs(n, [n])
        return cycles

    def assert_no_inversions(self) -> None:
        cycles = self.cycles()
        if cycles:
            rendered = "; ".join(" -> ".join(c) for c in cycles)
            raise AssertionError(
                f"lock-order inversion observed at runtime: {rendered}")


class _WitnessLock:
    """Wrapper over a real Lock/RLock that reports to the witness."""

    def __init__(self, inner, witness: LockOrderWitness, label: str):
        self._inner = inner
        self._witness = witness
        self._label = label

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._acquired(self._label)
        return got

    def release(self) -> None:
        self._inner.release()
        self._witness._released(self._label)

    def locked(self) -> bool:
        inner = getattr(self._inner, "locked", None)  # RLock lacks it on 3.10
        return bool(inner()) if inner is not None else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<witnessed {self._label} {self._inner!r}>"


@contextlib.contextmanager
def witness():
    """Patch ``threading.Lock``/``RLock`` so locks created in this window
    report acquisition order; yields the :class:`LockOrderWitness`."""
    w = LockOrderWitness()
    orig_lock, orig_rlock = threading.Lock, threading.RLock

    def make(ctor):
        def factory():
            return _WitnessLock(ctor(), w, _creation_label())
        return factory

    threading.Lock = make(orig_lock)
    threading.RLock = make(orig_rlock)
    try:
        yield w
    finally:
        threading.Lock = orig_lock
        threading.RLock = orig_rlock
