"""DK5xx — durability and ordering discipline for the distributed planes.

Two bug classes that each cost a human review pass to catch get rules:

* **DK501** — a blocking call while holding a *durable-state* lock. The
  PR 6 bug: ``jax.extend.backend.resolve_backend()`` (seconds of first-
  touch compile) ran under the PS center lock, stalling every worker.
  The rule extends the DK202 guarded-attr model with a blocking-call
  taxonomy (socket/file I/O, ``time.sleep``, jax first-touch) and fires
  when such a call sits *lexically* inside ``with <lock>:`` for a lock
  whose guarded attributes include the center / journal / commit state.
  Lexical on purpose: the journal's ``fsync`` lives in a helper *called*
  under its lock — that is the deliberate durability write, not a
  hazard; the rule flags the direct form that stalls the plane.
* **DK502** — ACK/reply emission reachable before the corresponding
  journal append in the same handler. The OffsetJournal discipline is
  intent-before-RPC: a commit RPC (or reply/ACK write) that precedes the
  ``journal.intent()`` / ``fsync`` / ``write_epoch`` in its function
  reopens the crash window the journal exists to close (the PR 7 "fence
  not durable" shape). Checked as an intra-function ordering graph:
  first emission site vs first durable site.
"""

from __future__ import annotations

import ast

from distkeras_tpu.analysis.core import (
    Finding, Module, RuleInfo, call_name, module_rule)
from distkeras_tpu.analysis.rules_concurrency import (
    _attr_writes_shallow, _ModuleLocks)

#: attr-name substrings marking a lock as guarding durable plane state.
_DURABLE_STATE = ("center", "journal", "store", "frontier", "intent",
                  "commit", "last_seq", "epoch", "ahead")

#: call names (dotted, or bare) that block: file/socket I/O, sleeps, and
#: jax first-touch (compile / backend resolution).
_BLOCKING_EXACT = frozenset({
    "open", "os.fsync", "os.replace", "os.rename", "time.sleep",
    "socket.create_connection", "socket.create_server",
    "resolve_backend",
})
_BLOCKING_ATTRS = frozenset({
    # any receiver: socket/file verbs + jax first-touch entry points
    "sleep", "connect", "accept", "recv", "recv_into", "sendall",
    "makefile", "fsync", "resolve_backend", "block_until_ready",
    "device_put", "jit", "compile",
})

#: DK502 call taxonomies. Durable = the journal/epoch write that must
#: come first; emit = the RPC/ACK that makes the result visible.
_DURABLE_CALL_ATTRS = frozenset({
    "intent", "fsync", "write_epoch", "_persist_locked",
})
_EMIT_ATTRS = frozenset({"commit", "sendall", "send_frame", "request"})
_EMIT_RECEIVER_HINTS = ("client", "conn", "sock", "peer", "sub", "ps")


def _guarded_durable_locks(mod: Module, info: _ModuleLocks,
                           cls_node: ast.ClassDef) -> set:
    """Lock attr names of ``cls_node`` whose guarded writes touch durable
    plane state (the DK202 locked-writes map, filtered)."""
    lock_attrs = info.class_locks.get(cls_node.name, set())
    if not lock_attrs:
        return set()
    durable: set = set()

    def scan(node, held: set) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                expr = item.context_expr
                if (isinstance(expr, ast.Attribute)
                        and isinstance(expr.value, ast.Name)
                        and expr.value.id == "self"
                        and info.resolve(expr, cls_node.name)):
                    inner.add(expr.attr)
            for child in node.body:
                scan(child, inner)
            return
        for attr, _site in _attr_writes_shallow(node):
            if attr in lock_attrs:
                continue
            if held and any(s in attr.lower() for s in _DURABLE_STATE):
                durable.update(held)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                scan(child, held)

    for meth in cls_node.body:
        if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in meth.body:
                scan(child, set())
    return durable & lock_attrs


def _is_blocking(node: ast.Call) -> str:
    name = call_name(node.func)
    if name in _BLOCKING_EXACT:
        return name
    last = name.rsplit(".", 1)[-1]
    if last in _BLOCKING_ATTRS and "." in name:
        return name
    # jax.* first-touch anywhere under the lock is a compile hazard
    if name.startswith("jax."):
        return name
    return ""


@module_rule(
    RuleInfo("DK501", "blocking call while holding a durable-state lock"),
)
def check_blocking_under_lock(mod: Module) -> list:
    out: list = []
    info = _ModuleLocks(mod)
    for cls_node in [n for n in ast.walk(mod.tree)
                     if isinstance(n, ast.ClassDef)]:
        durable_locks = _guarded_durable_locks(mod, info, cls_node)
        if not durable_locks:
            continue

        def scan(node, held: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    expr = item.context_expr
                    if (isinstance(expr, ast.Attribute)
                            and isinstance(expr.value, ast.Name)
                            and expr.value.id == "self"
                            and expr.attr in durable_locks):
                        inner = True
                for child in node.body:
                    scan(child, inner)
                return
            if held and isinstance(node, ast.Call):
                what = _is_blocking(node)
                if what:
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset, "DK501",
                        f"`{what}()` while holding a lock guarding "
                        "center/journal state: blocking here stalls every "
                        "worker on the plane (the PR 6 resolve_backend "
                        "bug) — move the call before the lock"))
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                    scan(child, held)

        for meth in cls_node.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in meth.body:
                    scan(child, False)
    return out


def _durable_call(node: ast.Call) -> bool:
    name = call_name(node.func)
    last = name.rsplit(".", 1)[-1]
    if name == "os.fsync":
        return True
    if last not in _DURABLE_CALL_ATTRS:
        return False
    if last in ("write_epoch", "_persist_locked", "fsync"):
        return True
    # `.intent(...)`: require a journal-ish receiver so unrelated APIs
    # named `intent` stay out of the model.
    recv = name.rsplit(".", 2)
    return any("journal" in p.lower() or "store" in p.lower()
               for p in recv[:-1])


def _emit_call(node: ast.Call) -> bool:
    name = call_name(node.func)
    last = name.rsplit(".", 1)[-1]
    if last not in _EMIT_ATTRS or "." not in name:
        return False
    recv = name[: -(len(last) + 1)]
    return any(h in recv.lower() for h in _EMIT_RECEIVER_HINTS)


@module_rule(
    RuleInfo("DK502", "reply/ACK emitted before the journal append"),
)
def check_ack_before_journal(mod: Module) -> list:
    out: list = []
    for fn in [n for n in ast.walk(mod.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        durable_lines: list = []
        emits: list = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _durable_call(node):
                durable_lines.append(node.lineno)
            elif _emit_call(node):
                emits.append(node)
        if not durable_lines or not emits:
            continue
        first_durable = min(durable_lines)
        for node in emits:
            if node.lineno < first_durable:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, "DK502",
                    f"`{call_name(node.func)}()` emits before the journal "
                    f"append at line {first_durable}: intent-before-RPC — "
                    "a crash between them replays or loses the record "
                    "(the OffsetJournal discipline)"))
    return out
