"""dk-check CLI: ``python -m distkeras_tpu.analysis [paths...]``.

Exit status 0 = clean, 1 = findings, 2 = usage error. See docs/ANALYSIS.md
for the rule catalog and suppression syntax.
"""

from __future__ import annotations

import argparse
import os
import sys

from distkeras_tpu.analysis import core


def _write_env_docs(repo_root: str) -> int:
    from distkeras_tpu.runtime import config

    docs_dir = os.path.join(repo_root, "docs")
    changed = 0
    for name in sorted(os.listdir(docs_dir)):
        if not name.endswith(".md"):
            continue
        path = os.path.join(docs_dir, name)
        with open(path, encoding="utf-8") as f:
            text = f.read()
        try:
            fresh = config.splice_env_docs(text)
        except ValueError:
            continue
        if fresh != text:
            with open(path, "w", encoding="utf-8") as f:
                f.write(fresh)
            print(f"dk-check: rewrote env table(s) in {path}")
            changed += 1
    if not changed:
        print("dk-check: env docs already in sync")
    return 0


def _write_metric_docs(repo_root: str) -> int:
    from distkeras_tpu.telemetry import registry

    docs_dir = os.path.join(repo_root, "docs")
    changed = 0
    for name in sorted(os.listdir(docs_dir)):
        if not name.endswith(".md"):
            continue
        path = os.path.join(docs_dir, name)
        with open(path, encoding="utf-8") as f:
            text = f.read()
        try:
            fresh = registry.splice_metric_docs(text)
        except ValueError:
            continue
        if fresh != text:
            with open(path, "w", encoding="utf-8") as f:
                f.write(fresh)
            print(f"dk-check: rewrote metric table(s) in {path}")
            changed += 1
    if not changed:
        print("dk-check: metric docs already in sync")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.analysis",
        description="dk-check: repo-aware static analysis "
                    "(DK1xx jax purity, DK2xx concurrency, DK3xx config, "
                    "DK4xx wire protocol, DK5xx durability, DK6xx "
                    "contract registries)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to check "
                             "(default: the distkeras_tpu package)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--select", action="append", default=None,
                        metavar="DKxxx", help="only rules with this ID prefix "
                        "(repeatable, e.g. --select DK2 --select DK301)")
    parser.add_argument("--ignore", action="append", default=None,
                        metavar="DKxxx", help="drop rules with this ID prefix")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--write-env-docs", action="store_true",
                        help="regenerate the env-var tables in docs/*.md "
                             "from runtime.config.ENV_REGISTRY and exit")
    parser.add_argument("--write-metric-docs", action="store_true",
                        help="regenerate the metric tables in docs/*.md "
                             "from telemetry.registry.METRIC_REGISTRY "
                             "and exit")
    args = parser.parse_args(argv)

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.write_env_docs:
        return _write_env_docs(os.path.dirname(pkg_dir))
    if args.write_metric_docs:
        return _write_metric_docs(os.path.dirname(pkg_dir))
    if args.list_rules:
        core._load_rules()
        for rule in sorted(core.RULE_CATALOG):
            print(f"{rule}  {core.RULE_CATALOG[rule].summary}")
        return 0

    paths = args.paths or [pkg_dir]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"dk-check: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    findings = core.run(paths, select=args.select, ignore=args.ignore)
    print(core.render(findings, args.format))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
