"""DK4xx — wire-protocol registry discipline.

The netps frame protocol (``netps/wire.py``) is a hand-rolled contract:
op kinds, frame header keys, error kinds, and the byte-level struct
layouts. PRs 4-17 grew call sites faster than the contract — these rules
pin every protocol token to the declared registries so drift is a
finding, not a code-review catch:

* **DK401** — op-kind discipline. In the module that defines
  ``OP_REGISTRY`` (wire.py), every ``OP_*`` constant must be a registry
  key and every key must be a declared constant (with a cap gate that
  exists in ``CAPS``). Everywhere else, op kinds are ``wire.OP_*``
  references: a raw op string in a dispatch comparison, an ``_rpc(...)``
  first argument, an ``{"op": ...}`` frame literal, or a stray ``OP_*``
  assignment is drift waiting to happen.
* **DK402** — header/error literals must come from the declared
  registries: a ``header.get("k")`` / ``h["k"]`` key absent from
  ``wire.HEADER_KEYS``, or an error kind (``_err("...")`` / an
  ``.get("error")`` comparison) absent from ``wire.ERROR_KINDS``.
* **DK403** — raw ``struct.pack/unpack`` outside wire.py: byte layouts
  live in one file (``wire._PREFIX``, ``wire.U32``, ...); a private
  struct call elsewhere in the netps plane is an undeclared wire format.

DK402/DK403 scope: modules under ``netps/`` or importing
``distkeras_tpu.netps`` — the serialization/datasets struct users are
not on the wire and stay exempt.
"""

from __future__ import annotations

import ast
import os
import re

from distkeras_tpu.analysis.core import (
    Finding, Module, RuleInfo, call_name, module_rule)

_OP_CONST_RE = re.compile(r"^OP_[A-Z0-9_]+$")
_HEADER_RECEIVERS = frozenset({"hdr", "rhdr", "header", "reply"})
_STRUCT_CALLS = frozenset({
    "pack", "unpack", "pack_into", "unpack_from", "iter_unpack",
    "calcsize", "Struct",
})


def _wire():
    from distkeras_tpu.netps import wire

    return wire


def _netps_scoped(mod: Module) -> bool:
    """Under netps/ or importing it — the modules that speak the wire."""
    if (os.sep + "netps" + os.sep) in os.path.normpath(mod.path):
        return True
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            if any(a.name.startswith("distkeras_tpu.netps")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            m = node.module or ""
            if m.startswith("distkeras_tpu.netps"):
                return True
            if m == "distkeras_tpu" and any(a.name == "netps"
                                            for a in node.names):
                return True
    return False


def _defines_registry(mod: Module) -> bool:
    return any(isinstance(n, ast.Assign)
               and any(isinstance(t, ast.Name) and t.id == "OP_REGISTRY"
                       for t in n.targets)
               for n in mod.tree.body)


def _str_const(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


def _op_expr(node) -> bool:
    """Does this expression read the op kind? (``op``, ``x.op``,
    ``h.get("op")``, ``h["op"]``)"""
    if isinstance(node, ast.Name) and node.id == "op":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "op":
        return True
    if (isinstance(node, ast.Call)
            and call_name(node.func).rsplit(".", 1)[-1] == "get"
            and node.args and _str_const(node.args[0])
            and node.args[0].value == "op"):
        return True
    if (isinstance(node, ast.Subscript) and _str_const(node.slice)
            and node.slice.value == "op"):
        return True
    return False


def _check_registry_module(mod: Module) -> list:
    """The wire.py half of DK401: OP_* constants <-> OP_REGISTRY keys."""
    out: list = []
    consts: dict = {}       # OP_NAME -> (value, line)
    caps_keys: set = set()
    reg_node = None
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if _OP_CONST_RE.match(t.id) and _str_const(node.value):
                consts[t.id] = (node.value.value, node.lineno)
            elif t.id == "OP_REGISTRY":
                reg_node = node
            elif t.id == "CAPS" and isinstance(node.value, ast.Dict):
                caps_keys = {k.value for k in node.value.keys
                             if _str_const(k)}
    if reg_node is None or not isinstance(reg_node.value, ast.Dict):
        return out
    key_names: set = set()   # OP_* constants referenced as keys
    key_values: set = set()  # literal-string keys
    for key, val in zip(reg_node.value.keys, reg_node.value.values):
        if isinstance(key, ast.Name):
            key_names.add(key.id)
        elif _str_const(key):
            key_values.add(key.value)
        # cap gate: OpSpec's first argument must be a declared capability
        if (isinstance(val, ast.Call)
                and call_name(val.func).rsplit(".", 1)[-1] == "OpSpec"
                and val.args and _str_const(val.args[0]) and caps_keys
                and val.args[0].value not in caps_keys):
            out.append(Finding(
                mod.path, val.lineno, val.col_offset, "DK401",
                f"OP_REGISTRY cap gate `{val.args[0].value!r}` is not a "
                "declared CAPS capability"))
    for name, (value, line) in sorted(consts.items()):
        if name not in key_names and value not in key_values:
            out.append(Finding(
                mod.path, line, 0, "DK401",
                f"`{name}` is not declared in OP_REGISTRY: every op kind "
                "carries its cap gate and reply keys there"))
    for value in sorted(key_values):
        if value not in {v for v, _ in consts.values()}:
            out.append(Finding(
                mod.path, reg_node.lineno, 0, "DK401",
                f"OP_REGISTRY key `{value!r}` has no OP_* constant: "
                "declare the constant and key the registry by it"))
    return out


def _op_literal_findings(mod: Module, ops: frozenset) -> list:
    """The everywhere-else half of DK401: raw op strings in op contexts."""
    out: list = []

    def flag(node, value: str) -> None:
        if value in ops:
            hint = (f"use wire.OP_{value.upper()}" if value.isidentifier()
                    else "use the wire.OP_* constant")
            msg = f"raw op string `{value!r}`: {hint}"
        else:
            msg = (f"op `{value!r}` is not declared in wire.OP_REGISTRY: "
                   "undeclared ops bypass the cap-gate/reply contract")
        out.append(Finding(mod.path, node.lineno, node.col_offset,
                           "DK401", msg))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and _OP_CONST_RE.match(t.id)
                        and _str_const(node.value)):
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset, "DK401",
                        f"`{t.id}` declared outside wire.py: op constants "
                        "live in wire.OP_REGISTRY, import them from there"))
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if not any(_op_expr(s) for s in sides):
                continue
            for s in sides:
                if _str_const(s):
                    flag(s, s.value)
                elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                    for el in s.elts:
                        if _str_const(el):
                            flag(el, el.value)
        elif isinstance(node, ast.Call):
            name = call_name(node.func).rsplit(".", 1)[-1]
            if (name in ("_rpc", "_rpc_traced") and node.args
                    and _str_const(node.args[0])):
                flag(node.args[0], node.args[0].value)
        elif isinstance(node, ast.Dict):
            for key, val in zip(node.keys, node.values):
                if (_str_const(key) and key.value == "op"
                        and _str_const(val)):
                    flag(val, val.value)
    return out


@module_rule(
    RuleInfo("DK401", "op kind drifts from wire.OP_REGISTRY"),
)
def check_op_registry(mod: Module) -> list:
    if _defines_registry(mod):
        return _check_registry_module(mod)
    if not _netps_scoped(mod):
        return []
    return _op_literal_findings(mod, frozenset(_wire().OP_REGISTRY))


@module_rule(
    RuleInfo("DK402", "undeclared frame header key / error kind literal"),
)
def check_header_literals(mod: Module) -> list:
    if _defines_registry(mod) or not _netps_scoped(mod):
        return []
    wire = _wire()
    out: list = []

    def header_key(node) -> None:
        # .get("k") / ["k"] on a header-named receiver
        key = None
        if isinstance(node, ast.Call):
            name = call_name(node.func)
            if (name.rsplit(".", 1)[-1] == "get"
                    and name.split(".")[0] in _HEADER_RECEIVERS
                    and name.count(".") == 1
                    and node.args and _str_const(node.args[0])):
                key = node.args[0]
        elif isinstance(node, ast.Subscript):
            recv = node.value
            if (isinstance(recv, ast.Name)
                    and recv.id in _HEADER_RECEIVERS
                    and _str_const(node.slice)):
                key = node.slice
        if key is not None and key.value not in wire.HEADER_KEYS:
            out.append(Finding(
                mod.path, key.lineno, key.col_offset, "DK402",
                f"frame header key `{key.value!r}` is not declared in "
                "wire.HEADER_KEYS: undeclared keys are invisible to the "
                "protocol contract"))

    def error_kind(node) -> None:
        if isinstance(node, ast.Call):
            name = call_name(node.func).rsplit(".", 1)[-1]
            if (name in ("_err", "err") and node.args
                    and _str_const(node.args[0])
                    and node.args[0].value not in wire.ERROR_KINDS):
                bad = node.args[0]
                out.append(Finding(
                    mod.path, bad.lineno, bad.col_offset, "DK402",
                    f"error kind `{bad.value!r}` is not declared in "
                    "wire.ERROR_KINDS: clients dispatch on these strings"))
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            reads_err = any(
                isinstance(s, ast.Call)
                and call_name(s.func).rsplit(".", 1)[-1] == "get"
                and s.args and _str_const(s.args[0])
                and s.args[0].value == "error" for s in sides) or any(
                isinstance(s, ast.Name) and s.id == "error_kind"
                for s in sides)
            if not reads_err:
                return
            for s in sides:
                consts = ([s] if _str_const(s) else
                          [el for el in getattr(s, "elts", ())
                           if _str_const(el)])
                for c in consts:
                    if c.value not in wire.ERROR_KINDS:
                        out.append(Finding(
                            mod.path, c.lineno, c.col_offset, "DK402",
                            f"error kind `{c.value!r}` is not declared "
                            "in wire.ERROR_KINDS: clients dispatch on "
                            "these strings"))

    for node in ast.walk(mod.tree):
        header_key(node)
        error_kind(node)
    return out


@module_rule(
    RuleInfo("DK403", "raw struct.pack/unpack outside wire.py"),
)
def check_raw_struct(mod: Module) -> list:
    if _defines_registry(mod) or not _netps_scoped(mod):
        return []
    out: list = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node.func)
        parts = name.split(".")
        if (len(parts) == 2 and parts[0] == "struct"
                and parts[1] in _STRUCT_CALLS):
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, "DK403",
                f"raw `{name}()` on the wire plane: byte layouts are "
                "declared once in wire.py (wire._PREFIX, wire.U32, ...) — "
                "an ad-hoc struct call here is an undeclared frame format"))
    return out
