"""dk-check core: findings, suppressions, the rule registry, the runner.

The analyzer is a plain-AST pass (no imports of the analyzed code, so a
broken module still gets checked) with three repo-specific rule families:

* ``DK1xx`` — JAX purity/retrace hazards (``rules_jax``)
* ``DK2xx`` — host-thread concurrency hazards (``rules_concurrency``)
* ``DK3xx`` — environment/config discipline (``rules_config``)
* ``DK4xx`` — wire-protocol registry discipline (``rules_protocol``)
* ``DK5xx`` — durability/ordering discipline (``rules_durability``)
* ``DK6xx`` — contract-registry cross-checks (``rules_contracts``)

plus **DK001**, the meta-rule: a ``# dk: disable=RULE`` suppression whose
rule can no longer fire on that line is itself a finding — suppressions
are part of the code under review and must not outlive their reason.

Two rule shapes exist: **module rules** see one parsed file at a time;
**project rules** see the whole file set (the lock-order graph and the
registry/docs cross-checks need global state).

Suppression: a ``# dk: disable=DK101`` (or ``# dk: disable=DK101,DK204``,
or blanket ``# dk: disable``) comment suppresses findings attributed to
that physical line; ``# dk: disable-file=DK301`` anywhere suppresses the
rule for the whole file. Suppressions are part of the code under review —
each one should carry a justification in the surrounding comment.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Iterable, Optional

_SUPPRESS_RE = re.compile(
    r"#\s*dk:\s*(?P<kind>disable(?:-file)?)\s*(?:=\s*(?P<rules>[\w,\s]+))?")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str      # as given to the runner (relative when inputs were)
    line: int
    col: int
    rule: str      # stable ID, e.g. "DK101"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file handed to the rules."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        #: line -> set of suppressed rule IDs (empty set = all rules)
        self.suppressions: dict = {}
        #: rules suppressed for the whole file
        self.file_suppressions: set = set()
        #: file-suppressed rule -> line of its disable-file comment (DK001)
        self.file_suppression_lines: dict = {}
        self._parse_suppressions()

    def _parse_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = [(i + 1, line[line.index("#"):])
                        for i, line in enumerate(self.source.splitlines())
                        if "#" in line]
        for line, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in (m.group("rules") or "").split(",")
                     if r.strip()}
            if m.group("kind") == "disable-file":
                self.file_suppressions |= rules or {"*"}
                for r in rules:
                    self.file_suppression_lines.setdefault(r, line)
            else:
                self.suppressions.setdefault(line, set()).update(rules or {"*"})

    def suppressed(self, rule: str, line: int) -> bool:
        if self.file_suppressions & {rule, "*"}:
            return True
        rules = self.suppressions.get(line)
        return rules is not None and bool(rules & {rule, "*"})


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    """Catalog entry: what ``--list-rules`` and docs/ANALYSIS.md print."""

    rule: str
    summary: str


#: (checker, [RuleInfo]) pairs; module checkers take one Module, project
#: checkers take the full list.
_MODULE_CHECKERS: list = []
_PROJECT_CHECKERS: list = []
RULE_CATALOG: dict = {}

# The suppression meta-rule lives in the runner itself (it needs the raw,
# pre-suppression finding set), so it registers here, not via a decorator.
RULE_CATALOG["DK001"] = RuleInfo(
    "DK001", "stale suppression: the rule can no longer fire here")


def module_rule(*infos: RuleInfo):
    def deco(fn):
        _MODULE_CHECKERS.append(fn)
        for i in infos:
            RULE_CATALOG[i.rule] = i
        return fn
    return deco


def project_rule(*infos: RuleInfo):
    def deco(fn):
        _PROJECT_CHECKERS.append(fn)
        for i in infos:
            RULE_CATALOG[i.rule] = i
        return fn
    return deco


def _load_rules() -> None:
    # Import for registration side effects; idempotent.
    from distkeras_tpu.analysis import (  # noqa: F401
        rules_concurrency, rules_config, rules_contracts, rules_durability,
        rules_jax, rules_protocol)


def iter_py_files(paths: Iterable[str]) -> list:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def parse_modules(paths: Iterable[str]) -> tuple:
    """(modules, findings) — a syntactically broken file becomes a DK000
    finding instead of crashing the run."""
    modules, findings = [], []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            modules.append(Module(path, source))
        except SyntaxError as e:
            findings.append(Finding(path, e.lineno or 1, (e.offset or 1) - 1,
                                    "DK000", f"syntax error: {e.msg}"))
    return modules, findings


def _rule_selected(rule: str, select, ignore) -> bool:
    if select and not any(rule.startswith(s) for s in select):
        return False
    if ignore and any(rule.startswith(s) for s in ignore):
        return False
    return True


def run(paths: Iterable[str], select: Optional[Iterable[str]] = None,
        ignore: Optional[Iterable[str]] = None) -> list:
    """Run every registered rule over ``paths``; returns sorted, unsuppressed
    findings. ``select``/``ignore`` filter by rule-ID prefix (``DK2``,
    ``DK201``)."""
    _load_rules()
    select = [s.upper() for s in select] if select else None
    ignore = [s.upper() for s in ignore] if ignore else None
    modules, findings = parse_modules(paths)
    by_path = {m.path: m for m in modules}
    for checker in _MODULE_CHECKERS:
        for mod in modules:
            findings.extend(checker(mod))
    for checker in _PROJECT_CHECKERS:
        findings.extend(checker(modules))
    findings.extend(_stale_suppressions(modules, findings))
    kept = []
    for f in findings:
        if not _rule_selected(f.rule, select, ignore):
            continue
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    return sorted(set(kept))


def _stale_suppressions(modules, findings) -> list:
    """DK001: a specific-rule suppression that matched no raw finding.

    Works on the *pre-suppression* finding set — a suppression is live
    iff the rule it names actually fires on its line (or anywhere in the
    file, for ``disable-file``). Blanket ``*`` suppressions are exempt:
    they state intent about the line, not about one rule's behavior.
    """
    out = []
    by_mod: dict = {}
    for f in findings:
        by_mod.setdefault(f.path, set()).add((f.line, f.rule))
    for mod in modules:
        hits = by_mod.get(mod.path, set())
        file_rules = {r for _ln, r in hits}
        for line, rules in sorted(mod.suppressions.items()):
            for rule in sorted(rules - {"*"}):
                if (line, rule) not in hits:
                    out.append(Finding(
                        mod.path, line, 0, "DK001",
                        f"stale suppression: {rule} can no longer fire on "
                        "this line — remove the `# dk: disable` comment "
                        "(or fix the rule ID)"))
        for rule in sorted(mod.file_suppressions - {"*"}):
            if rule not in file_rules:
                out.append(Finding(
                    mod.path, mod.file_suppression_lines.get(rule, 1), 0,
                    "DK001",
                    f"stale suppression: {rule} no longer fires anywhere "
                    "in this file — remove the `# dk: disable-file` "
                    "comment"))
    return out


def render(findings: list, fmt: str = "text") -> str:
    if fmt == "json":
        return json.dumps({"findings": [f.to_json() for f in findings],
                           "count": len(findings)}, indent=2)
    lines = [f.render() for f in findings]
    lines.append(f"dk-check: {len(findings)} finding(s)")
    return "\n".join(lines)


# -- shared AST helpers (used by the rule modules) --------------------------

def call_name(node: ast.AST) -> str:
    """Dotted name of a call target / attribute chain, '' when dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_scope(fn: ast.AST):
    """Yield nodes of a function body without descending into nested defs
    (class bodies still descend — they execute inline)."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        node = todo.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            todo.extend(ast.iter_child_nodes(node))
