"""DK1xx — JAX purity / retrace lints.

A function is **traced** when its body runs under a jax transform: its
Python side effects happen once at trace time (silently stale thereafter),
its host reads are burned into the compiled program as constants, and
non-hashable static arguments force a retrace per call. These rules mark a
function traced when it is

* decorated with ``jit``/``pjit``/``pmap``/``vmap``/``grad``/
  ``value_and_grad``/``shard_map``/``pallas_call`` (bare, dotted, or via
  ``partial(jax.jit, ...)``), or
* a local ``def``/``lambda`` passed to one of those wrappers, or to a
  ``lax.``-qualified control-flow combinator (``scan``, ``cond``,
  ``while_loop``, ``fori_loop``, ``switch``, ``map``, ``associated_scan``).

Known limit (documented in docs/ANALYSIS.md): traced-ness does not
propagate through ordinary calls — a helper called *from* a traced body is
only checked if it is itself wrapped. The runtime lock-order witness and
the engines' own tests cover the dynamic side.
"""

from __future__ import annotations

import ast

from distkeras_tpu.analysis.core import (
    Finding, Module, RuleInfo, call_name, module_rule)

_WRAPPERS = frozenset({
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "shard_map",
    "pallas_call",
})
_LAX_COMBINATORS = frozenset({
    "scan", "cond", "while_loop", "fori_loop", "switch", "map",
    "associative_scan",
})
#: DK101 — host reads whose value is frozen at trace time.
_IMPURE_READS = frozenset({
    "os.environ.get", "os.getenv", "time.time", "time.time_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.monotonic",
    "time.monotonic_ns", "datetime.now", "datetime.utcnow", "uuid.uuid4",
})
_CONFIG_ACCESSORS = frozenset({"env_bool", "env_int", "env_float", "env_str"})
#: DK102 — host I/O / side effects that silently run only at trace time.
_IO_CALLS = frozenset({"open", "print", "input"})
_IO_PREFIXES = ("subprocess.", "shutil.", "logging.")
_IO_OS_CALLS = frozenset({
    "os.remove", "os.unlink", "os.makedirs", "os.mkdir", "os.listdir",
    "os.rename", "os.stat", "os.kill", "os.system",
})
# Names that read as container mutation. `update`/`pop`/`setdefault` are
# deliberately absent: optax's pure `tx.update(...)` and pytree `.pop` idioms
# collide with the dict methods and would drown DK105 in false positives.
_MUTATORS = frozenset({
    "append", "extend", "insert", "add", "clear", "remove", "discard",
    "appendleft",
})
_TELE_METHODS = frozenset({"counter", "gauge", "histogram", "span", "event"})


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_wrapper_ref(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)`` reference?"""
    if isinstance(node, ast.Call) and _last(call_name(node.func)) == "partial":
        return bool(node.args) and _is_wrapper_ref(node.args[0])
    name = call_name(node)
    return bool(name) and _last(name) in _WRAPPERS


def _is_lax_combinator(call: ast.Call) -> bool:
    name = call_name(call.func)
    if "." not in name:
        return False
    head, last = name.rsplit(".", 1)
    return last in _LAX_COMBINATORS and head.split(".")[-1] == "lax"


def _collect_traced(mod: Module) -> list:
    """(node, reason) for every function object whose body is traced."""
    defs: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    traced: dict = {}

    def mark(fn_node, reason: str) -> None:
        traced.setdefault(id(fn_node), (fn_node, reason))

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_wrapper_ref(dec) or (
                        isinstance(dec, ast.Call) and _is_wrapper_ref(dec.func)):
                    mark(node, f"decorated with {ast.unparse(dec)}")
        elif isinstance(node, ast.Call):
            if _is_wrapper_ref(node.func):
                wrapper = _last(call_name(node.func)) or "partial"
                cands = node.args[:1]
            elif _is_lax_combinator(node):
                wrapper = call_name(node.func)
                cands = list(node.args) + [kw.value for kw in node.keywords]
            else:
                continue
            for arg in cands:
                if isinstance(arg, ast.Lambda):
                    mark(arg, f"passed to {wrapper}")
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    mark(defs[arg.id], f"passed to {wrapper}")
    return list(traced.values())


def _locals_of(fn) -> set:
    """Every name bound anywhere inside ``fn`` (params, assignments, loop
    targets, nested defs) — mutation of these is internal to the trace."""
    names: set = set()

    def add_target(t) -> None:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                names.add(n.id)

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                names.add(arg.arg)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                add_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr)):
            add_target(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            add_target(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    add_target(item.optional_vars)
        elif isinstance(node, ast.comprehension):
            add_target(node.target)
    return names


def _tele_handles(mod: Module) -> set:
    """Names bound from ``telemetry.get()`` anywhere in the module."""
    handles = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = call_name(node.value.func)
            if _last(name) == "get" and "telemetry" in name.split("."):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        handles.add(t.id)
    return handles


@module_rule(
    RuleInfo("DK101", "impure host read (env/time/random) inside traced code"),
    RuleInfo("DK102", "host I/O or side effect inside traced code"),
    RuleInfo("DK103", "telemetry call inside traced code"),
    RuleInfo("DK104", "non-hashable static argument on a jitted function"),
    RuleInfo("DK105", "traced code mutates enclosing/global state"),
)
def check_jax(mod: Module) -> list:
    out: list = []
    traced = _collect_traced(mod)
    handles = _tele_handles(mod)
    fname = lambda fn: getattr(fn, "name", "<lambda>")  # noqa: E731

    for fn, reason in traced:
        local_names = _locals_of(fn)
        for node in ast.walk(fn):
            line, col = getattr(node, "lineno", fn.lineno), getattr(
                node, "col_offset", 0)
            if isinstance(node, ast.Call):
                name = call_name(node.func)
                last = _last(name)
                root = name.split(".")[0]
                if (name in _IMPURE_READS or last in _CONFIG_ACCESSORS
                        or (root in ("random", "np", "numpy")
                            and "random" in name.split(".")[:-1])
                        or (root == "random" and "." in name)):
                    out.append(Finding(
                        mod.path, line, col, "DK101",
                        f"`{name}()` inside traced `{fname(fn)}` ({reason}): "
                        "the value is frozen at trace time — pass it in as "
                        "an argument or read it before tracing"))
                elif (name in _IO_CALLS or name in _IO_OS_CALLS
                      or name == "time.sleep"
                      or name.startswith(_IO_PREFIXES)
                      or (root == "warnings" and last == "warn")):
                    out.append(Finding(
                        mod.path, line, col, "DK102",
                        f"host I/O `{name}()` inside traced `{fname(fn)}` "
                        f"({reason}): runs once at trace time, never per "
                        "step — use jax.debug.print/callback or hoist it"))
                elif (root == "telemetry" and "." in name) or (
                        root in handles and last in _TELE_METHODS):
                    out.append(Finding(
                        mod.path, line, col, "DK103",
                        f"telemetry call `{name}()` inside traced "
                        f"`{fname(fn)}` ({reason}): records trace-time, not "
                        "run-time — instrument the host loop instead"))
                elif last in _MUTATORS:
                    recv = call_name(node.func)
                    recv_root = recv.split(".")[0]
                    if (recv_root and recv_root not in local_names
                            and recv_root != "self"
                            and recv.count(".") == 1):
                        out.append(Finding(
                            mod.path, line, col, "DK105",
                            f"`{recv}()` inside traced `{fname(fn)}` mutates "
                            f"closed-over `{recv_root}`: happens at trace "
                            "time only — return the value instead"))
            elif isinstance(node, ast.Subscript):
                if call_name(node.value) == "os.environ":
                    out.append(Finding(
                        mod.path, line, col, "DK101",
                        f"`os.environ[...]` inside traced `{fname(fn)}` "
                        f"({reason}): frozen at trace time"))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(Finding(
                    mod.path, line, col, "DK105",
                    f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                    f" {', '.join(node.names)}` inside traced `{fname(fn)}` "
                    f"({reason}): rebinding happens at trace time only"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.append(Finding(
                            mod.path, line, col, "DK105",
                            f"write to `self.{t.attr}` inside traced "
                            f"`{fname(fn)}` ({reason}): object state mutates "
                            "at trace time only — thread it through the "
                            "carry instead"))
    out.extend(_check_static_args(mod))
    return out


def _mutable_default(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    return (isinstance(node, ast.Call)
            and call_name(node.func) in ("list", "dict", "set", "bytearray"))


def _check_static_args(mod: Module) -> list:
    """DK104: ``static_argnums``/``static_argnames`` naming a parameter whose
    default is a mutable (unhashable) literal — every call retraces (or
    raises) instead of hitting the jit cache."""
    out: list = []
    defs: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    def static_kw(call: ast.Call):
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                yield kw

    def check_pair(fn_def, kw, site) -> None:
        params = fn_def.args.args
        defaults = fn_def.args.defaults
        off = len(params) - len(defaults)
        by_index = {off + i: d for i, d in enumerate(defaults)}
        by_name = {params[off + i].arg: d for i, d in enumerate(defaults)}
        vals = (kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value])
        for v in vals:
            if not isinstance(v, ast.Constant):
                continue
            default = (by_index.get(v.value) if isinstance(v.value, int)
                       else by_name.get(v.value))
            if default is not None and _mutable_default(default):
                out.append(Finding(
                    mod.path, site.lineno, site.col_offset, "DK104",
                    f"static arg {v.value!r} of `{fn_def.name}` defaults to "
                    "a mutable (unhashable) value: jit static args must be "
                    "hashable or every call retraces"))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _last(
                call_name(node.func)) in ("jit", "pjit"):
            for kw in static_kw(node):
                if node.args and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in defs:
                    check_pair(defs[node.args[0].id], kw, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_wrapper_ref(dec):
                    for kw in static_kw(dec):
                        check_pair(node, kw, dec)
    return out
