"""Exhaustive-interleaving checker for the distributed planes.

dk-check's lint rules (DK2xx/DK5xx) reason about lock/ordering hazards
*lexically*; this module closes the loop dynamically: it enumerates EVERY
schedule of a small cooperative-thread scenario against the REAL protocol
machines — ``netps.server.PSServer``'s dedup table and epoch fence,
``streaming.journal.OffsetJournal``'s crash-recovery ``resolve()``, and
``netps.hier.AggregatorServer``'s combined-window flush plane — and
asserts the exactly-once and fence-monotonicity invariants in every one.

The concurrency seam is the same one the fleet simulator fills
(``sim.fleet_driver.SimThreadFactory``): scenarios receive a
Thread-signature-compatible factory (``factory(target=..., name=...)``)
and register cooperative threads through it. The one divergence from the
sim is the unit of progress: here a thread's target is a *generator
function* and every ``yield`` is a preemption point, so the explorer —
not wall-clock scheduling — decides the interleaving. Code between two
yields is atomic, which matches the real system exactly when the segment
is one public API call (every ``PSServer._op_*`` runs under the center
lock; every ``OffsetJournal`` method runs under its own lock).

Exploration is stateless-model-checking DFS: each run replays a choice
prefix from a fresh scenario instance, then follows the default policy
(lowest runnable thread) while enqueueing every untaken alternative as a
new prefix. Each complete schedule executes exactly once. A scenario may
opt into *crash points*: at every choice point the explorer also branches
into "the process dies here" (budget 1 — the crash ends the run), after
which the scenario's ``finish()`` performs deterministic recovery and the
final invariants must still hold. RAM state is lost in a crash; the
in-memory ``MemJournal`` "disk" dict and the (separate-process) PS server
survive, exactly mirroring a trainer-process death in the streaming
runtime.

Determinism is load-bearing: scenarios must not branch on wall-clock or
randomness, so a violation's reproducer is just its schedule — the
choice sequence printed with the finding.

Run ``python -m distkeras_tpu.analysis.interleave`` (CI does, budgeted at
120 s) to enumerate all scenarios and exit 1 on any violation;
``--mutate`` seeds a dedup-skipping server mutation and exits 0 only if
the explorer catches it (the checker's own regression test).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Callable, Iterable, List, Optional, Tuple

import numpy as np

CRASH = -1  # schedule token: the modeled process dies at this choice point


# ---------------------------------------------------------------------------
# The cooperative-thread seam (SimThreadFactory-shaped)
# ---------------------------------------------------------------------------

class CoopThread:
    """Cooperative thread over a generator target: ``step()`` advances it
    to the next ``yield``; the public surface (``start`` / ``is_alive`` /
    ``join``) matches what the sim's scheduler expects of a thread."""

    def __init__(self, target: Callable, name: str = "coop"):
        self.name = name
        self._target = target
        self._gen = None
        self._done = False

    def start(self) -> None:
        self._gen = self._target()

    def is_alive(self) -> bool:
        return self._gen is not None and not self._done

    def step(self) -> None:
        try:
            next(self._gen)
        except StopIteration:
            self._done = True

    def kill(self) -> None:
        self._done = True

    def join(self, timeout: Optional[float] = None) -> None:
        return None


class CoopThreadFactory:
    """``thread_factory=`` seam filler, Thread-signature compatible like
    ``SimThreadFactory`` (extra kwargs such as ``daemon`` are accepted
    and ignored); collects the threads for the explorer to schedule."""

    def __init__(self):
        self.threads: List[CoopThread] = []

    def __call__(self, target=None, name: str = "coop",
                 **_kw) -> CoopThread:
        t = CoopThread(target, name=name)
        self.threads.append(t)
        return t


# ---------------------------------------------------------------------------
# Explorer
# ---------------------------------------------------------------------------

class Violation:
    def __init__(self, scenario: str, schedule: Tuple[int, ...],
                 message: str):
        self.scenario = scenario
        self.schedule = schedule
        self.message = message

    def __repr__(self) -> str:
        sched = ",".join("X" if c == CRASH else str(c)
                         for c in self.schedule)
        return f"[{self.scenario}] schedule=({sched}): {self.message}"


class ExploreResult:
    def __init__(self, name: str):
        self.name = name
        self.complete = 0       # schedules run to completion
        self.crashed = 0        # schedules ending in a modeled crash
        self.transitions = 0    # atomic steps executed across all runs
        self.violations: List[Violation] = []

    @property
    def schedules(self) -> int:
        return self.complete + self.crashed


def explore(make_scenario: Callable, crash_points: bool = False,
            max_schedules: Optional[int] = None) -> ExploreResult:
    """DFS over all interleavings of ``make_scenario()``'s threads.

    Each pending entry is a choice prefix; a run replays it, then follows
    the lowest-runnable-thread policy, pushing every untaken alternative
    (and, when ``crash_points``, a CRASH branch) at each fresh choice
    point. Invariants are checked after every step and once more after
    ``finish()`` — so safety holds in every reachable state, not just at
    quiescence."""
    result = ExploreResult(getattr(make_scenario, "name", None)
                           or make_scenario().name)
    pending: List[Tuple[int, ...]] = [()]
    while pending:
        if max_schedules is not None and result.schedules >= max_schedules:
            break
        prefix = pending.pop()
        scen = make_scenario()
        factory = CoopThreadFactory()
        scen.build(factory)
        threads = factory.threads
        for t in threads:
            t.start()
        trace: List[int] = []
        crashed = False
        try:
            while True:
                runnable = [i for i, t in enumerate(threads)
                            if t.is_alive()]
                if not runnable:
                    break
                depth = len(trace)
                if depth < len(prefix):
                    choice = prefix[depth]
                else:
                    choice = runnable[0]
                    for alt in runnable[1:]:
                        pending.append(tuple(trace) + (alt,))
                    if crash_points and depth > 0:
                        pending.append(tuple(trace) + (CRASH,))
                if choice == CRASH:
                    crashed = True
                    for t in threads:
                        t.kill()
                    scen.crash()
                    trace.append(CRASH)
                    break
                threads[choice].step()
                trace.append(choice)
                result.transitions += 1
                for msg in scen.check_step():
                    result.violations.append(
                        Violation(scen.name, tuple(trace), msg))
            scen.finish()
            for msg in scen.check_final():
                result.violations.append(
                    Violation(scen.name, tuple(trace), msg))
        finally:
            scen.close()
        if crashed:
            result.crashed += 1
        else:
            result.complete += 1
    return result


# ---------------------------------------------------------------------------
# Scenario base + shared server plumbing
# ---------------------------------------------------------------------------

class Scenario:
    """One model-checked configuration: ``build`` registers cooperative
    threads via the factory seam; ``check_step`` runs after every atomic
    step; ``crash`` models process death (RAM lost, durable state kept);
    ``finish`` is deterministic recovery; ``check_final`` asserts the
    end-to-end invariants; ``close`` releases OS resources."""

    name = "scenario"

    def build(self, thread_factory: CoopThreadFactory) -> None:
        raise NotImplementedError

    def check_step(self) -> List[str]:
        return []

    def crash(self) -> None:
        return None

    def finish(self) -> None:
        return None

    def check_final(self) -> List[str]:
        return []

    def close(self) -> None:
        return None


def _new_server(server_cls=None, **kw):
    """A real ``PSServer`` with a 1-tensor center, never ``serve()``d —
    scenarios drive ``_dispatch`` directly, so every op runs the genuine
    handler (lock, dedup table, fence, commit_log) minus the socket hop."""
    from distkeras_tpu.netps.server import PSServer

    cls = server_cls or PSServer
    return cls(center=[np.zeros(4, np.float32)], lease_s=3600.0, **kw)


def _close_server(srv) -> None:
    try:
        srv._listener.close()
    except OSError:
        pass
    uds = getattr(srv, "_uds_listener", None)
    if uds is not None:
        try:
            uds.close()
        except OSError:
            pass


def _join(srv, wid: int) -> dict:
    from distkeras_tpu.netps import wire

    reply, _ = srv._dispatch(wire.OP_JOIN, {"worker_id": wid}, [])
    assert reply.get("ok"), f"setup join failed: {reply}"
    return reply


def _commit(srv, wid: int, seq: int) -> dict:
    """An empty-delta commit: ``validate_delta([])`` is falsy so no
    backend resolve happens, but ``_fold_locked`` still runs the full
    dedup / commit_log / last_seq bookkeeping — the machine under test."""
    from distkeras_tpu.netps import wire

    reply, _ = srv._dispatch(
        wire.OP_COMMIT, {"worker_id": wid, "seq": seq, "pulled": 0}, [])
    return reply


def _fold_pairs(srv) -> List[Tuple[int, int]]:
    return [(w, s) for (w, s, _st) in srv.commit_log]


# ---------------------------------------------------------------------------
# Scenario 1: the dedup table (exactly-once under retransmit)
# ---------------------------------------------------------------------------

class DedupScenario(Scenario):
    """2 workers x 3 commits, every commit sent twice (the lost-ACK
    retransmit — serial per worker, exactly like the real client's
    retry-then-advance loop), all cross-worker interleavings.

    Invariants: the commit_log never holds two folds of one ``(wid,
    seq)``; ``last_seq`` is per-worker monotone; at quiescence every
    commit folded exactly once and exactly one of its two sends was
    answered ``applied``."""

    name = "dedup"
    WORKERS = 2
    COMMITS = 3

    def __init__(self, server_cls=None):
        self._server_cls = server_cls

    def build(self, thread_factory: CoopThreadFactory) -> None:
        self.srv = _new_server(self._server_cls)
        self.wids = list(range(self.WORKERS))
        for w in self.wids:
            _join(self.srv, w)
        self.replies: List[Tuple[int, int, int, dict]] = []
        self._prev_last_seq: dict = {}
        for w in self.wids:
            thread_factory(target=self._worker(w), name=f"w{w}")

    def _worker(self, wid: int):
        # original, then lost-ACK retransmit, serially per worker
        sends = [(seq, attempt) for seq in range(self.COMMITS)
                 for attempt in (0, 1)]

        def script():
            for i, (seq, attempt) in enumerate(sends):
                if i:
                    yield  # preemption point BETWEEN sends, no trailing one
                reply = _commit(self.srv, wid, seq)
                self.replies.append((wid, seq, attempt, reply))
        return script

    def check_step(self) -> List[str]:
        out = []
        pairs = _fold_pairs(self.srv)
        if len(set(pairs)) != len(pairs):
            out.append(f"duplicate fold in commit_log: {pairs}")
        for w, s in self.srv._last_seq.items():
            if s < self._prev_last_seq.get(w, -1):
                out.append(f"last_seq regressed for worker {w}: "
                           f"{self._prev_last_seq[w]} -> {s}")
            self._prev_last_seq[w] = s
        return out

    def check_final(self) -> List[str]:
        out = []
        folds = _fold_pairs(self.srv)
        for w in self.wids:
            for seq in range(self.COMMITS):
                n = folds.count((w, seq))
                if n != 1:
                    out.append(f"(wid={w}, seq={seq}) folded {n} times, "
                               "want exactly 1")
                applied = sum(1 for rw, rs, _a, r in self.replies
                              if (rw, rs) == (w, seq) and r.get("applied"))
                if applied != 1:
                    out.append(f"(wid={w}, seq={seq}) answered applied "
                               f"{applied} times across 2 sends, want 1")
        want = self.WORKERS * self.COMMITS
        if self.srv.commits_total != want:
            out.append(f"commits_total={self.srv.commits_total}, "
                       f"want {want}")
        return out

    def close(self) -> None:
        _close_server(self.srv)


class _NoDedupServer:
    """Seeded mutant: forgets the dedup table entry before every commit,
    so a retransmit re-folds — the regression the explorer must catch.
    Built lazily (subclassing PSServer at import time would import numpy
    server machinery even for pure-lint callers)."""

    _cls = None

    def __new__(cls, *a, **kw):
        from distkeras_tpu.netps.server import PSServer

        if cls._cls is None:
            class NoDedup(PSServer):
                def _op_commit(self, header, arrays):
                    wid = header.get("worker_id")
                    if wid is not None:
                        self._last_seq.pop(int(wid), None)
                    return PSServer._op_commit(self, header, arrays)
            cls._cls = NoDedup
        return cls._cls(*a, **kw)


# ---------------------------------------------------------------------------
# Scenario 2: the epoch fence (zombie primary can never fold again)
# ---------------------------------------------------------------------------

class FenceScenario(Scenario):
    """2 workers x 4 commits racing a fencer that raises the epoch three
    times (a failover storm) — 11!/(4!4!3!) = 11550 schedules.

    Invariants: ``epoch`` never decreases; ``_fenced`` never unsets; once
    any fence is accepted the commit_log is frozen (a fenced ex-primary
    answers ``not_primary`` and must never fold again); an ``applied``
    commit reply can only have been issued by an unfenced server."""

    name = "fence"
    WORKERS = 2
    COMMITS = 4
    FENCE_EPOCHS = (1, 2, 3)

    def build(self, thread_factory: CoopThreadFactory) -> None:
        self.srv = _new_server()
        self.wids = list(range(self.WORKERS))
        for w in self.wids:
            _join(self.srv, w)
        self.commit_replies: List[Tuple[int, int, bool, dict]] = []
        self.fence_replies: List[Tuple[int, dict]] = []
        self._prev_epoch = self.srv.epoch
        self._was_fenced = False
        self._frozen_log_len: Optional[int] = None
        for w in self.wids:
            thread_factory(target=self._worker(w), name=f"w{w}")
        thread_factory(target=self._fencer, name="fencer")

    def _worker(self, wid: int):
        def script():
            for seq in range(self.COMMITS):
                if seq:
                    yield
                fenced_before = self.srv._fenced
                reply = _commit(self.srv, wid, seq)
                self.commit_replies.append((wid, seq, fenced_before, reply))
        return script

    def _fencer(self):
        from distkeras_tpu.netps import wire

        for i, epoch in enumerate(self.FENCE_EPOCHS):
            if i:
                yield
            reply, _ = self.srv._dispatch(wire.OP_FENCE, {"epoch": epoch},
                                          [])
            self.fence_replies.append((epoch, reply))

    def check_step(self) -> List[str]:
        out = []
        if self.srv.epoch < self._prev_epoch:
            out.append(f"epoch regressed: {self._prev_epoch} -> "
                       f"{self.srv.epoch}")
        self._prev_epoch = self.srv.epoch
        if self._was_fenced and not self.srv._fenced:
            out.append("fence lifted: _fenced went True -> False")
        if self.srv._fenced and self._frozen_log_len is None:
            self._frozen_log_len = len(self.srv.commit_log)
        self._was_fenced = self.srv._fenced or self._was_fenced
        if (self._frozen_log_len is not None
                and len(self.srv.commit_log) != self._frozen_log_len):
            out.append(
                f"fold after fence: commit_log grew "
                f"{self._frozen_log_len} -> {len(self.srv.commit_log)}")
        return out

    def check_final(self) -> List[str]:
        out = []
        pairs = _fold_pairs(self.srv)
        if len(set(pairs)) != len(pairs):
            out.append(f"duplicate fold in commit_log: {pairs}")
        for wid, seq, fenced_before, reply in self.commit_replies:
            if reply.get("applied") and fenced_before:
                out.append(f"(wid={wid}, seq={seq}) applied by an "
                           "already-fenced server")
            if fenced_before and "error" not in reply:
                out.append(f"(wid={wid}, seq={seq}) got a non-error reply "
                           "from a fenced server")
        accepted = [e for e, r in self.fence_replies if r.get("fenced")]
        if not accepted:
            out.append("no fence accepted despite epochs above the "
                       "server's")
        if not self.srv._fenced:
            out.append("server not fenced at quiescence")
        return out

    def close(self) -> None:
        _close_server(self.srv)


# ---------------------------------------------------------------------------
# Scenario 3: the offset journal (crash-recovery resolve(), exactly-once)
# ---------------------------------------------------------------------------

class MemJournal:
    """``OffsetJournal`` persisted to an in-memory dict standing in for
    the disk: a crash drops the journal OBJECT (RAM), the dict survives
    (the fsynced file). Overrides exactly the two seams the real class
    isolates persistence behind. Built lazily for the same import-cost
    reason as ``_NoDedupServer``."""

    _cls = None

    def __new__(cls, disk: dict):
        import json

        from distkeras_tpu.streaming.journal import OffsetJournal

        if cls._cls is None:
            class _MemJournal(OffsetJournal):
                def __init__(self, disk):
                    self._disk = disk
                    OffsetJournal.__init__(self, "<mem-journal>")

                def _persist_locked(self):
                    self._disk["state"] = json.dumps(self._snapshot())

                def _load_one(self, path):
                    state = self._disk.get("state")
                    return json.loads(state) if state else None
            cls._cls = _MemJournal
        return cls._cls(disk)


class JournalScenario(Scenario):
    """The streaming plane's two-phase commit under every interleaving
    AND every crash point: 2 workers each ingest 2 records through the
    real ``intent -> commit RPC -> committed`` triple against a real
    ``PSServer`` and a shared ``MemJournal``. A crash kills both workers
    and the journal object; recovery loads a fresh journal from the
    surviving dict, runs the real ``resolve()`` against the server's
    surviving dedup evidence, then re-reads and re-sends whatever did not
    land — under fresh seqs from the real re-join's ``last_seq``.

    Invariants: after recovery every record offset folded into the
    center EXACTLY once (no loss, no double-train) and the journal holds
    all offsets committed with an empty out-of-order set."""

    name = "journal"
    WORKERS = 2
    RECORDS = 2  # offsets per worker

    def build(self, thread_factory: CoopThreadFactory) -> None:
        self.srv = _new_server()
        self.wids = list(range(self.WORKERS))
        for w in self.wids:
            _join(self.srv, w)
        self.disk: dict = {}
        self.journal = MemJournal(self.disk)
        self.offsets = {w: [w * self.RECORDS + i
                            for i in range(self.RECORDS)]
                        for w in self.wids}
        self.total = self.WORKERS * self.RECORDS
        #: god's-eye (wid, seq) -> offset map — the harness's view, NOT
        #: process RAM, so it survives the modeled crash for checking.
        self.sent: dict = {}
        self.next_seq = {w: 0 for w in self.wids}
        for w in self.wids:
            thread_factory(target=self._worker(w), name=f"w{w}")

    def _worker(self, wid: int):
        def script():
            for i, offset in enumerate(self.offsets[wid]):
                if i:
                    yield
                seq = self.next_seq[wid]
                self.next_seq[wid] += 1
                self.journal.intent(wid, seq, offset)
                self.sent[(wid, seq)] = offset
                yield
                _commit(self.srv, wid, seq)
                yield
                self.journal.committed(wid, offset)
        return script

    def check_step(self) -> List[str]:
        pairs = _fold_pairs(self.srv)
        if len(set(pairs)) != len(pairs):
            return [f"duplicate fold in commit_log: {pairs}"]
        return []

    def crash(self) -> None:
        self.journal = None  # RAM gone; self.disk (the "file") survives

    def finish(self) -> None:
        """Deterministic recovery — the streaming runtime's resume path
        in miniature. Runs on clean completion too (provably a no-op:
        no surviving intents, nothing uncommitted)."""
        journal = MemJournal(self.disk)
        journal.load()
        journal.resolve(
            {w: self.srv._last_seq.get(w, -1) for w in self.wids})
        done = journal.committed_offsets_upto(self.total)
        for w in self.wids:
            # Re-join recovers the seq watermark exactly like a restarted
            # trainer: dedup would eat any commit at or below last_seq.
            seq = int(_join(self.srv, w)["last_seq"]) + 1
            for offset in self.offsets[w]:
                if offset in done:
                    continue
                journal.intent(w, seq, offset)
                self.sent[(w, seq)] = offset
                _commit(self.srv, w, seq)
                journal.committed(w, offset)
                seq += 1
        self.journal = journal

    def check_final(self) -> List[str]:
        out = []
        fold_count = {o: 0 for w in self.wids for o in self.offsets[w]}
        for pair in _fold_pairs(self.srv):
            offset = self.sent.get(pair)
            if offset is None:
                out.append(f"fold of a never-sent commit: {pair}")
            else:
                fold_count[offset] += 1
        for offset, n in sorted(fold_count.items()):
            if n != 1:
                out.append(f"offset {offset} folded {n} times, want "
                           "exactly 1 (exactly-once broken)")
        done = self.journal.committed_offsets_upto(self.total)
        if done != set(range(self.total)):
            out.append(f"journal committed {sorted(done)}, want all of "
                       f"0..{self.total - 1}")
        if self.journal.skip_offsets():
            out.append("out-of-order set non-empty at quiescence: "
                       f"{sorted(self.journal.skip_offsets())}")
        if self.journal._intents:
            out.append(f"surviving intents after recovery: "
                       f"{self.journal._intents}")
        return out

    def close(self) -> None:
        _close_server(self.srv)


# ---------------------------------------------------------------------------
# Scenario 4: the aggregation tree's flush plane (no window folded twice
# at the root)
# ---------------------------------------------------------------------------

class TreeFlushScenario(Scenario):
    """An aggregator's flush racing its children's retransmits AND an
    upstream eviction: 2 children each send one commit twice (the
    lost-ACK retransmit) into a real ``AggregatorServer`` whose uplink
    dials a real, served root ``PSServer``; a flusher thread forwards
    combined windows (``_flush_once(force=True)`` — the tree node's
    drain path runs the same code); an evictor revokes the aggregator's
    root lease once (``revoke()`` — the deterministic stand-in for a
    lease lapse), so a flush can land evicted at any point relative to
    the absorbs. 7 steps, 630 schedules.

    The aggregator is never ``start()``ed (no real flusher thread, no
    heartbeats), so the explorer owns every interleaving; uplink RPCs
    are synchronous inside one atomic step, so the root is quiescent at
    every check point.

    Invariants: no child ``(wid, seq)`` double-absorbed at the
    aggregator; no combined window folded twice at the root (root
    commit_log pair uniqueness AND ``root.commits_total ==
    agg.forwarded``); the window-conservation ledger balances at EVERY
    step (``absorbed == forwarded_commits + lost_commits + open``) — an
    evicted flush must show up as a counted loss, never a silent gap,
    and never a re-fold."""

    name = "tree_flush"
    WORKERS = 2
    COMMITS = 1  # per child, each sent twice
    FLUSHES = 2

    def build(self, thread_factory: CoopThreadFactory) -> None:
        from distkeras_tpu.netps.hier import AggregatorServer

        self.root = _new_server()
        self.root.start()
        self.agg = AggregatorServer(self.root.endpoint, lease_s=3600.0,
                                    flush_interval=3600.0)
        self.wids = list(range(self.WORKERS))
        for w in self.wids:
            _join(self.agg, w)
        self._prev_root_total = self.root.commits_total
        for w in self.wids:
            thread_factory(target=self._child(w), name=f"c{w}")
        thread_factory(target=self._flusher, name="flusher")
        thread_factory(target=self._evictor, name="evictor")

    def _child(self, wid: int):
        # original then lost-ACK retransmit, serially — the real client's
        # retry-then-advance loop against the AGGREGATOR, not the root
        sends = [(seq, attempt) for seq in range(self.COMMITS)
                 for attempt in (0, 1)]

        def script():
            for i, (seq, _attempt) in enumerate(sends):
                if i:
                    yield
                _commit(self.agg, wid, seq)
        return script

    def _flusher(self):
        for i in range(self.FLUSHES):
            if i:
                yield
            self.agg._flush_once(force=True)

    def _evictor(self):
        # The aggregator's root lease lapses mid-run: membership dropped
        # NOW, its next uplink RPC answers evicted (the client re-joins,
        # the in-flight window is a counted loss — never a retransmit).
        self.root.revoke(self.agg._up.worker_id)
        return
        yield  # pragma: no cover - makes the target a generator fn

    def check_step(self) -> List[str]:
        out = []
        agg_pairs = _fold_pairs(self.agg)
        if len(set(agg_pairs)) != len(agg_pairs):
            out.append(f"child commit double-absorbed: {agg_pairs}")
        root_pairs = _fold_pairs(self.root)
        if len(set(root_pairs)) != len(root_pairs):
            out.append(f"window folded twice at root: {root_pairs}")
        if self.root.commits_total < self._prev_root_total:
            out.append(f"root commits_total regressed: "
                       f"{self._prev_root_total} -> "
                       f"{self.root.commits_total}")
        self._prev_root_total = self.root.commits_total
        ledger = (self.agg.forwarded_commits + self.agg.lost_commits
                  + self.agg._acc_count)
        if self.agg.absorbed != ledger:
            out.append(f"conservation broken: absorbed={self.agg.absorbed} "
                       f"!= forwarded {self.agg.forwarded_commits} + lost "
                       f"{self.agg.lost_commits} + open "
                       f"{self.agg._acc_count}")
        return out

    def finish(self) -> None:
        # The tree node's close-path drain: one forced flush empties the
        # open window (forwarded, or a counted loss if it lands evicted).
        self.agg._flush_once(force=True)

    def check_final(self) -> List[str]:
        out = []
        want = self.WORKERS * self.COMMITS
        agg_pairs = _fold_pairs(self.agg)
        for w in self.wids:
            for seq in range(self.COMMITS):
                n = agg_pairs.count((w, seq))
                if n != 1:
                    out.append(f"child (wid={w}, seq={seq}) absorbed {n} "
                               "times, want exactly 1")
        if self.agg.absorbed != want:
            out.append(f"absorbed={self.agg.absorbed}, want {want}")
        if self.agg._acc_count:
            out.append(f"open window survived the forced drain: "
                       f"{self.agg._acc_count} commits")
        if (self.agg.forwarded_commits + self.agg.lost_commits
                != self.agg.absorbed):
            out.append(f"final ledger: forwarded {self.agg.forwarded_commits}"
                       f" + lost {self.agg.lost_commits} != absorbed "
                       f"{self.agg.absorbed}")
        if self.root.commits_total != self.agg.forwarded:
            out.append(f"root folded {self.root.commits_total} combined "
                       f"commits, aggregator forwarded "
                       f"{self.agg.forwarded} — a window folded twice or "
                       "vanished")
        root_pairs = _fold_pairs(self.root)
        if len(set(root_pairs)) != len(root_pairs):
            out.append(f"window folded twice at root: {root_pairs}")
        return out

    def close(self) -> None:
        import socket

        try:
            self.agg._up.leave()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self.agg._up.close()
        _close_server(self.agg)
        # Poke the root's accept loop awake before joining it — without
        # this every schedule pays the full accept-poll interval in
        # close(), and 630 schedules of it blows the CI budget.
        self.root._stop.set()
        try:
            host, port = self.root.endpoint.rsplit(":", 1)
            socket.create_connection((host, int(port)), timeout=1.0).close()
        except OSError:
            pass
        self.root.close()


# ---------------------------------------------------------------------------
# Suite + CLI
# ---------------------------------------------------------------------------

SCENARIOS = {
    "dedup": lambda: (DedupScenario, False),
    "fence": lambda: (FenceScenario, False),
    "journal": lambda: (JournalScenario, True),
    "tree_flush": lambda: (TreeFlushScenario, False),
}


def run_suite(names: Optional[Iterable[str]] = None,
              mutate: bool = False) -> List[ExploreResult]:
    results = []
    for name in (names or sorted(SCENARIOS)):
        cls, crash_points = SCENARIOS[name]()
        if mutate and name == "dedup":
            results.append(explore(lambda: DedupScenario(_NoDedupServer),
                                   crash_points=False))
        else:
            results.append(explore(cls, crash_points=crash_points))
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distkeras_tpu.analysis.interleave",
        description="exhaustively model-check the dedup / fence / "
                    "journal / tree-flush machines across every thread "
                    "interleaving")
    parser.add_argument("--scenario", action="append", default=None,
                        choices=sorted(SCENARIOS),
                        help="run only this scenario (repeatable)")
    parser.add_argument("--mutate", action="store_true",
                        help="seed the no-dedup server mutation; exits 0 "
                             "only if the explorer CATCHES it")
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    results = run_suite(args.scenario, mutate=args.mutate)
    wall = time.monotonic() - t0
    total_sched = sum(r.schedules for r in results)
    total_viol = sum(len(r.violations) for r in results)
    for r in results:
        print(f"interleave[{r.name}]: {r.complete} complete schedules, "
              f"{r.crashed} crash points, {r.transitions} transitions, "
              f"{len(r.violations)} violation(s)")
        for v in r.violations[:10]:
            print(f"  {v!r}")
        if len(r.violations) > 10:
            print(f"  ... and {len(r.violations) - 10} more")
    print(f"interleave: state space = {total_sched} schedules "
          f"({sum(r.transitions for r in results)} transitions) "
          f"in {wall:.1f}s")
    if args.mutate:
        caught = total_viol > 0
        print("interleave: seeded dedup mutation "
              + ("CAUGHT" if caught else "MISSED"))
        return 0 if caught else 1
    return 1 if total_viol else 0


if __name__ == "__main__":
    sys.exit(main())
