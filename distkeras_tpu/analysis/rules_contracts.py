"""DK6xx — contract-registry cross-checks (telemetry names, fault kinds).

The DK30x pattern (declare once, generate docs, lint the drift) applied
to the two other stringly-typed contract surfaces:

* **DK601** — a ``telemetry.counter/gauge/histogram/span`` name literal
  not declared in :mod:`distkeras_tpu.telemetry.registry`: undeclared
  names are invisible to the OBSERVABILITY tables and to dashboards
  keyed on the registry. F-strings check their constant lead against the
  registry's ``dynamic`` prefixes.
* **DK602** — metric registry/docs drift: a registered metric absent
  from the ``docs/`` tables, or a ``<!-- dk-metric:begin -->`` block
  whose content no longer matches the registry rendering (fix with
  ``python -m distkeras_tpu.analysis --write-metric-docs``).
* **DK603** — fault-kind drift between ``resilience/faults.py``
  (``_KINDS`` / ``_NET_KINDS``) and the RESILIENCE.md fault tables: an
  implemented kind with no documented row, or a documented entry no
  ``FaultPlan`` accepts. (``*_r@F`` documents every ``_r`` reply
  variant; ``seed`` is plan syntax, not a kind.)

DK602/DK603 only fire when the scan includes the real registry /
faults module, so the fixture corpus stays naturally exempt (the DK303
pattern).
"""

from __future__ import annotations

import ast
import glob
import os
import re

from distkeras_tpu.analysis.core import (
    Finding, Module, RuleInfo, call_name, module_rule, project_rule)

_METRIC_KINDS = frozenset({"counter", "gauge", "histogram", "span"})
_TELEMETRY_RECEIVERS = frozenset({"telemetry", "tele", "tel", "t", "_t"})
_REGISTRY_SUFFIX = os.path.join("telemetry", "registry.py")
_FAULTS_SUFFIX = os.path.join("resilience", "faults.py")

#: backtick token in RESILIENCE.md: the kind name before ``@``/``=``.
_FAULT_TOKEN_RE = re.compile(r"`(\*?[a-z][a-z0-9_]*|\*_r)(?:@[^`]*|=[^`]*)?`")


def _registry():
    from distkeras_tpu.telemetry import registry

    return registry


def _metric_call(node: ast.Call):
    """(kind, name_node) when this is a telemetry name-taking call with a
    literal first argument; None otherwise."""
    name = call_name(node.func)
    if not name:
        return None
    parts = name.split(".")
    kind = parts[-1]
    if kind not in _METRIC_KINDS:
        return None
    if len(parts) > 1 and parts[-2] not in _TELEMETRY_RECEIVERS:
        return None
    if not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return kind, arg
    if isinstance(arg, ast.JoinedStr):
        return kind, arg
    return None


def _joined_lead(node: ast.JoinedStr) -> str:
    lead = []
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            lead.append(part.value)
        else:
            break
    return "".join(lead)


@module_rule(
    RuleInfo("DK601", "telemetry name not declared in telemetry/registry"),
)
def check_metric_names(mod: Module) -> list:
    if os.path.normpath(mod.path).endswith(_REGISTRY_SUFFIX):
        return []
    reg = _registry()
    out: list = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = _metric_call(node)
        if hit is None:
            continue
        kind, arg = hit
        if isinstance(arg, ast.Constant):
            if not reg.declared(kind, arg.value):
                out.append(Finding(
                    mod.path, arg.lineno, arg.col_offset, "DK601",
                    f"{kind} name `{arg.value!r}` is not declared in "
                    "telemetry/registry.py: undeclared metrics are "
                    "invisible to the OBSERVABILITY tables"))
        else:
            lead = _joined_lead(arg)
            if not reg.declared_prefix(kind, lead):
                out.append(Finding(
                    mod.path, arg.lineno, arg.col_offset, "DK601",
                    f"dynamic {kind} name (constant lead `{lead!r}`) "
                    "matches no dynamic=True prefix in "
                    "telemetry/registry.py: declare the prefix"))
    return out


def _docs_dir_for(mod_path: str) -> str:
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(mod_path)))
    return os.path.join(os.path.dirname(pkg_root), "docs")


@project_rule(
    RuleInfo("DK602", "metric docs table out of sync with the registry"),
)
def check_metric_docs(modules) -> list:
    reg_mod = next((m for m in modules if os.path.normpath(m.path)
                    .endswith(_REGISTRY_SUFFIX)), None)
    if reg_mod is None:
        return []
    docs_dir = _docs_dir_for(reg_mod.path)
    if not os.path.isdir(docs_dir):
        return []
    reg = _registry()
    docs: dict = {}
    for path in sorted(glob.glob(os.path.join(docs_dir, "*.md"))):
        with open(path, encoding="utf-8") as f:
            docs[path] = f.read()
    out: list = []

    def decl_line(name: str) -> int:
        for i, line in enumerate(reg_mod.source.splitlines(), 1):
            if f'"{name}"' in line:
                return i
        return 1

    blob = "\n".join(docs.values())
    for m in reg.iter_metrics():
        if f"`{m.name}`" not in blob and f"`{m.name}*`" not in blob:
            out.append(Finding(
                reg_mod.path, decl_line(m.name), 0, "DK602",
                f"metric `{m.name}` is registered but appears in no "
                "docs/*.md table: run `python -m distkeras_tpu.analysis "
                "--write-metric-docs`"))
    for path, text in docs.items():
        try:
            fresh = reg.splice_metric_docs(text)
        except ValueError:
            continue
        if fresh != text:
            out.append(Finding(
                reg_mod.path, 1, 0, "DK602",
                f"{os.path.basename(path)} metric table is stale vs the "
                "registry: run `python -m distkeras_tpu.analysis "
                "--write-metric-docs`"))
    return out


def _parse_kind_sets(mod: Module) -> dict:
    """{set_name: (kinds, line)} for _KINDS / _NET_KINDS frozensets."""
    out: dict = {}
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(n in ("_KINDS", "_NET_KINDS") for n in names):
            continue
        val = node.value
        elts = []
        if (isinstance(val, ast.Call) and val.args
                and call_name(val.func) in ("frozenset", "set")):
            val = val.args[0]
        if isinstance(val, (ast.Set, ast.Tuple, ast.List)):
            elts = [e.value for e in val.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        out[names[0]] = (frozenset(elts), node.lineno)
    return out


@project_rule(
    RuleInfo("DK603", "fault kinds drift from the RESILIENCE.md tables"),
)
def check_fault_kinds(modules) -> list:
    faults_mod = next((m for m in modules if os.path.normpath(m.path)
                       .endswith(_FAULTS_SUFFIX)), None)
    if faults_mod is None:
        return []
    doc_path = os.path.join(_docs_dir_for(faults_mod.path),
                            "RESILIENCE.md")
    if not os.path.isfile(doc_path):
        return []
    sets = _parse_kind_sets(faults_mod)
    code_kinds = frozenset().union(*(k for k, _ in sets.values())) \
        if sets else frozenset()
    if not code_kinds:
        return []
    with open(doc_path, encoding="utf-8") as f:
        doc = f.read()
    doc_kinds = set()
    table_kinds: dict = {}   # token -> first doc line (fault-table rows)
    for i, line in enumerate(doc.splitlines(), 1):
        tokens = _FAULT_TOKEN_RE.findall(line)
        doc_kinds.update(tokens)
        if line.lstrip().startswith("|"):
            first_cell = line.split("|")[1] if "|" in line else ""
            for tok in _FAULT_TOKEN_RE.findall(first_cell):
                # only @/= entry syntax marks a fault-plan row
                if re.search(rf"`{re.escape(tok)}[@=]", first_cell):
                    table_kinds.setdefault(tok, i)
    out: list = []
    for name, (kinds, line) in sorted(sets.items()):
        for kind in sorted(kinds):
            covered = (kind in doc_kinds
                       or (kind.endswith("_r") and "*_r" in doc_kinds
                           and kind[:-2] in doc_kinds))
            if not covered:
                out.append(Finding(
                    faults_mod.path, line, 0, "DK603",
                    f"fault kind `{kind}` ({name}) has no row in "
                    "docs/RESILIENCE.md: every injectable fault documents "
                    "its recovery path there"))
    for tok, line in sorted(table_kinds.items()):
        if tok in ("seed", "*_r") or tok in code_kinds:
            continue
        out.append(Finding(
            faults_mod.path, 1, 0, "DK603",
            f"docs/RESILIENCE.md line {line} documents fault `{tok}` "
            "but no FaultPlan accepts it: stale docs row"))
    return out
