"""DK3xx — environment/config discipline lints.

The ``DKTPU_*`` surface is the framework's operational API; PR 1/2 scattered
34+ reads across the package. These rules pin it to one home:

* **DK301** — any ``os.environ`` / ``os.getenv`` use outside
  ``runtime/config.py``: read through the typed registry accessors
  (``env_bool``/``env_int``/``env_float``/``env_str``) instead.
* **DK302** — a ``DKTPU_*`` name (in any string literal, docstrings
  included) that is not declared in ``ENV_REGISTRY``: undeclared knobs are
  invisible to docs and to ``env_*`` type checking.
* **DK303** — registry/docs drift: a registered variable absent from the
  ``docs/`` tables, or a ``<!-- dk-env:begin -->`` table block whose content
  no longer matches the registry rendering (fix with
  ``python -m distkeras_tpu.analysis --write-env-docs``).
"""

from __future__ import annotations

import ast
import glob
import os
import re

from distkeras_tpu.analysis.core import (
    Finding, Module, RuleInfo, call_name, module_rule, project_rule)

_CONFIG_SUFFIX = os.path.join("runtime", "config.py")
_DKTPU_RE = re.compile(r"\bDKTPU_[A-Z][A-Z0-9_]*\b")


def _registry_names() -> frozenset:
    from distkeras_tpu.runtime import config

    return frozenset(config.ENV_REGISTRY)


def _is_config_module(path: str) -> bool:
    return os.path.normpath(path).endswith(_CONFIG_SUFFIX)


@module_rule(
    RuleInfo("DK301", "os.environ read outside runtime/config.py"),
    RuleInfo("DK302", "undeclared DKTPU_* environment variable"),
)
def check_env_discipline(mod: Module) -> list:
    out: list = []
    if not _is_config_module(mod.path):
        seen_lines: set = set()
        for node in ast.walk(mod.tree):
            hit = None
            if isinstance(node, (ast.Attribute, ast.Name)):
                name = call_name(node)
                if name == "os.environ":
                    hit = "`os.environ`"
            if isinstance(node, ast.Call):
                name = call_name(node.func)
                if name in ("os.getenv", "os.putenv", "os.unsetenv"):
                    hit = f"`{name}()`"
            if hit and node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, "DK301",
                    f"{hit} outside runtime/config.py: declare the variable "
                    "in ENV_REGISTRY and read it through "
                    "config.env_bool/env_int/env_float/env_str"))
        registered = _registry_names()

        def flag(node, name: str) -> None:
            if name not in registered:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, "DK302",
                    f"`{name}` is not declared in "
                    "runtime.config.ENV_REGISTRY: undeclared env "
                    "vars bypass typing and the docs tables"))

        fstring_parts: set = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.JoinedStr):
                # constant parts of an f-string never reach ast.Constant
                # below (3.12+ folds them into the JoinedStr) — check the
                # resolvable text and remember the parts we covered.
                for i, part in enumerate(node.values):
                    if (isinstance(part, ast.Constant)
                            and isinstance(part.value, str)):
                        fstring_parts.add(id(part))
                        for name in _DKTPU_RE.findall(part.value):
                            flag(part, name)
                        # f"DKTPU_{name}": a bare prefix flowing into a
                        # formatted value builds the name at runtime.
                        if (re.search(r"DKTPU_[A-Z0-9_]*$", part.value)
                                and i + 1 < len(node.values)
                                and isinstance(node.values[i + 1],
                                               ast.FormattedValue)):
                            out.append(Finding(
                                mod.path, part.lineno, part.col_offset,
                                "DK302",
                                "f-string builds a DKTPU_* env var name "
                                "at runtime: no registry entry can ever "
                                "match it — construct the full literal "
                                "and declare it"))
            elif (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Add)):
                # `"DKTPU_" + name` concatenation: when both sides are
                # constants the full name is checkable; a dynamic tail
                # means the variable can never be matched to the registry
                # at all — flag the construction itself.
                left, right = node.left, node.right
                if (isinstance(left, ast.Constant)
                        and isinstance(left.value, str)
                        and re.fullmatch(r"DKTPU_[A-Z0-9_]*",
                                         left.value)):
                    if (isinstance(right, ast.Constant)
                            and isinstance(right.value, str)):
                        for name in _DKTPU_RE.findall(
                                left.value + right.value):
                            flag(node, name)
                    else:
                        out.append(Finding(
                            mod.path, node.lineno, node.col_offset,
                            "DK302",
                            f"`{left.value}` + <dynamic> builds an env "
                            "var name at runtime: no registry entry can "
                            "ever match it — construct the full DKTPU_* "
                            "literal and declare it"))
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in fstring_parts):
                for name in _DKTPU_RE.findall(node.value):
                    flag(node, name)
    return out


@project_rule(
    RuleInfo("DK303", "env-var docs table out of sync with the registry"),
)
def check_env_docs(modules) -> list:
    """Only fires when the scan includes the real registry module (so the
    fixture corpus, which has no docs tree, is naturally exempt)."""
    config_mod = next((m for m in modules if _is_config_module(m.path)), None)
    if config_mod is None:
        return []
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        config_mod.path)))
    docs_dir = os.path.join(os.path.dirname(pkg_root), "docs")
    if not os.path.isdir(docs_dir):
        return []
    from distkeras_tpu.runtime import config

    docs: dict = {}
    for path in sorted(glob.glob(os.path.join(docs_dir, "*.md"))):
        with open(path, encoding="utf-8") as f:
            docs[path] = f.read()
    out: list = []

    def decl_line(name: str) -> int:
        for i, line in enumerate(config_mod.source.splitlines(), 1):
            if f'"{name}"' in line:
                return i
        return 1

    blob = "\n".join(docs.values())
    for var in config.ENV_REGISTRY.values():
        if f"`{var.name}`" not in blob and var.name not in blob:
            out.append(Finding(
                config_mod.path, decl_line(var.name), 0, "DK303",
                f"`{var.name}` is registered but appears in no docs/*.md "
                "table: run `python -m distkeras_tpu.analysis "
                "--write-env-docs`"))
    for path, text in docs.items():
        try:
            fresh = config.splice_env_docs(text)
        except ValueError:
            continue
        if fresh != text:
            out.append(Finding(
                config_mod.path, 1, 0, "DK303",
                f"{os.path.relpath(path, os.path.dirname(pkg_root))} env "
                "table is stale vs ENV_REGISTRY: run `python -m "
                "distkeras_tpu.analysis --write-env-docs`"))
    return out
