"""DK2xx — host-thread concurrency lints.

The package's threaded surface (telemetry registry, RoundFeeder, native
loader, racelab parameter server, fault plans) shares mutable state under
plain ``threading.Lock``s. These rules build a static model of that
surface:

* **DK201** — a lock-acquisition-order graph: every ``with lock_b:`` nested
  (syntactically, or one call level deep within the same module) inside
  ``with lock_a:`` adds the edge ``a -> b``; a cycle in the global graph is
  a potential deadlock. The graph is intentionally conservative —
  cross-module call edges are not resolved statically; the runtime witness
  (``distkeras_tpu.analysis.witness``) covers real interleavings.
* **DK202** — an attribute that is written under a class lock somewhere but
  also written (or mutated via ``.append``/``.update``/...) outside any
  lock in another method: the unlocked write races the locked readers.
  ``__init__`` is exempt (no concurrent access before construction ends).
* **DK203** — ``threading.Thread`` created neither ``daemon=True`` nor
  joined anywhere in the module: a silent leak that blocks interpreter
  shutdown.
* **DK204** — a bare ``except:`` / ``except BaseException:`` handler that
  neither re-raises nor uses the caught exception object: it swallows
  ``KeyboardInterrupt``/``SystemExit``, turning Ctrl-C into an infinite
  worker loop.
"""

from __future__ import annotations

import ast
import os

from distkeras_tpu.analysis.core import (
    Finding, Module, RuleInfo, call_name, module_rule, project_rule,
    walk_scope)

_LOCK_CTORS = frozenset({"Lock", "RLock"})
_MUTATORS = frozenset({
    "append", "extend", "insert", "update", "add", "pop", "clear", "remove",
    "setdefault", "popitem", "discard",
})


def _modbase(path: str) -> str:
    return os.path.splitext(os.path.basename(path))[0]


def _is_lock_ctor(node) -> bool:
    return (isinstance(node, ast.Call)
            and call_name(node.func).rsplit(".", 1)[-1] in _LOCK_CTORS)


class _ModuleLocks:
    """Lock declarations + per-function acquisition structure of one file."""

    def __init__(self, mod: Module):
        self.mod = mod
        base = _modbase(mod.path)
        #: lock id -> declaration line
        self.locks: dict = {}
        #: class name -> set of lock attr names
        self.class_locks: dict = {}
        self.module_locks: set = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
                        self.locks[f"{base}.{t.id}"] = node.lineno
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            attrs.add(t.attr)
                            self.locks[f"{base}.{node.name}.{t.attr}"] = \
                                sub.lineno
            if attrs:
                self.class_locks[node.name] = attrs
        self.base = base

    def resolve(self, expr, cls: str) -> str:
        """Lock id for a ``with`` item expression, '' if not a known lock."""
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.base}.{expr.id}"
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls
                and expr.attr in self.class_locks.get(cls, ())):
            return f"{self.base}.{cls}.{expr.attr}"
        return ""


def _functions(mod: Module):
    """Yield (qualname, class name or '', FunctionDef) for every def."""
    def visit(body, cls, prefix):
        for node in body:
            if isinstance(node, ast.ClassDef):
                yield from visit(node.body, node.name, f"{prefix}{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield f"{prefix}{node.name}", cls, node
                yield from visit(node.body, cls, f"{prefix}{node.name}.")
    yield from visit(mod.tree.body, "", "")


def build_lock_graph(modules) -> tuple:
    """(edges, sites, acquires) over all modules.

    ``edges``: set of (lock_a, lock_b) — b acquired while a held.
    ``sites``: edge -> (path, line) of the inner acquisition.
    ``acquires``: function qualname (module-prefixed) -> set of lock ids the
    function may acquire, transitively through same-module calls.
    """
    infos = [(_ModuleLocks(m), m) for m in modules]
    # Pass 1: direct acquisitions + same-module call lists per function.
    direct: dict = {}
    calls: dict = {}
    fn_meta: dict = {}
    for info, mod in infos:
        names = {q.rsplit(".", 1)[-1]: f"{info.base}:{q}"
                 for q, _c, _n in _functions(mod)}
        for qual, cls, fn in _functions(mod):
            key = f"{info.base}:{qual}"
            fn_meta[key] = (info, mod, cls, fn)
            acq, callees = set(), set()
            for node in walk_scope(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lock = info.resolve(item.context_expr, cls)
                        if lock:
                            acq.add(lock)
                elif isinstance(node, ast.Call):
                    name = call_name(node.func)
                    if name.startswith("self.") and name.count(".") == 1 and cls:
                        callees.add(f"{info.base}:{cls}.{name[5:]}")
                    elif name and "." not in name and name in names:
                        callees.add(names[name])
            direct[key] = acq
            calls[key] = callees
    # Fixpoint: transitive acquire sets through same-module calls.
    acquires = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for k, callees in calls.items():
            for c in callees:
                extra = acquires.get(c, set()) - acquires[k]
                if extra:
                    acquires[k] |= extra
                    changed = True
    # Pass 2: edges from syntactic nesting + calls made while holding.
    edges: set = set()
    sites: dict = {}

    def scan(node, held, info, mod, cls, key):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                lock = info.resolve(item.context_expr, cls)
                if lock:
                    for h in held:
                        if h != lock:
                            edges.add((h, lock))
                            sites.setdefault((h, lock),
                                             (mod.path, node.lineno))
                    inner.append(lock)
            for child in node.body:
                scan(child, inner, info, mod, cls, key)
            return
        if isinstance(node, ast.Call) and held:
            for callee in _resolve_call(node, info, cls, key):
                for lock in acquires.get(callee, ()):
                    for h in held:
                        if h != lock:
                            edges.add((h, lock))
                            sites.setdefault(
                                (h, lock), (mod.path, node.lineno))
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                scan(child, held, info, mod, cls, key)

    def _resolve_call(node, info, cls, key):
        name = call_name(node.func)
        if name.startswith("self.") and name.count(".") == 1 and cls:
            return [f"{info.base}:{cls}.{name[5:]}"]
        if name and "." not in name:
            cand = f"{info.base}:{name}"
            if cand in acquires:
                return [cand]
        return []

    for key, (info, mod, cls, fn) in fn_meta.items():
        for child in fn.body:
            scan(child, [], info, mod, cls, key)
    return edges, sites, acquires


def _find_cycles(edges) -> list:
    graph: dict = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen_sets = [], []
    state: dict = {}

    def dfs(n, stack):
        state[n] = 1
        for m in graph.get(n, ()):
            if state.get(m, 0) == 1:
                cyc = stack[stack.index(m):] + [m]
                nodes = frozenset(cyc)
                if nodes not in seen_sets:
                    seen_sets.append(nodes)
                    cycles.append(cyc)
            elif state.get(m, 0) == 0:
                dfs(m, stack + [m])
        state[n] = 2

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            dfs(n, [n])
    return cycles


@project_rule(
    RuleInfo("DK201", "lock-acquisition-order cycle (potential deadlock)"),
)
def check_lock_order(modules) -> list:
    edges, sites, _ = build_lock_graph(modules)
    out = []
    for cyc in _find_cycles(edges):
        path, line = sites.get((cyc[0], cyc[1]), (modules[0].path, 1))
        out.append(Finding(
            path, line, 0, "DK201",
            "lock-order cycle " + " -> ".join(cyc) + ": two threads taking "
            "these locks in opposite orders deadlock; pick one global order"))
    return out


@module_rule(
    RuleInfo("DK202", "write to a lock-guarded attribute outside the lock"),
    RuleInfo("DK203", "thread is neither daemon nor ever joined"),
    RuleInfo("DK204", "bare except swallows KeyboardInterrupt"),
)
def check_threading(mod: Module) -> list:
    out: list = []
    info = _ModuleLocks(mod)
    out.extend(_check_shared_writes(mod, info))
    out.extend(_check_threads(mod))
    out.extend(_check_bare_except(mod))
    return out


def _attr_writes(fn):
    """(attr, node, mutating) for self.X writes / self.X.mutator() calls."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    yield t.attr, node
        elif isinstance(node, ast.Call):
            name = call_name(node.func)
            if (name.startswith("self.") and name.count(".") == 2
                    and name.rsplit(".", 1)[-1] in _MUTATORS):
                yield name.split(".")[1], node


def _check_shared_writes(mod: Module, info: _ModuleLocks) -> list:
    out = []
    for cls_node in [n for n in ast.walk(mod.tree)
                     if isinstance(n, ast.ClassDef)]:
        lock_attrs = info.class_locks.get(cls_node.name)
        if not lock_attrs:
            continue
        locked_writes: dict = {}
        unlocked_writes: dict = {}
        for meth in cls_node.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue

            def scan(node, held: bool) -> None:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner = held or any(
                        info.resolve(i.context_expr, cls_node.name)
                        for i in node.items)
                    for child in node.body:
                        scan(child, inner)
                    return
                for attr, site in _attr_writes_shallow(node):
                    if attr in lock_attrs:
                        continue
                    (locked_writes if held else unlocked_writes).setdefault(
                        attr, []).append((meth.name, site))
                for child in ast.iter_child_nodes(node):
                    if not isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                        scan(child, held)

            for child in meth.body:
                scan(child, False)
        for attr, sites in unlocked_writes.items():
            if attr not in locked_writes:
                continue
            guardian = locked_writes[attr][0][0]
            for meth_name, site in sites:
                if meth_name == "__init__":
                    continue
                out.append(Finding(
                    mod.path, site.lineno, site.col_offset, "DK202",
                    f"`self.{attr}` is written under a lock in "
                    f"`{cls_node.name}.{guardian}` but without one here "
                    f"(`{meth_name}`): unlocked write races locked readers"))
    return out


def _attr_writes_shallow(node):
    """Like _attr_writes but for ONE node (no recursion — scan() recurses)."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                yield t.attr, node
    elif isinstance(node, ast.Call):
        name = call_name(node.func)
        if (name.startswith("self.") and name.count(".") == 2
                and name.rsplit(".", 1)[-1] in _MUTATORS):
            yield name.split(".")[1], node


def _check_threads(mod: Module) -> list:
    out = []
    # Names/attrs a created thread flows into, incl. list-comprehension
    # collections; a `.join()` on any of them (or on the loop var of a `for`
    # over them) counts as join discipline.
    joined: set = set()
    daemon_set: set = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = call_name(node.func)
            if name.endswith(".join") or name == "join":
                joined.add(name.rsplit(".join", 1)[0].split(".")[-1]
                           if "." in name else name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Attribute) and t.attr == "daemon"):
                    if isinstance(t.value, ast.Name):
                        daemon_set.add(t.value.id)
    # loop vars: `for t in threads: t.join()` -> joining `t` covers `threads`
    loop_map: dict = {}
    for node in ast.walk(mod.tree):
        if (isinstance(node, (ast.For, ast.AsyncFor))
                and isinstance(node.target, ast.Name)
                and isinstance(node.iter, ast.Name)):
            loop_map.setdefault(node.target.id, set()).add(node.iter.id)
    covered = set(joined)
    for var in joined:
        covered |= loop_map.get(var, set())

    class _Finder(ast.NodeVisitor):
        def __init__(self):
            self.parents: list = []

        def generic_visit(self, node):
            self.parents.append(node)
            super().generic_visit(node)
            self.parents.pop()

        def visit_Call(self, node):
            name = call_name(node.func)
            if name.rsplit(".", 1)[-1] == "Thread" and (
                    name in ("Thread", "threading.Thread")
                    or name.endswith(".Thread")):
                for kw in node.keywords:
                    if kw.arg == "daemon" and isinstance(
                            kw.value, ast.Constant) and kw.value.value:
                        break
                else:
                    target = self._binding(node)
                    if target not in covered and target not in daemon_set:
                        out.append(Finding(
                            mod.path, node.lineno, node.col_offset, "DK203",
                            "threading.Thread created without daemon=True "
                            "and never joined in this module: a leaked "
                            "non-daemon thread blocks interpreter shutdown"))
            self.generic_visit(node)

        def _binding(self, call) -> str:
            for p in reversed(self.parents):
                if isinstance(p, ast.Assign):
                    t = p.targets[0]
                    if isinstance(t, ast.Name):
                        return t.id
                    if isinstance(t, ast.Attribute):
                        return t.attr
                if isinstance(p, (ast.ListComp, ast.GeneratorExp)):
                    continue
            return ""

    _Finder().visit(mod.tree)
    return out


def _check_bare_except(mod: Module) -> list:
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        is_bare = node.type is None
        catches_base = (isinstance(node.type, (ast.Name, ast.Attribute))
                        and call_name(node.type).rsplit(".", 1)[-1]
                        == "BaseException")
        if not (is_bare or catches_base):
            continue
        reraises = any(isinstance(n, ast.Raise) and n.exc is None
                       for n in ast.walk(node))
        uses_bound = node.name is not None and any(
            isinstance(n, ast.Name) and n.id == node.name
            and isinstance(n.ctx, ast.Load) for n in ast.walk(node))
        if reraises or uses_bound:
            continue  # propagates/records the exception: not swallowing
        what = "bare `except:`" if is_bare else "`except BaseException:`"
        out.append(Finding(
            mod.path, node.lineno, node.col_offset, "DK204",
            f"{what} swallows KeyboardInterrupt/SystemExit: catch "
            "`Exception`, or re-raise / surface the caught object"))
    return out
