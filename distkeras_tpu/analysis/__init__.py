"""dk-check: repo-aware static analysis for distkeras_tpu.

Three rule families over a plain-AST pass (no imports of the analyzed
code), run as ``python -m distkeras_tpu.analysis [paths]``:

* **DK1xx** (``rules_jax``) — JAX purity/retrace hazards: env/time/random
  reads, host I/O, or telemetry calls inside jitted/shard_map'd code,
  non-hashable static args, trace-time mutation of enclosing state.
* **DK2xx** (``rules_concurrency``) — host-thread hazards: lock-order
  cycles, unlocked writes to lock-guarded attributes, leaked non-daemon
  threads, KeyboardInterrupt-swallowing except handlers. The static lock
  graph is cross-checked at runtime by :mod:`.witness`.
* **DK3xx** (``rules_config``) — env discipline: ``os.environ`` confined to
  ``runtime/config.py``, every ``DKTPU_*`` name declared in
  ``ENV_REGISTRY``, docs tables generated from the registry.

Suppress a finding with ``# dk: disable=DK204`` on its line (justify in the
comment); catalog and how-to in docs/ANALYSIS.md. CI
(``.github/workflows/tier1.yml`` job ``static-analysis``) fails on any
non-suppressed finding.
"""

from distkeras_tpu.analysis.core import (  # noqa: F401
    Finding, RULE_CATALOG, render, run)
from distkeras_tpu.analysis.witness import (  # noqa: F401
    LockOrderWitness, witness)
