"""Core runtime: device-mesh bootstrap, serialization, configuration."""
